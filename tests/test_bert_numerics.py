"""Golden-vector numerics: our JAX BERT vs the HF torch reference.

SURVEY.md §4 names this the gate for weight-porting fidelity: the reference's
compute core is candle BertModel + masked mean pooling
(reference: services/preprocessing_service/src/embedding_generator.rs:198-207);
we verify our forward matches transformers' BertModel / XLMRobertaModel on
randomly-initialized tiny checkpoints (no network needed), in fp32, to tight
tolerance. bf16 is then checked for coarse agreement (MXU production dtype).
"""

import dataclasses

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from symbiont_tpu.models.bert import (  # noqa: E402
    BertConfig,
    bert_encode,
    cross_encoder_score,
    embed_sentences,
    mean_pool,
)
from symbiont_tpu.models.convert import convert_bert  # noqa: E402

TINY = dict(
    vocab_size=99,
    hidden_size=32,
    num_hidden_layers=3,
    num_attention_heads=4,
    intermediate_size=64,
    max_position_embeddings=64,
)


def _rand_inputs(rng, B=3, S=10, vocab=99, pad_to=16):
    ids = rng.integers(3, vocab, size=(B, pad_to))
    mask = np.zeros((B, pad_to), np.int32)
    for i, ln in enumerate([S, S - 3, S - 5]):
        mask[i, :ln] = 1
        ids[i, ln:] = 0
    return ids.astype(np.int32), mask


@pytest.fixture(scope="module")
def torch_bert():
    torch.manual_seed(0)
    cfg = transformers.BertConfig(**TINY)
    model = transformers.BertModel(cfg).eval()
    return model, cfg


@pytest.fixture(scope="module")
def torch_xlmr():
    torch.manual_seed(1)
    cfg = transformers.XLMRobertaConfig(**TINY, pad_token_id=1)
    model = transformers.XLMRobertaModel(cfg).eval()
    return model, cfg


def _our_cfg(hf_cfg, **kw) -> BertConfig:
    cfg = BertConfig.from_hf(hf_cfg.to_dict())
    return dataclasses.replace(cfg, dtype="float32", **kw)


def test_bert_last_hidden_matches_hf(torch_bert):
    model, hf_cfg = torch_bert
    ids, mask = _rand_inputs(np.random.default_rng(0))
    with torch.no_grad():
        ref = model(input_ids=torch.tensor(ids.astype(np.int64)),
                    attention_mask=torch.tensor(mask.astype(np.int64)))
    cfg = _our_cfg(hf_cfg)
    params = convert_bert(model.state_dict(), cfg)
    ours = bert_encode(params, jnp.asarray(ids), jnp.asarray(mask), cfg)
    ref_np = ref.last_hidden_state.numpy()
    # padding positions are junk in both impls; compare only real tokens
    m = mask[..., None].astype(bool)
    np.testing.assert_allclose(np.where(m, np.asarray(ours), 0),
                               np.where(m, ref_np, 0), atol=2e-5, rtol=1e-4)


def test_xlmr_position_offset_matches_hf(torch_xlmr):
    """XLM-RoBERTa layout = the reference's default mpnet-multilingual model."""
    model, hf_cfg = torch_xlmr
    ids, mask = _rand_inputs(np.random.default_rng(1))
    with torch.no_grad():
        ref = model(input_ids=torch.tensor(ids.astype(np.int64)),
                    attention_mask=torch.tensor(mask.astype(np.int64)))
    cfg = _our_cfg(hf_cfg)
    assert cfg.position_offset == 2  # pad_token_id(1) + 1
    params = convert_bert(model.state_dict(), cfg)
    ours = bert_encode(params, jnp.asarray(ids), jnp.asarray(mask), cfg)
    m = mask[..., None].astype(bool)
    np.testing.assert_allclose(np.where(m, np.asarray(ours), 0),
                               np.where(m, ref.last_hidden_state.numpy(), 0),
                               atol=2e-5, rtol=1e-4)


def test_mean_pool_matches_reference_semantics(torch_bert):
    """sum(h*mask)/sum(mask) — reference: embedding_generator.rs:201-207."""
    model, hf_cfg = torch_bert
    ids, mask = _rand_inputs(np.random.default_rng(2))
    with torch.no_grad():
        ref_h = model(input_ids=torch.tensor(ids.astype(np.int64)),
                      attention_mask=torch.tensor(mask.astype(np.int64))
                      ).last_hidden_state.numpy()
    manual = (ref_h * mask[..., None]).sum(1) / mask.sum(1, keepdims=True)
    cfg = _our_cfg(hf_cfg)
    params = convert_bert(model.state_dict(), cfg)
    ours = embed_sentences(params, jnp.asarray(ids), jnp.asarray(mask), cfg,
                           pooling="mean")
    np.testing.assert_allclose(np.asarray(ours), manual, atol=2e-5, rtol=1e-4)


def test_normalized_embeddings_unit_norm(torch_bert):
    model, hf_cfg = torch_bert
    ids, mask = _rand_inputs(np.random.default_rng(3))
    cfg = _our_cfg(hf_cfg)
    params = convert_bert(model.state_dict(), cfg)
    out = embed_sentences(params, jnp.asarray(ids), jnp.asarray(mask), cfg,
                          normalize=True)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(out), axis=-1), 1.0,
                               atol=1e-5)


def test_cross_encoder_matches_hf():
    """ms-marco-style rerank head (BASELINE.md config #4)."""
    torch.manual_seed(2)
    hf_cfg = transformers.BertConfig(**TINY, num_labels=1)
    model = transformers.BertForSequenceClassification(hf_cfg).eval()
    ids, mask = _rand_inputs(np.random.default_rng(4))
    with torch.no_grad():
        ref = model(input_ids=torch.tensor(ids.astype(np.int64)),
                    attention_mask=torch.tensor(mask.astype(np.int64))).logits[:, 0]
    cfg = _our_cfg(hf_cfg)
    params = convert_bert(model.state_dict(), cfg, with_pooler=True)
    ours = cross_encoder_score(params, jnp.asarray(ids), jnp.asarray(mask), cfg)
    np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=3e-5, rtol=1e-4)


def test_bf16_close_to_fp32(torch_bert):
    """Production dtype sanity: bf16 embeddings ≈ fp32 (cosine > 0.995)."""
    model, hf_cfg = torch_bert
    ids, mask = _rand_inputs(np.random.default_rng(5))
    cfg32 = _our_cfg(hf_cfg)
    cfg16 = dataclasses.replace(cfg32, dtype="bfloat16")
    params = convert_bert(model.state_dict(), cfg32)
    e32 = np.asarray(embed_sentences(params, jnp.asarray(ids), jnp.asarray(mask), cfg32))
    e16 = np.asarray(embed_sentences(params, jnp.asarray(ids), jnp.asarray(mask), cfg16))
    cos = (e32 * e16).sum(-1) / (np.linalg.norm(e32, axis=-1) * np.linalg.norm(e16, axis=-1))
    assert cos.min() > 0.995, cos


def test_padding_invariance():
    """Embedding of a sentence must not change when batch-padded longer —
    the property that makes length-bucketing (SURVEY.md §5.7) safe."""
    import symbiont_tpu.models.bert as bert_mod

    cfg = BertConfig(vocab_size=50, hidden_size=16, num_layers=2, num_heads=2,
                     intermediate_size=32, max_position_embeddings=32,
                     dtype="float32")
    params = bert_mod.init_params(jax.random.key(0), cfg)
    ids = np.zeros((1, 8), np.int32)
    ids[0, :5] = [4, 5, 6, 7, 8]
    mask = np.zeros((1, 8), np.int32)
    mask[0, :5] = 1
    short = embed_sentences(params, jnp.asarray(ids), jnp.asarray(mask), cfg)
    ids_l = np.zeros((1, 16), np.int32)
    ids_l[0, :5] = [4, 5, 6, 7, 8]
    mask_l = np.zeros((1, 16), np.int32)
    mask_l[0, :5] = 1
    long = embed_sentences(params, jnp.asarray(ids_l), jnp.asarray(mask_l), cfg)
    np.testing.assert_allclose(np.asarray(short), np.asarray(long), atol=1e-5)


def test_convert_cli_roundtrip(tmp_path, torch_bert, capsys):
    """python -m symbiont_tpu.models.convert: HF dir → cached checkpoint →
    reload gives the same params the direct loader produces."""
    model, hf_cfg = torch_bert
    hf_dir = tmp_path / "hf"
    model.save_pretrained(hf_dir)

    from symbiont_tpu.models import convert as convert_mod
    from symbiont_tpu.train.checkpoint import load_params

    out = tmp_path / "ckpt"
    convert_mod.main([str(hf_dir), "--out", str(out)])
    assert "converted OK" in capsys.readouterr().out

    cached, meta = load_params(out)
    assert meta["kind"] == "bert"
    direct, cfg = convert_mod.load_bert_model(hf_dir)
    import jax

    for a, b in zip(jax.tree.leaves(direct), jax.tree.leaves(cached)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert meta["config"]["hidden_size"] == cfg.hidden_size
