"""EngineService: the engine.* request-reply plane native workers call into.

Covers every op (embed batch/query, generate, vector upsert/search, graph
save, health) plus the typed-error-reply convention on bad input — the same
convention the reference uses on its request-reply paths (reference:
services/preprocessing_service/src/main.rs:183-196).
"""

import asyncio
import json

import numpy as np
import pytest

from symbiont_tpu import subjects
from symbiont_tpu.bus.inproc import InprocBus
from symbiont_tpu.config import EngineConfig, GraphStoreConfig, VectorStoreConfig
from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.graph.store import GraphStore
from symbiont_tpu.memory.vector_store import VectorStore
from symbiont_tpu.schema import TokenizedTextMessage, to_json
from symbiont_tpu.services.engine_service import EngineService
from symbiont_tpu.utils.ids import current_timestamp_ms


def _engine():
    cfg = EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                       batch_buckets=[2, 4], dtype="float32")
    return TpuEngine(cfg)


class _FakeLm:
    class config:
        model_dir = None
        arch = "llama"

    def generate(self, prompt, max_new_tokens, **kw):
        return f"gen[{prompt}]x{max_new_tokens}"


async def _req(bus, subject, payload, timeout=30.0):
    msg = await bus.request(subject, json.dumps(payload).encode(), timeout)
    return json.loads(msg.data)


def _run(coro):
    asyncio.run(coro)


def test_engine_service_ops(tmp_path):
    async def scenario():
        bus = InprocBus()
        store = VectorStore(VectorStoreConfig(dim=32, data_dir=str(tmp_path)))
        graph = GraphStore(GraphStoreConfig(data_dir=str(tmp_path)))
        svc = EngineService(bus, engine=_engine(), lm=_FakeLm(),
                            vector_store=store, graph_store=graph)
        await svc.start()
        try:
            # embed batch
            r = await _req(bus, subjects.ENGINE_EMBED_BATCH,
                           {"texts": ["hello world", "tpu"]})
            assert r["error_message"] is None
            assert len(r["vectors"]) == 2 and len(r["vectors"][0]) == 32

            # embed query matches batch row
            q = await _req(bus, subjects.ENGINE_EMBED_QUERY, {"text": "hello world"})
            np.testing.assert_allclose(q["vector"], r["vectors"][0], rtol=1e-5)

            # generate
            g = await _req(bus, subjects.ENGINE_GENERATE,
                           {"prompt": "abc", "max_new_tokens": 7})
            assert g["text"] == "gen[abc]x7"

            # vector upsert + search round-trip
            up = await _req(bus, subjects.ENGINE_VECTOR_UPSERT, {"points": [
                {"id": "00000000-0000-0000-0000-000000000001",
                 "vector": q["vector"], "payload": {"sentence_text": "hello world"}},
            ]})
            assert up["upserted"] == 1
            hits = await _req(bus, subjects.ENGINE_VECTOR_SEARCH,
                              {"vector": q["vector"], "top_k": 1})
            assert hits["hits"][0]["payload"]["sentence_text"] == "hello world"
            assert hits["hits"][0]["score"] == pytest.approx(1.0, abs=1e-3)

            # graph save
            tok = TokenizedTextMessage(
                original_id="doc-1", source_url="http://x",
                tokens=["Hello", "world"], sentences=["Hello world."],
                timestamp_ms=current_timestamp_ms())
            gs = await _req(bus, subjects.ENGINE_GRAPH_SAVE,
                            {"message": json.loads(to_json(tok))})
            assert gs["error_message"] is None
            assert graph.get_document("doc-1") is not None

            # health reflects wired backends
            h = await _req(bus, subjects.ENGINE_HEALTH, {})
            assert h["ok"] and h["backends"] == {
                "embed": True, "rerank": False, "generate": True,
                "vector": True, "graph": True}
            assert h["embedding_dim"] == 32 and h["vector_count"] == 1
        finally:
            await svc.stop()

    _run(scenario())


def test_engine_service_error_replies(tmp_path):
    async def scenario():
        bus = InprocBus()
        svc = EngineService(bus, engine=_engine())
        await svc.start()
        try:
            r = await _req(bus, subjects.ENGINE_EMBED_BATCH, {"texts": "nope"})
            assert "list of strings" in r["error_message"]
            # non-JSON body
            msg = await bus.request(subjects.ENGINE_EMBED_QUERY, b"{bad", 10.0)
            assert "bad request" in json.loads(msg.data)["error_message"]
            # an op with no backend wired is simply not subscribed: request
            # times out rather than half-answering
            with pytest.raises(TimeoutError):
                await bus.request(subjects.ENGINE_GENERATE, b"{}", 0.2)
            # EXCEPT rerank: always subscribed so a rerank-disabled stack
            # fails fast with a typed error, not a caller timeout
            r = await _req(bus, subjects.ENGINE_RERANK,
                           {"query": "q", "passages": ["p"]}, timeout=5.0)
            assert "no cross-encoder" in r["error_message"]
        finally:
            await svc.stop()

    _run(scenario())


def test_engine_service_b64_encodings(tmp_path):
    """The compact base64 f32 forms on the framework-internal engine plane
    (r5): embed.batch replies with one b64 block when asked, vector.upsert
    accepts the b64 request form, and malformed shapes get typed errors
    instead of silently dropping points."""
    import base64

    async def scenario():
        bus = InprocBus()
        store = VectorStore(VectorStoreConfig(dim=32, data_dir=str(tmp_path)))
        svc = EngineService(bus, engine=_engine(), vector_store=store)
        await svc.start()
        try:
            plain = await _req(bus, subjects.ENGINE_EMBED_BATCH,
                               {"texts": ["hello world", "tpu"]})
            b64 = await _req(bus, subjects.ENGINE_EMBED_BATCH,
                             {"texts": ["hello world", "tpu"],
                              "encoding": "b64"})
            assert b64["error_message"] is None
            assert b64["count"] == 2 and b64["dim"] == 32
            rows = np.frombuffer(base64.b64decode(b64["vectors_b64"]),
                                 dtype=np.float32).reshape(2, 32)
            # b64 is EXACT f32 — tighter than the JSON text round-trip
            np.testing.assert_allclose(rows, np.asarray(plain["vectors"]),
                                       rtol=1e-6)

            ids = [f"00000000-0000-4000-8000-{i:012d}" for i in range(2)]
            up = await _req(bus, subjects.ENGINE_VECTOR_UPSERT, {
                "ids": ids, "dim": 32,
                "vectors_b64": base64.b64encode(
                    rows.astype(np.float32).tobytes()).decode(),
                "payloads": [{"sentence_text": "hello world"},
                             {"sentence_text": "tpu"}]})
            assert up["error_message"] is None and up["upserted"] == 2
            hits = await _req(bus, subjects.ENGINE_VECTOR_SEARCH,
                              {"vector": plain["vectors"][0], "top_k": 1})
            assert hits["hits"][0]["id"] == ids[0]

            # malformed: payload count != id count must ERROR, not truncate
            bad = await _req(bus, subjects.ENGINE_VECTOR_UPSERT, {
                "ids": ids, "dim": 32,
                "vectors_b64": base64.b64encode(
                    rows.astype(np.float32).tobytes()).decode(),
                "payloads": [{}]})
            assert bad["error_message"] is not None
            # malformed: float count != ids*dim must ERROR
            bad2 = await _req(bus, subjects.ENGINE_VECTOR_UPSERT, {
                "ids": ids, "dim": 32,
                "vectors_b64": base64.b64encode(
                    rows[:1].astype(np.float32).tobytes()).decode(),
                "payloads": [{}, {}]})
            assert bad2["error_message"] is not None
        finally:
            await svc.stop()

    _run(scenario())
