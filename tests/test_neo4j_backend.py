"""External-Neo4j backend: HTTP tx/commit adapter against a fake endpoint.

The fake records every Cypher statement + parameters and answers the
RETURN id(d) row, so the adapter's write parity with the reference's
save_to_neo4j (single transaction, MERGE semantics, skip-empty rules —
reference: services/knowledge_graph_service/src/main.rs:23-140) is asserted
statement-by-statement without a Neo4j server.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from symbiont_tpu.config import GraphStoreConfig
from symbiont_tpu.graph.neo4j_backend import Neo4jGraphStore, make_graph_store
from symbiont_tpu.graph.store import GraphStore
from symbiont_tpu.schema import TokenizedTextMessage


class _FakeNeo4j(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0) or 0)
        body = json.loads(self.rfile.read(n))
        state = self.server.state
        state["auth"].append(self.headers.get("Authorization"))
        state["paths"].append(self.path)
        results = []
        for st in body["statements"]:
            state["statements"].append((st["statement"], st.get("parameters", {})))
            if "RETURN id(d)" in st["statement"]:
                results.append({"columns": ["id(d)"], "data": [{"row": [42]}]})
            elif "RETURN count" in st["statement"]:
                results.append({"columns": ["count"], "data": [{"row": [7]}]})
            else:
                results.append({"columns": [], "data": []})
        out = json.dumps({"results": results, "errors": []}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)


@pytest.fixture()
def fake_neo4j():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeNeo4j)
    srv.state = {"statements": [], "auth": [], "paths": []}
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv.state
    srv.shutdown()


def _msg():
    return TokenizedTextMessage(
        original_id="doc-1", source_url="http://src",
        sentences=["First sentence.", "  ", "Second one."],
        tokens=["Alpha", "beta", " ", "ALPHA"],
        timestamp_ms=1718000000000)


def test_save_tokenized_statement_parity(fake_neo4j):
    uri, state = fake_neo4j
    store = Neo4jGraphStore(GraphStoreConfig(uri=uri, user="u", password="p"),
                            retries=1, retry_delay_s=0.01)
    store.ensure_schema()
    doc_id = store.save_tokenized(_msg())
    assert doc_id == 42

    stmts = state["statements"]
    # schema: constraint + index (main.rs:158-173)
    assert "REQUIRE d.original_id IS UNIQUE" in stmts[0][0]
    assert "ON (t.text_lc)" in stmts[1][0]
    # document MERGE with upsert of source_url/timestamp (main.rs:37-63)
    doc_stmt, doc_params = stmts[2]
    assert doc_stmt.startswith("MERGE (d:Document")
    assert "ON CREATE SET" in doc_stmt and "ON MATCH SET" in doc_stmt
    assert doc_params == {"original_id": "doc-1", "source_url": "http://src",
                          "ts": 1718000000000}
    # sentences: blank skipped (main.rs:71-77), order carried on the edge
    sent = [s for s in stmts if "HAS_SENTENCE" in s[0]]
    assert [p["text"] for _, p in sent] == ["First sentence.", "Second one."]
    assert [p["order"] for _, p in sent] == [0, 2]
    # tokens: blank skipped, lowercase merge key + original case stored
    # (main.rs:100-125); both casings of "alpha" hit the same key
    tok = [s for s in stmts if "CONTAINS_TOKEN" in s[0]]
    assert [p["lc"] for _, p in tok] == ["alpha", "beta", "alpha"]
    assert [p["orig"] for _, p in tok] == ["Alpha", "beta", "ALPHA"]
    # one transactional commit for the whole document (main.rs:32-134):
    # schema used two commits, the save exactly one more
    assert len(state["paths"]) == 3
    assert state["paths"][-1].endswith("/db/neo4j/tx/commit")
    # basic auth carried
    assert state["auth"][-1].startswith("Basic ")

    assert store.counts() == {"Document": 7, "Sentence": 7, "Token": 7}
    store.close()


def test_connect_retry_then_fail():
    store = Neo4jGraphStore(GraphStoreConfig(uri="http://127.0.0.1:1"),
                            retries=2, retry_delay_s=0.01)
    with pytest.raises(ConnectionError, match="unreachable"):
        store.ensure_schema()


def test_backend_selection(tmp_path):
    embedded = make_graph_store(GraphStoreConfig(data_dir=str(tmp_path)))
    assert isinstance(embedded, GraphStore)
    embedded.close()
    assert isinstance(
        make_graph_store(GraphStoreConfig(uri="http://127.0.0.1:1")),
        Neo4jGraphStore)


def test_stack_env_aliases(fake_neo4j, tmp_path, monkeypatch):
    """Reference .env drop-in: NEO4J_URI/USER/PASSWORD select and configure
    the external backend through config loading (reference: .env.example)."""
    from symbiont_tpu.config import load_config

    uri, _ = fake_neo4j
    monkeypatch.setenv("NEO4J_URI", uri)
    monkeypatch.setenv("NEO4J_USER", "svc")
    monkeypatch.setenv("NEO4J_PASSWORD", "secret")
    cfg = load_config()
    assert cfg.graph_store.uri == uri
    store = make_graph_store(cfg.graph_store)
    assert isinstance(store, Neo4jGraphStore)
    assert store._auth  # credentials from env made it into the adapter


def test_bolt_uri_fails_fast():
    """The reference's .env carries bolt://host:7687; the adapter speaks the
    HTTP API and must say so immediately, not retry into a timeout."""
    with pytest.raises(ValueError, match="bolt"):
        Neo4jGraphStore(GraphStoreConfig(uri="bolt://neo4j:7687"))


def test_repeated_sentence_keeps_both_orders(fake_neo4j):
    uri, state = fake_neo4j
    store = Neo4jGraphStore(GraphStoreConfig(uri=uri), retries=1,
                            retry_delay_s=0.01)
    msg = TokenizedTextMessage(original_id="d", source_url="u",
                               sentences=["Same.", "Other.", "Same."],
                               tokens=[], timestamp_ms=1)
    store.save_tokenized(msg)
    sent = [s for s in state["statements"] if "HAS_SENTENCE" in s[0]]
    # order lives INSIDE the MERGE pattern → duplicate text at a new
    # position creates a second edge instead of overwriting the first
    assert all("{order: $order}" in s for s, _ in sent)
    assert [p["order"] for _, p in sent] == [0, 1, 2]
