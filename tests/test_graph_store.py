"""Graph store tests: MERGE semantics parity with save_to_neo4j
(reference: services/knowledge_graph_service/src/main.rs:23-140)."""

from symbiont_tpu.schema import TokenizedTextMessage
from symbiont_tpu.graph import GraphStore


def _msg(**kw):
    base = dict(original_id="doc-1", source_url="http://x",
                tokens=["Hello", "world", "hello"],
                sentences=["Hello world.", "Second one."],
                timestamp_ms=1000)
    base.update(kw)
    return TokenizedTextMessage(**base)


def _store(tmp_path):
    return GraphStore(path=str(tmp_path / "g.sqlite3"))


def test_save_creates_nodes_and_edges(tmp_path):
    g = _store(tmp_path)
    g.save_tokenized(_msg())
    c = g.counts()
    assert c["Document"] == 1
    assert c["Sentence"] == 2
    # tokens are lowercase-keyed: Hello and hello merge (main.rs:110-118)
    assert c["Token"] == 2
    assert g.document_sentences("doc-1") == ["Hello world.", "Second one."]
    assert g.documents_containing_token("HELLO") == ["doc-1"]


def test_document_merge_updates_not_duplicates(tmp_path):
    g = _store(tmp_path)
    g.save_tokenized(_msg())
    g.save_tokenized(_msg(source_url="http://y", timestamp_ms=2000))
    assert g.counts()["Document"] == 1
    doc = g.get_document("doc-1")
    assert doc["source_url"] == "http://y"  # ON MATCH SET (main.rs:38-40)
    assert doc["processed_at_ms"] == 2000


def test_empty_sentences_and_tokens_skipped(tmp_path):
    g = _store(tmp_path)
    g.save_tokenized(_msg(sentences=["ok.", "  ", ""], tokens=["a", " ", ""]))
    c = g.counts()
    assert c["Sentence"] == 1 and c["Token"] == 1


def test_shared_sentences_across_documents(tmp_path):
    g = _store(tmp_path)
    g.save_tokenized(_msg())
    g.save_tokenized(_msg(original_id="doc-2", sentences=["Hello world."],
                          tokens=["shared"]))
    c = g.counts()
    assert c["Document"] == 2
    assert c["Sentence"] == 2  # "Hello world." merged across docs
    assert sorted(g.documents_containing_token("hello")) == ["doc-1"]


def test_token_case_updates_original(tmp_path):
    g = _store(tmp_path)
    g.save_tokenized(_msg(tokens=["WORLD"]))
    g.save_tokenized(_msg(tokens=["world"]))
    # last write wins on text_original_case (ON MATCH SET, main.rs:113-116)
    rows = g._db.execute(
        "SELECT props FROM nodes WHERE label='Token' AND merge_key='world'"
    ).fetchall()
    import json

    assert json.loads(rows[0][0])["text_original_case"] == "world"


def test_persistence_across_reopen(tmp_path):
    path = tmp_path / "g.sqlite3"
    g = GraphStore(path=str(path))
    g.save_tokenized(_msg())
    g.close()
    g2 = GraphStore(path=str(path))
    assert g2.counts()["Document"] == 1
    assert g2.document_sentences("doc-1") == ["Hello world.", "Second one."]


def test_unicode_tokens(tmp_path):
    g = _store(tmp_path)
    g.save_tokenized(_msg(tokens=["Привет", "МИР"], sentences=["Привет мир."]))
    assert g.documents_containing_token("привет") == ["doc-1"]
    assert g.documents_containing_token("мир") == ["doc-1"]
