"""The standing perf gate (scripts/perf_gate.sh) cannot rot.

ROADMAP item 1's unlanded half: the `--gate` regression machinery existed
since PR 1 but nothing RAN it pre-merge. scripts/perf_gate.sh is that one
command; these tests pin its contract in both directions — rc 0 on the
real archived numbers, rc != 0 on a synthetically regressed copy and on a
lost primary — hermetically (candidate mode gates existing archives; no
bench run, no jax import, sub-second). The quick-run mode (which actually
re-measures the host-only micro-tiers) is exercised under `-m gate` +
`slow` so a loaded CI box can't flake the fast tier on CPU timing noise.
"""

import json
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "perf_gate.sh"

pytestmark = pytest.mark.gate


def _run_gate(*args, env=None):
    import os

    full_env = dict(os.environ)
    full_env.update(env or {})
    return subprocess.run(["bash", str(SCRIPT), *args], cwd=REPO,
                          capture_output=True, text=True, env=full_env,
                          timeout=300)


def _gateable_primary(line: dict) -> str:
    """A declared primary the gate actually compares (present, numeric,
    not tunnel-bound)."""
    from symbiont_tpu.bench.archive import _TUNNEL_BOUND

    for key in line.get("primary_metrics", []):
        v = line.get(key)
        if isinstance(v, (int, float)) and v and not _TUNNEL_BOUND.match(key):
            return key
    raise AssertionError("no gateable primary in the archive line")


def test_gate_passes_on_the_real_archive():
    """The acceptance bar's green half: the committed BENCH_LATEST gates
    clean against itself (zero deltas are inside every noise bar)."""
    proc = _run_gate("BENCH_LATEST.json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no regression" in proc.stdout


def test_gate_fails_on_synthetic_regression(tmp_path):
    """The acceptance bar's red half: regress ONE gateable primary beyond
    any noise bar and the same command must exit nonzero, naming it."""
    from symbiont_tpu.bench.archive import _lower_is_better

    line = json.loads((REPO / "BENCH_LATEST.json").read_text())
    key = _gateable_primary(line)
    line[key] = line[key] * 3 if _lower_is_better(key) else line[key] / 3
    bad = tmp_path / "regressed.json"
    bad.write_text(json.dumps(line))
    proc = _run_gate(str(bad))
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert key in proc.stderr, proc.stderr


def test_gate_fails_on_lost_primary(tmp_path):
    """The r5 failure mode itself: a declared primary present in the
    baseline but MISSING from the candidate is a failure, not a silently
    narrowed comparison."""
    line = json.loads((REPO / "BENCH_LATEST.json").read_text())
    key = _gateable_primary(line)
    del line[key]
    bad = tmp_path / "lost.json"
    bad.write_text(json.dumps(line))
    proc = _run_gate(str(bad))
    assert proc.returncode != 0, proc.stdout + proc.stderr
    assert key in proc.stderr and "missing" in proc.stderr


def test_quick_baseline_is_schema_valid_and_self_gates():
    """BENCH_GATE_BASELINE.json (the committed quick-tier baseline the
    no-candidate mode gates against — BENCH_LATEST predates the quick
    tiers' primaries, so the two declare disjoint sets) must stay a valid
    archive line that gates clean against itself."""
    from symbiont_tpu.bench import archive

    path = REPO / "BENCH_GATE_BASELINE.json"
    assert archive.validate_file(path) == []
    line = archive.load_archive(path)
    # every quick-tier primary is declared AND measured in the baseline
    for key in ("obs_span_record_per_s", "obs_critical_path_512_ms",
                "obs_fleet_merge_per_s", "ser_frame_vs_json_bytes_x"):
        assert key in line["primary_metrics"], key
        assert isinstance(line.get(key), (int, float)), key
    proc = _run_gate(str(path),
                     env={"PERF_GATE_BASELINE": "BENCH_GATE_BASELINE.json"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_quick_run_mode_measures_and_gates():
    """The full no-candidate mode: re-measure the host-only micro-tiers
    and gate them against the committed quick baseline. Marked slow —
    the measurement is real CPU timing and a loaded box may legitimately
    sit outside the bars; the fast tier pins the plumbing above."""
    proc = _run_gate()
    # rc 0 (clean) or 1-with-a-GATE-line (a real regression verdict) are
    # both "the gate WORKED"; anything else (usage error, crash, refusal
    # to compare) is the rot this test exists to catch
    if proc.returncode != 0:
        assert "GATE:" in proc.stderr, proc.stdout + proc.stderr
    else:
        assert "no regression" in proc.stdout, proc.stdout + proc.stderr


def test_script_is_executable_and_documented():
    assert SCRIPT.exists()
    assert SCRIPT.stat().st_mode & 0o111, "perf_gate.sh must be executable"
    text = SCRIPT.read_text()
    assert "--gate" in text and "BENCH_GATE_BASELINE" in text
    # PERF.md documents the standing gate (doc.py methodology notes)
    perf_doc = (REPO / "docs" / "PERF.md").read_text()
    assert "perf_gate.sh" in perf_doc
