"""Process-failure plane: the pure-Python symbus broker (bus/pybroker.py —
wire/log parity with native/symbus) and the ProcessSupervisor
(resilience/procsup.py) that turns "resilient in one process" into
"resilient as a deployment".

The `-m chaos` scenarios spawn REAL OS processes and kill them with real
signals (SIGKILL / SIGSTOP) — the same plan `scripts/multiproc.sh` and the
`load_multiproc` bench tier run at full scale.
"""

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _connect(port):
    from symbiont_tpu.bus.tcp import TcpBus

    bus = TcpBus("127.0.0.1", port)
    await bus.connect()
    return bus


# ---------------------------------------------------------------- pybroker


def test_pybroker_pub_sub_queue_groups_and_request_reply():
    """The native broker's core semantics (test_tcp_bus.py's suite) hold
    against the Python twin — same client, same wire, no g++ needed."""
    from symbiont_tpu.bus.pybroker import PyBroker

    async def main():
        broker = PyBroker(port=0)
        await broker.start()
        port = broker.bound_port
        a, b, c = [await _connect(port) for _ in range(3)]
        try:
            # fanout + wildcard + headers
            sub = await b.subscribe("greet.*")
            await asyncio.sleep(0.05)
            await a.publish("greet.world", "привет".encode(),
                            headers={"X-Trace-Id": "t1"})
            msg = await sub.next(2)
            assert msg is not None
            assert msg.subject == "greet.world"
            assert msg.data.decode() == "привет"
            assert msg.headers["X-Trace-Id"] == "t1"

            # queue-group sharding: exactly-once across members
            s1 = await b.subscribe("jobs", queue="workers")
            s2 = await c.subscribe("jobs", queue="workers")
            await asyncio.sleep(0.05)
            for i in range(10):
                await a.publish("jobs", str(i).encode())
            got1 = got2 = 0
            deadline = time.time() + 3
            while got1 + got2 < 10 and time.time() < deadline:
                got1 += (await s1.next(0.05)) is not None
                got2 += (await s2.next(0.05)) is not None
            assert got1 + got2 == 10
            assert got1 > 0 and got2 > 0

            # request-reply + timeout on an unserved subject
            esub = await b.subscribe("svc.echo")

            async def responder():
                m = await esub.next(3)
                await b.publish(m.reply, b"pong:" + m.data)

            task = asyncio.create_task(responder())
            reply = await a.request("svc.echo", b"ping", timeout=3)
            assert reply.data == b"pong:ping"
            await task
            with pytest.raises(TimeoutError):
                await a.request("svc.nobody", b"x", timeout=0.2)
        finally:
            for bus in (a, b, c):
                await bus.close()
            await broker.stop()

    asyncio.run(main())


def test_pybroker_durable_redelivery_filter_and_dead_letter():
    """streams.hpp semantics in the Python twin: ack-after-durable,
    redelivery after ack_wait, filter auto-ack, max_deliver counted
    dead-lettered (drop), stream stats surface."""
    from symbiont_tpu.bus.pybroker import PyBroker

    async def main():
        broker = PyBroker(port=0)
        await broker.start()
        a = await _connect(broker.bound_port)
        b = await _connect(broker.bound_port)
        try:
            await a.add_stream("s", ["data.>"], ack_wait_s=0.15,
                               max_deliver=3)
            d = await b.durable_subscribe("s", "g",
                                          filter_subject="data.keep.*")
            await a.publish("data.keep.1", b"keep")
            await a.publish("data.skip", b"skip")  # outside the filter
            m = await d.next(2)
            assert m is not None and m.data == b"keep"
            assert m.headers["X-Symbus-Deliveries"] == "1"
            # unacked: redelivers with the attempt counted
            m2 = await d.next(2)
            assert m2 is not None and m2.headers["X-Symbus-Deliveries"] == "2"
            m3 = await d.next(2)
            assert m3 is not None and m3.headers["X-Symbus-Deliveries"] == "3"
            # budget exhausted -> dead-lettered (counted, no more retries)
            assert await d.next(0.5) is None
            stats = await a.stream_stats()
            g = stats["s"]["groups"]["g"]
            assert g["dead_lettered"] == 1
            # the filtered-out message was auto-acked: floor past BOTH
            assert g["ack_floor"] == 2
        finally:
            await a.close()
            await b.close()
            await broker.stop()

    asyncio.run(main())


def test_pybroker_symlog_replay_preserves_unacked_work(tmp_path):
    """An UNACKED captured message survives a broker stop/start over the
    same --data-dir and redelivers to a re-attached consumer — the
    streams.hpp .symlog contract, byte-format included, in Python."""
    from symbiont_tpu.bus.pybroker import PyBroker

    async def main():
        broker = PyBroker(port=0, data_dir=str(tmp_path))
        await broker.start()
        a = await _connect(broker.bound_port)
        await a.add_stream("p", ["work.>"], ack_wait_s=0.2, max_deliver=5)
        d = await a.durable_subscribe("p", "g")
        await a.publish("work.1", b"acked")
        m = await d.next(2)
        assert m is not None and m.data == b"acked"
        await a.ack(m)
        await a.publish("work.2", b"survivor")
        m = await d.next(2)
        assert m is not None and m.data == b"survivor"
        # NOT acked: must come back after the restart
        await a.close()
        await broker.stop()

        # the log is the real on-disk artifact (same format as native)
        assert (tmp_path / "p.symlog").exists()

        broker2 = PyBroker(port=0, data_dir=str(tmp_path))
        await broker2.start()
        b = await _connect(broker2.bound_port)
        try:
            d2 = await b.durable_subscribe("p", "g")
            m = await d2.next(3)
            assert m is not None and m.data == b"survivor", m
            assert int(m.headers["X-Symbus-Seq"]) == 2
            await b.ack(m)
            # the acked message from before the restart never reappears
            assert await d2.next(0.5) is None
            stats = await b.stream_stats()
            assert stats["p"]["groups"]["g"]["ack_floor"] == 2
        finally:
            await b.close()
            await broker2.stop()

    asyncio.run(main())


# --------------------------------------------------------------- supervisor

# a deliberately tiny heartbeat worker (no jax import): boots in ~a second,
# beats every 0.15s — the supervisor's contract is exercised by signals,
# not by what the worker computes
_TOY_WORKER = """
import asyncio, sys
from symbiont_tpu.bus.connect import connect

async def main():
    # connect() retries the initial dial (worker and broker start
    # concurrently under the supervisor)
    bus = await connect("symbus://127.0.0.1:" + sys.argv[1])
    while True:
        await bus.publish("_sys.heartbeat." + sys.argv[2], b"{}")
        await asyncio.sleep(0.15)

asyncio.run(main())
"""


def _toy_spec(port: int, role: str, timeout_s: float = 2.0):
    from symbiont_tpu.resilience.procsup import WorkerSpec

    return WorkerSpec(
        role=role,
        argv=[sys.executable, "-c", _TOY_WORKER, str(port), role],
        heartbeat_timeout_s=timeout_s, boot_grace_s=30.0,
        backoff_base_s=0.1, backoff_max_s=1.0)


@pytest.mark.chaos
def test_supervisor_restarts_sigkilled_worker_and_detects_sigstop(tmp_path):
    """The two kill classes the plan throws at workers: SIGKILL (exit-code
    path) restarts with backoff; SIGSTOP (the hang no exit code reveals)
    is detected via stalled heartbeats, SIGKILLed, and restarted. Recovery
    is measured from supervisor liveness confirmations — the same
    machinery behind `load_proc_recovery_s`."""
    from symbiont_tpu.bus.pybroker import PyBroker
    from symbiont_tpu.resilience.procsup import ProcessSupervisor

    async def main():
        broker = PyBroker(port=0, data_dir=str(tmp_path / "bus"))
        await broker.start()
        port = broker.bound_port
        sup = ProcessSupervisor(bus_url=f"symbus://127.0.0.1:{port}",
                                stdio=subprocess.DEVNULL)
        sup.add_worker(_toy_spec(port, "toy"))
        await sup.start()
        try:
            t0 = time.monotonic()
            await sup.wait_role_up("toy", after=t0 - 1, timeout_s=30)

            # SIGKILL → monitor sees rc=-9 → restart
            t_kill = time.monotonic()
            os.kill(sup.pid("toy"), signal.SIGKILL)
            ts = await sup.wait_role_up("toy", after=t_kill, timeout_s=30)
            assert sup.restarts("toy") == 1
            assert ts - t_kill < 15

            # SIGSTOP → heartbeats stall → hang detector SIGKILLs → restart
            t_stop = time.monotonic()
            os.kill(sup.pid("toy"), signal.SIGSTOP)
            ts = await sup.wait_role_up("toy", after=t_stop + 2.0,
                                        timeout_s=30)
            assert sup.restarts("toy") == 2
            assert ts - t_stop < 20
        finally:
            await sup.stop()
            await broker.stop()

    asyncio.run(main())


@pytest.mark.chaos
def test_supervisor_broker_death_is_survived_by_worker_judgment(tmp_path):
    """Kill the BROKER under a supervised fleet: the supervisor must (1)
    restart it, (2) NOT kill healthy workers for the heartbeat gap its
    death caused (the broker-respawn grace), and (3) see worker heartbeats
    resume through the restarted broker."""
    from symbiont_tpu.resilience.procsup import (
        ProcessSupervisor,
        pybroker_spec,
    )

    async def main():
        port = _free_port()
        sup = ProcessSupervisor(bus_url=f"symbus://127.0.0.1:{port}",
                                stdio=subprocess.DEVNULL)
        sup.add_worker(pybroker_spec(port, str(tmp_path / "bus"),
                                     heartbeat_timeout_s=2.0))
        sup.add_worker(_toy_spec(port, "toy", timeout_s=3.0))
        await sup.start()
        try:
            t0 = time.monotonic()
            await sup.wait_role_up("toy", after=t0 - 1, timeout_s=30)
            t_kill = time.monotonic()
            os.kill(sup.pid("broker"), signal.SIGKILL)
            await sup.wait_role_up("broker", after=t_kill, timeout_s=30)
            # worker heartbeats resume over the restarted broker (its
            # client auto-reconnects + re-SUBs)
            await sup.wait_role_up("toy", after=t_kill + 0.5, timeout_s=30)
            assert sup.restarts("broker") == 1
            # the worker was never collateral damage
            assert sup.restarts("toy") == 0
        finally:
            await sup.stop()

    asyncio.run(main())


@pytest.mark.chaos
def test_zero_loss_pipeline_across_worker_sigkill_multiproc(tmp_path):
    """A miniature of the load_multiproc hard gate, cheap enough for the
    chaos suite: durable publisher → consumer PROCESS that acks after
    'storing', SIGKILLed mid-stream — every message lands exactly once
    across the restart (redelivery + idempotent dedup by the consumer)."""
    from symbiont_tpu.bus.pybroker import PyBroker
    from symbiont_tpu.resilience.procsup import ProcessSupervisor, WorkerSpec

    consumer_src = """
import asyncio, sys
from pathlib import Path
from symbiont_tpu.bus.tcp import TcpBus

async def main():
    out = Path(sys.argv[2])
    bus = TcpBus("127.0.0.1", int(sys.argv[1]))
    await bus.connect()
    await bus.add_stream("w", ["job.>"], ack_wait_s=0.5, max_deliver=20)
    sub = await bus.durable_subscribe("w", "g")
    hb = asyncio.get_running_loop().create_task(beat(bus))
    while True:
        msg = await sub.next(1.0)
        if msg is None:
            continue
        # idempotent append (dedup on read side); fsync BEFORE ack —
        # the ack-after-durable contract under test
        with open(out, "a") as f:
            f.write(msg.data.decode() + "\\n")
            f.flush()
        await bus.ack(msg)

async def beat(bus):
    while True:
        await bus.publish("_sys.heartbeat.consumer", b"{}")
        await asyncio.sleep(0.15)

asyncio.run(main())
"""

    async def main():
        broker = PyBroker(port=0, data_dir=str(tmp_path / "bus"))
        await broker.start()
        port = broker.bound_port
        out = tmp_path / "landed.txt"
        sup = ProcessSupervisor(bus_url=f"symbus://127.0.0.1:{port}",
                                stdio=subprocess.DEVNULL)
        sup.add_worker(WorkerSpec(
            role="consumer",
            argv=[sys.executable, "-c", consumer_src, str(port), str(out)],
            heartbeat_timeout_s=3.0, backoff_base_s=0.1, backoff_max_s=1.0))
        await sup.start()
        pub = await _connect(port)
        try:
            t0 = time.monotonic()
            await sup.wait_role_up("consumer", after=t0 - 1, timeout_s=30)
            for i in range(10):
                await pub.publish(f"job.{i}", f"m{i}".encode())
            # let some land, then kill mid-stream
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if out.exists() and len(out.read_text().splitlines()) >= 2:
                    break
                await asyncio.sleep(0.02)
            t_kill = time.monotonic()
            os.kill(sup.pid("consumer"), signal.SIGKILL)
            for i in range(10, 20):
                await pub.publish(f"job.{i}", f"m{i}".encode())
            await sup.wait_role_up("consumer", after=t_kill, timeout_s=30)
            deadline = time.monotonic() + 30
            want = {f"m{i}" for i in range(20)}
            got = set()
            while time.monotonic() < deadline:
                if out.exists():
                    got = set(out.read_text().splitlines())
                if want <= got:
                    break
                await asyncio.sleep(0.1)
            assert want <= got, sorted(want - got)
        finally:
            await pub.close()
            await sup.stop()
            await broker.stop()

    asyncio.run(main())
