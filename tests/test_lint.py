"""Contract-linter proof suite (symbiont_tpu/lint/, docs/LINTING.md).

Three contracts, each proven here:

1. every rule family FIRES — synthetic known-violation trees under
   tmp_path run through the same engine the CLI uses, and each seeded
   violation produces its finding;
2. the allowlist machinery works both ways — a matching entry suppresses
   exactly its site, and a stale entry (no matching site) is itself an
   error (the ratchet);
3. the real repo is CLEAN — ``python -m symbiont_tpu.lint`` exits 0 with
   every allowlist entry still live (the acceptance bar: the linter runs
   in tier-1, so a new violation or a dead waiver fails CI).
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from symbiont_tpu.lint import LintContext, repo_root, run

pytestmark = pytest.mark.lint

REPO = repo_root()


def _write(root: Path, rel: str, body: str) -> None:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(body))


def _rules_of(findings):
    return {f.rule for f in findings}


def _run(root, rule_ids=None, allowlists=None):
    findings, ctx = run(root=root, rule_ids=rule_ids,
                        allowlists=allowlists if allowlists is not None
                        else {})
    return findings, ctx


# --------------------------------------------------------------- wiring


def _wiring_tree(tmp_path: Path) -> Path:
    _write(tmp_path, "symbiont_tpu/subjects.py", '''
        GOOD_SUB = "tasks.good"
        DEAD_SUB = "data.dead.limb"
        UNCONSUMED = "events.unconsumed"
        ALL_SUBJECTS = [GOOD_SUB, UNCONSUMED]
        ''')
    _write(tmp_path, "symbiont_tpu/services/svc.py", '''
        from symbiont_tpu import subjects

        class Svc:
            async def setup(self, bus):
                await bus.subscribe(subjects.GOOD_SUB)
                await bus.subscribe(subjects.DEAD_SUB)

            async def emit(self, bus):
                await bus.publish(subjects.GOOD_SUB, b"{}")
                await bus.publish(subjects.UNCONSUMED, b"{}")
        ''')
    return tmp_path


def test_dead_limb_rule_fires(tmp_path):
    findings, _ = _run(_wiring_tree(tmp_path),
                       rule_ids=["subject-dead-limb"])
    dead = [f for f in findings if f.rule == "subject-dead-limb"]
    assert len(dead) == 1 and "data.dead.limb" in dead[0].message
    duplex = [f for f in findings if f.rule == "subject-full-duplex"]
    assert len(duplex) == 1 and "events.unconsumed" in duplex[0].message


def test_dead_limb_allowlist_suppresses_and_goes_stale(tmp_path):
    root = _wiring_tree(tmp_path)
    # live entry: DEAD_SUB is still subscribed -> suppressed, not stale
    findings, ctx = _run(root, rule_ids=["subject-dead-limb"],
                         allowlists={"subject-unproduced":
                                     {"DEAD_SUB": "test"}})
    assert not [f for f in findings if f.rule == "subject-dead-limb"]
    assert not [f for f in findings if f.rule == "stale-allowlist"]
    # stale entry: names a subject nothing subscribes
    findings, _ = _run(root, rule_ids=["subject-dead-limb"],
                       allowlists={"subject-unproduced":
                                   {"DEAD_SUB": "t", "NEVER_SEEN": "t"}})
    stale = [f for f in findings if f.rule == "stale-allowlist"]
    assert len(stale) == 1 and "NEVER_SEEN" in stale[0].message


# ------------------------------------------------------------ data plane


def _dataplane_tree(tmp_path: Path) -> Path:
    _write(tmp_path, "symbiont_tpu/services/hot.py", '''
        from dataclasses import asdict

        class Hot:
            async def handle(self, msg):
                vec = [float(x) for x in msg.data]
                d = asdict(msg)
                return vec, d, "f16"
        ''')
    return tmp_path


def test_dataplane_rules_fire(tmp_path):
    findings, _ = _run(_dataplane_tree(tmp_path),
                       rule_ids=["no-per-float-conversion",
                                 "no-asdict-on-ingest",
                                 "no-hardcoded-frame-dtype"])
    assert _rules_of(findings) >= {"no-per-float-conversion",
                                   "no-asdict-on-ingest",
                                   "no-hardcoded-frame-dtype"}
    # sites carry the dotted scope the allowlist keys on
    assert any("Hot.handle" in f.message for f in findings)


def test_dataplane_allowlist_is_site_exact(tmp_path):
    root = _dataplane_tree(tmp_path)
    allow = {"no-per-float-conversion":
             {("symbiont_tpu/services/hot.py", "Hot.handle"): "test"}}
    findings, _ = _run(root, rule_ids=["no-per-float-conversion"],
                       allowlists=allow)
    assert not findings  # suppressed AND live -> nothing, not even stale
    # a different scope does not match -> finding stands, entry stale
    allow = {"no-per-float-conversion":
             {("symbiont_tpu/services/hot.py", "Hot.other"): "test"}}
    findings, _ = _run(root, rule_ids=["no-per-float-conversion"],
                       allowlists=allow)
    assert _rules_of(findings) == {"no-per-float-conversion",
                                   "stale-allowlist"}


# ------------------------------------------------------- event loop rule


def test_blocking_call_rule_fires_per_category(tmp_path):
    _write(tmp_path, "symbiont_tpu/services/blocky.py", '''
        import time

        class Blocky:
            async def handle(self, msg):
                time.sleep(0.1)
                with open("/tmp/x") as f:
                    f.read()
                self.store.search([1.0], 5)
                with self._lock:
                    pass

            async def indirect(self):
                self._sync_io()

            def _sync_io(self):
                with open("/tmp/y") as f:
                    return f.read()
        ''')
    findings, _ = _run(tmp_path, rule_ids=["async-blocking-call"])
    msgs = "\n".join(f.message for f in findings)
    assert "time.sleep" in msgs
    assert "open()" in msgs
    assert "store/graph call" in msgs
    assert "with self._lock" in msgs
    # one level of self-method indirection is resolved for I/O categories
    assert any("indirect" in f.message and "_sync_io" in f.message
               for f in findings)
    # executor-routed work (nested lambda/def scopes) is NOT flagged
    _write(tmp_path, "symbiont_tpu/services/clean.py", '''
        import asyncio

        class Clean:
            async def handle(self, msg):
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, lambda: open("/tmp/z"))
                await loop.run_in_executor(None, self.store.search, [1], 5)
        ''')
    findings, _ = _run(tmp_path, rule_ids=["async-blocking-call"])
    assert not [f for f in findings if "clean.py" in f.file]


# -------------------------------------------------------------- lock order


def test_lock_order_cycle_and_self_deadlock_fire(tmp_path):
    _write(tmp_path, "symbiont_tpu/engine/locky.py", '''
        import threading

        class AB:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._b_lock:
                    self._take_a()

            def _take_a(self):
                with self._a_lock:
                    pass

        class Re:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        ''')
    findings, _ = _run(tmp_path, rule_ids=["lock-order-cycle"])
    rules = _rules_of(findings)
    assert "lock-order-cycle" in rules, findings
    assert "lock-self-deadlock" in rules, findings
    cycle = next(f for f in findings if f.rule == "lock-order-cycle")
    assert "locky.AB._a_lock" in cycle.message
    assert "locky.AB._b_lock" in cycle.message
    # RLock re-entry is legal and silent
    _write(tmp_path, "symbiont_tpu/engine/relock.py", '''
        import threading

        class Ok:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        ''')
    findings, _ = _run(tmp_path, rule_ids=["lock-order-cycle"])
    assert not [f for f in findings if "relock" in f.message]
    # a canonical-cycle allowlist entry suppresses exactly that cycle
    # (lock ids are repo-relative dotted module paths — stems would
    # collide across scope dirs)
    mod = "symbiont_tpu.engine.locky"
    allow = {"lock-order": {
        f"{mod}.AB._a_lock -> {mod}.AB._b_lock -> {mod}.AB._a_lock": "t",
        f"{mod}.Re._lock -> {mod}.Re._lock": "t"}}
    findings, _ = _run(tmp_path, rule_ids=["lock-order-cycle"],
                       allowlists=allow)
    assert not findings, findings


# ------------------------------------------------------------ jax hygiene


def test_jax_static_args_rule_fires(tmp_path):
    _write(tmp_path, "symbiont_tpu/models/badjit.py", '''
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("cfgg",))
        def step(params, x, cfg):
            return x

        def per_call(x):
            fn = jax.jit(lambda y: y + 1)
            return fn(x)
        ''')
    findings, _ = _run(tmp_path, rule_ids=["jax-static-args",
                                           "jax-jit-in-function"])
    msgs = "\n".join(f.message for f in findings)
    assert "'cfgg'" in msgs and "names no parameter" in msgs
    assert "config param 'cfg'" in msgs
    assert any(f.rule == "jax-jit-in-function" for f in findings)


def test_jax_host_sync_rule_fires(tmp_path):
    _write(tmp_path, "symbiont_tpu/engine/engine.py", '''
        import numpy as np

        class E:
            def dispatch(self, batches):
                out = []
                for b in batches:
                    out.append(np.asarray(b))
                return out

            def scalar(self, x):
                return x.item()
        ''')
    findings, _ = _run(tmp_path, rule_ids=["jax-host-sync-in-loop"])
    msgs = "\n".join(f.message for f in findings)
    assert "np.asarray" in msgs and ".item()" in msgs
    # host-data literals (list comprehensions etc.) are not device pulls
    _write(tmp_path, "symbiont_tpu/engine/engine.py", '''
        import numpy as np

        class E:
            def dispatch(self, widths):
                for w in widths:
                    lens = np.asarray([min(w, 8) for _ in range(3)])
                return lens
        ''')
    findings, _ = _run(tmp_path, rule_ids=["jax-host-sync-in-loop"])
    assert not findings


def test_nested_def_sites_report_once_under_their_own_scope(tmp_path):
    """A violation inside a closure must yield ONE finding, named by the
    closure's dotted scope (an allowlist entry has exactly one spelling)."""
    _write(tmp_path, "symbiont_tpu/engine/engine.py", '''
        import numpy as np

        class E:
            def outer(self, xs):
                def inner(v):
                    return v.item()
                return [inner(x) for x in xs]
        ''')
    findings, _ = _run(tmp_path, rule_ids=["jax-host-sync-in-loop"])
    assert len(findings) == 1, findings
    assert "E.outer.inner" in findings[0].message


def test_wait_for_event_wait_idiom_not_flagged(tmp_path):
    """`await asyncio.wait_for(event.wait(), t)` is the standard asyncio
    idiom — the un-awaited-.wait() check must not fire on calls anywhere
    under an await expression."""
    _write(tmp_path, "symbiont_tpu/services/waity.py", '''
        import asyncio

        class W:
            async def handle(self):
                await asyncio.wait_for(self._ready.wait(), timeout=5)

            async def bad(self, w):
                w.proc.wait(timeout=5)
        ''')
    findings, _ = _run(tmp_path, rule_ids=["async-blocking-call"])
    assert len(findings) == 1, findings
    assert "W.bad" in findings[0].message and "proc.wait" in findings[0].message


# ------------------------------------------------------------- cpp parity


def _parity_tree(tmp_path: Path) -> Path:
    _write(tmp_path, "symbiont_tpu/subjects.py", '''
        TASKS_GOOD = "tasks.good"
        ALL_SUBJECTS = []
        ''')
    _write(tmp_path, "symbiont_tpu/utils/telemetry.py", '''
        TRACE_HEADER = "X-Trace-Id"
        TENANT_HEADER = "X-Symbiont-Tenant"
        ''')
    _write(tmp_path, "symbiont_tpu/schema/frames.py", '''
        import struct
        FRAME_HEADER = "X-Symbiont-Frame"
        FRAME_MAGIC = b"SYTF"
        FRAME_VERSION = 1
        DTYPE_F32 = 1
        DTYPE_F16 = 2
        _HDR = struct.Struct("<4sBBHII")
        _SIZE_BY_DTYPE = {DTYPE_F32: 4, DTYPE_F16: 2}
        ''')
    _write(tmp_path, "symbiont_tpu/runner.py", '''
        import json, os

        class Stack:
            async def _heartbeat_loop(self, role, interval_s):
                payload = json.dumps({"role": role, "pid": os.getpid()})
                return payload
        ''')
    _write(tmp_path, "native/services/common.hpp", '''
        inline const char* TASKS_GOOD = "tasks.goodX";
        inline const char* TENANT_HEADER = "X-Symbiont-Ten4nt";
        constexpr uint8_t FRAME_VERSION = 1;
        constexpr uint8_t FRAME_DTYPE_F32 = 1;
        constexpr uint8_t FRAME_DTYPE_F16 = 9;
        constexpr size_t FRAME_HDR_LEN = 12;
        // "SYTF" magic; only tensor/f32 wired here
        inline const char* ct = "tensor/f32";
        inline size_t frame_elem_size(uint8_t dtype) {
          if (dtype == FRAME_DTYPE_F32) return 4;
          if (dtype == FRAME_DTYPE_F16) return 2;
          return 0;
        }
        inline std::string heartbeat_payload(const std::string& role) {
          std::string out = "{\\"role\\": \\"";
          out += "\\", \\"pid_\\": ";
          return out;
        }
        ''')
    _write(tmp_path, "native/services/rogue.cpp", '''
        #include "common.hpp"
        int main() {
          bus.publish("engine.subject.nobody.serves", "{}");
          headers["X-Symbiont-Unknown"] = "1";
        }
        ''')
    return tmp_path


def test_cpp_parity_rule_fires_on_every_surface(tmp_path):
    findings, _ = _run(_parity_tree(tmp_path), rule_ids=["cpp-parity"])
    msgs = "\n".join(f.message for f in findings)
    assert "subject constant TASKS_GOOD drifted" in msgs
    assert "header constant TENANT_HEADER drifted" in msgs
    assert "dtype byte drifted for 'f16'" in msgs
    assert "'tensor/f16' missing" in msgs
    assert "header length drifted" in msgs
    assert "heartbeat payload fields drifted" in msgs
    assert "engine.subject.nobody.serves" in msgs
    assert "X-Symbiont-Unknown" in msgs


# -------------------------------------------------------------- knob drift


def test_knob_doc_drift_rule_fires(tmp_path):
    _write(tmp_path, "symbiont_tpu/mod.py", '''
        import os
        A = os.environ.get("SYMBIONT_DOCUMENTED_KNOB")
        B = os.environ.get("SYMBIONT_SECRET_KNOB")
        ''')
    _write(tmp_path, "native/services/shell.cpp", '''
        auto v = env_or("SYMBIONT_SECRET_CPP_KNOB", "1");
        ''')
    _write(tmp_path, "docs/KNOBS.md",
           "| `SYMBIONT_DOCUMENTED_KNOB` | documented |\n")
    findings, _ = _run(tmp_path, rule_ids=["knob-doc-drift"])
    names = "\n".join(f.message for f in findings)
    assert "SYMBIONT_SECRET_KNOB" in names
    assert "SYMBIONT_SECRET_CPP_KNOB" in names
    assert "SYMBIONT_DOCUMENTED_KNOB" not in names


# ------------------------------------------------------- engine plumbing


def test_unparseable_file_is_a_finding(tmp_path):
    _write(tmp_path, "symbiont_tpu/services/broken.py",
           "def f(:\n    pass\n")
    findings, _ = _run(tmp_path, rule_ids=["async-blocking-call"])
    assert any(f.rule == "lint-parse" for f in findings)


def test_unknown_rule_id_raises():
    with pytest.raises(KeyError):
        run(root=REPO, rule_ids=["no-such-rule"], allowlists={})


def test_findings_render_structured(tmp_path):
    findings, _ = _run(_dataplane_tree(tmp_path),
                       rule_ids=["no-asdict-on-ingest"])
    line = findings[0].render()
    # file:line rule-id severity message
    head, rule, sev = line.split(" ", 2)[0], line.split(" ")[1], \
        line.split(" ")[2]
    assert head.startswith("symbiont_tpu/services/hot.py:")
    assert rule == "no-asdict-on-ingest" and sev == "error"


# ------------------------------------------------------- the real repo


def test_repo_is_clean_with_live_allowlists():
    """The acceptance bar: zero findings on the real tree, every central
    allowlist entry still live (run through the engine, not the CLI, so a
    failure names the findings)."""
    findings, _ctx = run(root=REPO)  # central allowlists
    assert not findings, "\n" + "\n".join(f.render() for f in findings)


def test_cli_exit_codes(tmp_path):
    env_repo = subprocess.run(
        [sys.executable, "-m", "symbiont_tpu.lint"],
        cwd=REPO, capture_output=True, text=True)
    assert env_repo.returncode == 0, env_repo.stdout + env_repo.stderr
    root = _dataplane_tree(tmp_path)
    dirty = subprocess.run(
        [sys.executable, "-m", "symbiont_tpu.lint", "--root", str(root),
         "--rules", "no-asdict-on-ingest"],
        cwd=REPO, capture_output=True, text=True)
    assert dirty.returncode == 1
    assert "no-asdict-on-ingest error" in dirty.stdout
    usage = subprocess.run(
        [sys.executable, "-m", "symbiont_tpu.lint", "--rules", "bogus"],
        cwd=REPO, capture_output=True, text=True)
    assert usage.returncode == 2


def test_cli_list_rules():
    out = subprocess.run(
        [sys.executable, "-m", "symbiont_tpu.lint", "--list"],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 0
    for rid in ("subject-dead-limb", "async-blocking-call",
                "lock-order-cycle", "jax-static-args", "cpp-parity",
                "knob-doc-drift"):
        assert rid in out.stdout
