"""Vector store tests: ensure/upsert/search parity, durability, sharding."""

import numpy as np
import pytest

import jax

from symbiont_tpu.config import VectorStoreConfig
from symbiont_tpu.memory import VectorStore


def _cfg(tmp_path=None, **kw):
    kw.setdefault("dim", 8)
    kw.setdefault("shard_capacity", 16)
    return VectorStoreConfig(data_dir=str(tmp_path) if tmp_path else "", **kw)


def _unit(v):
    v = np.asarray(v, np.float32)
    return v / np.linalg.norm(v)


def test_upsert_and_search_exact_cosine_order():
    store = VectorStore(_cfg())
    store.ensure_collection()
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(20, 8)).astype(np.float32)
    store.upsert([(f"p{i}", vecs[i], {"sentence_text": f"s{i}", "sentence_order": i})
                  for i in range(20)])
    q = vecs[7]
    hits = store.search(q, top_k=5)
    assert hits[0].id == "p7"
    assert hits[0].score == pytest.approx(1.0, abs=2e-2)  # bf16 matmul
    # scores descending, exact order matches numpy cosine
    cos = (vecs @ _unit(q)) / np.linalg.norm(vecs, axis=1)
    expect = [f"p{i}" for i in np.argsort(-cos)[:5]]
    assert [h.id for h in hits] == expect
    assert hits[0].payload["sentence_text"] == "s7"


def test_top_k_larger_than_corpus():
    store = VectorStore(_cfg())
    store.upsert([("a", np.ones(8), {}), ("b", -np.ones(8), {})])
    hits = store.search(np.ones(8), top_k=10)
    assert [h.id for h in hits] == ["a", "b"]


def test_upsert_overwrites_existing_id():
    store = VectorStore(_cfg())
    store.upsert([("x", _unit(np.arange(1, 9)), {"v": 1})])
    store.upsert([("x", -_unit(np.arange(1, 9)), {"v": 2})])
    assert store.count() == 1
    hits = store.search(-np.arange(1, 9, dtype=np.float32), top_k=1)
    assert hits[0].payload["v"] == 2
    assert hits[0].score > 0.9


def test_dim_mismatch_raises():
    store = VectorStore(_cfg())
    with pytest.raises(ValueError, match="dim"):
        store.upsert([("bad", np.ones(5), {})])
    store.upsert([("ok", np.ones(8), {})])
    with pytest.raises(ValueError):
        store.ensure_collection(dim=16)  # existing data at dim 8
    with pytest.raises(ValueError, match="dim"):
        store.search(np.ones(3), top_k=1)


def test_empty_store_and_zero_k():
    store = VectorStore(_cfg())
    assert store.search(np.ones(8), top_k=3) == []
    store.upsert([("a", np.ones(8), {})])
    assert store.search(np.ones(8), top_k=0) == []


def test_growth_across_capacity_blocks():
    store = VectorStore(_cfg())  # shard_capacity 16
    rng = np.random.default_rng(1)
    vecs = rng.normal(size=(40, 8)).astype(np.float32)  # 3 blocks
    for i in range(40):
        store.upsert([(f"p{i}", vecs[i], {})])
    hits = store.search(vecs[33], top_k=1)
    assert hits[0].id == "p33"


def test_wal_durability_and_reload(tmp_path):
    store = VectorStore(_cfg(tmp_path))
    rng = np.random.default_rng(2)
    vecs = rng.normal(size=(5, 8)).astype(np.float32)
    store.upsert([(f"p{i}", vecs[i], {"i": i}) for i in range(5)])
    # simulate crash: new store instance on same dir, no compact
    store2 = VectorStore(_cfg(tmp_path))
    assert store2.count() == 5
    assert store2.search(vecs[3], top_k=1)[0].id == "p3"


def test_compact_then_reload_with_wal_tail(tmp_path):
    store = VectorStore(_cfg(tmp_path))
    rng = np.random.default_rng(3)
    vecs = rng.normal(size=(6, 8)).astype(np.float32)
    store.upsert([(f"p{i}", vecs[i], {}) for i in range(4)])
    store.compact()
    store.upsert([(f"p{i}", vecs[i], {}) for i in range(4, 6)])  # post-snapshot WAL
    store3 = VectorStore(_cfg(tmp_path))
    assert store3.count() == 6
    assert store3.search(vecs[5], top_k=1)[0].id == "p5"


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_search_matches_unsharded():
    from symbiont_tpu.parallel import build_mesh

    rng = np.random.default_rng(4)
    vecs = rng.normal(size=(64, 8)).astype(np.float32)
    points = [(f"p{i}", vecs[i], {}) for i in range(64)]
    plain = VectorStore(_cfg())
    plain.upsert(points)
    sharded = VectorStore(_cfg(), mesh=build_mesh())
    sharded.upsert(points)
    q = rng.normal(size=8).astype(np.float32)
    h1 = [h.id for h in plain.search(q, top_k=8)]
    h2 = [h.id for h in sharded.search(q, top_k=8)]
    assert h1 == h2


def test_load_counts_skipped_corrupt_wal_lines(tmp_path, caplog):
    """A pre-r5 rollback skips r5 `vector_b64` WAL records as corrupt —
    silent data loss. The count is now surfaced: one warning with the
    number, and `last_load_skipped_lines` for programmatic checks
    (flush-before-rollback requirement documented in docs/DEPLOYMENT.md)."""
    import json as _json
    import logging

    store = VectorStore(_cfg(tmp_path))
    rng = np.random.default_rng(11)
    vecs = rng.normal(size=(3, 8)).astype(np.float32)
    store.upsert([(f"p{i}", vecs[i], {"i": i}) for i in range(3)])
    assert store.last_load_skipped_lines == 0
    wal = tmp_path / f"{store.config.collection}.wal.jsonl"
    with open(wal, "a", encoding="utf-8") as f:
        f.write("{not json at all\n")
        f.write(_json.dumps({"id": "q1", "unknown_format": [1, 2]}) + "\n")
    with caplog.at_level(logging.WARNING,
                         logger="symbiont_tpu.memory.vector_store"):
        store2 = VectorStore(_cfg(tmp_path))
    assert store2.count() == 3  # intact records still load
    assert store2.last_load_skipped_lines == 2
    assert any("skipped 2" in r.getMessage() for r in caplog.records)


def test_clean_load_reports_zero_skipped(tmp_path):
    store = VectorStore(_cfg(tmp_path))
    rng = np.random.default_rng(12)
    store.upsert([("a", rng.normal(size=8).astype(np.float32), {})])
    store2 = VectorStore(_cfg(tmp_path))
    assert store2.count() == 1
    assert store2.last_load_skipped_lines == 0
