"""Checked-in golden vectors vs the live JAX engine — NO torch needed.

The counterpart of scripts/make_goldens.py (see its docstring for the full
flow): where a real checkpoint exists, this validates the whole
load-convert-tokenize-embed path against transformers outputs computed
offline and checked in — so a slim TPU host never needs torch to prove
semantic fidelity (VERDICT r3 item 8's fallback path).

Gated on BOTH env vars; skipped otherwise (this sandbox has no egress, so
no real checkpoint — and therefore no checked-in goldens — exist yet):

    SYMBIONT_MODEL_DIR=models/minilm \
    SYMBIONT_GOLDEN_FILE=tests/goldens/minilm.npz \
    python -m pytest tests/test_golden_vectors.py -q
"""

import hashlib
import os
from pathlib import Path

import numpy as np
import pytest

REAL_DIR = os.environ.get("SYMBIONT_MODEL_DIR")
GOLDEN_FILE = os.environ.get("SYMBIONT_GOLDEN_FILE")


@pytest.mark.skipif(
    not (REAL_DIR and GOLDEN_FILE),
    reason="needs SYMBIONT_MODEL_DIR + SYMBIONT_GOLDEN_FILE — fetch a "
    "checkpoint (scripts/fetch_model.py) and emit goldens "
    "(scripts/make_goldens.py) where egress exists")
def test_engine_matches_checked_in_goldens():
    from symbiont_tpu.config import EngineConfig
    from symbiont_tpu.engine.engine import TpuEngine

    g = np.load(GOLDEN_FILE, allow_pickle=False)
    # the goldens must belong to THIS checkpoint, not a sibling
    cfg_sha = hashlib.sha256(
        (Path(REAL_DIR) / "config.json").read_bytes()).hexdigest()
    assert str(g["config_sha"]) == cfg_sha, (
        "golden file was generated from a different checkpoint")

    eng = TpuEngine(EngineConfig(model_dir=REAL_DIR, dtype="float32",
                                 data_parallel=False))
    texts = [str(t) for t in g["texts"]]
    ours = eng.embed_texts(texts)
    ref = g["embeddings"]
    assert ours.shape == ref.shape
    cos = (ours * ref).sum(-1) / (
        np.linalg.norm(ours, axis=-1) * np.linalg.norm(ref, axis=-1))
    assert cos.min() > 0.999, cos
    # semantic sanity on the canonical corpus: the paraphrase pair (0, 1)
    # outranks the unrelated pair (0, 2)
    n = ours / np.linalg.norm(ours, axis=-1, keepdims=True)
    assert n[0] @ n[1] > n[0] @ n[2]
