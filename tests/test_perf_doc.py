"""docs/PERF.md is RENDERED from an archived bench line, never hand-edited.

Round-2 verdict weak #1: the doc quoted an unarchived run with transposed
TTFT rows. The fix is mechanical rendering (`python bench.py --render-doc
BENCH_rNN.json > docs/PERF.md`); this test re-renders from the archive the
doc names in its header and asserts the committed file matches byte-for-byte
— every number in the doc therefore provably comes from the archived JSON.
"""

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def _doc_and_archive():
    doc = (REPO / "docs" / "PERF.md").read_text()
    m = re.search(r"Rendered from `(BENCH_(?:r\d+|LATEST)\.json)`", doc)
    assert m, "PERF.md must name its source archive in the header"
    name = m.group(1)
    archive = REPO / name
    assert archive.exists(), f"named archive {name} missing from repo root"
    return doc, archive, name


def test_perf_doc_not_stale():
    """The doc must render from the NEWEST measurement present (VERDICT r3
    weak #2: the doc sat on a favorable old round with the suite green).
    Naming an old BENCH_rNN while a newer round's archive exists fails;
    BENCH_LATEST.json (written by every full `python bench.py` run) must be
    at least as recent as the newest driver archive."""
    _, archive, name = _doc_and_archive()
    rounds = list(REPO.glob("BENCH_r[0-9]*.json"))
    if not rounds:
        return
    newest = max(rounds,
                 key=lambda p: int(re.search(r"r(\d+)", p.name).group(1)))
    if name.startswith("BENCH_r"):
        assert name == newest.name, (
            f"docs/PERF.md renders {name} but {newest.name} exists — "
            f"regenerate: python bench.py --render-doc {newest.name} "
            f"> docs/PERF.md (or run a full bench)")
    else:
        latest = bench.load_archive(archive)
        newest_parsed = bench.load_archive(newest)
        assert (latest.get("ts", 0) >= newest_parsed.get("ts", 0)
                or latest == newest_parsed), (
            f"BENCH_LATEST.json is older than {newest.name} — rerun "
            f"python bench.py (full) to refresh the doc")


def test_perf_doc_matches_named_archive_exactly():
    doc, archive, name = _doc_and_archive()
    rendered = bench.render_doc(bench.load_archive(archive), name)
    assert doc == rendered, (
        "docs/PERF.md differs from its archive render — regenerate with "
        f"`python bench.py --render-doc {name} > docs/PERF.md`")


def test_every_table_value_is_an_archive_field():
    """Belt-and-braces on top of byte equality: each numeric cell in the doc
    table corresponds to a field value in the archived JSON line."""
    doc, archive, _ = _doc_and_archive()
    data = bench.load_archive(archive)
    archived = {bench._fmt(v) for v in data.values()
                if isinstance(v, (int, float))}
    for row in doc.splitlines():
        if not row.startswith("| `"):
            continue
        cells = [c.strip() for c in row.strip("|").split("|")]
        nums = re.findall(r"[\d,]+\.?\d*", cells[2])
        for n in nums:
            assert n in archived, (n, row)


def test_render_doc_needs_no_device():
    """Doc rendering must work in a CPU-only checkout (no jax import)."""
    out = bench.render_doc(bench.load_archive(REPO / "BENCH_r02.json"),
                           "BENCH_r02.json")
    assert out.startswith("# Measured performance")
    assert "9,890.4" in out  # the archived primary value


def test_load_archive_accepts_raw_line(tmp_path):
    """The driver wraps the line in {..., "parsed": {...}}; a raw line from
    `python bench.py > out.json` must load identically."""
    import json

    raw = {"metric": "m", "value": 1.5, "unit": "u", "vs_baseline": 2.0}
    p = tmp_path / "raw.json"
    p.write_text(json.dumps(raw))
    assert bench.load_archive(p) == raw
