"""Static pipeline-wiring checks — now a thin shim over the contract
linter (symbiont_tpu/lint/, docs/LINTING.md).

The scans that used to live inline here (subject wiring vs call sites,
the per-float / asdict / frame-dtype data-plane bans) graduated into lint
rules in PR 12; this file keeps the original test NAMES green while
delegating to the same engine `python -m symbiont_tpu.lint` runs, so the
contracts stay pinned from tier-1 exactly as before — plus the scanner
ground-truth self-check that keeps the shared scan from rotting into
vacuous passes.

History preserved in the rule docstrings: the reference SHIPPED a dead
limb (knowledge_graph_service subscribed data.processed_text.tokenized
while nothing published it — SURVEY.md fact #3); the dead-limb rule makes
that bug class impossible to reintroduce.
"""

from __future__ import annotations

import pytest

from symbiont_tpu.lint import LintContext, repo_root, run
from symbiont_tpu.lint.rules import wiring

pytestmark = pytest.mark.lint

REPO = repo_root()


def _findings(rule_ids):
    """Run the named rules over the real repo with the CENTRAL allowlists
    (the same invocation the CLI makes), split into (violations, stale)."""
    findings, _ = run(root=REPO, rule_ids=rule_ids)
    stale = [f for f in findings if f.rule == "stale-allowlist"]
    real = [f for f in findings if f.rule != "stale-allowlist"]
    return real, stale


def _render(fs):
    return "\n".join(f.render() for f in fs)


# ----------------------------------------------------------- subject wiring


def test_no_subscribed_but_never_published_subject():
    real, _ = _findings(["subject-dead-limb"])
    assert not real, _render(real)


def test_allowlist_entries_are_still_served():
    """The allowlist documents SERVED endpoints without in-repo callers;
    if the subscription disappears the entry is stale — prune it."""
    _, stale = _findings(["subject-dead-limb"])
    assert not stale, _render(stale)


def test_pipeline_subjects_have_consumers_and_producers():
    """Both directions for the reference-parity pipeline subjects
    (ALL_SUBJECTS): the full-duplex wiring SURVEY.md §1-L3 documents.
    (The engine emits these as subject-full-duplex findings from the same
    rule pass.)"""
    real, _ = _findings(["subject-dead-limb"])
    assert not [f for f in real if f.rule == "subject-full-duplex"], \
        _render(real)


# --------------------------------------------------------------- data plane


def test_no_per_float_conversion_on_message_paths():
    real, _ = _findings(["no-per-float-conversion"])
    assert not real, _render(real)


def test_float_list_allowlist_entries_still_exist():
    _, stale = _findings(["no-per-float-conversion"])
    assert not stale, _render(stale)


def test_no_dataclass_asdict_on_ingest_services():
    real, _ = _findings(["no-asdict-on-ingest"])
    assert not real, _render(real)


def test_asdict_allowlist_entries_still_exist():
    _, stale = _findings(["no-asdict-on-ingest"])
    assert not stale, _render(stale)


def test_no_hardcoded_frame_dtype_in_services():
    real, _ = _findings(["no-hardcoded-frame-dtype"])
    assert not real, _render(real)


def test_frame_dtype_allowlist_entries_still_exist():
    _, stale = _findings(["no-hardcoded-frame-dtype"])
    assert not stale, _render(stale)


# ---------------------------------------------------- scanner ground truth


def test_scanner_sees_known_ground_truth():
    """Self-check so the scanner can't silently rot into vacuous passes:
    a few known call sites must classify as expected."""
    ctx = LintContext(REPO)
    producers, consumers = wiring.scan(ctx)
    # api publishes the perceive task; perception consumes it
    assert any("services/api.py" in f
               for f in producers["TASKS_PERCEIVE_URL"])
    assert any("services/perception.py" in f
               for f in consumers["TASKS_PERCEIVE_URL"])
    # the un-orphaned subject: preprocessing produces, knowledge_graph eats
    assert any("services/preprocessing.py" in f
               for f in producers["DATA_PROCESSED_TEXT_TOKENIZED"])
    assert any("services/knowledge_graph.py" in f
               for f in consumers["DATA_PROCESSED_TEXT_TOKENIZED"])
    # engine_service's aliased `await sub(...)` sites are seen as consumers
    assert any("services/engine_service.py" in f
               for f in consumers["ENGINE_HEALTH"])
    # native C++ engine_call sites are seen as producers
    assert any(f.startswith("native/")
               for f in producers.get("ENGINE_VECTOR_SEARCH", set())), \
        "native engine_call producer sites not detected"
