"""Static pipeline-wiring check: subjects.py vs actual call sites.

The reference SHIPPED a dead limb — knowledge_graph_service subscribed
`data.processed_text.tokenized` while nothing published it (SURVEY.md fact
#3, reference CHANGELOG.md:57-60): the whole knowledge-graph path was
silently inert in v0.3.0. This test makes that bug class impossible to
reintroduce here: it walks every Python AND native C++ source for
`subjects.<NAME>` / `subjects::<NAME>` (and literal subject strings in the
C++ tree), classifies each site as producer (publish / request /
engine_call) or consumer (subscribe / durable_subscribe / _subscribe_loop),
and fails on any subscribed-but-never-published subject.
"""

import re
from pathlib import Path

import symbiont_tpu.subjects as subjects_mod
from symbiont_tpu import subjects

REPO = Path(__file__).resolve().parent.parent

# producer call tokens: the Python bus surface plus the native helper that
# wraps request-reply to the engine plane (native/services/common.hpp)
_PRODUCER_CALLS = ("publish(", "request(", "engine_call(")
# consumer call tokens; "await sub(" covers engine_service's local alias
# `sub = self._subscribe_loop`
_CONSUMER_CALLS = ("durable_subscribe(", "_subscribe_loop(", "subscribe(",
                   "await sub(")
_NEITHER_CALLS = ("add_stream(",)  # capture config, not production

# Served-but-uncalled endpoints we KEEP deliberately: the engine plane is a
# public RPC surface for native worker shells and external bus clients;
# engine.embed.query is the non-fused query-embedding endpoint exported in
# the generated C++ header for remote callers. Anything else showing up
# here is a dead limb — fix the wiring, don't grow this list.
ALLOWED_UNPRODUCED = {"ENGINE_EMBED_QUERY"}


def _subject_constants() -> dict:
    """NAME -> value for every real subject constant (queue-group names are
    subscription arguments, not subjects)."""
    out = {}
    for name in dir(subjects_mod):
        if not name.isupper():
            continue
        value = getattr(subjects_mod, name)
        if isinstance(value, str) and not value.startswith("q."):
            out[name] = value
    return out


def _classify(context: str):
    """Nearest preceding call token wins (multi-line calls put the callee
    before the subject argument)."""
    best_pos, best_kind = -1, None
    for token, kind in (
            [(t, "producer") for t in _PRODUCER_CALLS]
            + [(t, "consumer") for t in _CONSUMER_CALLS]
            + [(t, None) for t in _NEITHER_CALLS]):
        i = context.rfind(token)
        if i > best_pos:
            best_pos, best_kind = i, kind
    return best_kind if best_pos >= 0 else None


def _scan():
    consts = _subject_constants()
    by_value = {v: k for k, v in consts.items()}
    producers, consumers = {}, {}
    files = [p for p in (REPO / "symbiont_tpu").rglob("*.py")
             if p.name != "subjects.py"]
    native_files = []
    for ext in ("*.cpp", "*.hpp", "*.h"):
        native_files += list((REPO / "native").rglob(ext))
    const_ref = re.compile(r"subjects(?:\.|::)([A-Z][A-Z0-9_]*)")
    for f in files + native_files:
        text = f.read_text(errors="replace")
        hits = [(m.start(), m.group(1)) for m in const_ref.finditer(text)
                if m.group(1) in consts]
        if f in native_files:
            # native code may also use the literal subject string (e.g.
            # knowledge_graph.cpp's engine_call(bus, "engine.graph.save"))
            for value, name in by_value.items():
                for m in re.finditer(re.escape(f'"{value}"'), text):
                    hits.append((m.start(), name))
        for pos, name in hits:
            kind = _classify(text[max(0, pos - 200):pos])
            target = {"producer": producers, "consumer": consumers}.get(kind)
            if target is not None:
                target.setdefault(name, set()).add(
                    str(f.relative_to(REPO)))
    return producers, consumers


def test_no_subscribed_but_never_published_subject():
    producers, consumers = _scan()
    dead = set(consumers) - set(producers) - ALLOWED_UNPRODUCED
    assert not dead, (
        f"dead limbs: subscribed but never published anywhere "
        f"(the reference's data.processed_text.tokenized bug class): "
        f"{ {d: sorted(consumers[d]) for d in sorted(dead)} }")


def test_allowlist_entries_are_still_served():
    """The allowlist documents SERVED endpoints without in-repo callers; if
    the subscription disappears the entry is stale — prune it."""
    _, consumers = _scan()
    stale = ALLOWED_UNPRODUCED - set(consumers)
    assert not stale, f"ALLOWED_UNPRODUCED entries no longer subscribed: {stale}"


def test_pipeline_subjects_have_consumers_and_producers():
    """Both directions for the eight reference-parity pipeline subjects
    (ALL_SUBJECTS): each must have at least one producer AND one consumer —
    the full-duplex wiring SURVEY.md §1-L3 documents."""
    producers, consumers = _scan()
    name_by_value = {getattr(subjects, n): n for n in dir(subjects)
                     if n.isupper() and isinstance(getattr(subjects, n), str)}
    for value in subjects.ALL_SUBJECTS:
        name = name_by_value[value]
        assert name in producers, f"pipeline subject {value} has no producer"
        assert name in consumers, f"pipeline subject {value} has no consumer"


# --------------------------------------------------------------------------
# Data-plane guard: the binary tensor-frame plane (schema/frames) exists so
# bulk floats never pass through per-float Python conversion on the message
# hot path. A `[float(x) for x in ...]` list comprehension inside services/
# is exactly the regression that rebuilt the old wall — ban it statically,
# with an allowlist for the small query-reply paths where a handful of
# floats is not a data plane.

# (file relative to repo root, enclosing function) pairs that may keep a
# per-float conversion: bounded, latency-path payloads (top-k scores).
# Anything new showing up here is the hot path regressing to JSON float
# lists — route it through schema/frames (or ndarray.tolist()) instead.
FLOAT_LIST_ALLOWED = {
    ("symbiont_tpu/services/engine_service.py",
     "EngineService._rerank.op"),
}

_FLOAT_LIST = re.compile(r"\[\s*float\(")
_SCOPE = re.compile(r"^(\s*)(?:(?:async\s+)?def|class)\s+(\w+)")


def _pattern_sites(pattern: re.Pattern):
    """(file, dotted-scope-path) for every `pattern` hit in services/ — an
    indent stack qualifies nested scopes (`EngineService._rerank.op`), so
    allowlist entries name one exact site, not every handler's inner
    `op`. Comment lines are skipped: a ban is about code, and the docs
    that EXPLAIN the ban must be allowed to name it."""
    sites = set()
    for f in sorted((REPO / "symbiont_tpu" / "services").glob("*.py")):
        stack: list = []  # (indent, name)
        for line in f.read_text().splitlines():
            m = _SCOPE.match(line)
            if m:
                indent = len(m.group(1))
                while stack and stack[-1][0] >= indent:
                    stack.pop()
                stack.append((indent, m.group(2)))
            if line.lstrip().startswith("#"):
                continue
            if pattern.search(line):
                path = ".".join(n for _, n in stack) or "<module>"
                sites.add((str(f.relative_to(REPO)), path))
    return sites


def _float_list_sites():
    return _pattern_sites(_FLOAT_LIST)


def test_no_per_float_conversion_on_message_paths():
    sites = _float_list_sites()
    offenders = sites - FLOAT_LIST_ALLOWED
    assert not offenders, (
        "per-float Python conversion on a services/ message path — the "
        "serialization wall the tensor-frame data plane removed "
        "(docs/PERF.md 'data plane' section). Use schema/frames or "
        f"ndarray.tolist() instead: {sorted(offenders)}")


def test_float_list_allowlist_entries_still_exist():
    """A stale allowlist entry means the conversion was removed — prune it
    so the guard stays tight."""
    stale = FLOAT_LIST_ALLOWED - _float_list_sites()
    assert not stale, f"FLOAT_LIST_ALLOWED entries no longer present: {stale}"


# --------------------------------------------------------------------------
# Object-churn guard: `dataclasses.asdict` recursively materializes a dict
# per field per call — on the ingest hot-path services that was exactly the
# per-message churn the zero-churn decode removed (vector_memory built one
# QdrantPointPayload dataclass + asdict dict PER SENTENCE). Payload dicts on
# message paths are built directly now (their keys pinned by
# tests/test_store_wire_fixtures.py); anything re-introducing asdict inside
# services/ shows up here. `dataclasses.replace` stays fine — it is O(1)
# per call and carries no per-row cost.

ASDICT_ALLOWED: set = set()  # no current site may use it; keep it that way

_ASDICT = re.compile(r"\basdict\s*\(")


def test_no_dataclass_asdict_on_ingest_services():
    offenders = _pattern_sites(_ASDICT) - ASDICT_ALLOWED
    assert not offenders, (
        "dataclasses.asdict on a services/ message path — per-message "
        "dict churn the zero-churn ingest decode removed (schema/frames "
        "decode_embeddings_lazy + direct payload dict build). Build the "
        f"dict directly instead: {sorted(offenders)}")


def test_asdict_allowlist_entries_still_exist():
    stale = ASDICT_ALLOWED - _pattern_sites(_ASDICT)
    assert not stale, f"ASDICT_ALLOWED entries no longer present: {stale}"


# --------------------------------------------------------------------------
# Frame-dtype guard: the SYTF dtype registry (name ↔ header byte ↔ numpy
# dtype ↔ content type) lives in schema/frames.py and NOWHERE else. A
# service hand-rolling a frame header, magic, dtype byte, or dtype-name
# literal is how a future dtype ends up half-wired (decodable on one hop,
# garbage on another). One allowlisted encoder may map a negotiated
# encoding value to a dtype name; everything else calls frames helpers
# with no dtype knowledge at all.

FRAME_DTYPE_ALLOWED = {
    ("symbiont_tpu/services/engine_service.py",
     "EngineService._embed_batch.op"),
}

# hand-rolled content types, the frame magic, dtype-constant references,
# or quoted dtype-name literals — anywhere in services/
_FRAME_DTYPE = re.compile(
    r"""tensor/f|SYTF|DTYPE_F|["']f(?:16|32)["']""")


def test_no_hardcoded_frame_dtype_in_services():
    offenders = _pattern_sites(_FRAME_DTYPE) - FRAME_DTYPE_ALLOWED
    assert not offenders, (
        "hard-coded frame dtype outside schema/frames.py — the dtype "
        "registry is centralized there so new dtypes (f16 was the first) "
        "wire every hop at once. Call frames.attach_frame/encode_frame "
        f"with a negotiated name instead: {sorted(offenders)}")


def test_frame_dtype_allowlist_entries_still_exist():
    stale = FRAME_DTYPE_ALLOWED - _pattern_sites(_FRAME_DTYPE)
    assert not stale, f"FRAME_DTYPE_ALLOWED entries no longer present: {stale}"


def test_scanner_sees_known_ground_truth():
    """Self-check so the scanner can't silently rot into vacuous passes:
    a few known call sites must classify as expected."""
    producers, consumers = _scan()
    # api publishes the perceive task; perception consumes it
    assert any("services/api.py" in f
               for f in producers["TASKS_PERCEIVE_URL"])
    assert any("services/perception.py" in f
               for f in consumers["TASKS_PERCEIVE_URL"])
    # the un-orphaned subject: preprocessing produces, knowledge_graph eats
    assert any("services/preprocessing.py" in f
               for f in producers["DATA_PROCESSED_TEXT_TOKENIZED"])
    assert any("services/knowledge_graph.py" in f
               for f in consumers["DATA_PROCESSED_TEXT_TOKENIZED"])
    # engine_service's aliased `await sub(...)` sites are seen as consumers
    assert any("services/engine_service.py" in f
               for f in consumers["ENGINE_HEALTH"])
    # native C++ engine_call sites are seen as producers
    assert any(f.startswith("native/")
               for f in producers.get("ENGINE_VECTOR_SEARCH", set())), \
        "native engine_call producer sites not detected"
