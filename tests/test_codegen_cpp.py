"""Cross-language wire parity: Python encoder ↔ generated C++ decoder.

The reference never solved schema sync (hand-copied Rust ↔ TS shapes,
reference: frontend/src/app/page.tsx:7-48). Here we *prove* sync: every wire
message is encoded by Python, parsed + re-emitted by the generated C++, and
decoded back by Python, field-for-field.
"""

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from symbiont_tpu import schema
from symbiont_tpu.schema import codegen, from_json, to_json

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(shutil.which("g++") is None, reason="g++ not available")

HARNESS = r"""
#include <iostream>
#include <sstream>
#include <string>
#include "symbiont_schema.hpp"

using namespace symbiont;

// Reads one JSON line per wire type in registry order, echoes the C++
// re-serialization; exercises parse() and to_json_string() for every struct.
int main() {
  std::string line;
  int i = 0;
  const char* names[] = {TYPE_LIST};
  while (std::getline(std::cin, line)) {
    std::string name = names[i++];
    try {
      std::string out = DISPATCH(name, line);
      std::cout << out << "\n";
    } catch (const std::exception& e) {
      std::cout << "ERROR " << name << ": " << e.what() << "\n";
      return 1;
    }
  }
  return 0;
}
"""


def _build_harness(tmp_path: Path) -> Path:
    outdir = tmp_path / "gen"
    codegen.main(str(outdir))
    names = [t.__name__ for t in schema.WIRE_TYPES]
    dispatch = "\n".join(
        f'  if (name == "{n}") return {n}::parse(line).to_json_string();' for n in names
    )
    src = HARNESS.replace("TYPE_LIST", ", ".join(f'"{n}"' for n in names)).replace(
        'DISPATCH(name, line)', "dispatch(name, line)"
    )
    src = src.replace(
        "int main() {",
        "std::string dispatch(const std::string& name, const std::string& line) {\n"
        + dispatch
        + '\n  throw std::runtime_error("unknown type " + name);\n}\n\nint main() {',
    )
    cpp = tmp_path / "harness.cpp"
    cpp.write_text(src)
    exe = tmp_path / "harness"
    subprocess.run(
        ["g++", "-std=c++17", "-O1", "-o", str(exe), str(cpp),
         "-I", str(REPO / "native"), "-I", str(outdir / "cpp")],
        check=True, capture_output=True, text=True,
    )
    return exe


def _sample(cls):
    """One populated instance per wire type (same fixtures as test_schema)."""
    from tests.test_schema import CASES

    for c in CASES:
        if type(c) is cls:
            return c
    raise AssertionError(f"no fixture for {cls}")


def test_cpp_round_trip_all_types(tmp_path):
    exe = _build_harness(tmp_path)
    msgs = [_sample(t) for t in schema.WIRE_TYPES]
    stdin = "\n".join(to_json(m) for m in msgs) + "\n"
    proc = subprocess.run([str(exe)], input=stdin, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = proc.stdout.strip().split("\n")
    assert len(lines) == len(msgs)
    for msg, line in zip(msgs, lines):
        back = from_json(type(msg), line)
        assert back == msg, f"{type(msg).__name__}: {line}"


def test_committed_generated_files_in_sync():
    """generated/ must match fresh codegen output — guards against editing the
    schema without re-running `python -m symbiont_tpu.schema.codegen generated`."""
    cpp = (REPO / "generated" / "cpp" / "symbiont_schema.hpp").read_text()
    ts = (REPO / "generated" / "ts" / "schema.ts").read_text()
    assert cpp == codegen.gen_cpp(), "regenerate: python -m symbiont_tpu.schema.codegen generated"
    assert ts == codegen.gen_ts(), "regenerate: python -m symbiont_tpu.schema.codegen generated"


def test_cpp_rejects_malformed_numbers(tmp_path):
    """Strict number grammar parity: serde/Python reject these; C++ must too."""
    exe = _build_harness(tmp_path)
    for bad in ('{"url": 01}', '{"url": .5}', '{"url": 1.}', '{"url": +1}'):
        proc = subprocess.run([str(exe)], input=bad + "\n", capture_output=True,
                              text=True)
        assert proc.returncode == 1, f"C++ accepted {bad!r}"


def test_cpp_rejects_unknown_field(tmp_path):
    exe = _build_harness(tmp_path)
    bad = json.dumps({"url": "http://x", "extra": 1})
    proc = subprocess.run([str(exe)], input=bad + "\n", capture_output=True, text=True)
    assert proc.returncode == 1
    assert "unknown field" in proc.stdout


def test_cpp_missing_optional_ok(tmp_path):
    exe = _build_harness(tmp_path)
    # GenerateTextTask is 4th in registry order; feed prior types valid inputs
    msgs = [_sample(t) for t in schema.WIRE_TYPES[:3]]
    stdin = "\n".join(to_json(m) for m in msgs)
    stdin += "\n" + json.dumps({"task_id": "t", "max_length": 3}) + "\n"
    proc = subprocess.run([str(exe)], input=stdin, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout
    last = json.loads(proc.stdout.strip().split("\n")[-1])
    assert last["prompt"] is None
    assert last["max_length"] == 3
