"""Elastic autoscaler + drain protocol (resilience/autoscale.py,
ProcessSupervisor.scale_role).

Three layers, mirroring how the plane is built:

- pure policy units (injected clock + signals — no processes): bounds
  parsing, the ops budget, scale-out dwell, scale-in clean passes, the
  no-flap guarantee under an oscillating signal;
- in-process drain-protocol units over the inproc durable bus: a drained
  service detaches its durable consumers (new work goes to the surviving
  group member only), the UpsertCoalescer flushes immediately in drain
  mode, and the full runner stack drains end to end (flush + final
  `draining: true` heartbeat + /readyz 503);
- `-m chaos` scenarios with REAL OS processes over the pybroker: a
  scale-out replica shards the durable queue group, a drained scale-in
  loses nothing with traffic still flowing, a SIGKILL mid-drain loses
  nothing (redelivery), a drain that exceeds its deadline is SIGKILLed
  and still loses nothing, and a crash-looping worker parks in the
  `crashlooped` state instead of restarting forever.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from symbiont_tpu.config import AutoscaleConfig
from symbiont_tpu.resilience.autoscale import (
    Autoscaler,
    OpsBudget,
    RoleSignals,
    parse_role_bounds,
)

REPO = Path(__file__).resolve().parent.parent


# ------------------------------------------------------------ policy units


def test_parse_role_bounds():
    assert parse_role_bounds("") == {}
    out = parse_role_bounds("embed=1:4, decode=2:2")
    assert out["embed"].min == 1 and out["embed"].max == 4
    assert out["decode"].min == 2 and out["decode"].max == 2
    for bad in ("embed", "embed=4", "embed=0:4", "embed=3:2", "embed=a:b"):
        with pytest.raises(ValueError):
            parse_role_bounds(bad)
    # the config section validates at construction (env-typo = boot error)
    with pytest.raises(ValueError):
        AutoscaleConfig(roles="embed=0:3")
    with pytest.raises(ValueError):
        AutoscaleConfig(queue_high=4.0, queue_low=8.0)


def test_ops_budget_sliding_window():
    t = [0.0]
    b = OpsBudget(2, 10.0, clock=lambda: t[0])
    assert b.try_take() and b.try_take()
    assert not b.try_take()
    assert b.remaining() == 0
    t[0] = 10.5  # both ops age out of the window together
    assert b.try_take() and b.try_take()
    assert not b.try_take()


class _FakeWorker:
    draining = False


class _FakeSup:
    """Records scale_role calls; replica bookkeeping like the real one."""

    _broker_healthy = True

    def __init__(self, roles=("embed",)):
        self.calls = []
        self.n = {r: 1 for r in roles}
        self.drain_deadline_s = 30.0
        self.workers = {}
        self._sync()

    def _sync(self):
        self.workers = {}
        for r, k in self.n.items():
            for i in range(k):
                name = r if i == 0 else f"{r}-{i + 1}"
                self.workers[name] = _FakeWorker()

    def replicas(self, role):
        return [n for n in self.workers
                if n == role or n.startswith(role + "-")]

    async def scale_role(self, role, n):
        self.calls.append((role, n))
        self.n[role] = n
        self._sync()


def _policy(sup, sig, t, **over):
    kw = dict(enabled=True, roles="embed=1:3", eval_s=0.1, queue_high=10.0,
              queue_low=1.0, out_dwell_s=1.0, in_dwell_s=2.0,
              in_clean_passes=2, budget_ops=4, budget_window_s=60.0,
              drain_deadline_s=5.0)
    kw.update(over)
    cfg = AutoscaleConfig(**kw)
    return Autoscaler(sup, cfg, signals=lambda b: sig, clock=lambda: t[0])


def test_scale_out_respects_dwell_and_bounds():
    t = [0.0]
    sup = _FakeSup()
    sig = {"embed": RoleSignals(queue_depth=50.0)}
    a = _policy(sup, sig, t)

    async def main():
        await a.evaluate_once()               # first breach acts now
        assert sup.calls == [("embed", 2)]
        t[0] += 0.5
        await a.evaluate_once()               # inside the dwell: holds
        assert sup.calls == [("embed", 2)]
        t[0] += 1.0
        await a.evaluate_once()               # past the dwell: grows
        assert sup.calls[-1] == ("embed", 3)
        t[0] += 2.0
        await a.evaluate_once()               # at max: holds
        assert sup.calls[-1] == ("embed", 3)
        assert a.flaps() == 0

    asyncio.run(main())


def test_scale_in_needs_consecutive_clean_passes_and_dwell():
    t = [0.0]
    sup = _FakeSup()
    sup.n["embed"] = 3
    sup._sync()
    sig = {"embed": RoleSignals(queue_depth=0.5)}
    a = _policy(sup, sig, t)

    async def main():
        t[0] += 10.0
        await a.evaluate_once()               # clean pass 1: holds
        assert sup.calls == []
        # a noisy (dead-band) pass resets the streak
        sig["embed"] = RoleSignals(queue_depth=5.0)
        await a.evaluate_once()
        sig["embed"] = RoleSignals(queue_depth=0.5)
        await a.evaluate_once()               # clean 1 again
        assert sup.calls == []
        await a.evaluate_once()               # clean 2 + dwell: shrinks
        assert sup.calls == [("embed", 2)]

    asyncio.run(main())


def test_oscillating_signal_never_flaps():
    """The tentpole's hysteresis claim: breach, clear, breach, clear …
    every pass — the fleet must park, not thrash spawn/drain cycles."""
    t = [0.0]
    sup = _FakeSup()
    sig = {"embed": RoleSignals(queue_depth=50.0)}
    a = _policy(sup, sig, t)

    async def main():
        for i in range(40):
            hot = i % 2 == 0
            sig["embed"] = RoleSignals(queue_depth=50.0 if hot else 0.0)
            await a.evaluate_once()
            t[0] += 0.3
        # scale-outs may accumulate to max (each past its dwell), but the
        # clean streak resets on every hot pass, so NOTHING scales in —
        # and no reversal lands inside a hysteresis window
        assert all(d == "out" for _, _, d, _ in a.decisions)
        assert a.flaps() == 0

    asyncio.run(main())


def test_budget_exhaustion_blocks_scaling():
    t = [0.0]
    sup = _FakeSup()
    sig = {"embed": RoleSignals(queue_depth=50.0)}
    a = _policy(sup, sig, t, budget_ops=1)

    async def main():
        await a.evaluate_once()
        assert sup.calls == [("embed", 2)]
        t[0] += 5.0                            # past every dwell
        await a.evaluate_once()                # budget empty: refused
        assert sup.calls == [("embed", 2)]

    asyncio.run(main())


def test_broker_down_skips_the_pass():
    t = [0.0]
    sup = _FakeSup()
    sup._broker_healthy = False
    sig = {"embed": RoleSignals(queue_depth=50.0)}
    a = _policy(sup, sig, t)

    async def main():
        await a.evaluate_once()   # stale signals + unpublishable drain
        assert sup.calls == []

    asyncio.run(main())


# --------------------------------------------- drain protocol (in-process)


def test_drain_detaches_durable_consumer_new_work_goes_to_survivor():
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.services.base import Service

    class Consumer(Service):
        name = "toy"

        def __init__(self, bus, seen):
            super().__init__(bus)
            self.seen = seen

        async def _setup(self):
            await self._subscribe_loop("job.*", self._handle, queue="g",
                                       durable_stream="s")

        async def _handle(self, msg):
            self.seen.append(bytes(msg.data))

    async def main():
        bus = InprocBus()
        await bus.add_stream("s", ["job.>"], ack_wait_s=0.2, max_deliver=10)
        seen_a, seen_b = [], []
        a, b = Consumer(bus, seen_a), Consumer(bus, seen_b)
        await a.start()
        await b.start()
        for i in range(6):
            await bus.publish(f"job.{i}", f"m{i}".encode())
        deadline = time.monotonic() + 5
        while len(seen_a) + len(seen_b) < 6 and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        assert len(seen_a) + len(seen_b) == 6
        await a.drain()
        frozen = len(seen_a)
        for i in range(6, 16):
            await bus.publish(f"job.{i}", f"m{i}".encode())
        deadline = time.monotonic() + 5
        while len(seen_b) < 16 - frozen and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        # the drained member pulled NOTHING new; the survivor got it all,
        # exactly once (no redelivery: every pre-drain handler acked)
        assert len(seen_a) == frozen
        assert sorted(seen_a + seen_b) == sorted(
            f"m{i}".encode() for i in range(16))
        await b.stop()
        await a.stop()  # idempotent after drain
        await bus.close()

    asyncio.run(main())


def test_coalescer_drain_mode_flushes_without_age_window():
    from symbiont_tpu.services.coalesce import UpsertCoalescer

    import numpy as np

    flushed = []

    def flush_fn(ids, rows, payloads):
        flushed.append(list(ids))
        return len(ids)

    async def main():
        c = UpsertCoalescer(flush_fn, max_rows=10_000,
                            max_age_ms=60_000.0, name="t")
        await c.start()
        add = asyncio.create_task(
            c.add(["a", "b"], np.zeros((2, 4), np.float32), [{}, {}]))
        await asyncio.sleep(0.05)
        assert not flushed  # neither rows nor age triggered
        c.drain_mode()
        n = await asyncio.wait_for(add, 2.0)  # resolves promptly
        assert n == 2 and flushed == [["a", "b"]]
        # adds DURING drain mode still flush (a handler mid-flight may
        # land one after the flip)
        n = await asyncio.wait_for(
            c.add(["c"], np.zeros((1, 4), np.float32), [{}]), 2.0)
        assert n == 1 and flushed[-1] == ["c"]
        await c.stop()

    asyncio.run(main())


def test_heartbeat_payload_parse_tolerates_all_shapes():
    from symbiont_tpu.resilience.procsup import (
        ProcessSupervisor,
        WorkerSpec,
        _Worker,
    )

    w = _Worker(WorkerSpec(role="r", argv=["true"]))
    note = ProcessSupervisor._note_heartbeat_payload
    note(w, b"")                       # toy workers beat empty payloads
    assert not w.reported_draining and w.reported_capacity == 1.0
    note(w, b"not json")
    assert not w.reported_draining
    note(w, json.dumps({"role": "r", "pid": 1}).encode())  # pre-field beat
    assert not w.reported_draining and w.reported_capacity == 1.0
    note(w, json.dumps({"role": "r", "pid": 1, "capacity": 0,
                        "draining": True}).encode())
    assert w.reported_draining and w.reported_capacity == 0.0


def test_fleet_rollup_folds_draining_and_crashlooped():
    from symbiont_tpu.obs.fleet import FleetAggregator
    from symbiont_tpu.obs.trace_store import TraceStore
    from symbiont_tpu.utils.telemetry import Metrics

    agg = FleetAggregator(local_role="gateway", store=TraceStore(16),
                          registry=Metrics())
    agg.merge_metrics("procsup", {"full": True, "pid": 1, "metrics": {
        'gauge.procsup.up{role="embed-2"}': 1.0,
        'gauge.procsup.draining{role="embed-2"}': 1.0,
        'gauge.procsup.crashlooped{role="embed-2"}': 0.0,
        'counter.procsup.scale_out{role="embed"}': 2.0,
        'counter.procsup.scale_in{role="embed"}': 1.0,
        'counter.procsup.drain_timeouts{role="embed"}': 0.0,
    }})
    roles = agg.rollup()["roles"]
    assert roles["embed-2"]["draining"] == 1.0
    assert roles["embed-2"]["crashlooped"] == 0.0
    assert roles["embed"]["scale_out"] == 2.0
    assert roles["embed"]["scale_in"] == 1.0
    assert roles["embed"]["drain_timeouts"] == 0.0


# C++ gateway admission parity (common.hpp AdmissionGate): stub json
# DECLARATIONS only — nothing odr-uses the inline json helpers, so this
# compiles and RUNS on GCC 10 where the full native tree cannot build
# (same harness stance as tests/test_fleet.py's heartbeat parity).
CPP_ADMISSION_HARNESS = r"""
#include <string>
#include <vector>

namespace json {
struct Value {
  std::string dump() const;
  const Value& at(const std::string&) const;
  bool is_null() const;
  std::string as_string() const;
  double as_number() const;
  bool has(const std::string&) const;
  const std::vector<Value>& as_array() const;
};
Value parse(const std::string&);
}  // namespace json

#include "services/common.hpp"
#include <cassert>
#include <cstdio>

int main() {
  setenv("SYMBIONT_ADMISSION_SEARCH_RATE", "2", 1);
  setenv("SYMBIONT_ADMISSION_SEARCH_BURST", "3", 1);
  setenv("SYMBIONT_ADMISSION_MAX_TENANTS", "2", 1);
  symbiont::AdmissionGate g;
  g.configure();
  double ra = 0.0;
  int64_t t = 0;
  using G = symbiont::AdmissionGate;
  // burst of 3, then refused with a refill-shaped Retry-After hint
  assert(g.admit(G::SEARCH, "t0", &ra, t));
  assert(g.admit(G::SEARCH, "t0", &ra, t));
  assert(g.admit(G::SEARCH, "t0", &ra, t));
  assert(!g.admit(G::SEARCH, "t0", &ra, t));
  assert(ra > 0.0 && ra <= 0.5 + 1e-9);
  // rate 2/s: one second later exactly two tokens are back
  t += 1000;
  assert(g.admit(G::SEARCH, "t0", &ra, t));
  assert(g.admit(G::SEARCH, "t0", &ra, t));
  assert(!g.admit(G::SEARCH, "t0", &ra, t));
  // tenant universe bounded at 2 ("default" pre-seeded + t0): every
  // fresh identity shares ONE overflow bucket — minting tenant headers
  // buys no fresh burst (3 total across fresh-a/b/c, then refused)
  assert(g.admit(G::SEARCH, "fresh-a", &ra, t));
  assert(g.admit(G::SEARCH, "fresh-b", &ra, t));
  assert(g.admit(G::SEARCH, "fresh-c", &ra, t));
  assert(!g.admit(G::SEARCH, "fresh-d", &ra, t));
  assert(g.tenant_overflows() >= 4);
  std::printf("OK\n");
  return 0;
}
"""


def test_cpp_admission_gate_via_stub_json_harness(tmp_path):
    import shutil
    import tempfile  # noqa: F401

    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        pytest.skip("no C++ compiler on this host")
    src = tmp_path / "adm.cpp"
    src.write_text(CPP_ADMISSION_HARNESS)
    exe = tmp_path / "adm"
    proc = subprocess.run(
        [gxx, "-std=c++17", "-O1", "-I", str(REPO / "native"),
         str(src), "-o", str(exe)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        "the stub-json admission TU must compile even where json.hpp "
        f"cannot (GCC 10):\n{proc.stderr[:2000]}")
    out = subprocess.run([str(exe)], capture_output=True, text=True,
                         timeout=60)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "OK"


def test_runner_stack_drains_end_to_end():
    """The worker half of the protocol in the REAL stack (stub engine,
    inproc durable bus): a `_sys.drain.<role>` message stops durable
    pulls, flushes the UpsertCoalescer (the pending row lands even with a
    60s age window), publishes a final `draining: true` heartbeat, flips
    the gateway's /readyz to 503, and wakes the drained event main()
    exits on."""
    import tempfile

    import numpy as np

    from symbiont_tpu import subjects
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.config import (
        ApiConfig,
        EngineConfig,
        GraphStoreConfig,
        SymbiontConfig,
        TextGeneratorConfig,
        VectorStoreConfig,
    )
    from symbiont_tpu.runner import SymbiontStack

    class _ModelCfg:
        hidden_size = 16

    class StubEngine:
        def __init__(self):
            self.config = EngineConfig(embedding_dim=16, max_batch=16,
                                       flush_deadline_ms=2.0)
            self.model_cfg = _ModelCfg()
            self.cross_params = None
            self.stats = {"embed_calls": 0, "compiles": 0}

        def embed_texts(self, texts):
            return np.zeros((len(texts), 16), np.float32)

    async def main():
        with tempfile.TemporaryDirectory() as td:
            cfg = SymbiontConfig(
                vector_store=VectorStoreConfig(
                    dim=16, data_dir=f"{td}/vs",
                    # only the drain may flush: proves flush-on-drain
                    coalesce_max_age_ms=60_000.0),
                graph_store=GraphStoreConfig(data_dir=f"{td}/gs"),
                text_generator=TextGeneratorConfig(markov_state_path=None),
                api=ApiConfig(host="127.0.0.1", port=0))
            cfg.runner.services = "perception,preprocessing,vector_memory,api"
            cfg.runner.role = "worker"
            cfg.runner.heartbeat_s = 0.1
            cfg.bus.durable = True
            bus = InprocBus()
            beats = []
            sub = await bus.subscribe(subjects.SYS_HEARTBEAT + ".>")

            async def collect():
                async for m in sub:
                    beats.append(json.loads(m.data))

            collector = asyncio.create_task(collect())
            stack = SymbiontStack(
                cfg, bus=bus, engine=StubEngine(),
                fetcher=lambda url: "<html><p>one sentence.</p></html>")
            await stack.start()
            await asyncio.sleep(0.25)
            assert beats and beats[0]["capacity"] == 1 \
                and beats[0]["draining"] is False
            from symbiont_tpu.utils.telemetry import metrics

            base_msgs = metrics.get("coalesce.messages",
                                    labels={"service": "vector_memory"})
            await bus.publish(subjects.TASKS_PERCEIVE_URL,
                              json.dumps({"url": "http://x/1"}).encode())
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if metrics.get("coalesce.messages",
                               labels={"service": "vector_memory"}) \
                        > base_msgs:
                    break
                await asyncio.sleep(0.02)
            assert stack.vector_store.count() == 0  # parked in the window
            await bus.publish(f"{subjects.SYS_DRAIN}.worker", b"{}")
            await asyncio.wait_for(stack.drained.wait(), 10)
            assert stack.vector_store.count() == 1  # flush-on-drain landed
            final = [b for b in beats if b.get("draining")]
            assert final and final[-1]["capacity"] == 0
            assert stack.api._ready is False  # /readyz went 503 first
            await stack.stop()
            await bus.close()
            collector.cancel()

    asyncio.run(main())


# -------------------------------------------------- chaos (real processes)

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def _connect(port):
    from symbiont_tpu.bus.tcp import TcpBus

    bus = TcpBus("127.0.0.1", port)
    await bus.connect()
    return bus


# A drain-aware durable consumer worker (no jax import: boots fast). argv:
# port, out_path, role, drain_mode (clean|slow|ignore). It consumes the
# "w" stream in queue group "g" (fsync-before-ack), beats with the real
# payload shape, and on `_sys.drain.<role>` runs the worker half of the
# protocol: detach the durable consumer, final draining beat, exit 0.
_DRAIN_WORKER = """
import asyncio, json, os, sys, time
from pathlib import Path
from symbiont_tpu.bus.connect import connect

PORT, OUT, MODE = int(sys.argv[1]), Path(sys.argv[2]), sys.argv[4]
# replicas spawned by scale_role inherit the base argv but carry their own
# identity in SYMBIONT_RUNNER_ROLE (procsup._replica_spec) — same contract
# as the real runner
ROLE = os.environ.get("SYMBIONT_RUNNER_ROLE") or sys.argv[3]

def payload(draining):
    return json.dumps({"role": ROLE, "pid": os.getpid(),
                       "capacity": 0 if draining else 1,
                       "draining": draining}).encode()

async def main():
    bus = await connect("symbus://127.0.0.1:%d" % PORT)
    await bus.add_stream("w", ["job.>"], ack_wait_s=0.5, max_deliver=50)
    sub = await bus.durable_subscribe("w", "g")
    drain_sub = await bus.subscribe("_sys.drain." + ROLE)
    draining = asyncio.Event()

    async def beat():
        while True:
            await bus.publish("_sys.heartbeat." + ROLE,
                              payload(draining.is_set()))
            await asyncio.sleep(0.15)

    async def drain_watch():
        await drain_sub.next(None)
        draining.set()

    hb = asyncio.get_running_loop().create_task(beat())
    dw = asyncio.get_running_loop().create_task(drain_watch())
    while not draining.is_set():
        msg = await sub.next(0.1)
        if msg is None:
            continue
        with open(OUT, "a") as f:
            f.write(msg.data.decode() + chr(10))
            f.flush()
            os.fsync(f.fileno())
        await bus.ack(msg)
    if MODE == "ignore":
        # a truly WEDGED drain: deaf to the bus request AND to the
        # supervisor's SIGTERM escalation — only the deadline SIGKILL
        # can clear it
        import signal as _signal
        _signal.signal(_signal.SIGTERM, _signal.SIG_IGN)
        while True:
            await asyncio.sleep(1)
    sub.close()              # detach: unacked work redelivers elsewhere
    if MODE == "slow":
        await asyncio.sleep(3.0)     # mid-drain SIGKILL window
    await bus.publish("_sys.heartbeat." + ROLE, payload(True))
    await bus.flush()
    sys.exit(0)

asyncio.run(main())
"""


def _drain_spec(port: int, out, role: str, mode: str = "clean",
                timeout_s: float = 3.0):
    from symbiont_tpu.resilience.procsup import WorkerSpec

    return WorkerSpec(
        role=role,
        argv=[sys.executable, "-c", _DRAIN_WORKER, str(port), str(out),
              role, mode],
        heartbeat_timeout_s=timeout_s, boot_grace_s=30.0,
        backoff_base_s=0.1, backoff_max_s=1.0)


def _landed(out) -> set:
    return set(out.read_text().splitlines()) if out.exists() else set()


@pytest.mark.chaos
def test_scale_out_shards_group_and_drained_scale_in_loses_nothing(
        tmp_path):
    """The full elastic cycle with real processes: scale_role(2) spawns a
    replica that joins the durable queue group (fan-in free), scale_role(1)
    retires it through the drain protocol WHILE traffic still flows, and
    every message lands exactly once."""
    from symbiont_tpu.bus.pybroker import PyBroker
    from symbiont_tpu.resilience.procsup import ProcessSupervisor

    async def main():
        broker = PyBroker(port=0, data_dir=str(tmp_path / "bus"))
        await broker.start()
        port = broker.bound_port
        out = tmp_path / "landed.txt"
        sup = ProcessSupervisor(bus_url=f"symbus://127.0.0.1:{port}",
                                stdio=subprocess.DEVNULL,
                                drain_deadline_s=10.0)
        sup.add_worker(_drain_spec(port, out, "embed"))
        await sup.start()
        pub = await _connect(port)
        try:
            t0 = time.monotonic()
            await sup.wait_role_up("embed", after=t0 - 1, timeout_s=30)
            r = await sup.scale_role("embed", 2)
            assert r["added"] == ["embed-2"]
            assert sup.replicas("embed") == ["embed", "embed-2"]
            await sup.wait_role_up("embed-2", after=t0, timeout_s=30)
            for i in range(20):
                await pub.publish(f"job.{i}", f"m{i}".encode())
            deadline = time.monotonic() + 15
            while len(_landed(out)) < 20 and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert len(_landed(out)) == 20

            # retire the replica with traffic STILL flowing: messages in
            # flight during the drain redeliver to the survivor
            scale_in = asyncio.create_task(sup.scale_role("embed", 1))
            for i in range(20, 40):
                await pub.publish(f"job.{i}", f"m{i}".encode())
                await asyncio.sleep(0.01)
            r = await scale_in
            assert r["drained"] == ["embed-2"]
            assert sup.replicas("embed") == ["embed"]
            want = {f"m{i}" for i in range(40)}
            deadline = time.monotonic() + 20
            while not want <= _landed(out) \
                    and time.monotonic() < deadline:
                await asyncio.sleep(0.1)
            assert want <= _landed(out), sorted(want - _landed(out))
        finally:
            await pub.close()
            await sup.stop()
            await broker.stop()

    asyncio.run(main())


@pytest.mark.chaos
def test_sigkill_mid_drain_loses_nothing(tmp_path):
    """The ISSUE's kill-chaos-during-resize scenario: a worker is
    SIGKILLed in the middle of its drain (consumer already detached,
    process still flushing). Its unacked deliveries redeliver to the
    surviving replica — exact zero loss."""
    from symbiont_tpu.bus.pybroker import PyBroker
    from symbiont_tpu.resilience.procsup import ProcessSupervisor

    async def main():
        broker = PyBroker(port=0, data_dir=str(tmp_path / "bus"))
        await broker.start()
        port = broker.bound_port
        out = tmp_path / "landed.txt"
        sup = ProcessSupervisor(bus_url=f"symbus://127.0.0.1:{port}",
                                stdio=subprocess.DEVNULL,
                                drain_deadline_s=15.0)
        sup.add_worker(_drain_spec(port, out, "embed"))
        await sup.start()
        pub = await _connect(port)
        try:
            t0 = time.monotonic()
            await sup.wait_role_up("embed", after=t0 - 1, timeout_s=30)
            # the replica being retired drains SLOWLY (3s between detach
            # and exit) — the SIGKILL window
            from symbiont_tpu.resilience.procsup import WorkerSpec  # noqa
            spec = _drain_spec(port, out, "embed", mode="clean")
            slow = _drain_spec(port, out, "embed-2", mode="slow")
            slow.base_role = "embed"
            sup.add_worker(slow)
            w2 = sup.workers["embed-2"]
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, sup._spawn, w2)
            w2.task = asyncio.create_task(sup._monitor(w2))
            await sup.wait_role_up("embed-2", after=t0, timeout_s=30)
            for i in range(30):
                await pub.publish(f"job.{i}", f"m{i}".encode())
            await asyncio.sleep(0.5)  # some in flight, some landed
            scale_in = asyncio.create_task(sup.scale_role("embed", 1))
            await asyncio.sleep(1.0)  # drain started, worker in its sleep
            pid = sup.pid("embed-2")
            if pid is not None:
                os.kill(pid, signal.SIGKILL)
            await scale_in
            want = {f"m{i}" for i in range(30)}
            deadline = time.monotonic() + 20
            while not want <= _landed(out) \
                    and time.monotonic() < deadline:
                await asyncio.sleep(0.1)
            assert want <= _landed(out), sorted(want - _landed(out))
        finally:
            await pub.close()
            await sup.stop()
            await broker.stop()

    asyncio.run(main())


@pytest.mark.chaos
def test_drain_deadline_exceeded_sigkills_and_redelivers(tmp_path):
    """A worker that IGNORES the drain request: the supervisor's deadline
    SIGKILLs it (counted in procsup.drain_timeouts), its unacked work
    redelivers, and nothing is lost."""
    from symbiont_tpu.bus.pybroker import PyBroker
    from symbiont_tpu.resilience.procsup import ProcessSupervisor
    from symbiont_tpu.utils.telemetry import metrics

    async def main():
        broker = PyBroker(port=0, data_dir=str(tmp_path / "bus"))
        await broker.start()
        port = broker.bound_port
        out = tmp_path / "landed.txt"
        sup = ProcessSupervisor(bus_url=f"symbus://127.0.0.1:{port}",
                                stdio=subprocess.DEVNULL,
                                drain_deadline_s=1.5)
        sup.add_worker(_drain_spec(port, out, "embed"))
        await sup.start()
        pub = await _connect(port)
        try:
            t0 = time.monotonic()
            await sup.wait_role_up("embed", after=t0 - 1, timeout_s=30)
            stubborn = _drain_spec(port, out, "embed-2", mode="ignore")
            stubborn.base_role = "embed"
            sup.add_worker(stubborn)
            w2 = sup.workers["embed-2"]
            await asyncio.get_running_loop().run_in_executor(
                None, sup._spawn, w2)
            w2.task = asyncio.create_task(sup._monitor(w2))
            await sup.wait_role_up("embed-2", after=t0, timeout_s=30)
            for i in range(20):
                await pub.publish(f"job.{i}", f"m{i}".encode())
            await asyncio.sleep(0.3)
            before = metrics.get("procsup.drain_timeouts",
                                 labels={"role": "embed-2"}) or 0
            t_drain = time.monotonic()
            r = await sup.scale_role("embed", 1)
            assert r["drained"] == ["embed-2"]
            # deadline enforced: the wait did not exceed ~deadline + slack
            assert time.monotonic() - t_drain < 10
            assert metrics.get("procsup.drain_timeouts",
                               labels={"role": "embed-2"}) == before + 1
            assert "embed-2" not in sup.workers
            want = {f"m{i}" for i in range(20)}
            deadline = time.monotonic() + 20
            while not want <= _landed(out) \
                    and time.monotonic() < deadline:
                await asyncio.sleep(0.1)
            assert want <= _landed(out), sorted(want - _landed(out))
        finally:
            await pub.close()
            await sup.stop()
            await broker.stop()

    asyncio.run(main())


@pytest.mark.chaos
def test_restart_storm_parks_worker_crashlooped(tmp_path):
    """A worker whose argv dies instantly: after storm_max_restarts inside
    the window it PARKS (crashlooped=True, procsup.crashlooped=1, no more
    respawns) instead of fork/exec'ing forever."""
    from symbiont_tpu.bus.pybroker import PyBroker
    from symbiont_tpu.resilience.procsup import (
        ProcessSupervisor,
        WorkerSpec,
    )
    from symbiont_tpu.utils.telemetry import metrics

    async def main():
        broker = PyBroker(port=0, data_dir=str(tmp_path / "bus"))
        await broker.start()
        port = broker.bound_port
        sup = ProcessSupervisor(bus_url=f"symbus://127.0.0.1:{port}",
                                stdio=subprocess.DEVNULL,
                                storm_max_restarts=3, storm_window_s=60.0,
                                crashloop_cooloff_s=600.0)
        sup.add_worker(WorkerSpec(
            role="broken", argv=[sys.executable, "-c", "raise SystemExit(1)"],
            backoff_base_s=0.05, backoff_max_s=0.1))
        await sup.start()
        try:
            deadline = time.monotonic() + 20
            w = sup.workers["broken"]
            while not w.crashlooped and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            assert w.crashlooped, f"restarts={w.restarts}"
            assert metrics.gauge_get("procsup.crashlooped",
                                     labels={"role": "broken"}) == 1
            parked_at = sup.restarts("broken")
            assert parked_at == 3
            await asyncio.sleep(1.0)
            # parked: the restart counter stays frozen during the cool-off
            assert sup.restarts("broken") == parked_at
        finally:
            await sup.stop()
            await broker.stop()

    asyncio.run(main())
