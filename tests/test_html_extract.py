"""Scraper extraction tests on HTML fixtures (reference extraction logic:
services/perception_service/src/main.rs:86-170 — untested there)."""

from symbiont_tpu.services.html_extract import extract_main_text


def test_article_preferred_over_body():
    html = """
    <html><body>
      <div><p>sidebar junk</p></div>
      <article><h1>Title</h1><p>Body text.</p></article>
    </body></html>"""
    out = extract_main_text(html)
    assert "Title" in out and "Body text." in out
    assert "sidebar junk" not in out


def test_selector_cascade_order():
    # div.content chosen when no article/main/div[role=main]
    html = """
    <html><body>
      <div class="content wide"><p>the content</p></div>
      <div class="entry-content"><p>entry</p></div>
    </body></html>"""
    out = extract_main_text(html)
    assert "the content" in out
    assert "entry" not in out


def test_div_role_main():
    html = "<body><div role='main'><p>roled</p></div><p>outside</p></body>"
    out = extract_main_text(html)
    assert out == "roled"


def test_body_fallback_and_text_selectors():
    html = """
    <body><h2>H</h2><ul><li>item one</li><li>item two</li></ul>
    <span>a span</span><table><td>not extracted</td></table></body>"""
    out = extract_main_text(html)
    assert "H" in out and "item one" in out and "a span" in out
    assert "not extracted" not in out  # td is not in the text-selector list


def test_script_and_style_excluded():
    html = """<body><article>
      <p>keep<script>var x = 'drop';</script></p>
      <style>.c{}</style><p>also keep</p></article></body>"""
    out = extract_main_text(html)
    assert "keep" in out and "also keep" in out
    assert "drop" not in out and ".c{}" not in out


def test_text_nodes_trimmed_and_joined():
    # a text node's internal newline survives to the final line-split pass
    # (reference trims whole nodes, then trims lines: main.rs:135-152)
    html = "<body><p>  a \n  b  <b>c</b>  </p></body>"
    assert extract_main_text(html) == "a\nb c"
    assert extract_main_text("<body><p> x  <b>y</b> </p></body>") == "x y"


def test_empty_and_garbage_html():
    assert extract_main_text("") == ""
    assert extract_main_text("<<<not html>>>") == ""
    assert extract_main_text("<body><p>   </p></body>") == ""


def test_malformed_nesting_tolerated():
    html = "<body><article><p>one<p>two</article>"
    out = extract_main_text(html)
    assert "one" in out and "two" in out
