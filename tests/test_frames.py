"""Binary tensor frames (schema/frames + native common.hpp mirror).

Three contracts under test:

1. the BYTE LAYOUT — golden fixtures built independently of the codec
   (struct.pack by hand from the spec) pin both directions, and when a C++
   toolchain is available the native encoder/decoder in
   native/services/common.hpp is compiled and run against the same bytes
   (Python encodes → C++ decodes, C++ encodes → Python decodes);
2. the NEGOTIATION / fallback contract — a frame-capable publisher with
   frames off emits byte-exact reference wire JSON a JSON-only peer
   ingests; a frame-capable consumer accepts both forms; an engine caller
   that does not opt in gets JSON float lists;
3. LOSSLESSNESS through the resilience plane — a frame-bearing message
   that dead-letters replays from the DLQ bit-for-bit, headers included.
"""

import asyncio
import json
import shutil
import struct
import subprocess
import tempfile
from pathlib import Path

import numpy as np
import pytest

from symbiont_tpu import subjects
from symbiont_tpu.bus.inproc import InprocBus
from symbiont_tpu.schema import TextWithEmbeddingsMessage, frames, from_json
from symbiont_tpu.utils.ids import deterministic_point_id

REPO = Path(__file__).resolve().parent.parent

GOLDEN_ROWS = np.array([[1.0, -2.5, 0.15625],
                        [3.5, 65504.0, -0.0]], dtype=np.float32)


def golden_frame_bytes() -> bytes:
    """The spec, transcribed independently of the codec under test."""
    out = b"SYTF"                      # magic
    out += struct.pack("<B", 1)        # version
    out += struct.pack("<B", 1)        # dtype f32le
    out += struct.pack("<H", 0)        # reserved
    out += struct.pack("<I", 2)        # rows
    out += struct.pack("<I", 3)        # cols
    for v in [1.0, -2.5, 0.15625, 3.5, 65504.0, -0.0]:
        out += struct.pack("<f", v)
    return out


def golden_f16_frame_bytes() -> bytes:
    """The half-width form, same spec-transcription stance. Every GOLDEN_ROWS
    value is exactly representable in binary16 (65504.0 is the f16 max), so
    the f16 frame is lossless for this fixture."""
    out = b"SYTF"
    out += struct.pack("<B", 1)        # version
    out += struct.pack("<B", 2)        # dtype f16le
    out += struct.pack("<H", 0)        # reserved
    out += struct.pack("<I", 2)        # rows
    out += struct.pack("<I", 3)        # cols
    for v in [1.0, -2.5, 0.15625, 3.5, 65504.0, -0.0]:
        out += struct.pack("<e", v)
    return out


# ------------------------------------------------------------- byte layout

def test_encode_matches_golden_bytes():
    assert frames.encode_frame(GOLDEN_ROWS) == golden_frame_bytes()


def test_decode_golden_bytes():
    rows = frames.decode_frame(golden_frame_bytes())
    assert rows.shape == (2, 3)
    np.testing.assert_array_equal(rows, GOLDEN_ROWS)
    # -0.0 sign survives (bit-exactness, not just value equality)
    assert np.signbit(rows[1, 2])


def test_attach_detach_roundtrip():
    body = b'{"k":"v"}'
    data, headers = frames.attach_frame(body, GOLDEN_ROWS)
    assert headers[frames.FRAME_HEADER] == f"tensor/f32;off={len(body)}"
    json_part, rows = frames.detach_frame(data, headers)
    assert json_part == body
    np.testing.assert_array_equal(rows, GOLDEN_ROWS)


def test_detach_without_header_is_passthrough():
    data, rows = frames.detach_frame(b'{"a":1}', {})
    assert data == b'{"a":1}' and rows is None


@pytest.mark.parametrize("mutate", [
    lambda b: b[:20],                          # truncated payload
    lambda b: b"XXXX" + b[4:],                 # bad magic
    lambda b: b[:4] + b"\x09" + b[5:],         # unknown version
    lambda b: b[:5] + b"\x07" + b[6:],         # unknown dtype
])
def test_malformed_frames_raise(mutate):
    with pytest.raises(frames.FrameError):
        frames.decode_frame(mutate(golden_frame_bytes()))


# ----------------------------------------------------------- f16 wire form

def test_encode_f16_matches_golden_bytes():
    assert frames.encode_frame(GOLDEN_ROWS, dtype="f16") == \
        golden_f16_frame_bytes()


def test_decode_f16_golden_bytes():
    rows = frames.decode_frame(golden_f16_frame_bytes())
    assert rows.dtype == np.float16 and rows.shape == (2, 3)
    np.testing.assert_array_equal(rows.astype(np.float32), GOLDEN_ROWS)
    assert np.signbit(rows[1, 2])  # -0.0 survives the half form too


def test_attach_detach_f16_roundtrip():
    body = b'{"k":"v"}'
    data, headers = frames.attach_frame(body, GOLDEN_ROWS, dtype="f16")
    assert headers[frames.FRAME_HEADER] == f"tensor/f16;off={len(body)}"
    json_part, rows = frames.detach_frame(data, headers)
    assert json_part == body and rows.dtype == np.float16
    np.testing.assert_array_equal(rows.astype(np.float32), GOLDEN_ROWS)
    # halving check: same rows, ~half the frame payload bytes
    f32_len = len(frames.encode_frame(GOLDEN_ROWS))
    f16_len = len(frames.encode_frame(GOLDEN_ROWS, dtype="f16"))
    assert f16_len - frames.FRAME_HDR_LEN == (f32_len
                                              - frames.FRAME_HDR_LEN) // 2


def test_unsupported_dtype_byte_raises_not_garbage():
    """An f32/f16-only consumer receiving a future dtype byte must
    FrameError (delivery stays unacked for redelivery/DLQ) — never
    misparse the payload at the wrong element width."""
    fut = golden_f16_frame_bytes()
    fut = fut[:5] + struct.pack("<B", 3) + fut[6:]  # hypothetical dtype 3
    with pytest.raises(frames.FrameError, match="dtype"):
        frames.decode_frame(fut)


def test_f16_encode_refuses_overflow():
    """A finite value beyond the binary16 range (±65504) must FrameError at
    encode, not ship as ±inf (one inf row poisons every cosine against it
    downstream — review finding). The exact f16 max still frames."""
    ok = np.array([[65504.0, -65504.0]], np.float32)
    assert frames.decode_frame(frames.encode_frame(ok, dtype="f16")) is not None
    with pytest.raises(frames.FrameError, match="f16 range"):
        frames.encode_frame(np.array([[1e10, 1.0]], np.float32), dtype="f16")
    # the f32 form takes the same payload unchanged
    assert frames.encode_frame(np.array([[1e10, 1.0]], np.float32))


def test_frames_mode_env(monkeypatch):
    monkeypatch.delenv("SYMBIONT_FRAMES", raising=False)
    assert frames.frames_mode() == "f32"
    monkeypatch.setenv("SYMBIONT_FRAMES", "f16")
    assert frames.frames_mode() == "f16"
    assert frames.frames_enabled()
    monkeypatch.setenv("SYMBIONT_FRAMES", "0")
    assert frames.frames_mode() == "off"
    assert not frames.frames_enabled()
    monkeypatch.setenv("SYMBIONT_FRAMES", "1")
    assert frames.frames_mode() == "f32"


@pytest.mark.parametrize("value", [
    "tensor/f64;off=2", "tensor/f32", "tensor/f32;off=x",
    "tensor/f32;off=-1"])
def test_malformed_header_values_raise(value):
    with pytest.raises(frames.FrameError):
        frames.detach_frame(b"{}" + golden_frame_bytes(),
                            {frames.FRAME_HEADER: value})


def test_frame_offset_beyond_body_raises():
    with pytest.raises(frames.FrameError):
        frames.detach_frame(b"{}", {frames.FRAME_HEADER:
                                    "tensor/f32;off=999"})


# --------------------------------------------------- message-level contract

def _sample_args():
    rng = np.random.default_rng(3)
    sentences = ["The MXU does matmuls.", "HBM is the bottleneck!"]
    vectors = rng.standard_normal((2, 8)).astype(np.float32)
    return sentences, vectors


def test_frame_message_roundtrip():
    sentences, vectors = _sample_args()
    data, headers = frames.encode_embeddings_message(
        "doc-1", "http://d", sentences, vectors, "m", 123, use_frame=True)
    msg, rows = frames.decode_embeddings_message(data, headers)
    assert rows is not None
    np.testing.assert_array_equal(rows, vectors)  # bit-exact f32
    assert [se.sentence_text for se in msg.embeddings_data] == sentences
    assert all(se.embedding == [] for se in msg.embeddings_data)
    assert (msg.original_id, msg.source_url, msg.model_name,
            msg.timestamp_ms) == ("doc-1", "http://d", "m", 123)


def test_fallback_is_wire_json_a_json_only_peer_ingests():
    """The negotiated fallback: frames off → the exact reference wire
    shape, decodable by a peer that knows nothing about frames."""
    sentences, vectors = _sample_args()
    data, headers = frames.encode_embeddings_message(
        "doc-1", "http://d", sentences, vectors, "m", 123, use_frame=False)
    assert frames.FRAME_HEADER not in headers
    # a JSON-only peer: plain strict schema decode, no frames module
    peer_view = from_json(TextWithEmbeddingsMessage, data)
    got = np.asarray([se.embedding for se in peer_view.embeddings_data],
                     np.float32)
    np.testing.assert_array_equal(got, vectors)  # f32→double→f32 is exact


def test_frame_row_count_mismatch_raises():
    sentences, vectors = _sample_args()
    data, headers = frames.encode_embeddings_message(
        "doc-1", "http://d", sentences, vectors, "m", 123, use_frame=True)
    # clip one sentence out of the JSON metadata, keep the 2-row frame
    off = frames.frame_offset(headers)
    meta = json.loads(data[:off])
    meta["embeddings_data"] = meta["embeddings_data"][:1]
    body = json.dumps(meta, separators=(",", ":")).encode()
    bad = body + data[off:]
    with pytest.raises(frames.FrameError):
        frames.decode_embeddings_message(
            bad, {frames.FRAME_HEADER: f"tensor/f32;off={len(body)}"})


def test_frames_enabled_env(monkeypatch):
    monkeypatch.delenv("SYMBIONT_FRAMES", raising=False)
    assert frames.frames_enabled()
    for off_value in ("0", "false", "no", "off"):
        monkeypatch.setenv("SYMBIONT_FRAMES", off_value)
        assert not frames.frames_enabled()
    monkeypatch.setenv("SYMBIONT_FRAMES", "1")
    assert frames.frames_enabled()


# ------------------------------------------------- store + service plumbing

def test_upsert_rows_matches_upsert(tmp_path):
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore

    rng = np.random.default_rng(5)
    rows = rng.standard_normal((6, 16)).astype(np.float32)
    ids = [deterministic_point_id("d", i) for i in range(6)]
    payloads = [{"sentence_text": f"s{i}"} for i in range(6)]

    a = VectorStore(VectorStoreConfig(dim=16, data_dir=str(tmp_path / "a")))
    a.upsert(list(zip(ids, rows, payloads)))
    b = VectorStore(VectorStoreConfig(dim=16, data_dir=str(tmp_path / "b")))
    # a read-only frombuffer view — exactly what the bus decode hands over
    view = np.frombuffer(rows.tobytes(), dtype=np.float32).reshape(6, 16)
    assert not view.flags.writeable
    b.upsert_rows(ids, view, payloads)

    assert a.count() == b.count() == 6
    np.testing.assert_array_equal(a._vectors, b._vectors)
    assert a._payloads == b._payloads
    # WAL durability identical: a fresh load reconstructs the same store
    b2 = VectorStore(VectorStoreConfig(dim=16, data_dir=str(tmp_path / "b")))
    np.testing.assert_array_equal(b2._vectors, b._vectors)

    # overwrite semantics shared with upsert: same ids, new vectors
    rows2 = rng.standard_normal((6, 16)).astype(np.float32)
    b.upsert_rows(ids, rows2, payloads)
    assert b.count() == 6

    with pytest.raises(ValueError):
        b.upsert_rows(ids, rows2[:3], payloads)
    with pytest.raises(ValueError):
        b.upsert_rows(ids, rows2, payloads[:3])
    with pytest.raises(ValueError):
        b.upsert_rows(ids, rows2[:, :8], payloads)


def test_vector_memory_service_ingests_both_forms(tmp_path):
    """The same document through the frame wire and the JSON wire lands
    identically in the store (the consumer-side half of interop)."""
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore
    from symbiont_tpu.services.vector_memory import VectorMemoryService

    sentences, vectors = _sample_args()

    async def ingest(doc_id, use_frame, store):
        bus = InprocBus()
        svc = VectorMemoryService(bus, store)
        await svc.start()
        try:
            data, fheaders = frames.encode_embeddings_message(
                doc_id, "http://d", sentences, vectors, "m", 123,
                use_frame=use_frame)
            await bus.publish(subjects.DATA_TEXT_WITH_EMBEDDINGS, data,
                              headers=fheaders)
            for _ in range(100):
                if store.count() >= len(sentences):
                    break
                await asyncio.sleep(0.01)
        finally:
            await svc.stop()
            await bus.close()

    sa = VectorStore(VectorStoreConfig(dim=8, data_dir=str(tmp_path / "f")))
    sb = VectorStore(VectorStoreConfig(dim=8, data_dir=str(tmp_path / "j")))
    asyncio.run(ingest("doc-x", True, sa))
    asyncio.run(ingest("doc-x", False, sb))
    assert sa.count() == sb.count() == len(sentences)
    np.testing.assert_array_equal(sa._vectors, sb._vectors)
    assert sa._ids == sb._ids
    assert [p["sentence_text"] for p in sa._payloads] == sentences


def test_vector_memory_frame_ingest_without_upsert_rows(tmp_path):
    """A backend exposing only the reference upsert() surface (bare
    external Qdrant, no resilience wrapper) must still ingest frame
    messages — the service falls back to the point-tuple surface."""
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore
    from symbiont_tpu.services.vector_memory import VectorMemoryService

    sentences, vectors = _sample_args()

    class UpsertOnlyStore:
        def __init__(self):
            self.inner = VectorStore(VectorStoreConfig(
                dim=8, data_dir=str(tmp_path)))

        def ensure_collection(self, dim=None):
            self.inner.ensure_collection(dim)

        def upsert(self, points):
            return self.inner.upsert(points)

        def count(self):
            return self.inner.count()

    store = UpsertOnlyStore()
    assert not hasattr(store, "upsert_rows")

    async def scenario():
        bus = InprocBus()
        svc = VectorMemoryService(bus, store)
        await svc.start()
        try:
            data, fheaders = frames.encode_embeddings_message(
                "doc-q", "http://d", sentences, vectors, "m", 123,
                use_frame=True)
            await bus.publish(subjects.DATA_TEXT_WITH_EMBEDDINGS, data,
                              headers=fheaders)
            for _ in range(200):
                if store.count() >= len(sentences):
                    break
                await asyncio.sleep(0.01)
        finally:
            await svc.stop()
            await bus.close()

    asyncio.run(scenario())
    assert store.count() == len(sentences)
    np.testing.assert_allclose(
        store.inner._vectors,
        vectors / np.linalg.norm(vectors, axis=1, keepdims=True),
        rtol=1e-6)


def test_vector_memory_ingests_f16_wire(tmp_path):
    """SYMBIONT_FRAMES=f16 publisher → consumer: the half-width rows land
    in the store upcast to f32, matching the f32 wire within f16 rounding
    (the store's matrix/WAL stay f32 — upsert_rows upcasts on ingest)."""
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore
    from symbiont_tpu.services.vector_memory import VectorMemoryService

    sentences, vectors = _sample_args()

    async def ingest(use_dtype, store):
        bus = InprocBus()
        svc = VectorMemoryService(bus, store)
        await svc.start()
        try:
            data, fheaders = frames.encode_embeddings_message(
                "doc-h", "http://d", sentences, vectors, "m", 123,
                use_frame=True, wire_dtype=use_dtype)
            await bus.publish(subjects.DATA_TEXT_WITH_EMBEDDINGS, data,
                              headers=fheaders)
            for _ in range(200):
                if store.count() >= len(sentences):
                    break
                await asyncio.sleep(0.01)
        finally:
            await svc.stop()
            await bus.close()

    sa = VectorStore(VectorStoreConfig(dim=8, data_dir=str(tmp_path / "16")))
    sb = VectorStore(VectorStoreConfig(dim=8, data_dir=str(tmp_path / "32")))
    asyncio.run(ingest("f16", sa))
    asyncio.run(ingest("f32", sb))
    assert sa.count() == sb.count() == len(sentences)
    assert sa._vectors.dtype == np.float32
    # f16 rounding is the only difference (~2^-11 relative)
    np.testing.assert_allclose(sa._vectors, sb._vectors, atol=2e-3)


def test_upsert_rows_upcasts_f16_view(tmp_path):
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore

    rng = np.random.default_rng(9)
    rows32 = rng.standard_normal((4, 16)).astype(np.float32)
    rows16 = np.frombuffer(rows32.astype("<f2").tobytes(),
                           dtype="<f2").reshape(4, 16)
    assert not rows16.flags.writeable  # the zero-copy bus view shape
    store = VectorStore(VectorStoreConfig(dim=16, data_dir=str(tmp_path)))
    ids = [deterministic_point_id("d", i) for i in range(4)]
    store.upsert_rows(ids, rows16, [{"sentence_text": str(i)}
                                    for i in range(4)])
    assert store._vectors.dtype == np.float32
    want = rows16.astype(np.float32)
    want = want / np.linalg.norm(want, axis=1, keepdims=True)
    np.testing.assert_allclose(store._vectors, want, rtol=1e-6)


def test_engine_embed_reply_negotiation(tmp_path):
    """Request-reply negotiation: a caller opting in gets a frame reply; a
    caller that does not (an old peer) gets JSON float lists — and both
    decode to the same vectors. The upsert op accepts a frame request."""
    from symbiont_tpu.config import EngineConfig, VectorStoreConfig
    from symbiont_tpu.engine.engine import TpuEngine
    from symbiont_tpu.memory.vector_store import VectorStore
    from symbiont_tpu.services.engine_service import EngineService

    async def scenario():
        bus = InprocBus()
        eng = TpuEngine(EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                                     batch_buckets=[2, 4], dtype="float32"))
        store = VectorStore(VectorStoreConfig(dim=32,
                                              data_dir=str(tmp_path)))
        svc = EngineService(bus, engine=eng, vector_store=store)
        await svc.start()
        try:
            texts = ["hello world", "tpu"]
            # frame-capable caller
            msg = await bus.request(
                subjects.ENGINE_EMBED_BATCH,
                json.dumps({"texts": texts, "encoding": "frame"}).encode(),
                timeout=30.0)
            meta_b, rows = frames.detach_frame(msg.data, msg.headers)
            meta = json.loads(meta_b)
            assert meta["error_message"] is None
            assert rows is not None and rows.shape == (2, 32)
            assert (meta["count"], meta["dim"]) == (2, 32)
            assert "_frame" not in meta  # the ndarray never hits JSON

            # JSON-only caller: negotiated fallback
            msg2 = await bus.request(
                subjects.ENGINE_EMBED_BATCH,
                json.dumps({"texts": texts}).encode(), timeout=30.0)
            assert frames.FRAME_HEADER not in msg2.headers
            legacy = json.loads(msg2.data)
            np.testing.assert_allclose(
                np.asarray(legacy["vectors"], np.float32), rows, rtol=1e-6)

            # frame REQUEST into the upsert op (the C++ shell's hop)
            ids = [deterministic_point_id("d", i) for i in range(2)]
            body = json.dumps({"ids": ids, "dim": 32,
                               "payloads": [{"sentence_text": t}
                                            for t in texts]}).encode()
            data, fheaders = frames.attach_frame(body, rows)
            up = await bus.request(subjects.ENGINE_VECTOR_UPSERT, data,
                                   timeout=30.0, headers=fheaders)
            up_r = json.loads(up.data)
            assert up_r["error_message"] is None and up_r["upserted"] == 2
            assert store.count() == 2
            np.testing.assert_allclose(
                store._vectors,
                rows / np.linalg.norm(rows, axis=1, keepdims=True),
                rtol=1e-6)
        finally:
            await svc.stop()
            await bus.close()

    asyncio.run(scenario())


def test_engine_embed_reply_frame16_negotiation(tmp_path):
    """Per-hop dtype negotiation, both directions: a frame16 caller gets a
    half-width reply from a NEW engine; the same request to an engine that
    has never heard of frame16 (reference-era peer, simulated by a stub
    that ignores `encoding`) degrades to the JSON float-list path every
    caller accepts."""
    from symbiont_tpu.config import EngineConfig
    from symbiont_tpu.engine.engine import TpuEngine
    from symbiont_tpu.services.engine_service import EngineService

    async def scenario():
        bus = InprocBus()
        eng = TpuEngine(EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                                     batch_buckets=[2, 4], dtype="float32"))
        svc = EngineService(bus, engine=eng)
        await svc.start()
        try:
            texts = ["hello world", "tpu"]
            msg = await bus.request(
                subjects.ENGINE_EMBED_BATCH,
                json.dumps({"texts": texts,
                            "encoding": "frame16"}).encode(), timeout=30.0)
            meta_b, rows = frames.detach_frame(msg.data, msg.headers)
            meta = json.loads(meta_b)
            assert meta["error_message"] is None
            assert rows is not None and rows.dtype == np.float16
            assert rows.shape == (2, 32)
            assert msg.headers[frames.FRAME_HEADER].startswith("tensor/f16")

            # f32 baseline from the same engine: f16 reply == f32 reply
            # within half rounding
            msg2 = await bus.request(
                subjects.ENGINE_EMBED_BATCH,
                json.dumps({"texts": texts,
                            "encoding": "frame"}).encode(), timeout=30.0)
            _, rows32 = frames.detach_frame(msg2.data, msg2.headers)
            np.testing.assert_allclose(rows.astype(np.float32), rows32,
                                       atol=2e-3)
        finally:
            await svc.stop()
            await bus.close()

    asyncio.run(scenario())


def test_frame16_request_to_old_engine_degrades_to_json():
    """The old-peer half of the negotiation: a reference-era engine that
    ignores the `encoding` field replies JSON float lists, and the
    frame-capable caller's detach_frame path handles it unchanged."""
    async def scenario():
        bus = InprocBus()
        sub = await bus.subscribe(subjects.ENGINE_EMBED_BATCH)

        async def old_engine():
            msg = await sub.next(5.0)
            req = json.loads(msg.data)  # ignores req["encoding"] entirely
            await bus.publish(msg.reply, json.dumps(
                {"vectors": [[1.0, 2.0]] * len(req["texts"]),
                 "error_message": None}).encode())

        task = asyncio.ensure_future(old_engine())
        try:
            msg = await bus.request(
                subjects.ENGINE_EMBED_BATCH,
                json.dumps({"texts": ["a"],
                            "encoding": "frame16"}).encode(), timeout=5.0)
            meta_b, rows = frames.detach_frame(msg.data, msg.headers)
            assert rows is None  # JSON fallback — no frame rode along
            assert json.loads(meta_b)["vectors"] == [[1.0, 2.0]]
            await task
        finally:
            sub.close()
            await bus.close()

    asyncio.run(scenario())


def test_dlq_replay_roundtrips_frame_losslessly(tmp_path):
    """Resilience-plane contract: a frame-bearing delivery that exhausts
    max_deliver dead-letters with data AND headers intact, and an operator
    replay re-enters the durable flow with the frame decodable."""
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore
    from symbiont_tpu.services.vector_memory import VectorMemoryService

    sentences, vectors = _sample_args()

    async def scenario():
        bus = InprocBus()
        await bus.add_stream("pipeline",
                             [subjects.DATA_TEXT_WITH_EMBEDDINGS],
                             ack_wait_s=0.1, max_deliver=2)
        store = VectorStore(VectorStoreConfig(dim=8,
                                              data_dir=str(tmp_path)))
        svc = VectorMemoryService(bus, store, durable_stream="pipeline")
        # poison the handler so every delivery fails → DLQ
        real_upsert_rows = store.upsert_rows
        fail = {"on": True}

        def flaky(ids, rows, payloads=None):
            if fail["on"]:
                raise RuntimeError("injected store outage")
            return real_upsert_rows(ids, rows, payloads)

        store.upsert_rows = flaky
        await svc.start()
        try:
            data, fheaders = frames.encode_embeddings_message(
                "doc-dlq", "http://d", sentences, vectors, "m", 123,
                use_frame=True)
            await bus.publish(subjects.DATA_TEXT_WITH_EMBEDDINGS, data,
                              headers=fheaders)
            for _ in range(200):
                if len(bus.dlq):
                    break
                await asyncio.sleep(0.02)
            entries = bus.dlq.list()
            assert len(entries) == 1
            parked = bus.dlq.get(entries[0].id)
            assert parked.data == data  # bit-for-bit, frame included
            assert parked.headers[frames.FRAME_HEADER] == \
                fheaders[frames.FRAME_HEADER]
            m, rows = frames.decode_embeddings_message(parked.data,
                                                       parked.headers)
            np.testing.assert_array_equal(rows, vectors)

            # handler fixed → replay → the document lands
            fail["on"] = False
            assert await bus.dlq.replay(bus) == 1
            for _ in range(200):
                if store.count() >= len(sentences):
                    break
                await asyncio.sleep(0.02)
            assert store.count() == len(sentences)
            np.testing.assert_allclose(
                store._vectors,
                vectors / np.linalg.norm(vectors, axis=1, keepdims=True),
                rtol=1e-6)
        finally:
            await svc.stop()
            await bus.close()

    asyncio.run(scenario())


# ----------------------------------------------------------- C++ parity

CPP_HARNESS = r"""
#include "json.hpp"
#include "services/common.hpp"
#include <cstdio>
#include <iostream>
#include <map>
#include <string>

// stdin: full frame-bearing body; argv[1]: the X-Symbiont-Frame header
// value. Decodes via symbiont::split_frame, prints rows/cols/dtype and
// every float (%.9g round-trips f32; f16 payloads upconvert through
// symbiont::half_to_float), then re-encodes the payload through
// symbiont::make_frame AT ITS WIRE DTYPE and prints its hex — Python
// asserts both ways, for the f32 and the half-width f16 form alike.
int main(int argc, char** argv) {
  std::string body((std::istreambuf_iterator<char>(std::cin)),
                   std::istreambuf_iterator<char>());
  std::map<std::string, std::string> headers;
  if (argc > 1) headers[symbiont::FRAME_HEADER] = argv[1];
  std::string json_part;
  symbiont::FrameView fv;
  if (!symbiont::split_frame(headers, body, json_part, fv)) {
    std::printf("noframe\n");
    return 0;
  }
  std::printf("%u %u %u\n", fv.rows, fv.cols, (unsigned)fv.dtype);
  auto rows = symbiont::frame_rows(fv);
  for (const auto& r : rows)
    for (float v : r) std::printf("%.9g\n", (double)v);
  std::string raw(fv.payload, fv.payload_len);
  std::string re = symbiont::make_frame(raw, fv.rows, fv.cols, fv.dtype);
  for (unsigned char c : re) std::printf("%02x", c);
  std::printf("\n");
  return 0;
}
"""


def _compile_harness(tmp: Path):
    gxx = shutil.which("g++") or shutil.which("clang++")
    if gxx is None:
        pytest.skip("no C++ compiler on this host")
    src = tmp / "frame_parity.cpp"
    src.write_text(CPP_HARNESS)
    exe = tmp / "frame_parity"
    proc = subprocess.run(
        [gxx, "-std=c++17", "-O1", "-I", str(REPO / "native"),
         str(src), "-o", str(exe)],
        capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        pytest.skip("C++ toolchain cannot build the native tree here "
                    f"(same limitation as test_codegen_cpp): {proc.stderr[:400]}")
    return exe


def test_cpp_frame_parity():
    """Python encodes → the real C++ decoder decodes; the real C++ encoder
    re-emits → bytes identical to Python's. Covers BOTH wire dtypes (the
    f16 golden-byte parity satellite rides the same harness). Skips where
    the native tree cannot compile (this sandbox's gcc lacks float
    to_chars)."""
    with tempfile.TemporaryDirectory() as td:
        exe = _compile_harness(Path(td))
        body = b'{"meta":1}'
        for dtype, code in (("f32", 1), ("f16", 2)):
            data, headers = frames.attach_frame(body, GOLDEN_ROWS,
                                                dtype=dtype)
            out = subprocess.run(
                [str(exe), headers[frames.FRAME_HEADER]], input=data,
                capture_output=True, timeout=60).stdout.decode().split()
            rows, cols, dt = int(out[0]), int(out[1]), int(out[2])
            assert (rows, cols) == GOLDEN_ROWS.shape and dt == code
            got = np.array(out[3:3 + rows * cols],
                           np.float32).reshape(rows, cols)
            # GOLDEN_ROWS is exactly representable in f16, so both forms
            # decode to identical f32 values
            np.testing.assert_array_equal(got, GOLDEN_ROWS)
            # C++ re-encoded frame == Python-encoded frame, byte for byte
            assert bytes.fromhex(out[3 + rows * cols]) == \
                frames.encode_frame(GOLDEN_ROWS, dtype=dtype)
        # and a frameless body passes through as the JSON fallback
        noframe = subprocess.run([str(exe)], input=body,
                                 capture_output=True, timeout=60)
        assert noframe.stdout.decode().strip() == "noframe"


# ------------------------------------------------ reply-frame negotiation


def test_lazy_decode_matches_dataclass_decode():
    """decode_embeddings_lazy sees the same data as the dataclass decoder
    on BOTH wire forms — just without the per-sentence object churn."""
    sentences, vectors = _sample_args()
    for use_frame in (True, False):
        data, headers = frames.encode_embeddings_message(
            "doc-l", "http://d", sentences, vectors, "m", 77,
            use_frame=use_frame)
        m, rows = frames.decode_embeddings_message(data, headers)
        lazy = frames.decode_embeddings_lazy(data, headers)
        assert lazy.original_id == m.original_id == "doc-l"
        assert lazy.source_url == m.source_url
        assert lazy.model_name == m.model_name
        assert lazy.timestamp_ms == m.timestamp_ms == 77
        assert lazy.sentences == [e.sentence_text
                                  for e in m.embeddings_data] == sentences
        np.testing.assert_allclose(lazy.rows, vectors, rtol=1e-6)
        if use_frame:
            # the frame path hands back the SAME zero-copy view
            assert rows is not None
            np.testing.assert_array_equal(lazy.rows, rows)


def test_lazy_decode_rejects_mismatch_and_ragged():
    sentences, vectors = _sample_args()
    data, headers = frames.encode_embeddings_message(
        "doc-m", "http://d", sentences, vectors, "m", 1, use_frame=True)
    # frame row count vs sentence count mismatch
    body = json.loads(data[:frames.frame_offset(headers)])
    body["embeddings_data"] = body["embeddings_data"][:-1]
    prefix = json.dumps(body, separators=(",", ":")).encode()
    bad = prefix + data[frames.frame_offset(headers):]
    bad_headers = {frames.FRAME_HEADER: f"tensor/f32;off={len(prefix)}"}
    with pytest.raises(frames.FrameError):
        frames.decode_embeddings_lazy(bad, bad_headers)
    # ragged JSON-fallback embedding lists cannot form one block
    ragged = json.dumps({
        "original_id": "x", "source_url": "u", "model_name": "m",
        "timestamp_ms": 1, "embeddings_data": [
            {"sentence_text": "a", "embedding": [1.0, 2.0]},
            {"sentence_text": "b", "embedding": [1.0]}]}).encode()
    with pytest.raises(Exception):
        frames.decode_embeddings_lazy(ragged, None)


def test_query_embedding_reply_frame_negotiation():
    """tasks.embedding.for_query reply path: an X-Symbiont-Accept-Frame
    requester gets a schema-valid reply with an EMPTY embedding list and
    the [1, dim] block appended as a frame; a requester without the header
    (a reference-era peer) still gets the float-list reply — and both
    decode to the same vector."""
    from symbiont_tpu.config import EngineConfig
    from symbiont_tpu.schema import (
        QueryEmbeddingResult,
        QueryForEmbeddingTask,
        to_json_bytes,
    )
    from symbiont_tpu.services.preprocessing import PreprocessingService

    class _StubEngine:
        def __init__(self):
            self.config = EngineConfig(embedding_dim=8, max_batch=8,
                                       flush_deadline_ms=2.0)

        def embed_texts(self, texts):
            return np.asarray([[float(len(t))] * 8 for t in texts],
                              np.float32)

    async def scenario():
        bus = InprocBus()
        svc = PreprocessingService(bus, _StubEngine())
        await svc.start()
        try:
            task = to_json_bytes(QueryForEmbeddingTask(
                request_id="r1", text_to_embed="hello"))
            # frame-capable requester
            reply = await bus.request(
                subjects.TASKS_EMBEDDING_FOR_QUERY, task, timeout=5.0,
                headers={frames.ACCEPT_FRAME_HEADER: "1"})
            json_part, rows = frames.detach_frame(reply.data, reply.headers)
            res = from_json(QueryEmbeddingResult, json_part)
            assert res.error_message is None and res.embedding == []
            assert rows is not None and rows.shape == (1, 8)
            np.testing.assert_array_equal(rows[0], [5.0] * 8)
            # reference-era requester: no header, float-list reply
            reply = await bus.request(subjects.TASKS_EMBEDDING_FOR_QUERY,
                                      task, timeout=5.0)
            json_part, rows = frames.detach_frame(reply.data, reply.headers)
            assert rows is None
            res = from_json(QueryEmbeddingResult, json_part)
            assert res.embedding == [5.0] * 8
        finally:
            await svc.stop()
            await bus.close()

    asyncio.run(scenario())


def test_api_two_hop_search_decodes_frame_reply(tmp_path):
    """The Python gateway's 2-hop fallback opts into the reply frame and
    the search still returns correct hits end-to-end (api → preprocessing
    frame reply → vector_memory search)."""
    import urllib.request

    from symbiont_tpu.config import (
        ApiConfig,
        GraphStoreConfig,
        SymbiontConfig,
        TextGeneratorConfig,
        VectorStoreConfig,
    )
    from symbiont_tpu.runner import SymbiontStack

    class _StubEngine:
        class _ModelCfg:
            hidden_size = 8

        def __init__(self):
            from symbiont_tpu.config import EngineConfig

            self.config = EngineConfig(embedding_dim=8, max_batch=8,
                                       flush_deadline_ms=2.0)
            self.model_cfg = self._ModelCfg()
            self.cross_params = None
            self.stats = {"embed_calls": 0, "compiles": 0}

        def embed_texts(self, texts):
            # deterministic unit vectors keyed by first word length
            out = np.zeros((len(texts), 8), np.float32)
            for i, t in enumerate(texts):
                out[i, min(7, len(t.split()[0]))] = 1.0
            return out

    page = ("<html><body><main><p>Alpha beta gamma.</p>"
            "<p>Delta epsilon zeta.</p></main></body></html>")
    cfg = SymbiontConfig(
        vector_store=VectorStoreConfig(dim=8,
                                       data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(markov_state_path=None),
        api=ApiConfig(host="127.0.0.1", port=0, fused_search=False),
    )
    cfg.runner.services = ("perception,preprocessing,vector_memory,"
                           "knowledge_graph,text_generator,api")

    async def scenario():
        stack = SymbiontStack(cfg, bus=InprocBus(), engine=_StubEngine(),
                              fetcher=lambda url: page)
        await stack.start()
        loop = asyncio.get_running_loop()
        port = stack.api.port
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/submit-url",
                data=json.dumps({"url": "http://fake/doc"}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            assert (await loop.run_in_executor(
                None, lambda: urllib.request.urlopen(req, timeout=10))
                ).status == 200
            for _ in range(200):
                if stack.vector_store.count() >= 2:
                    break
                await asyncio.sleep(0.05)
            assert stack.vector_store.count() >= 2
            sreq = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/search/semantic",
                data=json.dumps({"query_text": "alpha beta",
                                 "top_k": 2}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            body = json.loads((await loop.run_in_executor(
                None, lambda: urllib.request.urlopen(sreq, timeout=10))
                ).read())
            assert body["error_message"] is None
            assert len(body["results"]) == 2
        finally:
            await stack.stop()

    asyncio.run(scenario())
