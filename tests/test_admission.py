"""Overload-protection plane (resilience/admission.py + the API edge +
service-base deadline drop): per-tenant quotas with 429/Retry-After,
weighted-fair scheduling under a hot tenant, edge + propagated deadlines,
the SLO shed ladder's hysteresis, capacity-aware generation admission,
SSE-disconnect generation cancellation, and /readyz vs /healthz.

Everything timing-sensitive runs on injectable clocks (TokenBucket,
DegradationLadder) or seeded fault plans — no sleep-and-hope assertions
for the admission arithmetic itself.
"""

import asyncio
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from symbiont_tpu import subjects
from symbiont_tpu.bus.inproc import InprocBus
from symbiont_tpu.config import (
    AdmissionConfig,
    ApiConfig,
    BusConfig,
    GraphStoreConfig,
    SymbiontConfig,
    TextGeneratorConfig,
    VectorStoreConfig,
)
from symbiont_tpu.resilience import admission as adm
from symbiont_tpu.resilience.admission import (
    AdmissionController,
    AdmissionReject,
    DegradationLadder,
    TokenBucket,
    WeightedFairQueue,
)
from symbiont_tpu.runner import SymbiontStack
from symbiont_tpu.services.api import ApiService
from symbiont_tpu.utils.telemetry import (
    DEADLINE_HEADER,
    TENANT_HEADER,
    child_headers,
    metrics,
)

PAGE = ("<html><body><main><p>Admission testing sentence one.</p>"
        "<p>Admission testing sentence two.</p></main></body></html>")


class _StubEngine:
    class _ModelCfg:
        hidden_size = 16

    def __init__(self):
        from symbiont_tpu.config import EngineConfig

        self.config = EngineConfig(embedding_dim=16, max_batch=8,
                                   flush_deadline_ms=2.0)
        self.model_cfg = self._ModelCfg()
        self.cross_params = None
        self.stats = {"embed_calls": 0, "compiles": 0}

    def embed_texts(self, texts):
        self.stats["embed_calls"] += 1
        rng = np.random.default_rng(len(texts))
        return rng.standard_normal((len(texts), 16)).astype(np.float32)


def _http(port, method, path, body=None, headers=None, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json", **(headers or {})},
        method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"{}")


async def _wait_for(cond, timeout=20.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return cond()


# ------------------------------------------------------------- token bucket


def test_token_bucket_burst_and_refill():
    now = [0.0]
    b = TokenBucket(rate=2.0, burst=4.0, clock=lambda: now[0])
    assert [b.try_take() for _ in range(4)] == [True] * 4
    assert b.try_take() is False  # burst exhausted
    assert b.retry_after_s() == pytest.approx(0.5)  # 1 token / 2 per s
    now[0] = 0.5
    assert b.try_take() is True  # refilled exactly one
    assert b.try_take() is False
    now[0] = 100.0
    # refill caps at burst, never beyond
    assert [b.try_take() for _ in range(4)] == [True] * 4
    assert b.try_take() is False


def test_admission_controller_quota_exhaustion_and_recovery():
    """Satellite: quota exhaustion mid-burst → reject, then recovery after
    refill — and tenants are isolated (one tenant's burst never drains
    another's bucket)."""
    now = [0.0]
    ctl = AdmissionController(
        AdmissionConfig(search_rate=1.0, search_burst=2.0),
        clock=lambda: now[0])
    ctl.admit("search", "hot")
    ctl.admit("search", "hot")
    with pytest.raises(AdmissionReject) as ei:
        ctl.admit("search", "hot")
    assert ei.value.reason == "quota"
    assert ei.value.retry_after_s > 0
    ctl.admit("search", "calm")  # other tenant unaffected
    now[0] = 1.0
    ctl.admit("search", "hot")  # recovered after refill
    with pytest.raises(AdmissionReject):
        ctl.admit("search", "hot")


def test_tenant_universe_is_bounded():
    """Review regression: the tenant header is client-supplied — minting a
    fresh tenant per request must not buy a fresh full-burst bucket every
    time (quota bypass) nor grow per-tenant state without bound. Past
    max_tenants, new identities share the overflow tenant; operator-
    configured (weighted) tenants always keep their identity."""
    ctl = AdmissionController(AdmissionConfig(
        max_tenants=3, search_rate=1.0, search_burst=2.0,
        fair_weights="gold=4"))
    assert ctl.resolve_tenant("default") == "default"  # pre-seeded
    assert ctl.resolve_tenant("a") == "a"
    assert ctl.resolve_tenant("b") == "b"
    assert ctl.resolve_tenant("b") == "b"  # known stays known
    assert ctl.resolve_tenant("freshly-minted") == adm.OVERFLOW_TENANT
    assert ctl.resolve_tenant("gold") == "gold"  # operator-configured
    # the shared overflow bucket actually clamps: attacker tenants pool
    ctl.admit("search", ctl.resolve_tenant("atk-1"))
    ctl.admit("search", ctl.resolve_tenant("atk-2"))
    with pytest.raises(AdmissionReject):
        ctl.admit("search", ctl.resolve_tenant("atk-3"))
    assert len(ctl._seen_tenants) == 3  # no growth past the cap


# ------------------------------------------------------ weighted-fair queue


def test_fair_queue_one_hot_tenant_nine_light():
    """Satellite: fairness under one hot tenant + nine light ones. The hot
    tenant floods 30 requests; each light tenant submits one. With the
    stride scheduler every light tenant is served among the FIRST grants
    after the backlog forms — never behind the hot tenant's queue."""

    async def scenario():
        q = WeightedFairQueue(concurrency=1, max_queue=64)
        order = []

        async def worker(tenant):
            await q.acquire(tenant)
            order.append(tenant)
            await asyncio.sleep(0)  # hold the slot across one tick
            q.release(tenant)

        tasks = [asyncio.create_task(worker("hot")) for _ in range(30)]
        await asyncio.sleep(0)  # hot tenant's backlog forms first
        tasks += [asyncio.create_task(worker(f"light{i}"))
                  for i in range(9)]
        await asyncio.gather(*tasks)
        assert len(order) == 39
        # every light tenant served within the first 12 grants: vtimes
        # interleave 1:1, they can never sit behind the hot backlog
        first_12 = order[:12]
        assert all(f"light{i}" in first_12 for i in range(9)), order[:15]
        assert q.queued() == 0

    asyncio.run(scenario())


def test_fair_queue_weights_and_bounded_rejection():
    async def scenario():
        q = WeightedFairQueue(concurrency=1, max_queue=8,
                              weights={"gold": 3.0})
        order = []

        async def worker(tenant):
            await q.acquire(tenant)
            order.append(tenant)
            await asyncio.sleep(0)
            q.release(tenant)

        tasks = [asyncio.create_task(worker(t))
                 for t in ["gold", "free"] * 2 + ["gold", "gold"]]
        await asyncio.sleep(0)
        await asyncio.gather(*tasks)
        # weight 3 tenant gets ~3 grants per 1 of the weight-1 tenant
        assert order[:4].count("gold") >= 3, order

        # bounded: the third queued waiter for one tenant rejects
        q2 = WeightedFairQueue(concurrency=1, max_queue=2)

        async def worker2(tenant):
            await q2.acquire(tenant)
            order.append(tenant)
            await asyncio.sleep(0)
            q2.release(tenant)

        release_x = asyncio.Event()

        async def blocker_fn():
            await q2.acquire("x")
            await release_x.wait()  # pin the only slot deterministically
            q2.release("x")

        blocker = asyncio.create_task(blocker_fn())
        await asyncio.sleep(0)  # x holds the only slot
        held = [asyncio.create_task(worker2("y")) for _ in range(2)]
        await asyncio.sleep(0)  # both y waiters queued (queue full)
        with pytest.raises(AdmissionReject) as ei:
            await q2.acquire("y")
        assert ei.value.reason == "queue_full"
        release_x.set()
        await asyncio.gather(blocker, *held)
        assert q2.queued() == 0

    asyncio.run(scenario())


def test_fair_queue_cancelled_waiter_leaves_queue_usable():
    """Review regression: a queued waiter whose task is cancelled (client
    disconnect) must not leave an empty per-tenant deque mapped — that
    disabled the uncontended fast path forever, with no slot holder left
    to ever grant, deadlocking every later acquire."""

    async def scenario():
        q = WeightedFairQueue(concurrency=1, max_queue=8)
        release_a = asyncio.Event()

        async def holder():
            await q.acquire("a")
            await release_a.wait()
            q.release("a")

        h = asyncio.create_task(holder())
        await asyncio.sleep(0)  # a holds the only slot
        waiter = asyncio.create_task(q.acquire("b"))
        await asyncio.sleep(0)  # b queued
        waiter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await waiter
        assert q.queued() == 0
        release_a.set()
        await h
        # all slots free, nobody waiting: this acquire must return
        # immediately (pre-fix: parked forever behind the stale deque)
        await asyncio.wait_for(q.acquire("c"), timeout=5.0)
        q.release("c")

    asyncio.run(scenario())


def test_fair_queue_uncontended_history_does_not_starve():
    """Review regression: fast-path grants must advance the global virtual
    clock too. A tenant active through a quiet period used to bank virtual
    lateness; once contention started, a fresh tenant (floored at the
    stale clock) monopolized every slot until it caught up — starving the
    previously well-behaved tenant."""

    async def scenario():
        q = WeightedFairQueue(concurrency=1, max_queue=64)
        # tenant a: 100 uncontended fast-path acquires
        for _ in range(100):
            await q.acquire("a")
            q.release("a")
        order = []
        release_x = asyncio.Event()

        async def holder():
            await q.acquire("x")
            await release_x.wait()
            q.release("x")

        async def worker(tenant):
            await q.acquire(tenant)
            order.append(tenant)
            await asyncio.sleep(0)
            q.release(tenant)

        h = asyncio.create_task(holder())
        await asyncio.sleep(0)  # x pins the slot so a backlog forms
        tasks = []
        for _ in range(4):  # interleave arrivals: a, b, a, b, ...
            tasks.append(asyncio.create_task(worker("a")))
            tasks.append(asyncio.create_task(worker("b")))
            await asyncio.sleep(0)
        release_x.set()
        await asyncio.gather(h, *tasks)
        # equal weights from equal footing: grants alternate — b must NOT
        # get all four slots before a's first (the pre-fix order)
        assert order[:4].count("a") == 2, order

    asyncio.run(scenario())


# ----------------------------------------------------------- shed ladder


def test_shed_ladder_hysteresis_no_flapping():
    """Satellite: an oscillating breach (breach, clear, breach, ...) must
    PARK the ladder, not flap it — escalation needs the dwell time, and
    stepping down needs consecutive clean passes AND the dwell."""
    now = [100.0]
    ladder = DegradationLadder(recovery_passes=3, hold_s=5.0,
                               clock=lambda: now[0])
    ladder.observe(True)
    assert ladder.level == 1
    # oscillate fast (1s per pass): WITHIN the dwell window nothing moves
    for i in range(4):
        now[0] += 1.0
        ladder.observe(i % 2 == 0)
        assert ladder.level == 1, (i, ladder.level)
    # a longer oscillation may still ESCALATE (the breach persists every
    # other pass — that is real pressure) but must never step DOWN: the
    # alternating clears can never reach recovery_passes in a row
    levels = []
    for i in range(10):
        now[0] += 1.0
        ladder.observe(i % 2 == 0)
        levels.append(ladder.level)
    assert all(b >= a for a, b in zip(levels, levels[1:])), levels
    assert ladder.level == 2  # parked at the top rung, no bounce
    assert ladder.shed_generation("low") == "degrade_search"
    assert ladder.shed_generation("normal") == "degrade_search"
    assert ladder.shed_generation("high") is None  # never ladder-shed
    assert ladder.search_degraded() and ladder.degrade_top_k(10) == 3
    # zero the clean-pass streak (the oscillation's last pass was clean)
    now[0] += 10.0
    ladder.observe(True)
    assert ladder.level == 2  # already at the top rung: parked
    # recovery: needs recovery_passes CONSECUTIVE clean passes (dwell is
    # amply served by now) — and only ever steps down one rung at a time
    now[0] += 10.0
    ladder.observe(False)
    ladder.observe(False)
    assert ladder.level == 2  # two clean passes < recovery_passes
    ladder.observe(False)
    assert ladder.level == 1  # third clean pass: one rung down
    # a breach RESETS the clean-pass streak (and the dwell blocks its
    # escalation — the level just holds)
    ladder.observe(True)
    assert ladder.level == 1
    now[0] += 10.0
    ladder.observe(False)
    ladder.observe(False)
    assert ladder.level == 1  # streak restarted after the breach
    ladder.observe(False)
    assert ladder.level == 0
    assert ladder.shed_generation("low") is None


def test_watchdog_pass_listener_drives_ladder():
    """The SloWatchdog → ladder wiring: breach passes escalate, clean
    passes (including no-new-samples passes) count toward recovery."""
    from symbiont_tpu.obs.watchdog import SloWatchdog
    from symbiont_tpu.utils.telemetry import Metrics

    reg = Metrics()
    wd = SloWatchdog({"probe.op": 5.0}, registry=reg)
    now = [0.0]
    ladder = DegradationLadder(recovery_passes=1, hold_s=0.0,
                               clock=lambda: now[0])
    wd.add_listener(ladder.on_slo_pass)
    reg.observe("span.probe.op.ms", 100.0)
    assert len(wd.evaluate()) == 1
    assert ladder.level == 1
    wd.thresholds["probe.op"] = 10000.0
    reg.observe("span.probe.op.ms", 1.0)
    wd.evaluate()
    assert ladder.level == 0


# ------------------------------------------------------- deadline helpers


def test_deadline_helpers_and_child_header_threading():
    clock = lambda: 1000.0  # noqa: E731 — seconds
    h = {DEADLINE_HEADER: adm.mint_deadline(500.0, None, clock=clock),
         TENANT_HEADER: "gold"}
    assert adm.parse_deadline_ms(h) == 1000_500.0
    assert not adm.expired(h, clock=clock)
    assert adm.expired(h, clock=lambda: 1001.0)
    assert adm.tenant_of(h) == "gold"
    assert adm.tenant_of({}) == "default"
    # a client deadline can only TIGHTEN the edge budget, never extend it
    tighter = adm.mint_deadline(500.0, {DEADLINE_HEADER: "1000100"},
                                clock=clock)
    assert tighter == "1000100"
    looser = adm.mint_deadline(500.0, {DEADLINE_HEADER: "9999999999"},
                               clock=clock)
    assert looser == str(int(1000.0 * 1000 + 500))
    # garbage is NO deadline (work must not become immortal or insta-dead)
    assert adm.parse_deadline_ms({DEADLINE_HEADER: "soon"}) is None
    # the PR 2 span-header threading carries the admission pair verbatim
    out = child_headers({"X-Trace-Id": "t", "X-Span-Id": "s",
                         DEADLINE_HEADER: "123", TENANT_HEADER: "acme"})
    assert out[DEADLINE_HEADER] == "123" and out[TENANT_HEADER] == "acme"
    assert out["X-Trace-Id"] == "t" and out["X-Span-Id"] == "s"


# --------------------------------------------------------- API edge (HTTP)


def _stack_config(tmp_path, **admission_kw):
    cfg = SymbiontConfig(
        vector_store=VectorStoreConfig(dim=16,
                                       data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(markov_state_path=None),
        api=ApiConfig(host="127.0.0.1", port=0, fused_search=False),
        admission=AdmissionConfig(**admission_kw),
    )
    cfg.runner.services = ("perception,preprocessing,vector_memory,"
                           "knowledge_graph,text_generator,api")
    return cfg


def test_edge_deadline_already_expired_rejects_without_publish(tmp_path):
    """Satellite: a request arriving with an already-expired deadline is
    429'd at the edge — no bus publish, nothing downstream ever sees it."""

    async def scenario():
        bus = InprocBus()
        stack = SymbiontStack(_stack_config(tmp_path), bus=bus,
                              engine=_StubEngine(), fetcher=lambda u: PAGE)
        await stack.start()
        loop = asyncio.get_running_loop()
        port = stack.api.port
        seen = []
        sub = await bus.subscribe(subjects.TASKS_PERCEIVE_URL)

        async def spy():
            async for m in sub:
                seen.append(m)

        spy_task = asyncio.create_task(spy())
        try:
            status, headers, body = await loop.run_in_executor(
                None, lambda: _http(
                    port, "POST", "/api/submit-url",
                    {"url": "http://x/doc"},
                    {DEADLINE_HEADER: "1"}))  # epoch ms 1: long dead
            assert status == 429 and body["reason"] == "deadline"
            assert "Retry-After" in headers
            # generation and search refuse the same way
            for path, payload in (
                    ("/api/generate-text",
                     {"task_id": "t", "max_length": 4}),
                    ("/api/search/semantic",
                     {"query_text": "q", "top_k": 1})):
                status, headers, body = await loop.run_in_executor(
                    None, lambda p=path, b=payload: _http(
                        port, "POST", p, b, {DEADLINE_HEADER: "1"}))
                assert status == 429 and body["reason"] == "deadline"
            await asyncio.sleep(0.2)
            assert seen == []  # nothing was published
        finally:
            spy_task.cancel()
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


def test_quota_429_with_retry_after_then_recovery_over_http(tmp_path):
    """Satellite: quota exhaustion mid-burst answers 429 + Retry-After at
    the HTTP surface, and the SAME tenant recovers after the refill
    (injectable clock on the controller — no sleeps)."""

    async def scenario():
        now = [0.0]
        cfg = _stack_config(tmp_path)
        bus = InprocBus()
        stack = SymbiontStack(cfg, bus=bus, engine=_StubEngine(),
                              fetcher=lambda u: PAGE)
        await stack.start()
        # swap in a clock-injected controller (the runner built a real one)
        stack.api.admission = AdmissionController(
            AdmissionConfig(ingest_rate=1.0, ingest_burst=2.0),
            clock=lambda: now[0])
        loop = asyncio.get_running_loop()
        port = stack.api.port

        def submit(tenant):
            return _http(port, "POST", "/api/submit-url",
                         {"url": "http://x/doc"}, {TENANT_HEADER: tenant})

        try:
            for _ in range(2):
                status, _, _ = await loop.run_in_executor(
                    None, submit, "burst")
                assert status == 200
            status, headers, body = await loop.run_in_executor(
                None, submit, "burst")
            assert status == 429 and body["reason"] == "quota"
            assert int(headers["Retry-After"]) >= 1
            # another tenant is untouched by the hot tenant's exhaustion
            status, _, _ = await loop.run_in_executor(None, submit, "calm")
            assert status == 200
            now[0] = 2.0  # refill
            status, _, _ = await loop.run_in_executor(None, submit, "burst")
            assert status == 200
            assert metrics.get("admission.throttled",
                               labels={"class": "ingest",
                                       "tenant": "burst"}) >= 1
        finally:
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


def test_readyz_gates_on_stack_readiness(tmp_path):
    """Satellite: /healthz is liveness (200 as soon as the socket is up);
    /readyz is readiness — 503 while deferred, 200 after mark_ready. The
    runner wires defer + mark around engine placement."""

    async def scenario():
        api = ApiService(InprocBus(), ApiConfig(host="127.0.0.1", port=0),
                         BusConfig(), defer_ready=True)
        await api.start()
        loop = asyncio.get_running_loop()
        try:
            status, _, body = await loop.run_in_executor(
                None, _http, api.port, "GET", "/healthz")
            assert status == 200 and body["status"] == "ok"
            status, _, body = await loop.run_in_executor(
                None, _http, api.port, "GET", "/readyz")
            assert status == 503 and body["status"] == "starting"
            # review regression: the open-but-cold port must refuse
            # data-path work honestly (503 + Retry-After) — a 200 would
            # publish into a bus with no consumers yet: silent loss
            status, hdrs, body = await loop.run_in_executor(
                None, lambda: _http(api.port, "POST", "/api/submit-url",
                                    {"url": "http://x/warm"}))
            assert status == 503 and "Retry-After" in hdrs
            assert metrics.get("api.not_ready_rejects") >= 1
            api.mark_ready()
            status, _, body = await loop.run_in_executor(
                None, _http, api.port, "GET", "/readyz")
            assert status == 200 and body["status"] == "ready"
            status, _, _ = await loop.run_in_executor(
                None, lambda: _http(api.port, "POST", "/api/submit-url",
                                    {"url": "http://x/warm"}))
            assert status == 200  # same request admitted once ready
        finally:
            await api.stop()

        # the full runner stack arrives ready (placement done in start())
        bus = InprocBus()
        stack = SymbiontStack(_stack_config(tmp_path), bus=bus,
                              engine=_StubEngine(), fetcher=lambda u: PAGE)
        await stack.start()
        try:
            status, _, body = await loop.run_in_executor(
                None, _http, stack.api.port, "GET", "/readyz")
            assert status == 200
        finally:
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


def test_generation_capacity_shed_consults_lm():
    """Capacity-aware generation admission: the edge consults the LM's
    can_admit (KV-row occupancy) BEFORE accepting a stream — at capacity
    the answer is 429/kv_capacity, and admission.shed counts it."""

    async def scenario():
        full = [True]
        api = ApiService(InprocBus(), ApiConfig(host="127.0.0.1", port=0),
                         BusConfig(), gen_capacity=lambda: not full[0])
        await api.start()
        loop = asyncio.get_running_loop()
        try:
            def gen():
                return _http(api.port, "POST", "/api/generate-text",
                             {"task_id": "cap", "max_length": 4},
                             {TENANT_HEADER: "t"})

            status, headers, body = await loop.run_in_executor(None, gen)
            assert status == 429 and body["reason"] == "kv_capacity"
            assert "Retry-After" in headers
            assert metrics.get("admission.shed",
                               labels={"reason": "kv_capacity",
                                       "tenant": "t"}) >= 1
            full[0] = False
            status, _, _ = await loop.run_in_executor(None, gen)
            assert status == 200
        finally:
            await api.stop()

    asyncio.run(scenario())


def test_lm_can_admit_counts_allocated_rows():
    """LmEngine.can_admit against real sessions: allocated KV rows gate
    admission, and a finished session releases its rows."""
    jax = pytest.importorskip("jax")  # noqa: F841
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    lm = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                           num_heads=2, intermediate_size=64,
                           max_positions=64, dtype="float32",
                           prompt_buckets=[8], new_token_buckets=[8],
                           stream_chunk=4, session_min_rows=2))
    assert lm.can_admit(1, 0)  # cap 0 = unbounded
    assert lm.kv_rows_allocated() == 0
    sess = lm.start_session(["a", "b"], [8, 8], temperature=0.0)
    assert lm.kv_rows_allocated() == sess.bb
    assert lm.can_admit(1, sess.bb + 1)
    assert not lm.can_admit(1, sess.bb)
    while not sess.done():
        sess.step()
    assert lm.kv_rows_allocated() == 0
    assert lm.can_admit(1, sess.bb)


def test_lm_can_admit_paged_quotes_pages_not_rows():
    """The 429-vs-admit boundary under kv_layout=paged: can_admit answers
    from free-page accounting (pool free + evictable − reserved by live
    rows), not dense row capacity — and a radix-hit prompt, which needs
    only its post-fork fresh pages, is admitted where a cold prompt of
    the same shape is refused."""
    jax = pytest.importorskip("jax")  # noqa: F841
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    def mk(**kw):
        return LmEngine(LmConfig(
            enabled=True, hidden_size=32, num_layers=1, num_heads=2,
            intermediate_size=64, max_positions=256, dtype="float32",
            prompt_buckets=[16], new_token_buckets=[32], stream_chunk=8,
            session_min_rows=1, gen_max_batch=1, kv_layout="paged",
            kv_page_tokens=16, temperature=0.0, **kw))

    # pool sized for ONE session (3 blocks/row: 16 prompt + 32 decode
    # tokens at 16/page): a second concurrent session must 429 even
    # though a dense engine would have row capacity for it
    lm = mk(kv_pool_pages=5, kv_radix=False)
    assert lm.can_admit(1, 0)
    sess = lm.start_session(["hold the pool"], [32], temperature=0.0)
    assert not lm.can_admit(1, 0)
    while not sess.done():
        sess.step()
    assert lm.can_admit(1, 0)  # pages returned → admissible again

    # radix deduction: same boundary, but a warm prompt's shared pages
    # don't count against the quote
    lm2 = mk(kv_pool_pages=6)
    sess2 = lm2.start_session(["warm this prompt"], [32], temperature=0.0)
    while not sess2.done():
        sess2.step()
    held = lm2.pool.alloc(3)  # leave 1 free + 1 retained
    assert lm2.can_admit(1, 0, prompts=["warm this prompt"],
                         max_new_tokens=[32])
    assert not lm2.can_admit(1, 0, prompts=["cold prompt here"],
                             max_new_tokens=[32])
    for pid in held:
        lm2.pool.release(pid)


# -------------------------------------------- deadline propagation (chaos)


def test_expired_deadline_dropped_at_every_downstream_service(tmp_path):
    """Acceptance: an expired message is dropped at EVERY downstream
    service — handler never invoked, no retry, no DLQ. The deadline is
    minted at the edge (valid there), and a seeded fault DELAYS the
    perception handler past it, so everything downstream receives
    already-expired work through the real child_headers threading."""
    from symbiont_tpu.resilience.faults import FaultPlan, FaultRule

    plan = FaultPlan(seed=21, rules=[
        FaultRule(seam="handler", kind="delay", delay_s=0.7,
                  match="perception:tasks.perceive.url", times=1)])

    async def scenario():
        cfg = _stack_config(tmp_path,
                            deadline_ingest_ms=300.0)  # expires mid-scrape
        cfg.bus.durable = True
        cfg.bus.durable_ack_wait_s = 0.2
        engine = _StubEngine()
        bus = InprocBus()
        stack = SymbiontStack(cfg, bus=bus, engine=engine,
                              fetcher=lambda u: PAGE)
        await stack.start()
        loop = asyncio.get_running_loop()
        port = stack.api.port
        base_expired = metrics.get("admission.expired",
                                   labels={"service": "preprocessing",
                                           "subject":
                                           "data.raw_text.discovered"})
        try:
            with plan.activate():
                status, _, _ = await loop.run_in_executor(
                    None, lambda: _http(port, "POST", "/api/submit-url",
                                        {"url": "http://x/doc"}))
                assert status == 200  # valid at the edge: accepted
                # perception's delayed handler publishes AFTER the deadline
                ok = await _wait_for(lambda: metrics.get(
                    "admission.expired",
                    labels={"service": "preprocessing",
                            "subject": "data.raw_text.discovered"})
                    > base_expired, timeout=10.0)
            assert ok, "preprocessing never counted the expired drop"
            await asyncio.sleep(0.6)  # would-be redeliveries / retries
            # the handler body NEVER ran: no embed, nothing stored
            assert engine.stats["embed_calls"] == 0
            assert stack.vector_store.count() == 0
            # ACKED, not retried: durable redelivery never fired for it,
            # and it never landed in the DLQ as poison
            assert len(bus.dlq) == 0
            assert metrics.get("bus.failed",
                               labels={"service": "preprocessing",
                                       "subject":
                                       "data.raw_text.discovered"}) == 0
        finally:
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


def test_fresh_deadline_flows_end_to_end(tmp_path):
    """Control for the drop test: the same stack with a roomy deadline
    ingests normally — the deadline machinery is inert for live work."""

    async def scenario():
        cfg = _stack_config(tmp_path, deadline_ingest_ms=30000.0)
        cfg.bus.durable = True
        bus = InprocBus()
        stack = SymbiontStack(cfg, bus=bus, engine=_StubEngine(),
                              fetcher=lambda u: PAGE)
        await stack.start()
        loop = asyncio.get_running_loop()
        try:
            status, _, _ = await loop.run_in_executor(
                None, lambda: _http(stack.api.port, "POST",
                                    "/api/submit-url",
                                    {"url": "http://x/doc"}))
            assert status == 200
            assert await _wait_for(lambda: stack.vector_store.count() >= 2)
        finally:
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


# ------------------------------------------- SSE disconnect cancellation


def test_cancel_tag_frees_rows_and_kv_gauges_return_to_baseline():
    """Satellite (deterministic half): cancelling a session row frees it
    immediately — capacity returns, and the lm.kv_* occupancy gauges read
    baseline once every row is cancelled, without decoding to budget."""
    jax = pytest.importorskip("jax")  # noqa: F841
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    lm = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                           num_heads=2, intermediate_size=64,
                           max_positions=64, dtype="float32",
                           prompt_buckets=[8], new_token_buckets=[8],
                           stream_chunk=4, session_min_rows=2))
    labels = {"service": "lm", "kv_dtype": "float32"}
    sess = lm.start_session(["a", "b"], [8, 8], temperature=0.0)
    tags = [r.tag for r in sess.rows if r is not None]
    assert metrics.gauge_get("lm.kv_rows_active", labels=labels) == 2
    assert metrics.gauge_get("lm.kv_rows_allocated",
                             labels=labels) == sess.bb
    assert sess.cancel_tag(tags[0])
    assert metrics.gauge_get("lm.kv_rows_active", labels=labels) == 1
    assert sess.capacity() >= 1  # the slot is admissible again
    assert sess.cancel_tag(tags[1])
    assert sess.done()
    # every gauge back to baseline without a single further decode step
    assert metrics.gauge_get("lm.kv_rows_active", labels=labels) == 0
    assert metrics.gauge_get("lm.kv_rows_allocated", labels=labels) == 0
    assert not sess.cancel_tag(tags[0])  # idempotent on a dead tag


def test_sse_disconnect_cancels_stream_and_skips_final(tmp_path):
    """Satellite (end-to-end half): an SSE client following its task
    disconnects mid-stream → the gateway publishes
    tasks.generation.cancel → the text generator closes the decode stream
    early and publishes NO final event; the kv gauges stay at baseline
    after the abort."""
    pytest.importorskip("jax")
    from symbiont_tpu.config import LmConfig

    cfg = SymbiontConfig(
        vector_store=VectorStoreConfig(dim=16,
                                       data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(markov_state_path=None),
        api=ApiConfig(host="127.0.0.1", port=0, sse_keepalive_s=0.3),
        # heavy enough that a 256-token decode spans many chunk
        # boundaries of real wall time — the cancel must land mid-flight
        lm=LmConfig(enabled=True, hidden_size=256, num_layers=2,
                    num_heads=4, intermediate_size=512, max_positions=512,
                    dtype="float32", prompt_buckets=[16],
                    new_token_buckets=[256], stream_chunk=8,
                    gen_flush_deadline_ms=5.0, temperature=0.0),
    )
    cfg.runner.services = "text_generator,api"

    async def scenario():
        bus = InprocBus()
        stack = SymbiontStack(cfg, bus=bus)
        await stack.start()
        loop = asyncio.get_running_loop()
        port = stack.api.port
        finals = []
        sub = await bus.subscribe(subjects.EVENTS_TEXT_GENERATED)

        async def collect():
            async for m in sub:
                finals.append(json.loads(m.data))

        collector = asyncio.create_task(collect())
        try:
            # SSE client follows ITS task
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           port)
            writer.write(b"GET /api/events?task_id=cancel-me HTTP/1.1\r\n"
                         b"Host: x\r\n\r\n")
            await writer.drain()
            status, _, _ = await loop.run_in_executor(
                None, lambda: _http(port, "POST", "/api/generate-text",
                                    {"task_id": "cancel-me",
                                     "prompt": "tensor", "max_length": 256,
                                     "stream": True}))
            assert status == 200
            # wait for the FIRST delta (decode demonstrably in flight)...
            got_delta = False
            deadline = loop.time() + 120
            while loop.time() < deadline and not got_delta:
                line = await asyncio.wait_for(reader.readline(), 120)
                got_delta = line.startswith(b"data: ")
            assert got_delta
            # ...then vanish mid-generation
            writer.close()
            ok = await _wait_for(
                lambda: metrics.get("text_generator.cancelled") >= 1,
                timeout=30.0)
            assert ok, "cancel never reached the text generator"
            assert metrics.get("api.sse_gen_cancels") >= 1
            await asyncio.sleep(0.3)  # drain any delta already in flight
            chunks = metrics.get("text_generator.stream_chunks")
            await asyncio.sleep(0.5)
            # decode actually STOPPED (no further chunks) and no final
            # message was published for the cancelled task
            assert metrics.get("text_generator.stream_chunks") == chunks
            assert not any(f["original_task_id"] == "cancel-me"
                           for f in finals)
            # stream path holds no session rows: gauges at baseline
            labels = {"service": "lm", "kv_dtype": "float32"}
            assert metrics.gauge_get("lm.kv_rows_active",
                                     labels=labels) == 0
        finally:
            collector.cancel()
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


def test_cancel_arriving_before_generate_is_honored():
    """Review regression: under overload a generate task can sit bus-queued
    while its SSE reader vanishes — the cancel then arrives BEFORE
    _handle_generate registers the task. It must tombstone the id so the
    decode aborts on arrival instead of running its full budget (and no
    final event is published for a reader that is already gone)."""
    from symbiont_tpu.bus.core import Msg
    from symbiont_tpu.schema import GenerateTextTask, to_json_bytes
    from symbiont_tpu.services.text_generator import TextGeneratorService

    async def scenario():
        bus = InprocBus()
        svc = TextGeneratorService(bus, train_on_ingest=False,
                                   state_path=None)
        finals = []
        sub = await bus.subscribe(subjects.EVENTS_TEXT_GENERATED)

        async def collect():
            async for m in sub:
                finals.append(json.loads(m.data))

        collector = asyncio.create_task(collect())
        before = metrics.get("text_generator.cancelled")
        try:
            await svc._handle_cancel(Msg(
                subjects.TASKS_GENERATION_CANCEL,
                json.dumps({"task_id": "race-1"}).encode()))
            task = GenerateTextTask(task_id="race-1", prompt="hello",
                                    max_length=32)
            await svc._handle_generate(Msg(
                subjects.TASKS_GENERATION_TEXT, to_json_bytes(task)))
            assert metrics.get("text_generator.cancelled") == before + 1
            assert "race-1" not in svc._cancelled_early  # consumed
            await asyncio.sleep(0.1)
            assert finals == []  # no final event for the vanished reader
            # an UNcancelled task on the same service still publishes
            task2 = GenerateTextTask(task_id="live-1", prompt="hello",
                                     max_length=16)
            await svc._handle_generate(Msg(
                subjects.TASKS_GENERATION_TEXT, to_json_bytes(task2)))
            assert await _wait_for(
                lambda: any(f["original_task_id"] == "live-1"
                            for f in finals))
            # review regression: a LATE cancel (task already finished —
            # e.g. the SSE reader closed right as the final raced out)
            # must not tombstone the id: a resubmission reusing it would
            # be silently cancelled before decoding
            await svc._handle_cancel(Msg(
                subjects.TASKS_GENERATION_CANCEL,
                json.dumps({"task_id": "live-1"}).encode()))
            assert "live-1" not in svc._cancelled_early
            finals.clear()
            await svc._handle_generate(Msg(
                subjects.TASKS_GENERATION_TEXT, to_json_bytes(task2)))
            assert await _wait_for(
                lambda: any(f["original_task_id"] == "live-1"
                            for f in finals))
        finally:
            collector.cancel()
            await bus.close()

    asyncio.run(scenario())


def test_sse_disconnect_of_unsubmitted_task_publishes_no_cancel():
    """Review regression: a reader that pre-connects /api/events with a
    client-minted task id and drops BEFORE ever POSTing the generation
    must not publish a cancel — the tombstone would silently kill the
    legitimate submission that follows."""

    async def scenario():
        bus = InprocBus()
        api = ApiService(bus, ApiConfig(host="127.0.0.1", port=0,
                                        sse_keepalive_s=0.2), BusConfig())
        await api.start()
        cancels = []

        async def watch():
            sub = await bus.subscribe(subjects.TASKS_GENERATION_CANCEL)
            async for m in sub:
                cancels.append(json.loads(m.data))

        watcher = asyncio.create_task(watch())
        before = metrics.get("api.sse_gen_cancels")
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           api.port)
            writer.write(b"GET /api/events?task_id=never-submitted "
                         b"HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            await reader.readline()  # status line: connection is live
            writer.close()
            await asyncio.sleep(0.5)  # teardown ran (keepalive tick)
            assert cancels == []
            assert metrics.get("api.sse_gen_cancels") == before
        finally:
            watcher.cancel()
            await api.stop()
            await bus.close()

    asyncio.run(scenario())


def test_graph_search_rides_fair_queue_and_degraded_rung():
    """Review regression: /api/search/graph shares the 'search' admission
    class — it must also ride the weighted-fair concurrency queue and the
    ladder's degraded top-k clamp, or a graph-search storm sidesteps both
    protections semantic search enforces."""

    async def scenario():
        bus = InprocBus()
        ctl = AdmissionController(AdmissionConfig(
            search_rate=1000, search_burst=1000, search_concurrency=1))
        ladder = DegradationLadder(clock=lambda: 100.0)
        ladder.level = 2  # degraded search rung
        api = ApiService(bus, ApiConfig(host="127.0.0.1", port=0),
                         BusConfig(), admission=ctl, ladder=ladder)
        await api.start()
        seen = []

        async def answer():
            sub = await bus.subscribe(subjects.TASKS_SEARCH_GRAPH_REQUEST)
            async for m in sub:
                seen.append(json.loads(m.data))
                await bus.publish(m.reply, json.dumps(
                    {"results": [], "error_message": None}).encode())

        answering = asyncio.create_task(answer())
        loop = asyncio.get_running_loop()
        try:
            acquires = []
            real_acquire = ctl.fair_queue.acquire

            async def counting_acquire(tenant):
                acquires.append(tenant)
                await real_acquire(tenant)

            ctl.fair_queue.acquire = counting_acquire
            status, _, body = await loop.run_in_executor(
                None, lambda: _http(api.port, "POST", "/api/search/graph",
                                    {"query_text": "abc", "top_k": 50},
                                    {TENANT_HEADER: "g"}))
            assert status == 200
            assert acquires == ["g"]  # rode the fair queue
            assert ctl.fair_queue.queued() == 0
            assert ctl.fair_queue._free == 1  # and released the slot
            # rung 2 clamped the requested top_k before the bus hop
            assert seen and seen[0]["top_k"] == ladder.degraded_top_k
            assert metrics.get("admission.degraded",
                               labels={"what": "search",
                                       "tenant": "g"}) >= 1
        finally:
            answering.cancel()
            await api.stop()
            await bus.close()

    asyncio.run(scenario())


# ------------------------------------------------- graph-augmented search


def test_graph_search_end_to_end(tmp_path):
    """Satellite: the knowledge-graph limb as a live scenario — ingest
    builds the graph (entity extraction → graph upsert), then
    POST /api/search/graph answers token-overlap hits with snippets."""

    async def scenario():
        bus = InprocBus()
        stack = SymbiontStack(_stack_config(tmp_path), bus=bus,
                              engine=_StubEngine(), fetcher=lambda u: PAGE)
        await stack.start()
        loop = asyncio.get_running_loop()
        port = stack.api.port
        try:
            status, _, _ = await loop.run_in_executor(
                None, lambda: _http(port, "POST", "/api/submit-url",
                                    {"url": "http://x/doc"}))
            assert status == 200
            assert await _wait_for(
                lambda: stack.graph_store.counts()["Document"] >= 1)
            status, _, body = await loop.run_in_executor(
                None, lambda: _http(port, "POST", "/api/search/graph",
                                    {"query_text":
                                     "admission TESTING sentence",
                                     "top_k": 3}))
            assert status == 200 and body["error_message"] is None
            assert len(body["results"]) == 1
            hit = body["results"][0]
            assert hit["match_count"] == 3  # case-insensitive overlap
            assert "admission" in hit["matched_tokens"]
            assert "Admission testing sentence one." in hit["snippet"]
            # no-overlap query: clean empty result, not an error
            status, _, body = await loop.run_in_executor(
                None, lambda: _http(port, "POST", "/api/search/graph",
                                    {"query_text": "zzz qqq", "top_k": 3}))
            assert status == 200 and body["results"] == []
            # empty query: 400 at the edge
            status, _, body = await loop.run_in_executor(
                None, lambda: _http(port, "POST", "/api/search/graph",
                                    {"query_text": " ", "top_k": 3}))
            assert status == 400
        finally:
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


# ---------------------------------------------------------------------------
# Engine-plane tenant fairness (PR 10): the batcher's per-tenant lanes must
# uphold the fairness guarantee WITHOUT any edge admission in front — the
# exact scenario where a replicated/bypassed/restarted gateway would
# otherwise re-create hot-tenant starvation at the device queue.
# ---------------------------------------------------------------------------


def _jain(xs):
    xs = [float(x) for x in xs]
    ssq = sum(x * x for x in xs)
    return 0.0 if not ssq else (sum(xs) ** 2) / (len(xs) * ssq)


class _SlowStubEngine:
    """Duck-typed embed engine whose forward is slow enough that a backlog
    forms — chunk composition (not engine speed) decides who gets served."""

    class _ModelCfg:
        hidden_size = 8

    def __init__(self, delay_s=0.005):
        from symbiont_tpu.config import EngineConfig

        self.config = EngineConfig(embedding_dim=8, max_batch=4,
                                   flush_deadline_ms=1.0)
        self.model_cfg = self._ModelCfg()
        self.delay_s = delay_s
        self.served = []  # flush order, one entry per text

    def embed_texts(self, texts):
        import time as _t

        _t.sleep(self.delay_s)
        self.served.extend(texts)
        return np.zeros((len(texts), 8), np.float32)


def test_batcher_fairness_with_edge_admission_disabled():
    """One ~10x hot tenant floods the micro-batcher DIRECTLY (no edge, no
    quotas, no fair queue): per-tenant admitted throughput across the
    backlog window must still be fair (Jain >= 0.8 over completion of the
    normals' work), because TenantLanes interleaves lanes stride-fair
    instead of FIFO-serving the hot tenant's head start."""
    from symbiont_tpu.engine.batcher import MicroBatcher

    engine = _SlowStubEngine()
    normals = [f"t{i}" for i in range(4)]

    async def scenario():
        b = MicroBatcher(engine)
        await b.start()
        try:
            # the hot tenant gets its whole flood queued FIRST — under the
            # old FIFO every normal tenant would wait out all 60 items
            hot = [asyncio.ensure_future(
                b.embed([f"hot-{i}"], tenant="hot")) for i in range(60)]
            waits = {}
            t0 = asyncio.get_running_loop().time()

            async def timed(tenant, i):
                await b.embed([f"{tenant}-{i}"], tenant=tenant)
                waits.setdefault(tenant, []).append(
                    asyncio.get_running_loop().time() - t0)

            normal_futs = [asyncio.ensure_future(timed(t, i))
                           for t in normals for i in range(6)]
            await asyncio.gather(*normal_futs)
            # every normal tenant finished its 6 items while the hot flood
            # was still draining — the FIFO order would have served all 60
            # hot items first
            remaining_hot = sum(1 for f in hot if not f.done())
            assert remaining_hot > 0, (
                "hot flood fully drained before the normals finished — "
                "the lanes did not interleave")
            admitted = {t: len(waits[t]) for t in normals}
            admitted["hot"] = 60 - remaining_hot
            jain = _jain(admitted.values())
            assert jain >= 0.8, (jain, admitted)
            await asyncio.gather(*hot)
        finally:
            await b.close()

    asyncio.run(scenario())


def test_tenant_lanes_stride_order_and_requeue():
    from symbiont_tpu.engine.batcher import TenantLanes

    class Item:
        def __init__(self, tag, tenant):
            self.tag, self.tenant = tag, tenant
            self.future = None

    lanes = TenantLanes(kind="test")
    for i in range(4):
        lanes.append(Item(f"a{i}", "a"))
    for i in range(2):
        lanes.append(Item(f"b{i}", "b"))
    # stride order with equal weights: strict interleave while both lanes
    # hold items, per-lane FIFO always
    order = [it.tag for it in lanes.fair_order()]
    assert order == ["a0", "b0", "a1", "b1", "a2", "a3"]
    # iteration (the duck-typed deque surface) matches the fair order and
    # consumes nothing
    assert [it.tag for it in lanes] == order
    assert len(lanes) == 6
    # popleft serves exactly that order; peek always previews it
    assert lanes.peek().tag == "a0"
    got = [lanes.popleft().tag for _ in range(3)]
    assert got == ["a0", "b0", "a1"]
    # requeue_front returns items to their OWN lanes, ahead, in order
    back = [it for it in lanes.fair_order()]
    lanes.requeue_front([i for i in back if i.tenant == "a"][:1])
    assert lanes.peek().tenant in ("a", "b")
    assert len(lanes) == 4


def test_tenant_lanes_bounded_reject_and_overflow_fold():
    from symbiont_tpu.engine.batcher import TenantLanes
    from symbiont_tpu.resilience.admission import (
        OVERFLOW_TENANT,
        AdmissionReject,
    )

    class Item:
        def __init__(self, tenant):
            self.tenant = tenant
            self.future = None

    lanes = TenantLanes(kind="test", max_per_tenant=2, max_lanes=3)
    lanes.append(Item("a"))
    lanes.append(Item("a"))
    with pytest.raises(AdmissionReject) as ei:
        lanes.append(Item("a"))  # lane full -> bounded, shed
    assert ei.value.reason == "engine_lane_full"
    # the identity bound is CUMULATIVE (resolve_tenant stance, and the
    # default lane is pre-seeded like the edge's): max_lanes=3 means
    # {default, a, b} — every identity AFTER that shares the overflow
    # lane forever, so cycling fresh tenant names grows no clock state
    # and no gauge label cardinality
    lanes.append(Item("b"))
    assert lanes._lane_key(Item("c")) == OVERFLOW_TENANT
    lanes.append(Item("c"))
    lanes.append(Item("fresh-1"))
    assert lanes._lane_key(Item("fresh-2")) == OVERFLOW_TENANT
    # overflow lane is bounded too
    with pytest.raises(AdmissionReject):
        lanes.append(Item("fresh-2"))
    # ...and DRAINING everything retires the clock debt: a drained lane's
    # entry is forgotten (≤ one grant past the floor), so the vtime book
    # tracks live lanes, not every identity ever seen
    while len(lanes):
        lanes.popleft()
    assert lanes._clock._vtime == {}


def test_tenant_depth_gauge_tracks_lanes():
    from symbiont_tpu.engine.batcher import TenantLanes
    from symbiont_tpu.utils.telemetry import metrics

    class Item:
        def __init__(self, tenant):
            self.tenant = tenant
            self.future = None

    lanes = TenantLanes(kind="gaugetest")
    lanes.append(Item("gold"))
    lanes.append(Item("gold"))
    assert metrics.gauge_get("batcher.tenant_depth",
                             labels={"batcher": "gaugetest",
                                     "tenant": "gold"}) == 2
    lanes.popleft()
    assert metrics.gauge_get("batcher.tenant_depth",
                             labels={"batcher": "gaugetest",
                                     "tenant": "gold"}) == 1


def test_gen_batcher_threads_tenant_and_stays_bounded():
    """GenBatcher lanes: tenant kwarg lands items in their lanes and the
    gen lane bound rejects with the typed AdmissionReject."""
    from types import SimpleNamespace

    from symbiont_tpu.engine.batcher import GenBatcher

    class FakeLm:
        config = SimpleNamespace(gen_max_batch=8, gen_flush_deadline_ms=1.0,
                                 new_token_buckets=[16], temperature=1.0,
                                 top_k=0, gen_tenant_lane_depth=2)

    async def scenario():
        b = GenBatcher(FakeLm())  # _run not started: queue-only test
        futs = [asyncio.ensure_future(
            b.generate("p", 4, tenant="flood")) for _ in range(2)]
        await asyncio.sleep(0)  # let the submits land
        with pytest.raises(AdmissionReject):
            await b.generate("p", 4, tenant="flood")
        assert len(b._queue) == 2
        for f in futs:
            f.cancel()

    asyncio.run(scenario())


def test_stride_clock_shared_between_edge_and_lanes():
    """The edge fair queue and the batcher lanes run the SAME scheduler
    class (StrideClock) — weight semantics cannot drift between planes."""
    from symbiont_tpu.engine.batcher import TenantLanes
    from symbiont_tpu.resilience.admission import StrideClock

    clock = StrideClock({"gold": 2.0})
    # gold (weight 2) gets two grants per free grant
    grants = []
    for _ in range(6):
        t = clock.pick(["gold", "free"])
        grants.append(t)
        clock.charge(t)
    assert grants.count("gold") == 4 and grants.count("free") == 2
    lanes = TenantLanes(kind="wtest", weights={"gold": 2.0})
    assert lanes._clock.weights == {"gold": 2.0}
