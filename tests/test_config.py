import json

from symbiont_tpu.config import SymbiontConfig, load_config


def test_defaults():
    cfg = SymbiontConfig()
    assert cfg.vector_store.dim == 768
    assert cfg.vector_store.collection == "symbiont_document_embeddings"
    assert cfg.engine.length_buckets == [32, 64, 128, 256, 512]


def test_file_then_env_precedence(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"api": {"port": 9000}, "engine": {"embedding_dim": 384}}))
    cfg = load_config(p, env={"SYMBIONT_API_PORT": "9100"})
    assert cfg.api.port == 9100  # env wins over file
    assert cfg.engine.embedding_dim == 384  # file wins over default


def test_reference_env_aliases(tmp_path):
    cfg = load_config(env={
        "NATS_URL": "symbus://bus:4233",
        "FORCE_CPU": "true",
        "API_SERVER_PORT": "8088",
    })
    assert cfg.bus.url == "symbus://bus:4233"
    assert cfg.engine.force_cpu is True
    assert cfg.api.port == 8088


def test_canonical_env_beats_legacy_alias():
    cfg = load_config(env={
        "NATS_URL": "nats://old-host:4222",
        "SYMBIONT_BUS_URL": "symbus://bus:4233",
    })
    assert cfg.bus.url == "symbus://bus:4233"


def test_explicit_missing_config_path_raises(tmp_path):
    import pytest

    with pytest.raises(FileNotFoundError):
        load_config(tmp_path / "missing.json")


def test_unknown_file_key_rejected(tmp_path):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps({"api": {"bogus": 1}}))
    try:
        load_config(p)
    except ValueError as e:
        assert "bogus" in str(e)
    else:
        raise AssertionError("expected ValueError")


def test_fused_top_k_must_be_covered_by_warm_buckets():
    """api.fused_search_max_top_k above vector_store.warm_top_k would send
    fused queries into unwarmed k buckets (cold compile inside the probe
    timeout) — rejected at startup."""
    import pytest

    from symbiont_tpu.config import ApiConfig, SymbiontConfig, VectorStoreConfig

    with pytest.raises(ValueError, match="warm_top_k"):
        SymbiontConfig(api=ApiConfig(fused_search_max_top_k=64))
    SymbiontConfig(api=ApiConfig(fused_search_max_top_k=64),
                   vector_store=VectorStoreConfig(warm_top_k=64))


def test_validators_fire_on_loaded_overrides():
    """File/env overrides mutate sections via setattr, bypassing dataclass
    construction — load_config must re-run the validators afterwards."""
    import pytest

    from symbiont_tpu.config import load_config

    with pytest.raises(ValueError, match="warm_top_k"):
        load_config(env={"SYMBIONT_API_FUSED_SEARCH_MAX_TOP_K": "64"})
    with pytest.raises(ValueError, match="stream_chunk"):
        load_config(env={"SYMBIONT_LM_STREAM_CHUNK": "24"})
    load_config(env={"SYMBIONT_API_FUSED_SEARCH_MAX_TOP_K": "64",
                     "SYMBIONT_VECTOR_STORE_WARM_TOP_K": "64"})
