"""Wire-schema round-trip tests.

Parity with the reference's entire automated test suite — 13 serde round-trip
tests (reference: libs/shared_models/src/lib.rs:123-537) — plus strict-decode
cases the reference lacks.
"""

import json

import pytest

from symbiont_tpu import schema
from symbiont_tpu.schema import (
    GeneratedTextChunk,
    GeneratedTextMessage,
    GenerateTextTask,
    PerceiveUrlTask,
    QdrantPointPayload,
    QueryEmbeddingResult,
    QueryForEmbeddingTask,
    RawTextMessage,
    SemanticSearchApiRequest,
    SemanticSearchApiResponse,
    SemanticSearchNatsResult,
    SemanticSearchNatsTask,
    SemanticSearchResultItem,
    SentenceEmbedding,
    TextWithEmbeddingsMessage,
    TokenizedTextMessage,
    from_json,
    to_json,
)

PAYLOAD = QdrantPointPayload(
    original_document_id="doc-1",
    source_url="http://example.com",
    sentence_text="Hello world.",
    sentence_order=3,
    model_name="mpnet",
    processed_at_ms=1718000000000,
)

CASES = [
    PerceiveUrlTask(url="http://example.com"),
    RawTextMessage(id="test-id", source_url="http://example.com",
                   raw_text="Some raw text", timestamp_ms=1718000000000),
    TokenizedTextMessage(original_id="doc-1", source_url="http://example.com",
                         tokens=["Hello", "world"], sentences=["Hello world."],
                         timestamp_ms=1718000000000),
    GenerateTextTask(task_id="t-1", prompt="seed", max_length=50),
    GenerateTextTask(task_id="t-2", prompt=None, max_length=50),
    GeneratedTextMessage(original_task_id="t-1", generated_text="words words",
                         timestamp_ms=1718000000000),
    SentenceEmbedding(sentence_text="Hello.", embedding=[0.1, -0.2, 0.3]),
    TextWithEmbeddingsMessage(
        original_id="doc-1", source_url="http://example.com",
        embeddings_data=[SentenceEmbedding(sentence_text="a", embedding=[1.0, 2.0])],
        model_name="mpnet", timestamp_ms=1718000000000),
    # rerank=True first: the C++ parity harness samples the first case per
    # type, so this exercises the generated bool codec end-to-end
    SemanticSearchApiRequest(query_text="with rerank", top_k=5, rerank=True),
    SemanticSearchApiRequest(query_text="what is symbiont", top_k=5),
    QueryForEmbeddingTask(request_id="r-1", text_to_embed="query text"),
    QueryEmbeddingResult(request_id="r-1", embedding=[0.5, 0.5],
                         model_name="mpnet", error_message=None),
    QueryEmbeddingResult(request_id="r-2", embedding=None, model_name=None,
                         error_message="boom"),
    PAYLOAD,
    SemanticSearchNatsTask(request_id="r-1", query_embedding=[0.1] * 4, top_k=3),
    SemanticSearchResultItem(qdrant_point_id="p-1", score=0.87, payload=PAYLOAD),
    SemanticSearchNatsResult(
        request_id="r-1",
        results=[SemanticSearchResultItem(qdrant_point_id="p-1", score=0.9,
                                          payload=PAYLOAD)],
        error_message=None),
    SemanticSearchApiResponse(search_request_id="r-1", results=[],
                              error_message="nothing found"),
    GeneratedTextChunk(original_task_id="t-1", text_delta="hello ",
                       seq=3, done=False, timestamp_ms=1718000000000),
]


@pytest.mark.parametrize("msg", CASES, ids=lambda m: type(m).__name__)
def test_round_trip(msg):
    raw = to_json(msg)
    back = from_json(type(msg), raw)
    assert back == msg
    # and the JSON is plain-dict stable
    assert json.loads(to_json(back)) == json.loads(raw)


def test_all_thirteen_types_registered():
    # parity check against reference: libs/shared_models/src/lib.rs declares
    # 13 (+2 nested); GeneratedTextChunk is this framework's streaming
    # addition
    assert len(schema.WIRE_TYPES) == 13 + 2 + 1
    names = {t.__name__ for t in schema.WIRE_TYPES}
    assert {
        "PerceiveUrlTask", "RawTextMessage", "TokenizedTextMessage",
        "GenerateTextTask", "GeneratedTextMessage", "SentenceEmbedding",
        "TextWithEmbeddingsMessage", "SemanticSearchApiRequest",
        "QueryForEmbeddingTask", "QueryEmbeddingResult", "QdrantPointPayload",
        "SemanticSearchNatsTask", "SemanticSearchResultItem",
        "SemanticSearchNatsResult", "SemanticSearchApiResponse",
        "GeneratedTextChunk",
    } == names


def test_optional_serializes_as_null():
    raw = to_json(GenerateTextTask(task_id="t", prompt=None, max_length=5))
    assert json.loads(raw)["prompt"] is None


def test_missing_required_field_raises():
    with pytest.raises(ValueError, match="missing required field"):
        from_json(RawTextMessage, '{"id": "x"}')


def test_unknown_field_raises():
    with pytest.raises(ValueError, match="unknown fields"):
        from_json(PerceiveUrlTask, '{"url": "u", "extra": 1}')


def test_unicode_round_trip():
    # reference corpus is Russian text (reference:
    # services/text_generator_service/src/main.rs:170) — non-ASCII must survive
    msg = RawTextMessage(id="id", source_url="u", raw_text="Привет, мир! 世界",
                         timestamp_ms=1)
    assert from_json(RawTextMessage, to_json(msg)).raw_text == "Привет, мир! 世界"


def test_missing_optional_field_defaults_none():
    got = from_json(GenerateTextTask, '{"task_id": "t", "max_length": 3}')
    assert got.prompt is None


def test_reference_search_request_still_decodes():
    """Reference-era clients send only query_text/top_k (reference:
    libs/shared_models/src/lib.rs:55-58); the added rerank flag must stay
    optional and strictly boolean when present."""
    got = from_json(SemanticSearchApiRequest, '{"query_text": "q", "top_k": 2}')
    assert got.rerank is None
    with pytest.raises(ValueError, match="expected boolean"):
        from_json(SemanticSearchApiRequest,
                  '{"query_text": "q", "top_k": 2, "rerank": 1}')


def test_deterministic_point_id():
    """Content-derived point ids: stable, uuid-shaped, distinct per
    (doc, order) — the idempotent-redelivery contract (C++ parity is asserted
    in test_native_services.py over the real pipeline)."""
    import re

    from symbiont_tpu.utils.ids import deterministic_point_id

    a = deterministic_point_id("doc-1", 0)
    assert a == deterministic_point_id("doc-1", 0)
    assert re.fullmatch(
        r"[0-9a-f]{8}-[0-9a-f]{4}-5[0-9a-f]{3}-[89ab][0-9a-f]{3}-[0-9a-f]{12}",
        a)
    others = {deterministic_point_id("doc-1", 1),
              deterministic_point_id("doc-2", 0),
              deterministic_point_id("doc", 10),
              deterministic_point_id("doc1", 0)}
    assert a not in others and len(others) == 4
