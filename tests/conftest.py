"""Test harness setup.

Multi-chip testing without a real pod: force the JAX CPU backend with 8 virtual
devices (SURVEY.md §4 item 4) so sharding/collective tests exercise a real
8-device mesh. Must run before the first `import jax` anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The sandbox's sitecustomize registers the axon TPU backend and force-updates
# jax_platforms to "axon,cpu", overriding the env var — push it back to cpu
# before any backend is instantiated. Guarded: the schema/config/bus tests
# must still run where jax isn't installed.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

import pytest  # noqa: E402


@pytest.fixture
def tmp_data_dir(tmp_path):
    return tmp_path


# Native build selection shared by the broker/worker test modules.
# SYMBIONT_NATIVE_BUILD=build-tsan SYMBIONT_NATIVE_MAKE_TARGET=tsan runs them
# against ThreadSanitizer builds (see native/Makefile).
from pathlib import Path as _Path  # noqa: E402

_REPO = _Path(__file__).resolve().parent.parent
NATIVE_MAKE_TARGET = os.environ.get("SYMBIONT_NATIVE_MAKE_TARGET", "all")


def native_bin(name: str) -> str:
    build = os.environ.get("SYMBIONT_NATIVE_BUILD", "build")
    return str(_REPO / "native" / build / name)
