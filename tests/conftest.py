"""Test harness setup.

Multi-chip testing without a real pod: force the JAX CPU backend with 8 virtual
devices (SURVEY.md §4 item 4) so sharding/collective tests exercise a real
8-device mesh. Must run before the first `import jax` anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The sandbox's sitecustomize registers the axon TPU backend and force-updates
# jax_platforms to "axon,cpu", overriding the env var — push it back to cpu
# before any backend is instantiated. Guarded: the schema/config/bus tests
# must still run where jax isn't installed.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:
    pass

import pytest  # noqa: E402

# Two test tiers (VERDICT r3 item 7): `pytest -m "not slow"` is the fast
# tier (<2 min on CPU — logic, schema, stores, bus, numerics goldens);
# the slow tier adds compile-heavy JAX modules, multi-process/native
# integration, and e2e pipelines. Whole modules are marked here so the
# split can't silently rot as tests are added to existing files.
SLOW_MODULES = {
    "test_e2e_pipeline",     # full-stack async pipelines, many engines
    "test_multihost",        # spawns real OS processes for collectives
    "test_parallel",         # ring/Ulysses/GPipe: many XLA compiles
    "test_native_services",  # builds C++ tree, spawns broker + workers
    "test_engine",           # dozens of (bucket, batch) executables
    "test_lm_engine",        # decode-loop compiles per geometry
    "test_train",            # train-step compiles + checkpoint I/O
    "test_online_train",     # fine-tune passes on device
    "test_qdrant_backend",   # includes a full-stack pipeline run
    "test_ops_flash",        # pallas kernel compiles fwd+bwd
    "test_gpt_numerics",     # transformers goldens + decode compiles
    "test_engine_service",   # engine-plane request-reply over real engines
    "test_tcp_bus",          # broker build + socket timing waits
    "test_durable_streams",  # broker build + redelivery ack_wait sleeps
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        module = item.nodeid.split("::", 1)[0].rsplit("/", 1)[-1]
        if module.removesuffix(".py") in SLOW_MODULES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def tmp_data_dir(tmp_path):
    return tmp_path


# Native build selection shared by the broker/worker test modules.
# SYMBIONT_NATIVE_BUILD=build-tsan SYMBIONT_NATIVE_MAKE_TARGET=tsan runs them
# against ThreadSanitizer builds (see native/Makefile).
from pathlib import Path as _Path  # noqa: E402

_REPO = _Path(__file__).resolve().parent.parent
NATIVE_MAKE_TARGET = os.environ.get("SYMBIONT_NATIVE_MAKE_TARGET", "all")


def native_bin(name: str) -> str:
    build = os.environ.get("SYMBIONT_NATIVE_BUILD", "build")
    return str(_REPO / "native" / build / name)
