"""Test harness setup.

Multi-chip testing without a real pod: force the JAX CPU backend with 8 virtual
devices (SURVEY.md §4 item 4) so sharding/collective tests exercise a real
8-device mesh. Must run before the first `import jax` anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def tmp_data_dir(tmp_path):
    return tmp_path
