"""In-proc bus semantics: pub/sub, queue groups, wildcards, request-reply."""

import asyncio

import pytest

from symbiont_tpu.bus.core import subject_matches
from symbiont_tpu.bus.inproc import InprocBus


def test_subject_matching():
    assert subject_matches("a.b.c", "a.b.c")
    assert not subject_matches("a.b.c", "a.b")
    assert not subject_matches("a.b", "a.b.c")
    assert subject_matches("a.*.c", "a.x.c")
    assert not subject_matches("a.*.c", "a.x.y")
    assert subject_matches("a.>", "a.b.c")
    assert subject_matches("a.>", "a.b")
    assert not subject_matches("a.>", "a")
    assert not subject_matches("x.>", "a.b")


def _run(coro):
    return asyncio.run(coro)


def test_pub_sub_fanout():
    async def main():
        bus = InprocBus()
        s1 = await bus.subscribe("t.x")
        s2 = await bus.subscribe("t.x")
        await bus.publish("t.x", b"hello")
        m1 = await s1.next(1)
        m2 = await s2.next(1)
        assert m1.data == m2.data == b"hello"
        await bus.close()

    _run(main())


def test_queue_group_delivers_to_one_member():
    async def main():
        bus = InprocBus()
        a = await bus.subscribe("work", queue="g")
        b = await bus.subscribe("work", queue="g")
        plain = await bus.subscribe("work")
        for i in range(10):
            await bus.publish("work", str(i).encode())
        got_a = got_b = 0
        for _ in range(10):
            if await a.next(0.01):
                got_a += 1
        # drain b
        while await b.next(0.01):
            got_b += 1
        got_plain = 0
        while await plain.next(0.01):
            got_plain += 1
        assert got_a + got_b == 10  # shared exactly once
        assert got_a == 5 and got_b == 5  # round-robin
        assert got_plain == 10  # plain sub still sees everything
        await bus.close()

    _run(main())


def test_request_reply_and_timeout():
    async def main():
        bus = InprocBus()
        sub = await bus.subscribe("svc.echo")

        async def responder():
            msg = await sub.next(2)
            await bus.publish(msg.reply, b"pong:" + msg.data)

        task = asyncio.create_task(responder())
        reply = await bus.request("svc.echo", b"ping", timeout=2)
        assert reply.data == b"pong:ping"
        await task
        with pytest.raises(TimeoutError):
            await bus.request("svc.nobody", b"x", timeout=0.05)
        await bus.close()

    _run(main())


def test_headers_propagate():
    async def main():
        bus = InprocBus()
        sub = await bus.subscribe("h.test")
        await bus.publish("h.test", b"x", headers={"X-Trace-Id": "t-123"})
        msg = await sub.next(1)
        assert msg.headers["X-Trace-Id"] == "t-123"
        await bus.close()

    _run(main())


def test_slow_consumer_drops_not_blocks():
    async def main():
        bus = InprocBus()
        sub = await bus.subscribe("flood", maxsize=4)
        for i in range(10):
            await bus.publish("flood", str(i).encode())
        assert bus.stats["dropped"] == 6
        got = 0
        while await sub.next(0.01):
            got += 1
        assert got == 4
        await bus.close()

    _run(main())


def test_overflow_drop_increments_bus_dropped_metric():
    """Subscription._deliver drop-on-overflow must be ACCOUNTED, not
    silent (pre-resilience it vanished without a trace): every dropped
    message increments the subject-labeled `bus.dropped` counter."""
    from symbiont_tpu.utils.telemetry import metrics

    async def main():
        bus = InprocBus()
        sub = await bus.subscribe("flood.metric", maxsize=2)
        before = metrics.get("bus.dropped",
                             labels={"subject": "flood.metric"})
        for i in range(7):
            await bus.publish("flood.metric", str(i).encode())
        after = metrics.get("bus.dropped",
                            labels={"subject": "flood.metric"})
        assert after - before == 5  # 7 published, 2 queued, 5 dropped
        # the close-sentinel eviction path is NOT a consumer drop: closing
        # a full subscription must not inflate the metric
        sub.close()
        assert metrics.get("bus.dropped",
                           labels={"subject": "flood.metric"}) == after
        await bus.close()

    _run(main())


def test_publish_after_close_raises():
    async def main():
        bus = InprocBus()
        await bus.close()
        with pytest.raises(RuntimeError):
            await bus.publish("x", b"y")

    _run(main())


def test_durable_consumer_reattaches_across_broker_sigkill_and_restart(
        tmp_path):
    """Full broker DEATH, not just a TCP reset (which the chaos suite's
    mini-broker already covers): a real broker subprocess is SIGKILLed with
    captured-but-unacked work outstanding, restarted over the same
    --data-dir, and the SAME client object must auto-reconnect, re-attach
    its durable consumer, and receive the surviving work — the stream log
    (bus/pybroker.py, byte-format parity with native/symbus/streams.hpp)
    plus the TcpBus reconnect book together make broker death a pause, not
    a loss."""
    import os
    import signal
    import socket
    import subprocess
    import sys
    import time as _time

    from symbiont_tpu.bus.tcp import TcpBus

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    def spawn_broker():
        proc = subprocess.Popen(
            [sys.executable, "-m", "symbiont_tpu.bus.pybroker",
             "--host", "127.0.0.1", "--port", str(port),
             "--data-dir", str(tmp_path)],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        deadline = _time.time() + 30
        while _time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port),
                                              timeout=0.2):
                    return proc
            except OSError:
                _time.sleep(0.05)
        proc.kill()
        raise RuntimeError("pybroker did not start")

    async def main():
        proc = spawn_broker()
        bus = TcpBus("127.0.0.1", port, reconnect_base_s=0.05)
        await bus.connect()
        try:
            await bus.add_stream("p", ["evt.>"], ack_wait_s=0.3,
                                 max_deliver=10)
            sub = await bus.durable_subscribe("p", "g")
            await bus.publish("evt.1", b"before-acked")
            m = await sub.next(3)
            assert m is not None and m.data == b"before-acked"
            await bus.ack(m)
            await bus.publish("evt.2", b"unacked-survivor")
            m = await sub.next(3)
            assert m is not None and m.data == b"unacked-survivor"
            # deliberately NOT acked, then the broker process DIES
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            proc = spawn_broker()
            # same client object: reconnect loop re-SUBs, re-issues
            # add_stream, re-attaches the durable consumer — then the
            # replayed log redelivers the unacked message
            deadline = _time.time() + 30
            got = None
            while _time.time() < deadline:
                m = await sub.next(0.5)
                if m is not None and m.data == b"unacked-survivor":
                    got = m
                    break
            assert got is not None, "unacked work lost across broker death"
            assert int(got.headers["X-Symbus-Seq"]) == 2
            await bus.ack(got)
            # the pre-death ACK survived too: seq 1 never reappears
            extra = await sub.next(0.7)
            assert extra is None or extra.data != b"before-acked"
            # publishes keep working on the restarted broker
            await bus.publish("evt.3", b"after")
            m = await sub.next(3)
            assert m is not None and m.data == b"after"
            await bus.ack(m)
            assert bus.stats["reconnects"] >= 1
        finally:
            await bus.close()
            proc.terminate()
            proc.wait(timeout=10)

    _run(main())
