"""Native C++ worker services, driven end-to-end over the real broker.

Each test spawns the C++ broker plus one or more native worker binaries
(native/services/*.cpp) and talks to them from the Python TCP client —
proving the full cross-language contract: symbus wire protocol, generated
schema structs, queue groups, trace headers, and the engine.* request-reply
plane (SURVEY.md §2 native-components checklist).
"""

import asyncio
import json
import os
import shutil
import socket
import subprocess
import time
from pathlib import Path

import pytest

from symbiont_tpu import subjects
from symbiont_tpu.schema import (
    GeneratedTextMessage,
    GenerateTextTask,
    RawTextMessage,
    from_json,
    to_json_bytes,
)
from symbiont_tpu.utils.ids import current_timestamp_ms, generate_uuid

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


from tests.conftest import NATIVE_MAKE_TARGET, native_bin


def _spawn_broker():
    subprocess.run(["make", "-C", str(REPO / "native"), NATIVE_MAKE_TARGET],
                   check=True, capture_output=True)
    port = _free_port()
    proc = subprocess.Popen(
        [native_bin("symbus_broker"), "--port", str(port),
         "--host", "127.0.0.1"], stderr=subprocess.PIPE)
    for _ in range(100):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.2):
                break
        except OSError:
            time.sleep(0.05)
    else:
        proc.kill()
        raise RuntimeError("broker did not start")
    return proc, port


@pytest.fixture(scope="module")
def broker():
    proc, port = _spawn_broker()
    yield port
    proc.terminate()
    proc.wait(timeout=5)


@pytest.fixture()
def fresh_broker():
    """Function-scoped broker for DURABLE tests: once any worker creates the
    'pipeline' stream, the broker captures every later message on its
    subjects — a shared broker would replay unrelated tests' pipeline
    traffic into a durable test's consumer groups (observed: +18 points
    from an earlier test's docs)."""
    proc, port = _spawn_broker()
    yield port
    proc.terminate()
    proc.wait(timeout=5)


def spawn_worker(name: str, port: int, extra_env: dict | None = None):
    env = dict(os.environ,
               SYMBIONT_BUS_URL=f"symbus://127.0.0.1:{port}",
               **(extra_env or {}))
    proc = subprocess.Popen([native_bin(name)], env=env, stderr=subprocess.PIPE)
    return proc


def stop_worker(proc) -> str:
    proc.terminate()
    try:
        _, err = proc.communicate(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()
        _, err = proc.communicate()
    return (err or b"").decode(errors="replace")


def decode_emb_msg(msg):
    """Decode a data.text.with_embeddings bus message in EITHER wire form
    (the C++ workers publish binary tensor frames by default now) into a
    TextWithEmbeddingsMessage with the float lists materialized."""
    from symbiont_tpu.schema import frames

    m, rows = frames.decode_embeddings_message(msg.data, msg.headers)
    if rows is not None:
        for se, row in zip(m.embeddings_data, rows):
            se.embedding = row.tolist()
    return m


async def _tcp_bus(port):
    from symbiont_tpu.bus.tcp import TcpBus

    bus = TcpBus("127.0.0.1", port)
    await bus.connect()
    return bus


async def _wait_ready(proc, pattern: bytes = b"ready", timeout: float = 30.0):
    """Wait for the worker's structured ready log line on stderr."""
    os.set_blocking(proc.stderr.fileno(), False)
    buf = b""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        chunk = proc.stderr.read()
        if chunk:
            buf += chunk
            if pattern in buf:
                return buf
        await asyncio.sleep(0.05)
    raise TimeoutError(f"worker not ready; stderr so far: {buf!r}")


def test_text_generator_markov(broker):
    async def scenario():
        proc = spawn_worker("text_generator", broker)
        try:
            await _wait_ready(proc)
            bus = await _tcp_bus(broker)
            sub = await bus.subscribe(subjects.EVENTS_TEXT_GENERATED)

            # cold start: seed corpus only (reference main.rs:170 parity)
            task = GenerateTextTask(task_id=generate_uuid(), prompt=None,
                                    max_length=8)
            await bus.publish(subjects.TASKS_GENERATION_TEXT, to_json_bytes(task))
            msg = await sub.next(10.0)
            assert msg is not None, "no generated event"
            out = from_json(GeneratedTextMessage, msg.data)
            assert out.original_task_id == task.task_id
            assert out.generated_text != "Model not trained."
            seed_words = set("Это первое предложение для обучения нашей "
                             "марковской модели оно простое".split())
            assert set(out.generated_text.split()) <= seed_words
            assert len(out.generated_text.split()) <= 8
            # trace header propagated outward
            assert "X-Trace-Id" in msg.headers

            # continuous learning: feed a doc, then generate from its words
            raw = RawTextMessage(
                id=generate_uuid(), source_url="http://t",
                raw_text="alpha beta gamma delta epsilon zeta",
                timestamp_ms=current_timestamp_ms())
            await bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED,
                              to_json_bytes(raw))
            await asyncio.sleep(0.3)
            seen_new = False
            for _ in range(30):
                task = GenerateTextTask(task_id=generate_uuid(), prompt=None,
                                        max_length=6)
                await bus.publish(subjects.TASKS_GENERATION_TEXT,
                                  to_json_bytes(task))
                msg = await sub.next(10.0)
                out = from_json(GeneratedTextMessage, msg.data)
                if out.generated_text.split()[0] == "alpha":
                    seen_new = True
                    break
            assert seen_new, "markov chain never used the ingested document"
            await bus.close()
        finally:
            stop_worker(proc)

    asyncio.run(scenario())


def test_native_pipeline_preprocessing_vector_memory(broker):
    """The reference's main pipeline (SURVEY.md §3.1/§3.2) with BOTH worker
    shells native: raw text → C++ preprocessing (clean/split in C++, embed via
    engine.embed.batch) → C++ vector_memory (upsert via engine.vector.upsert)
    → semantic search through the C++ shell — Python only owns the device."""

    async def scenario():
        from symbiont_tpu.config import EngineConfig, VectorStoreConfig
        from symbiont_tpu.engine.engine import TpuEngine
        from symbiont_tpu.memory.vector_store import VectorStore
        from symbiont_tpu.schema import (
            QueryEmbeddingResult,
            QueryForEmbeddingTask,
            SemanticSearchNatsResult,
            SemanticSearchNatsTask,
            TextWithEmbeddingsMessage,
            TokenizedTextMessage,
        )
        from symbiont_tpu.services.engine_service import EngineService

        import tempfile

        eng = TpuEngine(EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                                     batch_buckets=[2, 4], dtype="float32"))
        with tempfile.TemporaryDirectory() as td:
            store = VectorStore(VectorStoreConfig(dim=32, data_dir=td))
            engine_bus = await _tcp_bus(broker)
            svc = EngineService(engine_bus, engine=eng, vector_store=store)
            await svc.start()
            pre = spawn_worker("preprocessing", broker)
            vm = spawn_worker("vector_memory", broker)
            try:
                await _wait_ready(pre)
                await _wait_ready(vm)
                bus = await _tcp_bus(broker)
                sub_emb = await bus.subscribe(subjects.DATA_TEXT_WITH_EMBEDDINGS)
                sub_tok = await bus.subscribe(subjects.DATA_PROCESSED_TEXT_TOKENIZED)

                raw = RawTextMessage(
                    id=generate_uuid(), source_url="http://doc",
                    raw_text="  The MXU  does matmuls. HBM is the bottleneck! ok ",
                    timestamp_ms=current_timestamp_ms())
                await bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED,
                                  to_json_bytes(raw))

                emb_msg = await sub_emb.next(60.0)
                assert emb_msg is not None, "no with_embeddings published"
                emb = decode_emb_msg(emb_msg)
                assert [se.sentence_text for se in emb.embeddings_data] == [
                    "The MXU does matmuls.", "HBM is the bottleneck!", "ok"]
                assert all(len(se.embedding) == 32 for se in emb.embeddings_data)
                assert emb.original_id == raw.id

                tok_msg = await sub_tok.next(10.0)
                tok = from_json(TokenizedTextMessage, tok_msg.data)
                assert tok.tokens[0] == "The" and tok.sentences == [
                    s.sentence_text for s in emb.embeddings_data]

                # vector_memory consumed the same publish → wait for upsert
                for _ in range(100):
                    if store.count() >= 3:
                        break
                    await asyncio.sleep(0.1)
                assert store.count() == 3
                # C++ minted the same deterministic point ids as Python would
                # (idempotent-redelivery contract, utils.ids parity)
                from symbiont_tpu.utils.ids import deterministic_point_id
                expected_ids = {deterministic_point_id(raw.id, i)
                                for i in range(3)}
                assert set(store._id_to_row) == expected_ids

                # redelivery idempotence: same doc again → same ids, no dupes
                await bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED,
                                  to_json_bytes(raw))
                assert await sub_emb.next(60.0) is not None
                await asyncio.sleep(1.0)  # let the second upsert land
                assert store.count() == 3

                # query-embedding request-reply through the C++ shell
                qtask = QueryForEmbeddingTask(request_id=generate_uuid(),
                                              text_to_embed="HBM is the bottleneck!")
                qmsg = await bus.request(subjects.TASKS_EMBEDDING_FOR_QUERY,
                                         to_json_bytes(qtask), 60.0)
                qres = from_json(QueryEmbeddingResult, qmsg.data)
                assert qres.error_message is None
                assert qres.request_id == qtask.request_id
                assert len(qres.embedding) == 32

                # semantic search request-reply through the C++ shell
                stask = SemanticSearchNatsTask(request_id=generate_uuid(),
                                               query_embedding=qres.embedding,
                                               top_k=2)
                smsg = await bus.request(subjects.TASKS_SEARCH_SEMANTIC_REQUEST,
                                         to_json_bytes(stask), 60.0)
                sres = from_json(SemanticSearchNatsResult, smsg.data)
                assert sres.error_message is None
                assert len(sres.results) == 2
                top = sres.results[0]
                assert top.payload.sentence_text == "HBM is the bottleneck!"
                # query vector crossed two f32-JSON hops (C++ shells), so the
                # self-match cosine is 1.0 only to ~1e-2
                assert top.score == pytest.approx(1.0, abs=2e-2)
                assert top.payload.original_document_id == raw.id
                assert top.payload.sentence_order == 1

                # typed error reply on an undecodable search task
                bad = await bus.request(subjects.TASKS_SEARCH_SEMANTIC_REQUEST,
                                        b'{"nope": 1}', 30.0)
                bres = from_json(SemanticSearchNatsResult, bad.data)
                assert bres.error_message is not None
                assert bres.request_id == "unknown"
                await bus.close()
            finally:
                err_pre = stop_worker(pre)
                err_vm = stop_worker(vm)
                await svc.stop()
                await engine_bus.close()
                assert "upserted 3 points" in err_vm, err_vm
                assert "WARN" not in err_pre.split("ready")[1] if "ready" in err_pre else True

    asyncio.run(scenario())


FIXTURE_HTML = """<!doctype html>
<html><head><title>t</title><style>.c{display:none}</style>
<script>var drop = 1;</script></head>
<body><nav><span>menu junk</span></nav>
<article>
  <h1>TPU &amp; XLA</h1>
  <p>The MXU does large matmuls.   It likes bf16!</p>
  <ul><li>first point</li><li>second &#8212; point</li></ul>
  <p>Closing <b>thought</b>.</p>
</article>
<footer><span>footer junk</span></footer></body></html>"""


def test_native_perception_scrape(broker):
    """C++ perception fetches a local HTTP page, runs the native selector
    cascade, and publishes RawTextMessage — and its extraction matches the
    Python twin byte-for-byte (two implementations, one spec)."""
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/redirect":
                self.send_response(302)
                self.send_header("Location", "/page.html")
                self.end_headers()
                return
            body = FIXTURE_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    http_port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    async def scenario():
        proc = spawn_worker("perception", broker)
        try:
            await _wait_ready(proc)
            bus = await _tcp_bus(broker)
            sub = await bus.subscribe(subjects.DATA_RAW_TEXT_DISCOVERED)

            from symbiont_tpu.schema import PerceiveUrlTask
            from symbiont_tpu.services.html_extract import extract_main_text

            # plain fetch, then via a redirect
            for path in ("/page.html", "/redirect"):
                task = PerceiveUrlTask(
                    url=f"http://127.0.0.1:{http_port}{path}")
                await bus.publish(subjects.TASKS_PERCEIVE_URL,
                                  to_json_bytes(task))
                msg = await sub.next(15.0)
                assert msg is not None, f"no raw text for {path}"
                raw = from_json(RawTextMessage, msg.data)
                assert raw.source_url == task.url
                assert raw.raw_text == extract_main_text(FIXTURE_HTML)
                assert "TPU & XLA" in raw.raw_text
                assert "junk" not in raw.raw_text and "drop" not in raw.raw_text

            await bus.close()
        finally:
            stop_worker(proc)
            httpd.shutdown()

    asyncio.run(scenario())


def test_native_perception_chunked_framing(broker):
    """Chunked transfer decoding: a well-formed chunked body decodes and
    publishes; a malformed chunk-size line must be treated as truncation —
    NOT as the 0-terminator — so a corrupted body is never passed off as a
    complete page (ADVICE r4: strtol returns 0 for garbage)."""
    import threading

    html = FIXTURE_HTML.encode()
    mid = len(html) // 2
    head = ("HTTP/1.1 200 OK\r\nContent-Type: text/html\r\n"
            "Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n").encode()

    def chunk(b: bytes) -> bytes:
        return f"{len(b):x}\r\n".encode() + b + b"\r\n"

    responses = {
        # two chunks + proper terminator → decodes to the full fixture
        "/ok": head + chunk(html[:mid]) + chunk(html[mid:]) + b"0\r\n\r\n",
        # extractable first chunk, then a garbage size line and FIN: the old
        # decoder read strtol("zz")==0 as the terminator and published the
        # truncated page; it must throw instead
        "/bad": head + chunk(html[:mid]) + b"zz\r\n",
    }

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    raw_port = srv.getsockname()[1]

    def serve():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            with conn:
                req = b""
                while b"\r\n\r\n" not in req:
                    d = conn.recv(4096)
                    if not d:
                        break
                    req += d
                parts = req.split(b" ")
                path = parts[1].decode() if len(parts) > 1 else "/"
                conn.sendall(responses.get(
                    path, b"HTTP/1.1 404 nf\r\nContent-Length: 0\r\n\r\n"))

    threading.Thread(target=serve, daemon=True).start()

    async def scenario():
        proc = spawn_worker("perception", broker)
        try:
            await _wait_ready(proc)
            bus = await _tcp_bus(broker)
            sub = await bus.subscribe(subjects.DATA_RAW_TEXT_DISCOVERED)

            from symbiont_tpu.schema import PerceiveUrlTask
            from symbiont_tpu.services.html_extract import extract_main_text

            bad_url = f"http://127.0.0.1:{raw_port}/bad"
            ok_url = f"http://127.0.0.1:{raw_port}/ok"
            # bad first, then ok: the worker handles tasks in order, so the
            # FIRST published message proves whether /bad leaked a partial
            for url in (bad_url, ok_url):
                await bus.publish(subjects.TASKS_PERCEIVE_URL, to_json_bytes(
                    PerceiveUrlTask(url=url)))
            msg = await sub.next(20.0)
            assert msg is not None, "no raw text published"
            raw = from_json(RawTextMessage, msg.data)
            assert raw.source_url == ok_url, \
                "malformed chunked body was published as complete"
            assert raw.raw_text == extract_main_text(FIXTURE_HTML)
            await bus.close()
        finally:
            stop_worker(proc)
            srv.close()

    asyncio.run(scenario())


def _make_tls_server(handler_cls, tmp_path):
    """TLS listener on 127.0.0.1 with an ephemeral self-signed cert (IP SAN),
    plus the PEM path a client must trust. Offline: cert minted locally."""
    import datetime
    import http.server
    import ipaddress
    import ssl

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "symbiont-test")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]),
                critical=False)
            .sign(key, hashes.SHA256()))
    cert_pem = tmp_path / "cert.pem"
    key_pem = tmp_path / "key.pem"
    cert_pem.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_pem.write_bytes(key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption()))

    httpd = http.server.HTTPServer(("127.0.0.1", 0), handler_cls)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(str(cert_pem), str(key_pem))
    httpd.socket = ctx.wrap_socket(httpd.socket, server_side=True)
    return httpd, str(cert_pem)


def test_native_perception_https_tls(broker, tmp_path):
    """The native worker scrapes an https page end-to-end: TLS via
    dlopen(libssl) with SNI + certificate verification against
    SYMBIONT_TLS_CA_FILE (reference scrapes https through reqwest's TLS,
    perception_service/src/main.rs:89-94). An untrusted listener (no CA
    configured) must FAIL verification and publish nothing."""
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = FIXTURE_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    httpd, ca_file = _make_tls_server(Handler, tmp_path)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    async def scenario():
        from symbiont_tpu.schema import PerceiveUrlTask
        from symbiont_tpu.services.html_extract import extract_main_text

        url = f"https://127.0.0.1:{port}/page.html"

        # trusted: full https scrape lands on the bus
        proc = spawn_worker("perception", broker,
                            {"SYMBIONT_TLS_CA_FILE": ca_file})
        try:
            await _wait_ready(proc)
            bus = await _tcp_bus(broker)
            sub = await bus.subscribe(subjects.DATA_RAW_TEXT_DISCOVERED)
            await bus.publish(subjects.TASKS_PERCEIVE_URL,
                              to_json_bytes(PerceiveUrlTask(url=url)))
            msg = await sub.next(15.0)
            assert msg is not None, "no raw text from the https scrape"
            raw = from_json(RawTextMessage, msg.data)
            assert raw.source_url == url
            assert raw.raw_text == extract_main_text(FIXTURE_HTML)
        finally:
            # stop BEFORE spawning the untrusted worker: both share the
            # q.perception queue group, and the broker's round-robin could
            # otherwise hand the negative-path task to this trusted one
            stop_worker(proc)

        try:
            # untrusted CA: verification must fail, nothing published
            proc2 = spawn_worker("perception", broker)
            await _wait_ready(proc2)
            await bus.publish(subjects.TASKS_PERCEIVE_URL,
                              to_json_bytes(PerceiveUrlTask(url=url)))
            assert await sub.next(2.0) is None
            err2 = stop_worker(proc2)
            assert "scrape failed" in err2 and "TLS" in err2, err2
            await bus.close()
        finally:
            httpd.shutdown()

    asyncio.run(scenario())


def test_native_api_gateway_full_stack(broker):
    """The complete reference surface (SURVEY.md §1-L4) served by the C++
    gateway, with C++ preprocessing/vector_memory/text_generator behind it and
    the Python process reduced to the engine plane: HTTP validation parity,
    2-hop search with status mapping, SSE push, CORS, metrics."""
    import http.client as http_client
    import tempfile

    async def scenario():
        from symbiont_tpu.config import EngineConfig, VectorStoreConfig
        from symbiont_tpu.engine.engine import TpuEngine
        from symbiont_tpu.memory.vector_store import VectorStore
        from symbiont_tpu.services.engine_service import EngineService

        eng = TpuEngine(EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                                     batch_buckets=[2, 4], dtype="float32",
                                     rerank_enabled=True))
        api_port = _free_port()
        with tempfile.TemporaryDirectory() as td:
            store = VectorStore(VectorStoreConfig(dim=32, data_dir=td))
            engine_bus = await _tcp_bus(broker)
            svc = EngineService(engine_bus, engine=eng, vector_store=store)
            await svc.start()
            workers = [spawn_worker("preprocessing", broker),
                       spawn_worker("vector_memory", broker),
                       spawn_worker("text_generator", broker),
                       spawn_worker("api_gateway", broker,
                                    {"SYMBIONT_API_PORT": str(api_port),
                                     "SYMBIONT_FRONTEND_PATH":
                                         str(REPO / "frontend" / "index.html")})]
            try:
                for w in workers:
                    await _wait_ready(w)

                def http(method, path, payload=None, headers=None):
                    conn = http_client.HTTPConnection("127.0.0.1", api_port,
                                                      timeout=60)
                    body = json.dumps(payload) if payload is not None else None
                    conn.request(method, path, body=body, headers=headers or {})
                    r = conn.getresponse()
                    data = r.read().decode()
                    hdrs = dict(r.getheaders())
                    conn.close()
                    return r.status, (json.loads(data) if data else None), hdrs

                loop = asyncio.get_running_loop()
                hx = lambda *a, **kw: loop.run_in_executor(None, lambda: http(*a, **kw))

                # healthz + validation parity
                status, body, _ = await hx("GET", "/healthz")
                assert (status, body) == (200, {"status": "ok"})
                # engine-plane health through the C++ gateway
                status, body, _ = await hx("GET", "/api/health/engine")
                assert status == 200 and body["ok"] is True
                assert body["backends"]["embed"] is True

                # bundled UI at GET /
                c = http_client.HTTPConnection("127.0.0.1", api_port, timeout=30)
                c.request("GET", "/")
                r = c.getresponse()
                page = r.read().decode()
                assert r.status == 200
                assert r.getheader("Content-Type").startswith("text/html")
                assert "symbiont-tpu" in page and "/api/search/semantic" in page
                c.close()
                status, body, _ = await hx("POST", "/api/submit-url", {"url": "  "})
                assert status == 400 and body["message"] == "URL cannot be empty"
                status, body, _ = await hx("POST", "/api/generate-text",
                                           {"task_id": " ", "prompt": None,
                                            "max_length": 5})
                assert status == 400 and "task_id" in body["message"]
                status, body, _ = await hx("POST", "/api/generate-text",
                                           {"task_id": "t", "prompt": None,
                                            "max_length": 5000})
                assert status == 400 and "between 1 and 1000" in body["message"]
                status, body, _ = await hx("GET", "/nope")
                assert status == 404

                # Python-twin parity: oversized / unparseable Content-Length
                # answered with 413 / 400, not a silently dropped socket
                r2, w2 = await asyncio.open_connection("127.0.0.1", api_port)
                w2.write(b"POST /api/submit-url HTTP/1.1\r\nHost: x\r\n"
                         b"Content-Length: 999999999999\r\n\r\n")
                await w2.drain()
                got = await asyncio.wait_for(r2.read(4096), 10)
                assert got.startswith(b"HTTP/1.1 413 ")
                w2.close()
                r2, w2 = await asyncio.open_connection("127.0.0.1", api_port)
                w2.write(b"POST /api/submit-url HTTP/1.1\r\nHost: x\r\n"
                         b"Content-Length: banana\r\n\r\n")
                await w2.drain()
                got = await asyncio.wait_for(r2.read(4096), 10)
                assert got.startswith(b"HTTP/1.1 400 ")
                w2.close()

                # CORS: exact-host origins only
                _, _, hdrs = await hx("GET", "/healthz",
                                      headers={"Origin": "http://localhost:3000"})
                assert hdrs.get("Access-Control-Allow-Origin") == "http://localhost:3000"
                _, _, hdrs = await hx("GET", "/healthz",
                                      headers={"Origin": "http://localhost.evil.com"})
                assert "Access-Control-Allow-Origin" not in hdrs

                # SSE client (raw socket to keep it simple)
                sse_reader, sse_writer = await asyncio.open_connection(
                    "127.0.0.1", api_port)
                sse_writer.write(b"GET /api/events HTTP/1.1\r\n"
                                 b"Host: x\r\nAccept: text/event-stream\r\n\r\n")
                await sse_writer.drain()
                head = await asyncio.wait_for(
                    sse_reader.readuntil(b"\r\n\r\n"), 10)
                assert b"text/event-stream" in head
                await asyncio.sleep(0.3)  # let the hub register us

                # ingest directly (perception is covered separately)
                raw = RawTextMessage(
                    id=generate_uuid(), source_url="http://doc",
                    raw_text="Exact cosine topk runs on the MXU. "
                             "Collectives ride the ICI!",
                    timestamp_ms=current_timestamp_ms())
                bus = await _tcp_bus(broker)
                await bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED,
                                  to_json_bytes(raw))
                for _ in range(600):
                    if store.count() >= 2:
                        break
                    await asyncio.sleep(0.1)
                assert store.count() == 2

                # 2-hop search through C++ gateway + C++ shells + TPU engine
                status, body, _ = await hx("POST", "/api/search/semantic",
                                           {"query_text": "Collectives ride the ICI!",
                                            "top_k": 1})
                assert status == 200, body
                assert body["error_message"] is None
                assert body["results"][0]["payload"]["sentence_text"] == \
                    "Collectives ride the ICI!"
                assert set(body["results"][0]["payload"]) == {
                    "original_document_id", "source_url", "sentence_text",
                    "sentence_order", "model_name", "processed_at_ms"}

                # 3-hop search + cross-encoder rerank through the C++ gateway
                status, body, _ = await hx("POST", "/api/search/semantic",
                                           {"query_text": "cosine topk",
                                            "top_k": 2, "rerank": True})
                assert status == 200, body
                assert body["error_message"] is None
                rr_scores = [r["score"] for r in body["results"]]
                assert len(rr_scores) == 2
                assert rr_scores == sorted(rr_scores, reverse=True)

                # generation → SSE push
                status, body, _ = await hx("POST", "/api/generate-text",
                                           {"task_id": "sse-1", "prompt": None,
                                            "max_length": 6})
                assert status == 200 and body["task_id"] == "sse-1"
                async def next_data_frame():
                    # skip keep-alive comment frames (": keep-alive")
                    while True:
                        frame = await sse_reader.readuntil(b"\n\n")
                        lines = [ln[6:] for ln in frame.decode().splitlines()
                                 if ln.startswith("data: ")]
                        if lines:
                            return lines
                data_lines = await asyncio.wait_for(next_data_frame(), 20)
                event = json.loads("\n".join(data_lines))
                assert event["original_task_id"] == "sse-1"
                assert event["generated_text"]
                sse_writer.close()

                # metrics counted the calls
                status, body, _ = await hx("GET", "/api/metrics")
                assert status == 200
                assert body["counters"]["api.POST./api/search/semantic"] == 2
                assert body["counters"]["api.sse_broadcast"] >= 1
                await bus.close()
            finally:
                for w in workers:
                    stop_worker(w)
                await svc.stop()
                await engine_bus.close()

    asyncio.run(scenario())


def test_native_sse_task_id_filter(broker):
    """?task_id= routing through the C++ gateway: a filtered SSE client gets
    only its task's events; an unfiltered one keeps the reference's
    broadcast-to-all behavior (main.rs:215-270)."""
    import http.client as http_client

    async def scenario():
        api_port = _free_port()
        workers = [spawn_worker("text_generator", broker),
                   spawn_worker("api_gateway", broker,
                                {"SYMBIONT_API_PORT": str(api_port)})]
        try:
            for w in workers:
                await _wait_ready(w)

            async def sse_client(query: str):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", api_port)
                writer.write(f"GET /api/events{query} HTTP/1.1\r\n"
                             f"Host: x\r\nAccept: text/event-stream\r\n"
                             f"\r\n".encode())
                await writer.drain()
                head = await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"), 10)
                assert b"text/event-stream" in head
                return reader, writer

            plain = await sse_client("")
            only_b = await sse_client("?task_id=native-B")
            await asyncio.sleep(0.3)

            def gen(tid):
                conn = http_client.HTTPConnection("127.0.0.1", api_port,
                                                  timeout=30)
                conn.request("POST", "/api/generate-text",
                             body=json.dumps({"task_id": tid, "prompt": None,
                                              "max_length": 4}))
                r = conn.getresponse()
                assert r.status == 200, r.read()
                r.read()
                conn.close()

            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, gen, "native-A")
            await loop.run_in_executor(None, gen, "native-B")

            async def read_events(reader, n, timeout=15.0):
                got = []

                async def pull():
                    while len(got) < n:
                        frame = await reader.readuntil(b"\n\n")
                        lines = [ln[6:] for ln in frame.decode().splitlines()
                                 if ln.startswith("data: ")]
                        if lines:
                            got.append(json.loads("\n".join(lines)))
                try:
                    await asyncio.wait_for(pull(), timeout)
                except asyncio.TimeoutError:
                    pass
                return got

            plain_events = await read_events(plain[0], 2)
            # filtered client expects exactly 1; brief over-wait catches leaks
            b_events = await read_events(only_b[0], 2, timeout=2.0)

            assert [e["original_task_id"] for e in plain_events] == \
                ["native-A", "native-B"]
            assert [e["original_task_id"] for e in b_events] == ["native-B"]
            for r, w in (plain, only_b):
                w.close()
        finally:
            for w in workers:
                stop_worker(w)

    asyncio.run(scenario())


def test_native_gateway_survives_garbage_http(broker):
    """Robustness fuzz for the hand-written C++ HTTP parser: random garbage,
    truncated requests, huge start lines, null bytes, and pipelined noise
    must never crash the gateway — it answers (or closes) per connection and
    keeps serving real requests afterwards."""
    import http.client as http_client
    import random

    async def scenario():
        api_port = _free_port()
        gw = spawn_worker("api_gateway", broker,
                          {"SYMBIONT_API_PORT": str(api_port)})
        try:
            await _wait_ready(gw)
            rng = random.Random(1234)
            payloads = [
                b"\x00\x01\x02\xff\xfe garbage\r\n\r\n",
                b"GET\r\n\r\n",                       # no path/version
                b"GET " + b"A" * 70000 + b" HTTP/1.1\r\n\r\n",  # huge path
                b"POST /api/submit-url HTTP/1.1\r\nContent-Length: 10\r\n\r\nshort",
                b"\r\n\r\n\r\n",
                bytes(rng.getrandbits(8) for _ in range(4096)),
                b"GET /api/events HTTP/1.1\r\nHost\r\nBad Header Line\r\n\r\n",
                b"POST /api/generate-text HTTP/1.1\r\nContent-Length: 5\r\n\r\n{]!!}",
                # pipelined: a valid request with trailing leftover bytes the
                # parser must not mis-frame into the next read
                b"GET /healthz HTTP/1.1\r\nHost: x\r\n\r\n"
                b"BOGUS LEFTOVER \xff\x00\r\n\r\n",
            ]
            for p in payloads:
                w = None
                try:
                    r, w = await asyncio.open_connection("127.0.0.1", api_port)
                    w.write(p)
                    # every await bounded: a parser that stops reading
                    # without closing must not hang the suite — the
                    # process-alive + healthz asserts below still gate
                    await asyncio.wait_for(w.drain(), 5)
                    try:
                        await asyncio.wait_for(r.read(4096), 3)
                    except asyncio.TimeoutError:
                        pass  # parser may legitimately wait for more bytes
                except (asyncio.TimeoutError, OSError):
                    pass  # dropped connection is acceptable; crashing is not
                finally:
                    if w is not None:
                        w.close()
            assert gw.poll() is None, "gateway process died on garbage input"
            # still serving real traffic afterwards
            conn = http_client.HTTPConnection("127.0.0.1", api_port, timeout=15)
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            assert resp.status == 200
            resp.read()
            conn.close()
        finally:
            stop_worker(gw)

    asyncio.run(scenario())


def test_native_knowledge_graph(broker):
    """C++ knowledge_graph shell: tokenized stream → engine.graph.save →
    sqlite MERGE-parity store (the un-orphaned path, SURVEY.md fact #3),
    including idempotent re-save (MERGE, not duplicate) and log-and-continue
    on a bad payload."""
    import tempfile

    async def scenario():
        from symbiont_tpu.config import GraphStoreConfig
        from symbiont_tpu.graph.store import GraphStore
        from symbiont_tpu.schema import TokenizedTextMessage
        from symbiont_tpu.services.engine_service import EngineService

        with tempfile.TemporaryDirectory() as td:
            store = GraphStore(GraphStoreConfig(data_dir=td))
            engine_bus = await _tcp_bus(broker)
            svc = EngineService(engine_bus, graph_store=store)
            await svc.start()
            proc = spawn_worker("knowledge_graph", broker)
            try:
                await _wait_ready(proc)
                bus = await _tcp_bus(broker)
                msg = TokenizedTextMessage(
                    original_id="doc-1", source_url="http://kg",
                    tokens=["The", "MXU", "the", "", "ICI"],
                    sentences=["The MXU.", "  ", "The ICI."],
                    timestamp_ms=current_timestamp_ms())
                await bus.publish(subjects.DATA_PROCESSED_TEXT_TOKENIZED,
                                  to_json_bytes(msg))
                for _ in range(100):
                    if store.counts()["Document"] >= 1:
                        break
                    await asyncio.sleep(0.1)
                # tokens dedupe case-insensitively; empties skipped
                # (reference: main.rs:71-77,103-109)
                assert store.counts() == {"Document": 1, "Sentence": 2,
                                          "Token": 3, "edges": 5}
                assert store.document_sentences("doc-1") == [
                    "The MXU.", "The ICI."]
                assert store.documents_containing_token("mxu") == ["doc-1"]

                # MERGE: same doc again does not duplicate
                await bus.publish(subjects.DATA_PROCESSED_TEXT_TOKENIZED,
                                  to_json_bytes(msg))
                # bad payload: logged, loop survives
                await bus.publish(subjects.DATA_PROCESSED_TEXT_TOKENIZED,
                                  b'{"nope": 1}')
                await asyncio.sleep(0.5)
                assert store.counts() == {"Document": 1, "Sentence": 2,
                                          "Token": 3, "edges": 5}
                await bus.close()
            finally:
                err = stop_worker(proc)
                await svc.stop()
                await engine_bus.close()
            assert "saved doc doc-1" in err, err
            assert "bad tokenized message" in err, err

    asyncio.run(scenario())


def test_native_knowledge_graph_durable_ack(fresh_broker):
    broker = fresh_broker
    """Durable mode: the KG worker filter-subscribes to only its subject and
    acks after commit — a successful save must NOT redeliver, and foreign
    pipeline subjects must never reach its parse loop."""
    import tempfile

    async def scenario():
        from symbiont_tpu.config import GraphStoreConfig
        from symbiont_tpu.graph.store import GraphStore
        from symbiont_tpu.schema import TokenizedTextMessage
        from symbiont_tpu.services.engine_service import EngineService

        with tempfile.TemporaryDirectory() as td:
            store = GraphStore(GraphStoreConfig(data_dir=td))
            engine_bus = await _tcp_bus(broker)
            svc = EngineService(engine_bus, graph_store=store)
            await svc.start()
            proc = spawn_worker(
                "knowledge_graph", broker,
                {"SYMBIONT_BUS_DURABLE": "1",
                 "SYMBIONT_BUS_DURABLE_ACK_WAIT_MS": "600"})
            try:
                await _wait_ready(proc, b"ready (durable)")
                bus = await _tcp_bus(broker)
                # a foreign pipeline subject must be filtered out by the broker
                await bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED,
                                  b'{"id": "x", "source_url": "u", '
                                  b'"raw_text": "t", "timestamp_ms": 1}')
                msg = TokenizedTextMessage(
                    original_id="dur-1", source_url="http://kg",
                    tokens=["ack"], sentences=["Ack after commit."],
                    timestamp_ms=current_timestamp_ms())
                await bus.publish(subjects.DATA_PROCESSED_TEXT_TOKENIZED,
                                  to_json_bytes(msg))
                for _ in range(100):
                    if store.counts()["Document"] >= 1:
                        break
                    await asyncio.sleep(0.1)
                assert store.counts()["Document"] == 1
                # wait past several ack_wait windows: an un-acked save would
                # redeliver and log "saved doc" again
                await asyncio.sleep(2.0)
                await bus.close()
            finally:
                err = stop_worker(proc)
                await svc.stop()
                await engine_bus.close()
            assert err.count("saved doc dur-1") == 1, err
            assert "bad tokenized message" not in err, err

    asyncio.run(scenario())


def test_text_generator_lm_backend(broker):
    """LM mode: the C++ worker forwards prompts to engine.generate — served
    here by the Python EngineService over the same broker (the real
    native-shell ↔ TPU-engine topology)."""

    async def scenario():
        from symbiont_tpu.services.engine_service import EngineService

        class FakeLm:
            class config:
                model_dir = None
                arch = "test"

            def generate(self, prompt, max_new_tokens, temperature=None,
                         top_k=None):
                if temperature is not None:
                    return f"lm says: {prompt}! t={temperature} k={top_k}"
                return f"lm says: {prompt}!"

        engine_bus = await _tcp_bus(broker)
        svc = EngineService(engine_bus, lm=FakeLm())
        await svc.start()
        proc = spawn_worker("text_generator", broker,
                            {"SYMBIONT_TEXTGEN_BACKEND": "lm"})
        try:
            await _wait_ready(proc, b"backend=lm")
            bus = await _tcp_bus(broker)
            sub = await bus.subscribe(subjects.EVENTS_TEXT_GENERATED)
            task = GenerateTextTask(task_id=generate_uuid(),
                                    prompt="hello tpu", max_length=32)
            await bus.publish(subjects.TASKS_GENERATION_TEXT, to_json_bytes(task))
            msg = await sub.next(15.0)
            assert msg is not None, "no generated event"
            out = from_json(GeneratedTextMessage, msg.data)
            assert out.generated_text == "lm says: hello tpu!"
            assert out.original_task_id == task.task_id

            # per-request sampling params ride the C++ worker → engine hop
            task = GenerateTextTask(task_id=generate_uuid(), prompt="again",
                                    max_length=32, temperature=1.5, top_k=7)
            await bus.publish(subjects.TASKS_GENERATION_TEXT, to_json_bytes(task))
            msg = await sub.next(15.0)
            assert msg is not None, "no generated event (sampled)"
            out = from_json(GeneratedTextMessage, msg.data)
            assert out.generated_text == "lm says: again! t=1.5 k=7"
            await bus.close()
        finally:
            stop_worker(proc)
            await svc.stop()
            await engine_bus.close()

    asyncio.run(scenario())


def test_native_preprocessing_coalesces_docs(broker):
    """The pipelined feed (VERDICT r4 next-1): one replica coalesces multiple
    pending documents' sentences into fewer engine.embed.batch hops, and —
    the critical invariant — every doc still gets exactly ITS vectors in
    sentence order (offset bookkeeping across the coalesced reply). Each
    published embedding must match embedding that sentence directly."""
    import tempfile

    import numpy as np

    async def scenario():
        from symbiont_tpu.config import EngineConfig, VectorStoreConfig
        from symbiont_tpu.engine.engine import TpuEngine
        from symbiont_tpu.memory.vector_store import VectorStore
        from symbiont_tpu.schema import RawTextMessage, TextWithEmbeddingsMessage
        from symbiont_tpu.services.engine_service import EngineService
        from symbiont_tpu.utils.telemetry import metrics

        eng = TpuEngine(EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                                     batch_buckets=[2, 4, 32], max_batch=64,
                                     dtype="float32", data_parallel=False))
        with tempfile.TemporaryDirectory() as td:
            store = VectorStore(VectorStoreConfig(dim=32, data_dir=td))
            engine_bus = await _tcp_bus(broker)
            svc = EngineService(engine_bus, engine=eng, vector_store=store)
            await svc.start()
            # max_inflight=1 forces docs 2..n to queue behind doc 1's hop and
            # ride ONE coalesced request when it completes
            pre = spawn_worker("preprocessing", broker,
                               {"SYMBIONT_PREPROC_MAX_INFLIGHT": "1"})
            try:
                await _wait_ready(pre)
                bus = await _tcp_bus(broker)
                sub_emb = await bus.subscribe(subjects.DATA_TEXT_WITH_EMBEDDINGS)
                calls_before = metrics.snapshot()["counters"].get(
                    "engine.embed.batch", 0)

                docs = []
                for i in range(6):
                    # distinct sentence counts stress the offset arithmetic
                    n_sents = 2 + (i % 3)
                    text = ". ".join(f"Doc {i} sentence {j} about tensors"
                                     for j in range(n_sents)) + "."
                    docs.append(RawTextMessage(
                        id=f"co-doc-{i}", source_url=f"http://co/{i}",
                        raw_text=text, timestamp_ms=current_timestamp_ms()))
                for d in docs:
                    await bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED,
                                      to_json_bytes(d))

                got = {}
                for _ in range(len(docs)):
                    m = await sub_emb.next(60.0)
                    assert m is not None, f"only {len(got)}/{len(docs)} docs"
                    out = decode_emb_msg(m)
                    got[out.original_id] = out
                assert set(got) == {d.id for d in docs}

                calls_after = metrics.snapshot()["counters"].get(
                    "engine.embed.batch", 0)
                assert calls_after - calls_before < len(docs), (
                    "no coalescing: one embed hop per doc "
                    f"({calls_after - calls_before} hops for {len(docs)} docs)")

                # alignment: every published vector == embedding that exact
                # sentence directly (the frame path is exact f32 end-to-end;
                # with SYMBIONT_FRAMES=0 the only lossy leg would be the C++
                # float→JSON dump of the publish)
                for d in docs:
                    out = got[d.id]
                    sents = [se.sentence_text for se in out.embeddings_data]
                    direct = eng.embed_texts(sents)
                    for se, want in zip(out.embeddings_data, direct):
                        assert np.allclose(se.embedding, want, atol=1e-4), (
                            f"vector mismatch for {d.id}: {se.sentence_text!r}")
                await bus.close()
            finally:
                err = stop_worker(pre)
                await svc.stop()
                await engine_bus.close()
                assert "WARN" not in (err.split("ready", 1)[1]
                                      if "ready" in err else err), err

    asyncio.run(scenario())


def test_native_pipeline_survives_replica_kill(fresh_broker):
    broker = fresh_broker
    """Fault injection at stack level (SURVEY.md §5.3): SIGKILL a durable
    preprocessing replica while it holds unacked deliveries mid-embed; every
    document must still land — redelivered to the surviving replica after
    ack_wait — and land exactly once (deterministic point ids make the
    inevitable redelivery-after-publish overlap idempotent). The reference
    silently loses any in-flight document on a worker crash (SURVEY.md §5.3:
    core NATS, at-most-once)."""
    import tempfile

    async def scenario():
        from symbiont_tpu.config import EngineConfig, VectorStoreConfig
        from symbiont_tpu.engine.engine import TpuEngine
        from symbiont_tpu.memory.vector_store import VectorStore
        from symbiont_tpu.schema import RawTextMessage
        from symbiont_tpu.services.engine_service import EngineService

        eng = TpuEngine(EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                                     batch_buckets=[2, 4], max_batch=8,
                                     dtype="float32", data_parallel=False))
        with tempfile.TemporaryDirectory() as td:
            store = VectorStore(VectorStoreConfig(dim=32, data_dir=td))
            engine_bus = await _tcp_bus(broker)
            svc = EngineService(engine_bus, engine=eng, vector_store=store)
            await svc.start()
            env = {"SYMBIONT_BUS_DURABLE": "1",
                   "SYMBIONT_BUS_DURABLE_ACK_WAIT_MS": "1000"}
            pa = spawn_worker("preprocessing", broker, env)
            pb = spawn_worker("preprocessing", broker, env)
            vm = spawn_worker("vector_memory", broker, env)
            try:
                for p in (pa, pb, vm):
                    await _wait_ready(p, b"ready (durable)")
                bus = await _tcp_bus(broker)
                # enough docs that the pipelined workers (r5: coalesced,
                # multiple requests in flight) cannot drain them inside the
                # kill window — the count_at_kill guard below verifies
                docs, sents = 48, 3
                for i in range(docs):
                    text = ". ".join(f"Sentence {i} {j} about tensors"
                                     for j in range(sents)) + "."
                    await bus.publish(
                        subjects.DATA_RAW_TEXT_DISCOVERED,
                        to_json_bytes(RawTextMessage(
                            id=f"doc-{i}", source_url=f"http://u/{i}",
                            raw_text=text,
                            timestamp_ms=current_timestamp_ms())))
                await asyncio.sleep(0.01)  # deliveries in flight, unacked
                expected = docs * sents
                count_at_kill = store.count()
                pa.kill()  # SIGKILL: no ack, no goodbye
                # the fault window must actually contain unfinished work, or
                # this test would go green without exercising redelivery
                assert count_at_kill < expected, (
                    f"pipeline drained before the kill ({count_at_kill}); "
                    f"fault window missed — raise docs or shrink the sleep")
                for _ in range(300):
                    if store.count() >= expected:
                        break
                    await asyncio.sleep(0.1)
                assert store.count() == expected, (
                    f"lost work after replica kill: {store.count()}/{expected}")
                # past further ack windows: redeliveries must stay idempotent
                await asyncio.sleep(2.0)
                assert store.count() == expected
                await bus.close()
            finally:
                pa.kill()  # idempotent if already dead
                stop_worker(pa)
                stop_worker(pb)
                stop_worker(vm)
                await svc.stop()
                await engine_bus.close()

    asyncio.run(scenario())


def test_native_pipeline_survives_engine_restart(fresh_broker):
    broker = fresh_broker
    """The OTHER half of the two-plane failure semantics (SURVEY.md §7 hard
    part 6): the ENGINE plane drops abruptly (TCP connection severed with
    embed hops potentially in flight) and more documents arrive during the
    outage; durable pipeline workers keep every delivery unacked (their
    engine.embed hops fail or time out), and redelivery after ack_wait
    completes ALL documents once a fresh engine plane re-registers — none
    lost, none duplicated. Engine restart never restarts the workers."""
    import tempfile

    async def scenario():
        from symbiont_tpu.config import EngineConfig, VectorStoreConfig
        from symbiont_tpu.engine.engine import TpuEngine
        from symbiont_tpu.memory.vector_store import VectorStore
        from symbiont_tpu.schema import RawTextMessage
        from symbiont_tpu.services.engine_service import EngineService

        def mk_engine():
            return TpuEngine(EngineConfig(
                embedding_dim=32, length_buckets=[8, 16], batch_buckets=[2, 4],
                max_batch=8, dtype="float32", data_parallel=False))

        with tempfile.TemporaryDirectory() as td:
            store = VectorStore(VectorStoreConfig(dim=32, data_dir=td))
            engine_bus = await _tcp_bus(broker)
            svc = EngineService(engine_bus, engine=mk_engine(),
                                vector_store=store)
            await svc.start()
            # max_deliver sized for the outage: attempts churn every
            # ~ack_wait while the plane is down (plus first-embed compiles
            # after restart), and a dead-lettered doc would read as data
            # loss — the production default (5) assumes transient blips,
            # not a deliberately long outage window
            env = {"SYMBIONT_BUS_DURABLE": "1",
                   "SYMBIONT_BUS_DURABLE_ACK_WAIT_MS": "800",
                   "SYMBIONT_BUS_DURABLE_MAX_DELIVER": "50",
                   "SYMBIONT_ENGINE_TIMEOUT_MS": "700"}
            pre = spawn_worker("preprocessing", broker, env)
            vm = spawn_worker("vector_memory", broker, env)
            try:
                await _wait_ready(pre, b"ready (durable)")
                await _wait_ready(vm, b"ready (durable)")
                bus = await _tcp_bus(broker)
                docs, sents = 4, 3

                def publish_doc(i: int):
                    text = ". ".join(f"Outage doc {i} s{j} about chips"
                                     for j in range(sents)) + "."
                    return bus.publish(
                        subjects.DATA_RAW_TEXT_DISCOVERED,
                        to_json_bytes(RawTextMessage(
                            id=f"odoc-{i}", source_url=f"http://o/{i}",
                            raw_text=text,
                            timestamp_ms=current_timestamp_ms())))

                # half the docs arrive, then the engine plane's connection
                # is severed ABRUPTLY (no graceful stop: in-flight embed
                # hops get no reply); the rest arrive during the outage
                for i in range(docs // 2):
                    await publish_doc(i)
                await asyncio.sleep(0.02)
                await engine_bus.close()  # abrupt: drops subscriptions
                await svc.stop()
                # measured AFTER stop() drained in-flight upsert handlers:
                # anything still mid-handler at the cut lands before this
                count_at_cut = store.count()
                for i in range(docs // 2, docs):
                    await publish_doc(i)
                # workers churn failures against the dead plane; anything
                # not upserted before the cut stays pending, nothing new lands
                await asyncio.sleep(1.5)
                assert store.count() == count_at_cut

                # engine plane comes BACK (fresh process-equivalent: new
                # engine, new bus connection; the store is the durable truth)
                engine_bus2 = await _tcp_bus(broker)
                svc2 = EngineService(engine_bus2, engine=mk_engine(),
                                     vector_store=store)
                await svc2.start()
                expected = docs * sents
                for _ in range(400):
                    if store.count() >= expected:
                        break
                    await asyncio.sleep(0.1)
                assert store.count() == expected, (
                    f"work lost across engine restart: "
                    f"{store.count()}/{expected}")
                await asyncio.sleep(1.5)  # further redeliveries: idempotent
                assert store.count() == expected
                await bus.close()
                await svc2.stop()
                await engine_bus2.close()
            finally:
                stop_worker(pre)
                stop_worker(vm)

    asyncio.run(scenario())
