"""Online LM fine-tune over ingested text (the LM "evolving organism" loop).

The Markov backend already learns from every ingested document; these tests
prove the decoder-LM backend does too: ingest → a few AdamW steps over the
packed text → serving params updated → generation measurably shifts
(reference ceiling: the Markov chain retrained from one constant at boot,
text_generator_service/src/main.rs:169-174 — no learning at all for its LM-
equivalent path).
"""

import asyncio

import numpy as np
import pytest

from symbiont_tpu.config import LmConfig
from symbiont_tpu.engine.lm import LmEngine
from symbiont_tpu.train.online import OnlineLmTrainer

TINY = dict(enabled=True, arch="llama", hidden_size=32, num_layers=2,
            num_heads=4, intermediate_size=64, max_positions=128,
            dtype="float32", prompt_buckets=[8], new_token_buckets=[16],
            temperature=0.0)

CORPUS = ["the mesh shards batches across data parallel devices " * 4,
          "collectives ride the interconnect between the chips " * 4]


def test_generation_shifts_after_ingest_train():
    """The 'Done' criterion from the round-2 verdict ask #9: greedy
    generation changes after training on ingested text, and the LM loss on
    that text goes down — the organism demonstrably learned from reading."""
    lm = LmEngine(LmConfig(**TINY))
    trainer = OnlineLmTrainer(lm, learning_rate=5e-3, seq_len=32,
                              batch_size=4)
    before = lm.generate("the mesh", 16, temperature=0.0)
    first = trainer.train_on_texts(CORPUS, steps=1)
    for _ in range(6):
        last = trainer.train_on_texts(CORPUS, steps=4)
    assert last["loss"] < first["loss"], (first, last)
    after = lm.generate("the mesh", 16, temperature=0.0)
    assert after != before, "generation did not shift after ingest-train"
    assert trainer.stats["param_syncs"] >= 7


def test_serving_params_never_donated():
    """lm_train_step donates its input state; the serving engine must keep
    working across many train/generate interleavings (a shared buffer would
    raise 'buffer donated' on the second pass)."""
    lm = LmEngine(LmConfig(**TINY))
    trainer = OnlineLmTrainer(lm, seq_len=16, batch_size=2)
    for _ in range(3):
        trainer.train_on_texts(CORPUS, steps=1)
        assert isinstance(lm.generate("x", 8, temperature=0.0), str)


def test_train_state_persists_and_restores(tmp_path):
    """Crash-safe continuation: a restarted trainer resumes from the saved
    optimizer state (step count + params), and a model-shape mismatch falls
    back to fresh state instead of crashing the service."""
    path = str(tmp_path / "lm_train")
    lm = LmEngine(LmConfig(**TINY))
    trainer = OnlineLmTrainer(lm, seq_len=16, batch_size=2, state_path=path)
    out = trainer.train_on_texts(CORPUS, steps=3)
    steps_done = trainer.stats["train_steps"]
    assert steps_done == out["steps"] > 0

    lm2 = LmEngine(LmConfig(**TINY))
    trainer2 = OnlineLmTrainer(lm2, seq_len=16, batch_size=2, state_path=path)
    assert trainer2.stats["train_steps"] == steps_done  # resumed, not reset
    # restored params flow into the new serving engine immediately
    a = np.asarray(trainer.state.params["wte"])
    b = np.asarray(lm2.params["wte"]).astype(a.dtype)
    np.testing.assert_array_equal(a, b)

    # different geometry → graceful fresh start
    other = dict(TINY, hidden_size=64, intermediate_size=128)
    lm3 = LmEngine(LmConfig(**other))
    trainer3 = OnlineLmTrainer(lm3, seq_len=16, batch_size=2, state_path=path)
    assert trainer3.stats["train_steps"] == 0


def test_pack_handles_empty_and_short_texts():
    lm = LmEngine(LmConfig(**TINY))
    trainer = OnlineLmTrainer(lm, seq_len=16, batch_size=2)
    assert trainer.train_on_texts([""])["steps"] == 0  # nothing to learn
    out = trainer.train_on_texts(["ab"], steps=1)  # cycles to fill the batch
    assert out["steps"] == 1 and np.isfinite(out["loss"])


def test_long_text_carries_over_instead_of_dropping():
    """Regression: one pass used to keep only the first batch_size×seq_len
    tokens of the buffer and silently drop the rest. Text beyond one batch
    must train as additional batches now, and any sub-batch remainder must
    carry over to the next pass."""
    lm = LmEngine(LmConfig(**TINY))
    trainer = OnlineLmTrainer(lm, seq_len=16, batch_size=2)  # need = 32
    long_text = "every sentence the organism reads matters " * 12  # ~500 tok
    out = trainer.train_on_texts([long_text], steps=1)
    assert out["batches"] >= 3  # multiple batches, not a single truncation
    total = out["batches"] * 32 + trainer.stats["tokens_pending"]
    assert total >= 500 * 0.9  # nearly all tokens accounted for
    # the carried remainder trains on the next (even empty) pass
    if trainer.stats["tokens_pending"]:
        out2 = trainer.train_on_texts([], steps=1)
        assert out2["steps"] >= 1
        assert trainer.stats["tokens_pending"] == 0


def test_service_ingest_triggers_lm_training():
    """Service wiring: raw-text messages buffer until the threshold, then a
    fine-tune pass runs and the serving engine's params move."""
    from symbiont_tpu import subjects
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.schema import RawTextMessage, to_json_bytes
    from symbiont_tpu.services.text_generator import TextGeneratorService
    from symbiont_tpu.utils.ids import current_timestamp_ms, generate_uuid
    from symbiont_tpu.utils.telemetry import metrics

    async def scenario():
        lm = LmEngine(LmConfig(**TINY))
        trainer = OnlineLmTrainer(lm, learning_rate=5e-3, seq_len=16,
                                  batch_size=2)
        wte_before = np.asarray(lm.params["wte"]).copy()
        bus = InprocBus()
        svc = TextGeneratorService(bus, lm_generate=lm.generate,
                                   train_on_ingest=False, lm_trainer=trainer,
                                   lm_train_min_chars=64, lm_train_steps=1)
        await svc.start()
        try:
            # below threshold: buffered, no pass yet
            await bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED,
                              to_json_bytes(RawTextMessage(
                                  id=generate_uuid(), source_url="u",
                                  raw_text="short",
                                  timestamp_ms=current_timestamp_ms())))
            await asyncio.sleep(0.2)
            assert trainer.stats["train_steps"] == 0
            # crossing the threshold triggers a pass
            await bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED,
                              to_json_bytes(RawTextMessage(
                                  id=generate_uuid(), source_url="u",
                                  raw_text=CORPUS[0],
                                  timestamp_ms=current_timestamp_ms())))
            # generous: the pass jit-compiles the train step in an executor
            # thread, which can take tens of seconds on a loaded CI machine
            for _ in range(1200):
                if trainer.stats["train_steps"] > 0:
                    break
                await asyncio.sleep(0.05)
            assert trainer.stats["train_steps"] >= 1
            # usually both docs drain in one pass; under handler-ordering
            # races the short one may still be buffered for the next pass
            assert 1 <= trainer.stats["train_docs"] <= 2
            wte_after = np.asarray(lm.params["wte"])
            assert not np.array_equal(wte_before, wte_after), \
                "serving engine params did not move after ingest training"
            snap = metrics.snapshot()["counters"]
            assert snap.get("text_generator.lm_train_passes", 0) >= 1
        finally:
            await svc.stop()

    asyncio.run(scenario())


def test_masters_init_from_precast_checkpoint(monkeypatch, tmp_path):
    """ADVICE r5: with the engine storing params at bf16, a fresh trainer
    against a real checkpoint must initialize its f32 masters from the
    ORIGINAL pre-cast weights, not from the engine's bf16-rounded copy —
    and a resumed train state must still win over the checkpoint."""
    import jax
    import jax.numpy as jnp

    from symbiont_tpu.models import convert as convert_mod

    base = LmEngine(LmConfig(**dict(TINY, dtype="bfloat16")))
    # a "checkpoint" whose f32 values differ from their bf16 rounding by
    # less than one bf16 ulp (~0.4% relative): bf16(ck) == bf16 engine
    # params, so only a pre-cast load can reproduce ck exactly
    ck_params = jax.tree.map(
        lambda a: (np.asarray(a, np.float32) * np.float32(1 + 1e-4)
                   if jnp.issubdtype(a.dtype, jnp.floating)
                   else np.asarray(a)), base.params)
    model_cfg = base.model_cfg
    calls = {"n": 0}

    def fake_load(model_dir):
        calls["n"] += 1
        return ck_params, model_cfg

    monkeypatch.setattr(convert_mod, "load_gpt_model", fake_load)

    lm = LmEngine(LmConfig(**dict(TINY, dtype="bfloat16",
                                  model_dir=str(tmp_path / "ck"))))
    assert calls["n"] == 1  # the engine itself booted from the checkpoint
    trainer = OnlineLmTrainer(lm, seq_len=16, batch_size=2)
    assert calls["n"] == 2  # the trainer re-read the pre-cast weights

    ck_leaves = jax.tree.leaves(ck_params)
    master_leaves = jax.tree.leaves(trainer.state.params)
    engine_leaves = jax.tree.leaves(lm.params)
    float_triples = [
        (c, m, e) for c, m, e in zip(ck_leaves, master_leaves, engine_leaves)
        if jnp.issubdtype(np.asarray(c).dtype, np.floating)]
    assert float_triples
    for ck, master, engine in float_triples:
        assert master.dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(master), np.asarray(ck))
        # and the masters are NOT just the widened bf16 engine params
        widened = np.asarray(engine, np.float32)
        if not np.allclose(np.asarray(ck), widened, rtol=0, atol=0):
            break
    else:
        pytest.fail("checkpoint indistinguishable from bf16 params — "
                    "the test corpus lost its sub-ulp perturbation")

    # a saved train state still wins over the checkpoint (resume path)
    state_path = str(tmp_path / "lm_train")
    trainer_saving = OnlineLmTrainer(lm, seq_len=16, batch_size=2,
                                     state_path=state_path)
    trainer_saving.train_on_texts(CORPUS, steps=1)
    steps = trainer_saving.stats["train_steps"]
    calls_before = calls["n"]
    resumed = OnlineLmTrainer(lm, seq_len=16, batch_size=2,
                              state_path=state_path)
    assert resumed.stats["train_steps"] == steps
    assert calls["n"] == calls_before  # resume never re-reads the checkpoint


def test_masters_fall_back_to_engine_params_on_load_failure(monkeypatch,
                                                            tmp_path):
    """A vanished/corrupt checkpoint dir must degrade to the old behavior
    (widened engine params) with a warning, never crash the service."""
    import jax
    import jax.numpy as jnp

    from symbiont_tpu.models import convert as convert_mod

    lm = LmEngine(LmConfig(**dict(TINY, dtype="bfloat16")))
    lm.config.model_dir = str(tmp_path / "gone")  # dir does not exist

    def boom(model_dir):
        raise FileNotFoundError(model_dir)

    monkeypatch.setattr(convert_mod, "load_gpt_model", boom)
    trainer = OnlineLmTrainer(lm, seq_len=16, batch_size=2)
    for a, b in zip(jax.tree.leaves(trainer.state.params),
                    jax.tree.leaves(lm.params)):
        if jnp.issubdtype(np.asarray(b).dtype, jnp.floating):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b, np.float32))


def test_runner_wires_trainer_when_enabled(tmp_path):
    """SymbiontStack builds the OnlineLmTrainer from LmConfig.ingest_train
    and hands it to the text generator service."""
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.config import (ApiConfig, EngineConfig,
                                     GraphStoreConfig, SymbiontConfig,
                                     TextGeneratorConfig, VectorStoreConfig)
    from symbiont_tpu.runner import SymbiontStack

    cfg = SymbiontConfig(
        engine=EngineConfig(embedding_dim=32, length_buckets=[16],
                            batch_buckets=[2], max_batch=2, dtype="float32",
                            data_parallel=False),
        lm=LmConfig(**dict(TINY, ingest_train=True,
                           ingest_train_seq_len=16, ingest_train_batch=2,
                           train_state_path=str(tmp_path / "lm_train"))),
        vector_store=VectorStoreConfig(dim=32,
                                       data_dir=str(tmp_path / "vs")),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(
            markov_state_path=str(tmp_path / "markov.json")),
        api=ApiConfig(host="127.0.0.1", port=0))

    async def scenario():
        stack = SymbiontStack(cfg, bus=InprocBus())
        await stack.start()
        try:
            svc = next(s for s in stack.services
                       if s.name == "text_generator")
            assert svc.lm_trainer is not None
            assert svc.lm_trainer.lm is stack.lm
        finally:
            await stack.stop()

    asyncio.run(scenario())
