"""Reference-frontend compatibility, asserted instead of claimed.

README says the reference's Next.js UI works against these gateways
unmodified. This module backs that claim: CONTRACT below transcribes every
expectation the reference UI's own code makes of its API — routes it fetches,
request payloads it sends, response fields it destructures, and the SSE
framing EventSource requires (reference: frontend/src/app/page.tsx:7-48
interfaces, :63-96 SSE wiring, :98-197 handlers) — and both gateways are
driven through all of them.

Two layers of enforcement:
1. `test_contract_matches_reference_source` re-DERIVES the routes and
   interface fields from the reference's page.tsx with regexes and asserts
   CONTRACT matches, so the transcription itself can't rot (runs only where
   the reference checkout exists; the gateway tests below never need it).
2. `test_python_gateway_meets_contract` / `test_native_gateway_meets_contract`
   run the checks against live gateways end-to-end (real ingest → search →
   generate → SSE).
"""

import asyncio
import json
import re
import shutil
import urllib.error
import urllib.request
from pathlib import Path

import pytest

REFERENCE_TSX = Path("/root/reference/frontend/src/app/page.tsx")

CONTRACT = {
    # route → payload the UI posts (page.tsx:106-110,134-139,166-171)
    "routes": {
        "/api/submit-url": {"url": "http://example.com/doc1"},
        "/api/generate-text": {"task_id": "contract-task-1", "prompt": None,
                               "max_length": 50},
        "/api/search/semantic": {"query_text": "vector memory stores",
                                 "top_k": 5},
    },
    "sse_route": "/api/events",  # page.tsx:66 EventSource target
    # response fields the UI destructures (page.tsx interfaces)
    "ApiResponse": {"message"},  # task_id optional (page.tsx:7-10)
    "SharedGeneratedTextMessage": {"original_task_id", "generated_text",
                                   "timestamp_ms"},
    "SemanticSearchApiResponsePayload": {"search_request_id", "results",
                                         "error_message"},
    "SemanticSearchResultItem": {"qdrant_point_id", "score", "payload"},
    "QdrantPointPayload": {"original_document_id", "source_url",
                           "sentence_text", "sentence_order", "model_name",
                           "processed_at_ms"},
    # the UI runs on a different origin (localhost:3000) than the API, so
    # fetch/EventSource need CORS on every route (reference CORS setup:
    # api_service/src/main.rs:555-567)
    "cors_origin": "http://localhost:3000",
}

DOC_HTML = """
  <html><body><article>
    <p>TPUs accelerate matrix multiplication. They excel at embeddings!</p>
    <p>Vector memory stores every sentence.</p>
  </article></body></html>"""


# ------------------------------------------------- layer 1: derive from TSX

@pytest.mark.skipif(not REFERENCE_TSX.exists(),
                    reason="reference checkout not present")
def test_contract_matches_reference_source():
    """CONTRACT is a faithful transcription of page.tsx: same fetched routes,
    same interface field names. If the reference UI changes, this fails
    before the gateway tests can silently test the wrong contract."""
    src = REFERENCE_TSX.read_text()

    fetched = set(re.findall(r"fetch\(`\$\{API_BASE_URL\}(/[\w/-]+)`", src))
    assert {f"/api{r}" for r in fetched} == set(CONTRACT["routes"])
    (sse,) = re.findall(r"EventSource\(`\$\{API_BASE_URL\}(/[\w/-]+)`", src)
    assert f"/api{sse}" == CONTRACT["sse_route"]

    def interface_fields(name: str) -> set:
        m = re.search(rf"interface {name} \{{(.*?)\}}", src, re.S)
        assert m, f"interface {name} not found in page.tsx"
        return set(re.findall(r"^\s*(\w+)\??:", m.group(1), re.M))

    assert interface_fields("ApiResponse") == CONTRACT["ApiResponse"] | {"task_id"}
    for iface in ("SharedGeneratedTextMessage", "SemanticSearchResultItem",
                  "QdrantPointPayload", "SemanticSearchApiResponsePayload"):
        assert interface_fields(iface) == CONTRACT[iface], iface
    # the payload the generate handler builds (page.tsx:128-132)
    for field in ("task_id", "prompt", "max_length"):
        assert field in CONTRACT["routes"]["/api/generate-text"]
    assert re.search(r"prompt:.*?null", src)  # UI really sends null prompts


# ----------------------------------------------- layer 2: drive the gateways

def _http(method, port, path, body=None, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json", **(headers or {})},
        method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"null"), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null"), dict(e.headers)


async def _check_contract(port, wait_ingested):
    """Drive one live gateway through every CONTRACT expectation."""
    loop = asyncio.get_running_loop()

    def hx(method, path, body=None, headers=None):
        return loop.run_in_executor(
            None, lambda: _http(method, port, path, body, headers))

    origin = {"Origin": CONTRACT["cors_origin"]}

    # --- SSE first (the UI connects on mount, before any form submit) -----
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {CONTRACT['sse_route']} HTTP/1.1\r\n"
                 f"Host: x\r\nAccept: text/event-stream\r\n"
                 f"Origin: {CONTRACT['cors_origin']}\r\n\r\n".encode())
    await writer.drain()
    head = (await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 15)).decode()
    status_line, *header_lines = head.split("\r\n")
    assert " 200 " in status_line, status_line
    sse_headers = {k.strip().lower(): v.strip() for k, _, v in
                   (h.partition(":") for h in header_lines if ":" in h)}
    # EventSource hard-fails on any other content type
    assert sse_headers["content-type"].startswith("text/event-stream")
    # cross-origin EventSource silently dies without CORS
    assert sse_headers.get("access-control-allow-origin") in (
        CONTRACT["cors_origin"], "*")
    await asyncio.sleep(0.3)  # let the hub register this client

    # --- submit-url (page.tsx:106-116) ------------------------------------
    status, body, headers = await hx("POST", "/api/submit-url",
                                     CONTRACT["routes"]["/api/submit-url"],
                                     origin)
    assert status == 200, body
    assert isinstance(body["message"], str) and body["message"]
    assert headers.get("Access-Control-Allow-Origin") in (
        CONTRACT["cors_origin"], "*")
    # error path renders data.message too (page.tsx:115)
    status, body, _ = await hx("POST", "/api/submit-url", {"url": " "}, origin)
    assert status != 200 and isinstance(body["message"], str)

    await wait_ingested()

    # --- semantic search (page.tsx:166-190) -------------------------------
    status, body, headers = await hx(
        "POST", "/api/search/semantic",
        CONTRACT["routes"]["/api/search/semantic"], origin)
    assert status == 200, body
    assert set(body) >= CONTRACT["SemanticSearchApiResponsePayload"]
    assert body["error_message"] is None
    assert isinstance(body["search_request_id"], str)
    assert body["results"], "ingested corpus must be searchable"
    for item in body["results"]:
        assert set(item) >= CONTRACT["SemanticSearchResultItem"]
        assert isinstance(item["score"], (int, float))  # .toFixed(4) on it
        assert set(item["payload"]) == CONTRACT["QdrantPointPayload"]
    assert headers.get("Access-Control-Allow-Origin") in (
        CONTRACT["cors_origin"], "*")

    # --- generate-text (page.tsx:134-144) ---------------------------------
    status, body, _ = await hx("POST", "/api/generate-text",
                               CONTRACT["routes"]["/api/generate-text"],
                               origin)
    assert status == 200, body
    assert isinstance(body["message"], str) and body["message"]

    # --- the generated result arrives over SSE (page.tsx:71-82) -----------
    async def next_data_frame():
        while True:  # EventSource ignores comment keep-alives (": ...")
            frame = await reader.readuntil(b"\n\n")
            lines = [ln[6:] for ln in frame.decode().splitlines()
                     if ln.startswith("data: ")]
            if lines:
                return json.loads("\n".join(lines))

    event = await asyncio.wait_for(next_data_frame(), 30)
    assert set(event) >= CONTRACT["SharedGeneratedTextMessage"]
    assert event["original_task_id"] == \
        CONTRACT["routes"]["/api/generate-text"]["task_id"]
    assert isinstance(event["generated_text"], str)
    assert isinstance(event["timestamp_ms"], int)
    writer.close()


def test_python_gateway_meets_contract(tmp_path):
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.config import (ApiConfig, EngineConfig,
                                     GraphStoreConfig, SymbiontConfig,
                                     TextGeneratorConfig, VectorStoreConfig)
    from symbiont_tpu.runner import SymbiontStack

    cfg = SymbiontConfig(
        engine=EngineConfig(embedding_dim=32, length_buckets=[16, 32],
                            batch_buckets=[2, 8], max_batch=8,
                            dtype="float32", data_parallel=False,
                            flush_deadline_ms=2.0),
        vector_store=VectorStoreConfig(dim=32, data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(
            markov_state_path=str(tmp_path / "markov.json")),
        api=ApiConfig(host="127.0.0.1", port=0, sse_keepalive_s=0.5))

    async def scenario():
        stack = SymbiontStack(cfg, bus=InprocBus(),
                              fetcher=lambda url: DOC_HTML)
        await stack.start()
        try:
            async def wait_ingested():
                # generous: first embed compiles executables (~20s CPU)
                for _ in range(1200):
                    if stack.vector_store.count() >= 3:
                        return
                    await asyncio.sleep(0.1)
                raise TimeoutError("ingest pipeline stalled")

            await _check_contract(stack.api.port, wait_ingested)
        finally:
            await stack.stop()

    asyncio.run(scenario())


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_native_gateway_meets_contract(tmp_path):
    """Same contract against the C++ gateway with C++ workers behind it."""
    import tempfile

    from tests.test_native_services import (_free_port, _tcp_bus, _wait_ready,
                                            spawn_worker, stop_worker)
    from tests.test_native_services import broker as _broker_fixture  # noqa: F401

    import subprocess

    from tests.conftest import NATIVE_MAKE_TARGET, native_bin

    REPO = Path(__file__).resolve().parent.parent
    subprocess.run(["make", "-C", str(REPO / "native"), NATIVE_MAKE_TARGET],
                   check=True, capture_output=True)
    import socket
    import time

    port = _free_port()
    broker_proc = subprocess.Popen(
        [native_bin("symbus_broker"), "--port", str(port),
         "--host", "127.0.0.1"], stderr=subprocess.PIPE)
    for _ in range(100):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.2):
                break
        except OSError:
            time.sleep(0.05)
    else:
        broker_proc.kill()
        raise RuntimeError("broker did not start")

    async def scenario():
        from symbiont_tpu.config import EngineConfig, VectorStoreConfig
        from symbiont_tpu.engine.engine import TpuEngine
        from symbiont_tpu.memory.vector_store import VectorStore
        from symbiont_tpu.services.engine_service import EngineService

        eng = TpuEngine(EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                                     batch_buckets=[2, 4], dtype="float32"))
        api_port = _free_port()
        with tempfile.TemporaryDirectory() as td:
            store = VectorStore(VectorStoreConfig(dim=32, data_dir=td))
            engine_bus = await _tcp_bus(port)
            svc = EngineService(engine_bus, engine=eng, vector_store=store)
            await svc.start()
            workers = [spawn_worker("perception", port),
                       spawn_worker("preprocessing", port),
                       spawn_worker("vector_memory", port),
                       spawn_worker("text_generator", port),
                       spawn_worker("api_gateway", port,
                                    {"SYMBIONT_API_PORT": str(api_port)})]
            try:
                for w in workers:
                    await _wait_ready(w)

                # serve the CONTRACT submit-url target for the C++ scraper
                import http.server
                import threading

                class Handler(http.server.BaseHTTPRequestHandler):
                    def do_GET(self):
                        page = DOC_HTML.encode()
                        self.send_response(200)
                        self.send_header("Content-Type", "text/html")
                        self.send_header("Content-Length", str(len(page)))
                        self.end_headers()
                        self.wfile.write(page)

                    def log_message(self, *a):
                        pass

                web = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
                threading.Thread(target=web.serve_forever, daemon=True).start()
                CONTRACT["routes"]["/api/submit-url"] = {
                    "url": f"http://127.0.0.1:{web.server_address[1]}/doc1"}

                async def wait_ingested():
                    # generous: first embed compiles executables (~20s CPU)
                    for _ in range(1200):
                        if store.count() >= 3:
                            return
                        await asyncio.sleep(0.1)
                    raise TimeoutError("native ingest pipeline stalled")

                try:
                    await _check_contract(api_port, wait_ingested)
                finally:
                    web.shutdown()
            finally:
                for w in workers:
                    stop_worker(w)
                await svc.stop()
                await engine_bus.close()

    try:
        asyncio.run(scenario())
    finally:
        broker_proc.terminate()
        broker_proc.wait(timeout=5)
