"""Quantization plane gates (ROADMAP item 4, models/quant.py).

Quality parity is a HARD BAR, enforced here on tiny models on CPU (the
bench quant tier re-measures the same contracts at real geometry on
device, with speed primaries):

- embed parity: cosine ≥ 0.999 between quantized and bf16 embeddings on a
  fixed corpus, for the f16 and int8 weight paths (fp8's 3 mantissa bits
  get a documented looser bar — docs/QUANTIZATION.md);
- rerank-order preservation on the top-k under quantized cross-encoder
  weights;
- LM logit agreement under int8 weights, and TOKEN-IDENTICAL greedy decode
  between the int8 KV cache and the unquantized cache on the tiny GPT test
  model — through generate_batch, streaming, and a continuous-batching
  session with a mid-decode admit (merge_rows on the quantized layout);
- the KV occupancy gauges report dtype-adjusted capacity (bytes and
  rows-per-GiB move the way the storage dtype says they must).

Everything is seeded and CPU-deterministic: a pass here is a pass forever
on this platform.
"""

import dataclasses

import jax
import numpy as np
import pytest

from symbiont_tpu.config import EngineConfig, LmConfig
from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.engine.lm import LmEngine
from symbiont_tpu.models import bert as bert_mod
from symbiont_tpu.models import gpt as gpt_mod
from symbiont_tpu.models import quant
from symbiont_tpu.models.bert import BertConfig
from symbiont_tpu.models.gpt import GPTConfig
from symbiont_tpu.utils.telemetry import metrics

# the fixed parity corpus: mixed lengths, deterministic
CORPUS = [
    "The MXU does matmuls all day.",
    "HBM bandwidth is the wall, not flops.",
    "Quantization moves half the bytes.",
    "A sentence.",
    "Length buckets keep the shapes static so nothing ever recompiles "
    "during steady-state serving.",
    "Per-channel scales keep the dequant exact along the output features.",
    "tpu",
    "Decode is weight-read bound at small batch.",
]

BERT_CFG = BertConfig(vocab_size=30000, hidden_size=64, num_layers=2,
                      num_heads=2, intermediate_size=256,
                      max_position_embeddings=64, dtype="bfloat16")


def _engine(mode: str, params, rerank: bool = False,
            dtype: str = "bfloat16") -> TpuEngine:
    return TpuEngine(
        EngineConfig(embedding_dim=64, length_buckets=[16, 32],
                     batch_buckets=[4, 8], dtype=dtype, quantize=mode,
                     rerank_enabled=rerank),
        params=params, model_cfg=BERT_CFG)


def _row_cosines(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    num = np.sum(a * b, axis=1)
    den = np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1)
    return num / np.maximum(den, 1e-12)


@pytest.fixture(scope="module")
def bert_params():
    return bert_mod.init_params(jax.random.key(0), BERT_CFG)


def test_config_modes_match_quant_modes():
    """config.QUANTIZE_MODES is THE mode list (jax-free module, so the
    validators can use it directly); quant.MODES re-exports it."""
    from symbiont_tpu.config import QUANTIZE_MODES

    assert quant.MODES is QUANTIZE_MODES
    for mode in quant.MODES:
        EngineConfig(quantize=mode)
        LmConfig(quantize=mode)
    with pytest.raises(ValueError):
        EngineConfig(quantize="int4")
    with pytest.raises(ValueError):
        LmConfig(quantize="int4")
    with pytest.raises(ValueError):
        LmConfig(kv_quant="f16")  # KV variant is none|int8 only


def test_channel_quantize_error_bound():
    rng = np.random.default_rng(0)
    w = rng.standard_normal((128, 32)).astype(np.float32) * 0.05
    qt = quant.channel_quantize(w, 127.0, np.int8)
    back = np.asarray(qt.dequantize())
    # symmetric int8: per-element error ≤ scale/2, scale = amax/127
    amax = np.abs(w).max(axis=0)
    assert (np.abs(back - w) <= amax / 127.0 / 2 + 1e-7).all()
    # and the scale axis is the LAST one (per output channel)
    assert qt.scale.shape == (32,)


def test_embed_cosine_parity_vs_bf16(bert_params):
    """THE parity gate: quantized embeddings vs the bf16 baseline on the
    fixed corpus — cosine ≥ 0.999 for f16 and int8 (the acceptance bar),
    fp8 at its documented looser bar."""
    base = _engine("none", bert_params).embed_texts(CORPUS)
    bars = {"f16": 0.999, "int8": 0.999, "fp8": 0.998}
    for mode, bar in bars.items():
        out = _engine(mode, bert_params).embed_texts(CORPUS)
        cos = _row_cosines(base, out)
        assert cos.min() >= bar, (mode, cos.min())


def test_rerank_order_preserved(bert_params):
    """Top-k rerank ORDER under int8 cross-encoder weights must match the
    baseline (order, not raw scores, is what the API returns). Run at f32
    compute: the SYNTHETIC random cross-encoder maps every passage to
    nearly the same CLS point (score gaps ~1e-5), so at bf16 the gap is
    below bf16 rounding noise and order flips measure the fixture, not
    quantization — f32 isolates exactly the int8 error this gate is about
    (real checkpoints separate scores by orders of magnitude more; the
    bench quant tier re-checks there)."""
    passages = CORPUS
    base = _engine("none", bert_params, rerank=True, dtype="float32")
    quantized = _engine("int8", bert_params, rerank=True, dtype="float32")
    for query in ("which part is the bottleneck?", "matmul throughput"):
        s0 = base.rerank(query, passages)
        s1 = quantized.rerank(query, passages)
        assert list(np.argsort(-s0)) == list(np.argsort(-s1)), query


def test_param_bytes_gauge_dtype_labeled(bert_params):
    _engine("none", bert_params)
    _engine("int8", bert_params)
    full = metrics.gauge_get("engine.param_bytes",
                             labels={"service": "engine", "dtype": "f32"})
    narrow = metrics.gauge_get("engine.param_bytes",
                               labels={"service": "engine", "dtype": "int8"})
    assert full > 0 and narrow > 0
    # int8 + f32 scales ≈ ¼ of f32-at-rest (rank-1 params stay f32)
    assert narrow < 0.30 * full


# ------------------------------------------------------------------- LM

GPT_KW = dict(enabled=True, hidden_size=64, num_layers=2, num_heads=2,
              intermediate_size=128, max_positions=256, dtype="float32",
              prompt_buckets=[16], new_token_buckets=[16], stream_chunk=4,
              session_min_rows=4, seed=3)


def _lm(**over) -> LmEngine:
    return LmEngine(LmConfig(**{**GPT_KW, **over}))


def test_gpt_int8_weight_logit_agreement():
    """Prefill logits under int8 weights stay directionally identical to
    the unquantized forward (cosine per row ≥ 0.999 at f32 compute)."""
    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                    num_heads=2, intermediate_size=128,
                    max_position_embeddings=128, arch="llama",
                    dtype="float32")
    params = gpt_mod.init_params(jax.random.key(1), cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 97, (2, 8)).astype(np.int32)
    mask = np.ones((2, 8), np.int32)
    import jax.numpy as jnp

    _, logits_a, _, _ = gpt_mod.prefill(params, jnp.asarray(ids),
                                        jnp.asarray(mask), cfg, 16)
    _, logits_b, _, _ = gpt_mod.prefill(quant.quantize_params(params, "int8"),
                                        jnp.asarray(ids), jnp.asarray(mask),
                                        cfg, 16)
    cos = _row_cosines(np.asarray(logits_a), np.asarray(logits_b))
    assert cos.min() >= 0.999


def test_int8_kv_greedy_token_identical_generate_batch():
    """The acceptance bar: int8 KV decode produces token-identical greedy
    output vs the unquantized cache on the tiny GPT test model. gpt2 arch:
    learned positions make successive greedy tokens vary, so this is not a
    trivially-repeating comparison."""
    a = _lm(arch="gpt2", kv_quant="none")
    b = _lm(arch="gpt2", kv_quant="int8")
    prompts = ["the quick brown fox", "quantize the cache", ""]
    out_a = a.generate_batch(prompts, [12, 12, 12], temperature=0.0)
    out_b = b.generate_batch(prompts, [12, 12, 12], temperature=0.0)
    assert out_a == out_b
    assert any(len(set(t)) > 1 for t in out_a)  # non-degenerate output


def test_int8_kv_greedy_token_identical_stream_and_session():
    """Same bar through the chunked paths: streaming decode and a
    continuous-batching session with a mid-decode admit (merge_rows must
    splice the quantized layout — slabs AND scale planes)."""
    a = _lm(arch="gpt2", kv_quant="none")
    b = _lm(arch="gpt2", kv_quant="int8")
    sa = "".join(a.generate_stream("the quick brown fox", 12,
                                   temperature=0.0))
    sb = "".join(b.generate_stream("the quick brown fox", 12,
                                   temperature=0.0))
    assert sa == sb and sa

    def run_session(lm):
        s = lm.start_session(["the quick brown fox"], [12], temperature=0.0)
        out = dict()
        first = s.step()
        out.update(first)
        tags = s.admit(["hello world"], [8], temperature=0.0)
        assert tags and tags[0] is not None
        while not s.done():
            out.update(s.step())
        return sorted(out.items())

    sess_a, sess_b = run_session(a), run_session(b)
    assert sess_a == sess_b
    assert len(sess_a) == 2  # both the original and the admitted row landed


def test_kv_gauges_report_dtype_adjusted_capacity():
    """lm.kv_cache_bytes / lm.kv_rows_per_gib are labeled by KV storage
    dtype and move the way the dtype says: int8 slabs + f32 scale planes
    hold ≥3× more rows per byte than this model's f32 cache (≈2× vs a
    bf16 cache in production)."""
    a = _lm(kv_quant="none")    # dtype float32 → f32 cache slabs
    b = _lm(kv_quant="int8")
    sess_a = a.start_session(["hello"], [12], temperature=0.0)
    sess_b = b.start_session(["hello"], [12], temperature=0.0)
    sess_a.step()
    sess_b.step()
    la = {"service": "lm", "kv_dtype": "float32"}
    lb = {"service": "lm", "kv_dtype": "int8"}
    bytes_a = metrics.gauge_get("lm.kv_cache_bytes", labels=la)
    bytes_b = metrics.gauge_get("lm.kv_cache_bytes", labels=lb)
    assert bytes_a > 0 and bytes_b > 0
    # int8 + f32 per-(pos, head) scales at head_dim 32: 1 + 4/32 = 1.125
    # bytes/elem vs 4 → ~0.28×
    assert bytes_b < 0.35 * bytes_a
    rows_a = metrics.gauge_get("lm.kv_rows_per_gib", labels=la)
    rows_b = metrics.gauge_get("lm.kv_rows_per_gib", labels=lb)
    assert rows_b > 3.0 * rows_a > 0
    # drain so the weakref gauges retire cleanly
    while not sess_a.done():
        sess_a.step()
    while not sess_b.done():
        sess_b.step()


def test_int8_weight_lm_generates():
    """Smoke: quantized LM weights decode end-to-end (engine-level knob)."""
    lm = _lm(quantize="int8")
    out = lm.generate("hello", 8, temperature=0.0)
    assert isinstance(out, str) and out


def test_f16_storage_survives_wider_compute_dtype():
    """Review finding: lm.quantize=f16 with f32 compute used to re-widen
    the weights during placement (model-dtype cast after quantize) while
    the gauge still said f16. Storage must stay bf16 — the trace-time
    entry cast upcasts on-chip — and the gauge byte count must show it."""
    import jax
    import jax.numpy as jnp

    wide = _lm(quantize="none")          # dtype float32 → f32 at rest
    narrow = _lm(quantize="f16")         # must be bf16 at rest anyway
    r2 = [leaf for leaf in jax.tree.leaves(narrow.params)
          if getattr(leaf, "ndim", 0) >= 2]
    assert r2 and all(leaf.dtype == jnp.bfloat16 for leaf in r2)
    full = metrics.gauge_get("lm.param_bytes",
                             labels={"service": "lm", "dtype": "float32"})
    half = metrics.gauge_get("lm.param_bytes",
                             labels={"service": "lm", "dtype": "f16"})
    assert 0 < half < 0.6 * full
    # and it still decodes (bf16 weights upcast at trace into f32 compute)
    assert narrow.generate("hello", 8, temperature=0.0)
    del wide, narrow


# ----------------------------------------------------- training interplay

def test_online_trainer_over_quantized_engine():
    """Review finding: the f32-masters fallback used to copy the engine's
    QuantTensor leaves verbatim, so `lm.quantize=int8` + online fine-tune
    crashed every pass ('grad requires real-valued inputs ... got int8').
    Masters must DEQUANTIZE to f32, train, and sync back (update_params
    re-quantizes on placement)."""
    from symbiont_tpu.train.online import OnlineLmTrainer

    lm = _lm(quantize="int8", ingest_train=True)
    trainer = OnlineLmTrainer(lm, seq_len=16, batch_size=2)
    import jax

    for leaf in jax.tree.leaves(trainer.state.params,
                                is_leaf=quant.is_quantized):
        assert not quant.is_quantized(leaf)
    out = trainer.train_on_texts(["quantized online learning " * 8])
    assert isinstance(out, dict)
    assert trainer.stats["train_steps"] >= 1
    assert trainer.stats["last_loss"] is not None


def test_lm_loss_trains_unquantized_cache_under_kv_quant():
    """Review finding: a serving config with kv_quant=int8 must NOT put
    quantize-on-append round() (zero gradient) into the training forward —
    lm_loss forces an unquantized cache, so gradients match the
    kv_quant=none config exactly."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from symbiont_tpu.train import trainer as trainer_mod

    cfg = GPTConfig(vocab_size=61, hidden_size=32, num_layers=2,
                    num_heads=2, intermediate_size=64,
                    max_position_embeddings=64, arch="llama",
                    dtype="float32")
    params = gpt_mod.init_params(jax.random.key(2), cfg)
    rng = np.random.default_rng(1)
    batch = {"ids": jnp.asarray(rng.integers(0, 61, (2, 16)), jnp.int32),
             "mask": jnp.ones((2, 16), jnp.int32)}
    grads_plain = jax.grad(trainer_mod.lm_loss)(params, batch, cfg)
    qcfg = dataclasses.replace(cfg, kv_quant="int8")
    grads_q = jax.grad(trainer_mod.lm_loss)(params, batch, qcfg)
    flat_a = jax.tree.leaves(grads_plain)
    flat_b = jax.tree.leaves(grads_q)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
