"""Host-side tokenization: HF-native and hash tokenizers.

The HF path is exercised against a real tokenizer.json built in-test (no
network), covering the reference's truncation semantics
(embedding_generator.rs:93-99) and the batch path the engine's bulk ingest
uses.
"""

import pytest

from symbiont_tpu.engine.tokenizer import HashTokenizer, HFTokenizer, load_tokenizer


@pytest.fixture(scope="module")
def hf_tokenizer_file(tmp_path_factory):
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace
    from tokenizers.processors import TemplateProcessing

    words = ["the", "mxu", "does", "matmuls", "hbm", "is", "bottleneck",
             "fast", "and", "wide"]
    vocab = {"[PAD]": 0, "[UNK]": 1, "[CLS]": 2, "[SEP]": 3}
    vocab.update({w: i + 4 for i, w in enumerate(words)})
    tok = Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
    tok.pre_tokenizer = Whitespace()
    tok.post_processor = TemplateProcessing(
        single="[CLS] $A [SEP]",
        pair="[CLS] $A [SEP] $B:1 [SEP]:1",
        special_tokens=[("[CLS]", 2), ("[SEP]", 3)])
    f = tmp_path_factory.mktemp("tok") / "tokenizer.json"
    tok.save(str(f))
    return f


def test_hf_encode_and_specials(hf_tokenizer_file):
    t = HFTokenizer(hf_tokenizer_file)
    assert (t.cls_id, t.sep_id, t.pad_id) == (2, 3, 0)
    ids = t.encode("the mxu does matmuls", 32)
    assert ids[0] == t.cls_id and ids[-1] == t.sep_id
    assert len(ids) == 6


def test_hf_truncation_keeps_sep(hf_tokenizer_file):
    t = HFTokenizer(hf_tokenizer_file)
    ids = t.encode("the mxu does matmuls hbm is bottleneck fast and wide", 6)
    assert len(ids) == 6
    assert ids[-1] == t.sep_id  # LongestFirst parity: specials survive


def test_hf_encode_batch_matches_single(hf_tokenizer_file):
    t = HFTokenizer(hf_tokenizer_file)
    texts = ["the mxu", "hbm is the bottleneck", "",
             "the mxu does matmuls hbm is bottleneck fast and wide"]
    batch = t.encode_batch(texts, 6)
    assert batch == [t.encode(x, 6) for x in texts]


def test_hf_encode_pair_types(hf_tokenizer_file):
    t = HFTokenizer(hf_tokenizer_file)
    ids, types = t.encode_pair("the mxu", "hbm is fast", 32)
    assert len(ids) == len(types)
    assert types[0] == 0 and types[-1] == 1


def test_load_tokenizer_selects_backend(hf_tokenizer_file, tmp_path):
    assert isinstance(load_tokenizer(hf_tokenizer_file.parent, 100), HFTokenizer)
    assert isinstance(load_tokenizer(str(tmp_path), 100), HashTokenizer)
    assert isinstance(load_tokenizer(None, 100), HashTokenizer)


def test_hash_batch_matches_single():
    t = HashTokenizer(100)
    texts = ["a b c", "", "d " * 50]
    assert t.encode_batch(texts, 16) == [t.encode(x, 16) for x in texts]


def test_engine_with_hf_tokenizer(hf_tokenizer_file):
    """Full embed path over the real (native) tokenizer backend."""
    from symbiont_tpu.config import EngineConfig
    from symbiont_tpu.engine.engine import TpuEngine

    eng = TpuEngine(EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                                 batch_buckets=[2, 4], max_batch=4,
                                 dtype="float32", data_parallel=False),
                    tokenizer=HFTokenizer(hf_tokenizer_file))
    out = eng.embed_texts(["the mxu does matmuls", "hbm is the bottleneck"])
    assert out.shape == (2, 32)
    import numpy as np

    assert np.isfinite(out).all()
