"""Paged KV subsystem (symbiont_tpu/kv/): token identity vs dense, pool
refcount/eviction semantics, radix prefix sharing, merge_rows three-way
layout splicing, and the paged admission boundary."""

import numpy as np
import pytest

from symbiont_tpu.config import LmConfig
from symbiont_tpu.engine.lm import LmEngine
from symbiont_tpu.kv.pool import PagePool, PoolExhausted
from symbiont_tpu.kv.radix import RadixCache
from symbiont_tpu.utils.telemetry import Metrics


def tiny(layout, kv_quant="none", **kw):
    base = dict(enabled=True, arch="llama", hidden_size=64, num_layers=2,
                num_heads=4, intermediate_size=128, max_positions=512,
                dtype="float32", prompt_buckets=[16, 64],
                new_token_buckets=[32], kv_quant=kv_quant,
                kv_layout=layout, kv_page_tokens=16, temperature=0.0,
                session_min_rows=4, gen_max_batch=4, stream_chunk=4)
    base.update(kw)
    return LmConfig(**base)


def drain(sess):
    out = {}
    while not sess.done():
        for tag, text in sess.step():
            out[tag] = text
    for tag, text in sess._drain_all():
        out[tag] = text
    return out


# --------------------------------------------------------------- identity


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_session_token_identity_vs_dense(kv_quant):
    """The hard gate: greedy decode through the continuous-batching
    session path is token-identical between the dense and paged layouts,
    including a mid-flight admit and a cancel."""
    def run(layout):
        eng = LmEngine(tiny(layout, kv_quant))
        s = eng.start_session(["hello world this is a test"], [12],
                              temperature=0.0)
        out = {}
        for _ in range(2):
            for tag, text in s.step():
                out[tag] = text
        out_tags = s.admit(["the quick brown fox"], [8], temperature=0.0)
        assert None not in out_tags
        victim = s.admit(["to be cancelled"], [20], temperature=0.0)[0]
        assert s.cancel_tag(victim)
        out.update(drain(s))
        return out

    dense, paged = run("dense"), run("paged")
    assert dense == paged


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_generate_batch_identity_vs_dense(kv_quant):
    prompts = ["hello world this is a test", "the quick brown fox"]
    dense = LmEngine(tiny("dense", kv_quant)).generate_batch(
        prompts, [8, 8], temperature=0.0)
    paged = LmEngine(tiny("paged", kv_quant)).generate_batch(
        prompts, [8, 8], temperature=0.0)
    assert dense == paged


def test_streaming_identity_vs_dense():
    dense = "".join(LmEngine(tiny("dense")).generate_stream(
        "stream me please", 12, temperature=0.0))
    paged = "".join(LmEngine(tiny("paged")).generate_stream(
        "stream me please", 12, temperature=0.0))
    assert dense == paged


# ------------------------------------------------------------- page pool


def mk_pool(n_pages=8, page=4, registry=None):
    return PagePool(num_layers=1, n_pages=n_pages, page_tokens=page,
                    kv_heads=2, head_dim=4, dtype=np.float32,
                    quantized=False, dtype_label="f32",
                    registry=registry or Metrics())


def test_pool_alloc_release_refcount():
    pool = mk_pool(n_pages=5)
    pages = pool.alloc(3)
    assert len(set(pages)) == 3 and 0 not in pages  # scratch never handed out
    assert pool.pages_free == 1 and pool.pages_live == 3
    pool.retain(pages[0])          # second row maps the same page
    pool.release(pages[0])
    assert pool.pages_live == 3    # still refcounted by the first row
    for pid in pages:
        pool.release(pid)
    assert pool.pages_live == 0 and pool.pages_free == 4
    with pytest.raises(AssertionError):
        pool.release(pages[0])     # double release is a bug, not a no-op


def test_pool_committed_pages_retained_then_lru_evicted():
    reg = Metrics()
    pool = mk_pool(n_pages=5, registry=reg)
    a, b, c = pool.alloc(3)
    for pid in (a, b):
        pool.commit(pid)
    for pid in (a, b, c):
        pool.release(pid)
    # committed pages wait in the retained set; uncommitted went free
    assert pool.pages_retained == 2 and pool.pages_free == 2
    pool.touch(a)                  # b becomes LRU
    got = pool.alloc(3)            # demand exceeds free → evicts b
    assert len(got) == 3
    assert b in got and a not in got
    families = dict((n, v) for n, _, v in
                    dict(reg.export())["counters"]
                    if n == "kv.radix_evictions")
    assert families["kv.radix_evictions"] == 1


def test_pool_exhausted_after_evicting_everything():
    pool = mk_pool(n_pages=4)
    held = pool.alloc(3)
    with pytest.raises(PoolExhausted):
        pool.alloc(1)
    pool.release(held[0])
    assert pool.alloc(1)


# ------------------------------------------------------------ radix trie


def test_radix_match_commit_fork_and_eviction():
    pool = mk_pool(n_pages=16, page=4)
    radix = RadixCache(pool, page_tokens=4)
    P, pad = 8, 0
    row1 = np.arange(1, 9, dtype=np.int32)          # blocks (1,2,3,4),(5,6,7,8)
    pages1 = pool.alloc(2)
    logits = np.full(11, 7.0, np.float32)
    radix.commit(P, pad, row1, pages1, logits)

    # full hit: both pages + the stored logits
    m = radix.match(P, pad, row1)
    assert m.blocks == 2 and m.pages == pages1
    assert m.logits is not None and m.logits[0] == 7.0

    # COW fork at block 1: same first block, divergent second → the match
    # ends at the shared prefix and the new row commits its own page there
    row2 = row1.copy()
    row2[4:] = [9, 9, 9, 9]
    m2 = radix.match(P, pad, row2)
    assert m2.blocks == 1 and m2.pages == [pages1[0]] and m2.logits is None
    fork_page = pool.alloc(1)[0]
    radix.commit(P, pad, row2, [pages1[0], fork_page], logits)
    assert radix.match(P, pad, row2).blocks == 2

    # a different pad is a different trie: right-aligned content differs
    assert radix.match(P, pad + 1, row1).blocks == 0

    # evicting the shared ROOT page drops both branches (a block without
    # its prefix is unreachable)
    for pid in pages1 + [fork_page]:
        pool.release(pid)
    radix.forget_page(pages1[0])
    assert radix.match(P, pad, row1).blocks == 0
    assert radix.match(P, pad, row2).blocks == 0
    assert radix.stats["committed_pages"] == 0


def test_radix_session_full_hit_skips_prefill():
    """Second identical admit wires committed pages + stored logits —
    TTFT collapses to ~one decode chunk (no prefill work at all)."""
    from symbiont_tpu.obs.engine_timeline import engine_timeline

    eng = LmEngine(tiny("paged"))
    cold = drain(eng.start_session(["repeat prompt radix"], [8],
                                   temperature=0.0))
    assert eng.radix.stats["committed_pages"] > 0
    engine_timeline.clear()
    hit = drain(eng.start_session(["repeat prompt radix"], [8],
                                  temperature=0.0))
    assert list(hit.values()) == list(cold.values())
    assert eng.radix.stats["full_hits"] == 1
    summ = engine_timeline.summary()
    assert summ["decode_radix_hit_pct"] == 100.0
    # the hit admit recorded ~zero prefill: pages were wired, not computed
    assert summ["decode_prefill_ms_total"] < 5.0


def test_radix_partial_hit_shares_prefix_pages():
    eng = LmEngine(tiny("paged"))
    drain(eng.start_session(["repeat prompt radix"], [8], temperature=0.0))
    committed = eng.radix.stats["committed_pages"]
    # same length → same (P, pad) trie; divergent tail → COW fork past the
    # shared blocks (only the fresh tail blocks commit new pages)
    drain(eng.start_session(["repeat prompt RADIX"], [8], temperature=0.0))
    assert eng.radix.stats["hits"] >= 1
    assert 0 < eng.radix.stats["committed_pages"] - committed < committed


def test_cancel_returns_pages_and_gauges_reach_baseline():
    eng = LmEngine(tiny("paged", kv_radix=False))
    total = eng.pool.pages_free
    s = eng.start_session(["first prompt here"], [16], temperature=0.0)
    s.step()  # decode mid-flight so decode blocks exist beyond the prompt
    tag = s.admit(["second prompt joins"], [8], temperature=0.0)[0]
    assert eng.pool.pages_live > 0
    assert s.cancel_tag(tag)
    # cancel the remaining row too: every page must come straight back
    # (no radix → nothing is retained)
    for t in [r.tag for r in s.rows if r is not None]:
        s.cancel_tag(t)
    assert eng.pool.pages_live == 0
    assert eng.pool.pages_free == total
    assert eng.kv_row_counts() == (0, 0)


def test_update_params_clears_radix():
    eng = LmEngine(tiny("paged"))
    drain(eng.start_session(["repeat prompt radix"], [8], temperature=0.0))
    assert eng.radix.stats["committed_pages"] > 0
    eng.update_params(eng.params)
    assert eng.radix.stats["committed_pages"] == 0
    assert eng.pool.pages_retained == 0  # stale K/V freed with the trie


# ------------------------------------------------------------- admission


def test_can_admit_page_accounting_boundary():
    """The 429-vs-admit boundary under the paged layout: can_admit quotes
    actual pages needed (session span minus radix-shared blocks), not row
    capacity. A pool sized for one session rejects a second concurrent
    one, and frees unlock admission again."""
    # 1 row/session, P=16,new=32 → 3 blocks; pool of 4 usable pages fits
    # one session (3 pages) but not two
    cfg = tiny("paged", session_min_rows=1, gen_max_batch=1,
               prompt_buckets=[16], kv_pool_pages=5, kv_radix=False)
    eng = LmEngine(cfg)
    assert eng.can_admit(1, 0)
    s = eng.start_session(["hold the pool"], [32], temperature=0.0)
    assert not eng.can_admit(1, 0)  # 3 reserved + 1 free < 3 needed
    drain(s)
    assert eng.can_admit(1, 0)      # pages returned → admissible again


def test_can_admit_radix_hit_needs_fewer_pages():
    """A prompt whose pages are already committed passes admission where a
    cold prompt of the same shape is refused — the radix deduction."""
    cfg = tiny("paged", session_min_rows=1, gen_max_batch=1,
               prompt_buckets=[16], kv_pool_pages=6)
    eng = LmEngine(cfg)
    drain(eng.start_session(["warm this prompt"], [32], temperature=0.0))
    # 5 usable pages, 1 committed+retained. A session spans 3 blocks; hold
    # 3 free pages so a cold admit (3 fresh, retained evictable → avail 2)
    # fails but the warm prompt (1 shared + 2 fresh) fits.
    held = eng.pool.alloc(3)
    assert eng.can_admit(1, 0, prompts=["warm this prompt"],
                         max_new_tokens=[32])
    assert not eng.can_admit(1, 0, prompts=["cold prompt here"],
                             max_new_tokens=[32])
    for pid in held:
        eng.pool.release(pid)


# ------------------------------------------------- merge_rows three ways


@pytest.mark.parametrize("kv_quant", ["none", "int8"])
def test_merge_rows_layout_splicing(kv_quant):
    """merge_rows splices all three layouts field-wise: dense and int8 go
    through the jitted slab path, paged through scatter + row-state merge.
    The observable contract is the same for all three — a spliced row
    decodes exactly its standalone greedy output (asserted per layout via
    the session path, which exercises merge_rows directly)."""
    for layout in ("dense", "paged"):
        eng = LmEngine(tiny(layout, kv_quant))
        solo = eng.generate_batch(["the quick brown fox"], [8],
                                  temperature=0.0)[0]
        s = eng.start_session(["hello world this is a test"], [12],
                              temperature=0.0)
        s.step()
        tag = s.admit(["the quick brown fox"], [8], temperature=0.0)[0]
        out = drain(s)
        assert out[tag] == solo, layout


def test_paged_splice_rejected_when_budget_gone():
    eng = LmEngine(tiny("paged"))
    s = eng.start_session(["hello world this is a test"], [8],
                          temperature=0.0)
    prep = s.prepare_admit(["late arrival"], [32])
    while not s.done():
        s.step()
    tags = s.splice(prep)  # budget exhausted → rejected, not truncated
    assert tags == [None]
    assert eng.pool.pages_live == 0  # rejection leaked nothing
