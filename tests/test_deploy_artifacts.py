"""Deployment artifacts, validated to the offline ceiling.

No docker exists in this sandbox, so `deploy/docker-compose.yml` (parity
target: the reference's one-command 10-container bring-up,
docker-compose.yml:1-151) is validated statically instead of executed:
YAML lint, dockerfile existence + COPY-source checks, env-var wiring against
the real config layer, and the subject-topology orphan check — the exact bug
class the reference shipped (orphaned data.processed_text.tokenized,
CHANGELOG.md:57-60).
"""

import re
from pathlib import Path

import pytest

yaml = pytest.importorskip("yaml")

from symbiont_tpu.deploy import validate_compose  # noqa: E402

REPO = Path(__file__).resolve().parent.parent
COMPOSE = REPO / "deploy" / "docker-compose.yml"


def test_shipped_compose_is_clean():
    assert validate_compose(COMPOSE) == []


def test_compose_covers_reference_bringup():
    """Same one-command surface as the reference: broker (its NATS), all five
    worker roles, gateway, engine; optional external stores mirror the
    reference's Qdrant/Neo4j images."""
    doc = yaml.safe_load(COMPOSE.read_text())
    svcs = doc["services"]
    for required in ("broker", "engine", "perception", "preprocessing",
                     "vector_memory", "knowledge_graph", "text_generator",
                     "gateway"):
        assert required in svcs, required
        assert not svcs[required].get("profiles"), \
            f"{required} must be in the default profile"
    # externals are opt-in and match the reference's pinned images
    assert svcs["qdrant"]["profiles"] == ["external-stores"]
    assert svcs["qdrant"]["image"] == "qdrant/qdrant:v1.14.0"
    assert svcs["neo4j"]["image"] == "neo4j:5.18.0"
    # health-gated bring-up (the reference has no healthchecks at all in
    # v0.3.0 — SURVEY.md §5.3): workers wait for a healthy broker
    assert "healthcheck" in svcs["broker"]
    assert "healthcheck" in svcs["gateway"]
    for w in ("perception", "preprocessing", "vector_memory",
              "knowledge_graph", "text_generator", "engine"):
        assert svcs[w]["depends_on"]["broker"]["condition"] == \
            "service_healthy", w


def test_dockerfile_copy_sources_exist():
    """Every COPY source in both dockerfiles exists relative to the build
    context (repo root) — a rename breaks the build only at docker time,
    which this sandbox doesn't have, so catch it here."""
    for df in ("Dockerfile.native", "Dockerfile.engine"):
        text = (REPO / "deploy" / df).read_text()
        assert text.lstrip().startswith(("#", "ARG", "FROM"))
        assert "FROM" in text
        for m in re.finditer(r"^COPY (?!--from)([^\n]+)", text, re.M):
            *sources, _dest = m.group(1).split()
            for src in sources:
                assert (REPO / src).exists(), f"{df}: COPY source {src} missing"


def test_orphaned_subject_detected(tmp_path):
    """Removing preprocessing from the topology orphans the embeddings
    subject (vector_memory consumes it, nobody produces) — the validator
    must say so."""
    doc = yaml.safe_load(COMPOSE.read_text())
    del doc["services"]["preprocessing"]
    p = tmp_path / "compose.yml"
    p.write_text(yaml.safe_dump(doc))
    problems = validate_compose(p)
    assert any("orphaned subject: data.text.with_embeddings" in x
               for x in problems), problems
    assert any("dead-end subject: data.raw_text.discovered" in x
               for x in problems), problems


def test_env_typo_detected(tmp_path):
    doc = yaml.safe_load(COMPOSE.read_text())
    doc["services"]["engine"]["environment"].append(
        "SYMBIONT_ENGINE_MODELDIR=/oops")  # missing underscore
    p = tmp_path / "compose.yml"
    p.write_text(yaml.safe_dump(doc))
    problems = validate_compose(p)
    assert any("SYMBIONT_ENGINE_MODELDIR" in x for x in problems), problems


def test_mapping_style_environment_also_validated(tmp_path):
    """compose allows environment as a mapping ({KEY: value}) as well as a
    list (["KEY=value"]); typo detection and runner-role extraction must see
    both forms (regression: mapping form used to bypass both checks)."""
    doc = yaml.safe_load(COMPOSE.read_text())
    doc["services"]["engine"]["environment"] = {
        "SYMBIONT_ENGINE_MODELDIR": "/oops",  # typo'd key, mapping form
        "SYMBIONT_RUNNER_SERVICES": "engine",
        "SYMBIONT_BUS_URL": "symbus://broker:4233"}
    p = tmp_path / "compose.yml"
    p.write_text(yaml.safe_dump(doc))
    problems = validate_compose(p)
    assert any("SYMBIONT_ENGINE_MODELDIR" in x for x in problems), problems
    # role extraction still worked: no orphan/dead-end false positives beyond
    # the injected typo
    assert all("subject" not in x for x in problems), problems


def test_string_form_build_checks_dockerfile(tmp_path):
    """`build: <context>` shorthand must still get a Dockerfile-existence
    check (regression: only the dict form was handled)."""
    doc = yaml.safe_load(COMPOSE.read_text())
    doc["services"]["broker"]["build"] = str(tmp_path / "nodir")
    p = tmp_path / "compose.yml"
    p.write_text(yaml.safe_dump(doc))
    problems = validate_compose(p)
    assert any("broker" in x and "does not exist" in x
               for x in problems), problems


def test_bad_depends_on_and_missing_dockerfile_detected(tmp_path):
    doc = yaml.safe_load(COMPOSE.read_text())
    doc["services"]["gateway"]["depends_on"] = {"nonexistent": {
        "condition": "service_started"}}
    doc["services"]["broker"]["build"]["dockerfile"] = "deploy/Nope"
    p = tmp_path / "deploy" / "compose.yml"
    p.parent.mkdir()
    # keep the ../ build context resolvable from the tmp copy
    doc["services"]["broker"]["build"]["context"] = str(REPO)
    doc["services"]["gateway"]["build"]["context"] = str(REPO)
    p.write_text(yaml.safe_dump(doc))
    problems = validate_compose(p)
    assert any("depends_on unknown service 'nonexistent'" in x
               for x in problems), problems
    assert any("Nope does not exist" in x for x in problems), problems


def test_cli_entrypoint(capsys):
    from symbiont_tpu.deploy import main

    assert main([str(COMPOSE)]) == 0
    assert "topology OK" in capsys.readouterr().out


def test_compat_command_against_fakes(capsys):
    """The live-store compat command (VERDICT r4 next-5): drive the FULL
    `--compat qdrant=... neo4j=...` CLI against the fake servers — every
    check green end-to-end — then prove a dead target actually fails."""
    import threading
    from http.server import ThreadingHTTPServer

    from symbiont_tpu.deploy import main
    from tests.test_neo4j_backend import _FakeNeo4j
    from tests.test_qdrant_backend import _FakeQdrant

    q = ThreadingHTTPServer(("127.0.0.1", 0), _FakeQdrant)
    q.fake_store = {"collections": {}}
    n = ThreadingHTTPServer(("127.0.0.1", 0), _FakeNeo4j)
    n.state = {"statements": [], "auth": [], "paths": []}
    for srv in (q, n):
        threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        rc = main(["--compat",
                   f"qdrant=http://127.0.0.1:{q.server_address[1]}",
                   f"neo4j=http://127.0.0.1:{n.server_address[1]}"])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "all compat checks passed" in out
        assert "FAIL" not in out
        # the suite cleaned up after itself on the qdrant side
        assert q.fake_store["collections"] == {}
        # neo4j cleanup issued the namespaced DETACH DELETE
        assert any("DETACH DELETE" in st for st, _ in n.state["statements"])
    finally:
        q.shutdown()
        n.shutdown()


def test_compat_command_fails_on_dead_target(capsys):
    import socket

    from symbiont_tpu.deploy import _qdrant_compat

    with socket.socket() as s:  # grab a port nothing listens on
        s.bind(("127.0.0.1", 0))
        dead = s.getsockname()[1]
    failures = _qdrant_compat(f"http://127.0.0.1:{dead}", say=lambda *a: None)
    assert failures, "a dead qdrant target must produce failures"


def test_help_flag_exits_cleanly(capsys):
    """`--help` used to be treated as a compose path and die with a
    FileNotFoundError traceback (VERDICT r5 weak #5)."""
    from symbiont_tpu.deploy import main

    assert main(["--help"]) == 0
    assert "Usage" in capsys.readouterr().err
    assert main(["-h"]) == 0
    assert main([]) == 2  # no args still prints usage, but is an error


def test_missing_compose_path_is_friendly(capsys):
    from symbiont_tpu.deploy import main

    assert main(["no/such/compose.yml"]) == 2
    assert "does not exist" in capsys.readouterr().err


def test_compat_duplicate_target_kind_rejected(capsys):
    """`--compat qdrant=A qdrant=B` silently checked only B while the
    operator believed both were covered (ADVICE r5 finding)."""
    from symbiont_tpu.deploy import main

    rc = main(["--compat", "qdrant=http://a:6333", "qdrant=http://b:6333"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "given twice" in err and "qdrant" in err
