"""External-Qdrant backend: REST adapter against a faithful fake server.

The fake implements the four REST endpoints the adapter uses (ensure,
upsert?wait=true, search, count) with real cosine scoring, so the adapter's
request/response handling is exercised end-to-end — including through the
full service stack — without a Qdrant binary (offline test tier, SURVEY.md
§4 item 3's fake-backend strategy).
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from symbiont_tpu.config import VectorStoreConfig
from symbiont_tpu.memory.qdrant_backend import QdrantStore, make_vector_store
from symbiont_tpu.memory.vector_store import VectorStore


class _FakeQdrant(BaseHTTPRequestHandler):
    store = None  # set per-instance on the server

    def log_message(self, *a):
        pass

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0) or 0)
        return json.loads(self.rfile.read(n)) if n else {}

    def do_PUT(self):
        s = self.server.fake_store
        path = self.path.split("?")[0]
        parts = path.strip("/").split("/")
        if len(parts) == 2 and parts[0] == "collections":
            if parts[1] in s["collections"]:
                self._reply(409, {"status": {"error": "already exists"}})
                return
            cfg = self._body()
            s["collections"][parts[1]] = {
                "dim": cfg["vectors"]["size"], "points": {}}
            self._reply(200, {"result": True, "status": "ok"})
            return
        if len(parts) == 3 and parts[2] == "points":
            if s.get("fail_upserts_after_requests", -1) == 0:
                self._reply(500, {"status": {"error": "injected failure"}})
                return
            if "fail_upserts_after_requests" in s:
                s["fail_upserts_after_requests"] -= 1
            col = s["collections"][parts[1]]
            for p in self._body()["points"]:
                vec = np.asarray(p["vector"], np.float32)
                assert vec.shape == (col["dim"],)
                col["points"][str(p["id"])] = (vec, p.get("payload") or {})
            self._reply(200, {"result": {"status": "completed"}})
            return
        self._reply(404, {"status": {"error": "not found"}})

    def do_GET(self):
        s = self.server.fake_store
        parts = self.path.strip("/").split("/")
        col = s["collections"].get(parts[1]) if len(parts) == 2 else None
        if col is None:
            self._reply(404, {"status": {"error": "no collection"}})
            return
        self._reply(200, {"result": {"config": {"params": {
            "vectors": {"size": col["dim"], "distance": "Cosine"}}}}})

    def do_DELETE(self):
        s = self.server.fake_store
        parts = self.path.strip("/").split("/")
        if len(parts) == 2 and parts[0] == "collections":
            if s["collections"].pop(parts[1], None) is not None:
                self._reply(200, {"result": True, "status": "ok"})
            else:
                self._reply(404, {"status": {"error": "no collection"}})
            return
        self._reply(404, {"status": {"error": "not found"}})

    def do_POST(self):
        s = self.server.fake_store
        parts = self.path.strip("/").split("/")
        col = s["collections"].get(parts[1])
        if col is None:
            self._reply(404, {"status": {"error": "no collection"}})
            return
        if parts[-1] == "search":
            req = self._body()
            q = np.asarray(req["vector"], np.float32)
            q = q / max(float(np.linalg.norm(q)), 1e-12)
            hits = []
            for pid, (vec, payload) in col["points"].items():
                v = vec / max(float(np.linalg.norm(vec)), 1e-12)
                hits.append({"id": pid, "score": float(q @ v),
                             "payload": payload if req.get("with_payload") else None})
            hits.sort(key=lambda h: -h["score"])
            self._reply(200, {"result": hits[: req["limit"]]})
            return
        if parts[-1] == "count":
            self._reply(200, {"result": {"count": len(col["points"])}})
            return
        self._reply(404, {"status": {"error": "not found"}})


@pytest.fixture()
def fake_qdrant():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeQdrant)
    srv.fake_store = {"collections": {}}
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}", srv.fake_store
    srv.shutdown()


def _cfg(uri, dim=8):
    return VectorStoreConfig(uri=uri, dim=dim, collection="symbiont_test")


def test_ensure_upsert_search_count(fake_qdrant):
    uri, state = fake_qdrant
    store = QdrantStore(_cfg(uri), retries=2, retry_delay_s=0.05)
    store.ensure_collection()
    store.ensure_collection()  # idempotent (409 swallowed)
    assert state["collections"]["symbiont_test"]["dim"] == 8

    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(5, 8)).astype(np.float32)
    n = store.upsert([(f"p{i}", vecs[i], {"sentence_text": f"s{i}", "i": i})
                      for i in range(5)])
    assert n == 5 and store.count() == 5

    hits = store.search(vecs[3], 2)
    assert hits[0].id == "p3"  # self-match wins under cosine
    assert hits[0].payload["sentence_text"] == "s3"
    assert len(hits) == 2
    assert store.search(vecs[0], 0) == []


def test_upsert_partial_commit_marker(fake_qdrant, monkeypatch):
    """Chunked upsert is not atomic: a failure on chunk i>0 raises with
    .points_committed = how many points landed before it (documented
    partial-commit contract; retries are idempotent by id)."""
    uri, state = fake_qdrant
    store = QdrantStore(_cfg(uri), retries=2, retry_delay_s=0.05)
    store.ensure_collection()
    monkeypatch.setattr(QdrantStore, "UPSERT_CHUNK", 2)
    rng = np.random.default_rng(1)
    vecs = rng.normal(size=(5, 8)).astype(np.float32)
    state["fail_upserts_after_requests"] = 1  # chunk 0 lands, chunk 1 fails
    with pytest.raises(Exception) as ei:
        store.upsert([(f"q{i}", vecs[i], {}) for i in range(5)])
    assert ei.value.points_committed == 2
    assert state["collections"]["symbiont_test"]["points"].keys() >= {"q0", "q1"}
    del state["fail_upserts_after_requests"]
    # whole-call retry overwrites committed points idempotently
    assert store.upsert([(f"q{i}", vecs[i], {}) for i in range(5)]) == 5
    assert store.count() == 5


def test_connect_retry_then_fail():
    store = QdrantStore(_cfg("http://127.0.0.1:1"), retries=2,
                        retry_delay_s=0.01)
    with pytest.raises(ConnectionError, match="unreachable"):
        store.ensure_collection()


def test_backend_selection():
    assert isinstance(make_vector_store(_cfg(None)), VectorStore)
    assert isinstance(make_vector_store(_cfg("http://127.0.0.1:1")), QdrantStore)


def test_full_stack_over_external_qdrant(fake_qdrant, tmp_path):
    """The complete pipeline (ingest → embed → upsert → 2-hop search) with
    vector memory backed by the external Qdrant instead of the embedded
    store — the reference-migration deployment (QDRANT_URI)."""
    import asyncio

    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.config import (
        ApiConfig,
        EngineConfig,
        GraphStoreConfig,
        SymbiontConfig,
        TextGeneratorConfig,
    )
    from symbiont_tpu.runner import SymbiontStack
    from tests.test_e2e_pipeline import _fake_fetcher, _http, _wait_until

    uri, _ = fake_qdrant
    cfg = SymbiontConfig(
        engine=EngineConfig(embedding_dim=32, length_buckets=[16, 32],
                            batch_buckets=[2, 8], max_batch=8, dtype="float32",
                            data_parallel=False, flush_deadline_ms=2.0),
        vector_store=_cfg(uri, dim=32),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(
            markov_state_path=str(tmp_path / "markov.json")),
        # external corpus → no fused subject served; skip the probe
        api=ApiConfig(host="127.0.0.1", port=0, fused_search=False),
    )

    async def scenario():
        stack = SymbiontStack(cfg, bus=InprocBus(), fetcher=_fake_fetcher)
        await stack.start()
        try:
            # the runner wraps the external backend in the resilience
            # plane's breaker + WAL-spill adapter (docs/RESILIENCE.md)
            from symbiont_tpu.resilience.stores import (
                ResilientVectorStore,
            )

            assert isinstance(stack.vector_store, ResilientVectorStore)
            assert isinstance(stack.vector_store.inner, QdrantStore)
            loop = asyncio.get_running_loop()
            status, _ = await loop.run_in_executor(None, lambda: _http(
                "POST", stack.api.port, "/api/submit-url",
                {"url": "http://example.com/doc1"}))
            assert status == 200
            ok = await _wait_until(lambda: stack.vector_store.count() >= 3)
            assert ok, f"pipeline stalled; count={stack.vector_store.count()}"
            status, body = await loop.run_in_executor(None, lambda: _http(
                "POST", stack.api.port, "/api/search/semantic",
                {"query_text": "matrix multiplication", "top_k": 2}))
            assert status == 200, body
            assert len(body["results"]) == 2
            assert body["results"][0]["payload"]["sentence_text"]
        finally:
            await stack.stop()

    asyncio.run(scenario())


def test_dim_mismatch_fails_fast(fake_qdrant):
    uri, _ = fake_qdrant
    QdrantStore(_cfg(uri, dim=8), retries=1, retry_delay_s=0.01).ensure_collection()
    store16 = QdrantStore(_cfg(uri, dim=16), retries=1, retry_delay_s=0.01)
    with pytest.raises(ValueError, match="dim=8"):
        store16.ensure_collection()


def test_non_http_uri_rejected():
    with pytest.raises(ValueError, match="REST endpoint"):
        QdrantStore(_cfg("grpc://host:6334"))
