"""Resilience-plane units: fault plan determinism, circuit breaker state
machine, DLQ quarantine store, store wrappers (spill + replay), loop
supervisor, retry jitter/async, handler timeout + retry, durable in-proc
streams. The end-to-end zero-loss proofs live in tests/test_chaos.py."""

import asyncio
import random

import pytest

from symbiont_tpu.bus.core import Msg
from symbiont_tpu.bus.inproc import InprocBus
from symbiont_tpu.resilience.breaker import (
    CircuitBreaker,
    CircuitOpenError,
)
from symbiont_tpu.resilience.dlq import DeadLetterStore
from symbiont_tpu.resilience.faults import FaultInjected, FaultPlan, FaultRule
from symbiont_tpu.resilience.stores import (
    ResilientGraphStore,
    ResilientVectorStore,
)
from symbiont_tpu.resilience.supervisor import jittered, supervise
from symbiont_tpu.services.base import Service
from symbiont_tpu.utils.retry import connect_retry, connect_retry_async
from symbiont_tpu.utils.telemetry import metrics


def _run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------- fault plan

def test_fault_rule_positional_determinism():
    plan = FaultPlan(seed=1, rules=[
        FaultRule(seam="handler", kind="error", match="svc:*",
                  after=1, times=2)])
    # op 0 skipped (after=1), ops 1-2 fire, op 3+ exhausted
    fired = [plan.check("handler", "svc:a") is not None for _ in range(5)]
    assert fired == [False, True, True, False, False]
    assert plan.fired[("handler", "error")] == 2
    # non-matching seam/key never counts
    assert plan.check("store.upsert", "svc:a") is None
    assert plan.check("handler", "other:a") is None


def test_fault_plan_seeded_probability_reproducible():
    def transcript(seed):
        plan = FaultPlan(seed=seed, rules=[
            FaultRule(seam="bus.publish", kind="drop", times=0, prob=0.5)])
        return [plan.check("bus.publish", "s") is not None
                for _ in range(32)]

    assert transcript(7) == transcript(7)
    assert transcript(7) != transcript(8)  # astronomically unlikely to tie


def test_fault_kinds_raise_or_sleep():
    plan = FaultPlan(rules=[
        FaultRule(seam="store.upsert", kind="error", times=1),
        FaultRule(seam="store.upsert", kind="reset", times=1),
    ])
    with pytest.raises(FaultInjected):
        plan.sync_fault("store.upsert", "x")
    with pytest.raises(ConnectionResetError):
        plan.sync_fault("store.upsert", "x")
    assert plan.sync_fault("store.upsert", "x") is None  # exhausted

    async def hang():
        p = FaultPlan(rules=[FaultRule(seam="handler", kind="hang",
                                       delay_s=0.01, times=1)])
        rule = await p.async_fault("handler", "k")
        assert rule is not None and rule.kind == "hang"

    _run(hang())


def test_fault_plan_activation_scoped():
    from symbiont_tpu.resilience import faults

    assert faults.active_plan() is None
    plan = FaultPlan()
    with plan.activate():
        assert faults.active_plan() is plan
        inner = FaultPlan()
        with inner.activate():
            assert faults.active_plan() is inner
        assert faults.active_plan() is plan
    assert faults.active_plan() is None


def test_fault_rule_rejects_unknown_kind():
    with pytest.raises(ValueError):
        FaultRule(seam="handler", kind="explode")


# --------------------------------------------------------- circuit breaker

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_opens_half_opens_and_recovers():
    clock = _Clock()
    br = CircuitBreaker("t", failure_threshold=3, reset_timeout_s=10.0,
                        clock=clock)
    boom = lambda: (_ for _ in ()).throw(RuntimeError("down"))  # noqa: E731
    for _ in range(3):
        with pytest.raises(RuntimeError):
            br.call(boom)
    assert br.state == "open"
    # open: refuse FAST with CircuitOpenError (a ConnectionError subclass)
    with pytest.raises(CircuitOpenError):
        br.call(lambda: "never runs")
    assert issubclass(CircuitOpenError, ConnectionError)
    # before the window: still open; after: one half-open probe admitted
    clock.t = 9.9
    assert not br.allow()
    clock.t = 10.1
    assert br.state == "half_open"
    assert br.allow()
    assert not br.allow()  # second concurrent probe refused
    br.record_success()
    assert br.state == "closed"
    assert br.allow()


def test_breaker_half_open_failure_reopens():
    clock = _Clock()
    br = CircuitBreaker("t2", failure_threshold=1, reset_timeout_s=5.0,
                        clock=clock)
    with pytest.raises(RuntimeError):
        br.call(lambda: (_ for _ in ()).throw(RuntimeError()))
    clock.t = 6.0
    with pytest.raises(RuntimeError):  # the probe fails
        br.call(lambda: (_ for _ in ()).throw(RuntimeError()))
    assert br.state == "open"
    assert br.retry_in_s() == pytest.approx(5.0, abs=0.01)


def test_breaker_fatal_exceptions_bypass_accounting():
    br = CircuitBreaker("t3", failure_threshold=1)
    with pytest.raises(ValueError):
        br.call(lambda: (_ for _ in ()).throw(ValueError("config")),
                fatal=(ValueError,))
    assert br.state == "closed"  # config errors never trip the breaker


# -------------------------------------------------------------------- DLQ

def test_dlq_bounded_with_eviction_and_replay():
    store = DeadLetterStore(capacity=2)
    for i in range(3):
        store.quarantine(f"s.{i}", f"payload{i}".encode(), {"h": "v"},
                         reason="max_deliver", deliveries=5)
    assert len(store) == 2  # oldest evicted
    subjects = [e.subject for e in store.list()]
    assert subjects == ["s.1", "s.2"]
    entry = store.list()[0]
    s = entry.summary()
    assert s["data_preview"] == "payload1"
    import base64

    assert base64.b64decode(s["data_b64"]) == b"payload1"

    class _FakeBus:
        def __init__(self):
            self.published = []

        async def publish(self, subject, data, headers=None):
            self.published.append((subject, data, headers))

    async def scenario():
        bus = _FakeBus()
        n = await store.replay(bus, entry.id)
        assert n == 1 and len(store) == 1
        subject, data, headers = bus.published[0]
        assert subject == "s.1" and data == b"payload1"
        assert headers["X-Symbiont-Replayed"] == "1"
        # replay-all drains the rest
        assert await store.replay(bus) == 1
        assert len(store) == 0

    _run(scenario())


# ---------------------------------------------------------- store wrappers

class _FlakyVectorStore:
    """Fails the first `fail_n` upserts, then recovers."""

    supports_fused = False

    def __init__(self, fail_n=0):
        self.fail_n = fail_n
        self.calls = 0
        self.points = {}

    def ensure_collection(self, dim=None):
        pass

    def upsert(self, points):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise ConnectionError("backend down")
        for pid, vec, payload in points:
            self.points[pid] = (vec, payload)
        return len(points)

    def search(self, query, top_k):
        return []

    def count(self):
        return len(self.points)


def test_vector_wrapper_spills_and_replays(tmp_path):
    inner = _FlakyVectorStore(fail_n=2)
    br = CircuitBreaker("vtest", failure_threshold=10, reset_timeout_s=0.01)
    spill = tmp_path / "spill.jsonl"
    store = ResilientVectorStore(inner, breaker=br, spill_path=str(spill))
    # outage: both writes report success (spilled), nothing reaches inner
    assert store.upsert([("a", [1.0], {"k": 1})]) == 1
    assert store.upsert([("b", [2.0], {"k": 2})]) == 1
    assert inner.count() == 0 and store.spill_pending() == 2
    assert spill.exists()
    # recovery: the next write replays the spill FIRST, then lands itself
    assert store.upsert([("c", [3.0], {"k": 3})]) == 1
    assert inner.count() == 3 and store.spill_pending() == 0
    assert list(inner.points) == ["a", "b", "c"]  # rough arrival order kept
    assert not spill.exists()


def test_vector_wrapper_spill_survives_restart(tmp_path):
    spill = tmp_path / "spill.jsonl"
    down = ResilientVectorStore(_FlakyVectorStore(fail_n=99),
                                breaker=CircuitBreaker(
                                    "vp", failure_threshold=1,
                                    reset_timeout_s=30.0),
                                spill_path=str(spill))
    down.upsert([("a", [1.0], {})])
    assert down.spill_pending() == 1
    # process restart during the outage: the journal reloads from disk
    healthy_inner = _FlakyVectorStore()
    revived = ResilientVectorStore(healthy_inner,
                                   breaker=CircuitBreaker("vp2"),
                                   spill_path=str(spill))
    assert revived.spill_pending() == 1
    assert revived.replay_spill() == 1
    assert healthy_inner.count() == 1 and revived.spill_pending() == 0


def test_vector_wrapper_open_breaker_read_fallback():
    class _Hits:
        def search(self, query, top_k):
            return ["local-hit"]

    br = CircuitBreaker("vr", failure_threshold=1, reset_timeout_s=60.0)
    store = ResilientVectorStore(_FlakyVectorStore(fail_n=99), breaker=br,
                                 fallback=_Hits())
    br.record_failure()  # threshold 1 -> open
    assert store.search([1.0], 3) == ["local-hit"]
    no_fallback = ResilientVectorStore(_FlakyVectorStore(), breaker=br)
    with pytest.raises(CircuitOpenError):
        no_fallback.search([1.0], 3)


def test_vector_wrapper_config_errors_propagate():
    class _DimMismatch(_FlakyVectorStore):
        def upsert(self, points):
            raise ValueError("dim mismatch")

    store = ResilientVectorStore(_DimMismatch(), breaker=CircuitBreaker("vc"))
    with pytest.raises(ValueError):
        store.upsert([("a", [1.0], {})])
    assert store.spill_pending() == 0  # never spilled: replay can't fix it


def test_graph_wrapper_spills_and_replays(tmp_path):
    from symbiont_tpu.schema import TokenizedTextMessage

    class _FlakyGraph:
        def __init__(self, fail_n):
            self.fail_n = fail_n
            self.calls = 0
            self.saved = []

        def ensure_schema(self):
            pass

        def save_tokenized(self, msg):
            self.calls += 1
            if self.calls <= self.fail_n:
                raise ConnectionError("neo4j down")
            self.saved.append(msg.original_id)
            return 1

        def counts(self):
            return {"Document": len(self.saved)}

        def close(self):
            pass

    inner = _FlakyGraph(fail_n=1)
    store = ResilientGraphStore(inner, breaker=CircuitBreaker(
        "gtest", failure_threshold=10),
        spill_path=str(tmp_path / "graph.spill.jsonl"))

    def doc(i):
        return TokenizedTextMessage(original_id=f"d{i}", source_url="u",
                                    tokens=["a"], sentences=["a."],
                                    timestamp_ms=1)

    assert store.save_tokenized(doc(0)) == -1  # spilled
    assert store.spill_pending() == 1
    assert store.save_tokenized(doc(1)) == 1  # replays d0 first
    assert inner.saved == ["d0", "d1"]
    assert store.spill_pending() == 0


# -------------------------------------------------------------- supervisor

def test_supervisor_restarts_crashed_loop_until_clean_exit():
    async def scenario():
        runs = []

        async def loop():
            runs.append(1)
            if len(runs) < 3:
                raise RuntimeError("loop died")
            return  # clean exit on the 3rd run

        before = metrics.get("service.loop_restarts",
                             labels={"service": "t", "task": "t:x"})
        await supervise(loop, name="t:x", backoff_base_s=0.01,
                        backoff_max_s=0.02, labels={"service": "t"},
                        rng=random.Random(0))
        assert len(runs) == 3
        after = metrics.get("service.loop_restarts",
                            labels={"service": "t", "task": "t:x"})
        assert after - before == 2

    _run(scenario())


def test_supervisor_stops_when_no_longer_wanted():
    async def scenario():
        wanted = [True]
        runs = []

        async def loop():
            runs.append(1)
            wanted[0] = False
            raise RuntimeError("died while stopping")

        await supervise(loop, name="t:y", backoff_base_s=0.01,
                        still_wanted=lambda: wanted[0])
        assert len(runs) == 1  # no resurrection after stop

    _run(scenario())


def test_jittered_bounds():
    rng = random.Random(3)
    for _ in range(100):
        v = jittered(1.0, rng)
        assert 0.5 <= v <= 1.0


# ------------------------------------------------------------------ retry

def test_connect_retry_jitter_and_async():
    sleeps = []

    import symbiont_tpu.utils.retry as retry_mod

    orig_sleep = retry_mod.time.sleep
    retry_mod.time.sleep = sleeps.append
    try:
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("not yet")
            return "up"

        assert connect_retry(flaky, retries=5, delay_s=1.0, what="svc",
                             jitter=True, rng=random.Random(1)) == "up"
    finally:
        retry_mod.time.sleep = orig_sleep
    assert len(sleeps) == 2
    assert all(0.5 <= s <= 1.0 for s in sleeps)  # full-jitter window

    async def scenario():
        calls = []

        async def flaky_async():
            calls.append(1)
            if len(calls) < 2:
                raise ConnectionError("not yet")
            return "up"

        out = await connect_retry_async(flaky_async, retries=3,
                                        delay_s=0.01, what="svc",
                                        jitter=True)
        assert out == "up"

        async def hopeless():
            raise ConnectionError("never")

        with pytest.raises(ConnectionError):
            await connect_retry_async(hopeless, retries=2, delay_s=0.01,
                                      what="svc2")

    _run(scenario())


# ------------------------------------------- service timeout/retry/stop

class _OneShotService(Service):
    name = "oneshot"

    def __init__(self, bus, handler, subject="t.x", durable_stream=None):
        super().__init__(bus)
        self._handler = handler
        self._subject = subject
        self._durable = durable_stream

    async def _setup(self):
        await self._subscribe_loop(self._subject, self._handler,
                                   queue="q.oneshot",
                                   durable_stream=self._durable)


def test_handler_timeout_cancels_and_frees_slot():
    async def scenario():
        bus = InprocBus()
        cancelled = []

        async def hang_forever(msg):
            try:
                await asyncio.sleep(60)
            except asyncio.CancelledError:
                cancelled.append(1)
                raise

        svc = _OneShotService(bus, hang_forever)
        svc.handler_timeout_s = 0.1
        before = metrics.get("bus.handler_timeout",
                             labels={"service": "oneshot", "subject": "t.x"})
        await svc.start()
        await bus.publish("t.x", b"x")
        for _ in range(100):
            if cancelled:
                break
            await asyncio.sleep(0.01)
        assert cancelled, "handler was not cancelled at the deadline"
        after = metrics.get("bus.handler_timeout",
                            labels={"service": "oneshot", "subject": "t.x"})
        assert after - before == 1
        # the semaphore slot came back: no hung-handler pinning
        assert svc._sem._value == 32
        await svc.stop()
        await bus.close()

    _run(scenario())


def test_handler_retry_with_backoff_eventually_succeeds():
    async def scenario():
        bus = InprocBus()
        attempts = []
        done = asyncio.Event()

        async def flaky(msg):
            attempts.append(1)
            if len(attempts) < 3:
                raise RuntimeError("transient")
            done.set()

        svc = _OneShotService(bus, flaky)
        svc.handler_retries = 3
        svc.handler_backoff_base_s = 0.01
        svc.handler_backoff_max_s = 0.02
        await svc.start()
        await bus.publish("t.x", b"x")
        await asyncio.wait_for(done.wait(), 5)
        assert len(attempts) == 3
        await svc.stop()
        await bus.close()

    _run(scenario())


def test_stop_awaits_cancelled_loop_tasks():
    async def scenario():
        bus = InprocBus()

        async def noop(msg):
            pass

        svc = _OneShotService(bus, noop)
        await svc.start()
        loops = list(svc._loops)
        assert loops
        await svc.stop()
        # gathered, not just cancelled: every loop task is DONE now, so no
        # "Task was destroyed but it is pending" at interpreter exit
        assert all(t.done() for t in loops)
        assert svc._loops == []
        await bus.close()

    _run(scenario())


def test_subscribe_loop_is_supervised():
    async def scenario():
        bus = InprocBus()
        handled = asyncio.Event()

        async def ok(msg):
            handled.set()

        svc = _OneShotService(bus, ok)
        svc.supervisor_backoff_base_s = 0.01
        svc.supervisor_backoff_max_s = 0.02
        await svc.start()
        # sabotage the semaphore so the DISPATCH LOOP itself (not the
        # handler) crashes on the next message — the pre-resilience loop
        # died here silently, never consuming again
        real_sem = svc._sem

        class _Bomb:
            async def acquire(self):
                svc._sem = real_sem  # heal for the restarted loop
                raise RuntimeError("loop body bomb")

        svc._sem = _Bomb()
        await bus.publish("t.x", b"boom")
        await asyncio.sleep(0.1)
        # supervised restart: a later message is still consumed
        await bus.publish("t.x", b"fine")
        await asyncio.wait_for(handled.wait(), 5)
        await svc.stop()
        await bus.close()

    _run(scenario())


# -------------------------------------------- durable in-proc bus (units)

def test_inproc_durable_capture_ack_redeliver():
    async def scenario():
        bus = InprocBus()
        await bus.add_stream("ingest", ["data.raw_text.>"], ack_wait_s=0.15,
                             max_deliver=3)
        # capture with NO consumer connected (at-least-once)
        await bus.publish("data.raw_text.discovered", b"one")
        await bus.publish("data.other", b"not captured")
        sub = await bus.durable_subscribe("ingest", "workers")
        m = await sub.next(2.0)
        assert m is not None and m.data == b"one"
        assert m.subject == "data.raw_text.discovered"
        assert m.headers["X-Symbus-Stream"] == "ingest"
        assert m.headers["X-Symbus-Deliveries"] == "1"
        # unacked -> redelivers after ack_wait
        r = await sub.next(2.0)
        assert r is not None and int(r.headers["X-Symbus-Deliveries"]) == 2
        await bus.ack(r)
        assert await sub.next(0.4) is None  # settled, no more deliveries
        stats = await bus.stream_stats()
        g = stats["ingest"]["groups"]["workers"]
        assert g["ack_floor"] == 1 and g["inflight"] == 0
        await bus.close()

    _run(scenario())


def test_inproc_durable_group_shares_and_filter_auto_acks():
    async def scenario():
        bus = InprocBus()
        await bus.add_stream("p", ["a.x", "a.y"], ack_wait_s=5.0)
        got_x, got_y = [], []
        sub_x = await bus.durable_subscribe("p", "gx", filter_subject="a.x")
        sub_y = await bus.durable_subscribe("p", "gy", filter_subject="a.y")
        for i in range(4):
            await bus.publish("a.x" if i % 2 == 0 else "a.y",
                              str(i).encode())
        for _ in range(2):
            mx = await sub_x.next(2.0)
            assert mx is not None and mx.subject == "a.x"
            got_x.append(mx)
            await bus.ack(mx)
            my = await sub_y.next(2.0)
            assert my is not None and my.subject == "a.y"
            got_y.append(my)
            await bus.ack(my)
        # each group's filter auto-acked the other's subjects: floors at 4
        stats = await bus.stream_stats()
        assert stats["p"]["groups"]["gx"]["ack_floor"] == 4
        assert stats["p"]["groups"]["gy"]["ack_floor"] == 4
        # two members of ONE group share (queue-group semantics)
        a = await bus.durable_subscribe("p", "shared")
        b = await bus.durable_subscribe("p", "shared")
        for i in range(6):
            await bus.publish("a.x", str(i).encode())
        seen_a = seen_b = 0
        for _ in range(60):
            ma = await a.next(0.05)
            if ma is not None:
                seen_a += 1
                await bus.ack(ma)
            mb = await b.next(0.05)
            if mb is not None:
                seen_b += 1
                await bus.ack(mb)
            if seen_a + seen_b >= 6:
                break
        assert seen_a + seen_b == 6
        assert seen_a and seen_b  # both replicas participated
        await bus.close()

    _run(scenario())


def test_inproc_durable_mismatched_filter_rejected():
    async def scenario():
        bus = InprocBus()
        await bus.add_stream("s", ["a.>"])
        await bus.durable_subscribe("s", "g", filter_subject="a.x")
        with pytest.raises(RuntimeError):
            await bus.durable_subscribe("s", "g", filter_subject="a.y")
        with pytest.raises(RuntimeError):
            await bus.durable_subscribe("nope", "g")
        await bus.close()

    _run(scenario())


def test_handler_raised_timeout_is_a_failure_not_a_deadline():
    """A TimeoutError raised BY the handler (bus request timeout, socket
    read timeout — on 3.11+ asyncio.TimeoutError IS builtin TimeoutError)
    must hit the retry/accounting path; only OUR wait_for cancellation is
    the deadline. Regression: the first cut matched on exception type and
    misclassified both."""

    async def scenario(timeout_s):
        bus = InprocBus()
        attempts = []
        done = asyncio.Event()

        async def raises_timeout(msg):
            attempts.append(1)
            if len(attempts) < 3:
                raise TimeoutError("downstream request timed out")
            done.set()

        svc = _OneShotService(bus, raises_timeout)
        svc.handler_timeout_s = timeout_s
        svc.handler_retries = 3
        svc.handler_backoff_base_s = 0.01
        svc.handler_backoff_max_s = 0.02
        before = metrics.get("bus.handler_timeout",
                             labels={"service": "oneshot", "subject": "t.x"})
        await svc.start()
        await bus.publish("t.x", b"x")
        await asyncio.wait_for(done.wait(), 5)
        assert len(attempts) == 3  # retried like any transient failure
        after = metrics.get("bus.handler_timeout",
                            labels={"service": "oneshot", "subject": "t.x"})
        assert after == before  # never accounted as a deadline timeout
        await svc.stop()
        await bus.close()

    _run(scenario(0.0))   # timeout disabled
    _run(scenario(5.0))   # timeout armed but not the one that fired


def test_inproc_durable_eviction_settles_for_groups():
    """Retention eviction must settle the evicted seq in every group: an
    unsettled hole below the floor would pin group.acked forever and
    freeze the ack floor (regression test for exactly that)."""
    import symbiont_tpu.bus.inproc as inproc_mod

    async def scenario():
        bus = InprocBus()
        await bus.add_stream("ev", ["e.x"], ack_wait_s=5.0)
        sub = await bus.durable_subscribe("ev", "g", maxsize=4)
        orig = inproc_mod.MAX_RETAINED
        inproc_mod.MAX_RETAINED = 4
        try:
            for i in range(10):  # 6 oldest evicted before any delivery
                await bus.publish("e.x", str(i).encode())
        finally:
            inproc_mod.MAX_RETAINED = orig
        got = []
        for _ in range(4):
            m = await sub.next(2.0)
            assert m is not None
            got.append(int(m.data))
            await bus.ack(m)
        assert got == [6, 7, 8, 9]  # the retained tail, in order
        stats = await bus.stream_stats()
        g = stats["ev"]["groups"]["g"]
        # the floor marched THROUGH the evicted seqs to the end: no
        # permanent hole, no unbounded acked set
        assert g["ack_floor"] == 10
        group = bus._streams["ev"].groups["g"]
        assert not group.acked and not group.state
        await bus.close()

    _run(scenario())


def test_inproc_durable_settled_messages_gc():
    async def scenario():
        bus = InprocBus()
        await bus.add_stream("gc", ["g.x"], ack_wait_s=5.0)
        sub = await bus.durable_subscribe("gc", "g")
        for i in range(10):
            await bus.publish("g.x", str(i).encode())
        for _ in range(10):
            m = await sub.next(2.0)
            await bus.ack(m)
        for _ in range(100):
            stats = await bus.stream_stats()
            if stats["gc"]["messages"] == 0:
                break
            await asyncio.sleep(0.01)
        # fully settled history is GC'd; the seq counter keeps advancing
        assert stats["gc"]["messages"] == 0
        assert stats["gc"]["last_seq"] == 10
        await bus.close()

    _run(scenario())
