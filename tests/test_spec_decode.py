"""Batched speculative decoding (docs/SPECULATIVE.md): drafter/target
compat validation, token-identity across layouts/quant, per-row variable
advance under splice/cancel, journal resume, and the fallback ladder.

Fast tier: validate_spec_draft (jax-free) + config knobs. Slow tier (jax):
the identity/compat matrix the ISSUE's hard gate names — spec-on greedy ==
spec-off greedy across {dense,paged} × {kv_quant none,int8}, sampled
resume-after-kill, heterogeneous accepts with mid-flight admission, and
the drafter-divergence / PoolExhausted degradations."""

import json

import pytest

from symbiont_tpu.config import LmConfig, load_config, validate_spec_draft

# ------------------------------------------------- compat validation (fast)


def _model_dir(tmp_path, name, vocab=256, tok_bytes=None):
    d = tmp_path / name
    d.mkdir()
    (d / "config.json").write_text(json.dumps({"vocab_size": vocab}))
    if tok_bytes is not None:
        (d / "tokenizer.json").write_bytes(tok_bytes)
    return str(d)


def test_validate_spec_draft_accepts_matching_pair(tmp_path):
    t = _model_dir(tmp_path, "target", vocab=512, tok_bytes=b"{tok}")
    d = _model_dir(tmp_path, "draft", vocab=512, tok_bytes=b"{tok}")
    validate_spec_draft(t, d)  # no raise


def test_validate_spec_draft_rejects_vocab_mismatch(tmp_path):
    t = _model_dir(tmp_path, "target", vocab=512)
    d = _model_dir(tmp_path, "draft", vocab=300)
    with pytest.raises(ValueError, match="vocab mismatch"):
        validate_spec_draft(t, d)


def test_validate_spec_draft_rejects_tokenizer_mismatch(tmp_path):
    t = _model_dir(tmp_path, "target", tok_bytes=b"{tok-a}")
    d = _model_dir(tmp_path, "draft", tok_bytes=b"{tok-b}")
    with pytest.raises(ValueError, match="tokenizer mismatch"):
        validate_spec_draft(t, d)


def test_validate_spec_draft_missing_config_is_clear(tmp_path):
    t = _model_dir(tmp_path, "target")
    with pytest.raises(ValueError, match="cannot read"):
        validate_spec_draft(t, str(tmp_path / "nope"))


def test_spec_knobs_env_overrides():
    cfg = load_config(env={"SYMBIONT_LM_SPEC_DRAFT_MODEL": "/models/draft",
                           "SYMBIONT_LM_SPEC_K": "12"})
    assert cfg.lm.spec_draft_model == "/models/draft"
    assert cfg.lm.spec_k == 12
    with pytest.raises(ValueError, match="spec_k"):
        load_config(env={"SYMBIONT_LM_SPEC_K": "0"})


# ------------------------------------------------------- jax fixtures (slow)

TINY = dict(enabled=True, arch="llama", hidden_size=32, num_layers=2,
            num_heads=4, intermediate_size=64, max_positions=256,
            dtype="float32", prompt_buckets=[16], new_token_buckets=[32],
            temperature=0.0, spec_k=4, stream_chunk=4, kv_page_tokens=16,
            gen_max_batch=8, session_min_rows=4)


def _engine(**kw):
    from symbiont_tpu.engine.lm import LmEngine

    return LmEngine(LmConfig(**dict(TINY, **kw)))


def _spec_engine(**kw):
    """Engine + an injected drafter that IS the target (same random init:
    same cfg ⇒ same seed ⇒ same params) — acceptance is 100% and greedy
    identity isolates the spec plumbing from drafter quality."""
    from symbiont_tpu.engine.lm import LmEngine

    donor = _engine(**kw)
    return LmEngine(LmConfig(**dict(TINY, **kw)), draft_params=donor.params,
                    draft_model_cfg=donor.model_cfg)


def _stream(eng, prompt, n, **kw):
    return "".join(eng.generate_stream(prompt, n, **kw))


def _session(eng, prompts, want, **kw):
    s = eng.start_session(prompts, want, **kw)
    done = []
    while not s.done():
        done += s.step()
    return sorted(done)


def _corrupting(real_draft, wrong_from=2):
    """Wrap draft_chunk to corrupt proposals from slot `wrong_from` on —
    forces PARTIAL acceptance so rejected slots become kv_valid holes that
    every later window must mask correctly."""

    def fn(draft_params, d_cache, pending, cur_pos, done, kv_valid, dcfg,
           spec_k):
        import jax.numpy as jnp

        cache, drafts = real_draft(draft_params, d_cache, pending, cur_pos,
                                   done, kv_valid, dcfg, spec_k)
        bad = (drafts + 1) % dcfg.vocab_size
        mix = jnp.where(jnp.arange(spec_k)[None, :] >= wrong_from,
                        bad, drafts)
        return cache, mix

    return fn


# ------------------------------------------------------ engine boot (slow)


@pytest.mark.slow
def test_missing_draft_dir_degrades_to_spec_off(tmp_path, caplog):
    eng = _engine(spec_draft_model=str(tmp_path / "not-there"))
    assert eng._draft is None  # one warning, engine decodes plain
    assert isinstance(eng.generate("hello", 8), str)


@pytest.mark.slow
def test_injected_drafter_vocab_mismatch_fails_fast():
    import dataclasses

    from symbiont_tpu.engine.lm import LmEngine

    donor = _engine()
    bad_cfg = dataclasses.replace(
        donor.model_cfg, vocab_size=donor.model_cfg.vocab_size + 1)
    with pytest.raises(ValueError, match="vocab"):
        LmEngine(LmConfig(**TINY), draft_params=donor.params,
                 draft_model_cfg=bad_cfg)


# ------------------------------------------- the identity hard gate (slow)


@pytest.mark.slow
@pytest.mark.parametrize("layout,kv_quant", [("dense", "none"),
                                             ("dense", "int8"),
                                             ("paged", "none"),
                                             ("paged", "int8")])
def test_spec_greedy_token_identical(layout, kv_quant):
    """ISSUE 19 hard gate: greedy spec-on == greedy spec-off, stream and
    batch session, across every KV layout × quantization pair."""
    kw = dict(kv_layout=layout, kv_quant=kv_quant)
    off, on = _engine(**kw), _spec_engine(**kw)
    prompt = "the quick brown fox jumps"
    assert _stream(off, prompt, 24) == _stream(on, prompt, 24)
    prompts = ["hello", "a much longer prompt with many words", ""]
    assert (_session(off, prompts, [20, 20, 20], temperature=0.0)
            == _session(on, prompts, [20, 20, 20], temperature=0.0))
    assert on._spec_proposed > 0
    assert on._spec_accepted == on._spec_proposed  # drafter IS the target


@pytest.mark.slow
@pytest.mark.parametrize("layout,kv_quant", [("dense", "none"),
                                             ("paged", "int8")])
def test_spec_partial_accept_token_identical(monkeypatch, layout, kv_quant):
    """Divergent drafter ⇒ heterogeneous per-row accepts and permanent
    kv_valid holes — output must STILL match spec-off exactly."""
    import symbiont_tpu.models.gpt as gpt_mod

    kw = dict(kv_layout=layout, kv_quant=kv_quant)
    off, on = _engine(**kw), _spec_engine(**kw)
    prompt = "the quick brown fox jumps"
    prompts = ["hello", "a much longer prompt with many words", ""]
    ref_s = _stream(off, prompt, 24)
    ref_b = _session(off, prompts, [20, 20, 20], temperature=0.0)
    monkeypatch.setattr(gpt_mod, "draft_chunk",
                        _corrupting(gpt_mod.draft_chunk))
    assert _stream(on, prompt, 24) == ref_s
    assert _session(on, prompts, [20, 20, 20], temperature=0.0) == ref_b
    assert 0 < on._spec_accepted < on._spec_proposed


@pytest.mark.slow
def test_spec_admit_and_cancel_mid_flight():
    """Newcomers splice into a speculating session (drafter rows ride the
    same row_map); a cancelled row frees immediately. Output for surviving
    rows matches the spec-off engine's."""

    def drive(eng):
        s = eng.start_session(["alpha prompt", "beta words"], [20, 20],
                              temperature=0.0)
        out = list(s.step())
        tags = s.admit(["gamma joins late"], [12], temperature=0.0)
        out += s.step()
        assert s.cancel_tag(tags[0])  # newcomer leaves before finishing
        while not s.done():
            out += s.step()
        return sorted(out)

    assert drive(_engine()) == drive(_spec_engine())


@pytest.mark.slow
def test_spec_sampled_resume_after_kill_token_identical(tmp_path):
    """Sampled spec-on stream killed at a chunk boundary resumes token-
    identically through the genlog journal: the tail's base key + split
    count re-derive the PRNG chain, and the `spec` flag re-ingests the
    pending token (journal records accepted tokens only)."""
    from symbiont_tpu.resilience.genlog import GenJournal

    prompt = "sampling is stochastic"
    kw = dict(temperature=0.8, seed=7)
    ref = _stream(_spec_engine(**kw), prompt, 24, temperature=0.8, top_k=8)

    eng = _spec_engine(**kw)
    eng.journal = journal = GenJournal(tmp_path / "s.genlog")
    got = []
    gen = eng.generate_stream(prompt, 24, temperature=0.8, top_k=8,
                              task_id="kill-me")
    for delta in gen:
        got.append(delta)
        if len(got) >= 2:
            gen.close()  # the SIGKILL stand-in at a chunk boundary
            break
    rec = journal.live_tails()["kill-me"]
    assert rec["key"] is not None and rec["key_splits"] >= 1

    adopter = _spec_engine(**dict(kw, seed=99))  # different-seed process
    deltas = list(adopter.generate_stream(
        "", rec["max_new"], temperature=rec["temperature"],
        top_k=rec["top_k"], task_id="kill-me", stream=True, resume=rec))
    assert rec["text"] + "".join(deltas) == ref


@pytest.mark.slow
def test_spec_resume_record_adopted_by_spec_off_engine(tmp_path):
    """The journal records ACCEPTED tokens only, so a spec-on worker's
    orphan adopts cleanly on a spec-off replica (and stays greedy-
    identical to the unkilled run)."""
    from symbiont_tpu.resilience.genlog import GenJournal

    prompt = "the quick brown fox jumps"
    ref = _stream(_engine(), prompt, 24)

    eng = _spec_engine()
    eng.journal = journal = GenJournal(tmp_path / "g.genlog")
    got = []
    gen = eng.generate_stream(prompt, 24, task_id="kill-me")
    for delta in gen:
        got.append(delta)
        if len(got) >= 2:
            gen.close()
            break
    rec = journal.live_tails()["kill-me"]
    adopter = _engine()  # no drafter at all
    deltas = list(adopter.generate_stream(
        "", rec["max_new"], temperature=rec["temperature"],
        top_k=rec["top_k"], task_id="kill-me", stream=True, resume=rec))
    assert rec["text"] + "".join(deltas) == ref


# ----------------------------------------------------- fallback rows (slow)


@pytest.mark.slow
def test_spec_divergence_ema_disables_session(monkeypatch):
    """An always-wrong drafter burns spec_k+1 slots per emitted token; the
    acceptance EMA turns speculation off for the session after a few
    rounds, and output still matches spec-off."""
    import symbiont_tpu.models.gpt as gpt_mod

    real = gpt_mod.draft_chunk

    def wrong(draft_params, d_cache, pending, cur_pos, done, kv_valid,
              dcfg, spec_k):
        cache, drafts = real(draft_params, d_cache, pending, cur_pos, done,
                             kv_valid, dcfg, spec_k)
        return cache, (drafts + 1) % dcfg.vocab_size

    kw = dict(new_token_buckets=[64])
    ref = _session(_engine(**kw), ["alpha prompt", "beta words"], [12, 12],
                   temperature=0.0)
    on = _spec_engine(**kw)
    monkeypatch.setattr(gpt_mod, "draft_chunk", wrong)
    s = on.start_session(["alpha prompt", "beta words"], [12, 12],
                         temperature=0.0)
    done = []
    while not s.done():
        done += s.step()
    assert sorted(done) == ref
    assert s._spec_on is False and s._spec_rounds >= 3


@pytest.mark.slow
def test_spec_pool_exhausted_degrades_to_plain(monkeypatch):
    """PoolExhausted while reserving the spec window's pages degrades the
    session to plain decode — never an error, output unchanged."""
    from symbiont_tpu.kv.pool import PoolExhausted

    kw = dict(kv_layout="paged")
    ref = _session(_engine(**kw), ["alpha prompt", "beta words"], [20, 20],
                   temperature=0.0)
    on = _spec_engine(**kw)
    s = on.start_session(["alpha prompt", "beta words"], [20, 20],
                         temperature=0.0)
    calls = {"n": 0}
    real_ensure = s._ensure_decode_blocks

    def flaky(chunk):
        calls["n"] += 1
        if calls["n"] == 1:
            raise PoolExhausted("pressure")
        return real_ensure(chunk)

    monkeypatch.setattr(s, "_ensure_decode_blocks", flaky)
    done = []
    while not s.done():
        done += s.step()
    assert sorted(done) == ref
    assert s._spec_on is False  # degraded, permanently for this session


@pytest.mark.slow
def test_spec_margin_guard_never_truncates_output():
    """want == bucket leaves no spec headroom mid-stream; the margin guard
    must hand back to plain decode early enough that every row still
    fills its full budget."""
    off, on = _engine(), _spec_engine()
    prompt = "margin case"
    a = _stream(off, prompt, 32)  # want == top new-token bucket
    b = _stream(on, prompt, 32)
    assert a == b and len(b) > 0


# --------------------------------------------------- instruments (slow)


@pytest.mark.slow
def test_spec_ledger_and_timeline_rows():
    from symbiont_tpu.obs.engine_timeline import engine_timeline
    from symbiont_tpu.obs.xprof import dispatch_ledger
    from symbiont_tpu.utils.telemetry import metrics

    engine_timeline.clear()
    on = _spec_engine()
    _session(on, ["hello", "world"], [16, 16], temperature=0.0)
    keys = {e["executable"].split("[")[0]
            for e in dispatch_ledger.snapshot()}
    assert {"lm.draft_prefill", "lm.draft_chunk",
            "lm.verify_chunk"} <= keys
    s = engine_timeline.summary()
    assert s["decode_spec_rounds"] >= 1
    assert s["decode_spec_accept_pct"] == 100.0  # drafter IS the target
    assert s["decode_spec_draft_ms_total"] >= 0.0
    # gauge exported for spec-enabled engines only
    labels = {"service": "lm", "kv_dtype": "float32"}
    assert metrics.gauge_get("lm.spec_accept_rate", labels=labels) == 1.0

    engine_timeline.clear()
    _session(_engine(), ["hello"], [8], temperature=0.0)
    assert "decode_spec_rounds" not in engine_timeline.summary()
