"""The bench subsystem (symbiont_tpu/bench/): tier isolation, repetition
stats, archive schema + gate, roofline dual ceilings, resource sampler.

The VERDICT r5 "done" bar this file encodes: a deliberately-injected tier
failure produces rc != 0 PLUS an archived `tier_failures` entry; a missing
declared primary metric alone also forces rc != 0; `load_archive` survives
the driver's `parsed: null` wrapper; and every committed BENCH archive
validates against the typed schema.
"""

import json
import os
import sys
import time
import types
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from symbiont_tpu.bench import archive, roofline, sampler, stats, tiers  # noqa: E402
from symbiont_tpu.bench.cli import build_line  # noqa: E402

import bench  # noqa: E402


# --------------------------------------------------------------- tier registry

def _mini_registry():
    reg = {}

    def tier(name, primary=(), quick=False):
        def deco(fn):
            reg[name] = tiers.Tier(name, fn, tuple(primary), quick)
            return fn
        return deco
    return reg, tier


def test_injected_tier_failure_is_archived_and_rc_nonzero():
    """A tier that throws → structured tier_failures entry with the
    traceback tail, other tiers still run, rc != 0, and the emitted line
    both carries the entry and validates against the schema."""
    reg, tier = _mini_registry()

    @tier("ok_tier", primary=("ok_metric",))
    def ok_tier(results, ctx):
        results["ok_metric"] = 1.0

    @tier("bomb", primary=("bomb_metric",))
    def bomb(results, ctx):
        raise RuntimeError("deliberately injected")

    @tier("after_bomb")
    def after_bomb(results, ctx):
        results["after_ran"] = 1

    results = {}
    run = tiers.run_tiers(results, types.SimpleNamespace(), log=lambda *a: 0,
                          registry_override=reg)
    assert results["after_ran"] == 1, "a dead tier must not stop the others"
    assert run.rc != 0
    [fail] = [f for f in run.failures if f["tier"] == "bomb"]
    assert "RuntimeError: deliberately injected" in fail["exc"]
    assert "deliberately injected" in fail["traceback_tail"]
    # the missing-primary sweep also flags the bomb's absent metric
    run.failures.extend(
        tiers.missing_primary_metrics(results, run, registry_override=reg))
    assert any("bomb_metric" in f["exc"] for f in run.failures)
    line = build_line(results, run)
    assert any(f["tier"] == "bomb" for f in line["tier_failures"])
    assert archive.validate_line(line) == []


def test_missing_primary_metric_alone_forces_failure():
    """A tier that completes without raising but never produces a declared
    primary metric is a failure — the r5 driver's run lost e2e_gen_tok_per_s
    with rc=0 exactly this way."""
    reg, tier = _mini_registry()

    @tier("quiet_loss", primary=("vanished_metric",))
    def quiet_loss(results, ctx):
        pass  # completes "successfully", archives nothing

    results = {}
    run = tiers.run_tiers(results, types.SimpleNamespace(), log=lambda *a: 0,
                          registry_override=reg)
    assert run.rc == 0  # no exception...
    missing = tiers.missing_primary_metrics(results, run,
                                            registry_override=reg)
    assert len(missing) == 1 and "vanished_metric" in missing[0]["exc"]
    run.failures.extend(missing)
    assert run.rc != 0  # ...but the loss still forces a nonzero exit


def test_skipped_tier_primaries_are_exempt():
    reg, tier = _mini_registry()

    @tier("gated", primary=("tpu_only_metric",))
    def gated(results, ctx):
        return "not a TPU device"

    results = {}
    run = tiers.run_tiers(results, types.SimpleNamespace(), log=lambda *a: 0,
                          registry_override=reg)
    assert run.skips == {"gated": "not a TPU device"}
    assert tiers.missing_primary_metrics(results, run,
                                         registry_override=reg) == []
    assert run.rc == 0


# ------------------------------------------------------------------- archive

def test_load_archive_tolerates_null_parsed_wrapper(tmp_path):
    """Direct regression test for the r5 crash: the driver wrapper carried
    `"parsed": null` and `d.get("parsed", d)` returned None, giving
    AttributeError in every consumer (tests/test_perf_doc.py:50)."""
    p = tmp_path / "BENCH_rXX.json"
    p.write_text(json.dumps(
        {"n": 5, "cmd": "python bench.py", "rc": 0,
         "tail": "something went sideways", "parsed": None}))
    d = bench.load_archive(p)
    assert isinstance(d, dict)
    assert d.get("ts", 0) == 0  # consumers may .get() freely
    # the schema layer knows this shape explicitly
    assert archive.is_null_parsed_wrapper(json.loads(p.read_text()))
    assert archive.validate_file(p) == []


def test_all_committed_bench_archives_validate():
    """Schema gate over BENCH_LATEST.json + every BENCH_r0*.json the driver
    has archived (satellite: the emitted line and all historical wrappers
    must type-check)."""
    paths = sorted(REPO.glob("BENCH_r0*.json")) + [REPO / "BENCH_LATEST.json"]
    assert paths, "no bench archives in the repo root?"
    for p in paths:
        assert archive.validate_file(p) == [], p.name


def test_validate_line_catches_malformed_fields():
    good = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 2.0}
    assert archive.validate_line(good) == []
    assert archive.validate_line({}) != []
    bad_type = dict(good, rerank_pairs_per_s="fast")
    assert any("rerank_pairs_per_s" in p
               for p in archive.validate_line(bad_type))
    bad_nan = dict(good, x_ms=float("nan"))
    assert any("x_ms" in p for p in archive.validate_line(bad_nan))
    orphan_min = dict(good, y_ms_min=1.0)
    assert any("y_ms_min" in p for p in archive.validate_line(orphan_min))
    bad_failures = dict(good, tier_failures=[{"tier": "x"}])  # no exc
    assert any("tier_failures" in p
               for p in archive.validate_line(bad_failures))


def test_regression_gate_noise_aware():
    base = {"primary_metrics": ["compute_only_emb_per_s",
                                "tinyllama_1b_ms_per_step_b128",
                                "e2e_ingest_emb_per_s", "tunnel_emb_per_s"],
            "compute_only_emb_per_s": 36000.0,
            "tinyllama_1b_ms_per_step_b128": 10.0,
            "e2e_ingest_emb_per_s": 1500.0,
            "e2e_ingest_emb_per_s_min": 1200.0,
            "e2e_ingest_emb_per_s_max": 1800.0,
            "tunnel_emb_per_s": 5000.0}
    cur = dict(base)
    # within noise: device-bound -2%, ms/step +2%
    cur["compute_only_emb_per_s"] = 35300.0
    cur["tinyllama_1b_ms_per_step_b128"] = 10.2
    assert archive.regression_gate(cur, base) == []
    # device-bound -20% → regression (higher is better)
    cur2 = dict(base, compute_only_emb_per_s=29000.0)
    assert any("compute_only_emb_per_s" in p
               for p in archive.regression_gate(cur2, base))
    # ms/step +20% → regression (lower is better)
    cur3 = dict(base, tinyllama_1b_ms_per_step_b128=12.0)
    assert any("ms_per_step" in p for p in archive.regression_gate(cur3, base))
    # e2e ingest -35%: inside 1.5x the baseline's own archived in-run
    # spread ((1800-1200)/1500 = 40% → 60% allowed) → NOT a regression
    cur4 = dict(base, e2e_ingest_emb_per_s=975.0)
    assert archive.regression_gate(cur4, base) == []
    # tunnel-bound is never gated even at -80%
    cur5 = dict(base, tunnel_emb_per_s=1000.0)
    assert archive.regression_gate(cur5, base) == []


# --------------------------------------------------------------------- stats

def test_stats_record_min_max_and_floor():
    results = {}
    med = stats.record(results, "e2e_gen_tok_per_s", [2000.0, 1900.0, 2100.0])
    assert med == 2000.0
    assert results["e2e_gen_tok_per_s_min"] == 1900.0
    assert results["e2e_gen_tok_per_s_max"] == 2100.0
    with pytest.raises(ValueError):
        stats.record(results, "too_few", [1.0, 2.0])
    assert stats.spread_fraction(results, "e2e_gen_tok_per_s") == \
        pytest.approx(0.1)
    assert stats.spread_fraction(results, "absent") is None


# ------------------------------------------------------------------ roofline

def test_roofline_no_point_sets_its_own_ceiling():
    """The r5 flaw, reconstructed: the fastest stream observed is a decode
    point. Against `vs_best_observed` it must be graded by the best OTHER
    stream (here the reference kernel), not by itself — so it reads >100%
    (honest overshoot) instead of exactly 100.0 (by construction)."""
    results = {
        "hbm_stream_gbps_measured": 517.3,
        "tinyllama_1b_hbm_gbps": 714.5,
        "tinyllama_1b_ms_per_step_noise_limited": 0,
        "tinyllama_1b_hbm_gbps_b128": 241.4,
        "tinyllama_1b_ms_per_step_noise_limited_b128": 0,
    }
    roofline.annotate(results)
    assert results["hbm_stream_gbps_ceiling"] == 714.5
    # b8 vs ref kernel AND vs best-other both divide by 517.3, never 714.5
    assert results["tinyllama_1b_hbm_util_vs_ref_kernel_pct"] == \
        pytest.approx(100 * 714.5 / 517.3, abs=0.1)
    assert results["tinyllama_1b_hbm_util_vs_best_observed_pct"] == \
        pytest.approx(100 * 714.5 / 517.3, abs=0.1)
    assert results["tinyllama_1b_hbm_util_vs_best_observed_pct"] != 100.0
    # b128 IS graded against the b8 point (the best other observed)
    assert results["tinyllama_1b_hbm_util_vs_best_observed_pct_b128"] == \
        pytest.approx(100 * 241.4 / 714.5, abs=0.1)


def test_roofline_noise_limited_points_never_raise_ceilings():
    results = {
        "hbm_stream_gbps_measured": 500.0,
        "gpt2_124m_hbm_gbps": 2000.0,  # wild noise-limited estimate
        "gpt2_124m_ms_per_step_noise_limited": 1,
        "tinyllama_1b_hbm_gbps_b32": 400.0,
        "tinyllama_1b_ms_per_step_noise_limited_b32": 0,
    }
    roofline.annotate(results)
    assert results["hbm_stream_gbps_ceiling"] == 500.0
    assert results["tinyllama_1b_hbm_util_vs_best_observed_pct_b32"] == \
        pytest.approx(80.0)


def test_decode_step_bytes_breakdown():
    """Weights dominate at b8 (>95%), KV grows linearly with batch, and the
    analytic parameter count matches the models' named sizes."""
    bd8 = roofline.decode_step_bytes("tinyllama_1b", 8, 64, 128)
    bd128 = roofline.decode_step_bytes("tinyllama_1b", 128, 64, 128)
    assert bd8["weight"] == bd128["weight"]  # shared by all rows
    assert bd8["weight"] / sum(bd8.values()) > 0.95
    assert bd128["kv"] == pytest.approx(16 * bd8["kv"])
    # ~1.1B params at bf16 ≈ 2.2 GB; GPT-2 124M ≈ 250 MB
    assert 2.0e9 < bd8["weight"] < 2.4e9
    gpt2 = roofline.analytic_param_bytes(roofline.GEOMETRIES["gpt2_124m"])
    assert 2.3e8 < gpt2 < 2.7e8


def test_roofline_annotation_of_committed_archive():
    """BENCH_LATEST.json (r5) archived tinyllama b8 at 100.0% 'of measured'
    because the point set its own ceiling; the accountant's derived fields
    over the SAME raw data must not reproduce that construction."""
    r = bench.load_archive(REPO / "BENCH_LATEST.json")
    annotated = roofline.annotated_for_render(r)
    assert annotated["tinyllama_1b_hbm_util_vs_best_observed_pct"] > 100.0
    assert annotated["tinyllama_1b_hbm_util_vs_ref_kernel_pct"] == \
        pytest.approx(100 * r["tinyllama_1b_hbm_gbps"]
                      / r["hbm_stream_gbps_measured"], abs=0.1)


# ------------------------------------------------------------------- sampler

def test_resource_sampler_accounts_own_process():
    s = sampler.ResourceSampler({"me": [os.getpid()]}).start()
    # burn a little CPU and write some bytes so the deltas are nonzero
    x = 0
    t0 = time.time()
    while time.time() - t0 < 0.05:
        x += sum(i * i for i in range(1000))
    window = s.stop()
    assert window["wall_s"] >= 0.05
    assert window.get("cpu_s_me", 0) >= 0
    assert window["cpu_s_engine_host"] >= 0
    results = {}
    sampler.archive_decomposition(results, "e2e_ingest", window)
    assert "e2e_ingest_cpu_s_engine_host" in results
    assert "e2e_ingest_host_cpu_utilization" in results
    assert archive.validate_line(
        {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
         **results}) == []


def test_sampler_dead_pid_is_not_fatal():
    s = sampler.ResourceSampler({"ghost": [99999999]}).start()
    window = s.stop()
    assert "cpu_s_ghost" not in window
    assert "cpu_s_engine_host" in window


# ------------------------------------------------------------------ CLI glue

def test_cli_gate_and_validate_commands(tmp_path):
    from symbiont_tpu.bench import cli

    base = {"metric": "m", "value": 100.0, "unit": "u", "vs_baseline": 1.0,
            "primary_metrics": ["compute_only_emb_per_s"],
            "compute_only_emb_per_s": 100.0}
    cur_bad = dict(base, compute_only_emb_per_s=50.0)
    bp = tmp_path / "base.json"
    cp = tmp_path / "cur.json"
    bp.write_text(json.dumps(base))
    cp.write_text(json.dumps(cur_bad))
    assert cli.main(["--validate", str(bp), str(cp)]) == 0
    assert cli.main(["--gate", str(cp), str(bp)]) == 1  # regression
    assert cli.main(["--gate", str(bp), str(bp)]) == 0  # self-compare clean
    # a null-parsed wrapper as the CURRENT run fails the gate loudly
    np_ = tmp_path / "null.json"
    np_.write_text(json.dumps({"n": 1, "cmd": "c", "rc": 0, "tail": "",
                               "parsed": None}))
    assert cli.main(["--gate", str(np_), str(bp)]) == 1


def test_env_injected_failure_hook(monkeypatch):
    """The arms-length proof command: SYMBIONT_BENCH_INJECT_FAILURE=1
    registers a quick tier that throws, so `python bench.py --quick` under
    that env exits nonzero with an archived `injected_failure` entry."""
    from symbiont_tpu.bench import cli

    monkeypatch.setenv("SYMBIONT_BENCH_INJECT_FAILURE", "1")
    cli._maybe_register_injection()
    try:
        reg = {"injected_failure": tiers.registry()["injected_failure"]}
        assert reg["injected_failure"].quick  # fires even under --quick
        results = {}
        run = tiers.run_tiers(results, types.SimpleNamespace(), quick=True,
                              log=lambda *a: 0, registry_override=reg)
        assert run.rc != 0
        line = build_line(results, run)
        [fail] = line["tier_failures"]
        assert fail["tier"] == "injected_failure"
        assert "deliberately injected" in fail["exc"]
        assert archive.validate_line(line) == []
    finally:
        tiers._REGISTRY.pop("injected_failure", None)


def test_cli_main_end_to_end_stub_registry(monkeypatch, capsys):
    """Full `cli.main` path (the thing `python bench.py` runs) against a
    stubbed registry: a clean run prints a schema-valid line with empty
    tier_failures and exits 0; an injected bomb makes the SAME entrypoint
    exit nonzero with the failure archived in the printed line."""
    from symbiont_tpu.bench import cli
    # pre-import the real tier modules so they land in sys.modules NOW and
    # register into the ORIGINAL registry — main()'s imports then no-op and
    # only the stubs below exist in the patched registry
    from symbiont_tpu.bench import (  # noqa: F401
        chaos, compute, decode, e2e, engine_plane, load, multichip, obs,
        quant, serialization)

    monkeypatch.setattr(tiers, "_REGISTRY", {})

    @tiers.register("stub_ok", primary_metrics=("stub_metric",), quick=True)
    def stub_ok(results, ctx):
        results["stub_metric"] = 1.0

    rc = cli.main(["--quick"])
    line = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert line["tier_failures"] == []
    assert archive.validate_line(line) == []

    @tiers.register("stub_bomb", primary_metrics=("never_metric",),
                    quick=True)
    def stub_bomb(results, ctx):
        raise RuntimeError("kaboom")

    rc = cli.main(["--quick"])
    line = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(f["tier"] == "stub_bomb" and "kaboom" in f["exc"]
               for f in line["tier_failures"])
    assert archive.validate_line(line) == []


def test_cli_only_runs_named_tier_and_never_persists(monkeypatch, capsys):
    """`--only TIER` (scripts/multichip.sh's fast loop) runs just the named
    tier, archives every other tier under tier_skips (exempting their
    primaries), rejects unknown names, and NEVER overwrites
    BENCH_LATEST.json — a partial line must not become the doc's source."""
    from symbiont_tpu.bench import cli
    from symbiont_tpu.bench import (  # noqa: F401
        chaos, compute, decode, e2e, engine_plane, load, multichip, obs,
        quant, serialization)

    monkeypatch.setattr(tiers, "_REGISTRY", {})

    @tiers.register("stub_a", primary_metrics=("a_metric",))
    def stub_a(results, ctx):
        results["a_metric"] = 1.0

    @tiers.register("stub_b", primary_metrics=("b_metric",))
    def stub_b(results, ctx):
        raise RuntimeError("must never run under --only stub_a")

    persisted = []
    monkeypatch.setattr(cli, "_persist_latest",
                        lambda line: persisted.append(line))
    rc = cli.main(["--only", "stub_a"])
    line = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert line["tier_failures"] == []
    assert line["a_metric"] == 1.0
    assert "stub_b" in line["tier_skips"]
    assert "b_metric" not in line["primary_metrics"]
    assert persisted == []  # --only is a partial run: no BENCH_LATEST

    assert cli.main(["--only", "no_such_tier"]) == 2
    capsys.readouterr()


def test_gate_rejects_null_parsed_on_either_side(tmp_path):
    """A null-parsed wrapper as BASELINE must fail the gate too: the empty
    primary_metrics intersection would otherwise compare zero metrics and
    report a clean pass (review finding)."""
    good = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
            "primary_metrics": ["compute_only_emb_per_s"],
            "compute_only_emb_per_s": 1.0}
    gp = tmp_path / "good.json"
    gp.write_text(json.dumps(good))
    np_ = tmp_path / "null.json"
    np_.write_text(json.dumps({"n": 1, "cmd": "c", "rc": 0, "tail": "",
                               "parsed": None}))
    assert any("parsed: null" in p
               for p in archive.gate_files(gp, np_))
    assert any("parsed: null" in p
               for p in archive.gate_files(np_, gp))


def test_validate_line_catches_orphan_max():
    good = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 2.0}
    orphan_max = dict(good, y_ms=1.0, y_ms_max=2.0)  # _min missing
    assert any("y_ms_max" in p for p in archive.validate_line(orphan_max))
    full = dict(good, y_ms=1.0, y_ms_min=0.5, y_ms_max=2.0)
    assert archive.validate_line(full) == []


def test_render_doc_cmd_handles_null_parsed_and_missing_operand(tmp_path,
                                                                capsys):
    from symbiont_tpu.bench import cli

    np_ = tmp_path / "null.json"
    np_.write_text(json.dumps({"n": 1, "cmd": "c", "rc": 0, "tail": "",
                               "parsed": None}))
    assert cli.main(["--render-doc", str(np_)]) == 1
    assert cli.main(["--render-doc"]) == 2
    assert capsys.readouterr().out == ""  # nothing rendered either way


def test_sampler_archives_its_own_wall():
    results = {}
    sampler.archive_decomposition(
        results, "e2e_ingest",
        {"wall_s": 10.0, "cpu_s_broker": 2.0, "cpu_s_engine_host": 3.0,
         "io_bytes_broker": 50_000_000})
    assert results["e2e_ingest_wall_s"] == 10.0
    assert results["e2e_ingest_host_cpu_utilization"] == 0.5
    assert results["e2e_ingest_bus_mb_per_s"] == 5.0


def test_gate_flags_primary_missing_from_current_run():
    """A gated primary the baseline HAS but the current run lost must be a
    gate failure, not a silent subset comparison (review finding — the r5
    vanished-metric class applied to the gate itself)."""
    base = {"primary_metrics": ["e2e_gen_tok_per_s"],
            "e2e_gen_tok_per_s": 2000.0}
    cur = {"primary_metrics": ["e2e_gen_tok_per_s"]}  # field vanished
    assert any("missing from the current run" in p
               for p in archive.regression_gate(cur, base))
    # absent from the BASELINE too → nothing to gate against, no problem
    assert archive.regression_gate(cur, {"primary_metrics":
                                         ["e2e_gen_tok_per_s"]}) == []


def test_render_doc_cmd_partial_archive_friendly_error(capsys):
    """BENCH_r01.json (4 fields) and any partial tier-failure run lack
    fields the doc template hard-requires: --render-doc must name the
    missing field and exit 1, not traceback (review finding)."""
    from symbiont_tpu.bench import cli

    assert cli.main(["--render-doc", str(REPO / "BENCH_r01.json")]) == 1
    assert capsys.readouterr().out == ""


def test_declared_primary_metrics_single_source():
    """The archived primary_metrics list derives from the tier registry
    (plus the roofline-produced utilization primary) — the same source
    missing_primary_metrics enforces, so the two cannot drift."""
    from symbiont_tpu.bench import cli
    # the real tier modules must be registered for this check
    from symbiont_tpu.bench import (  # noqa: F401
        chaos, compute, decode, e2e, engine_plane, load, multichip, obs,
        quant, serialization)

    declared = cli.declared_primary_metrics()
    assert cli.ROOFLINE_PRIMARY in declared
    for tier in tiers.registry().values():
        for m in tier.primary_metrics:
            assert m in declared
    # the noise floor for the drifting-denominator primary is drift-sized
    assert archive._noise_floor(cli.ROOFLINE_PRIMARY) == 0.45


def test_gate_tolerates_ref_kernel_denominator_drift():
    """Two no-change runs straddling the documented 517->715 GB/s reference
    kernel drift move util_vs_ref_kernel ~28%; the gate must not call that
    a regression (review finding)."""
    base = {"primary_metrics": ["tinyllama_1b_hbm_util_vs_ref_kernel_pct"],
            "tinyllama_1b_hbm_util_vs_ref_kernel_pct": 138.0}
    cur = dict(base, tinyllama_1b_hbm_util_vs_ref_kernel_pct=100.0)  # -27.5%
    assert archive.regression_gate(cur, base) == []
    collapsed = dict(base, tinyllama_1b_hbm_util_vs_ref_kernel_pct=45.0)
    assert archive.regression_gate(collapsed, base) != []  # beyond drift


def test_gate_vacuous_comparison_is_a_failure():
    """A gate that compared ZERO metrics must say so, not print a clean
    pass — the vacuous-pass path is how a --quick line (which declares only
    what it measured) would otherwise 'pass' against a full baseline."""
    a = {"primary_metrics": [], "value": 1.0}
    b = {"primary_metrics": ["compute_only_emb_per_s"],
         "compute_only_emb_per_s": 1.0}
    assert any("nothing was compared" in p
               for p in archive.regression_gate(a, b))


def test_declared_primary_metrics_excludes_skipped_tiers():
    """A --no-e2e / CPU-only line must not declare metrics its run
    deliberately skipped, or the gate would flag the legitimate skip as a
    lost metric (review finding)."""
    from symbiont_tpu.bench import cli
    from symbiont_tpu.bench import (  # noqa: F401
        chaos, compute, decode, e2e, engine_plane, load, multichip, obs,
        quant, serialization)

    full = cli.declared_primary_metrics()
    no_e2e = cli.declared_primary_metrics(skips={"e2e": "skipped by flag"})
    assert [m for m in full if m.startswith("e2e_")]
    assert not [m for m in no_e2e if m.startswith("e2e_")]
    # skipping an ingredient tier of the roofline primary drops it too
    cpu_only = cli.declared_primary_metrics(
        skips={"stream_ceiling": "not a TPU", "compute_mfu": "not a TPU"})
    assert cli.ROOFLINE_PRIMARY not in cpu_only
    assert "mfu_compute_only_pct" not in cpu_only


def test_bulk_ratio_fields_decoupled_from_registration_order():
    """The e2e÷bulk ratio no longer rides on the engine_plane tier having
    run EARLIER IN THE SAME PROCESS (the PR 6 registration-order coupling):
    with the prerequisite absent it archives an explicit null plus a note;
    with it present, the ratio — and the null+note shape schema-validates."""
    from symbiont_tpu.bench.e2e import bulk_ratio_fields

    absent = bulk_ratio_fields({"e2e_ingest_emb_per_s": 1800.0})
    assert absent["e2e_ingest_vs_bulk_x"] is None
    assert "ingest_10k_emb_per_s absent" in absent["e2e_ingest_vs_bulk_note"]

    present = bulk_ratio_fields({"e2e_ingest_emb_per_s": 1800.0,
                                 "ingest_10k_emb_per_s": 3000.0})
    assert present == {"e2e_ingest_vs_bulk_x": 0.6}

    line = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
            **absent}
    assert archive.validate_line(line) == []
    # null remains EXPLICIT: any other field archived as null still fails
    bad = dict(line, e2e_search_p50_ms=None)
    assert archive.validate_line(bad)


def test_quant_tier_registered_with_primaries():
    from symbiont_tpu.bench import quant  # noqa: F401

    reg = tiers.registry()
    assert "quant" in reg
    assert set(reg["quant"].primary_metrics) == {
        "quant_embed_cos_int8", "quant_embed_int8_vs_bf16_x",
        "quant_decode_int8kv_vs_bf16_x"}
    assert not reg["quant"].quick  # device tier: full runs only


# ------------------------------------------------------ load-tier seed knobs

def test_load_seed_flag_parsing():
    """--chaos-seed/--load-seed parse to ints, default 0, and reject
    garbage loudly — a typo'd seed must not silently replay seed 0."""
    from symbiont_tpu.bench import cli

    assert cli.parse_seed_flag(["--load-seed", "7"], "--load-seed") == 7
    assert cli.parse_seed_flag([], "--load-seed") == 0
    with pytest.raises(ValueError):
        cli.parse_seed_flag(["--load-seed", "banana"], "--load-seed")
    with pytest.raises(ValueError):
        cli.parse_seed_flag(["--load-seed"], "--load-seed")


def test_cli_seed_flags_reach_tier_ctx(monkeypatch, capsys):
    """The seeds ride ctx into every tier (the load tier archives them as
    load_seed/chaos_seed so a red run replays bit-for-bit), and a
    malformed seed is usage (rc 2), not a traceback."""
    from symbiont_tpu.bench import cli
    from symbiont_tpu.bench import (  # noqa: F401
        chaos, compute, decode, e2e, engine_plane, load, multichip, obs,
        quant, serialization)

    monkeypatch.setattr(tiers, "_REGISTRY", {})
    seen = {}

    @tiers.register("seed_probe", primary_metrics=("probe_ok",), quick=True)
    def probe(results, ctx):
        seen["load"] = ctx.load_seed
        seen["chaos"] = ctx.chaos_seed
        results["probe_ok"] = 1.0

    rc = cli.main(["--quick", "--load-seed", "11", "--chaos-seed", "42"])
    capsys.readouterr()
    assert rc == 0 and seen == {"load": 11, "chaos": 42}
    assert cli.main(["--quick", "--load-seed", "banana"]) == 2
    capsys.readouterr()
