"""Decoder LM golden tests vs HF torch (tiny random GPT-2 and Llama/TinyLlama
layouts) + static-shape KV-cache decode behavior.

BASELINE.md config #5 (TinyLlama-1.1B / GPT-2 generation on TPU) is served by
this model; these tests gate weight-conversion fidelity and the prefill/decode
cache math.
"""

import dataclasses

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from symbiont_tpu.models.convert import convert_gpt  # noqa: E402
from symbiont_tpu.models.gpt import (  # noqa: E402
    GPTConfig,
    forward,
    generate,
    init_cache,
    init_params,
)


def _fp32(cfg):
    return dataclasses.replace(cfg, dtype="float32")


@pytest.fixture(scope="module")
def torch_gpt2():
    torch.manual_seed(0)
    cfg = transformers.GPT2Config(vocab_size=97, n_embd=32, n_layer=2, n_head=4,
                                  n_positions=64)
    return transformers.GPT2LMHeadModel(cfg).eval(), cfg


@pytest.fixture(scope="module")
def torch_llama():
    torch.manual_seed(1)
    cfg = transformers.LlamaConfig(
        vocab_size=97, hidden_size=32, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, intermediate_size=64, max_position_embeddings=64,
        tie_word_embeddings=False)
    return transformers.LlamaForCausalLM(cfg).eval(), cfg


def _logits_ours(model, hf_cfg, ids):
    cfg = _fp32(GPTConfig.from_hf(hf_cfg.to_dict()))
    params = convert_gpt(model.state_dict(), cfg)
    B, S = ids.shape
    cache = init_cache(cfg, B, S, jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    logits, _ = forward(params, jnp.asarray(ids), cache, positions, cfg)
    return np.asarray(logits), cfg, params


def test_gpt2_logits_match_hf(torch_gpt2):
    model, hf_cfg = torch_gpt2
    ids = np.random.default_rng(0).integers(0, 97, size=(2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours, _, _ = _logits_ours(model, hf_cfg, ids)
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=1e-3)


def test_llama_logits_match_hf(torch_llama):
    model, hf_cfg = torch_llama
    ids = np.random.default_rng(1).integers(0, 97, size=(2, 12)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.tensor(ids.astype(np.int64))).logits.numpy()
    ours, cfg, _ = _logits_ours(model, hf_cfg, ids)
    assert cfg.kv_heads == 2  # GQA path exercised
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=1e-3)


def test_incremental_decode_matches_full_forward(torch_gpt2):
    """Prefill+1-token steps must equal one full forward (cache correctness)."""
    model, hf_cfg = torch_gpt2
    ids = np.random.default_rng(2).integers(0, 97, size=(1, 10)).astype(np.int32)
    full, cfg, params = _logits_ours(model, hf_cfg, ids)

    P = 6
    cache = init_cache(cfg, 1, 10, jnp.float32)
    pos = jnp.arange(P, dtype=jnp.int32)[None, :]
    logits, cache = forward(params, jnp.asarray(ids[:, :P]), cache, pos, cfg)
    cache = cache._replace(length=jnp.asarray(P, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits), full[:, :P], atol=1e-4, rtol=1e-3)
    for t in range(P, 10):
        step_logits, cache = forward(
            params, jnp.asarray(ids[:, t:t + 1]),
            cache, jnp.asarray([[t]], jnp.int32), cfg)
        cache = cache._replace(length=cache.length + 1)
        np.testing.assert_allclose(np.asarray(step_logits)[:, 0], full[:, t],
                                   atol=1e-4, rtol=1e-3)


def test_generate_greedy_matches_hf(torch_gpt2):
    model, hf_cfg = torch_gpt2
    prompt = np.random.default_rng(3).integers(0, 97, size=(1, 8)).astype(np.int32)
    with torch.no_grad():
        ref = model.generate(torch.tensor(prompt.astype(np.int64)), max_new_tokens=8,
                             do_sample=False, pad_token_id=0)
    cfg = _fp32(GPTConfig.from_hf(hf_cfg.to_dict()))
    params = convert_gpt(model.state_dict(), cfg)
    mask = np.ones_like(prompt)
    toks, lengths = generate(params, jnp.asarray(prompt), jnp.asarray(mask),
                             jax.random.key(0), cfg, max_new_tokens=8,
                             temperature=0.0)
    np.testing.assert_array_equal(np.asarray(toks)[0], ref.numpy()[0, 8:])
    assert int(lengths[0]) == 8


def test_generate_respects_eos():
    cfg = GPTConfig(vocab_size=11, hidden_size=16, num_layers=1, num_heads=2,
                    intermediate_size=32, max_position_embeddings=32,
                    dtype="float32")
    params = init_params(jax.random.key(0), cfg)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    mask = jnp.ones_like(prompt)
    # greedy argmax token becomes "eos": whatever it emits first, treat as eos
    toks, _ = generate(params, prompt, mask, jax.random.key(1), cfg,
                       max_new_tokens=6, temperature=0.0)
    first = int(np.asarray(toks)[0, 0])
    toks2, lengths2 = generate(params, prompt, mask, jax.random.key(1), cfg,
                               max_new_tokens=6, temperature=0.0, eos_id=first)
    # greedy on a deterministic model repeats states; eos at step 1 → length 1
    assert int(lengths2[0]) <= 6
    assert int(np.asarray(toks2)[0, 0]) == first


def test_pad_content_cannot_leak_into_generation(torch_gpt2):
    """Regression: padding-slot K/V must never be attended. Same prompt with
    different garbage in the pad region must generate identical tokens."""
    model, hf_cfg = torch_gpt2
    cfg = _fp32(GPTConfig.from_hf(hf_cfg.to_dict()))
    params = convert_gpt(model.state_dict(), cfg)
    b = np.array([50, 12, 30], np.int32)
    P = 6
    mask = np.zeros((1, P), np.int32)
    mask[0, :3] = 1
    ids_a = np.zeros((1, P), np.int32)
    ids_a[0, :3] = b
    ids_b = np.full((1, P), 55, np.int32)  # different pad garbage
    ids_b[0, :3] = b
    t_a, _ = generate(params, jnp.asarray(ids_a), jnp.asarray(mask),
                      jax.random.key(0), cfg, max_new_tokens=5, temperature=0.0)
    t_b, _ = generate(params, jnp.asarray(ids_b), jnp.asarray(mask),
                      jax.random.key(0), cfg, max_new_tokens=5, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(t_a), np.asarray(t_b))
    # and padded equals unpadded solo decode
    t_solo, _ = generate(params, jnp.asarray(b[None, :]),
                         jnp.asarray(np.ones((1, 3), np.int32)),
                         jax.random.key(0), cfg, max_new_tokens=5,
                         temperature=0.0)
    np.testing.assert_array_equal(np.asarray(t_a), np.asarray(t_solo))


def test_ragged_batch_prompt_lengths(torch_gpt2):
    """Rows with different prompt lengths decode from their own last token."""
    model, hf_cfg = torch_gpt2
    cfg = _fp32(GPTConfig.from_hf(hf_cfg.to_dict()))
    params = convert_gpt(model.state_dict(), cfg)
    rng = np.random.default_rng(4)
    a = rng.integers(1, 97, size=6).astype(np.int32)
    b = rng.integers(1, 97, size=4).astype(np.int32)
    P = 6
    ids = np.zeros((2, P), np.int32)
    mask = np.zeros((2, P), np.int32)
    ids[0, :6], mask[0, :6] = a, 1
    ids[1, :4], mask[1, :4] = b, 1
    toks_batch, _ = generate(params, jnp.asarray(ids), jnp.asarray(mask),
                             jax.random.key(0), cfg, max_new_tokens=4,
                             temperature=0.0)
    # row 1 alone, unpadded
    toks_solo, _ = generate(params, jnp.asarray(b[None, :]),
                            jnp.asarray(np.ones((1, 4), np.int32)),
                            jax.random.key(0), cfg, max_new_tokens=4,
                            temperature=0.0)
    np.testing.assert_array_equal(np.asarray(toks_batch)[1], np.asarray(toks_solo)[0])

