"""Overlap-everything ingest (ROADMAP item 3): the cross-message upsert
coalescer's ack/flush contract and the micro-batcher's in-flight window.

Covers the edge cases the coalesced-ack design must hold:
- flush-on-stop with pending acks (shutdown is a flush trigger, not a drop);
- a crashed flush — including one that COMMITTED before failing — fails
  every message it carried, whose redelivery re-coalesces without duplicate
  points (deterministic ids);
- a breaker-open store spills the whole coalesced batch to the WAL and the
  acks still release (the spill is durable by design);
- a poison dim group fails alone, not the healthy messages batched with it;
- the batcher's double-buffered flush window preserves per-submission
  results exactly even when a later flush completes first, and the
  `batcher.inflight` / `batcher.overlap_ratio` gauges see the overlap.
"""

import asyncio
import time

import numpy as np
import pytest

from symbiont_tpu import subjects
from symbiont_tpu.bus.inproc import InprocBus
from symbiont_tpu.schema import frames
from symbiont_tpu.services.coalesce import UpsertCoalescer, store_executor
from symbiont_tpu.services.vector_memory import VectorMemoryService
from symbiont_tpu.utils.ids import deterministic_point_id
from symbiont_tpu.utils.telemetry import metrics

DIM = 4


class _MemStore:
    """Dict store with upsert_rows; optional scripted failures."""

    def __init__(self, fail_first: int = 0, commit_before_fail: bool = False):
        self.points = {}
        self.calls = []  # row count per upsert_rows call
        self.fail_first = fail_first
        self.commit_before_fail = commit_before_fail

    def ensure_collection(self, dim=None):
        pass

    def upsert_rows(self, ids, rows, payloads):
        self.calls.append(len(ids))
        commit = self.fail_first <= 0 or self.commit_before_fail
        if commit:
            for pid, row, payload in zip(ids, np.asarray(rows), payloads):
                if row.shape[0] != DIM:
                    raise ValueError(f"dim {row.shape[0]} != {DIM}")
                self.points[pid] = (np.array(row), payload)
        if self.fail_first > 0:
            self.fail_first -= 1
            raise ConnectionError("injected store failure")
        return len(ids)

    def count(self):
        return len(self.points)


def _msg_bytes(doc_id: str, n_sentences: int = 2, dim: int = DIM):
    rows = np.full((n_sentences, dim), float(hash(doc_id) % 97),
                   np.float32)
    return frames.encode_embeddings_message(
        doc_id, "http://d", [f"sentence {i} of {doc_id}"
                             for i in range(n_sentences)],
        rows, "stub", 1)


# ------------------------------------------------------------- flush triggers

def test_rows_trigger_flushes_immediately():
    store = _MemStore()

    async def scenario():
        c = UpsertCoalescer(store.upsert_rows, max_rows=4, max_age_ms=10_000)
        await c.start()
        try:
            ns = await asyncio.gather(
                c.add(["a0", "a1"], np.ones((2, DIM), np.float32),
                      [{}, {}]),
                c.add(["b0", "b1"], np.ones((2, DIM), np.float32),
                      [{}, {}]))
            assert ns == [2, 2]
            # ONE coalesced call carried both messages' rows
            assert store.calls == [4]
            assert store.count() == 4
        finally:
            await c.stop()

    asyncio.run(scenario())


def test_age_trigger_flushes_a_lone_message():
    store = _MemStore()

    async def scenario():
        c = UpsertCoalescer(store.upsert_rows, max_rows=10_000,
                            max_age_ms=20)
        await c.start()
        try:
            t0 = time.monotonic()
            n = await c.add(["a0"], np.ones((1, DIM), np.float32), [{}])
            assert n == 1 and store.calls == [1]
            # the age bound is the ceiling on added ack latency
            assert time.monotonic() - t0 < 5.0
        finally:
            await c.stop()

    asyncio.run(scenario())


def test_flush_on_stop_with_pending_acks():
    """max_rows/age never fire: stop() itself must land the rows and
    release every pending ack-wait."""
    store = _MemStore()

    async def scenario():
        c = UpsertCoalescer(store.upsert_rows, max_rows=10_000,
                            max_age_ms=60_000)
        await c.start()
        adds = [asyncio.create_task(
            c.add([f"d{i}-0", f"d{i}-1"], np.ones((2, DIM), np.float32),
                  [{}, {}])) for i in range(3)]
        await asyncio.sleep(0.05)  # all queued, none flushed
        assert store.calls == []
        assert not any(t.done() for t in adds)
        await c.stop()
        assert await asyncio.gather(*adds) == [2, 2, 2]
        assert store.calls == [6] and store.count() == 6
        assert metrics.get("coalesce.flushes",
                           labels={"service": "vector_memory",
                                   "trigger": "stop"}) >= 1

    asyncio.run(scenario())


def test_crashed_flush_fails_every_carried_message():
    store = _MemStore(fail_first=1)

    async def scenario():
        c = UpsertCoalescer(store.upsert_rows, max_rows=4, max_age_ms=10_000)
        await c.start()
        try:
            results = await asyncio.gather(
                c.add(["a0", "a1"], np.ones((2, DIM), np.float32), [{}, {}]),
                c.add(["b0", "b1"], np.ones((2, DIM), np.float32), [{}, {}]),
                return_exceptions=True)
            assert all(isinstance(r, ConnectionError) for r in results), \
                results
            # the retry (the caller's redelivery in the real pipeline)
            # re-coalesces and lands
            ns = await asyncio.gather(
                c.add(["a0", "a1"], np.ones((2, DIM), np.float32), [{}, {}]),
                c.add(["b0", "b1"], np.ones((2, DIM), np.float32), [{}, {}]))
            assert ns == [2, 2] and store.count() == 4
        finally:
            await c.stop()

    asyncio.run(scenario())


def test_poison_dim_group_fails_alone():
    """Entries group by dim at flush: the mismatched message gets ITS
    ValueError; the healthy one commits from the same flush."""
    store = _MemStore()

    async def scenario():
        c = UpsertCoalescer(store.upsert_rows, max_rows=3, max_age_ms=10_000)
        await c.start()
        try:
            good = asyncio.create_task(
                c.add(["g0", "g1"], np.ones((2, DIM), np.float32), [{}, {}]))
            bad = asyncio.create_task(
                c.add(["p0"], np.ones((1, DIM + 3), np.float32), [{}]))
            results = await asyncio.gather(good, bad,
                                           return_exceptions=True)
            assert results[0] == 2
            assert isinstance(results[1], ValueError)
            assert store.count() == 2
        finally:
            await c.stop()

    asyncio.run(scenario())


# ------------------------------------ service-level: ack-after-flush contract

def _durable_vm_stack(store, *, ack_wait_s=0.3, max_deliver=5,
                      coalesce_max_rows=64, coalesce_max_age_ms=15.0):
    async def make(bus):
        await bus.add_stream("pipeline",
                             [subjects.DATA_TEXT_WITH_EMBEDDINGS],
                             ack_wait_s=ack_wait_s, max_deliver=max_deliver)
        svc = VectorMemoryService(bus, store, durable_stream="pipeline",
                                  coalesce_max_rows=coalesce_max_rows,
                                  coalesce_max_age_ms=coalesce_max_age_ms)
        await svc.start()
        return svc

    return make


async def _wait_for(cond, timeout=15.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.02)
    return cond()


def test_redelivery_after_crashed_flush_no_duplicate_points():
    """The flush COMMITS and then fails (crash between store write and
    ack): every carried delivery stays unacked, redelivers, re-coalesces —
    and the deterministic point ids overwrite instead of duplicating."""
    store = _MemStore(fail_first=1, commit_before_fail=True)
    n_docs, sents = 4, 2

    async def scenario():
        bus = InprocBus()
        svc = await _durable_vm_stack(store)(bus)
        try:
            for i in range(n_docs):
                data, headers = _msg_bytes(f"doc-{i}", sents)
                await bus.publish(subjects.DATA_TEXT_WITH_EMBEDDINGS, data,
                                  headers=headers)
            assert await _wait_for(
                lambda: bus.stats["redelivered"] >= 1
                and len(store.calls) >= 2)
            # a settled re-run of the same ids grew NOTHING: exactly one
            # point per (doc, sentence_order)
            assert store.count() == n_docs * sents
            expected_ids = {deterministic_point_id(f"doc-{i}", o)
                            for i in range(n_docs) for o in range(sents)}
            assert set(store.points) == expected_ids
            assert len(store.calls) >= 2  # the crashed flush + the retry
        finally:
            await svc.stop()
            await bus.close()

    asyncio.run(scenario())


def test_breaker_open_spills_coalesced_batch_and_acks_release(tmp_path):
    """ResilientVectorStore under the coalescer: the backend is down, the
    breaker opens, the WHOLE coalesced batch spills to the WAL — and the
    flush reports success, so every carried delivery acks (the spill IS
    durable). Recovery replays the spill into the inner store: zero loss."""
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore
    from symbiont_tpu.resilience.breaker import CircuitBreaker
    from symbiont_tpu.resilience.faults import FaultPlan, FaultRule
    from symbiont_tpu.resilience.stores import ResilientVectorStore

    inner = VectorStore(VectorStoreConfig(
        dim=DIM, data_dir=str(tmp_path / "inner"), shard_capacity=64))
    breaker = CircuitBreaker("coalesce_vs", failure_threshold=1,
                             reset_timeout_s=0.2)
    store = ResilientVectorStore(
        inner, breaker=breaker, spill_path=str(tmp_path / "spill.jsonl"))
    plan = FaultPlan(seed=21, rules=[
        FaultRule(seam="store.upsert", kind="error",
                  match="coalesce_vs", times=1)])
    n_docs, sents = 3, 2

    async def scenario():
        bus = InprocBus()
        svc = await _durable_vm_stack(store, coalesce_max_rows=6,
                                      coalesce_max_age_ms=10.0)(bus)
        try:
            with plan.activate():
                for i in range(n_docs):
                    data, headers = _msg_bytes(f"doc-{i}", sents)
                    await bus.publish(
                        subjects.DATA_TEXT_WITH_EMBEDDINGS, data,
                        headers=headers)

                # every delivery ACKS even though the backend is down
                # (spill counts as durable): the stream settles
                async def floor():
                    stats = await bus.stream_stats()
                    return stats["pipeline"]["groups"][
                        subjects.QUEUE_VECTOR_MEMORY]["ack_floor"]

                assert await _wait_for(lambda: store.spill_pending() > 0)
                deadline = asyncio.get_running_loop().time() + 15
                while (asyncio.get_running_loop().time() < deadline
                       and await floor() < n_docs):
                    await asyncio.sleep(0.02)
                assert await floor() >= n_docs, "acks did not release"
                # recovery: the half-open probe (or an operator replay)
                # drains the spill into the inner store
                await asyncio.sleep(0.25)
                await asyncio.get_running_loop().run_in_executor(
                    None, store.replay_spill)
            assert inner.count() == n_docs * sents
            assert store.spill_pending() == 0
        finally:
            await svc.stop()
            await bus.close()

    asyncio.run(scenario())


# ------------------------------------------- batcher in-flight window order

class _SlowFirstEngine:
    """Stub engine: the FIRST forward is slow, the second merely slow-ish
    (so the two demonstrably overlap and B still completes first), and
    every output row encodes its input text — so a mis-routed row under
    out-of-order flush completion is detectable, not silent."""

    def __init__(self):
        from symbiont_tpu.config import EngineConfig

        self.config = EngineConfig(embedding_dim=DIM, max_batch=4,
                                   flush_deadline_ms=1.0,
                                   max_inflight_flushes=2)
        self.calls = 0

    def embed_texts(self, texts):
        call = self.calls
        self.calls += 1
        time.sleep(0.5 if call == 0 else 0.2)
        return np.asarray([[float(t.split("-")[1])] * DIM for t in texts],
                          np.float32)


def test_inflight_window_preserves_results_under_slow_forward():
    from symbiont_tpu.engine.batcher import MicroBatcher

    eng = _SlowFirstEngine()
    labels = {"service": "engine", "batcher": "embed"}

    async def scenario():
        b = MicroBatcher(eng)
        await b.start()
        try:
            order = []
            a = asyncio.create_task(b.embed(["t-0", "t-1", "t-2", "t-3"]))
            a.add_done_callback(lambda _: order.append("a"))
            await asyncio.sleep(0.05)  # flush A is in its slow forward
            c = asyncio.create_task(b.embed(["t-10", "t-11", "t-12",
                                             "t-13"]))
            c.add_done_callback(lambda _: order.append("b"))
            await asyncio.sleep(0.05)
            # both flushes in the air: the second dispatched while the
            # first forward still runs — the double-buffered window
            assert metrics.gauge_get("batcher.inflight", labels=labels) == 2
            va, vb = await asyncio.gather(a, c)
            # strict per-submission result mapping despite B finishing first
            assert order == ["b", "a"]
            np.testing.assert_array_equal(va[:, 0], [0, 1, 2, 3])
            np.testing.assert_array_equal(vb[:, 0], [10, 11, 12, 13])
            assert metrics.gauge_get("batcher.overlap_ratio",
                                     labels=labels) > 0.1
        finally:
            await b.close()
        assert eng.calls == 2

    asyncio.run(scenario())


def test_store_executor_is_bounded_and_shared():
    ex = store_executor()
    assert ex is store_executor()
    assert ex._max_workers == 2


def test_coalescer_rejects_bad_shapes():
    async def scenario():
        c = UpsertCoalescer(lambda *a: 0, max_rows=4, max_age_ms=10)
        await c.start()
        try:
            with pytest.raises(ValueError):
                await c.add(["a"], np.ones((2, DIM), np.float32), [{}])
            with pytest.raises(ValueError):
                await c.add(["a", "b"], np.ones((2, DIM), np.float32), [{}])
        finally:
            await c.stop()

    asyncio.run(scenario())
