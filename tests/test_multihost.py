"""Multi-host bring-up, actually demonstrated (round-2 verdict ask #1).

`init_distributed` (symbiont_tpu/parallel/mesh.py) wraps
jax.distributed.initialize and docs/DEPLOYMENT.md Topology 3 describes the
multi-host deployment — but until this test nothing ever ran ≥2 processes.
Here TWO separate CPU processes (4 virtual devices each) form a real
jax.distributed cluster over a localhost coordinator, build ONE 8-device
mesh spanning both, and run ONE data-parallel train step whose gradient
psum crosses the process boundary — the SURVEY.md §4.4 promise ("test
multi-node without a real cluster") kept end-to-end.

Both workers must report the SAME loss and the same global batch sum: the
only way that happens is if the collectives really moved data between the
two processes.
"""

import os
import re
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
WORKER = REPO / "tests" / "multihost_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.parametrize("mode", ["dp", "tp"])
def test_two_process_train_step(mode):
    """mode='dp': gradient psum over 'data' crosses processes.
    mode='tp': megatron-sharded params whose 'tensor' axis pairs devices
    ACROSS the two processes — every TP collective rides the cross-host
    link (the distributed story beyond batch parallelism)."""
    port = _free_port()
    n_procs, local_devs = 2, 4

    def env_for(pid: int) -> dict:
        env = dict(os.environ)
        # each worker is its own "host" with its own local devices; scrub the
        # parent pytest env so the worker's device view is self-contained
        env.pop("XLA_FLAGS", None)
        env.update(
            PYTHONPATH=str(REPO),  # worker runs with script-dir sys.path[0]
            JAX_PLATFORMS="cpu",
            XLA_FLAGS=f"--xla_force_host_platform_device_count={local_devs}",
            SYMBIONT_COORDINATOR=f"127.0.0.1:{port}",
            SYMBIONT_NUM_PROCESSES=str(n_procs),
            SYMBIONT_PROCESS_ID=str(pid),
            SYMBIONT_MULTIHOST_MODE=mode,
        )
        return env

    procs = [subprocess.Popen([sys.executable, str(WORKER)],
                              env=env_for(pid), cwd=str(REPO),
                              stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                              text=True)
             for pid in range(n_procs)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:\n{out}\nstderr:\n{err}"

    reports = []
    for _, out, _ in outs:
        m = re.search(r"MULTIHOST ok global=(\d+) local=(\d+) procs=(\d+) "
                      r"loss=([\d.]+) sum=(\d+)", out)
        assert m, f"no MULTIHOST report in output:\n{out}"
        reports.append(m.groups())

    # both processes saw the same 8-device world...
    assert all(r[0] == "8" and r[1] == "4" and r[2] == "2" for r in reports), \
        reports
    # ...and agreed bit-for-bit on the cross-process collective results
    assert reports[0][3] == reports[1][3], f"loss diverged: {reports}"
    assert reports[0][4] == reports[1][4], f"global sum diverged: {reports}"
