"""Wire-level request fixtures for the external Qdrant / Neo4j adapters.

The adapters' behavioral tests (test_qdrant_backend.py / test_neo4j_backend.py)
run against in-process fakes, which proves the adapter against OUR idea of the
products. This tier is independent of those fakes: a recording HTTP server
captures every request the adapters emit — method, path, auth, raw body
BYTES — and asserts them against fixtures transcribed from the real products'
public API documentation:

- Qdrant REST API (api.qdrant.tech; parity target: what the reference writes
  through qdrant-client/gRPC, services/vector_memory_service/src/main.rs:
  24-119 collection create, :121-228 upsert, :230-456 search):
    PUT  /collections/{name}                 {"vectors":{"size","distance"}}
    PUT  /collections/{name}/points?wait=true {"points":[{"id","vector","payload"}]}
    POST /collections/{name}/points/search   {"vector","limit","with_payload","with_vector"}
    POST /collections/{name}/points/count    {"exact"}
  Quirk checks: distance enum is capitalized "Cosine"; point ids must be
  unsigned ints or UUIDs (arbitrary strings are rejected by real Qdrant).
- Neo4j HTTP API (/db/{database}/tx/commit, the documented transactional
  endpoint; parity target: knowledge_graph_service/src/main.rs:23-140):
    {"statements":[{"statement": cypher, "parameters": {...}}]}
  with Basic auth, and responses in {"results":[{"columns","data":[{"row"}]}],
  "errors":[]} shape.

Byte-level: raw request bodies are compared against json.dumps of the
fixture dicts (field order included), so any serialization drift shows up.
"""

import base64
import http.server
import json
import re
import threading
import uuid

import pytest

from symbiont_tpu.config import GraphStoreConfig, VectorStoreConfig
from symbiont_tpu.graph.neo4j_backend import Neo4jGraphStore
from symbiont_tpu.memory.qdrant_backend import QdrantStore
from symbiont_tpu.schema import TokenizedTextMessage
from symbiont_tpu.utils.ids import deterministic_point_id


class _Recorder:
    """Records (method, path, headers, body bytes); replies from a canned
    route table whose response JSONs are transcribed from the API docs."""

    def __init__(self, routes):
        self.requests = []
        recorder = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _serve(self):
                n = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(n) if n else b""
                recorder.requests.append(
                    (self.command, self.path, dict(self.headers), body))
                for (method, pattern), reply in routes.items():
                    if method == self.command and re.fullmatch(pattern,
                                                               self.path):
                        out = json.dumps(reply).encode()
                        self.send_response(200)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(out)))
                        self.end_headers()
                        self.wfile.write(out)
                        return
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()

            do_GET = do_POST = do_PUT = _serve

            def log_message(self, *a):
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                      Handler)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"

    def close(self):
        self.server.shutdown()


# ------------------------------------------------------------------- qdrant

# response shapes per the Qdrant REST docs
QDRANT_ROUTES = {
    ("PUT", r"/collections/[\w-]+"): {"result": True, "status": "ok",
                                      "time": 0.001},
    ("PUT", r"/collections/[\w-]+/points\?wait=true"): {
        "result": {"operation_id": 0, "status": "completed"},
        "status": "ok", "time": 0.002},
    ("POST", r"/collections/[\w-]+/points/search"): {
        "result": [{"id": "b2f5e0c2-0000-4000-8000-000000000001",
                    "version": 3, "score": 0.93,
                    "payload": {"sentence_text": "doc-hit"}}],
        "status": "ok", "time": 0.003},
    ("POST", r"/collections/[\w-]+/points/count"): {
        "result": {"count": 42}, "status": "ok", "time": 0.001},
}


@pytest.fixture()
def qdrant():
    rec = _Recorder(QDRANT_ROUTES)
    store = QdrantStore(VectorStoreConfig(
        dim=768, uri=rec.url, collection="symbiont_document_embeddings"),
        retries=1, retry_delay_s=0.0)
    yield rec, store
    rec.close()


def test_qdrant_collection_create_wire_shape(qdrant):
    """Collection create: 768-dim cosine, the reference's exact geometry
    (main.rs:20-22,34-42). Distance enum MUST be capitalized 'Cosine' — real
    Qdrant rejects 'cosine'."""
    rec, store = qdrant
    store.ensure_collection()
    method, path, headers, body = rec.requests[0]
    assert (method, path) == ("PUT",
                              "/collections/symbiont_document_embeddings")
    assert headers["Content-Type"] == "application/json"
    expected = {"vectors": {"size": 768, "distance": "Cosine"},
                "on_disk_payload": True}
    assert body == json.dumps(expected).encode()  # byte-level


def test_qdrant_upsert_wire_shape(qdrant):
    """Upsert: wait=true durability (main.rs:196), one point per sentence
    with the 6-field payload (main.rs:142-177), ids UUID-formatted (real
    Qdrant accepts only u64 or UUID ids)."""
    rec, store = qdrant
    pid = deterministic_point_id("doc-1", 0)
    uuid.UUID(pid)  # the real-product id constraint, enforced at test level
    payload = {"original_document_id": "doc-1", "source_url": "http://x",
               "sentence_text": "hello world", "sentence_order": 0,
               "model_name": "minilm", "processed_at_ms": 123}
    assert store.upsert([(pid, [0.25, -1.0, 0.5], payload)]) == 1
    method, path, _, body = rec.requests[0]
    assert method == "PUT"
    assert path == ("/collections/symbiont_document_embeddings/points"
                    "?wait=true")
    expected = {"points": [{"id": pid, "vector": [0.25, -1.0, 0.5],
                            "payload": payload}]}
    assert body == json.dumps(expected).encode()  # byte-level


def test_qdrant_bulk_upsert_chunks_requests(qdrant):
    """Real Qdrant rejects request bodies over its JSON cap (32MB default),
    so bulk upserts must split into multiple PUTs — each still wait=true."""
    rec, store = qdrant
    n = store.UPSERT_CHUNK * 2 + 17  # forces 3 requests
    pts = [(deterministic_point_id("bulk", i), [0.0, 1.0, 2.0],
            {"sentence_order": i}) for i in range(n)]
    assert store.upsert(pts) == n
    assert len(rec.requests) == 3
    sizes = [len(json.loads(b)["points"]) for _, _, _, b in rec.requests]
    assert sizes == [store.UPSERT_CHUNK, store.UPSERT_CHUNK, 17]
    for _, path, _, _ in rec.requests:
        assert path.endswith("/points?wait=true")


def test_qdrant_search_wire_shape(qdrant):
    """Search: top-k with payload on, vectors off (main.rs:261-286), and the
    documented {"result": [hits]} response decoded into SearchHits."""
    rec, store = qdrant
    hits = store.search([0.5, 0.25, 0.125], top_k=5)
    method, path, _, body = rec.requests[0]
    assert method == "POST"
    assert path == "/collections/symbiont_document_embeddings/points/search"
    expected = {"vector": [0.5, 0.25, 0.125], "limit": 5,
                "with_payload": True, "with_vector": False}
    assert body == json.dumps(expected).encode()  # byte-level
    assert len(hits) == 1
    assert hits[0].id == "b2f5e0c2-0000-4000-8000-000000000001"
    assert hits[0].score == pytest.approx(0.93)
    assert hits[0].payload == {"sentence_text": "doc-hit"}


def test_qdrant_count_wire_shape(qdrant):
    rec, store = qdrant
    assert store.count() == 42
    method, path, _, body = rec.requests[0]
    assert (method, path) == (
        "POST", "/collections/symbiont_document_embeddings/points/count")
    assert body == json.dumps({"exact": True}).encode()  # byte-level


# -------------------------------------------------------------------- neo4j

NEO4J_ROUTES = {
    # documented commit-endpoint response shape
    ("POST", r"/db/neo4j/tx/commit"): {
        "results": [{"columns": ["id(d)"], "data": [{"row": [7],
                                                     "meta": [None]}]}],
        "errors": []},
}


@pytest.fixture()
def neo4j():
    rec = _Recorder(NEO4J_ROUTES)
    store = Neo4jGraphStore(GraphStoreConfig(
        uri=rec.url, user="neo4j", password="secret", database="neo4j"),
        retries=1, retry_delay_s=0.0)
    yield rec, store
    rec.close()


def test_neo4j_tx_commit_wire_shape(neo4j):
    """save_tokenized: ONE POST to the documented transactional commit
    endpoint (single explicit transaction, main.rs:32-134) with Basic auth
    and {"statements": [{statement, parameters}]} framing."""
    rec, store = neo4j
    msg = TokenizedTextMessage(
        original_id="doc-9", source_url="http://src",
        sentences=["First sentence.", "  ", "Second one."],
        tokens=["First", "", "sentence"], timestamp_ms=777)
    assert store.save_tokenized(msg) == 7
    assert len(rec.requests) == 1  # one transaction, not N requests
    method, path, headers, body = rec.requests[0]
    assert (method, path) == ("POST", "/db/neo4j/tx/commit")
    assert headers["Content-Type"] == "application/json"
    assert headers["Authorization"] == \
        "Basic " + base64.b64encode(b"neo4j:secret").decode()
    doc = json.loads(body)
    assert set(doc) == {"statements"}
    for stmt in doc["statements"]:
        assert set(stmt) == {"statement", "parameters"}
    # document MERGE first, with the reference's exact property set
    s0 = doc["statements"][0]
    assert "MERGE (d:Document {original_id: $original_id})" in s0["statement"]
    assert s0["parameters"] == {"original_id": "doc-9",
                                "source_url": "http://src", "ts": 777}
    # blank sentence and empty token are skipped (main.rs:71-77,103-109):
    # 1 doc + 2 sentences + 2 tokens = 5 statements
    assert len(doc["statements"]) == 5
    orders = [s["parameters"]["order"] for s in doc["statements"]
              if "HAS_SENTENCE" in s["statement"]]
    assert orders == [0, 2]  # original positions survive the skip


def test_neo4j_ensure_schema_wire_shape(neo4j):
    """Schema ensure: unique constraint + text_lc index as separate commits
    (schema DDL cannot share a transaction with other DDL in one statement
    list on real Neo4j versions; the adapter sends one commit each)."""
    rec, store = neo4j
    store.ensure_schema()
    assert len(rec.requests) == 2
    bodies = [json.loads(b) for _, _, _, b in rec.requests]
    assert "REQUIRE d.original_id IS UNIQUE" in \
        bodies[0]["statements"][0]["statement"]
    assert "FOR (t:Token) ON (t.text_lc)" in \
        bodies[1]["statements"][0]["statement"]
    for b in bodies:
        assert b["statements"][0]["parameters"] == {}


def test_neo4j_error_response_raises(neo4j):
    """The documented errors[] array must fail the write loudly — real Neo4j
    returns HTTP 200 with errors populated, so status-code checking alone
    would silently drop documents."""
    rec, store = neo4j
    rec.server.shutdown()
    rec2 = _Recorder({("POST", r"/db/neo4j/tx/commit"): {
        "results": [],
        "errors": [{"code": "Neo.ClientError.Statement.SyntaxError",
                    "message": "bad cypher"}]}})
    store.base = rec2.url
    msg = TokenizedTextMessage(original_id="d", source_url="u",
                               sentences=["s"], tokens=["t"], timestamp_ms=1)
    with pytest.raises(RuntimeError, match="SyntaxError"):
        store.save_tokenized(msg)
    rec2.close()
