"""docs/OBSERVABILITY.md must not drift from the metrics the code registers.

Same discipline as tests/test_perf_doc.py, pointed at the series tables: a
stub-engine runner stack is booted and driven through one ingest + one
metrics scrape, and every metric family REGISTERED at runtime must then
appear in an OBSERVABILITY.md table row (or match the explicit
dynamic-name allowlist below). A new counter merged without its doc row
fails here, mechanically — doc coverage stops being a review nicety.

The reverse direction is deliberately not enforced: the doc also tables
series this boot cannot produce (TCP bus, breakers, LM decode, devices) —
documenting more than one stub boot exercises is correct, not drift.
"""

import asyncio
import json
import re
import urllib.request
from pathlib import Path

import numpy as np

from symbiont_tpu.utils.telemetry import metrics

REPO = Path(__file__).resolve().parent.parent

# dynamic-name families: per-span / per-route series whose NAMES embed
# runtime values — documented once by convention, not one row per name
ALLOWED_DYNAMIC = (
    re.compile(r"^span\."),           # span.<name>.ms / span.<name>.errors
    re.compile(r"^api\.(GET|POST)\."),  # api.<METHOD>.<route> counters
    # engine-plane per-op request counters: engine.<op> (+ .failed), one
    # per engine.* bus subject served (services/engine_service.py)
    re.compile(r"^engine\.[a-z_]+\.[a-z_.]+$"),
)


def _documented_families(doc: str) -> set:
    """Every backticked series name in a markdown TABLE row, label part
    stripped: "`bus.dropped{subject}`" → "bus.dropped"."""
    fams = set()
    for line in doc.splitlines():
        if not line.lstrip().startswith("|"):
            continue
        for token in re.findall(r"`([^`]+)`", line):
            name = token.split("{", 1)[0].strip()
            if re.fullmatch(r"[a-zA-Z0-9_.]+", name):
                fams.add(name)
    return fams


class _StubEngine:
    class _ModelCfg:
        hidden_size = 16

    def __init__(self):
        from symbiont_tpu.config import EngineConfig

        self.config = EngineConfig(embedding_dim=16, max_batch=8,
                                   flush_deadline_ms=2.0)
        self.model_cfg = self._ModelCfg()
        self.cross_params = None
        self.stats = {"embed_calls": 0, "compiles": 0}

    def embed_texts(self, texts):
        rng = np.random.default_rng(len(texts))
        return rng.standard_normal((len(texts), 16)).astype(np.float32)


def _boot_and_collect(tmp_path) -> set:
    """Boot the stub stack, push one document through the pipeline, scrape
    /metrics once, and return every registered metric family name."""
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.config import (
        ApiConfig,
        GraphStoreConfig,
        SymbiontConfig,
        TextGeneratorConfig,
        VectorStoreConfig,
    )
    from symbiont_tpu.runner import SymbiontStack

    page = ("<html><body><main><p>Doc drift check sentence one.</p>"
            "<p>Doc drift check sentence two!</p></main></body></html>")
    cfg = SymbiontConfig(
        vector_store=VectorStoreConfig(dim=16,
                                       data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(markov_state_path=None),
        api=ApiConfig(host="127.0.0.1", port=0),
    )
    cfg.runner.services = ("perception,preprocessing,vector_memory,"
                           "knowledge_graph,text_generator,api")
    # a named role turns the fleet telemetry plane on (obs/fleet.py):
    # exporter + aggregator register their `fleet.*` families at start,
    # so every one of them is doc-drift-enforced on this boot too
    cfg.runner.role = "drift"

    async def scenario() -> set:
        stack = SymbiontStack(cfg, bus=InprocBus(), engine=_StubEngine(),
                              fetcher=lambda url: page)
        await stack.start()
        loop = asyncio.get_running_loop()
        port = stack.api.port
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/submit-url",
                data=json.dumps({"url": "http://fake/doc"}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            assert (await loop.run_in_executor(
                None, lambda: urllib.request.urlopen(req, timeout=10))
                ).status == 200
            for _ in range(200):
                if stack.vector_store.count() >= 2:
                    break
                await asyncio.sleep(0.05)
            assert stack.vector_store.count() >= 2
            # scrape once so scrape-path series (if any) register too
            await loop.run_in_executor(None, lambda: urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10).read())
            ex = metrics.export()
            return ({n for n, _, _ in ex["counters"]}
                    | {n for n, _, _ in ex["gauges"]}
                    | {n for n, _, _ in ex["histograms"]})
        finally:
            await stack.stop()

    return asyncio.run(scenario())


def test_every_registered_family_is_documented(tmp_path):
    registered = _boot_and_collect(tmp_path)
    assert len(registered) >= 15, registered  # the boot really ran
    # PR 15 families must be IN the sweep (registered at boot / by the one
    # ingest), or the doc-drift contract silently stops covering them:
    # usage metering counters, the tail-retention gauges, and the
    # engine-timeline gauge all register on this stub boot
    for family in ("tenant.usage.tokens_in", "tenant.usage.tokens_out",
                   "tenant.usage.embed_rows", "tenant.usage.search_queries",
                   "tenant.usage.kv_row_seconds", "obs.trace_pinned_traces",
                   "obs.trace_sampled_out", "obs.trace_pin_evicted",
                   "obs.timeline_events"):
        assert family in registered, (
            f"{family} no longer registers on the stub boot — the "
            "doc-drift sweep has a blind spot")
    doc = (REPO / "docs" / "OBSERVABILITY.md").read_text()
    documented = _documented_families(doc)
    def covered(name: str) -> bool:
        # a family may be tabled under its registry name (dots) or its
        # rendered exposition name (process.open_fds → process_open_fds)
        for cand in (name, name.replace(".", "_")):
            if any(cand == fam or cand.startswith(fam + ".")
                   for fam in documented):
                return True
        return False

    missing = sorted(
        name for name in registered
        if not any(rx.match(name) for rx in ALLOWED_DYNAMIC)
        and not covered(name))
    assert not missing, (
        "metric families registered at runtime but absent from every "
        f"docs/OBSERVABILITY.md series table: {missing} — add a table row "
        "(or, for a name that embeds runtime values, extend "
        "ALLOWED_DYNAMIC in this test)")


def test_documented_allowlist_patterns_are_used():
    """Guard the allowlist itself: every pattern must still match at least
    one name the doc's conventions section describes — a stale pattern
    would silently exempt future families."""
    for rx, example in ((ALLOWED_DYNAMIC[0], "span.api.search.ms"),
                        (ALLOWED_DYNAMIC[1], "api.POST./api/submit-url"),
                        (ALLOWED_DYNAMIC[2], "engine.query.search")):
        assert rx.match(example), (rx.pattern, example)
    # and the op-counter pattern must NOT swallow the static engine series
    assert not ALLOWED_DYNAMIC[2].match("engine.no_reply_inbox")
    assert not ALLOWED_DYNAMIC[2].match("engine.compiles")
