"""End-to-end pipeline tests over the in-proc bus + real HTTP/SSE surface.

The integration tier the reference never had (SURVEY.md §4: "the implicit
integration test is manual docker-compose + curl"). Covers the three call
stacks of SURVEY.md §3: ingest (3.1), search (3.2), generate→SSE (3.3), plus
the restored knowledge-graph path (3.5).
"""

import asyncio
import json
import urllib.request

import numpy as np
import pytest

from symbiont_tpu.bus.inproc import InprocBus
from symbiont_tpu.config import (
    ApiConfig,
    EngineConfig,
    GraphStoreConfig,
    SymbiontConfig,
    TextGeneratorConfig,
    VectorStoreConfig,
)
from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.runner import SymbiontStack

FAKE_PAGES = {
    "http://example.com/doc1": """
      <html><body><article>
        <h1>Symbiont systems</h1>
        <p>TPUs accelerate matrix multiplication. They excel at embeddings!</p>
        <p>Vector memory stores every sentence.</p>
      </article></body></html>""",
    "http://example.com/doc2": """
      <html><body><main>
        <p>Knowledge graphs link tokens to documents. Search finds meaning?</p>
      </main></body></html>""",
}


def _fake_fetcher(url: str) -> str:
    if url in FAKE_PAGES:
        return FAKE_PAGES[url]
    raise OSError(f"unreachable {url}")


@pytest.fixture()
def stack_config(tmp_path):
    return SymbiontConfig(
        engine=EngineConfig(embedding_dim=32, length_buckets=[16, 32],
                            batch_buckets=[2, 8], max_batch=8, dtype="float32",
                            data_parallel=False, flush_deadline_ms=2.0,
                            rerank_enabled=True),
        vector_store=VectorStoreConfig(dim=32, data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(
            markov_state_path=str(tmp_path / "markov.json")),
        api=ApiConfig(host="127.0.0.1", port=0, sse_keepalive_s=0.5),
    )


async def _start_stack(stack_config):
    stack = SymbiontStack(stack_config, bus=InprocBus(), fetcher=_fake_fetcher)
    await stack.start()
    return stack


def _http(method, port, path, body=None, timeout=10):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"}, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


async def _wait_until(pred, timeout=90.0):
    # generous default: the first embed compiles its executables, which can
    # take tens of seconds when the whole suite loads the machine
    t = 0.0
    while t < timeout:
        if pred():
            return True
        await asyncio.sleep(0.05)
        t += 0.05
    return False


def test_ingest_search_generate_roundtrip(stack_config):
    async def scenario():
        stack = await _start_stack(stack_config)
        port = stack.api.port
        loop = asyncio.get_running_loop()

        def http(*a, **kw):
            return loop.run_in_executor(None, lambda: _http(*a, **kw))

        try:
            # --- 3.1 ingest ---------------------------------------------
            status, body = await http("POST", port, "/api/submit-url",
                                      {"url": "http://example.com/doc1"})
            assert status == 200
            assert "submitted successfully" in body["message"]
            await http("POST", port, "/api/submit-url",
                       {"url": "http://example.com/doc2"})
            ok = await _wait_until(lambda: stack.vector_store.count() >= 5)
            assert ok, f"pipeline stalled; count={stack.vector_store.count()}"

            # --- 3.2 search (2-hop request-reply) ------------------------
            status, body = await http("POST", port, "/api/search/semantic",
                                      {"query_text": "matrix multiplication",
                                       "top_k": 3})
            assert status == 200, body
            assert body["error_message"] is None
            assert len(body["results"]) == 3
            hit = body["results"][0]
            assert set(hit) == {"qdrant_point_id", "score", "payload"}
            assert set(hit["payload"]) == {
                "original_document_id", "source_url", "sentence_text",
                "sentence_order", "model_name", "processed_at_ms"}

            # the search above was served by the fused embed+top-k path
            # (engine and store co-located in this stack)
            status, body = await http("GET", port, "/api/metrics")
            assert status == 200
            assert body["counters"].get("api.fused_search", 0) >= 1

            # Prometheus exposition over the SAME run: the engine-plane
            # gauges (compile count, batch fill ratio, batcher queue depth)
            # carry service labels (obs acceptance criterion)
            def fetch_metrics():
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                    return r.status, r.headers["Content-Type"], \
                        r.read().decode()

            status, ctype, text = await loop.run_in_executor(
                None, fetch_metrics)
            assert status == 200 and ctype.startswith("text/plain")
            assert 'symbiont_engine_compiles{service="engine"}' in text
            assert ('symbiont_engine_batch_fill_ratio{service="engine"}'
                    in text)
            assert 'symbiont_batcher_queue_depth{batcher="embed"' in text
            assert "# TYPE symbiont_span_duration_ms summary" in text

            # --- 3.2b search + cross-encoder rerank (BASELINE #4) --------
            status, body = await http("POST", port, "/api/search/semantic",
                                      {"query_text": "matrix multiplication",
                                       "top_k": 3, "rerank": True})
            assert status == 200, body
            assert body["error_message"] is None
            scores = [r["score"] for r in body["results"]]
            assert len(scores) == 3
            assert scores == sorted(scores, reverse=True)

            # --- 3.5 knowledge graph (un-orphaned) -----------------------
            ok = await _wait_until(
                lambda: stack.graph_store.counts()["Document"] >= 2)
            assert ok
            docs = stack.graph_store.documents_containing_token("tpus")
            assert len(docs) == 1

            # --- 3.3 generate → SSE --------------------------------------
            sse_lines: list = []

            async def sse_reader():
                reader, writer = await asyncio.open_connection("127.0.0.1", port)
                writer.write(b"GET /api/events HTTP/1.1\r\nHost: x\r\n\r\n")
                await writer.drain()
                while True:
                    line = await reader.readline()
                    if line.startswith(b"data: "):
                        sse_lines.append(line[6:].strip())
                        break
                writer.close()

            reader_task = asyncio.create_task(sse_reader())
            await asyncio.sleep(0.2)
            status, body = await http("POST", port, "/api/generate-text",
                                      {"task_id": "t-1", "prompt": None,
                                       "max_length": 10})
            assert status == 200
            await asyncio.wait_for(reader_task, timeout=10)
            event = json.loads(sse_lines[0])
            assert event["original_task_id"] == "t-1"
            assert event["generated_text"]

            # trained-on-ingest: generator saw scraped docs, so vocabulary
            # beyond the seed corpus is reachable
            textgen = next(s for s in stack.services if s.name == "text_generator")
            assert textgen.markov.chain  # non-empty
        finally:
            await stack.stop()

    asyncio.run(scenario())


def test_api_validation_parity(stack_config):
    async def scenario():
        stack = await _start_stack(stack_config)
        port = stack.api.port
        loop = asyncio.get_running_loop()

        def http(*a, **kw):
            return loop.run_in_executor(None, lambda: _http(*a, **kw))

        try:
            # empty URL → 400 (reference: main.rs:48-53)
            status, body = await http("POST", port, "/api/submit-url", {"url": "  "})
            assert (status, body["message"]) == (400, "URL cannot be empty")
            # empty task_id → 400 (main.rs:125-131)
            status, body = await http("POST", port, "/api/generate-text",
                                      {"task_id": " ", "prompt": None,
                                       "max_length": 5})
            assert (status, body["message"]) == (400, "task_id cannot be empty")
            # max_length out of range → 400 with task_id echoed (main.rs:133-142)
            status, body = await http("POST", port, "/api/generate-text",
                                      {"task_id": "t", "prompt": None,
                                       "max_length": 1001})
            assert status == 400
            assert body["message"] == "max_length must be between 1 and 1000"
            assert body["task_id"] == "t"
            # unknown route
            status, _ = await http("GET", port, "/api/nope")
            assert status == 404
            # metrics + health (our additions)
            status, body = await http("GET", port, "/api/metrics")
            assert status == 200 and "counters" in body
            status, body = await http("GET", port, "/healthz")
            assert status == 200 and body["status"] == "ok"
            # engine-plane health over HTTP (one bus hop to engine.health)
            status, body = await http("GET", port, "/api/health/engine")
            assert status == 200 and body["ok"] is True
            assert body["backends"]["embed"] is True
            assert "vector_count" in body
            # bundled UI at GET / (executor: urlopen must not block the loop
            # the server runs on)
            def fetch_root():
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/", timeout=10) as r:
                    return r.status, r.headers["Content-Type"], r.read().decode()

            status, ctype, page = await loop.run_in_executor(None, fetch_root)
            assert status == 200 and ctype.startswith("text/html")
            assert "symbiont-tpu" in page
        finally:
            await stack.stop()

    asyncio.run(scenario())


def test_search_timeout_maps_to_503(stack_config):
    """No preprocessing service running → embed hop times out → 503
    (reference status mapping, main.rs:317-349)."""

    async def scenario():
        from symbiont_tpu.bus.inproc import InprocBus
        from symbiont_tpu.config import BusConfig
        from symbiont_tpu.services.api import ApiService

        bus = InprocBus()
        api = ApiService(bus, ApiConfig(host="127.0.0.1", port=0,
                                        fused_search=False),
                         BusConfig(request_timeout_embed_s=0.2,
                                   request_timeout_health_s=0.2))
        await api.start()
        loop = asyncio.get_running_loop()
        try:
            status, body = await loop.run_in_executor(
                None, lambda: _http("POST", api.port, "/api/search/semantic",
                                    {"query_text": "q", "top_k": 1}))
            assert status == 503
            assert "Failed to get embedding" in body["error_message"]
            # engine health with no engine plane → 503, not a hang
            status, body = await loop.run_in_executor(
                None, lambda: _http("GET", api.port, "/api/health/engine"))
            assert status == 503 and body["ok"] is False
        finally:
            await api.stop()

    asyncio.run(scenario())


def test_rerank_timeout_maps_to_503(stack_config):
    """Embed + search hops answered, rerank hop unanswered → 503 (same status
    scheme as the reference's hop timeouts, main.rs:317-349)."""

    async def scenario():
        from symbiont_tpu import subjects
        from symbiont_tpu.config import BusConfig
        from symbiont_tpu.schema import (
            QueryEmbeddingResult,
            QueryForEmbeddingTask,
            SemanticSearchNatsResult,
            SemanticSearchResultItem,
            QdrantPointPayload,
            from_json,
            to_json_bytes,
        )
        from symbiont_tpu.services.api import ApiService

        bus = InprocBus()

        async def embed_responder():
            sub = await bus.subscribe(subjects.TASKS_EMBEDDING_FOR_QUERY)
            async for msg in sub:
                task = from_json(QueryForEmbeddingTask, msg.data)
                await bus.publish(msg.reply, to_json_bytes(QueryEmbeddingResult(
                    request_id=task.request_id, embedding=[0.1, 0.2],
                    model_name="m", error_message=None)))

        async def search_responder():
            sub = await bus.subscribe(subjects.TASKS_SEARCH_SEMANTIC_REQUEST)
            payload = QdrantPointPayload(
                original_document_id="d", source_url="u", sentence_text="s",
                sentence_order=0, model_name="m", processed_at_ms=1)
            async for msg in sub:
                await bus.publish(msg.reply, to_json_bytes(SemanticSearchNatsResult(
                    request_id="r", results=[SemanticSearchResultItem(
                        qdrant_point_id="p", score=0.5, payload=payload)],
                    error_message=None)))

        tasks = [asyncio.create_task(embed_responder()),
                 asyncio.create_task(search_responder())]
        await asyncio.sleep(0)  # let responders subscribe
        api = ApiService(bus, ApiConfig(host="127.0.0.1", port=0,
                                        fused_search=False),
                         BusConfig(request_timeout_rerank_s=0.2))
        await api.start()
        loop = asyncio.get_running_loop()
        try:
            status, body = await loop.run_in_executor(
                None, lambda: _http("POST", api.port, "/api/search/semantic",
                                    {"query_text": "q", "top_k": 1,
                                     "rerank": True}))
            assert status == 503
            assert "Failed to get rerank scores" in body["error_message"]
        finally:
            await api.stop()
            for t in tasks:
                t.cancel()

    asyncio.run(scenario())


def test_scrape_failure_drops_silently(stack_config):
    """Unreachable URL: 200 at submit (fire-and-forget enqueue ack,
    reference main.rs:91-98), then nothing downstream."""

    async def scenario():
        stack = await _start_stack(stack_config)
        port = stack.api.port
        loop = asyncio.get_running_loop()
        try:
            status, _ = await loop.run_in_executor(
                None, lambda: _http("POST", port, "/api/submit-url",
                                    {"url": "http://unreachable.example"}))
            assert status == 200
            await asyncio.sleep(0.3)
            assert stack.vector_store.count() == 0
        finally:
            await stack.stop()

    asyncio.run(scenario())


def test_oversized_body_rejected(stack_config):
    """Content-length beyond the 16MB cap gets a 413 status (not a silently
    dropped socket) and the body is never buffered; an unparseable
    Content-Length gets a 400."""

    async def scenario():
        from symbiont_tpu.config import BusConfig
        from symbiont_tpu.services.api import ApiService

        api = ApiService(InprocBus(), ApiConfig(host="127.0.0.1", port=0),
                         BusConfig())
        await api.start()
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1", api.port)
            writer.write(b"POST /api/submit-url HTTP/1.1\r\nHost: x\r\n"
                         b"Content-Length: 999999999999\r\n\r\n")
            await writer.drain()
            got = await asyncio.wait_for(reader.read(4096), 5)
            assert got.startswith(b"HTTP/1.1 413 ")
            assert b"16MB" in got
            # server closed after answering (keep_alive=False)
            assert await asyncio.wait_for(reader.read(100), 5) == b""
            writer.close()

            reader, writer = await asyncio.open_connection("127.0.0.1", api.port)
            writer.write(b"POST /api/submit-url HTTP/1.1\r\nHost: x\r\n"
                         b"Content-Length: banana\r\n\r\n")
            await writer.drain()
            got = await asyncio.wait_for(reader.read(4096), 5)
            assert got.startswith(b"HTTP/1.1 400 ")
            writer.close()
        finally:
            await api.stop()

    asyncio.run(scenario())


def test_lm_backend_generate_roundtrip(tmp_path):
    """Full stack with the LM backend enabled: generate-text rides the
    generation micro-batcher through the runner wiring, prompt actually
    used (unlike the reference's Markov, main.rs:120-123)."""
    from symbiont_tpu.config import LmConfig

    cfg = SymbiontConfig(
        engine=EngineConfig(embedding_dim=32, length_buckets=[16, 32],
                            batch_buckets=[2, 8], max_batch=8, dtype="float32",
                            data_parallel=False, flush_deadline_ms=2.0),
        lm=LmConfig(enabled=True, hidden_size=32, num_layers=1, num_heads=2,
                    intermediate_size=64, max_positions=64, dtype="float32",
                    prompt_buckets=[8], new_token_buckets=[8],
                    gen_flush_deadline_ms=5.0),
        vector_store=VectorStoreConfig(dim=32, data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(
            markov_state_path=str(tmp_path / "markov.json")),
        api=ApiConfig(host="127.0.0.1", port=0, sse_keepalive_s=0.5),
    )

    async def scenario():
        stack = SymbiontStack(cfg, bus=InprocBus(), fetcher=_fake_fetcher)
        await stack.start()
        port = stack.api.port
        loop = asyncio.get_running_loop()
        try:
            assert stack._lm_batcher is not None

            sse_events: list = []

            async def sse_reader(n_finals):
                reader, writer = await asyncio.open_connection("127.0.0.1", port)
                writer.write(b"GET /api/events HTTP/1.1\r\nHost: x\r\n\r\n")
                await writer.drain()
                finals = 0
                while finals < n_finals:
                    line = await reader.readline()
                    if line.startswith(b"data: "):
                        e = json.loads(line[6:].strip())
                        sse_events.append(e)
                        finals += "generated_text" in e
                writer.close()

            reader_task = asyncio.create_task(sse_reader(3))
            await asyncio.sleep(0.2)
            # three concurrent requests → the batcher coalesces them
            for i in range(3):
                status, body = await loop.run_in_executor(None, lambda i=i: _http(
                    "POST", port, "/api/generate-text",
                    {"task_id": f"lm-{i}", "prompt": "seed", "max_length": 6,
                     "stream": True}))
                assert status == 200
            await asyncio.wait_for(reader_task, timeout=20)
            # per-request streaming (stream=true): the SSE channel carries
            # chunk deltas and final messages; per task, deltas
            # concatenated == the final generated_text
            finals = {e["original_task_id"]: e["generated_text"]
                      for e in sse_events if "generated_text" in e}
            assert set(finals) == {"lm-0", "lm-1", "lm-2"}
            for tid, full in finals.items():
                deltas = [e for e in sse_events
                          if e.get("original_task_id") == tid
                          and "text_delta" in e]
                assert deltas, f"no stream chunks for {tid}"
                assert deltas[-1]["done"] is True
                assert "".join(d["text_delta"] for d in deltas) == full
                assert [d["seq"] for d in deltas] == list(range(len(deltas)))
        finally:
            await stack.stop()

    asyncio.run(scenario())


def test_sse_task_id_filter():
    """Per-task SSE routing (?task_id=): the reference broadcasts every
    generation event to every SSE client (main.rs:215-270) and the UI
    correlates client-side; a filtered client must receive ONLY its task's
    events while unfiltered clients keep full-broadcast behavior."""
    from symbiont_tpu import subjects
    from symbiont_tpu.schema import GeneratedTextMessage, to_json_bytes
    from symbiont_tpu.services.api import ApiService
    from symbiont_tpu.utils.ids import current_timestamp_ms

    async def scenario():
        bus = InprocBus()
        api = ApiService(bus, ApiConfig(host="127.0.0.1", port=0,
                                        sse_keepalive_s=0.2))
        await api.start()
        port = api.port
        try:
            async def sse_client(query: str):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port)
                writer.write(f"GET /api/events{query} HTTP/1.1\r\n"
                             f"Host: x\r\n\r\n".encode())
                await writer.drain()
                await reader.readuntil(b"\r\n\r\n")
                return reader, writer

            plain = await sse_client("")
            only_a = await sse_client("?task_id=task-A")
            only_b = await sse_client("?task_id=task-B")
            await asyncio.sleep(0.2)

            for tid in ("task-A", "task-B", "task-A"):
                await bus.publish(subjects.EVENTS_TEXT_GENERATED,
                                  to_json_bytes(GeneratedTextMessage(
                                      original_task_id=tid,
                                      generated_text=f"text for {tid}",
                                      timestamp_ms=current_timestamp_ms())))

            async def read_events(reader, n, timeout=10.0):
                got = []
                async def pull():
                    while len(got) < n:
                        line = await reader.readline()
                        if line.startswith(b"data: "):
                            got.append(json.loads(line[6:]))
                try:
                    await asyncio.wait_for(pull(), timeout)
                except asyncio.TimeoutError:
                    pass
                return got

            plain_events = await read_events(plain[0], 3)
            a_events = await read_events(only_a[0], 2)
            # B expects exactly 1; wait briefly past it to catch leakage
            b_events = await read_events(only_b[0], 2, timeout=1.5)

            assert [e["original_task_id"] for e in plain_events] == \
                ["task-A", "task-B", "task-A"]  # unfiltered: sees all
            assert [e["original_task_id"] for e in a_events] == \
                ["task-A", "task-A"]
            assert [e["original_task_id"] for e in b_events] == ["task-B"]
            for r, w in (plain, only_a, only_b):
                w.close()
        finally:
            await api.stop()

    asyncio.run(scenario())


def test_fused_search_skips_large_top_k():
    """top_k above fused_search_max_top_k must bypass the fused probe
    entirely (return None fast, no bus request) — a cold large-k bucket
    would otherwise pay an XLA compile inside the probe timeout AND trip the
    negative cache for every other search."""
    import time

    from symbiont_tpu.config import BusConfig
    from symbiont_tpu.schema import SemanticSearchApiRequest
    from symbiont_tpu.services.api import ApiService

    async def scenario():
        # no engine service subscribed: a non-skipped probe would block for
        # the full 5s fused timeout
        api = ApiService(InprocBus(), ApiConfig(host="127.0.0.1", port=0),
                         BusConfig())
        req = SemanticSearchApiRequest(query_text="q", top_k=50)
        t0 = time.monotonic()
        assert await api._fused_search(req, {}) is None
        assert time.monotonic() - t0 < 1.0  # skipped, not timed out
        assert api._fused_down_until == 0.0  # negative cache untouched

    asyncio.run(scenario())


def test_generate_text_sampling_params_e2e(tmp_path):
    """VERDICT r1 item 5: per-request temperature/top_k ride the HTTP body →
    tasks.generation.text → GenBatcher → decode. Two greedy requests
    (temperature=0) produce identical text; a hot sampled request differs;
    out-of-range values 400 at the HTTP surface."""
    from symbiont_tpu import subjects
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.schema import GeneratedTextMessage, from_json

    cfg = SymbiontConfig(
        engine=EngineConfig(embedding_dim=32, length_buckets=[16, 32],
                            batch_buckets=[2, 8], max_batch=8, dtype="float32",
                            data_parallel=False, flush_deadline_ms=2.0),
        lm=LmConfig(enabled=True, hidden_size=32, num_layers=1, num_heads=2,
                    intermediate_size=64, max_positions=64, dtype="float32",
                    prompt_buckets=[8], new_token_buckets=[16],
                    temperature=0.0, gen_flush_deadline_ms=5.0),
        vector_store=VectorStoreConfig(dim=32, data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(
            markov_state_path=str(tmp_path / "markov.json")),
        api=ApiConfig(host="127.0.0.1", port=0, sse_keepalive_s=0.5),
    )

    async def scenario():
        bus = InprocBus()
        stack = SymbiontStack(cfg, bus=bus, fetcher=_fake_fetcher)
        await stack.start()
        port = stack.api.port
        loop = asyncio.get_running_loop()
        results: dict = {}
        sub = await bus.subscribe(subjects.EVENTS_TEXT_GENERATED)

        async def collect(n):
            async for msg in sub:
                out = from_json(GeneratedTextMessage, msg.data)
                results[out.original_task_id] = out.generated_text
                if len(results) >= n:
                    return

        def http(*a, **kw):
            return loop.run_in_executor(None, lambda: _http(*a, **kw))

        try:
            collector = asyncio.create_task(collect(3))
            for tid, extra in [("g1", {"temperature": 0.0}),
                               ("g2", {"temperature": 0.0}),
                               ("s1", {"temperature": 5.0, "top_k": 0})]:
                status, body = await http(
                    "POST", port, "/api/generate-text",
                    {"task_id": tid, "prompt": "once upon",
                     "max_length": 12, **extra})
                assert status == 200, body
            await asyncio.wait_for(collector, 60)

            assert results["g1"] == results["g2"]  # greedy is deterministic
            # 12 near-uniform byte tokens matching greedy is ~257^-12
            assert results["s1"] != results["g1"]

            # out-of-range values rejected at the HTTP surface
            status, body = await http("POST", port, "/api/generate-text",
                                      {"task_id": "bad", "prompt": None,
                                       "max_length": 5, "temperature": 99.0})
            assert status == 400 and "temperature" in body["message"]
            status, body = await http("POST", port, "/api/generate-text",
                                      {"task_id": "bad", "prompt": None,
                                       "max_length": 5, "top_k": 999999})
            assert status == 400 and "top_k" in body["message"]
        finally:
            await stack.stop()

    asyncio.run(scenario())
