"""Multi-chip SERVING plane gates (ROADMAP item 1) on the 8-virtual-device
CPU mesh.

test_parallel.py proves the parallel/ primitives (DP batch sharding, TP
forward, ring/Ulysses attention) in isolation; this module gates the LIVE
stack shapes the runner now builds from config:

- the runner constructs the mesh purely from `ParallelConfig` and threads
  it through TpuEngine, LmEngine, and the vector store — no caller-supplied
  mesh;
- DP embed through the mesh engine matches single-device (cosine parity on
  a fixed corpus) and the per-replica padding/shard-balance gauges account;
- corpus-sharded fused search (per-shard top-k + global merge,
  parallel/sharding.corpus_topk) returns IDENTICAL hits (ids, scores,
  order) to the single-device store, on both the store path and the fused
  engine path;
- TP greedy decode is token-identical to single-device through
  generate_batch AND a continuous-batching session with a mid-decode
  admit — including with int8-quantized weights (the PR 7 gap: QuantTensor
  leaves now shard with their scales instead of falling back).

Small geometries keep this in the fast tier; every test is seeded and
CPU-deterministic.
"""

import dataclasses

import numpy as np
import pytest

import jax

from symbiont_tpu.config import (
    EngineConfig,
    LmConfig,
    ParallelConfig,
    VectorStoreConfig,
)
from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.engine.lm import LmEngine
from symbiont_tpu.memory.vector_store import VectorStore
from symbiont_tpu.parallel import build_mesh, mesh_from_config, parse_mesh_spec
from symbiont_tpu.utils.telemetry import metrics

requires_8 = pytest.mark.skipif(len(jax.devices()) < 8,
                                reason="needs 8 devices")

ENG_KW = dict(embedding_dim=32, length_buckets=[8, 16], batch_buckets=[8, 16],
              max_batch=16, dtype="float32")
TEXTS = [f"sentence number {i} with a few words" for i in range(12)]


def _row_cos(a, b):
    num = np.sum(a * b, axis=1)
    den = np.maximum(np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1),
                     1e-12)
    return num / den


# ------------------------------------------------------------ config → mesh

def test_parse_mesh_spec():
    assert parse_mesh_spec("dp4xtp2") == [4, 2]
    assert parse_mesh_spec("dp8") == [8, 1]
    assert parse_mesh_spec("tp2") == [1, 2]
    assert parse_mesh_spec("4x2") == [4, 2]
    assert parse_mesh_spec("8") == [8, 1]
    with pytest.raises(ValueError):
        parse_mesh_spec("banana")


def test_parallel_config_validation():
    ParallelConfig(mesh_shape=[4, 2])
    with pytest.raises(ValueError):
        ParallelConfig(mesh_shape=[])
    with pytest.raises(ValueError):
        ParallelConfig(mesh_shape=[0, 8])
    with pytest.raises(ValueError):
        ParallelConfig(mesh_shape=[8])  # one size per axis name


@requires_8
def test_mesh_from_config_shapes():
    assert dict(mesh_from_config(ParallelConfig()).shape) == {
        "data": 8, "tensor": 1}
    assert dict(mesh_from_config(
        ParallelConfig(mesh_shape=[4, 2])).shape) == {"data": 4, "tensor": 2}


@requires_8
def test_runner_builds_mesh_purely_from_config(tmp_path):
    """The tentpole contract: a stack configured with mesh_shape=[4, 2]
    serves DP embed, a sharded corpus, AND TP decode with no code changes
    and no caller-supplied mesh — and registers the mesh.devices{axis}
    topology gauges."""
    import asyncio

    from symbiont_tpu.config import SymbiontConfig
    from symbiont_tpu.runner import SymbiontStack

    cfg = SymbiontConfig()
    cfg.parallel.mesh_shape = [4, 2]
    cfg.engine = EngineConfig(**ENG_KW)
    cfg.lm = LmConfig(enabled=True, arch="llama", hidden_size=32,
                      num_layers=1, num_heads=2, intermediate_size=64,
                      max_positions=64, dtype="float32", prompt_buckets=[8],
                      new_token_buckets=[8], stream_chunk=4)
    cfg.vector_store = VectorStoreConfig(dim=32,
                                         data_dir=str(tmp_path / "vs"),
                                         shard_capacity=64)
    cfg.graph_store.data_dir = str(tmp_path / "gs")
    cfg.text_generator.markov_state_path = None
    cfg.runner.services = "preprocessing,vector_memory,text_generator"

    async def scenario():
        stack = SymbiontStack(cfg)
        await stack.start()
        try:
            assert dict(stack.engine.mesh.shape) == {"data": 4, "tensor": 2}
            assert stack.engine._n_data == 4
            assert stack.vector_store.mesh is stack.engine.mesh
            assert stack.lm.mesh is stack.engine.mesh  # TP sharded decode
            assert metrics.gauge_get("mesh.devices",
                                     labels={"axis": "data"}) == 4
            assert metrics.gauge_get("mesh.devices",
                                     labels={"axis": "tensor"}) == 2
        finally:
            await stack.stop()

    asyncio.run(scenario())


@requires_8
def test_runner_standalone_vector_memory_worker_gets_mesh(tmp_path):
    """A store-only worker (engine in another process) still owns a
    device-resident corpus — the runner must build the mesh for it too, or
    corpus-sharded search silently degrades to one chip (review finding)."""
    import asyncio

    from symbiont_tpu.config import SymbiontConfig
    from symbiont_tpu.runner import SymbiontStack

    cfg = SymbiontConfig()
    cfg.vector_store = VectorStoreConfig(dim=32,
                                         data_dir=str(tmp_path / "vs"),
                                         shard_capacity=64)
    cfg.runner.services = "vector_memory"

    async def scenario():
        stack = SymbiontStack(cfg)
        await stack.start()
        try:
            assert stack.engine is None
            assert stack.vector_store.mesh is not None
            assert dict(stack.vector_store.mesh.shape)["data"] == 8
        finally:
            await stack.stop()

    asyncio.run(scenario())


@requires_8
def test_runner_parallel_disabled_keeps_meshless_engines():
    import asyncio

    from symbiont_tpu.config import SymbiontConfig
    from symbiont_tpu.runner import SymbiontStack

    cfg = SymbiontConfig()
    cfg.parallel.enabled = False
    cfg.engine = EngineConfig(**ENG_KW)
    cfg.runner.services = "preprocessing"

    async def scenario():
        stack = SymbiontStack(cfg)
        await stack.start()
        try:
            assert stack.engine.mesh is None
        finally:
            await stack.stop()

    asyncio.run(scenario())


# ------------------------------------------------------------------ DP embed

@requires_8
def test_dp_embed_parity_and_replica_gauges():
    """DP embed over the full 8-way data axis matches single-device row for
    row, and the per-replica padding-waste + shard-balance gauges account
    for the dispatched batch (ISSUE 8 satellite: engine.dp_* / per-replica
    batcher.padding_waste observability)."""
    mesh = build_mesh()
    dp = TpuEngine(EngineConfig(**ENG_KW), mesh=mesh)
    single = TpuEngine(EngineConfig(**ENG_KW, data_parallel=False))
    out_dp = dp.embed_texts(TEXTS)
    out_1 = single.embed_texts(TEXTS)
    np.testing.assert_allclose(out_dp, out_1, atol=1e-4, rtol=1e-3)
    assert _row_cos(out_dp, out_1).min() >= 0.999
    # the per-replica accounting itself, at a pinned shape: 13 real rows in
    # a 16-row batch over 8 replicas (2 rows each) — replicas 0-5 fully
    # real, replica 6 half padding, replica 7 all padding
    dp._note_padding([8] * 13, 8, 16, 13)
    waste = [metrics.gauge_get("batcher.padding_waste",
                               labels={"service": "engine",
                                       "replica": str(r)})
             for r in range(8)]
    assert waste[:6] == [0.0] * 6
    assert waste[6] == pytest.approx(0.5)
    assert waste[7] == pytest.approx(1.0)
    assert metrics.gauge_get("engine.dp_shard_balance",
                             labels={"service": "engine"}) == 0.0
    assert metrics.gauge_get("engine.dp_replicas",
                             labels={"service": "engine"}) == 8


@requires_8
def test_micro_batcher_rounds_flush_cap_to_data_axis():
    import asyncio

    from symbiont_tpu.engine.batcher import MicroBatcher

    mesh = build_mesh()
    eng = TpuEngine(EngineConfig(**ENG_KW), mesh=mesh)

    async def scenario():
        # a 13-item cap would bucket every full flush to 16 rows with 3
        # permanent pad rows; mesh-aware sizing rounds it to 16
        b = MicroBatcher(eng, max_batch=13)
        assert b.max_batch == 16
        await b.start()
        out = await b.embed(TEXTS[:4])
        assert out.shape == (4, 32)
        await b.close()

    asyncio.run(scenario())


# -------------------------------------------------------- sharded search

@requires_8
def test_sharded_search_identical_to_single_device():
    """Corpus-sharded fused search (per-shard top-k + global merge) returns
    IDENTICAL hits — ids, scores, order — to the single-device store, with
    the corpus actually sharded over the 'data' axis."""
    mesh = build_mesh()
    rng = np.random.default_rng(5)
    vecs = rng.standard_normal((300, 32)).astype(np.float32)
    ids = [f"p{i}" for i in range(300)]
    payloads = [{"i": i} for i in range(300)]

    def mk(m):
        s = VectorStore(VectorStoreConfig(dim=32, data_dir="",
                                          shard_capacity=64), mesh=m)
        s.upsert_rows(ids, vecs, payloads)
        return s

    plain, sharded = mk(None), mk(mesh)
    for qi in range(16):
        q = rng.standard_normal(32).astype(np.float32)
        a = plain.search(q, 7)
        b = sharded.search(q, 7)
        assert [(h.id, h.score) for h in a] == [(h.id, h.score) for h in b]
    # the device corpus really lives sharded
    spec = str(sharded._device_corpus.sharding.spec)
    assert "data" in spec, spec
    # 300 rows → capacity rounds to a multiple of both the block and the
    # data axis
    assert sharded._device_corpus.shape[0] % 8 == 0


@requires_8
def test_sharded_search_ties_preserve_index_order():
    """Score ties must resolve identically on both paths (lax.top_k breaks
    ties by position; shards concatenate in global row order)."""
    mesh = build_mesh()
    base = np.zeros((96, 32), np.float32)
    base[:, 0] = 1.0  # every row identical → every score ties
    ids = [f"t{i:03d}" for i in range(96)]

    def mk(m):
        s = VectorStore(VectorStoreConfig(dim=32, data_dir="",
                                          shard_capacity=32), mesh=m)
        s.upsert_rows(ids, base, [{} for _ in ids])
        return s

    q = np.zeros(32, np.float32)
    q[0] = 1.0
    a = mk(None).search(q, 10)
    b = mk(mesh).search(q, 10)
    assert [h.id for h in a] == [h.id for h in b] == ids[:10]


@requires_8
def test_fused_search_sharded_matches_split_and_single():
    """search_fused over a sharded corpus (engine qsearch executable with
    the per-shard top-k) returns the same hits as the single-device fused
    path AND as split search(embed_query)."""
    mesh = build_mesh()
    eng_dp = TpuEngine(EngineConfig(**ENG_KW), mesh=mesh)
    eng_1 = TpuEngine(EngineConfig(**ENG_KW, data_parallel=False))

    corpus_texts = [f"document about topic {i} and detail {i % 7}"
                    for i in range(40)]
    vecs = eng_1.embed_texts(corpus_texts)

    def mk(m):
        s = VectorStore(VectorStoreConfig(dim=32, data_dir="",
                                          shard_capacity=64), mesh=m)
        s.upsert_rows([f"d{i}" for i in range(40)], vecs,
                      [{"t": t} for t in corpus_texts])
        return s

    plain, sharded = mk(None), mk(mesh)
    for q in ("topic detail", "document about seven"):
        fused_sharded = sharded.search_fused(eng_dp, q, 5)
        fused_single = plain.search_fused(eng_1, q, 5)
        # hit sets and order identical; scores to float tolerance (the
        # query embed compiles under GSPMD on the mesh engine, so its f32
        # last bits may differ from the single-device executable)
        assert ([h.id for h in fused_sharded]
                == [h.id for h in fused_single])
        np.testing.assert_allclose([h.score for h in fused_sharded],
                                   [h.score for h in fused_single],
                                   atol=1e-4, rtol=1e-4)
        split = plain.search(eng_1.embed_query(q), 5)
        assert [h.id for h in fused_sharded] == [h.id for h in split]


# ------------------------------------------------------------------ TP decode

LM_KW = dict(enabled=True, arch="gpt2", hidden_size=32, num_layers=2,
             num_heads=2, intermediate_size=64, max_positions=128,
             dtype="float32", prompt_buckets=[8, 16], new_token_buckets=[16],
             stream_chunk=4, session_min_rows=4, seed=3)


def _session_outputs(lm):
    sess = lm.start_session(["the quick brown fox"], [12], temperature=0.0)
    out = dict(sess.step())
    tags = sess.admit(["hello world"], [8], temperature=0.0)
    assert tags and tags[0] is not None
    while not sess.done():
        out.update(sess.step())
    return sorted(out.items())


@requires_8
@pytest.mark.parametrize("quantize", ["none", "int8"])
def test_tp_decode_token_identical_through_serving_paths(quantize):
    """TP greedy decode == single-device, through generate_batch AND a
    session with a mid-decode admit. quantize='int8' runs the SAME bar
    with QuantTensor-sharded weights — the PR 7 'falls back unquantized'
    gap, closed (codes and per-channel scales shard together)."""
    mesh = build_mesh([4, 2])
    single = LmEngine(LmConfig(quantize=quantize, **LM_KW))
    tp = LmEngine(LmConfig(quantize=quantize, **LM_KW), mesh=mesh)
    assert tp.mesh is not None, "TP mesh must shard, not fall back"
    prompts = ["the quick brown fox", "mesh native decode"]
    base = single.generate_batch(prompts, [12, 12], temperature=0.0)
    out = tp.generate_batch(prompts, [12, 12], temperature=0.0)
    assert out == base
    assert _session_outputs(tp) == _session_outputs(single)


@requires_8
def test_tp_int8_params_shard_with_scales():
    """The sharded layout itself: int8 codes take the kernel's spec, the
    per-output-channel scales ride the kernel's LAST axis entry (col-
    sharded q/k/v scales shard on 'tensor', row-sharded o-proj scales
    replicate)."""
    from symbiont_tpu.models.quant import QuantTensor

    mesh = build_mesh([4, 2])
    tp = LmEngine(LmConfig(quantize="int8", **LM_KW), mesh=mesh)
    layer = tp.params["layers"][0]
    q_kernel = layer["q"]["kernel"]
    assert isinstance(q_kernel, QuantTensor)
    assert "tensor" in str(q_kernel.q.sharding.spec)
    assert "tensor" in str(q_kernel.scale.sharding.spec)
    o_kernel = layer["o"]["kernel"]
    assert "tensor" in str(o_kernel.q.sharding.spec)
    # row-sharded kernel: output channels unsharded → scales replicate
    assert "tensor" not in str(o_kernel.scale.sharding.spec)
    # the param-bytes gauge reports the narrow storage on the TP path too
    assert metrics.gauge_get("lm.param_bytes",
                             labels={"service": "lm", "dtype": "int8"}) > 0


@requires_8
def test_tp_on_with_quantize_no_longer_raises_or_warns(caplog):
    """tensor_parallel='on' + quantize=int8 must boot sharded-and-quantized
    silently (previously: unquantized fallback with a warning)."""
    import logging

    mesh = build_mesh([4, 2])
    with caplog.at_level(logging.WARNING, logger="symbiont_tpu.engine.lm"):
        lm = LmEngine(LmConfig(tensor_parallel="on", quantize="int8",
                               **LM_KW), mesh=mesh)
    assert lm.mesh is not None
    assert not [r for r in caplog.records
                if "unquantized" in r.getMessage()]
    assert lm.generate("hello", 8, temperature=0.0)
