"""Multi-chip behavior on the 8-virtual-device CPU mesh (SURVEY.md §4 item 4).

Verifies: DP batch sharding reproduces single-device embeddings; TP-sharded
decoder forward matches unsharded logits; ring attention matches full
attention (incl. causal); mesh construction errors.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from symbiont_tpu.models import bert as bert_mod
from symbiont_tpu.models import gpt as gpt_mod
from symbiont_tpu.parallel import (
    batch_sharding,
    build_mesh,
    gpt_param_sharding,
    replicate,
    shard_params,
)
from symbiont_tpu.parallel.ring_attention import ring_attention_sharded

requires_8 = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")


def _full_attention(q, k, v, causal=False):
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@requires_8
def test_mesh_build_and_shape_error():
    mesh = build_mesh()
    assert mesh.shape == {"data": 8, "tensor": 1}
    mesh2 = build_mesh([2, 4])
    assert mesh2.shape == {"data": 2, "tensor": 4}
    with pytest.raises(ValueError):
        build_mesh([3, 2])


@requires_8
def test_dp_embedding_matches_single_device():
    cfg = bert_mod.BertConfig(vocab_size=64, hidden_size=16, num_layers=2,
                              num_heads=2, intermediate_size=32,
                              max_position_embeddings=32, dtype="float32")
    params = bert_mod.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    B = 16  # divisible by 8
    ids = rng.integers(3, 64, size=(B, 12)).astype(np.int32)
    mask = np.ones((B, 12), np.int32)
    mask[:, 9:] = 0

    ref = np.asarray(bert_mod.embed_sentences(params, jnp.asarray(ids),
                                              jnp.asarray(mask), cfg))

    mesh = build_mesh()
    params_r = replicate(mesh, params)
    bs = batch_sharding(mesh)
    ids_s = jax.device_put(jnp.asarray(ids), bs)
    mask_s = jax.device_put(jnp.asarray(mask), bs)
    fn = jax.jit(lambda p, i, m: bert_mod.embed_sentences(p, i, m, cfg),
                 out_shardings=bs)
    out = np.asarray(fn(params_r, ids_s, mask_s))
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@requires_8
def test_tp_gpt_logits_match_unsharded():
    cfg = gpt_mod.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=8, intermediate_size=64,
                            max_position_embeddings=32, dtype="float32")
    params = gpt_mod.init_params(jax.random.key(1), cfg)
    ids = np.random.default_rng(1).integers(0, 64, size=(2, 10)).astype(np.int32)
    pos = jnp.broadcast_to(jnp.arange(10, dtype=jnp.int32), (2, 10))
    cache = gpt_mod.init_cache(cfg, 2, 10, jnp.float32)
    ref, _ = gpt_mod.forward(params, jnp.asarray(ids), cache, pos, cfg)

    mesh = build_mesh([1, 8])  # pure TP
    spec = gpt_param_sharding(mesh, params, arch="gpt2")
    params_tp = shard_params(mesh, params, spec)
    fn = jax.jit(lambda p, i: gpt_mod.forward(p, i, cache, pos, cfg)[0])
    out = fn(params_tp, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4,
                               rtol=1e-3)


@requires_8
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    rng = np.random.default_rng(2)
    B, S, NH, D = 2, 64, 4, 16  # S = 8 devices × 8 local
    q = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    ref = _full_attention(q, k, v, causal=causal)
    mesh = build_mesh([8, 1])
    out = ring_attention_sharded(q, k, v, mesh, axis_name="data", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5,
                               rtol=1e-4)


@requires_8
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    from symbiont_tpu.parallel.ulysses import ulysses_attention_sharded

    rng = np.random.default_rng(4)
    B, S, NH, D = 2, 64, 8, 16  # NH = 8 devices × 1 head each
    q = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    ref = _full_attention(q, k, v, causal=causal)
    mesh = build_mesh([8, 1])
    out = ulysses_attention_sharded(q, k, v, mesh, axis_name="data",
                                    causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5,
                               rtol=1e-4)
    # and it agrees with the ring scheme on the same shards
    ring = ring_attention_sharded(q, k, v, mesh, axis_name="data",
                                  causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ring), atol=1e-5,
                               rtol=1e-4)


@requires_8
def test_ulysses_rejects_indivisible_heads():
    from symbiont_tpu.parallel.ulysses import ulysses_attention_sharded

    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(1, 16, 6, 8)), jnp.float32)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention_sharded(q, q, q, build_mesh([8, 1]))


@requires_8
def test_ring_attention_long_sequence_memory_shape():
    """Sequence 8× a device's local block works (the long-context claim)."""
    rng = np.random.default_rng(3)
    B, S, NH, D = 1, 256, 2, 8
    q = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    out = ring_attention_sharded(q, k, v, build_mesh([8, 1]), causal=True)
    assert out.shape == (B, S, NH, D)
    ref = _full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5,
                               rtol=1e-4)


@requires_8
@pytest.mark.parametrize("arch,num_kv", [("gpt2", None), ("llama", 2)])
def test_sp_forward_matches_cache_forward(arch, num_kv):
    """Context-parallel training forward (sequence sharded over 8 devices,
    ring attention) reproduces the KV-cache forward's logits exactly —
    incl. GQA head expansion and RoPE with global positions."""
    from symbiont_tpu.parallel.context import gpt_forward_sp

    cfg = gpt_mod.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, num_kv_heads=num_kv,
                            intermediate_size=64, max_position_embeddings=64,
                            arch=arch, dtype="float32")
    params = gpt_mod.init_params(jax.random.key(2), cfg)
    B, S = 2, 32  # 8 devices × 4 local tokens
    ids = np.random.default_rng(6).integers(0, 64, size=(B, S)).astype(np.int32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    cache = gpt_mod.init_cache(cfg, B, S, jnp.float32)
    ref, _ = gpt_mod.forward(params, jnp.asarray(ids), cache, pos, cfg)

    mesh = build_mesh([8, 1])
    out = gpt_forward_sp(params, jnp.asarray(ids), mesh, cfg, axis="data")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4,
                               rtol=1e-3)


@requires_8
def test_sp_forward_rejects_indivisible_sequence():
    from symbiont_tpu.parallel.context import gpt_forward_sp

    cfg = gpt_mod.GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                            num_heads=4, intermediate_size=64,
                            max_position_embeddings=64, dtype="float32")
    params = gpt_mod.init_params(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="not divisible"):
        gpt_forward_sp(params, jnp.zeros((1, 30), jnp.int32),
                       build_mesh([8, 1]), cfg)


@requires_8
def test_sp_train_step_matches_unsharded():
    """One sequence-parallel train step == one plain train step: same loss,
    same updated params (long-context training is exact, not approximate)."""
    from symbiont_tpu.parallel.context import make_lm_train_step_sp
    from symbiont_tpu.train.trainer import lm_train_step, make_lm_train_state

    cfg = gpt_mod.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, num_kv_heads=2, intermediate_size=64,
                            max_position_embeddings=64, arch="llama",
                            dtype="float32")
    rng = np.random.default_rng(7)
    B, S = 2, 32
    batch = {"ids": jnp.asarray(rng.integers(1, 64, (B, S)), jnp.int32),
             "mask": jnp.asarray((rng.random((B, S)) < 0.9).astype(np.int32))}

    params = gpt_mod.init_params(jax.random.key(3), cfg)
    state_ref, tx = make_lm_train_state(params, learning_rate=1e-3)
    state_ref, m_ref = lm_train_step(state_ref, batch, cfg, tx)

    params2 = gpt_mod.init_params(jax.random.key(3), cfg)
    state_sp, tx2 = make_lm_train_state(params2, learning_rate=1e-3)
    mesh = build_mesh([8, 1])
    step_sp = make_lm_train_step_sp(mesh, cfg, tx2, axis="data")
    state_sp, m_sp = step_sp(state_sp, batch)

    np.testing.assert_allclose(float(m_sp["loss"]), float(m_ref["loss"]),
                               atol=1e-5, rtol=1e-5)
    ref_leaves = jax.tree.leaves(state_ref.params)
    sp_leaves = jax.tree.leaves(state_sp.params)
    for a, b in zip(ref_leaves, sp_leaves):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-4,
                                   rtol=1e-3)


@requires_8
@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gqa_matches_full(causal):
    """GQA ring: K/V rotate at kv_heads width, expand only locally — result
    must equal full attention over pre-expanded K/V."""
    rng = np.random.default_rng(8)
    B, S, NH, KVH, D = 2, 64, 8, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, NH, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KVH, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KVH, D)), jnp.float32)
    ref = _full_attention(q, jnp.repeat(k, NH // KVH, axis=2),
                          jnp.repeat(v, NH // KVH, axis=2), causal=causal)
    mesh = build_mesh([8, 1])
    out = ring_attention_sharded(q, k, v, mesh, axis_name="data",
                                 causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5,
                               rtol=1e-4)


@requires_8
def test_sp_forward_ulysses_matches_cache_forward():
    """The Ulysses (all-to-all) scheme as the SP attention backend must also
    reproduce the KV-cache forward — both schemes are exact, pick per
    workload (heads divisible by axis → Ulysses; else ring)."""
    from symbiont_tpu.parallel.context import gpt_forward_sp

    cfg = gpt_mod.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=8, intermediate_size=64,
                            max_position_embeddings=64, arch="gpt2",
                            dtype="float32")
    params = gpt_mod.init_params(jax.random.key(4), cfg)
    B, S = 2, 32
    ids = np.random.default_rng(9).integers(0, 64, size=(B, S)).astype(np.int32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    cache = gpt_mod.init_cache(cfg, B, S, jnp.float32)
    ref, _ = gpt_mod.forward(params, jnp.asarray(ids), cache, pos, cfg)

    mesh = build_mesh([8, 1])
    out = gpt_forward_sp(params, jnp.asarray(ids), mesh, cfg, axis="data",
                         attn_impl="ulysses")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4,
                               rtol=1e-3)


@requires_8
def test_sp_forward_ulysses_gqa_matches_cache_forward():
    """Ulysses SP with GQA (nkv < nh): the pre-all-to-all K/V head expansion
    must map query heads to the right KV groups."""
    from symbiont_tpu.parallel.context import gpt_forward_sp

    cfg = gpt_mod.GPTConfig(vocab_size=64, hidden_size=64, num_layers=2,
                            num_heads=8, num_kv_heads=2, intermediate_size=64,
                            max_position_embeddings=64, arch="llama",
                            dtype="float32")
    params = gpt_mod.init_params(jax.random.key(5), cfg)
    B, S = 2, 32
    ids = np.random.default_rng(10).integers(0, 64, size=(B, S)).astype(np.int32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    cache = gpt_mod.init_cache(cfg, B, S, jnp.float32)
    ref, _ = gpt_mod.forward(params, jnp.asarray(ids), cache, pos, cfg)

    mesh = build_mesh([8, 1])
    out = gpt_forward_sp(params, jnp.asarray(ids), mesh, cfg, axis="data",
                         attn_impl="ulysses")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4,
                               rtol=1e-3)


# ----------------------------------------------------------------- pipeline


@requires_8
@pytest.mark.parametrize("arch,num_kv", [("llama", 2), ("gpt2", None)])
def test_pp_loss_matches_unsharded(arch, num_kv):
    """Pipeline-parallel loss == plain loss on the same params/batch: the
    GPipe schedule changes execution order, not math."""
    from symbiont_tpu.parallel.pipeline import (lm_loss_pp, shard_pp_params,
                                                stack_layers)
    from symbiont_tpu.train.trainer import lm_loss

    cfg = gpt_mod.GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=4, num_heads=4,
        num_kv_heads=num_kv, intermediate_size=64,
        max_position_embeddings=32, arch=arch, dtype="float32",
        tie_word_embeddings=True)
    rng = np.random.default_rng(11)
    B, S = 8, 16
    batch = {"ids": jnp.asarray(rng.integers(1, 64, (B, S)), jnp.int32),
             "mask": jnp.asarray((rng.random((B, S)) < 0.9).astype(np.int32))}
    params = gpt_mod.init_params(jax.random.key(5), cfg)
    ref = float(lm_loss(params, batch, cfg))

    mesh = build_mesh([4], axis_names=("pipe",),
                      devices=jax.devices()[:4])  # 4 stages x 1 layer each
    placed = shard_pp_params(mesh, stack_layers(params))
    got = float(lm_loss_pp(placed, batch, cfg, mesh, num_microbatches=4))
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=1e-5)


@requires_8
def test_pp_train_step_matches_unsharded():
    """One pipeline-parallel train step == one plain train step: same loss,
    same updated params (backward is jax.grad's transpose of the pipelined
    forward — reverse ppermutes included)."""
    from symbiont_tpu.parallel.pipeline import (make_lm_train_step_pp,
                                                make_pp_train_state,
                                                stack_layers)
    from symbiont_tpu.train.trainer import lm_train_step, make_lm_train_state

    cfg = gpt_mod.GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                            num_heads=4, num_kv_heads=2, intermediate_size=64,
                            max_position_embeddings=32, arch="llama",
                            dtype="float32")
    rng = np.random.default_rng(13)
    B, S = 4, 16
    batch = {"ids": jnp.asarray(rng.integers(1, 64, (B, S)), jnp.int32),
             "mask": jnp.asarray((rng.random((B, S)) < 0.9).astype(np.int32))}

    params = gpt_mod.init_params(jax.random.key(9), cfg)
    state_ref, tx = make_lm_train_state(params, learning_rate=1e-3)
    state_ref, m_ref = lm_train_step(state_ref, batch, cfg, tx)

    mesh = build_mesh([2], axis_names=("pipe",), devices=jax.devices()[:2])
    params2 = gpt_mod.init_params(jax.random.key(9), cfg)
    state_pp, tx2 = make_pp_train_state(mesh, params2, learning_rate=1e-3)
    step_pp = make_lm_train_step_pp(mesh, cfg, tx2, num_microbatches=2)
    state_pp, m_pp = step_pp(state_pp, batch)

    np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]),
                               atol=1e-5, rtol=1e-5)
    # updated params agree leaf-for-leaf (ref's layer list stacked to match)
    ref_stacked = stack_layers(state_ref.params)
    for a, b in zip(jax.tree.leaves(ref_stacked),
                    jax.tree.leaves(state_pp.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=1e-4,
                                   rtol=1e-3)
    # params kept their pipe sharding through the optimizer update
    spec = str(jax.tree.leaves(state_pp.params["layers"])[0].sharding.spec)
    assert "pipe" in spec, spec


@requires_8
def test_pp_rejects_indivisible_shapes():
    from symbiont_tpu.parallel.pipeline import (lm_loss_pp, shard_pp_params,
                                                stack_layers)

    cfg = gpt_mod.GPTConfig(vocab_size=64, hidden_size=32, num_layers=3,
                            num_heads=4, num_kv_heads=2, intermediate_size=64,
                            max_position_embeddings=32, arch="llama",
                            dtype="float32")
    params = gpt_mod.init_params(jax.random.key(0), cfg)
    mesh = build_mesh([2], axis_names=("pipe",), devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="not divisible by pipe"):
        shard_pp_params(mesh, stack_layers(params))  # 3 layers, 2 stages
    cfg4 = dataclasses.replace(cfg, num_layers=4)
    params4 = gpt_mod.init_params(jax.random.key(0), cfg4)
    placed = shard_pp_params(mesh, stack_layers(params4))
    batch = {"ids": jnp.ones((3, 16), jnp.int32),
             "mask": jnp.ones((3, 16), jnp.int32)}
    with pytest.raises(ValueError, match="not divisible by microbatches"):
        lm_loss_pp(placed, batch, cfg4, mesh, num_microbatches=2)
