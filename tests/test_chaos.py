"""Chaos suite (`pytest -m chaos`, scripts/chaos.sh): deterministic
fault-injection scenarios proving the resilience-plane acceptance criteria
— ZERO-LOSS ingest on the durable in-proc bus under every injected fault
class (handler exception, handler hang past the timeout, delivery drop,
store outage with recovery, TCP disconnect), and poison-message quarantine:
exactly `durable_max_deliver` attempts, then the DLQ, inspectable and
replayable through `GET /api/dlq`.

Every scenario runs under a seeded FaultPlan (resilience/faults.py) so the
faults fire at the same operations on every run — loss counts are asserted
exactly, not "usually". The suite doubles as a bench tier
(symbiont_tpu/bench/chaos.py) so loss-under-fault regressions gate like
perf regressions.
"""

import asyncio
import json
import struct
import urllib.error
import urllib.request

import numpy as np
import pytest

from symbiont_tpu import subjects
from symbiont_tpu.bus.core import subject_matches
from symbiont_tpu.bus.inproc import InprocBus
from symbiont_tpu.config import (
    ApiConfig,
    GraphStoreConfig,
    SymbiontConfig,
    TextGeneratorConfig,
    VectorStoreConfig,
)
from symbiont_tpu.resilience.breaker import CircuitBreaker
from symbiont_tpu.resilience.faults import FaultPlan, FaultRule
from symbiont_tpu.resilience.stores import ResilientVectorStore
from symbiont_tpu.runner import SymbiontStack

pytestmark = pytest.mark.chaos

PAGE = ("<html><body><main><p>Chaos testing the ingest pipeline.</p>"
        "<p>Every message must survive the faults!</p></main></body></html>")
SENTENCES_PER_DOC = 2
N_DOCS = 6


class _StubEngine:
    """Duck-typed engine (same shape as test_observability's): the chaos
    suite is about the failure paths, not BERT numerics."""

    class _ModelCfg:
        hidden_size = 16

    def __init__(self):
        from symbiont_tpu.config import EngineConfig

        self.config = EngineConfig(embedding_dim=16, max_batch=8,
                                   flush_deadline_ms=2.0)
        self.model_cfg = self._ModelCfg()
        self.cross_params = None
        self.stats = {"embed_calls": 0, "compiles": 0}

    def embed_texts(self, texts):
        self.stats["embed_calls"] += 1
        rng = np.random.default_rng(len(texts))
        return rng.standard_normal((len(texts), 16)).astype(np.float32)


def _stack_config(tmp_path, *, services, ack_wait_s=0.3, max_deliver=5,
                  handler_timeout_s=0.0):
    cfg = SymbiontConfig(
        vector_store=VectorStoreConfig(dim=16,
                                       data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(markov_state_path=None),
        api=ApiConfig(host="127.0.0.1", port=0),
    )
    cfg.runner.services = services
    cfg.bus.durable = True
    cfg.bus.durable_ack_wait_s = ack_wait_s
    cfg.bus.durable_max_deliver = max_deliver
    cfg.resilience.handler_timeout_s = handler_timeout_s
    cfg.resilience.supervisor_backoff_base_s = 0.05
    cfg.resilience.supervisor_backoff_max_s = 0.1
    return cfg


async def _ingest_docs(bus, n_docs=N_DOCS):
    from symbiont_tpu.schema import PerceiveUrlTask, to_json_bytes

    for i in range(n_docs):
        await bus.publish(subjects.TASKS_PERCEIVE_URL,
                          to_json_bytes(PerceiveUrlTask(url=f"http://d/{i}")))


async def _wait_for(cond, timeout=20.0, interval=0.05):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return cond()


# ----------------------------------------------- fault class: handler crash

def test_zero_loss_under_handler_exceptions(tmp_path):
    """Injected exceptions in the vector-memory handler (fewer than
    max_deliver): every delivery redelivers until it sticks — the full
    document set lands, nothing lost."""
    plan = FaultPlan(seed=11, rules=[
        FaultRule(seam="handler", kind="error",
                  match="vector_memory:data.text.with_embeddings", times=3)])
    cfg = _stack_config(tmp_path,
                        services="perception,preprocessing,vector_memory")
    expected = N_DOCS * SENTENCES_PER_DOC

    async def scenario():
        bus = InprocBus()
        stack = SymbiontStack(cfg, bus=bus, engine=_StubEngine(),
                              fetcher=lambda url: PAGE)
        await stack.start()
        try:
            with plan.activate():
                await _ingest_docs(bus)
                ok = await _wait_for(
                    lambda: stack.vector_store.count() >= expected)
            assert ok, (f"lost ingest under handler faults: "
                        f"{stack.vector_store.count()}/{expected} points")
            assert stack.vector_store.count() == expected
            assert plan.fired[("handler", "error")] == 3
            assert bus.stats["redelivered"] >= 3
            assert len(bus.dlq) == 0  # transient faults never quarantine
        finally:
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


# ---------------------------------- fault class: crash under coalesced acks

def test_zero_loss_with_coalesced_acks_under_handler_faults(tmp_path):
    """Coalesced-ack semantics (services/coalesce.py) under chaos: rows
    from many messages share one flush and each durable delivery acks only
    after the flush carrying its rows commits. Injected handler crashes
    redeliver through the coalescer — the full document set lands exactly
    once (deterministic ids), and the coalescer demonstrably batched
    multiple messages per store call while the faults fired."""
    plan = FaultPlan(seed=15, rules=[
        FaultRule(seam="handler", kind="error",
                  match="vector_memory:data.text.with_embeddings", times=2)])
    cfg = _stack_config(tmp_path,
                        services="perception,preprocessing,vector_memory")
    cfg.vector_store.coalesce_max_rows = 8
    cfg.vector_store.coalesce_max_age_ms = 100.0
    expected = N_DOCS * SENTENCES_PER_DOC
    from symbiont_tpu.utils.telemetry import metrics

    labels = {"service": "vector_memory"}
    msgs0 = metrics.get("coalesce.messages", labels=labels)
    rows0 = metrics.get("coalesce.rows", labels=labels)

    async def scenario():
        bus = InprocBus()
        stack = SymbiontStack(cfg, bus=bus, engine=_StubEngine(),
                              fetcher=lambda url: PAGE)
        await stack.start()
        try:
            with plan.activate():
                await _ingest_docs(bus)
                ok = await _wait_for(
                    lambda: stack.vector_store.count() >= expected)
            assert ok, (f"lost ingest under coalesced acks: "
                        f"{stack.vector_store.count()}/{expected} points")
            assert stack.vector_store.count() == expected
            assert plan.fired[("handler", "error")] == 2
            assert bus.stats["redelivered"] >= 2
            assert len(bus.dlq) == 0
            # the coalescer really carried the load: every message went
            # through it, and at least one flush batched several messages
            assert metrics.get("coalesce.messages",
                               labels=labels) - msgs0 == N_DOCS
            assert metrics.get("coalesce.rows",
                               labels=labels) - rows0 == expected
            flush_hist = metrics.histogram_summary("coalesce.flush_rows",
                                                   labels=labels)
            assert flush_hist is not None and flush_hist["max"] >= \
                2 * SENTENCES_PER_DOC, flush_hist
        finally:
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


# ------------------------------------------------ fault class: handler hang

def test_zero_loss_under_handler_hang_past_timeout(tmp_path):
    """Injected hangs longer than the handler timeout: the handler is
    CANCELLED at the deadline (semaphore slot freed), the delivery stays
    unacked, redelivery completes the work — zero loss."""
    plan = FaultPlan(seed=12, rules=[
        FaultRule(seam="handler", kind="hang", delay_s=30.0,
                  match="vector_memory:data.text.with_embeddings", times=2)])
    cfg = _stack_config(tmp_path,
                        services="perception,preprocessing,vector_memory",
                        handler_timeout_s=0.2)
    expected = N_DOCS * SENTENCES_PER_DOC

    async def scenario():
        bus = InprocBus()
        stack = SymbiontStack(cfg, bus=bus, engine=_StubEngine(),
                              fetcher=lambda url: PAGE)
        await stack.start()
        try:
            with plan.activate():
                await _ingest_docs(bus)
                ok = await _wait_for(
                    lambda: stack.vector_store.count() >= expected)
            assert ok, (f"lost ingest under hang faults: "
                        f"{stack.vector_store.count()}/{expected} points")
            assert stack.vector_store.count() == expected
            assert plan.fired[("handler", "hang")] == 2
            from symbiont_tpu.utils.telemetry import metrics

            assert metrics.get("bus.handler_timeout",
                               labels={"service": "vector_memory",
                                       "subject":
                                       "data.text.with_embeddings"}) >= 2
            vm = next(s for s in stack.services
                      if s.name == "vector_memory")
            assert vm._sem._value == 32  # no slot pinned by a hung handler
        finally:
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


# --------------------------------------------- fault class: delivery drops

def test_zero_loss_under_delivery_drops(tmp_path):
    """Injected in-flight delivery drops on the durable pump: the delivery
    attempt is consumed but the message redelivers after ack_wait."""
    plan = FaultPlan(seed=13, rules=[
        FaultRule(seam="bus.deliver", kind="drop",
                  match="data.text.with_embeddings", times=3)])
    cfg = _stack_config(tmp_path,
                        services="perception,preprocessing,vector_memory",
                        ack_wait_s=0.2)
    expected = N_DOCS * SENTENCES_PER_DOC

    async def scenario():
        bus = InprocBus()
        stack = SymbiontStack(cfg, bus=bus, engine=_StubEngine(),
                              fetcher=lambda url: PAGE)
        await stack.start()
        try:
            with plan.activate():
                await _ingest_docs(bus)
                ok = await _wait_for(
                    lambda: stack.vector_store.count() >= expected)
            assert ok, (f"lost ingest under delivery drops: "
                        f"{stack.vector_store.count()}/{expected} points")
            assert stack.vector_store.count() == expected
            assert plan.fired[("bus.deliver", "drop")] == 3
        finally:
            await stack.stop()
            await bus.close()

    asyncio.run(scenario())


# ------------------------------------- fault class: store outage + recovery

def test_zero_loss_under_store_outage_with_recovery(tmp_path):
    """Mid-run vector-store outage: the first upserts fail, the breaker
    opens, writes SPILL to the WAL (handler keeps acking — the pipeline
    never backs up), and recovery replays the spill. Inner store ends with
    every point."""
    from symbiont_tpu.memory.vector_store import VectorStore
    from symbiont_tpu.schema import (
        SentenceEmbedding,
        TextWithEmbeddingsMessage,
        to_json_bytes,
    )
    from symbiont_tpu.services.vector_memory import VectorMemoryService

    inner = VectorStore(VectorStoreConfig(dim=4,
                                          data_dir=str(tmp_path / "inner"),
                                          shard_capacity=64))
    breaker = CircuitBreaker("chaos_vs", failure_threshold=2,
                             reset_timeout_s=0.2)
    store = ResilientVectorStore(inner, breaker=breaker,
                                 spill_path=str(tmp_path / "spill.jsonl"))
    plan = FaultPlan(seed=14, rules=[
        FaultRule(seam="store.upsert", kind="error", match="chaos_vs",
                  times=2)])
    n_msgs = 5

    async def scenario():
        bus = InprocBus()
        await bus.add_stream("pipeline",
                             [subjects.DATA_TEXT_WITH_EMBEDDINGS],
                             ack_wait_s=0.5, max_deliver=5)
        svc = VectorMemoryService(bus, store, durable_stream="pipeline")
        await svc.start()
        try:
            with plan.activate():
                for i in range(n_msgs):
                    msg = TextWithEmbeddingsMessage(
                        original_id=f"doc-{i}", source_url="http://d",
                        embeddings_data=[SentenceEmbedding(
                            sentence_text=f"s{i}",
                            embedding=[float(i), 1.0, 0.0, 0.0])],
                        model_name="stub", timestamp_ms=i)
                    await bus.publish(subjects.DATA_TEXT_WITH_EMBEDDINGS,
                                      to_json_bytes(msg))
                    await asyncio.sleep(0.12)  # spread across the outage
                # every message was ACKED (spill counts as durable): the
                # stream settles even while the backend is down
                stats_ok = await _wait_for_settled(bus, n_msgs)
                assert stats_ok, "durable stream did not settle"
                # recovery: drain whatever is still spilled
                drained = await _wait_for(
                    lambda: store.spill_pending() == 0, timeout=5.0)
                if not drained:
                    await asyncio.get_running_loop().run_in_executor(
                        None, store.replay_spill)
            assert inner.count() == n_msgs, (
                f"store outage lost writes: {inner.count()}/{n_msgs}")
            assert plan.fired[("store.upsert", "error")] == 2
            from symbiont_tpu.utils.telemetry import metrics

            assert metrics.get("store.spilled_points",
                               labels={"store": "chaos_vs"}) >= 1
        finally:
            await svc.stop()
            await bus.close()

    async def _wait_for_settled(bus, n):
        async def floor():
            stats = await bus.stream_stats()
            return stats["pipeline"]["groups"][
                subjects.QUEUE_VECTOR_MEMORY]["ack_floor"]

        deadline = asyncio.get_running_loop().time() + 20.0
        while asyncio.get_running_loop().time() < deadline:
            if await floor() >= n:
                return True
            await asyncio.sleep(0.05)
        return False

    asyncio.run(scenario())


# --------------------------------------------- fault class: TCP disconnect

class _MiniBroker:
    """~80-line in-test symbus broker speaking just enough of the wire
    protocol (native/symbus/protocol.hpp) to prove client reconnect: SUB /
    UNSUB / PUB / MSG routing plus auto-`{"ok": true}` replies on the
    `_SYMBUS.*` control subjects. `kill_connections()` resets every client
    socket without stopping the listener — the broker-restart story from
    the client's side."""

    def __init__(self):
        self.server = None
        self.conns = {}  # writer -> {sid: (subject, queue)}
        self.control_requests = []  # (subject, payload-dict)

    async def start(self) -> int:
        self.server = await asyncio.start_server(self._handle,
                                                 "127.0.0.1", 0)
        return self.server.sockets[0].getsockname()[1]

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()
        await self.kill_connections()

    async def kill_connections(self):
        for w in list(self.conns):
            w.close()
        self.conns.clear()

    def _msg_frame(self, sid, subject, reply, headers, data):
        def s(x):
            b = x.encode()
            return struct.pack("<H", len(b)) + b

        body = struct.pack("<BI", 5, sid) + s(subject) + s(reply or "")
        body += struct.pack("<H", len(headers))
        for k, v in headers.items():
            body += s(k) + s(v)
        body += struct.pack("<I", len(data)) + data
        return struct.pack("<I", len(body)) + body

    async def _route(self, subject, reply, headers, data):
        for w, subs in list(self.conns.items()):
            for sid, (pattern, _queue) in subs.items():
                if subject_matches(pattern, subject):
                    w.write(self._msg_frame(sid, subject, reply, headers,
                                            data))
                    await w.drain()

    async def _handle(self, reader, writer):
        self.conns[writer] = {}
        try:
            while True:
                head = await reader.readexactly(4)
                (n,) = struct.unpack("<I", head)
                payload = await reader.readexactly(n)
                from symbiont_tpu.bus.tcp import _FrameReader

                r = _FrameReader(payload)
                op = r.u8()
                if op == 1:  # SUB
                    sid = r.u32()
                    self.conns[writer][sid] = (r.s(), r.s() or None)
                elif op == 2:  # UNSUB
                    self.conns[writer].pop(r.u32(), None)
                elif op == 3:  # PUB
                    subject = r.s()
                    reply = r.s()
                    headers = {r.s(): r.s() for _ in range(r.u16())}
                    data = r.data()
                    if subject.startswith("_SYMBUS.") and reply:
                        try:
                            self.control_requests.append(
                                (subject, json.loads(data)))
                        except ValueError:
                            self.control_requests.append((subject, None))
                        await self._route(reply, None, {},
                                          json.dumps({"ok": True}).encode())
                    else:
                        await self._route(subject, reply or None, headers,
                                          data)
                elif op == 4:  # PING
                    pass
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self.conns.pop(writer, None)
            writer.close()


def test_tcp_bus_reconnects_resubscribes_and_reattaches_consumers():
    """A connection reset mid-run: the client auto-reconnects with backoff,
    re-sends every SUB, re-issues add_stream, re-attaches durable
    consumers, and messages published after the reset arrive — the client
    no longer dies permanently on one disconnect."""
    from symbiont_tpu.bus.tcp import TcpBus

    async def scenario():
        broker = _MiniBroker()
        port = await broker.start()
        bus = TcpBus("127.0.0.1", port, reconnect_base_s=0.05,
                     reconnect_max_s=0.2, send_wait_s=5.0)
        await bus.connect()
        try:
            sub = await bus.subscribe("t.events")
            await bus.add_stream("s", ["t.>"], ack_wait_s=1.0)
            dsub = await bus.durable_subscribe("s", "g")
            assert [s for s, _ in broker.control_requests] == [
                "_SYMBUS.stream.create", "_SYMBUS.consumer.create"]

            await bus.publish("t.events", b"before")
            m = await sub.next(5.0)
            assert m is not None and m.data == b"before"

            # ---- the fault: every client connection reset
            await broker.kill_connections()
            assert await _wait_for(lambda: bus.stats["disconnects"] >= 1,
                                   timeout=5.0)
            # publish during/after the gap: waits for the reconnect, then
            # sends — no ConnectionError, no dead client
            await bus.publish("t.events", b"after")
            m = await sub.next(5.0)
            assert m is not None and m.data == b"after", \
                "subscription did not survive the reconnect"
            assert bus.stats["reconnects"] == 1
            # session restored: stream + consumer re-issued broker-side
            control = [s for s, _ in broker.control_requests]
            assert control.count("_SYMBUS.stream.create") == 2
            assert control.count("_SYMBUS.consumer.create") == 2
            assert not dsub._closed  # durable sub survived too
        finally:
            await bus.close()
            await broker.stop()

    asyncio.run(scenario())


# ------------------------------------ poison message -> DLQ -> HTTP replay

def test_poison_message_quarantined_and_replayed_via_api(tmp_path):
    """A poison message fails every delivery: after EXACTLY max_deliver
    attempts it is quarantined (not redelivered, not dropped), shows up in
    GET /api/dlq with its failure metadata, and POST /api/dlq/replay
    re-enters it into the durable flow — where the fixed handler finally
    processes it. Zero loss, bounded retries."""
    from symbiont_tpu.services.api import ApiService
    from symbiont_tpu.services.base import Service

    max_deliver = 3
    poisoned = [True]
    processed = []

    class _IngestService(Service):
        name = "ingest"

        async def _setup(self):
            await self._subscribe_loop("work.item", self._handle,
                                       queue="q.ingest",
                                       durable_stream="jobs")

        async def _handle(self, msg):
            if poisoned[0]:
                raise RuntimeError("poison payload")
            processed.append(msg.data)

    async def scenario():
        bus = InprocBus()
        await bus.add_stream("jobs", ["work.item"], ack_wait_s=0.1,
                             max_deliver=max_deliver)
        svc = _IngestService(bus)
        await svc.start()
        api = ApiService(bus, ApiConfig(host="127.0.0.1", port=0))
        await api.start()
        loop = asyncio.get_running_loop()
        port = api.port

        def http(method, path, body=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=json.dumps(body).encode() if body is not None else None,
                headers={"Content-Type": "application/json"}, method=method)
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read())

        try:
            await bus.publish("work.item", b'{"job": "poison"}')
            assert await _wait_for(lambda: len(bus.dlq) == 1), \
                "poison message was not quarantined"
            # exactly max_deliver attempts, then quarantine — never more
            entry = bus.dlq.list()[0]
            assert entry.deliveries == max_deliver
            assert entry.subject == "work.item"
            await asyncio.sleep(0.3)  # would-be extra redeliveries
            from symbiont_tpu.utils.telemetry import metrics

            failed = metrics.get("bus.failed",
                                 labels={"service": "ingest",
                                         "subject": "work.item"})
            assert failed == max_deliver

            # inspectable over HTTP
            status, body = await loop.run_in_executor(
                None, http, "GET", "/api/dlq")
            assert status == 200 and body["available"] and body["size"] == 1
            (e,) = body["entries"]
            assert e["deliveries"] == max_deliver
            assert e["stream"] == "jobs" and e["group"] == "q.ingest"
            assert "max_deliver exhausted" in e["reason"]
            assert json.loads(e["data_preview"]) == {"job": "poison"}

            # fix the handler, replay through the HTTP surface
            poisoned[0] = False
            status, body = await loop.run_in_executor(
                None, lambda: http("POST", "/api/dlq/replay",
                                   {"id": e["id"]}))
            assert status == 200 and body["replayed"] == 1
            assert await _wait_for(lambda: len(processed) == 1), \
                "replayed message was not processed"
            assert processed[0] == b'{"job": "poison"}'
            status, body = await loop.run_in_executor(
                None, http, "GET", "/api/dlq")
            assert body["size"] == 0
        except urllib.error.HTTPError as err:
            raise AssertionError(f"unexpected HTTP error: {err}") from err
        finally:
            await api.stop()
            await svc.stop()
            await bus.close()

    asyncio.run(scenario())


def test_dlq_replay_error_shapes():
    """/api/dlq/replay input validation: missing selector -> 400, unknown
    id -> 404 (already replayed / evicted)."""
    from symbiont_tpu.services.api import ApiService

    async def scenario():
        bus = InprocBus()
        api = ApiService(bus, ApiConfig(host="127.0.0.1", port=0))
        await api.start()
        loop = asyncio.get_running_loop()
        port = api.port

        def post(body):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/dlq/replay",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        try:
            status, _ = await loop.run_in_executor(None, post, {})
            assert status == 400
            status, _ = await loop.run_in_executor(None, post, {"id": 999})
            assert status == 404
            status, body = await loop.run_in_executor(
                None, post, {"all": True})
            assert status == 200 and body["replayed"] == 0
        finally:
            await api.stop()
            await bus.close()

    asyncio.run(scenario())
