"""Engine tests: text parity, bucketing, executable cache, DP mesh, batcher."""

import asyncio

import numpy as np
import pytest

import jax

from symbiont_tpu.config import EngineConfig
from symbiont_tpu.engine.bucketing import choose_bucket, pad_to_bucket, plan_batches
from symbiont_tpu.engine.engine import TpuEngine
from symbiont_tpu.engine.text import clean_text, split_sentences, tokenize_words
from symbiont_tpu.engine.tokenizer import HashTokenizer


# ------------------------------------------------------------------- text

def test_clean_text_whitespace_parity():
    # reference: preprocessing_service/src/main.rs:28-33
    assert clean_text("  a\t b\n\nc  ") == "a b c"
    assert clean_text("\n \t ") == ""


def test_split_sentences_parity():
    # reference: preprocessing_service/src/main.rs:41-62
    assert split_sentences("One. Two? Three!") == ["One.", "Two?", "Three!"]
    assert split_sentences("No delimiter here") == ["No delimiter here"]
    assert split_sentences("Trailing remainder. extra") == ["Trailing remainder.", "extra"]
    assert split_sentences("Привет мир. Как дела?") == ["Привет мир.", "Как дела?"]
    # consecutive delimiters produce empty-trimmed slices like the reference
    assert split_sentences("Hi!! Done.") == ["Hi!", "!", "Done."]


def test_tokenize_words():
    assert tokenize_words("a b  c") == ["a", "b", "c"]


# -------------------------------------------------------------- bucketing

def test_choose_bucket():
    assert choose_bucket(5, [32, 64]) == 32
    assert choose_bucket(33, [32, 64]) == 64
    assert choose_bucket(100, [32, 64]) == 64  # clamp to max


def test_pad_to_bucket():
    ids, mask = pad_to_bucket([[1, 2], [3]], 4, pad_id=9)
    np.testing.assert_array_equal(ids, [[1, 2, 9, 9], [3, 9, 9, 9]])
    np.testing.assert_array_equal(mask, [[1, 1, 0, 0], [1, 0, 0, 0]])


def test_plan_batches_groups_by_bucket_and_limits_size():
    lengths = [5, 60, 6, 61, 7, 8]
    plans = plan_batches(lengths, [32, 64], max_batch=2)
    # all short ones in 32-bucket batches of ≤2, long ones in 64
    got = {}
    for bucket, idxs in plans:
        got.setdefault(bucket, []).extend(idxs)
        assert len(idxs) <= 2
    assert sorted(got[32]) == [0, 2, 4, 5]
    assert sorted(got[64]) == [1, 3]


# ----------------------------------------------------------------- engine

def _small_engine(**kw):
    cfg = EngineConfig(embedding_dim=32, length_buckets=[8, 16], batch_buckets=[2, 4],
                       max_batch=4, dtype="float32", data_parallel=False)
    return TpuEngine(cfg, **kw)


def test_embed_texts_order_and_shape():
    eng = _small_engine()
    texts = ["short one", "a much longer sentence with many words repeated " * 3,
             "mid size text here", "tiny"]
    out = eng.embed_texts(texts)
    assert out.shape == (4, 32)
    assert np.isfinite(out).all()
    # order must be restored after sort-by-length batching
    solo = np.stack([eng.embed_texts([t])[0] for t in texts])
    np.testing.assert_allclose(out, solo, atol=1e-4, rtol=1e-3)


def test_embed_empty_and_query():
    eng = _small_engine()
    assert eng.embed_texts([]).shape == (0, 32)
    q = eng.embed_query("hello world")
    assert q.shape == (32,)


def test_executable_cache_bounded_and_reused():
    eng = _small_engine()
    eng.embed_texts(["one two"])
    c0 = eng.stats["compiles"]
    eng.embed_texts(["three four"])  # same (bucket, batch) → no new compile
    assert eng.stats["compiles"] == c0
    eng.embed_texts(["w " * 14])  # longer → next bucket → one new compile
    assert eng.stats["compiles"] == c0 + 1


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_engine_data_parallel_matches_single():
    from symbiont_tpu.parallel import build_mesh

    cfg = EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                       batch_buckets=[8, 16], max_batch=16, dtype="float32")
    mesh = build_mesh()
    eng_dp = TpuEngine(cfg, mesh=mesh)
    eng_1 = TpuEngine(
        EngineConfig(embedding_dim=32, length_buckets=[8, 16], batch_buckets=[8, 16],
                     max_batch=16, dtype="float32", data_parallel=False))
    texts = [f"sentence number {i} with words" for i in range(12)]
    np.testing.assert_allclose(eng_dp.embed_texts(texts), eng_1.embed_texts(texts),
                               atol=1e-4, rtol=1e-3)


def test_rerank_with_synthetic_cross_encoder():
    import jax as _jax

    from symbiont_tpu.models import bert as bert_mod

    ccfg = bert_mod.BertConfig(vocab_size=30000, hidden_size=32, num_layers=2,
                               num_heads=2, intermediate_size=64,
                               max_position_embeddings=64, dtype="float32")
    cparams = bert_mod.init_params(_jax.random.key(7), ccfg, with_pooler=True)
    cfg = EngineConfig(embedding_dim=32, length_buckets=[16, 32], batch_buckets=[2, 4],
                       max_batch=4, dtype="float32", data_parallel=False)
    eng = TpuEngine(cfg, cross_params=cparams, cross_cfg=ccfg)
    scores = eng.rerank("what is tpu", ["tpu is an accelerator", "bananas are yellow",
                                        "tensor processing unit"])
    assert scores.shape == (3,)
    assert np.isfinite(scores).all()


def test_rerank_without_model_raises():
    eng = _small_engine()
    with pytest.raises(RuntimeError, match="no cross-encoder"):
        eng.rerank("q", ["p"])


# ---------------------------------------------------------------- batcher

def test_micro_batcher_batches_and_returns_in_order():
    from symbiont_tpu.engine.batcher import MicroBatcher

    eng = _small_engine()

    async def main():
        b = MicroBatcher(eng, max_batch=8, flush_deadline_ms=10)
        await b.start()
        r1, r2 = await asyncio.gather(
            b.embed(["alpha beta", "gamma"]),
            b.embed(["delta epsilon zeta"]),
        )
        await b.close()
        return r1, r2

    r1, r2 = asyncio.run(main())
    assert r1.shape == (2, 32) and r2.shape == (1, 32)
    ref = eng.embed_texts(["alpha beta", "gamma", "delta epsilon zeta"])
    np.testing.assert_allclose(np.vstack([r1, r2]), ref, atol=1e-4, rtol=1e-3)


def test_micro_batcher_propagates_errors():
    from symbiont_tpu.engine.batcher import MicroBatcher

    eng = _small_engine()

    def boom(texts):
        raise ValueError("device on fire")

    eng.embed_texts = boom  # type: ignore

    async def main():
        b = MicroBatcher(eng, max_batch=2, flush_deadline_ms=5)
        await b.start()
        with pytest.raises(ValueError, match="device on fire"):
            await b.embed(["x"])
        await b.close()

    asyncio.run(main())


def test_hash_tokenizer_deterministic():
    t = HashTokenizer(1000)
    a = t.encode("Hello, World", 16)
    b = t.encode("hello world", 16)
    assert a[0] == t.cls_id and a[-1] == t.sep_id
    # case-insensitive, punctuation tokenized separately
    assert a[1] == b[1]
    ids, types = t.encode_pair("a b", "c d e", 32)
    assert len(ids) == len(types)
    assert types[0] == 0 and types[-1] == 1


def test_profile_hook_writes_trace(tmp_path, monkeypatch):
    """SYMBIONT_PROFILE_DIR → embed runs under jax.profiler.trace and an
    XPlane trace lands in the directory (SURVEY.md §5.1 plan)."""
    monkeypatch.setenv("SYMBIONT_PROFILE_DIR", str(tmp_path))
    eng = _small_engine()
    eng.embed_texts(["profile me"])
    traces = list(tmp_path.rglob("*.xplane.pb"))
    assert traces, f"no xplane trace written under {tmp_path}"


def test_fused_query_search_matches_split_path(tmp_path):
    """embed_and_search (one device program) must rank exactly like the
    split embed_query → store.search path."""
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore

    eng = _small_engine()
    store = VectorStore(VectorStoreConfig(dim=32, data_dir=str(tmp_path),
                                          shard_capacity=64))
    corpus = [f"sentence number {i} about topic {i % 5}" for i in range(20)]
    vecs = eng.embed_texts(corpus)
    store.upsert([(f"p{i}", vecs[i], {"sentence_text": corpus[i], "i": i})
                  for i in range(len(corpus))])

    split = store.search(eng.embed_query("topic 3"), 5)
    fused = store.search_fused(eng, "topic 3", 5)
    assert [h.id for h in fused] == [h.id for h in split]
    for a, b in zip(fused, split):
        assert abs(a.score - b.score) < 1e-2  # bf16 matmul rounding
        assert a.payload == b.payload


def test_fused_query_search_empty_store(tmp_path):
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore

    eng = _small_engine()
    store = VectorStore(VectorStoreConfig(dim=32, data_dir=str(tmp_path)))
    assert store.search_fused(eng, "anything", 5) == []


def test_warm_fused_tracks_capacity_blocks(tmp_path):
    """warm_fused records the capacity it compiled for (k=8 AND k=16
    buckets); crossing a capacity block via upserts flags the warm as stale
    so the owner re-warms before the next query pays a fresh compile."""
    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore

    eng = _small_engine()
    store = VectorStore(VectorStoreConfig(dim=32, data_dir=str(tmp_path),
                                          shard_capacity=64))
    assert not store.fused_warm_stale()  # never warmed → nothing to re-warm
    store.warm_fused(eng, word_counts=(3,))
    assert store._warmed_capacity == 64
    assert not store.fused_warm_stale()

    rng = np.random.default_rng(0)
    store.upsert([(f"p{i}", rng.standard_normal(32), {})
                  for i in range(65)])  # 65 rows cross the 64-row block
    assert store.fused_warm_stale()
    store.warm_fused(eng, word_counts=(3,))
    assert store._warmed_capacity == 128
    assert not store.fused_warm_stale()


def test_concurrent_entry_points_stress(tmp_path):
    """The engine's concurrency contract (module docstring): embed / rerank /
    fused-search may run concurrently from multiple threads — results must
    equal the serial baselines and the stats counters must be exact (bare
    `+=` would lose increments under this contention)."""
    from concurrent.futures import ThreadPoolExecutor

    from symbiont_tpu.config import VectorStoreConfig
    from symbiont_tpu.memory.vector_store import VectorStore

    cfg = EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                       batch_buckets=[2, 4], max_batch=4, dtype="float32",
                       data_parallel=False, rerank_enabled=True)
    eng = TpuEngine(cfg)
    store = VectorStore(VectorStoreConfig(dim=32, data_dir=str(tmp_path),
                                          shard_capacity=64))
    corpus = [f"doc {i} about topic {i % 3}" for i in range(12)]
    vecs = eng.embed_texts(corpus)
    store.upsert([(f"p{i}", vecs[i], {"i": i}) for i in range(len(corpus))])

    texts = [f"query text number {i}" for i in range(6)]
    base_embed = eng.embed_texts(texts)
    base_rerank = eng.rerank("topic", corpus[:5])
    base_fused = [h.id for h in store.search_fused(eng, "topic 1", 4)]
    s0 = dict(eng.stats)

    N = 8
    with ThreadPoolExecutor(max_workers=12) as pool:
        emb_f = [pool.submit(eng.embed_texts, texts) for _ in range(N)]
        rr_f = [pool.submit(eng.rerank, "topic", corpus[:5]) for _ in range(N)]
        fu_f = [pool.submit(store.search_fused, eng, "topic 1", 4)
                for _ in range(N)]
        for f in emb_f:
            np.testing.assert_allclose(f.result(), base_embed, rtol=1e-5)
        for f in rr_f:
            np.testing.assert_allclose(f.result(), base_rerank, rtol=1e-5)
        for f in fu_f:
            assert [h.id for h in f.result()] == base_fused

    # counters exact under contention
    assert eng.stats["embed_calls"] == s0["embed_calls"] + N
    assert eng.stats["rerank_calls"] == s0["rerank_calls"] + N
    assert eng.stats["qsearch_calls"] == s0["qsearch_calls"] + N
    assert eng.stats["sentences_embedded"] == s0["sentences_embedded"] + N * len(texts)


def test_cold_executable_race_compiles_once():
    """Two threads racing a COLD executable key must converge on one cached
    executable and count one compile (the loser discards its wrapper)."""
    from concurrent.futures import ThreadPoolExecutor

    eng = _small_engine()
    texts = ["same shape text"] * 2
    with ThreadPoolExecutor(max_workers=2) as pool:
        a = pool.submit(eng.embed_texts, texts)
        b = pool.submit(eng.embed_texts, texts)
        np.testing.assert_allclose(a.result(), b.result(), rtol=1e-6)
    # both calls hit one (bucket, batch-bucket) shape → exactly one compile
    assert eng.stats["compiles"] == 1
    assert len(eng._exec_cache) == 1


# ------------------------------------------------- ingest host pipeline (r4)

def test_embed_texts_chunked_pipeline_matches_unchunked():
    """host_prep_chunk splits tokenization into prefetched chunks; results
    (and their row order) must be identical to the single-pass path."""
    texts = [f"sentence {i} " + "pad " * (i % 13) for i in range(30)]
    base = _small_engine().embed_texts(texts)
    cfg = EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                       batch_buckets=[2, 4], max_batch=4, dtype="float32",
                       data_parallel=False, host_prep_chunk=7)
    np.testing.assert_allclose(TpuEngine(cfg).embed_texts(texts), base,
                               atol=1e-4, rtol=1e-3)


def test_embed_texts_prefetch_overlaps_dispatch():
    """Tokenize of chunk N+1 must run CONCURRENTLY with dispatch of chunk N:
    the gated tokenizer blocks chunk 2's encode until chunk 1 has dispatched,
    so a serial implementation (encode everything, then dispatch) times out."""
    import threading

    from symbiont_tpu.engine.tokenizer import HashTokenizer

    dispatched = threading.Event()

    class GatedTok(HashTokenizer):
        def __init__(self):
            super().__init__(30000)
            self.calls = 0

        def encode_batch(self, texts, max_len):
            self.calls += 1
            if self.calls == 2:  # chunk 2 rides the prefetch thread
                assert dispatched.wait(10), \
                    "chunk-2 tokenize did not overlap chunk-1 dispatch"
            return super().encode_batch(texts, max_len)

    tok = GatedTok()
    cfg = EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                       batch_buckets=[2, 4], max_batch=4, dtype="float32",
                       data_parallel=False, host_prep_chunk=4)
    eng = TpuEngine(cfg, tokenizer=tok)
    orig = eng._dispatch_embed

    def wrapped(encoded, offset, buckets, pending):
        orig(encoded, offset, buckets, pending)
        dispatched.set()

    eng._dispatch_embed = wrapped
    out = eng.embed_texts([f"t {i} " + "w " * (i % 10) for i in range(10)])
    assert out.shape == (10, 32)
    assert tok.calls == 3  # 10 texts / chunk 4


def test_ids_ship_narrow_dtype_same_result():
    """Vocab ≤ 65535 ships uint16 ids over the wire (half the h2d bytes);
    embeddings must match the int32 wire bit-for-bit in float32."""
    eng = _small_engine()
    assert eng._ids_dtype == np.uint16  # synthetic vocab 30000 fits
    texts = ["alpha beta gamma", "delta " * 5, "x"]
    narrow = eng.embed_texts(texts)
    eng32 = _small_engine()
    eng32._ids_dtype = np.int32
    np.testing.assert_allclose(eng32.embed_texts(texts), narrow,
                               atol=1e-6, rtol=1e-6)


def test_concat_fetch_groups_match(monkeypatch):
    """Grouped single-copy fetch (CONCAT_FETCH_MAX) must scatter rows
    identically to the per-batch path across group boundaries."""
    texts = [f"g {i} " + "w " * (i % 11) for i in range(26)]
    base = _small_engine().embed_texts(texts)
    monkeypatch.setattr(TpuEngine, "CONCAT_FETCH_MAX", 2)
    np.testing.assert_allclose(_small_engine().embed_texts(texts), base,
                               atol=1e-4, rtol=1e-3)


def test_micro_batcher_overlapping_flushes():
    """max_inflight_flushes=2: a flush stuck materializing (on a remote
    device that tail is ~an RTT of waiting) must not block the next flush
    from dispatching — and the stuck flush still resolves correctly."""
    import asyncio
    import threading

    from symbiont_tpu.engine.batcher import MicroBatcher

    gate = threading.Event()

    class StubEngine:
        class config:
            max_batch = 2
            flush_deadline_ms = 1.0

        def embed_texts(self, texts):
            if texts[0] == "slow":
                assert gate.wait(10), "slow flush never released"
            return np.full((len(texts), 4), float(len(texts)), np.float32)

    async def scenario():
        b = MicroBatcher(StubEngine())
        await b.start()
        slow = asyncio.ensure_future(b.embed(["slow"]))
        await asyncio.sleep(0.1)  # slow flush is in its executor, gated
        fast = await asyncio.wait_for(b.embed(["fast", "fast2"]), 5)
        assert fast.shape == (2, 4) and fast[0, 0] == 2.0
        assert not slow.done()  # proves the second flush overlapped it
        gate.set()
        out = await asyncio.wait_for(slow, 5)
        assert out.shape == (1, 4)
        await b.close()

    asyncio.run(scenario())


def test_max_batch_beyond_largest_batch_bucket():
    """max_batch larger than the top batch bucket must clamp the batch
    PLAN at the top bucket (no executable exists for a bigger shape; an
    unclamped plan underflowed row padding) — regression found by the
    engine-restart chaos test, where a redelivery surge flushed a
    max_batch-sized chunk through buckets smaller than it. Clamping keeps
    the executable set exactly |length_buckets|×|batch_buckets|."""
    cfg = EngineConfig(embedding_dim=32, length_buckets=[8, 16],
                       batch_buckets=[2, 4], max_batch=8, dtype="float32",
                       data_parallel=False)
    eng = TpuEngine(cfg)
    assert eng._plan_cap == 4
    texts = [f"surge doc {i} with words" for i in range(8)]
    out = eng.embed_texts(texts)
    assert out.shape == (8, 32)
    # no shape outside the configured bucket grid was compiled
    assert all(B in (2, 4) for (_, _, B) in eng._exec_cache)
    solo = np.stack([eng.embed_texts([t])[0] for t in texts])
    np.testing.assert_allclose(out, solo, atol=1e-4, rtol=1e-3)
