"""LmEngine: byte tokenizer, bucketed decode executables, service wiring."""

import asyncio

import pytest

from symbiont_tpu.config import LmConfig
from symbiont_tpu.engine.lm import ByteTokenizer, LmEngine, _round_up

TINY = LmConfig(enabled=True, arch="llama", hidden_size=32, num_layers=2,
                num_heads=4, intermediate_size=64, max_positions=256,
                dtype="float32", prompt_buckets=[8, 16, 64],
                new_token_buckets=[8, 16], temperature=0.0)


def test_byte_tokenizer_roundtrip():
    t = ByteTokenizer()
    for s in ["hello world", "юникод работает", "emoji 🌱 ok", ""]:
        ids = t.encode(s, 512)
        assert ids[0] == t.bos_id
        assert t.decode(ids) == s
    assert len(t.encode("x" * 100, 8)) == 8


def test_round_up():
    assert _round_up(1, [8, 16]) == 8
    assert _round_up(9, [8, 16]) == 16
    assert _round_up(99, [8, 16]) == 16  # clamps at the top bucket


def test_generate_deterministic_greedy():
    lm = LmEngine(TINY)
    a = lm.generate("seed text", 8)
    b = lm.generate("seed text", 8)
    assert isinstance(a, str)
    assert a == b  # greedy (temperature=0) ignores the advancing key
    assert lm.stats["generate_calls"] == 2
    assert lm.stats["tokens_generated"] > 0


def test_generate_respects_max_new_tokens():
    lm = LmEngine(TINY)
    out = lm.generate("abc", 3)  # bucket rounds to 8, result trimmed to ≤3
    assert len(out.encode("utf-8", errors="replace")) <= 3


def test_prompt_longer_than_top_bucket_truncates():
    lm = LmEngine(TINY)
    out = lm.generate("word " * 500, 8)
    assert isinstance(out, str)


def test_prompt_bucket_never_overflows_positions():
    # regression: P + new_bucket must fit max_positions even when rounding
    # up would select a bucket past the cap (64-pos model, new bucket 16 →
    # prompt bucket 64 would overflow; must clamp to 48)
    cfg = LmConfig(enabled=True, arch="llama", hidden_size=32, num_layers=1,
                   num_heads=4, intermediate_size=64, max_positions=64,
                   dtype="float32", prompt_buckets=[8, 16, 64],
                   new_token_buckets=[16], temperature=0.0)
    lm = LmEngine(cfg)
    out = lm.generate("x" * 200, 16)  # 200-byte prompt rounds toward 64
    assert isinstance(out, str)

    # hard error path: even the smallest new bucket cannot fit the positions
    small = LmConfig(enabled=True, arch="llama", hidden_size=32, num_layers=1,
                     num_heads=4, intermediate_size=64, max_positions=8,
                     dtype="float32", prompt_buckets=[8],
                     new_token_buckets=[16], temperature=0.0)
    with pytest.raises(ValueError):
        LmEngine(small).generate("hi", 16)


def test_long_prompt_keeps_tail():
    # regression: the window fed to the model must be the prompt's TAIL
    lm = LmEngine(TINY)
    marker = "ZQX"
    long_prompt = ("a" * 5000) + marker  # tail marker far past any cap
    ids = lm.tokenizer.encode(long_prompt, 1 << 30)
    assert lm.tokenizer.decode(ids[-16:]).endswith(marker)
    out = lm.generate(long_prompt, 8)
    assert isinstance(out, str)


def test_text_generator_service_uses_lm():
    from symbiont_tpu import subjects
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.schema import (
        GeneratedTextMessage,
        GenerateTextTask,
        from_json,
        to_json_bytes,
    )
    from symbiont_tpu.services.text_generator import TextGeneratorService

    async def run():
        bus = InprocBus()
        lm = LmEngine(TINY)
        svc = TextGeneratorService(bus, lm_generate=lm.generate)
        await svc.start()
        sub = await bus.subscribe(subjects.EVENTS_TEXT_GENERATED)
        task = GenerateTextTask(task_id="t-lm", prompt="hello", max_length=8)
        await bus.publish(subjects.TASKS_GENERATION_TEXT, to_json_bytes(task))
        msg = await asyncio.wait_for(sub.__aiter__().__anext__(), timeout=60)
        out = from_json(GeneratedTextMessage, msg.data)
        await svc.stop()
        assert out.original_task_id == "t-lm"
        assert isinstance(out.generated_text, str)

    asyncio.run(run())


def test_generate_batch_greedy_matches_singles():
    """Greedy batched decode row i == greedy single decode of prompt i:
    right-alignment + kv_valid isolate rows from their batchmates."""
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=2,
                            num_heads=2, intermediate_size=64,
                            max_positions=128, dtype="float32",
                            prompt_buckets=[8, 16], new_token_buckets=[8],
                            temperature=0.0))
    prompts = ["hello", "a much longer prompt with many words",
               ""]
    singles = [eng.generate(p, 8, temperature=0.0) for p in prompts]
    batched = eng.generate_batch(prompts, [8, 8, 8], temperature=0.0)
    assert batched == singles


def test_generate_batch_per_request_trim():
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=64, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[8],
                            temperature=0.0))
    short, long = eng.generate_batch(["x", "x"], [2, 8], temperature=0.0)
    # byte tokenizer: one byte per token → lengths map to chars
    assert len(short.encode()) <= 2
    assert long.startswith(short)


def test_gen_batcher_batches_concurrent_requests():
    """N concurrent submissions within the flush window → ONE decode call,
    each future resolving to its own row."""
    import asyncio

    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.batcher import GenBatcher
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=64, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[8],
                            temperature=0.0, gen_max_batch=4,
                            gen_flush_deadline_ms=50.0))
    singles = [eng.generate(p, 6, temperature=0.0)
               for p in ["aa", "bb", "cc"]]
    sessions_before = eng.stats.get("sessions", 0)

    async def scenario():
        b = GenBatcher(eng)
        await b.start()
        try:
            return await asyncio.gather(b.generate("aa", 6),
                                        b.generate("bb", 6),
                                        b.generate("cc", 6))
        finally:
            await b.close()

    results = asyncio.run(scenario())
    assert results == singles
    # one decode SESSION served all three (flush-window batching)
    assert eng.stats["sessions"] == sessions_before + 1


def test_generate_stream_greedy_matches_generate():
    """Concatenated stream deltas == generate()'s full text (greedy), and
    deltas arrive in multiple chunks for a multi-chunk request."""
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=2,
                            num_heads=2, intermediate_size=64,
                            max_positions=128, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[16],
                            temperature=0.0, stream_chunk=4))
    full = eng.generate("hello", 16, temperature=0.0)
    deltas = list(eng.generate_stream("hello", 16, temperature=0.0))
    assert "".join(deltas) == full
    assert len(deltas) > 1  # actually streamed, not one blob


def test_slow_stream_consumer_does_not_starve_generate():
    """Regression (round-2 verdict weak #3): generate_stream used to hold the
    engine lock across yields, so a paused/slow SSE consumer starved every
    concurrent generate()/generate_batch() caller. The lock must be free
    while the stream consumer is parked between deltas."""
    import threading

    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=2,
                            num_heads=2, intermediate_size=64,
                            max_positions=128, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[16],
                            temperature=0.0, stream_chunk=4))
    stream = eng.generate_stream("hello", 16, temperature=0.0)
    first = next(stream)  # consumer now parked mid-stream, holding nothing
    assert first

    result = {}

    def concurrent():
        result["out"] = eng.generate("other prompt", 8, temperature=0.0)

    t = threading.Thread(target=concurrent)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive(), \
        "generate() starved by a paused stream consumer holding the lock"
    assert isinstance(result["out"], str)

    # the paused stream resumes and still matches generate() exactly
    rest = "".join(stream)
    assert first + rest == eng.generate("hello", 16, temperature=0.0)


def test_closed_stream_still_records_stats():
    """A client disconnect (generator close) must not lose the stats update
    and must release the engine for other callers."""
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=64, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[16],
                            temperature=0.0, stream_chunk=4))
    stream = eng.generate_stream("hello", 16, temperature=0.0)
    next(stream)
    stream.close()  # simulates the SSE client going away mid-stream
    assert eng.stats["generate_calls"] == 1
    assert eng.stats["tokens_generated"] > 0
    # engine is free: a follow-up call completes
    assert isinstance(eng.generate("x", 8), str)


def test_generate_stream_respects_max_new():
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=64, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[8],
                            temperature=0.0, stream_chunk=8))
    text = "".join(eng.generate_stream("x", 3, temperature=0.0))
    assert len(text.encode()) <= 3  # byte tokenizer: 1 byte per token


def test_incremental_decoder_multibyte_straddle():
    """A multi-byte UTF-8 char split across chunks must not leak a
    replacement char into the stream: the unstable tail is held back and the
    concatenated deltas equal the full decode exactly."""
    from symbiont_tpu.engine.lm import ByteTokenizer, IncrementalDecoder

    tok = ByteTokenizer()
    # "héllo" = 68 c3 a9 6c 6c 6f — split between c3 and a9
    full = list("héllo".encode("utf-8"))
    d = IncrementalDecoder(tok)
    out = d.push(full[:2])       # ends mid-'é' → 'h' only, ufffd held back
    assert out == "h"
    out += d.push(full[:4])      # 'é' completed + 'l'
    out += d.push(full)
    out += d.flush(full)
    assert out == "héllo"
    assert "�" not in out


def test_incremental_decoder_genuine_invalid_bytes():
    """Genuinely invalid bytes DO surface (at flush), they are not eaten."""
    from symbiont_tpu.engine.lm import ByteTokenizer, IncrementalDecoder

    tok = ByteTokenizer()
    toks = list(b"ok\xc3")  # dangling lead byte, never completed
    d = IncrementalDecoder(tok)
    out = d.push(toks)
    out += d.flush(toks)
    assert out == "ok�"


def test_stream_chunk_must_divide_new_buckets():
    """The chunked streaming scan runs whole chunks against a cache with
    exactly new_bucket decode slots; a non-dividing chunk would overrun it
    (relying on dynamic_update_slice clamp semantics), so LmConfig rejects
    the combination up front."""
    with pytest.raises(ValueError, match="stream_chunk"):
        LmConfig(stream_chunk=24, new_token_buckets=[64])
    # buckets smaller than the chunk are fine: chunk shrinks to the bucket
    LmConfig(stream_chunk=16, new_token_buckets=[8, 16, 64])


def test_incremental_decoder_non_prefix_stable_decode():
    """If decode is non-prefix-stable for a reason other than a trailing
    replacement-char run (e.g. decode-time cleanup), flush must still emit
    the divergent tail — the terminal output is never silently lost."""
    from symbiont_tpu.engine.lm import IncrementalDecoder

    class WeirdTok:
        def decode(self, ids):
            # decoding 3+ tokens "cleans up" earlier output: not a prefix
            return "ab" if len(ids) < 3 else "aXc"

    d = IncrementalDecoder(WeirdTok())
    assert d.push([1, 2]) == "ab"
    assert d.push([1, 2, 3]) == ""       # push stays conservative
    assert d.flush([1, 2, 3]) == "Xc"    # flush emits past the common prefix


def test_gen_batcher_mixed_sampling_shares_one_decode():
    """Per-request temperature/top_k are per-row traced vectors, so
    concurrent requests with DIFFERENT sampling params still decode as ONE
    batch — and each greedy row matches its single-call output exactly
    (rows are independent of their batchmates)."""
    import asyncio

    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.batcher import GenBatcher
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=64, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[8],
                            temperature=0.0, top_k=40, gen_max_batch=4,
                            gen_flush_deadline_ms=50.0))
    greedy_single = eng.generate("aa", 6, temperature=0.0)
    sessions_before = eng.stats.get("sessions", 0)

    async def scenario():
        b = GenBatcher(eng)
        await b.start()
        try:
            return await asyncio.gather(
                b.generate("aa", 6),                      # default → greedy
                b.generate("aa", 6, temperature=0.0),     # explicit default
                b.generate("aa", 6, temperature=5.0, top_k=0))  # sampled
        finally:
            await b.close()

    default, explicit, sampled = asyncio.run(scenario())
    assert default == explicit == greedy_single  # greedy rows unperturbed
    assert isinstance(sampled, str)
    # mixed sampling params share ONE decode session
    assert eng.stats["sessions"] == sessions_before + 1


def test_generate_top_k_beyond_vocab_is_safe():
    """top_k larger than the vocab must behave as full-vocab sampling, not
    crash lax.top_k (regression: client-supplied top_k=1000 with a 257-byte
    vocab)."""
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=64, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[8]))
    out = eng.generate("x", 6, temperature=1.0, top_k=1000)
    assert isinstance(out, str)


def test_sampling_top_k_bucket_bounds_executables():
    """_top_k_bucket: log-bounded static buckets; exact-k threshold stays
    dynamic."""
    from symbiont_tpu.models.gpt import _top_k_bucket

    assert _top_k_bucket(0, 257) == 0        # no cutoff
    assert _top_k_bucket(257, 257) == 0      # >= vocab → cutoff is a no-op
    assert _top_k_bucket(1000, 257) == 0
    assert _top_k_bucket(1, 257) == 8
    assert _top_k_bucket(8, 257) == 8
    assert _top_k_bucket(9, 257) == 16
    assert _top_k_bucket(40, 50257) == 64
    assert _top_k_bucket(200, 257) == 256


def test_sampling_values_do_not_recompile():
    """New temperature/top_k values within a bucket must reuse the compiled
    decode executable (they are traced, not static)."""
    from symbiont_tpu.models import gpt as gpt_mod

    eng = LmEngine(TINY)
    eng.generate("a", 6, temperature=0.7, top_k=5)
    n = gpt_mod._generate_jit._cache_size()
    eng.generate("a", 6, temperature=0.9, top_k=7)  # same top-k bucket (8)
    eng.generate("a", 6, temperature=1.3, top_k=3)
    assert gpt_mod._generate_jit._cache_size() == n


@pytest.mark.parametrize("arch,num_kv", [("gpt2", None), ("llama", 2)])
def test_bf16_close_to_fp32_prefill_and_decode(arch, num_kv):
    """Production-dtype gate for the decoder (ungated by torch — pure JAX):
    the bf16 attention path (bf16 softmax) must stay close to fp32 on BOTH
    shapes it serves: prefill (S>1, fresh cache, padded rows) and the decode
    step (S=1 against a populated, partially masked cache). Next-token
    distribution cosine > 0.995 per row."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from symbiont_tpu.models.gpt import (GPTConfig, forward, init_cache,
                                         init_params)

    cfg32 = GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                      num_heads=4, num_kv_heads=num_kv, intermediate_size=64,
                      max_position_embeddings=64, arch=arch, dtype="float32",
                      tie_word_embeddings=True)
    cfg16 = dataclasses.replace(cfg32, dtype="bfloat16")
    params = init_params(jax.random.key(11), cfg32)
    rng = np.random.default_rng(5)
    B, S, NEW = 3, 16, 4
    ids = jnp.asarray(rng.integers(1, 97, (B, S)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    # partially masked cache: row 1's first 5 slots are padding
    kv_valid = jnp.ones((B, S + NEW), bool).at[1, :5].set(False)

    def cos(a, b):
        pa = jax.nn.softmax(a, axis=-1)
        pb = jax.nn.softmax(b, axis=-1)
        return float(((pa * pb).sum(-1) / (jnp.linalg.norm(pa, axis=-1)
                     * jnp.linalg.norm(pb, axis=-1))).min())

    outs = {}
    for name, cfg, dt in (("f32", cfg32, jnp.float32),
                          ("bf16", cfg16, jnp.bfloat16)):
        cache = init_cache(cfg, B, S + NEW, dt)
        lo, cache = forward(params, ids, cache, positions, cfg, kv_valid)
        cache = cache._replace(length=jnp.asarray(S, jnp.int32))
        # one decode step against the populated cache
        tok = jnp.argmax(lo[:, -1], axis=-1).astype(jnp.int32)[:, None]
        lo1, _ = forward(params, tok, cache,
                         jnp.full((B, 1), S, jnp.int32), cfg, kv_valid)
        outs[name] = (lo[:, -1], lo1[:, 0])

    assert cos(outs["f32"][0], outs["bf16"][0]) > 0.995  # prefill
    assert cos(outs["f32"][1], outs["bf16"][1]) > 0.995  # decode w/ cache


# -------------------------------------------- continuous batching (round 4)

def test_session_matches_generate_batch():
    """A session with no admissions decodes exactly generate_batch's output
    (chunked scan == full scan in float32, greedy)."""
    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=2,
                            num_heads=2, intermediate_size=64,
                            max_positions=128, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[16],
                            stream_chunk=4, temperature=0.0))
    prompts, wants = ["hello", "wider prompt"], [10, 16]
    base = eng.generate_batch(prompts, wants, temperature=0.0)
    sess = eng.start_session(prompts, wants, temperature=0.0)
    out = {}
    while not sess.done() or any(r is not None for r in sess.rows):
        finished = sess.step()
        out.update(finished)
        if not finished and sess.done():
            break
    assert [out[0], out[1]] == base


def test_session_admit_matches_standalone():
    """THE continuous-batching correctness property: a request admitted at a
    chunk boundary of an in-flight decode produces EXACTLY its standalone
    output — the gap cache slots are masked and its logical positions carry
    on from its own prompt (gpt.merge_rows)."""
    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=2,
                            num_heads=2, intermediate_size=64,
                            max_positions=128, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[32],
                            stream_chunk=4, temperature=0.0))
    solo_a = eng.generate("hello", 20, temperature=0.0)
    solo_b = eng.generate("world!", 12, temperature=0.0)

    sess = eng.start_session(["hello"], [20], temperature=0.0)
    out = {}
    out.update(sess.step())  # chunk 1 decodes with A alone
    assert sess.capacity() >= 1 and sess.can_admit("world!", 12)
    (tag_b,) = sess.admit(["world!"], [12], temperature=[0.0], top_k=[0])
    assert tag_b not in out
    for _ in range(64):
        out.update(sess.step())
        if all(r is None for r in sess.rows):
            break
    assert out[0] == solo_a
    assert out[tag_b] == solo_b
    assert eng.stats["admitted"] == 1


def test_session_budget_and_capacity_gates():
    """can_admit refuses when the budget outruns the session's remaining
    steps, when no row is free, or when the prompt overflows the bucket."""
    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=128, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[8],
                            stream_chunk=4, temperature=0.0))
    sess = eng.start_session(["a"], [8], temperature=0.0)
    assert sess.capacity() == 3  # session_min_rows=4 reserves headroom rows
    sess.step()  # 4 of 8 steps spent
    assert not sess.can_admit("b", 8)        # budget > remaining steps
    assert sess.can_admit("b", 4)
    assert not sess.can_admit("x" * 50, 4)   # prompt overflows the P bucket

    eng1 = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                             num_heads=2, intermediate_size=64,
                             max_positions=128, dtype="float32",
                             prompt_buckets=[8], new_token_buckets=[8],
                             stream_chunk=4, temperature=0.0,
                             session_min_rows=1))
    sess2 = eng1.start_session(["a"], [8], temperature=0.0)  # bb == 1: full
    assert sess2.capacity() == 0
    assert not sess2.can_admit("b", 1)


def test_gen_batcher_admits_midflight():
    """A request submitted while a session decodes joins it at a chunk
    boundary instead of waiting for the whole decode — and still equals its
    standalone output."""
    import threading

    from symbiont_tpu.engine import lm as lm_mod
    from symbiont_tpu.engine.batcher import GenBatcher

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=128, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[32],
                            stream_chunk=4, temperature=0.0,
                            gen_max_batch=4, gen_flush_deadline_ms=5.0))
    solo_a = eng.generate("aa", 24, temperature=0.0)
    solo_b = eng.generate("bb", 8, temperature=0.0)

    gate = threading.Event()
    orig_step = lm_mod.BatchSession.step

    def gated_step(self):
        assert gate.wait(20), "test gate never opened"
        return orig_step(self)

    lm_mod.BatchSession.step = gated_step
    try:
        async def scenario():
            b = GenBatcher(eng)
            await b.start()
            try:
                t1 = asyncio.ensure_future(b.generate("aa", 24))
                await asyncio.sleep(0.1)   # t1's session is starting/gated
                t2 = asyncio.ensure_future(b.generate("bb", 8))
                await asyncio.sleep(0)     # t2 lands in the live queue
                gate.set()
                return await asyncio.gather(t1, t2), b.stats
            finally:
                await b.close()

        (ra, rb), stats = asyncio.run(scenario())
    finally:
        lm_mod.BatchSession.step = orig_step
    assert ra == solo_a
    assert rb == solo_b
    assert stats["admitted_midflight"] == 1
    assert stats["sessions"] == 1  # t2 never started its own session


def test_gen_batcher_start_failure_fails_all_futures():
    """A session that cannot start (e.g. budget overflows the position
    space) must FAIL every waiting future — not leave callers hanging."""
    from symbiont_tpu.engine.batcher import GenBatcher

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=8, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[16],
                            temperature=0.0, gen_max_batch=4,
                            gen_flush_deadline_ms=5.0))

    async def scenario():
        b = GenBatcher(eng)
        await b.start()
        try:
            futs = [b.generate("hi", 16), b.generate("yo", 16)]
            results = await asyncio.wait_for(
                asyncio.gather(*futs, return_exceptions=True), 15)
            assert all(isinstance(r, ValueError) for r in results), results
        finally:
            await b.close()

    asyncio.run(scenario())


def test_admission_does_not_stall_inflight_steps():
    """Regression (VERDICT r4 weak #4): a newcomer's prefill — which may
    compile a fresh shape, seconds of host time — must NOT stall the
    in-flight batch's chunk cadence. The prepare phase is slowed to 0.5 s
    (simulated compile); with prefill off the lock and overlapped with
    decoding, no inter-step gap may come close to it."""
    import time as time_mod

    from symbiont_tpu.engine import lm as lm_mod
    from symbiont_tpu.engine.batcher import GenBatcher

    eng = LmEngine(LmConfig(enabled=True, hidden_size=32, num_layers=1,
                            num_heads=2, intermediate_size=64,
                            max_positions=256, dtype="float32",
                            prompt_buckets=[8], new_token_buckets=[128],
                            stream_chunk=4, temperature=0.0,
                            gen_max_batch=4, gen_flush_deadline_ms=5.0,
                            session_min_rows=4))
    solo_a = eng.generate("aa", 100, temperature=0.0)
    solo_b = eng.generate("bb", 8, temperature=0.0)
    # warm every executable the measured run will hit, so gaps measure the
    # architecture, not one-time XLA compiles: session start (bb=4), its
    # chunk step, a bb2=1 admission prefill, and the post-merge step
    warm = eng.start_session(["w"], [100], temperature=0.0)
    warm.step()
    warm.splice(warm.prepare_admit(["w2"], [8], temperature=0.0))
    warm.step()

    step_times = []
    orig_step = lm_mod.BatchSession.step
    orig_prepare = lm_mod.BatchSession.prepare_admit

    def timed_step(self):
        time_mod.sleep(0.1)  # pace chunks so the session outlasts the prep
        r = orig_step(self)
        step_times.append(time_mod.perf_counter())
        return r

    def slow_prepare(self, *a, **kw):
        time_mod.sleep(1.5)  # simulated fresh-shape compile
        return orig_prepare(self, *a, **kw)

    lm_mod.BatchSession.step = timed_step
    lm_mod.BatchSession.prepare_admit = slow_prepare
    try:
        async def scenario():
            b = GenBatcher(eng)
            await b.start()
            try:
                t1 = asyncio.ensure_future(b.generate("aa", 100))
                await asyncio.sleep(0.1)   # t1's session is decoding
                t2 = asyncio.ensure_future(b.generate("bb", 8))
                return await asyncio.gather(t1, t2), b.stats
            finally:
                await b.close()

        (ra, rb), stats = asyncio.run(scenario())
    finally:
        lm_mod.BatchSession.step = orig_step
        lm_mod.BatchSession.prepare_admit = orig_prepare
    assert ra == solo_a
    assert rb == solo_b
    assert stats["admitted_midflight"] == 1, stats
    gaps = [b - a for a, b in zip(step_times, step_times[1:])]
    assert gaps, "no consecutive steps measured"
    # old architecture: one gap swallowed the whole 1.5 s prepare; now the
    # prepare overlaps decoding and the worst gap stays ~chunk-sized. The
    # threshold leaves 0.65 s of scheduler/GC headroom over the 0.1 s pace
    # so a loaded CI host can't fail it without a genuine stall.
    assert max(gaps) < 0.75, f"step stalled {max(gaps):.3f}s during admission"


def test_gen_batcher_requeue_wakes_run_loop():
    """Regression (ADVICE r4 medium): when a session steals the queue and
    re-inserts a rejected candidate, it must set _wake — otherwise a _run
    loop that parked on the cleared event after the steal never serves the
    re-queued request until an unrelated submission arrives. Reproduced
    deterministically by driving _flush directly against a parked-state
    batcher (queue stolen, wake cleared) with a session that rejects the
    newcomer."""
    from types import SimpleNamespace

    from symbiont_tpu.engine.batcher import GenBatcher, _PendingGen

    class FakeSess:
        rows = [SimpleNamespace(tag=0)]

        def __init__(self):
            self.steps_left = 2

        def capacity(self):
            return 1

        def can_admit(self, prompt, max_new, lookahead_chunks=0):
            return False  # newcomer's budget never fits

        def prefill_warm(self, k):
            return True

        def step(self):
            self.steps_left -= 1
            return [(0, "first done")] if self.steps_left == 0 else []

        def done(self):
            return self.steps_left <= 0

    class FakeLm:
        config = SimpleNamespace(gen_max_batch=8, gen_flush_deadline_ms=1.0,
                                 new_token_buckets=[16], temperature=1.0,
                                 top_k=0)

        def start_session(self, prompts, max_new, temperature, top_k,
                          tenants=None):
            return FakeSess()

    async def scenario():
        loop = asyncio.get_running_loop()
        b = GenBatcher(FakeLm())  # _run NOT started: we drive _flush by hand
        first = _PendingGen("a", 16, 1.0, 0, loop.create_future())
        b._submit(first)
        batch = b._take_chunk()
        # the race: B lands in the queue, then _run consumes the wake and
        # parks (queue momentarily empty from its point of view after the
        # session's steal) — modeled by clearing the event before _flush runs
        late = _PendingGen("b", 16, 1.0, 0, loop.create_future())
        b._submit(late)
        b._wake.clear()
        await b._flush(batch)
        assert first.future.result() == "first done"
        assert list(b._queue) == [late]  # rejected newcomer was re-queued...
        assert b._wake.is_set()        # ...and the run loop was woken

    asyncio.run(scenario())


def test_tp_decode_matches_single_device():
    """Tensor-parallel serving: an LmEngine over a mesh with tensor=4
    decodes EXACTLY what the single-device engine decodes (greedy, f32) —
    GSPMD inserts the TP collectives into the same jitted decode. This is
    the serve-models-bigger-than-one-chip path (SURVEY.md §2 TP row)."""
    import jax

    from symbiont_tpu.parallel import build_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = LmConfig(enabled=True, arch="llama", hidden_size=32, num_layers=2,
                   num_heads=4, intermediate_size=64, max_positions=128,
                   dtype="float32", prompt_buckets=[8, 16],
                   new_token_buckets=[16], stream_chunk=4, temperature=0.0)
    single = LmEngine(cfg)
    mesh = build_mesh([1, 4], devices=jax.devices()[:4])
    tp = LmEngine(cfg, mesh=mesh)
    # both engines seed identical synthetic params (jax.random.key(0))
    prompts = ["hello tensor parallel", "b"]
    base = single.generate_batch(prompts, [12, 12], temperature=0.0)
    sharded = tp.generate_batch(prompts, [12, 12], temperature=0.0)
    assert sharded == base
    # params actually live sharded across the tensor axis
    spec = str(tp.params["layers"][0]["q"]["kernel"].sharding.spec)
    assert "tensor" in spec, spec
    # the chunked/session path (prefill + decode_chunk) too
    sess = tp.start_session(["hello tensor parallel"], [12], temperature=0.0)
    out = {}
    for _ in range(16):
        out.update(sess.step())
        if sess.done():
            break
    assert out[0] == base[0]


def test_tp_decode_indivisible_heads_modes():
    """tensor_parallel="on" makes non-divisibility a hard error; the default
    "auto" falls back to single-device decode so a mesh whose tensor axis
    exists for the encoder/training can't brick LM boot (ADVICE r4); "off"
    never shards even when the geometry divides."""
    import jax

    from symbiont_tpu.parallel import build_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    base = dict(enabled=True, arch="llama", hidden_size=30, num_layers=1,
                num_heads=3, intermediate_size=64, max_positions=64,
                dtype="float32", prompt_buckets=[8], new_token_buckets=[8])
    mesh = build_mesh([1, 4], devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="divisible"):
        LmEngine(LmConfig(tensor_parallel="on", **base), mesh=mesh)
    # auto: boots single-device instead of raising
    lm = LmEngine(LmConfig(**base), mesh=mesh)
    assert lm.mesh is None
    assert lm.generate_batch(["hi"], [8], temperature=0.0)
    # off: divisible geometry, still unsharded
    divis = dict(base, hidden_size=32, num_heads=4)
    off = LmEngine(LmConfig(tensor_parallel="off", **divis), mesh=mesh)
    assert off.mesh is None
    with pytest.raises(ValueError, match="auto|on|off"):
        LmConfig(tensor_parallel="bogus", **base)
