"""Native symbus broker + TCP client: same semantics as the in-proc bus,
exercised against the real C++ broker over a real socket."""

import asyncio
import shutil
import socket
import os
import subprocess
import time
from pathlib import Path

import pytest

from tests.conftest import NATIVE_MAKE_TARGET, native_bin

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def broker():
    subprocess.run(["make", "-C", str(REPO / "native"), NATIVE_MAKE_TARGET],
                   check=True,
                   capture_output=True)
    port = _free_port()
    proc = subprocess.Popen(
        [native_bin("symbus_broker"), "--port", str(port),
         "--host", "127.0.0.1"],
        stderr=subprocess.PIPE)
    # wait for listen
    for _ in range(100):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.2):
                break
        except OSError:
            time.sleep(0.05)
    else:
        proc.kill()
        raise RuntimeError("broker did not start")
    yield port
    proc.terminate()
    proc.wait(timeout=5)


def _connect(port):
    from symbiont_tpu.bus.tcp import TcpBus

    async def go():
        bus = TcpBus("127.0.0.1", port)
        await bus.connect()
        return bus

    return go


def test_pub_sub_over_tcp(broker):
    async def main():
        a = await _connect(broker)()
        b = await _connect(broker)()
        sub = await b.subscribe("greet.*")
        await asyncio.sleep(0.05)  # let SUB land before PUB
        await a.publish("greet.world", "привет".encode(),
                        headers={"X-Trace-Id": "t1"})
        msg = await sub.next(2)
        assert msg is not None
        assert msg.subject == "greet.world"
        assert msg.data.decode() == "привет"
        assert msg.headers["X-Trace-Id"] == "t1"
        await a.close()
        await b.close()

    asyncio.run(main())


def test_queue_group_sharding_over_tcp(broker):
    async def main():
        pub = await _connect(broker)()
        w1 = await _connect(broker)()
        w2 = await _connect(broker)()
        s1 = await w1.subscribe("jobs", queue="workers")
        s2 = await w2.subscribe("jobs", queue="workers")
        await asyncio.sleep(0.05)
        for i in range(10):
            await pub.publish("jobs", str(i).encode())
        got1 = got2 = 0
        deadline = time.time() + 3
        while got1 + got2 < 10 and time.time() < deadline:
            m1 = await s1.next(0.05)
            m2 = await s2.next(0.05)
            got1 += m1 is not None
            got2 += m2 is not None
        assert got1 + got2 == 10
        assert got1 > 0 and got2 > 0  # actually shared
        for bus in (pub, w1, w2):
            await bus.close()

    asyncio.run(main())


def test_request_reply_over_tcp(broker):
    async def main():
        server = await _connect(broker)()
        client = await _connect(broker)()
        sub = await server.subscribe("svc.echo")

        async def responder():
            msg = await sub.next(3)
            await server.publish(msg.reply, b"pong:" + msg.data)

        await asyncio.sleep(0.05)
        task = asyncio.create_task(responder())
        reply = await client.request("svc.echo", b"ping", timeout=3)
        assert reply.data == b"pong:ping"
        await task
        with pytest.raises(TimeoutError):
            await client.request("svc.nobody", b"x", timeout=0.2)
        await server.close()
        await client.close()

    asyncio.run(main())


def test_large_payload_over_tcp(broker):
    """Embeddings cross the wire as JSON (SURVEY.md §1-L3 note) — a whole
    document's vectors can be megabytes."""

    async def main():
        a = await _connect(broker)()
        b = await _connect(broker)()
        sub = await b.subscribe("big")
        await asyncio.sleep(0.05)
        payload = b"x" * (4 * 1024 * 1024)
        await a.publish("big", payload)
        msg = await sub.next(5)
        assert msg is not None and len(msg.data) == len(payload)
        await a.close()
        await b.close()

    asyncio.run(main())


def test_unsubscribe_stops_delivery(broker):
    async def main():
        a = await _connect(broker)()
        b = await _connect(broker)()
        sub = await b.subscribe("u.x")
        await asyncio.sleep(0.05)
        await a.publish("u.x", b"1")
        assert (await sub.next(2)).data == b"1"
        sub.close()
        await asyncio.sleep(0.1)
        await a.publish("u.x", b"2")
        assert await sub.next(0.3) is None
        await a.close()
        await b.close()

    asyncio.run(main())


def test_full_stack_over_native_broker(broker, tmp_path):
    """The entire service stack runs against the C++ broker instead of the
    in-proc bus — multi-transport parity for the pipeline."""
    from tests.test_e2e_pipeline import _fake_fetcher, _http
    from symbiont_tpu.config import (ApiConfig, EngineConfig, GraphStoreConfig,
                                     SymbiontConfig, TextGeneratorConfig,
                                     VectorStoreConfig)
    from symbiont_tpu.runner import SymbiontStack

    cfg = SymbiontConfig(
        engine=EngineConfig(embedding_dim=32, length_buckets=[16, 32],
                            batch_buckets=[2, 8], max_batch=8, dtype="float32",
                            data_parallel=False, flush_deadline_ms=2.0),
        vector_store=VectorStoreConfig(dim=32, data_dir=str(tmp_path / "vs"),
                                       shard_capacity=64),
        graph_store=GraphStoreConfig(data_dir=str(tmp_path / "gs")),
        text_generator=TextGeneratorConfig(
            markov_state_path=str(tmp_path / "markov.json")),
        api=ApiConfig(host="127.0.0.1", port=0, sse_keepalive_s=0.5),
    )
    cfg.bus.url = f"symbus://127.0.0.1:{broker}"

    async def scenario():
        stack = SymbiontStack(cfg, fetcher=_fake_fetcher)
        await stack.start()
        loop = asyncio.get_running_loop()
        try:
            port = stack.api.port
            status, _ = await loop.run_in_executor(
                None, lambda: _http("POST", port, "/api/submit-url",
                                    {"url": "http://example.com/doc1"}))
            assert status == 200
            deadline = time.time() + 20
            while stack.vector_store.count() < 3 and time.time() < deadline:
                await asyncio.sleep(0.1)
            assert stack.vector_store.count() >= 3
            status, body = await loop.run_in_executor(
                None, lambda: _http("POST", port, "/api/search/semantic",
                                    {"query_text": "embeddings", "top_k": 2}))
            assert status == 200 and len(body["results"]) == 2
        finally:
            await stack.stop()

    asyncio.run(scenario())
