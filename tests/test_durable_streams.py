"""Durable streams: the JetStream-equivalent layer over the native broker.

The reference runs core NATS — at-most-once, a crashed consumer silently
loses in-flight work (SURVEY.md §1-L3 notes, §5.3). These tests prove the
four durability properties the design claims:

1. capture + push delivery with seq headers, ack advances the floor;
2. an unacked delivery redelivers after ack_wait (consumer crash story);
3. replicas in one group share the stream; a message delivered to a dead
   replica fails over to the live one;
4. messages and acks survive a broker restart (--data-dir log replay).
"""

import asyncio
import json
import shutil
import socket
import os
import subprocess
import time
from pathlib import Path

import pytest

from tests.conftest import NATIVE_MAKE_TARGET, native_bin

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_broker(port: int, data_dir=None):
    subprocess.run(["make", "-C", str(REPO / "native"), NATIVE_MAKE_TARGET],
                   check=True,
                   capture_output=True)
    args = [native_bin("symbus_broker"),
            "--port", str(port), "--host", "127.0.0.1"]
    if data_dir:
        args += ["--data-dir", str(data_dir)]
    proc = subprocess.Popen(args, stderr=subprocess.PIPE)
    for _ in range(100):
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=0.2):
                return proc
        except OSError:
            time.sleep(0.05)
    proc.kill()
    raise RuntimeError("broker did not start")


def _stop(proc):
    proc.terminate()
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()


async def _bus(port):
    from symbiont_tpu.bus.tcp import TcpBus

    bus = TcpBus("127.0.0.1", port)
    await bus.connect()
    return bus


def test_capture_deliver_ack_and_redelivery():
    port = _free_port()
    proc = _start_broker(port)
    try:
        async def scenario():
            bus = await _bus(port)
            await bus.add_stream("ingest", ["data.raw_text.>"],
                                 ack_wait_s=1.0, max_deliver=3)

            # capture happens with NO subscriber connected (at-least-once)
            await bus.publish("data.raw_text.discovered", b'{"n": 1}')
            await bus.publish("data.raw_text.discovered", b'{"n": 2}')
            await bus.publish("data.other", b"not captured")

            sub = await bus.durable_subscribe("ingest", "workers")
            m1 = await sub.next(5.0)
            m2 = await sub.next(5.0)
            assert m1 is not None and m2 is not None
            assert {json.loads(m1.data)["n"], json.loads(m2.data)["n"]} == {1, 2}
            assert m1.headers["X-Symbus-Stream"] == "ingest"
            assert m1.headers["X-Symbus-Subject"] == "data.raw_text.discovered"
            assert m1.headers["X-Symbus-Deliveries"] == "1"
            seqs = {int(m1.headers["X-Symbus-Seq"]),
                    int(m2.headers["X-Symbus-Seq"])}
            assert seqs == {1, 2}

            # ack only the first; the second must redeliver after ack_wait=1s
            await bus.ack(m1)
            first_unacked = m2  # stays unacked
            r = await sub.next(5.0)
            assert r is not None, "no redelivery of unacked message"
            assert int(r.headers["X-Symbus-Seq"]) == int(
                first_unacked.headers["X-Symbus-Seq"])
            assert int(r.headers["X-Symbus-Deliveries"]) == 2
            await bus.ack(r)

            stats = await bus.stream_stats()
            g = stats["ingest"]["groups"]["workers"]
            assert g["ack_floor"] == 2 and g["inflight"] == 0
            await bus.close()

        asyncio.run(scenario())
    finally:
        _stop(proc)


def test_max_deliver_dead_letters():
    port = _free_port()
    proc = _start_broker(port)
    try:
        async def scenario():
            bus = await _bus(port)
            await bus.add_stream("dl", ["dl.subject"], ack_wait_s=0.3,
                                 max_deliver=2)
            await bus.publish("dl.subject", b"poison")
            sub = await bus.durable_subscribe("dl", "g")
            # never ack: 2 deliveries then dead-letter
            d1 = await sub.next(5.0)
            d2 = await sub.next(5.0)
            assert d1 is not None and d2 is not None
            assert int(d2.headers["X-Symbus-Deliveries"]) == 2
            assert await sub.next(1.0) is None, "delivered past max_deliver"
            stats = await bus.stream_stats()
            assert stats["dl"]["groups"]["g"]["dead_lettered"] == 1
            await bus.close()

        asyncio.run(scenario())
    finally:
        _stop(proc)


def test_replica_failover():
    port = _free_port()
    proc = _start_broker(port)
    try:
        async def scenario():
            bus_pub = await _bus(port)
            await bus_pub.add_stream("fo", ["fo.docs"], ack_wait_s=0.5,
                                     max_deliver=5)
            # two replicas join the same group on separate connections
            replica_a = await _bus(port)
            replica_b = await _bus(port)
            sub_a = await replica_a.durable_subscribe("fo", "g")
            sub_b = await replica_b.durable_subscribe("fo", "g")

            for i in range(6):
                await bus_pub.publish("fo.docs", json.dumps({"i": i}).encode())

            got_a, got_b = [], []
            for _ in range(40):
                ma = await sub_a.next(0.1)
                if ma is not None:
                    got_a.append(ma)
                    await replica_a.ack(ma)
                mb = await sub_b.next(0.1)
                if mb is not None:
                    got_b.append(mb)
                    await replica_b.ack(mb)
                if len(got_a) + len(got_b) >= 6:
                    break
            assert len(got_a) + len(got_b) == 6
            # round-robin: both replicas participated
            assert got_a and got_b

            # replica A dies holding an unacked delivery → B gets it
            await bus_pub.publish("fo.docs", b'{"i": 99}')
            await asyncio.sleep(0.15)  # let the pump deliver somewhere
            await replica_a.close()    # A crashes without acking
            m = await sub_b.next(5.0)
            assert m is not None and json.loads(m.data)["i"] == 99
            await replica_b.ack(m)
            await replica_b.close()
            await bus_pub.close()

        asyncio.run(scenario())
    finally:
        _stop(proc)


def test_persistence_across_broker_restart(tmp_path):
    port = _free_port()
    data_dir = tmp_path / "streams"
    data_dir.mkdir()
    proc = _start_broker(port, data_dir)
    try:
        async def phase1():
            bus = await _bus(port)
            await bus.add_stream("p", ["p.docs"], ack_wait_s=5.0)
            for i in range(3):
                await bus.publish("p.docs", json.dumps({"i": i}).encode())
            sub = await bus.durable_subscribe("p", "g")
            m = await sub.next(5.0)
            assert json.loads(m.data)["i"] == 0
            await bus.ack(m)
            await asyncio.sleep(0.2)  # let the ack land in the log
            await bus.close()

        asyncio.run(phase1())
    finally:
        _stop(proc)

    assert (data_dir / "p.symlog").stat().st_size > 0
    proc = _start_broker(port, data_dir)
    try:
        async def phase2():
            bus = await _bus(port)
            # no add_stream: the stream was replayed from the log
            sub = await bus.durable_subscribe("p", "g")
            got = []
            for _ in range(2):
                m = await sub.next(5.0)
                assert m is not None, f"only {got} after restart"
                got.append(json.loads(m.data)["i"])
                await bus.ack(m)
            assert sorted(got) == [1, 2]  # 0 was acked before the restart
            assert await sub.next(0.5) is None
            await bus.close()

        asyncio.run(phase2())
    finally:
        _stop(proc)


def test_dead_letter_persisted_and_log_compacted(tmp_path):
    """A poison message that exhausted max_deliver must stay dead after a
    broker restart (its auto-ack is persisted), and restart must compact the
    log to live state instead of replaying the full append history."""
    port = _free_port()
    data_dir = tmp_path / "streams"
    data_dir.mkdir()
    proc = _start_broker(port, data_dir)
    try:
        async def phase1():
            bus = await _bus(port)
            await bus.add_stream("dlp", ["dlp.docs"], ack_wait_s=0.3,
                                 max_deliver=2)
            await bus.publish("dlp.docs", b"poison")
            # bulk of acked traffic: should vanish from the log at restart
            for i in range(50):
                await bus.publish("dlp.docs", json.dumps({"i": i}).encode())
            sub = await bus.durable_subscribe("dlp", "g")
            poisoned = 0
            for _ in range(60):
                m = await sub.next(2.0)
                if m is None:
                    break
                if m.data == b"poison":
                    poisoned += 1  # never ack the poison
                else:
                    await bus.ack(m)
            assert poisoned == 2  # delivered max_deliver times, then dropped
            stats = await bus.stream_stats()
            assert stats["dlp"]["groups"]["g"]["dead_lettered"] == 1
            await asyncio.sleep(0.5)  # let the dead-letter ack hit the log
            await bus.close()

        asyncio.run(phase1())
    finally:
        _stop(proc)

    size_before = (data_dir / "dlp.symlog").stat().st_size
    proc = _start_broker(port, data_dir)
    try:
        async def phase2():
            bus = await _bus(port)
            sub = await bus.durable_subscribe("dlp", "g")
            # nothing comes back: not the poison (dead-letter ack persisted),
            # not the acked bulk
            assert await sub.next(1.5) is None
            # last_seq survived the all-acked snapshot: a fresh publish must
            # number ABOVE the group floor and be delivered, not swallowed
            await bus.publish("dlp.docs", b"fresh")
            m = await sub.next(5.0)
            assert m is not None and m.data == b"fresh"
            assert int(m.headers["X-Symbus-Seq"]) > 50
            await bus.ack(m)
            await asyncio.sleep(0.3)  # let the ack land in the log
            await bus.close()

        asyncio.run(phase2())
    finally:
        _stop(proc)
    # replay rewrote the log as a snapshot: 51 msgs + ~52 acks of history
    # collapse to meta + group floor records
    size_after = (data_dir / "dlp.symlog").stat().st_size
    assert size_after < size_before / 4, (size_before, size_after)

    # second restart: now the snapshot itself is the replay source. With every
    # message acked it holds no REC_MSG, so last_seq can only come from the
    # meta record — if it replayed as 0, this publish would be numbered below
    # the group floor and silently swallowed.
    proc = _start_broker(port, data_dir)
    try:
        async def phase3():
            bus = await _bus(port)
            sub = await bus.durable_subscribe("dlp", "g")
            await bus.publish("dlp.docs", b"after-second-restart")
            m = await sub.next(5.0)
            assert m is not None and m.data == b"after-second-restart"
            assert int(m.headers["X-Symbus-Seq"]) > 51
            await bus.ack(m)
            await bus.close()

        asyncio.run(phase3())
    finally:
        _stop(proc)
