"""Markov chain parity tests (reference:
services/text_generator_service/src/main.rs:13-109 — untested there)."""

import random

from symbiont_tpu.models.markov import MarkovModel


def test_untrained_returns_sentinel():
    # reference: main.rs:84-89
    assert MarkovModel().generate(10) == "Model not trained."


def test_single_word_trains_starter_only():
    m = MarkovModel()
    m.train("hello")
    assert m.starters == ["hello"]
    assert m.chain == {}
    assert m.generate(5) == "Model not trained."  # chain empty → sentinel


def test_empty_text_noop():
    m = MarkovModel()
    m.train("")
    assert m.starters == [] and m.chain == {}


def test_generate_walks_chain():
    m = MarkovModel()
    m.train("a b c d")
    out = m.generate(10, rng=random.Random(0))
    words = out.split()
    assert words[0] == "a"
    # every adjacent pair must be a trained transition
    for cur, nxt in zip(words, words[1:]):
        assert nxt in m.chain[cur]
    assert len(words) <= 10


def test_max_length_bounds_output():
    m = MarkovModel()
    m.train("x y x y x y")
    for n in (1, 2, 5):
        assert len(m.generate(n, rng=random.Random(1)).split()) <= n


def test_duplicates_weight_transitions():
    # transitions are a multiset (reference pushes every occurrence,
    # main.rs:51-58): "a b" twice + "a c" once → b twice as likely
    m = MarkovModel()
    m.train("a b")
    m.train("a b")
    m.train("a c")
    assert sorted(m.chain["a"]) == ["b", "b", "c"]
    assert m.starters == ["a"]  # deduped


def test_incremental_training_and_state_round_trip():
    m = MarkovModel()
    m.train("раз два три")  # reference corpus is Russian; unicode must work
    m.train("четыре пять")
    state = m.to_state()
    m2 = MarkovModel.from_state(state)
    assert m2.chain == m.chain and m2.starters == m.starters
    assert m2.generate(4, rng=random.Random(2))


def test_markov_state_persists_across_service_restart(tmp_path):
    """SURVEY.md §5.4: learned chain survives a restart (the reference loses
    all learned state at every boot, main.rs:169-173)."""
    import asyncio

    from symbiont_tpu import subjects
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.schema import RawTextMessage, to_json_bytes
    from symbiont_tpu.services.text_generator import TextGeneratorService
    from symbiont_tpu.utils.ids import current_timestamp_ms, generate_uuid

    path = str(tmp_path / "markov.json")

    async def scenario():
        bus = InprocBus()
        svc = TextGeneratorService(bus, state_path=path)
        await svc.start()
        await bus.publish(subjects.DATA_RAW_TEXT_DISCOVERED, to_json_bytes(
            RawTextMessage(id=generate_uuid(), source_url="u",
                           raw_text="alpha beta gamma delta",
                           timestamp_ms=current_timestamp_ms())))
        for _ in range(100):
            if "alpha" in svc.markov.chain:
                break
            await asyncio.sleep(0.02)
        assert "alpha" in svc.markov.chain
        await svc.stop()
        await asyncio.sleep(0.05)  # let the save land

        svc2 = TextGeneratorService(bus, state_path=path)
        assert "alpha" in svc2.markov.chain  # restored, not rebuilt
        await bus.close()

    asyncio.run(scenario())


def test_markov_corrupt_state_starts_fresh(tmp_path):
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.services.text_generator import TextGeneratorService

    path = tmp_path / "markov.json"
    path.write_text("{not json")
    svc = TextGeneratorService(InprocBus(), state_path=str(path))
    assert svc.markov.chain  # seed corpus trained; no crash


def test_failed_state_save_is_retried(tmp_path, monkeypatch):
    """A failed persist (disk full, permissions) must leave the chain dirty
    so the next save window retries, instead of silently treating the
    learned delta as saved."""
    import asyncio

    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.services.text_generator import TextGeneratorService

    async def scenario():
        svc = TextGeneratorService(InprocBus(),
                                   state_path=str(tmp_path / "m.json"))
        svc.markov.train("один два три")
        svc._dirty = True

        calls = {"n": 0}
        real_write = svc._write_state

        def failing_write(snapshot):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("disk full")
            real_write(snapshot)

        monkeypatch.setattr(svc, "_write_state", failing_write)
        await svc._maybe_save(force=True)
        assert svc._dirty  # failure re-marked dirty
        await svc._maybe_save(force=True)
        assert not svc._dirty
        assert (tmp_path / "m.json").exists()

    asyncio.run(scenario())
