"""Markov chain parity tests (reference:
services/text_generator_service/src/main.rs:13-109 — untested there)."""

import random

from symbiont_tpu.models.markov import MarkovModel


def test_untrained_returns_sentinel():
    # reference: main.rs:84-89
    assert MarkovModel().generate(10) == "Model not trained."


def test_single_word_trains_starter_only():
    m = MarkovModel()
    m.train("hello")
    assert m.starters == ["hello"]
    assert m.chain == {}
    assert m.generate(5) == "Model not trained."  # chain empty → sentinel


def test_empty_text_noop():
    m = MarkovModel()
    m.train("")
    assert m.starters == [] and m.chain == {}


def test_generate_walks_chain():
    m = MarkovModel()
    m.train("a b c d")
    out = m.generate(10, rng=random.Random(0))
    words = out.split()
    assert words[0] == "a"
    # every adjacent pair must be a trained transition
    for cur, nxt in zip(words, words[1:]):
        assert nxt in m.chain[cur]
    assert len(words) <= 10


def test_max_length_bounds_output():
    m = MarkovModel()
    m.train("x y x y x y")
    for n in (1, 2, 5):
        assert len(m.generate(n, rng=random.Random(1)).split()) <= n


def test_duplicates_weight_transitions():
    # transitions are a multiset (reference pushes every occurrence,
    # main.rs:51-58): "a b" twice + "a c" once → b twice as likely
    m = MarkovModel()
    m.train("a b")
    m.train("a b")
    m.train("a c")
    assert sorted(m.chain["a"]) == ["b", "b", "c"]
    assert m.starters == ["a"]  # deduped


def test_incremental_training_and_state_round_trip():
    m = MarkovModel()
    m.train("раз два три")  # reference corpus is Russian; unicode must work
    m.train("четыре пять")
    state = m.to_state()
    m2 = MarkovModel.from_state(state)
    assert m2.chain == m.chain and m2.starters == m.starters
    assert m2.generate(4, rng=random.Random(2))
