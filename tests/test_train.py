"""Training-step tests: loss decreases, sharded DP+TP step runs on the
8-device mesh, checkpoint round-trip."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbiont_tpu.models import bert as bert_mod
from symbiont_tpu.models import gpt as gpt_mod
from symbiont_tpu.train.trainer import (
    contrastive_train_step,
    lm_train_step,
    make_embedder_train_state,
    make_lm_train_state,
    shard_lm_train_state,
)


def _bert_cfg():
    return bert_mod.BertConfig(vocab_size=64, hidden_size=16, num_layers=1,
                               num_heads=2, intermediate_size=32,
                               max_position_embeddings=32, dtype="float32")


def _gpt_cfg(**kw):
    base = dict(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                intermediate_size=64, max_position_embeddings=32,
                dtype="float32")
    base.update(kw)
    return gpt_mod.GPTConfig(**base)


def test_contrastive_loss_decreases():
    cfg = _bert_cfg()
    params = bert_mod.init_params(jax.random.key(0), cfg)
    state, tx = make_embedder_train_state(params, learning_rate=1e-3)
    rng = np.random.default_rng(0)
    B, S = 8, 10
    batch = {
        "q_ids": jnp.asarray(rng.integers(3, 64, (B, S)), jnp.int32),
        "q_mask": jnp.ones((B, S), jnp.int32),
        "p_ids": jnp.asarray(rng.integers(3, 64, (B, S)), jnp.int32),
        "p_mask": jnp.ones((B, S), jnp.int32),
    }
    losses = []
    for _ in range(8):
        state, m = contrastive_train_step(state, batch, cfg, tx)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 8


def test_lm_loss_decreases_and_masks_padding():
    cfg = _gpt_cfg()
    params = gpt_mod.init_params(jax.random.key(1), cfg)
    state, tx = make_lm_train_state(params, learning_rate=1e-3)
    rng = np.random.default_rng(1)
    ids = rng.integers(1, 64, (4, 16)).astype(np.int32)
    mask = np.ones((4, 16), np.int32)
    mask[2, 10:] = 0
    batch = {"ids": jnp.asarray(ids), "mask": jnp.asarray(mask)}
    losses = []
    for _ in range(8):
        state, m = lm_train_step(state, batch, cfg, tx)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_sharded_lm_train_step_dp_tp():
    """Full train step with TP-sharded params + DP-sharded batch on a 4x2
    mesh — the multi-chip training path dryrun_multichip exercises."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbiont_tpu.parallel import build_mesh

    mesh = build_mesh([4, 2])
    cfg = _gpt_cfg(num_heads=4)
    params = gpt_mod.init_params(jax.random.key(2), cfg)
    state, tx = make_lm_train_state(params)
    state = shard_lm_train_state(mesh, state, arch="gpt2")
    rng = np.random.default_rng(2)
    batch = {
        "ids": jax.device_put(
            jnp.asarray(rng.integers(1, 64, (8, 16)), jnp.int32),
            NamedSharding(mesh, P("data"))),
        "mask": jax.device_put(jnp.ones((8, 16), jnp.int32),
                               NamedSharding(mesh, P("data"))),
    }
    state2, m = lm_train_step(state, batch, cfg, tx)
    assert np.isfinite(float(m["loss"]))
    # params stay TP-sharded after the update
    qk = state2.params["layers"][0]["q"]["kernel"]
    assert "tensor" in str(qk.sharding.spec)
    # and a second step composes
    state3, m2 = lm_train_step(state2, batch, cfg, tx)
    assert np.isfinite(float(m2["loss"]))


def test_checkpoint_round_trip(tmp_path):
    from symbiont_tpu.train import checkpoint as ckpt

    cfg = _bert_cfg()
    params = bert_mod.init_params(jax.random.key(3), cfg)
    ckpt.save_params(tmp_path / "ck", params, meta={"model": "test"})
    assert ckpt.exists(tmp_path / "ck")
    restored, meta = ckpt.load_params(tmp_path / "ck")
    assert meta["model"] == "test"
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
                 params, restored)


def test_train_state_checkpoint_resume(tmp_path):
    """Save after step 1, restore into a fresh template, continue — the
    resumed run reproduces the uninterrupted run exactly (params + optimizer
    moments + step all round-trip)."""
    import jax
    import jax.numpy as jnp

    from symbiont_tpu.models import gpt as gpt_mod
    from symbiont_tpu.train import checkpoint as ckpt
    from symbiont_tpu.train.trainer import lm_train_step, make_lm_train_state

    cfg = gpt_mod.GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                            num_heads=2, intermediate_size=32,
                            max_position_embeddings=16, dtype="float32")
    rng = np.random.default_rng(0)
    batch = {"ids": jnp.asarray(rng.integers(1, 32, (2, 8)), jnp.int32),
             "mask": jnp.ones((2, 8), jnp.int32)}

    # uninterrupted: two steps
    s_ref, tx = make_lm_train_state(gpt_mod.init_params(jax.random.key(0), cfg))
    s_ref, _ = lm_train_step(s_ref, batch, cfg, tx)
    s_ref, m_ref = lm_train_step(s_ref, batch, cfg, tx)

    # interrupted: one step, save, restore into a fresh template, one step
    s1, tx1 = make_lm_train_state(gpt_mod.init_params(jax.random.key(0), cfg))
    s1, _ = lm_train_step(s1, batch, cfg, tx1)
    assert not ckpt.train_state_exists(tmp_path / "ts")
    ckpt.save_train_state(tmp_path / "ts", s1, meta={"arch": "gpt2"})
    assert ckpt.train_state_exists(tmp_path / "ts")

    template, tx2 = make_lm_train_state(gpt_mod.init_params(jax.random.key(7), cfg))
    restored, meta = ckpt.load_train_state(tmp_path / "ts", template)
    assert meta == {"arch": "gpt2"}
    assert int(restored.step) == 1
    s2, m2 = lm_train_step(restored, batch, cfg, tx2)

    assert int(s2.step) == int(s_ref.step) == 2
    np.testing.assert_allclose(float(m2["loss"]), float(m_ref["loss"]),
                               rtol=1e-6, atol=1e-7)
    for a, b in zip(jax.tree.leaves(s_ref.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=1e-6,
                                   atol=1e-7)


def test_train_state_structure_mismatch_raises(tmp_path):
    import jax

    from symbiont_tpu.models import gpt as gpt_mod
    from symbiont_tpu.train import checkpoint as ckpt
    from symbiont_tpu.train.trainer import make_lm_train_state

    cfg1 = gpt_mod.GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                             num_heads=2, intermediate_size=32,
                             max_position_embeddings=16, dtype="float32")
    cfg2 = gpt_mod.GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                             num_heads=2, intermediate_size=32,
                             max_position_embeddings=16, dtype="float32")
    s1, _ = make_lm_train_state(gpt_mod.init_params(jax.random.key(0), cfg1))
    ckpt.save_train_state(tmp_path / "ts", s1)
    # different layer count → leaf-count mismatch
    s2, _ = make_lm_train_state(gpt_mod.init_params(jax.random.key(0), cfg2))
    with pytest.raises(ValueError, match="mismatch"):
        ckpt.load_train_state(tmp_path / "ts", s2)
    # same tree structure, different geometry → per-leaf shape mismatch
    import dataclasses

    cfg3 = dataclasses.replace(cfg1, hidden_size=32, intermediate_size=64)
    s3, _ = make_lm_train_state(gpt_mod.init_params(jax.random.key(0), cfg3))
    with pytest.raises(ValueError, match="shape"):
        ckpt.load_train_state(tmp_path / "ts", s3)
