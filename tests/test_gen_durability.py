"""Durable generation sessions (resilience/genlog.py + engine resume +
supervisor rescue + exactly-once SSE edge — docs/RESILIENCE.md "Durable
generation sessions").

Fast tier: journal WAL semantics, orphan scan/rotation, SSE hub dedupe and
Last-Event-ID replay, service-level adoption (stub engine), the resume-
races-cancel and resume-under-pressure paths, and the supervisor's rescue
hooks. Slow tier (jax): token-identical greedy resume across dense/paged ×
kv_quant, and PRNG-state restore for sampled streams."""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from symbiont_tpu import subjects
from symbiont_tpu.resilience.genlog import GenJournal
from symbiont_tpu.utils.telemetry import metrics


def _rec(task_id, tokens, seq=0, **kw):
    base = dict(task_id=task_id, tenant="t", stream=True,
                prompt_ids=[1, 2, 3], max_new=16, temperature=0.0,
                top_k=0, tokens=list(tokens),
                chunk_start=max(0, len(tokens) - 4), text="", seq=seq,
                key=None, key_splits=0)
    base.update(kw)
    return base


# ------------------------------------------------------------ journal WAL


def test_journal_round_trip(tmp_path):
    path = tmp_path / "gen.genlog"
    j = GenJournal(path)
    j.append(_rec("a", [5, 6]))
    j.append(_rec("a", [5, 6, 7, 8], seq=1))
    j.append(_rec("b", [9]))
    assert len(j) == 2
    tails = j.live_tails()
    assert tails["a"]["tokens"] == [5, 6, 7, 8]  # last record wins
    assert tails["a"]["seq"] == 1
    j.mark_done("a")
    assert "a" not in j.live_tails()
    j.mark_done("a")  # idempotent no-op
    j.mark_done("never-seen")

    # survivor reload: a new incarnation of the same role sees b, not a
    j2 = GenJournal(path)
    assert set(j2.live_tails()) == {"b"}


def test_journal_append_without_task_id_is_dropped(tmp_path):
    j = GenJournal(tmp_path / "g.genlog")
    j.append({"tokens": [1]})
    assert len(j) == 0


def test_journal_max_tasks_eviction(tmp_path):
    j = GenJournal(tmp_path / "g.genlog", max_tasks=3)
    for i in range(5):
        j.append(_rec(f"t{i}", [i]))
    assert len(j) == 3
    assert set(j.live_tails()) == {"t2", "t3", "t4"}  # oldest evicted


def test_journal_compaction_bounds_bytes(tmp_path):
    path = tmp_path / "g.genlog"
    j = GenJournal(path, max_bytes=2000)
    for i in range(100):
        j.append(_rec("hot", list(range(i % 8))))
    # the file was rewritten to live tails only — far below 100 appends
    assert path.stat().st_size < 2000
    assert set(j.live_tails()) == {"hot"}
    # the compacted file still resumes correctly
    assert set(GenJournal.take_orphans(path)) == {"hot"}


def test_journal_corrupt_line_skipped(tmp_path):
    path = tmp_path / "g.genlog"
    j = GenJournal(path)
    j.append(_rec("ok", [1, 2]))
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"task_id": "torn", "tok')  # the SIGKILL's torn append
    tails = GenJournal.take_orphans(path)
    assert set(tails) == {"ok"}


def test_journal_degrades_on_write_error(tmp_path):
    # point the journal at a path whose parent is a FILE → open() raises
    blocker = tmp_path / "blocker"
    blocker.write_text("x")
    j = GenJournal(blocker / "g.genlog")
    before = metrics.get("gen.journal_errors", 0)
    j.append(_rec("a", [1]))
    assert j.enabled is False  # store down ⇒ durability off, decode lives
    assert metrics.get("gen.journal_errors", 0) == before + 1
    j.append(_rec("b", [2]))  # silently a no-op now
    assert len(j) == 0


def test_take_orphans_rotates_aside(tmp_path):
    path = tmp_path / "g.genlog"
    j = GenJournal(path)
    j.append(_rec("live", [1]))
    j.append(_rec("finished", [2]))
    j.mark_done("finished")
    tails = GenJournal.take_orphans(path)
    assert set(tails) == {"live"}
    assert not path.exists()  # rotated aside: restarted role starts fresh
    assert path.with_suffix(".genlog.orphaned").exists()
    # a second scan (double verdict) finds nothing — no double-republish
    assert GenJournal.take_orphans(path) == {}


# ------------------------------------------------------ exactly-once edge


def _chunk(task_id, seq, delta="x", done=False):
    return json.dumps({"original_task_id": task_id, "text_delta": delta,
                       "seq": seq, "done": done, "timestamp_ms": 0})


def _drain(client):
    out = []
    while not client.q.empty():
        out.append(client.q.get_nowait())
    return out


def test_sse_hub_dedupes_replayed_seq():
    from symbiont_tpu.services.api import _SseHub

    hub = _SseHub(capacity=32)
    c = hub.register("t1")
    hub.broadcast(_chunk("t1", 0))
    hub.broadcast(_chunk("t1", 1))
    hub.broadcast(_chunk("t1", 1))  # the resume's replayed chunk
    hub.broadcast(_chunk("t1", 0))  # stale requeue race
    hub.broadcast(_chunk("t1", 2, done=True))
    items = _drain(c)
    assert [json.loads(p)["seq"] for p, _, _ in items] == [0, 1, 2]
    # wire ids stamp task:seq so browsers echo Last-Event-ID back
    assert [i for _, i, _ in items] == ["t1:0", "t1:1", "t1:2"]
    assert [d for _, _, d in items] == [False, False, True]


def test_sse_hub_last_event_id_replay():
    from symbiont_tpu.services.api import _SseHub

    hub = _SseHub(capacity=32)
    for s in range(4):
        hub.broadcast(_chunk("t2", s, delta=f"d{s}"))
    # reconnect claiming it saw up to seq 1 → history replays 2, 3
    c = hub.register("t2", last_event_id="t2:1")
    replayed = _drain(c)
    assert [json.loads(p)["seq"] for p, _, _ in replayed] == [2, 3]
    # garbage Last-Event-ID replays nothing (and does not raise)
    c2 = hub.register("t2", last_event_id="not-an-id")
    assert _drain(c2) == []
    # a filtered client never replays another task's history
    c3 = hub.register("other", last_event_id="t2:1")
    assert _drain(c3) == []


def test_sse_hub_lagged_client_gets_terminal_close():
    from symbiont_tpu.services.api import _LAGGED, _SseHub

    hub = _SseHub(capacity=2)
    c = hub.register("t3")
    before = metrics.get("api.sse_lagged_closed", 0)
    for s in range(5):  # capacity 2 → overflow on the 3rd
        hub.broadcast(_chunk("t3", s))
    items = _drain(c)
    assert items[-1] is _LAGGED  # woken with the lag verdict, not silence
    assert c.lagged is True
    # no further events are queued behind the verdict
    hub.broadcast(_chunk("t3", 9))
    assert c.q.empty()
    del before  # counter moves in _serve_sse, not the hub


def test_sse_hub_unfiltered_client_and_non_json_payloads():
    from symbiont_tpu.services.api import _SseHub

    hub = _SseHub(capacity=8)
    c = hub.register(None)  # reference-style receive-everything client
    hub.broadcast(_chunk("tX", 0))
    hub.broadcast("not json at all")
    items = _drain(c)
    assert len(items) == 2
    assert items[1] == ("not json at all", None, False)


# ------------------------------------------------- service-level adoption


def _stub_resume(chunks, calls=None, raise_exc=None):
    """A duck-typed LmEngine.generate_stream: records the resume record it
    was handed, then yields the replay delta + continuation chunks."""

    def fn(prompt, max_new_tokens, temperature=None, top_k=None,
           tenant=None, task_id=None, stream=True, resume=None):
        if calls is not None:
            calls.append(dict(prompt=prompt, max_new=max_new_tokens,
                              tenant=tenant, task_id=task_id,
                              stream=stream, resume=resume))
        if raise_exc is not None:
            raise raise_exc
        yield from chunks

    return fn


def _resume_body(task_id, attempt=0, **kw):
    rec = _rec(task_id, [5, 6, 7, 8], seq=2, text="already-", stream=True,
               **kw)
    return json.dumps({"task_id": task_id, "record": rec,
                       "attempt": attempt}).encode()


def test_handle_resume_adopts_and_publishes():
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.schema import GeneratedTextMessage, from_json
    from symbiont_tpu.services.text_generator import TextGeneratorService

    async def run():
        bus = InprocBus()
        calls = []
        svc = TextGeneratorService(
            bus, lm_resume=_stub_resume(["emitted ", "rest"], calls))
        await svc.start()
        final = await bus.subscribe(subjects.EVENTS_TEXT_GENERATED)
        partial = await bus.subscribe(
            subjects.EVENTS_TEXT_GENERATED_PARTIAL)
        await bus.publish(subjects.TASKS_GENERATION_RESUME,
                          _resume_body("orph-1"))
        msg = await asyncio.wait_for(final.__aiter__().__anext__(),
                                     timeout=10)
        out = from_json(GeneratedTextMessage, msg.data)
        # journaled prefix text + replayed chunk + continuation
        assert out.original_task_id == "orph-1"
        assert out.generated_text == "already-emitted rest"
        assert calls[0]["resume"]["tokens"] == [5, 6, 7, 8]
        assert calls[0]["task_id"] == "orph-1"
        # seq numbering CONTINUED from the record (2, 3, then done at 4)
        seqs = []
        for _ in range(3):
            m = await asyncio.wait_for(partial.__aiter__().__anext__(),
                                       timeout=10)
            seqs.append(json.loads(m.data)["seq"])
        assert seqs == [2, 3, 4]
        await svc.stop()

    asyncio.run(run())


def test_handle_resume_non_streaming_skips_partials():
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.services.text_generator import TextGeneratorService

    async def run():
        bus = InprocBus()
        svc = TextGeneratorService(bus, lm_resume=_stub_resume(["batchy"]))
        await svc.start()
        final = await bus.subscribe(subjects.EVENTS_TEXT_GENERATED)
        partial = await bus.subscribe(
            subjects.EVENTS_TEXT_GENERATED_PARTIAL)
        body = json.dumps({"task_id": "orph-b", "attempt": 0,
                           "record": _rec("orph-b", [5], stream=False)})
        await bus.publish(subjects.TASKS_GENERATION_RESUME, body.encode())
        await asyncio.wait_for(final.__aiter__().__anext__(), timeout=10)
        # a batch-row adoption publishes NO stream chunks (nobody follows)
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(partial.__aiter__().__anext__(),
                                   timeout=0.1)
        await svc.stop()

    asyncio.run(run())


def test_handle_resume_drops_cancelled_tombstone():
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.services.text_generator import TextGeneratorService

    async def run():
        bus = InprocBus()
        calls = []
        svc = TextGeneratorService(bus,
                                   lm_resume=_stub_resume(["x"], calls))
        await svc.start()
        # the reader hung up before the worker died: its cancel fanned out
        # and tombstoned here — the resume must be dropped, not decoded
        await bus.publish(subjects.TASKS_GENERATION_CANCEL,
                          json.dumps({"task_id": "orph-c"}).encode())
        await asyncio.sleep(0.05)
        before = metrics.get("gen.resume_dropped_cancelled", 0)
        await bus.publish(subjects.TASKS_GENERATION_RESUME,
                          _resume_body("orph-c"))
        await asyncio.sleep(0.1)
        assert calls == []
        assert metrics.get("gen.resume_dropped_cancelled", 0) == before + 1
        await svc.stop()

    asyncio.run(run())


def test_handle_resume_requeues_on_pool_pressure():
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.kv.pool import PoolExhausted
    from symbiont_tpu.services.text_generator import TextGeneratorService

    async def run():
        bus = InprocBus()
        svc = TextGeneratorService(
            bus, lm_resume=_stub_resume([], raise_exc=PoolExhausted("full")),
            resume_max_attempts=3, resume_backoff_s=0.01)
        await svc.start()
        sub = await bus.subscribe(subjects.TASKS_GENERATION_RESUME)
        before_rq = metrics.get("gen.resume_requeued", 0)
        before_ab = metrics.get("gen.resume_abandoned", 0)
        await bus.publish(subjects.TASKS_GENERATION_RESUME,
                          _resume_body("orph-p", attempt=0))
        # attempt 0 → requeued as attempt 1 (our own subscribe sees the
        # republish alongside the service's queue-group delivery)
        seen = []
        async for m in sub:
            body = json.loads(m.data)
            seen.append(body["attempt"])
            if body["attempt"] >= 2:
                break
        assert seen[:3] == [0, 1, 2]
        await asyncio.sleep(0.1)  # attempt 2 is the last (max_attempts 3)
        assert metrics.get("gen.resume_requeued", 0) == before_rq + 2
        assert metrics.get("gen.resume_abandoned", 0) == before_ab + 1
        await svc.stop()

    asyncio.run(run())


def test_handle_resume_without_engine_abandons():
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.services.text_generator import TextGeneratorService

    async def run():
        bus = InprocBus()
        svc = TextGeneratorService(bus)  # markov-only replica: cannot adopt
        await svc.start()
        before = metrics.get("gen.resume_abandoned", 0)
        await bus.publish(subjects.TASKS_GENERATION_RESUME,
                          _resume_body("orph-n"))
        await asyncio.sleep(0.05)
        assert metrics.get("gen.resume_abandoned", 0) == before + 1
        await svc.stop()

    asyncio.run(run())


def test_completed_guard_covers_retry_path():
    """PR-9 tombstone gap regression: a cancel lands while a COMPLETED
    task's delivery is being retried — the tombstone must not poison the
    rerun into a cancel (the task already published its text here)."""
    from symbiont_tpu.bus.inproc import InprocBus
    from symbiont_tpu.schema import (
        GeneratedTextMessage,
        GenerateTextTask,
        from_json,
        to_json_bytes,
    )
    from symbiont_tpu.services.text_generator import TextGeneratorService

    async def run():
        bus = InprocBus()
        svc = TextGeneratorService(bus, train_on_ingest=False)
        await svc.start()
        sub = await bus.subscribe(subjects.EVENTS_TEXT_GENERATED)
        task = GenerateTextTask(task_id="done-1", prompt="", max_length=5)
        await bus.publish(subjects.TASKS_GENERATION_TEXT,
                          to_json_bytes(task))
        await asyncio.wait_for(sub.__aiter__().__anext__(), timeout=10)
        assert "done-1" in svc._completed_recent
        # stale cancel arrives post-completion: must NOT tombstone...
        await bus.publish(subjects.TASKS_GENERATION_CANCEL,
                          json.dumps({"task_id": "done-1"}).encode())
        await asyncio.sleep(0.05)
        assert "done-1" not in svc._cancelled_early
        # ...and even a tombstone that slipped in (cancel raced the
        # completion bookkeeping) must not cancel the retry of a task
        # recorded as completed
        svc._cancelled_early["done-1"] = time.monotonic()
        await bus.publish(subjects.TASKS_GENERATION_TEXT,
                          to_json_bytes(task))
        msg = await asyncio.wait_for(sub.__aiter__().__anext__(),
                                     timeout=10)
        out = from_json(GeneratedTextMessage, msg.data)
        assert out.original_task_id == "done-1"
        assert isinstance(out.generated_text, str)  # rerun, not a cancel
        await svc.stop()

    asyncio.run(run())


# -------------------------------------------------- supervisor-side rescue


class _StubBus:
    def __init__(self):
        self.published = []

    async def publish(self, subject, data, headers=None):
        self.published.append((subject, data))


def _gen_worker(tmp_path, role="genw"):
    from symbiont_tpu.resilience.procsup import _Worker, WorkerSpec

    return _Worker(WorkerSpec(
        role=role, argv=["true"],
        env={"SYMBIONT_GEN_JOURNAL_ENABLED": "1",
             "SYMBIONT_GEN_JOURNAL_DIR": str(tmp_path),
             "SYMBIONT_RUNNER_ROLE": role}))


def test_rescue_gen_orphans_republishes_tails(tmp_path):
    from symbiont_tpu.resilience.procsup import ProcessSupervisor

    async def run():
        path = tmp_path / "genw.genlog"
        j = GenJournal(path)
        j.append(_rec("o1", [1, 2]))
        j.append(_rec("o2", [3]))
        j.append(_rec("fin", [4]))
        j.mark_done("fin")
        sup = ProcessSupervisor()
        sup._bus = _StubBus()
        before = metrics.get("gen.orphans", 0)
        await sup._rescue_gen_orphans(_gen_worker(tmp_path))
        assert metrics.get("gen.orphans", 0) == before + 2
        assert not path.exists()  # rotated: restart starts a fresh journal
        bodies = {json.loads(d)["task_id"]: json.loads(d)
                  for s, d in sup._bus.published
                  if s == subjects.TASKS_GENERATION_RESUME}
        assert set(bodies) == {"o1", "o2"}
        assert bodies["o1"]["attempt"] == 0
        assert bodies["o1"]["record"]["tokens"] == [1, 2]
        # double verdict on the same death republishes nothing
        await sup._rescue_gen_orphans(_gen_worker(tmp_path))
        assert len(sup._bus.published) == 2

    asyncio.run(run())


def test_rescue_skips_without_journal_env_or_bus(tmp_path):
    from symbiont_tpu.resilience.procsup import (
        ProcessSupervisor,
        WorkerSpec,
        _Worker,
    )

    async def run():
        path = tmp_path / "genw.genlog"
        GenJournal(path).append(_rec("o1", [1]))
        sup = ProcessSupervisor()
        # no journal env → no scan even with a bus
        sup._bus = _StubBus()
        await sup._rescue_gen_orphans(
            _Worker(WorkerSpec(role="plain", argv=["true"])))
        assert sup._bus.published == []
        assert path.exists()
        # journal env but bus down → scan DEFERRED, file left in place so a
        # later verdict (or the restarted role's reload) still covers it
        sup._bus = None
        await sup._rescue_gen_orphans(_gen_worker(tmp_path))
        assert path.exists()

    asyncio.run(run())


def test_drain_deadline_sigkill_rescues_orphans(tmp_path):
    """Drain-deadline resume: a worker that ignores the drain past the
    deadline is SIGKILLed — and its journal tails republish, because a
    mid-stream generation is past its bus ack (durable redelivery alone
    cannot recover it)."""
    from symbiont_tpu.resilience.procsup import ProcessSupervisor

    async def run():
        path = tmp_path / "genw.genlog"
        GenJournal(path).append(_rec("drainee", [7, 8]))
        sup = ProcessSupervisor(drain_deadline_s=1.0)
        sup._bus = _StubBus()
        w = _gen_worker(tmp_path)
        sup.workers[w.spec.role] = w
        w.proc = subprocess.Popen(
            [sys.executable, "-c",
             "import signal, time; "
             "signal.signal(signal.SIGTERM, signal.SIG_IGN); "
             "time.sleep(60)"],
            start_new_session=True)
        try:
            await sup._drain_worker(w, deadline_s=1.5)
        finally:
            if w.proc.poll() is None:
                os.kill(w.proc.pid, signal.SIGKILL)
                w.proc.wait(timeout=5)
        assert w.drain_clean is False  # the deadline SIGKILL fired
        resumed = [json.loads(d)["task_id"]
                   for s, d in sup._bus.published
                   if s == subjects.TASKS_GENERATION_RESUME]
        assert resumed == ["drainee"]
        assert not path.exists()

    asyncio.run(run())


# --------------------------------------------------- engine resume (slow)

TINY = dict(enabled=True, arch="llama", hidden_size=32, num_layers=2,
            num_heads=4, intermediate_size=64, max_positions=256,
            dtype="float32", prompt_buckets=[8, 16, 64],
            new_token_buckets=[8, 16], temperature=0.0, stream_chunk=4)


def _run_with_kill(eng, journal, prompt, max_new, kill_after, **kw):
    """Stream until `kill_after` chunks arrived, then abandon the
    generator mid-flight — the SIGKILL stand-in (nothing downstream of
    the journal append runs for the killed chunk's successor)."""
    eng.journal = journal
    got = []
    gen = eng.generate_stream(prompt, max_new, task_id="kill-me", **kw)
    for delta in gen:
        got.append(delta)
        if len(got) >= kill_after:
            gen.close()
            break
    return "".join(got)


@pytest.mark.slow
@pytest.mark.parametrize("layout,kv_quant", [("dense", "none"),
                                             ("paged", "none"),
                                             ("paged", "int8")])
def test_resume_token_identical_greedy(tmp_path, layout, kv_quant):
    """The durability gate: kill a greedy stream at a chunk boundary,
    adopt its journal tail on a FRESH engine, and the reassembled text is
    byte-identical to an unkilled run (position-invariant re-prefill)."""
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    cfg = LmConfig(**dict(TINY, kv_layout=layout, kv_quant=kv_quant,
                          kv_page_tokens=8))
    prompt = "the quick brown fox jumps"
    ref = "".join(LmEngine(cfg).generate_stream(prompt, 16))

    eng = LmEngine(cfg)
    journal = GenJournal(tmp_path / "a.genlog")
    _run_with_kill(eng, journal, prompt, 16, kill_after=2)
    rec = journal.live_tails()["kill-me"]
    assert rec["key"] is None  # greedy journals no PRNG state

    adopter = LmEngine(cfg)  # fresh process: cold KV, no radix state
    deltas = list(adopter.generate_stream(
        "", rec["max_new"], temperature=rec["temperature"],
        top_k=rec["top_k"], task_id="kill-me", stream=True, resume=rec))
    assert rec["text"] + "".join(deltas) == ref


@pytest.mark.slow
def test_resume_restores_prng_for_sampled(tmp_path):
    """Sampled streams resume token-identically on a DIFFERENT-seed
    adopting engine: the journal carries the stream's base key + splits
    consumed, and resume re-derives the live key host-side."""
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    cfg = LmConfig(**dict(TINY, temperature=0.8, seed=7))
    prompt = "sampling is stochastic"
    ref = "".join(LmEngine(cfg).generate_stream(prompt, 16,
                                                temperature=0.8, top_k=8))

    eng = LmEngine(cfg)
    journal = GenJournal(tmp_path / "s.genlog")
    _run_with_kill(eng, journal, prompt, 16, kill_after=2,
                   temperature=0.8, top_k=8)
    rec = journal.live_tails()["kill-me"]
    assert rec["key"] is not None and rec["key_splits"] >= 1

    other = LmEngine(LmConfig(**dict(TINY, temperature=0.8, seed=99)))
    deltas = list(other.generate_stream(
        "", rec["max_new"], temperature=rec["temperature"],
        top_k=rec["top_k"], task_id="kill-me", stream=True, resume=rec))
    assert rec["text"] + "".join(deltas) == ref


@pytest.mark.slow
def test_batch_session_rows_journal_and_cancel_marks_done(tmp_path):
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(**dict(TINY, session_min_rows=2,
                                   gen_max_batch=2)))
    journal = eng.journal = GenJournal(tmp_path / "b.genlog")
    s = eng.start_session(["hello", "world"], [8, 8], temperature=0.0,
                          task_ids=["row-a", "row-b"])
    s.step()
    tails = journal.live_tails()
    assert set(tails) == {"row-a", "row-b"}
    assert tails["row-a"]["stream"] is False
    assert tails["row-a"]["prompt_ids"]  # post-trim prompt captured
    assert len(tails["row-a"]["tokens"]) >= 1
    # cancel is terminal ENGINE-side (no service publish will follow):
    # the row's journal tail must never resurrect as a resume
    assert s.cancel_tag(s.rows[1].tag)
    assert set(journal.live_tails()) == {"row-a"}
    # drive to completion; the finished row STAYS journaled — only the
    # service's post-publish mark_done retires it (crash-in-publish-window
    # coverage)
    while not s.done():
        s.step()
    s._drain_all()
    assert "row-a" in journal.live_tails()
