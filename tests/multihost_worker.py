"""Worker process for the 2-process multi-host bring-up test.

Run by tests/test_multihost.py, one subprocess per "host": each process owns
4 virtual CPU devices (xla_force_host_platform_device_count=4) and joins a
2-process jax.distributed cluster through the SAME production path a real
multi-host TPU deployment uses — `init_distributed` → `build_mesh` →
sharded train step (docs/DEPLOYMENT.md Topology 3). Nothing here is
test-double'd: the coordinator service, cross-process device discovery, and
the XLA collectives the train step's gradient psum lowers to are all real.

Two scenarios, selected by SYMBIONT_MULTIHOST_MODE:
- "dp" (default): pure data-parallel mesh over all 8 devices; the gradient
  psum over 'data' crosses the process boundary.
- "tp": a [4, 2] mesh whose 'tensor' axis PAIRS one device from each
  process, so every tensor-parallel collective in the train step (activation
  psums, gradient reductions) physically crosses hosts — the megatron-style
  sharding proven over DCN, not just ICI.

Protocol (parsed by the parent test): prints one line
    MULTIHOST ok global=<N> local=<n> procs=<P> loss=<float> sum=<int>
and exits 0; any assertion failure exits nonzero with a traceback.
"""

import os
import sys


def main() -> None:
    # must win over the sandbox's axon sitecustomize before backend init
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbiont_tpu.models import gpt as gpt_mod
    from symbiont_tpu.parallel.mesh import build_mesh, init_distributed
    from symbiont_tpu.train.trainer import TrainState, _adamw, lm_train_step

    # coordinator/process topology arrives via SYMBIONT_COORDINATOR /
    # SYMBIONT_NUM_PROCESSES / SYMBIONT_PROCESS_ID (set by the parent test),
    # exactly as a launcher would set them on a non-TPU cluster.
    n_global = init_distributed()
    n_local = len(jax.local_devices())
    procs = jax.process_count()
    assert procs == 2, f"expected 2 processes, got {procs}"
    assert n_global == 2 * n_local, (n_global, n_local)

    mode = os.environ.get("SYMBIONT_MULTIHOST_MODE", "dp")
    if mode == "tp":
        # tensor axis spans the processes: pair device i of process 0 with
        # device i of process 1, so TP collectives ride the cross-host link
        devs = np.asarray(jax.devices()).reshape(procs, n_local).T
        mesh = jax.sharding.Mesh(devs, ("data", "tensor"))
        assert all({d.process_index for d in row} == {0, 1}
                   for row in devs), "each tensor pair must span processes"
    else:
        # one DP mesh over the WHOLE cluster: both processes' devices
        mesh = build_mesh([n_global, 1])
    assert {d.process_index for d in mesh.devices.flat} == {0, 1}, \
        "mesh must span both processes"

    cfg = gpt_mod.GPTConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        intermediate_size=128, max_position_embeddings=32,
        arch="llama", num_kv_heads=2, dtype="float32",
        tie_word_embeddings=True)

    if mode == "tp":
        _run_tp(mesh, cfg, n_global, n_local, procs)
        return

    tx = _adamw(1e-3)
    rep = NamedSharding(mesh, P())

    # init params + opt state INSIDE jit with replicated out_shardings: under
    # multi-process JAX, eager ops on non-addressable arrays are invalid, so
    # all global state is born on-device from a shared seed.
    @jax.jit
    def init_state(key):
        params = gpt_mod.init_params(key, cfg)
        return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))

    state = jax.jit(init_state, out_shardings=rep)(jax.random.key(0))

    # global batch sharded over 'data': each process materializes only ITS
    # addressable shards; rows therefore physically live on different hosts.
    # _make_batch also proves a collective crosses the process boundary (a
    # global sum of the sharded array must equal the host-known total).
    batch, total = _make_batch(mesh, cfg, B=n_global)

    # ONE cross-process DP train step (gradient psum over 'data' spans hosts)
    state, metrics = lm_train_step(state, batch, cfg, tx)
    loss = float(metrics["loss"].addressable_shards[0].data)
    assert np.isfinite(loss), loss
    assert int(state.step.addressable_shards[0].data) == 1

    print(f"MULTIHOST ok global={n_global} local={n_local} procs={procs} "
          f"loss={loss:.6f} sum={total}", flush=True)


def _make_batch(mesh, cfg, B: int, S: int = 16):
    """Shared batch protocol for both scenarios: same seed → same global
    view on every process; rows sharded over 'data' so each process
    materializes only its addressable shards. Returns (batch, global_sum)
    where global_sum proves a collective crossed the process boundary."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(7)
    full_ids = rng.integers(1, cfg.vocab_size, (B, S)).astype(np.int32)
    bs = NamedSharding(mesh, P("data"))
    ids = jax.make_array_from_callback((B, S), bs, lambda idx: full_ids[idx])
    mask = jax.make_array_from_callback(
        (B, S), bs, lambda idx: np.ones((B, S), np.int32)[idx])
    total = int(jax.jit(jnp.sum)(ids).addressable_shards[0].data)
    assert total == int(full_ids.sum()), (total, int(full_ids.sum()))
    return {"ids": ids, "mask": mask}, total


def _run_tp(mesh, cfg, n_global: int, n_local: int, procs: int) -> None:
    """Cross-host tensor parallelism: params megatron-sharded over the
    'tensor' axis (which pairs devices ACROSS the two processes), then one
    FULL train step — forward, backward, AdamW update — so every TP
    collective and the sharded optimizer update cross the process
    boundary."""
    from functools import partial

    import jax
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbiont_tpu.models import gpt as gpt_mod
    from symbiont_tpu.parallel.sharding import gpt_param_sharding
    from symbiont_tpu.train.trainer import _adamw, lm_loss

    template = jax.eval_shape(lambda k: gpt_mod.init_params(k, cfg),
                              jax.random.key(0))
    spec = gpt_param_sharding(mesh, template, arch="llama")
    out_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), spec,
                          is_leaf=lambda x: isinstance(x, P))
    params = jax.jit(lambda k: gpt_mod.init_params(k, cfg),
                     out_shardings=out_sh)(jax.random.key(0))
    # q kernels really live split over the cross-host tensor axis
    assert "tensor" in str(params["layers"][0]["q"]["kernel"].sharding.spec)

    batch, total = _make_batch(mesh, cfg, B=mesh.shape["data"])

    @partial(jax.jit, static_argnums=(2,))
    def train_step(params, batch, cfg):
        # optimizer state created under jit so XLA propagates the TP
        # shardings into mu/nu — the sharded-update path is exercised too
        tx = _adamw(1e-3)
        opt_state = tx.init(params)
        loss, grads = jax.value_and_grad(lm_loss)(params, batch, cfg)
        updates, opt_state = tx.update(grads, opt_state, params)
        return loss, optax.apply_updates(params, updates)

    loss, new_params = train_step(params, batch, cfg)
    loss = float(loss.addressable_shards[0].data)
    assert np.isfinite(loss), loss
    # updated params kept the TP sharding through the optimizer update
    assert "tensor" in str(
        new_params["layers"][0]["q"]["kernel"].sharding.spec)

    print(f"MULTIHOST ok global={n_global} local={n_local} procs={procs} "
          f"loss={loss:.6f} sum={total}", flush=True)


if __name__ == "__main__":
    try:
        main()
    except Exception:
        import traceback

        traceback.print_exc()
        sys.exit(1)
