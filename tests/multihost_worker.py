"""Worker process for the 2-process multi-host bring-up test.

Run by tests/test_multihost.py, one subprocess per "host": each process owns
4 virtual CPU devices (xla_force_host_platform_device_count=4) and joins a
2-process jax.distributed cluster through the SAME production path a real
multi-host TPU deployment uses — `init_distributed` → `build_mesh` →
sharded train step (docs/DEPLOYMENT.md Topology 3). Nothing here is
test-double'd: the coordinator service, cross-process device discovery, and
the XLA collectives the train step's gradient psum lowers to are all real.

Two scenarios, selected by SYMBIONT_MULTIHOST_MODE:
- "dp" (default): pure data-parallel mesh over all 8 devices; the gradient
  psum over 'data' crosses the process boundary.
- "tp": a [4, 2] mesh whose 'tensor' axis PAIRS one device from each
  process, so every tensor-parallel collective in the train step (activation
  psums, gradient reductions) physically crosses hosts — the megatron-style
  sharding proven over DCN, not just ICI.

Protocol (parsed by the parent test): prints one line
    MULTIHOST ok global=<N> local=<n> procs=<P> loss=<float> sum=<int>
and exits 0; any assertion failure exits nonzero with a traceback.
"""

import os
import sys


def main() -> None:
    # must win over the sandbox's axon sitecustomize before backend init
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbiont_tpu.models import gpt as gpt_mod
    from symbiont_tpu.parallel.mesh import build_mesh, init_distributed
    from symbiont_tpu.train.trainer import TrainState, _adamw, lm_train_step

    # coordinator/process topology arrives via SYMBIONT_COORDINATOR /
    # SYMBIONT_NUM_PROCESSES / SYMBIONT_PROCESS_ID (set by the parent test),
    # exactly as a launcher would set them on a non-TPU cluster.
    n_global = init_distributed()
    n_local = len(jax.local_devices())
    procs = jax.process_count()
    assert procs == 2, f"expected 2 processes, got {procs}"
    assert n_global == 2 * n_local, (n_global, n_local)

    mode = os.environ.get("SYMBIONT_MULTIHOST_MODE", "dp")
    if mode == "tp":
        # tensor axis spans the processes: pair device i of process 0 with
        # device i of process 1, so TP collectives ride the cross-host link
        devs = np.asarray(jax.devices()).reshape(procs, n_local).T
        mesh = jax.sharding.Mesh(devs, ("data", "tensor"))
        assert all({d.process_index for d in row} == {0, 1}
                   for row in devs), "each tensor pair must span processes"
    else:
        # one DP mesh over the WHOLE cluster: both processes' devices
        mesh = build_mesh([n_global, 1])
    assert {d.process_index for d in mesh.devices.flat} == {0, 1}, \
        "mesh must span both processes"

    cfg = gpt_mod.GPTConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        intermediate_size=128, max_position_embeddings=32,
        arch="llama", num_kv_heads=2, dtype="float32",
        tie_word_embeddings=True)

    if mode == "tp":
        _run_tp(mesh, cfg, n_global, n_local, procs)
        return

    tx = _adamw(1e-3)
    rep = NamedSharding(mesh, P())

    # init params + opt state INSIDE jit with replicated out_shardings: under
    # multi-process JAX, eager ops on non-addressable arrays are invalid, so
    # all global state is born on-device from a shared seed.
    @jax.jit
    def init_state(key):
        params = gpt_mod.init_params(key, cfg)
        return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))

    state = jax.jit(init_state, out_shardings=rep)(jax.random.key(0))

    # global batch sharded over 'data': each process materializes only ITS
    # addressable shards; rows therefore physically live on different hosts.
    # _make_batch also proves a collective crosses the process boundary (a
    # global sum of the sharded array must equal the host-known total).
    batch, total = _make_batch(mesh, cfg, B=n_global)

    # ONE cross-process DP train step (gradient psum over 'data' spans hosts)
    state, metrics = lm_train_step(state, batch, cfg, tx)
    loss = float(metrics["loss"].addressable_shards[0].data)
    assert np.isfinite(loss), loss
    assert int(state.step.addressable_shards[0].data) == 1

    print(f"MULTIHOST ok global={n_global} local={n_local} procs={procs} "
          f"loss={loss:.6f} sum={total}", flush=True)


def _make_batch(mesh, cfg, B: int, S: int = 16):
    """Shared batch protocol for both scenarios: same seed → same global
    view on every process; rows sharded over 'data' so each process
    materializes only its addressable shards. Returns (batch, global_sum)
    where global_sum proves a collective crossed the process boundary."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.default_rng(7)
    full_ids = rng.integers(1, cfg.vocab_size, (B, S)).astype(np.int32)
    bs = NamedSharding(mesh, P("data"))
    ids = jax.make_array_from_callback((B, S), bs, lambda idx: full_ids[idx])
    mask = jax.make_array_from_callback(
        (B, S), bs, lambda idx: np.ones((B, S), np.int32)[idx])
    total = int(jax.jit(jnp.sum)(ids).addressable_shards[0].data)
    assert total == int(full_ids.sum()), (total, int(full_ids.sum()))
    return {"ids": ids, "mask": mask}, total


def _run_tp(mesh, cfg, n_global: int, n_local: int, procs: int) -> None:
    """Cross-host tensor parallelism through the PRODUCTION train step:
    a TrainState born TP-sharded (params megatron-split over the 'tensor'
    axis that pairs devices ACROSS the two processes, AdamW mu/nu mirroring
    the param shardings), driven through trainer.lm_train_step — so the
    exact code a real deployment runs does its forward, backward, and
    optimizer update across the host boundary."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from symbiont_tpu.models import gpt as gpt_mod
    from symbiont_tpu.parallel.sharding import gpt_param_sharding
    from symbiont_tpu.train.trainer import TrainState, _adamw, lm_train_step

    tx = _adamw(1e-3)
    rep = NamedSharding(mesh, P())
    template = jax.eval_shape(lambda k: gpt_mod.init_params(k, cfg),
                              jax.random.key(0))
    spec = gpt_param_sharding(mesh, template, arch="llama")
    param_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), spec,
                            is_leaf=lambda x: isinstance(x, P))

    # optimizer-state shardings mirror the params (adam mu/nu share the
    # param tree structure; counts and other scalars replicate)
    def opt_sharding(os_shape):
        if isinstance(os_shape, optax.ScaleByAdamState):
            return optax.ScaleByAdamState(count=rep, mu=param_sh, nu=param_sh)
        return jax.tree.map(lambda _: rep, os_shape)

    opt_shape = jax.eval_shape(tx.init, template)
    state_sh = TrainState(param_sh,
                          tuple(opt_sharding(s) for s in opt_shape), rep)

    def init_state(key):
        params = gpt_mod.init_params(key, cfg)
        return TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))

    state = jax.jit(init_state, out_shardings=state_sh)(jax.random.key(0))
    # q kernels really live split over the cross-host tensor axis
    assert "tensor" in str(
        state.params["layers"][0]["q"]["kernel"].sharding.spec)

    batch, total = _make_batch(mesh, cfg, B=mesh.shape["data"])

    # ONE production train step: every TP collective and the sharded AdamW
    # update cross the process boundary
    state, metrics = lm_train_step(state, batch, cfg, tx)
    loss = float(metrics["loss"].addressable_shards[0].data)
    assert np.isfinite(loss), loss
    gnorm = float(metrics["grad_norm"].addressable_shards[0].data)
    assert np.isfinite(gnorm) and gnorm > 0, gnorm
    assert int(state.step.addressable_shards[0].data) == 1
    # updated params kept the TP sharding through the optimizer update
    assert "tensor" in str(
        state.params["layers"][0]["q"]["kernel"].sharding.spec)

    print(f"MULTIHOST ok global={n_global} local={n_local} procs={procs} "
          f"loss={loss:.6f} sum={total}", flush=True)


if __name__ == "__main__":
    try:
        main()
    except Exception:
        import traceback

        traceback.print_exc()
        sys.exit(1)
