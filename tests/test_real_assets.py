"""Real checkpoint assets through the full load path — no network.

VERDICT.md round-1 gap #1: the converter had "never eaten a real
model.safetensors" and the engine had never loaded a model dir end-to-end.
This tier builds GENUINE assets on disk in the exact formats the HF hub ships
— a `model.safetensors` written by transformers' own serializer and a
WordPiece `tokenizer.json` actually *trained* by the `tokenizers` library —
then drives the standard production path: EngineConfig(model_dir=...) →
convert.load_bert_model + HFTokenizer → TpuEngine.embed_texts, golden-checked
against transformers' forward + the reference's masked mean pooling
(reference: services/preprocessing_service/src/embedding_generator.rs:198-207).

A second, env-gated tier (SYMBIONT_MODEL_DIR) runs the same golden check
against a real pretrained checkpoint (all-MiniLM-L6-v2 / mpnet) when one is
present — see scripts/fetch_model.py for the documented fetch path.
"""

import os
from pathlib import Path

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")
tokenizers = pytest.importorskip("tokenizers")

from symbiont_tpu.config import EngineConfig  # noqa: E402
from symbiont_tpu.engine.engine import TpuEngine  # noqa: E402
from symbiont_tpu.engine.tokenizer import HFTokenizer  # noqa: E402

CORPUS = [
    "the systolic array multiplies matrices in bfloat16",
    "high bandwidth memory feeds the matrix unit",
    "the compiler fuses elementwise operations into the matmul",
    "static shapes let the scheduler tile the loop onto hardware",
    "collectives ride the interconnect between chips in the mesh",
    "the vector store ranks documents by cosine similarity",
    "sentence embeddings are pooled from the final hidden states",
    "the scraper extracts the main content from a web page",
    "messages flow through the broker between worker services",
    "the gateway streams generated text to the browser",
    "a knowledge graph links documents sentences and tokens",
    "checkpoints let a restarted engine skip the conversion step",
    "length buckets avoid padding every sentence to the maximum",
    "the decoder caches keys and values between steps",
    "search latency is dominated by the forward pass of the query",
    "gradients are averaged across the data parallel axis",
] * 4


def _train_wordpiece(out_file: Path, vocab_size: int = 200) -> int:
    """Train a real WordPiece tokenizer (the algorithm and file format every
    BERT-family model in BASELINE.md ships) and save tokenizer.json."""
    from tokenizers import Tokenizer
    from tokenizers.models import WordPiece
    from tokenizers.normalizers import BertNormalizer
    from tokenizers.pre_tokenizers import BertPreTokenizer
    from tokenizers.processors import TemplateProcessing
    from tokenizers.trainers import WordPieceTrainer

    tok = Tokenizer(WordPiece(unk_token="[UNK]"))
    tok.normalizer = BertNormalizer(lowercase=True)
    tok.pre_tokenizer = BertPreTokenizer()
    trainer = WordPieceTrainer(
        vocab_size=vocab_size,
        special_tokens=["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"])
    tok.train_from_iterator(CORPUS, trainer)
    cls_id = tok.token_to_id("[CLS]")
    sep_id = tok.token_to_id("[SEP]")
    tok.post_processor = TemplateProcessing(
        single="[CLS] $A [SEP]",
        pair="[CLS] $A [SEP] $B:1 [SEP]:1",
        special_tokens=[("[CLS]", cls_id), ("[SEP]", sep_id)])
    tok.save(str(out_file))
    return tok.get_vocab_size()


@pytest.fixture(scope="module")
def model_dir(tmp_path_factory) -> Path:
    """A model dir indistinguishable in format from a hub snapshot:
    config.json + model.safetensors (transformers' own safe serializer) +
    a trained tokenizer.json."""
    d = tmp_path_factory.mktemp("real_model")
    vocab = _train_wordpiece(d / "tokenizer.json")
    torch.manual_seed(7)
    cfg = transformers.BertConfig(
        vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64)
    model = transformers.BertModel(cfg).eval()
    model.save_pretrained(d, safe_serialization=True)
    # AutoTokenizer (used by scripts/make_goldens.py) needs the class hint
    (d / "tokenizer_config.json").write_text(
        '{"tokenizer_class": "BertTokenizerFast", "pad_token": "[PAD]", '
        '"cls_token": "[CLS]", "sep_token": "[SEP]", "unk_token": "[UNK]", '
        '"mask_token": "[MASK]"}')
    return d


@pytest.fixture(scope="module")
def hf_ref(model_dir):
    model = transformers.BertModel.from_pretrained(model_dir).eval()
    tok = transformers.PreTrainedTokenizerFast(
        tokenizer_file=str(model_dir / "tokenizer.json"),
        pad_token="[PAD]", cls_token="[CLS]", sep_token="[SEP]",
        unk_token="[UNK]")
    return model, tok


def _hf_mean_pool(model, tok, texts):
    enc = tok(texts, padding=True, return_tensors="pt")
    with torch.no_grad():
        h = model(input_ids=enc["input_ids"],
                  attention_mask=enc["attention_mask"]).last_hidden_state
    m = enc["attention_mask"].unsqueeze(-1).float()
    return ((h * m).sum(1) / m.sum(1)).numpy()


def test_assets_are_the_real_formats(model_dir):
    assert (model_dir / "model.safetensors").exists()  # not a .bin, not .npz
    assert (model_dir / "config.json").exists()
    assert (model_dir / "tokenizer.json").exists()
    # the tokenizer is a trained subword model, not a toy word-level map
    import json

    tj = json.loads((model_dir / "tokenizer.json").read_text())
    assert tj["model"]["type"] == "WordPiece"
    assert any(k.startswith("##") for k in tj["model"]["vocab"])  # subwords


def test_engine_loads_model_dir_and_matches_hf(model_dir, hf_ref):
    """The production path: EngineConfig(model_dir) → converted safetensors
    weights + HFTokenizer → bucketed embed — golden vs transformers."""
    model, tok = hf_ref
    eng = TpuEngine(EngineConfig(model_dir=str(model_dir), dtype="float32",
                                 length_buckets=[16, 32, 64],
                                 batch_buckets=[2, 4, 8], max_batch=8,
                                 data_parallel=False))
    assert isinstance(eng.tokenizer, HFTokenizer)
    texts = ["the systolic array multiplies matrices",
             "search latency is dominated by the forward pass",
             "checkpoints skip conversion"]
    ours = eng.embed_texts(texts)
    ref = _hf_mean_pool(model, tok, texts)
    np.testing.assert_allclose(ours, ref, atol=3e-5, rtol=1e-4)


def test_tokenizer_ids_match_transformers(model_dir, hf_ref):
    _, tok = hf_ref
    ours = HFTokenizer(model_dir / "tokenizer.json")
    for text in ["high bandwidth memory feeds the matrix unit",
                 "an unseen word zyzzyva splits into subwords"]:
        ref_ids = tok(text)["input_ids"]
        assert ours.encode(text, 64) == ref_ids


def test_sharded_safetensors_roundtrip(model_dir, tmp_path):
    """The hub ships big models as sharded safetensors + index.json — the
    layout the reference special-cases (embedding_generator.rs:36-50).
    load_state_dict must reassemble it identically to the single file."""
    from symbiont_tpu.models.convert import load_state_dict

    single = load_state_dict(model_dir)
    model = transformers.BertModel.from_pretrained(model_dir).eval()
    sharded_dir = tmp_path / "sharded"
    model.save_pretrained(sharded_dir, safe_serialization=True,
                          max_shard_size="50KB")
    assert (sharded_dir / "model.safetensors.index.json").exists()
    assert not (sharded_dir / "model.safetensors").exists()
    sharded = load_state_dict(sharded_dir)
    assert set(sharded) == set(single)
    for k in single:
        np.testing.assert_array_equal(sharded[k], single[k])


def test_convert_cli_on_real_safetensors(model_dir, tmp_path, capsys):
    """`python -m symbiont_tpu.models.convert` on a hub-format dir caches a
    checkpoint the engine can boot from without reconversion."""
    from symbiont_tpu.models import convert as convert_mod
    from symbiont_tpu.train.checkpoint import load_params

    out = tmp_path / "ckpt"
    convert_mod.main([str(model_dir), "--out", str(out)])
    assert "converted OK" in capsys.readouterr().out
    _, meta = load_params(out)
    assert meta["kind"] == "bert"


def test_export_hf_bert_roundtrip_via_transformers(model_dir, hf_ref, tmp_path):
    """export_hf_bert is the inverse of convert_bert: a pytree written back
    to hub format must reload through transformers' own BertModel AND through
    our loader with bit-identical weights and golden-equal pooled outputs —
    so checkpoints trained in this framework are portable both ways."""
    from symbiont_tpu.models.convert import export_hf_bert, load_bert_model

    params, cfg = load_bert_model(model_dir)
    out = tmp_path / "exported"
    export_hf_bert(params, cfg, out,
                   tokenizer_file=model_dir / "tokenizer.json")

    # transformers reloads the exported dir (its own deserializer is the
    # judge of tensor names/shapes) and produces the same hidden states
    model, tok = hf_ref
    re_model = transformers.BertModel.from_pretrained(out).eval()
    texts = ["the systolic array multiplies matrices",
             "checkpoints skip conversion"]
    ref = _hf_mean_pool(model, tok, texts)
    got = _hf_mean_pool(re_model, tok, texts)
    np.testing.assert_allclose(got, ref, atol=1e-6)

    # and our own loader round-trips bit-identically
    params2, cfg2 = load_bert_model(out)
    assert cfg2 == cfg
    import jax

    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_export_hf_bert_preserves_position_offset(model_dir, tmp_path):
    """Advisor finding (round 2, medium): exporting an XLM-RoBERTa-family
    pytree (position_offset=2, the default mpnet-multilingual geometry) as
    model_type='bert'/pad=0 silently dropped the offset on reload. The
    exported config must invert BertConfig.from_hf."""
    import dataclasses

    from symbiont_tpu.models.convert import (export_hf_bert, load_bert_model,
                                             load_hf_config)

    params, cfg = load_bert_model(model_dir)
    cfg = dataclasses.replace(cfg, position_offset=2)  # pad_token_id 1 + 1
    out = tmp_path / "xlmr"
    export_hf_bert(params, cfg, out)
    hf_cfg = load_hf_config(out)
    assert hf_cfg["model_type"] == "xlm-roberta"
    assert hf_cfg["pad_token_id"] == 1
    _, cfg2 = load_bert_model(out)
    assert cfg2.position_offset == 2


def test_make_goldens_roundtrip(model_dir, tmp_path):
    """The offline-golden flow (scripts/make_goldens.py →
    tests/test_golden_vectors.py), proven end-to-end on the real-format
    checkpoint above — so the checked-in-golden path is known-working
    before a real snapshot ever lands (VERDICT r3 item 8 fallback)."""
    import subprocess
    import sys

    out = tmp_path / "goldens.npz"
    subprocess.run(
        [sys.executable, str(Path(__file__).parent.parent / "scripts" /
                             "make_goldens.py"), str(model_dir),
         "--out", str(out)],
        check=True, capture_output=True)
    g = np.load(out, allow_pickle=False)
    eng = TpuEngine(EngineConfig(model_dir=str(model_dir), dtype="float32",
                                 data_parallel=False))
    ours = eng.embed_texts([str(t) for t in g["texts"]])
    ref = g["embeddings"]
    cos = (ours * ref).sum(-1) / (
        np.linalg.norm(ours, axis=-1) * np.linalg.norm(ref, axis=-1))
    assert cos.min() > 0.999, cos


# --------------------------------------------------------- gated real tier

REAL_DIR = os.environ.get("SYMBIONT_MODEL_DIR")


@pytest.mark.skipif(
    not REAL_DIR, reason="SYMBIONT_MODEL_DIR not set — run scripts/fetch_model.py "
    "where egress exists, then point SYMBIONT_MODEL_DIR at the snapshot")
def test_real_pretrained_checkpoint_golden():
    """Golden embeddings vs transformers on a REAL pretrained checkpoint
    (all-MiniLM-L6-v2 / mpnet-multilingual — BASELINE.md configs #1/#3), plus
    a semantic sanity check: related sentences score higher than unrelated."""
    d = Path(REAL_DIR)
    model = transformers.AutoModel.from_pretrained(d).eval()
    tok = transformers.AutoTokenizer.from_pretrained(d)
    eng = TpuEngine(EngineConfig(model_dir=str(d), dtype="float32",
                                 data_parallel=False))
    texts = ["A cat sits on the mat.",
             "A kitten rests on a rug.",
             "The stock market fell sharply today."]
    ours = eng.embed_texts(texts)
    enc = tok(texts, padding=True, truncation=True, return_tensors="pt")
    with torch.no_grad():
        h = model(**{k: v for k, v in enc.items()
                     if k in ("input_ids", "attention_mask")}).last_hidden_state
    m = enc["attention_mask"].unsqueeze(-1).float()
    ref = ((h * m).sum(1) / m.sum(1)).numpy()
    cos = (ours * ref).sum(-1) / (
        np.linalg.norm(ours, axis=-1) * np.linalg.norm(ref, axis=-1))
    assert cos.min() > 0.999, cos
    # semantically meaningful: paraphrase pair beats the unrelated pair
    n = ours / np.linalg.norm(ours, axis=-1, keepdims=True)
    assert n[0] @ n[1] > n[0] @ n[2]
