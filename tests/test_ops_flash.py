"""Flash-attention kernel vs dense reference (pallas interpret mode on CPU —
same kernel code path that compiles on TPU)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbiont_tpu.ops.flash_attention import _dense_reference, flash_attention


def _rand_qkv(key, B, NH, NKV, Sq, Sk, D, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, NH, Sq, D), dtype)
    k = jax.random.normal(kk, (B, NKV, Sk, D), dtype)
    v = jax.random.normal(kv, (B, NKV, Sk, D), dtype)
    return q, k, v


def _pad_bias(key, B, Sk):
    lengths = jax.random.randint(key, (B,), 1, Sk + 1)
    mask = jnp.arange(Sk)[None, :] < lengths[:, None]
    return jnp.where(mask, 0.0, -1e9).astype(jnp.float32), mask


@pytest.mark.parametrize("Sq,Sk,blocks", [(64, 64, 32), (128, 128, 32),
                                          (96, 160, 32)])
def test_matches_dense_padding_mask(Sq, Sk, blocks):
    key = jax.random.key(0)
    q, k, v = _rand_qkv(key, 2, 4, 4, Sq, Sk, 64)
    bias, _ = _pad_bias(jax.random.key(1), 2, Sk)
    got = flash_attention(q, k, v, kv_bias=bias, block_q=blocks, block_k=blocks)
    want, _ = _dense_reference(q, k, v, bias, False, 1 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_matches_dense_causal():
    key = jax.random.key(2)
    q, k, v = _rand_qkv(key, 2, 4, 4, 128, 128, 64)
    got = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
    want, _ = _dense_reference(q, k, v, jnp.zeros((2, 128)), True,
                               1 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_matches_dense_gqa_causal_padded():
    key = jax.random.key(3)
    q, k, v = _rand_qkv(key, 2, 8, 2, 64, 64, 32)
    bias, _ = _pad_bias(jax.random.key(4), 2, 64)
    got = flash_attention(q, k, v, kv_bias=bias, causal=True,
                          block_q=32, block_k=32)
    want, _ = _dense_reference(q, k, v, bias, True, 1 / np.sqrt(32))
    # rows whose kv positions are all masked (pad rows) are garbage in both
    # implementations; compare only rows with at least one visible key.
    visible = np.asarray(bias[:, None, :, None] == 0) | np.zeros_like(got, bool)
    got, want = np.asarray(got), np.asarray(want)
    np.testing.assert_allclose(got[visible[:, :, : got.shape[2]]],
                               want[visible[:, :, : got.shape[2]]],
                               rtol=2e-5, atol=2e-5)


def test_odd_shapes_fall_back_to_dense():
    q, k, v = _rand_qkv(jax.random.key(5), 1, 2, 2, 7, 7, 16)
    got = flash_attention(q, k, v)
    want, _ = _dense_reference(q, k, v, jnp.zeros((1, 7)), False,
                               1 / np.sqrt(16))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_bfloat16_output_dtype():
    q, k, v = _rand_qkv(jax.random.key(6), 1, 2, 2, 64, 64, 64, jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=32, block_k=32)
    assert out.dtype == jnp.bfloat16
    want, _ = _dense_reference(q, k, v, jnp.zeros((1, 64)), False,
                               1 / np.sqrt(64))
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(want),
                               rtol=0.05, atol=0.05)


def test_gradients_match_dense():
    key = jax.random.key(7)
    q, k, v = _rand_qkv(key, 1, 2, 2, 64, 64, 32)
    bias, _ = _pad_bias(jax.random.key(8), 1, 64)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, kv_bias=bias, block_q=32,
                               block_k=32).sum()

    def loss_dense(q, k, v):
        out, _ = _dense_reference(q, k, v, bias, False, 1 / np.sqrt(32))
        return out.sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_bert_flash_equals_xla():
    from symbiont_tpu.models import bert

    cfg = bert.BertConfig(vocab_size=128, hidden_size=64, num_layers=2,
                          num_heads=4, intermediate_size=128,
                          max_position_embeddings=64, dtype="float32")
    params = bert.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (3, 64)), jnp.int32)
    lengths = [64, 10, 33]
    mask = jnp.asarray([[1] * n + [0] * (64 - n) for n in lengths], jnp.int32)

    out_xla = bert.embed_sentences(params, ids, mask, cfg)
    out_flash = bert.embed_sentences(
        params, ids, mask, dataclasses.replace(cfg, attn_impl="flash"))
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_xla),
                               rtol=2e-4, atol=2e-4)


def test_gpt_flash_prefill_equals_xla():
    from symbiont_tpu.models import gpt

    cfg = gpt.GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, num_kv_heads=2, intermediate_size=128,
                        max_position_embeddings=64, arch="llama",
                        dtype="float32")
    params = gpt.init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    B, S = 2, 32
    ids = jnp.asarray(rng.integers(0, 128, (B, S)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    kv_valid = jnp.ones((B, S), bool)

    cache = gpt.init_cache(cfg, B, S, jnp.float32)
    logits_xla, _ = gpt.forward(params, ids, cache, positions, cfg, kv_valid)
    cache = gpt.init_cache(cfg, B, S, jnp.float32)
    logits_flash, _ = gpt.forward(
        params, ids, cache, positions,
        dataclasses.replace(cfg, attn_impl="flash"), kv_valid)
    np.testing.assert_allclose(np.asarray(logits_flash),
                               np.asarray(logits_xla), rtol=2e-4, atol=2e-4)


def test_fused_backward_causal_multiblock_asymmetric():
    """The fused pallas backward (dK/dV + dQ kernels) vs the dense gradient:
    causal, multiple blocks per axis, and bq != bk so any transposed
    contraction shows up as a shape-or-value error instead of passing by
    coincidence."""
    key = jax.random.key(21)
    q, k, v = _rand_qkv(key, 2, 2, 2, 128, 128, 32)
    bias, _ = _pad_bias(jax.random.key(22), 2, 128)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, kv_bias=bias, causal=True,
                                block_q=64, block_k=32) ** 2).sum()

    def loss_dense(q, k, v):
        out, _ = _dense_reference(q, k, v, bias, True, 1 / np.sqrt(32))
        return (out ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_fused_backward_bias_gradient():
    """dbias from the fused backward (accumulated in-kernel per head, summed
    outside) matches the dense softmax-gradient column sums."""
    key = jax.random.key(23)
    q, k, v = _rand_qkv(key, 2, 2, 2, 64, 64, 32)

    def loss_flash(bias):
        return (flash_attention(q, k, v, kv_bias=bias, block_q=32,
                                block_k=32) ** 2).sum()

    def loss_dense(bias):
        out, _ = _dense_reference(q, k, v, bias, False, 1 / np.sqrt(32))
        return (out ** 2).sum()

    bias = jnp.zeros((2, 64), jnp.float32)
    g1 = jax.grad(loss_flash)(bias)
    g2 = jax.grad(loss_dense)(bias)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=2e-4, atol=2e-4)


def test_gqa_backward_matches_dense():
    """GQA (kv heads < q heads) routes to the dense-recompute backward and
    must still produce correct grouped-sum gradients."""
    key = jax.random.key(25)
    q, k, v = _rand_qkv(key, 1, 4, 2, 64, 64, 32)
    bias = jnp.zeros((1, 64), jnp.float32)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, kv_bias=bias, causal=True,
                               block_q=32, block_k=32).sum()

    def loss_dense(q, k, v):
        out, _ = _dense_reference(q, k, v, bias, True, 1 / np.sqrt(32))
        return out.sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)
