"""Benchmark CLI shim: every PERF.md table number in ONE parsed JSON line.

The harness itself lives in `symbiont_tpu/bench/` — a tier-isolated
registry (tiers.py), a repetition engine (stats.py), a per-process resource
sampler (sampler.py), a dual-ceiling roofline accountant (roofline.py), and
a typed archive schema + regression gate (archive.py); this file is the
thin CLI the driver and docs invoke:

    python bench.py                 # full run; rc != 0 on ANY tier failure
    python bench.py --quick         # primary embedding metric only (~1 min)
    python bench.py --no-e2e        # skip the full-stack tier
    python bench.py --render-doc BENCH_rNN.json > docs/PERF.md
    python bench.py --gate NEW.json BASELINE.json
    python bench.py --validate ARCHIVE.json [...]

Prints ONE JSON line to stdout (extra detail goes to stderr); the line
always carries `tier_failures`/`tier_skips`, and a thrown tier or a missing
declared primary metric exits nonzero AFTER the line is printed — the
archive carries the evidence (VERDICT r5 weak #1).

The reference publishes no numbers (BASELINE.md: "none exist"), so
vs_baseline is measured, not quoted: the same model on the same chip run the
reference's way — fixed padding to model max (514-equivalent) in serial
batches of 8 (reference: embedding_generator.rs:83-91,146) — versus this
framework's way (length-bucketed static shapes, big batches, bf16). The
ratio is the design win of SURVEY.md §5.7/§7 on identical hardware.
"""

from __future__ import annotations

import sys

# re-exports: tests and tooling import these through `bench` (the package
# modules are the single source; keep this list additions-only)
from symbiont_tpu.bench.archive import (load_archive,  # noqa: F401
                                        regression_gate, validate_file,
                                        validate_line)
from symbiont_tpu.bench.cli import main  # noqa: F401
from symbiont_tpu.bench.doc import _fmt, render_doc  # noqa: F401
from symbiont_tpu.bench.stats import med_min_max  # noqa: F401
from symbiont_tpu.bench.workload import (bert_fwd_flops,  # noqa: F401
                                         chip_peak_flops, log,
                                         make_sentences)

if __name__ == "__main__":
    sys.exit(main())
