"""Benchmark: every PERF.md table number in ONE parsed JSON line.

Prints ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
where extras carry every number docs/PERF.md quotes (MFU, search p50s,
ingest rate, rerank pairs/s, decode tok/s + TTFT, streaming first-delta) so
no doc number exists without a matching archived field (VERDICT r1 item 2).

The reference publishes no numbers (BASELINE.md: "none exist"), so
vs_baseline is measured, not quoted: the same model on the same chip run the
reference's way — fixed padding to model max (514-equivalent) in serial
batches of 8 (reference: embedding_generator.rs:83-91,146) — versus this
framework's way (length-bucketed static shapes, big batches, bf16). The ratio
is the design win of SURVEY.md §5.7/§7 on identical hardware.

MFU here = useful matmul FLOPs (real tokens, real sequence lengths — padding
does NOT count as useful work) / elapsed / chip peak bf16 FLOPs. A second
field reports hardware utilization including padding, which shows how much
of the gap is padding waste vs dispatch overhead.

Extra detail lines go to stderr; stdout carries exactly the one JSON line.
`python bench.py --quick` runs only the primary embedding metric (~1 min);
the default full run takes several minutes (it compiles several decode
executables).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def med_min_max(samples) -> tuple:
    """(median, min, max) of a sample list. The tunnel to the chip adds
    one-sided jitter of ±20% per run (docs/PERF.md) — a single sample is not
    a measurement, so every headline number reports all three (VERDICT r3
    weak #1)."""
    s = sorted(samples)
    n = len(s)
    mid = (s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2]))
    return mid, s[0], s[-1]


def make_sentences(n: int, rng) -> list:
    """Synthetic corpus with a realistic sentence-length mix (most sentences
    short, a tail of long ones — what the scraper actually produces)."""
    words = ["tensor", "processing", "unit", "accelerates", "matrix", "products",
             "the", "memory", "bandwidth", "of", "embeddings", "semantic",
             "search", "pipeline", "document", "sentences", "vector", "graph",
             "tokens", "model", "attention", "masked", "pooling", "batch"]
    out = []
    for _ in range(n):
        ln = int(np.clip(rng.lognormal(2.6, 0.7), 3, 120))
        out.append(" ".join(rng.choice(words, size=ln)))
    return out


# ------------------------------------------------------------------ MFU math

# peak dense bf16 FLOP/s per chip, keyed by substrings of jax device_kind
_PEAK_BF16 = [
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v4", 275e12),
]


def chip_peak_flops(device) -> float | None:
    kind = device.device_kind.lower()
    if device.platform not in ("tpu", "axon"):
        return None  # MFU is only meaningful against a known accelerator peak
    for sub, peak in _PEAK_BF16:
        if sub in kind:
            return peak
    return None


def bert_fwd_flops(lengths, H: int, I: int, L: int, seq_for_attn=None) -> float:
    """Matmul-only BERT forward FLOPs for a batch of sequences.

    Per token per layer: qkv+out projections 8H², MLP 4HI; attention
    (QKᵀ + AV) 4·S·H where S is the sequence length attended over. With
    seq_for_attn=None S is the sentence's own (real) length — useful-work
    FLOPs; pass the padded bucket length to count what the chip executed."""
    lengths = np.asarray(lengths, np.float64)
    s_attn = lengths if seq_for_attn is None else np.asarray(seq_for_attn,
                                                             np.float64)
    per_tok = L * (8.0 * H * H + 4.0 * H * I)
    return float((lengths * per_tok + L * 4.0 * H * lengths * s_attn).sum())


# ------------------------------------------------------------------- benches

def bench_rerank(results: dict) -> None:
    """BASELINE.md config #4: ms-marco-MiniLM-L-6 geometry cross-encoder,
    pairs/sec over a top-k-sized candidate set."""
    from symbiont_tpu.config import EngineConfig
    from symbiont_tpu.engine.engine import TpuEngine

    eng = TpuEngine(EngineConfig(
        embedding_dim=384, length_buckets=[128], batch_buckets=[64, 256],
        max_batch=256, dtype="bfloat16", data_parallel=False,
        rerank_enabled=True))
    rng = np.random.default_rng(1)
    passages = make_sentences(256, rng)
    query = "tensor processing unit matrix products"
    eng.rerank(query, passages)  # warmup: compiles the (128, 256) executable
    dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        eng.rerank(query, passages)
        dt = min(dt, time.time() - t0)
    results["rerank_pairs_per_s"] = round(256 / dt, 1)
    results["rerank_hop_ms"] = round(dt * 1000, 1)
    log(f"rerank (MiniLM-L6 CE geometry, 256 pairs, pad-128, bf16): "
        f"{256 / dt:.0f} pairs/s (256-pair hop {dt * 1000:.1f}ms)")


def bench_search_latency(results: dict) -> None:
    """BASELINE.md north-star metric #2: p50 semantic-search latency — query
    embed (MiniLM-L6 geometry) + exact cosine top-k over a 10k-row
    device-resident corpus. This is the compute path of the 2-hop
    request-reply orchestration (SURVEY.md §3.2); bus + HTTP add ~1ms."""
    import tempfile

    from symbiont_tpu.config import EngineConfig, VectorStoreConfig
    from symbiont_tpu.engine.engine import TpuEngine
    from symbiont_tpu.memory.vector_store import VectorStore

    eng = TpuEngine(EngineConfig(
        embedding_dim=384, length_buckets=[32, 64], batch_buckets=[1, 8, 512],
        max_batch=512, dtype="bfloat16", data_parallel=False))
    rng = np.random.default_rng(3)
    corpus = make_sentences(10_000, rng)
    with tempfile.TemporaryDirectory() as td:
        store = VectorStore(VectorStoreConfig(dim=384, data_dir=td,
                                              shard_capacity=16384))
        # warm run over the FULL corpus: the batch plan (and therefore the
        # grouped-concat fetch signatures) must match the timed run, or the
        # timed region pays their compiles
        eng.embed_texts(corpus)
        t_embed = float("inf")
        for _ in range(2):
            t0 = time.time()
            vecs = eng.embed_texts(corpus)
            t_embed = min(t_embed, time.time() - t0)
        t0 = time.time()
        store.upsert([(f"p{i}", vecs[i], {"sentence_text": corpus[i]})
                      for i in range(len(corpus))])
        t_upsert = time.time() - t0
        results["ingest_10k_emb_per_s"] = round(10_000 / t_embed, 1)
        results["upsert_10k_points_per_s"] = round(10_000 / t_upsert, 1)
        results["upsert_10k_s"] = round(t_upsert, 2)
        log(f"bulk ingest: 10k sentences embedded in {t_embed:.2f}s "
            f"({10_000 / t_embed:.0f} emb/s), upserted in {t_upsert:.2f}s")

        def measure(fn):
            """5 repeats of a 32-query sweep → (median, min, max) of the
            per-repeat p50s + median of the p95s (VERDICT r3: search p50s as
            median-of-5, not one sample on a ±20% link)."""
            fn(make_sentences(4, rng)[0])  # warm
            p50s, p95s = [], []
            for _ in range(5):
                lat = []
                for q in make_sentences(32, rng):
                    t0 = time.time()
                    fn(q)
                    lat.append(time.time() - t0)
                ms = sorted(1000 * x for x in lat)
                p50s.append(ms[len(ms) // 2])
                p95s.append(ms[int(len(ms) * 0.95)])
            p50, p50_min, p50_max = med_min_max(p50s)
            return p50, p50_min, p50_max, med_min_max(p95s)[0]

        def split(q):
            assert len(store.search(eng.embed_query(q), 5)) == 5

        def fused(q):
            assert len(store.search_fused(eng, q, 5)) == 5

        # warm every query-length bucket for both paths
        for ql in ["a b c", " ".join(["word"] * 40)]:
            split(ql), fused(ql)
        p50, p50_lo, p50_hi, p95 = measure(split)
        results["search_split_p50_ms"] = round(p50, 1)
        results["search_split_p50_ms_min"] = round(p50_lo, 1)
        results["search_split_p50_ms_max"] = round(p50_hi, 1)
        results["search_split_p95_ms"] = round(p95, 1)
        log(f"semantic search, split path (10k corpus, top-5): "
            f"p50 {p50:.1f}ms [{p50_lo:.1f}–{p50_hi:.1f}], p95 {p95:.1f}ms "
            f"(embed call + top-k call; median of 5 sweeps)")
        p50f, p50f_lo, p50f_hi, p95f = measure(fused)
        results["search_fused_p50_ms"] = round(p50f, 1)
        results["search_fused_p50_ms_min"] = round(p50f_lo, 1)
        results["search_fused_p50_ms_max"] = round(p50f_hi, 1)
        results["search_fused_p95_ms"] = round(p95f, 1)
        log(f"semantic search, FUSED path (10k corpus, top-5): "
            f"p50 {p50f:.1f}ms [{p50f_lo:.1f}–{p50f_hi:.1f}], p95 {p95f:.1f}ms "
            f"(one compiled embed+top-k program, one device round-trip)")


def bench_lm_decode(results: dict) -> None:
    """BASELINE.md config #5: GPT-2-small geometry (124M, vocab 50257)
    autoregressive decode — tokens/sec/chip and time-to-first-token."""
    _bench_decode_geometry("GPT-2 124M", "gpt2_124m", results, dict(
        vocab_size=50257, hidden_size=768, num_layers=12, num_heads=12,
        intermediate_size=3072, max_position_embeddings=1024, arch="gpt2"))


def bench_tinyllama_decode(results: dict) -> None:
    """BASELINE.md config #5 (second named model): TinyLlama-1.1B geometry —
    22 layers, GQA 32/4, SwiGLU, RoPE — decode on one chip, bf16."""
    _bench_decode_geometry("TinyLlama 1.1B", "tinyllama_1b", results, dict(
        vocab_size=32000, hidden_size=2048, num_layers=22, num_heads=32,
        num_kv_heads=4, intermediate_size=5632, max_position_embeddings=2048,
        arch="llama"))


def bench_stream_ceiling(results: dict) -> None:
    """Measure THIS RUN's achievable HBM stream bandwidth (reduce-sum over a
    3.2 GB bf16 array, 16 in-graph passes, best-of-3). The decode
    utilization fields divide by this, not a constant: the same kernel
    measured 581 GB/s and 715 GB/s on this chip hours apart, so a fixed
    denominator would make utilization drift meaningless across rounds."""
    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform not in ("tpu", "axon"):
        return
    big = jax.random.normal(jax.random.key(0), (24, 8192, 8192), jnp.bfloat16)

    @jax.jit
    def reduce(x):
        def body(acc, _):
            return acc + x.sum(), None
        return jax.lax.scan(body, jnp.zeros((), jnp.float32), None,
                            length=16)[0]

    np.asarray(reduce(big))
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        np.asarray(reduce(big))
        best = min(best, time.time() - t0)
    gbps = big.size * 2 / (best / 16) / 1e9
    results["hbm_stream_gbps_measured"] = round(gbps, 1)
    del big
    log(f"HBM stream ceiling (reduce-sum, 3.2 GB bf16, this run): "
        f"{gbps:.0f} GB/s (v5e paper: 819)")


def _bench_decode_geometry(label: str, key: str, results: dict,
                           cfg_kw: dict) -> None:
    """Decode tok/s at batch 8 (+ TTFT), then the batch 32/64/128 sweep —
    decode is HBM-bandwidth-bound on weight reads, so aggregate tok/s
    scales with batch until the KV-cache traffic catches up (VERDICT r3
    item 3: measure past batch 8).

    Each batch point also records ms/step and the achieved HBM
    bandwidth-utilization (weights + full-cache KV reads per step over the
    measured per-step time, against the chip's MEASURED pure-stream ceiling
    — see docs/PERF.md's decode roofline section), so a
    regression-from-roofline is visible in the archive (VERDICT r4 weak 3)."""
    import jax
    import jax.numpy as jnp

    from symbiont_tpu.models import gpt as gpt_mod

    cfg = gpt_mod.GPTConfig(dtype="bfloat16", **cfg_kw)
    # store weights AT model dtype: f32-at-rest doubled HBM residency and
    # (on the chunked serving path) re-paid a full convert every chunk
    params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(a.dtype, jnp.floating) else a,
        gpt_mod.init_params(jax.random.key(0), cfg))
    params = jax.device_put(params)
    param_bytes = sum(a.size * a.dtype.itemsize
                      for a in jax.tree.leaves(params))
    rng = np.random.default_rng(2)
    P, NEW = 64, 128
    key_ = jax.random.key(0)

    def run(B, ids, mask, max_new):
        toks, _ = gpt_mod.generate(params, ids, mask, key_, cfg,
                                   max_new_tokens=max_new, temperature=0.8,
                                   top_k=40)
        # np.asarray (device→host), NOT block_until_ready: through the
        # network-attached runtime block_until_ready can return before the
        # remote execution finishes, inflating tok/s by ~400× (observed);
        # materializing the tokens is the only honest completion barrier
        np.asarray(toks)

    for B in (8, 32, 64, 128):
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, P)), jnp.int32)
        mask = jnp.ones((B, P), jnp.int32)
        suffix = "" if B == 8 else f"_b{B}"
        run(B, ids, mask, 1)    # compile prefill + the 1-step scan
        run(B, ids, mask, NEW)  # compile the NEW-step scan
        # prefill + 1 step + dispatch/RTT, measured per batch: subtracted
        # below so ms/step (and the HBM-roofline fields derived from it)
        # reflect DECODE steps only, not the prompt forward (TTFT at B=8).
        # PAIRED samples, median of per-pair differences: each (dt1, dtN)
        # pair runs back-to-back so both walls share the link state — two
        # independently-sampled sets straddling a tunnel drift made the
        # subtraction wrong by up to a full RTT (~±0.9 ms/step at NEW=128;
        # observed as a model "exceeding" the measured bandwidth ceiling)
        dt1s, dts, diffs = [], [], []
        for _ in range(5):
            t0 = time.time()
            run(B, ids, mask, 1)
            d1 = time.time() - t0
            t0 = time.time()
            run(B, ids, mask, NEW)
            dN = time.time() - t0
            dt1s.append(d1)
            dts.append(dN)
            diffs.append(dN - d1)
        dt1 = med_min_max(dt1s)[0]
        dt = med_min_max(dts)[0]
        decode_s = max(med_min_max(diffs)[0], 0.0)
        if B == 8:
            results[f"{key}_ttft_ms"] = round(min(dt1s) * 1000, 1)
        results[f"{key}_tok_per_s{suffix}"] = round(B * NEW / dt, 1)
        if B == 8:
            results[f"{key}_tok_per_s_stream"] = round(NEW / dt, 1)
        # roofline context: bytes the chip must stream per decode step
        # (weights once — shared by all rows — plus the full padded KV
        # cache both k and v) over the measured per-step time, vs the
        # stream bandwidth THIS RUN measured (hbm_stream_gbps_measured —
        # the achievable rate drifts hour to hour on this device, so a
        # constant denominator would be meaningless)
        ms_step = decode_s / (NEW - 1) * 1000
        kv_bytes = (2 * cfg.num_layers * B * (P + NEW) * cfg.kv_heads
                    * cfg.head_dim * 2)
        gbps = ((param_bytes + kv_bytes) / (ms_step / 1000) / 1e9
                if ms_step > 0 else 0.0)
        # when the decode window is comparable to the subtracted prefill+RTT
        # term, the estimator is jitter-limited — flag it so nobody regresses
        # on noise (small models on a high-RTT link land here)
        noise_limited = decode_s < dt1
        results[f"{key}_ms_per_step{suffix}"] = round(ms_step, 2)
        results[f"{key}_hbm_gbps{suffix}"] = round(gbps, 1)
        results[f"{key}_ms_per_step_noise_limited{suffix}"] = int(
            noise_limited)
        # utilization fields are computed ONCE in main() against the final
        # observed ceiling (which this point may itself raise) — logging a
        # percentage here could contradict the archived value
        log(f"lm decode ({label} geometry, bf16, batch {B}, prompt {P}, "
            f"{NEW} new): {B * NEW / dt:.0f} tokens/s/chip "
            f"({NEW / dt:.0f} tok/s/stream, {ms_step:.2f} ms/step, "
            f"{gbps:.0f} GB/s streamed"
            + (", NOISE-LIMITED estimate" if noise_limited else "") + ")"
            + (f", TTFT {results[f'{key}_ttft_ms']:.0f}ms" if B == 8 else ""))


def bench_streaming(results: dict) -> None:
    """Token streaming (GPT-2 geometry): time to the FIRST text delta out of
    generate_stream — the user-visible latency win of chunked decode."""
    from symbiont_tpu.config import LmConfig
    from symbiont_tpu.engine.lm import LmEngine

    eng = LmEngine(LmConfig(
        enabled=True, arch="gpt2", hidden_size=768, num_layers=12,
        num_heads=12, intermediate_size=3072, max_positions=1024,
        dtype="bfloat16", prompt_buckets=[64], new_token_buckets=[128],
        stream_chunk=16, temperature=0.8))
    prompt = "the tensor processing unit " * 8

    def first_delta_and_total():
        t0 = time.time()
        first = None
        for _ in eng.generate_stream(prompt, 128):
            if first is None:
                first = time.time() - t0
        return first, time.time() - t0

    first_delta_and_total()  # warm: compiles prefill + chunk executables
    best_first, best_total = float("inf"), float("inf")
    for _ in range(3):
        first, total = first_delta_and_total()
        best_first = min(best_first, first)
        best_total = min(best_total, total)
    results["stream_first_delta_ms"] = round(best_first * 1000, 1)
    results["stream_total_128_s"] = round(best_total, 2)
    log(f"streaming (GPT-2 geom, prompt 64, 128 new, chunk 16): first text "
        f"delta {best_first * 1000:.0f}ms, full stream {best_total:.2f}s")


def bench_compute_mfu(results: dict, peak: float | None) -> None:
    """Compute-only MFU: 20 chained forwards on device-resident data (inputs
    varied per iteration so XLA cannot hoist the loop body), no host↔device
    transfers in the timed region. This is the chip-side capability a
    locally-attached deployment gets; the end-to-end MFU above additionally
    pays the tunnel's transfer wall.

    Three geometries spanning the BASELINE.md model set: MiniLM-384
    (config #1), mpnet-768 — the reference's actual default model
    (preprocessing_service/src/main.rs:305) — and e5-large-1024 (config #3,
    the largest encoder); wider matmuls fill the 128×128 MXU progressively
    better. FLOPs are derived from the engine's REAL model_cfg, not assumed
    (a shallower synthetic stand-in would otherwise inflate MFU silently)."""
    if peak is None:
        return
    _compute_mfu_geometry(results, peak, dim=384, B=1024, S=64,
                          key_suffix="")
    # B=1024 (was 512 through r4): the r5 shape sweep measured [1024,128]
    # best at this geometry (58.8-59.2% vs 55.9-57.4% at [512,128]); every
    # other lever tried measured WORSE — see the PERF.md note
    _compute_mfu_geometry(results, peak, dim=768, B=1024, S=128,
                          key_suffix="_768", N=12)
    # BASELINE.md config #3: e5-large geometry (1024-d, 24 layers) — the
    # largest encoder in the capability set; completes the model-set sweep
    _compute_mfu_geometry(results, peak, dim=1024, B=256, S=128,
                          key_suffix="_1024", N=8)


def _compute_mfu_geometry(results: dict, peak: float, dim: int, B: int,
                          S: int, key_suffix: str, N: int = 20) -> None:
    import jax
    import jax.numpy as jnp

    from symbiont_tpu.config import EngineConfig
    from symbiont_tpu.engine.engine import TpuEngine
    from symbiont_tpu.models import bert as bert_mod

    eng = TpuEngine(EngineConfig(
        embedding_dim=dim, length_buckets=[S], batch_buckets=[B],
        max_batch=B, dtype="bfloat16", data_parallel=False))
    cfg = eng.model_cfg
    H, I, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    ids = jnp.ones((B, S), jnp.int32)
    mask = jnp.ones((B, S), jnp.int32)

    @jax.jit
    def loop(params, ids, mask):
        def body(c, i):
            e = bert_mod.embed_sentences(params, (ids + i) % cfg.vocab_size,
                                         mask, cfg, pooling="mean")
            return c + e.sum(), None
        return jax.lax.scan(body, jnp.float32(0),
                            jnp.arange(N, dtype=jnp.int32))[0]

    # materialize the scalar (d2h) as the completion barrier — see run() in
    # _bench_decode_geometry for why block_until_ready alone is not enough
    # through the network-attached runtime
    np.asarray(loop(eng.params, ids, mask))
    # median-of-5 WITH min/max: these are the A/B-able primary metrics
    # (device-bound; measured spread ±1-2% vs the tunnel metrics' 2.5×),
    # so the archive must carry the evidence of that stability
    samples = []
    for _ in range(5):
        t0 = time.time()
        np.asarray(loop(eng.params, ids, mask))
        samples.append(time.time() - t0)
    dt, dt_lo, dt_hi = med_min_max(samples)  # of times; invert for rates
    tokens = N * B * S
    flops = tokens * L * (8 * H * H + 4 * H * I) + N * B * L * 4 * H * S * S
    results[f"mfu_compute_only{key_suffix}_pct"] = round(
        100 * flops / dt / peak, 2)
    results[f"mfu_compute_only{key_suffix}_pct_min"] = round(
        100 * flops / dt_hi / peak, 2)
    results[f"mfu_compute_only{key_suffix}_pct_max"] = round(
        100 * flops / dt_lo / peak, 2)
    results[f"compute_only{key_suffix}_emb_per_s"] = round(N * B / dt, 1)
    log(f"compute-only (no transfers, H={H} L={L}, [{B},{S}] bf16): "
        f"{N * B / dt:.0f} emb/s, MFU {100 * flops / dt / peak:.1f}% "
        f"[{100 * flops / dt_hi / peak:.1f}–{100 * flops / dt_lo / peak:.1f}]")


# ------------------------------------------------------------ full-stack e2e

def bench_e2e(results: dict) -> None:
    """Full-stack tier (VERDICT r3 item 1/2): what a user of the RUNNING
    stack sees, not the in-process engine object. Boots the native broker,
    the C++ api_gateway, C++ perception + preprocessing (×4 replicas on the
    queue group) + vector_memory workers, and the TPU engine plane; then
    drives the real HTTP surface:

    - ingest: POST /api/submit-url per document → C++ perception scrapes a
      local HTTP doc server → C++ preprocessing splits + embeds via
      engine.embed request-reply (micro-batched on the engine) → upsert;
      rate measured to the LAST durable upsert.
    - search: POST /api/search/semantic (the reference's whole 2-hop
      orchestration, api_service/src/main.rs:272-512) as median-of-5 sweeps.

    Every hop the engine-plane numbers exclude — HTTP parse, bus RTTs, JSON
    (de)serialization, queue-group routing — is inside these numbers."""
    import asyncio
    import pathlib
    import socket
    import subprocess
    import tempfile
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    REPO = pathlib.Path(__file__).resolve().parent
    try:
        subprocess.run(["make", "-C", str(REPO / "native")], check=True,
                       capture_output=True, timeout=600)
    except Exception as e:
        log(f"e2e tier SKIPPED: native build failed ({e})")
        return

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    # -- synthetic corpus served over local HTTP (perception scrapes it);
    # the last WARM_DOCS are a warm-up wave through the identical path so
    # the timed window measures steady state, not first-shape compiles.
    # 360 docs (was 120 through r4): at 120 the window was dominated by the
    # pipeline ramp (first docs trickling through scrape→split before the
    # engine sees a full backlog); 9k sentences measures the steady state
    # the metric is meant to capture (measured r5: 120 docs ≈ 950 emb/s,
    # 360 docs ≈ 1 800 emb/s, same stack)
    N_DOCS, SENTS, WARM_DOCS = 360, 25, 16
    rng = np.random.default_rng(7)
    doc_sentences = [[s.capitalize() for s in make_sentences(SENTS, rng)]
                     for _ in range(N_DOCS + WARM_DOCS)]
    pages = ["<html><body><main>"
             + "".join(f"<p>{s}.</p>" for s in sents)
             + "</main></body></html>" for sents in doc_sentences]

    class DocServer(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            i = int(self.path.rsplit("/", 1)[-1])
            body = pages[i].encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    docsrv = ThreadingHTTPServer(("127.0.0.1", 0), DocServer)
    threading.Thread(target=docsrv.serve_forever, daemon=True).start()
    doc_port = docsrv.server_address[1]

    bport, api_port = free_port(), free_port()
    broker = subprocess.Popen(
        [str(REPO / "native" / "build" / "symbus_broker"),
         "--port", str(bport), "--host", "127.0.0.1"],
        stderr=subprocess.DEVNULL)
    workers = []

    def spawn(name: str, extra: dict | None = None):
        import os

        env = dict(os.environ,
                   SYMBIONT_BUS_URL=f"symbus://127.0.0.1:{bport}",
                   **(extra or {}))
        p = subprocess.Popen([str(REPO / "native" / "build" / name)], env=env,
                             stderr=subprocess.PIPE)
        workers.append(p)
        return p

    async def wait_ready(proc, timeout=30.0):
        import os as _os

        _os.set_blocking(proc.stderr.fileno(), False)
        buf = b""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            chunk = proc.stderr.read()
            if chunk:
                buf += chunk
                if b"ready" in buf:
                    return
            await asyncio.sleep(0.05)
        raise TimeoutError(f"worker not ready: {buf!r}")

    async def drive(store, eng):
        import http.client as http_client
        import json as _json

        from symbiont_tpu.bus.tcp import TcpBus
        from symbiont_tpu.services.engine_service import EngineService

        bus = TcpBus("127.0.0.1", bport)
        await bus.connect()
        svc = EngineService(bus, engine=eng, vector_store=store)
        await svc.start()
        for _ in range(100):
            try:
                with socket.create_connection(("127.0.0.1", bport), 0.2):
                    break
            except OSError:
                await asyncio.sleep(0.05)
        # preprocessing replicas on the queue group: each is a synchronous
        # one-doc-at-a-time worker whose embed hop pays a device round-trip
        # (~110ms on this tunnel), so in-flight docs — and therefore how
        # well the engine micro-batcher can aggregate — scale with replicas
        n_preproc = 8
        results["e2e_preproc_replicas"] = n_preproc
        procs = [spawn("perception")]
        procs += [spawn("preprocessing") for _ in range(n_preproc)]
        procs += [spawn("vector_memory") for _ in range(2)]
        procs += [spawn("api_gateway", {"SYMBIONT_API_PORT": str(api_port)})]
        for p in procs:
            await wait_ready(p)

        loop = asyncio.get_running_loop()

        def http(method, path, payload=None):
            conn = http_client.HTTPConnection("127.0.0.1", api_port,
                                              timeout=120)
            conn.connect()
            # the client's own Nagle delay must not pollute the measurement
            conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            body = _json.dumps(payload) if payload is not None else None
            conn.request(method, path, body=body)
            r = conn.getresponse()
            data = r.read().decode()
            conn.close()
            return r.status, (_json.loads(data) if data else None)

        def hx(*a):
            return loop.run_in_executor(None, lambda: http(*a))

        # warm the executables the driven paths hit (compiles must not sit
        # inside the timed region — parity with the engine-plane benches):
        # the full (length, batch) grid the micro-batcher's flush mixes can
        # produce, then a warm ingest wave through the IDENTICAL HTTP path
        # (covers the grouped-concat fetch signatures too)
        eng.warmup(buckets=[32, 64, 128], batches=[1, 8, 32, 128, 512])
        store.warm_fused(eng)
        status, body = await hx("GET", "/healthz")
        assert status == 200, (status, body)
        warm_expected = WARM_DOCS * SENTS
        for i in range(N_DOCS, N_DOCS + WARM_DOCS):
            status, _ = await hx("POST", "/api/submit-url",
                                 {"url": f"http://127.0.0.1:{doc_port}/doc/{i}"})
            assert status == 200
        deadline = time.time() + 120
        while time.time() < deadline and store.count() < warm_expected:
            await asyncio.sleep(0.1)
        if store.count() < warm_expected:
            log(f"e2e warm wave incomplete: {store.count()}/{warm_expected}")
        warm_landed = store.count()

        # ---- ingest through the whole pipeline (steady state)
        expected = warm_landed + N_DOCS * SENTS
        t0 = time.time()
        for i in range(N_DOCS):
            status, _ = await hx("POST", "/api/submit-url",
                                 {"url": f"http://127.0.0.1:{doc_port}/doc/{i}"})
            assert status == 200
        deadline = time.time() + 300
        count = store.count()
        while time.time() < deadline:
            count = store.count()
            if count >= expected:
                break
            await asyncio.sleep(0.1)
        dt_ingest = time.time() - t0
        count = max(0, count - warm_landed)
        if count < N_DOCS * SENTS:
            log(f"e2e ingest: only {count}/{N_DOCS * SENTS} landed in time")
        results["e2e_ingest_emb_per_s"] = round(count / dt_ingest, 1)
        results["e2e_ingest_sentences"] = count
        results["e2e_ingest_s"] = round(dt_ingest, 2)
        log(f"e2e ingest (HTTP submit-url → scrape → split → embed → "
            f"upsert, {N_DOCS} docs, {n_preproc} preprocessing replicas): "
            f"{count} sentences in {dt_ingest:.2f}s → "
            f"{count / dt_ingest:.0f} emb/s")

        # ---- search over real HTTP (median-of-5 sweeps of 20 queries)
        for q in ["alpha beta", " ".join(["word"] * 40)]:
            status, body = await hx("POST", "/api/search/semantic",
                                    {"query_text": q, "top_k": 5})
            assert status == 200 and body["error_message"] is None, body
        p50s, p95s = [], []
        for _ in range(5):
            lat = []
            for q in make_sentences(20, rng):
                t0 = time.time()
                status, body = await hx("POST", "/api/search/semantic",
                                        {"query_text": q, "top_k": 5})
                lat.append(time.time() - t0)
                assert status == 200 and len(body["results"]) == 5, body
            ms = sorted(1000 * x for x in lat)
            p50s.append(ms[len(ms) // 2])
            p95s.append(ms[int(len(ms) * 0.95)])
        p50, p50_lo, p50_hi = med_min_max(p50s)
        results["e2e_search_p50_ms"] = round(p50, 1)
        results["e2e_search_p50_ms_min"] = round(p50_lo, 1)
        results["e2e_search_p50_ms_max"] = round(p50_hi, 1)
        results["e2e_search_p95_ms"] = round(med_min_max(p95s)[0], 1)
        log(f"e2e search (HTTP /api/search/semantic, 10 warm + 100 timed): "
            f"p50 {p50:.1f}ms [{p50_lo:.1f}–{p50_hi:.1f}], "
            f"p95 {results['e2e_search_p95_ms']:.1f}ms")

        # ---- full-stack generation: POST /api/generate-text → bus →
        # continuous-batching LM → SSE out of the C++ gateway (VERDICT r4
        # next-8; reference SSE path: api_service/src/main.rs:190-270)
        import threading
        import uuid as _uuid

        from symbiont_tpu.config import LmConfig
        from symbiont_tpu.engine.batcher import GenBatcher
        from symbiont_tpu.engine.lm import LmEngine
        from symbiont_tpu.services.text_generator import TextGeneratorService

        lm = LmEngine(LmConfig(
            enabled=True, arch="gpt2", hidden_size=768, num_layers=12,
            num_heads=12, intermediate_size=3072, max_positions=512,
            dtype="bfloat16", prompt_buckets=[64], new_token_buckets=[64],
            stream_chunk=16, gen_max_batch=16))
        gen_batcher = GenBatcher(lm)
        await gen_batcher.start()
        tg_bus = TcpBus("127.0.0.1", bport)
        await tg_bus.connect()
        tg = TextGeneratorService(tg_bus, lm_batcher=gen_batcher,
                                  lm_stream=lm.generate_stream,
                                  train_on_ingest=False)
        await tg.start()

        sse_events: list = []  # (wall-time, parsed event dict)
        sse_stop = threading.Event()

        def sse_listen():
            conn = http_client.HTTPConnection("127.0.0.1", api_port,
                                              timeout=300)
            conn.request("GET", "/api/events")
            r = conn.getresponse()
            while not sse_stop.is_set():
                line = r.readline()
                if not line:
                    break
                if line.startswith(b"data:"):
                    try:
                        sse_events.append(
                            (time.time(), _json.loads(line[5:].strip())))
                    except ValueError:
                        pass

        sse_thread = threading.Thread(target=sse_listen, daemon=True)
        sse_thread.start()
        await asyncio.sleep(0.3)  # SSE registered before the first event

        N_GEN, GEN_TOKENS = 16, 64
        prompt = "the tensor processing unit likes large matrix multiplies "

        def post_gen(stream=False):
            tid = str(_uuid.uuid4())
            body = {"task_id": tid, "prompt": prompt,
                    "max_length": GEN_TOKENS}
            if stream:
                body["stream"] = True
            status, _ = http("POST", "/api/generate-text", body)
            assert status == 200, status
            return tid

        def finals(ids):
            return {e["original_task_id"]: (t, e) for t, e in sse_events
                    if e.get("generated_text") is not None
                    and e.get("original_task_id") in ids}

        async def gen_wave(n):
            t0 = time.time()
            ids = {await loop.run_in_executor(None, post_gen)
                   for _ in range(n)}
            deadline = time.time() + 180
            while time.time() < deadline and len(finals(ids)) < n:
                await asyncio.sleep(0.05)
            done = finals(ids)
            assert len(done) == n, f"only {len(done)}/{n} generations"
            toks = sum(len(e["generated_text"].encode())
                       for _, e in done.values())
            return toks, max(t for t, _ in done.values()) - t0

        await gen_wave(N_GEN)  # warm: compiles session + admission shapes
        toks, dt_gen = await gen_wave(N_GEN)
        results["e2e_gen_clients"] = N_GEN
        results["e2e_gen_tok_per_s"] = round(toks / dt_gen, 1)
        log(f"e2e generation ({N_GEN} concurrent clients, {GEN_TOKENS} new "
            f"tokens each, continuous batcher): {toks} tokens in "
            f"{dt_gen:.2f}s → {toks / dt_gen:.0f} tok/s through the gateway")

        # streaming first-delta latency (stream=true rides the per-request
        # chunked decode; deltas ride events.text.generated.partial → SSE)
        warm_tid = post_gen(stream=True)  # warm the streaming executables
        deadline = time.time() + 120     # first compile can take tens of s
        while time.time() < deadline and not finals({warm_tid}):
            await asyncio.sleep(0.1)
        deltas = []
        for _ in range(3):
            t0 = time.time()
            tid = await loop.run_in_executor(None, post_gen, True)
            deadline = time.time() + 60
            first = None
            while time.time() < deadline and first is None:
                for t, e in sse_events:
                    if (e.get("original_task_id") == tid
                            and e.get("text_delta")):
                        first = t - t0
                        break
                await asyncio.sleep(0.01)
            assert first is not None, "no streaming delta arrived"
            deltas.append(first * 1000)
        results["e2e_first_delta_ms"] = round(sorted(deltas)[1], 1)
        log(f"e2e streaming: first SSE text delta "
            f"{results['e2e_first_delta_ms']:.0f}ms (median of 3, full "
            f"HTTP→bus→decode→SSE path)")
        sse_stop.set()
        await tg.stop()
        await gen_batcher.close()
        await tg_bus.close()
        await svc.stop()
        await bus.close()

    try:
        from symbiont_tpu.config import EngineConfig, VectorStoreConfig
        from symbiont_tpu.engine.engine import TpuEngine
        from symbiont_tpu.memory.vector_store import VectorStore

        with tempfile.TemporaryDirectory() as td:
            # engine at its RECOMMENDED bulk policy: the per-device-call floor
            # on this tunnel is ~100 ms regardless of batch (measured r5), so
            # the stack must amortize it — 512-row flushes, 4 in flight
            eng = TpuEngine(EngineConfig(
                embedding_dim=384, length_buckets=[32, 64, 128],
                batch_buckets=[1, 8, 32, 128, 512], max_batch=512,
                dtype="bfloat16", data_parallel=False,
                host_prep_chunk=256, max_inflight_flushes=4))
            # capacity covers the whole 9.4k-point corpus: crossing a
            # capacity block MID-RUN would invalidate the warmed fused
            # executables and send the timed searches down the 2-hop
            # fallback (observed: p50 110 ms → 365 ms)
            store = VectorStore(VectorStoreConfig(dim=384, data_dir=td,
                                                  shard_capacity=16384))
            asyncio.run(drive(store, eng))
    except Exception:
        import traceback

        log("e2e tier FAILED:\n" + traceback.format_exc())
    finally:
        for p in workers:
            p.terminate()
        broker.terminate()
        docsrv.shutdown()


# ------------------------------------------------------------- doc rendering

def load_archive(path) -> dict:
    """Read an archived bench line (either the raw JSON line or the driver's
    BENCH_r{N}.json wrapper, whose `parsed` key holds the line)."""
    import pathlib

    d = json.loads(pathlib.Path(path).read_text())
    return d.get("parsed", d)


def _fmt(x) -> str:
    """Render a measured value the way the table quotes it: thousands
    separators for big counts, the archived precision otherwise."""
    if isinstance(x, float) and x == int(x):
        x = int(x)
    if isinstance(x, int):
        return f"{x:,}"
    return f"{x:,.2f}" if abs(x) < 10 else f"{x:,.1f}"


def render_doc(r: dict, source_name: str) -> str:
    """docs/PERF.md, rendered MECHANICALLY from one archived bench line.

    Every measured number in the document is interpolated from `r` — the doc
    physically cannot diverge from the archived run (round-2 verdict weak #1:
    hand-copied values from an unarchived run, with transposed TTFT rows).
    tests/test_perf_doc.py re-renders from the named archive and asserts the
    committed file matches byte-for-byte."""
    legacy = "tunnel_emb_per_s" not in r
    if legacy:
        # pre-r5 archive: `value` WAS the tunnel-bound number
        r = dict(r)
        r["tunnel_emb_per_s"] = r["value"]
        for suf in ("min", "max", "samples"):
            if f"value_{suf}" in r:
                r[f"tunnel_emb_per_s_{suf}"] = r[f"value_{suf}"]
    f = {k: _fmt(v) for k, v in r.items() if isinstance(v, (int, float))}

    def rng(base: str) -> str:
        """Append ' [min–max]' when the archive carries the error-bar fields
        (median-of-5 runs from r4 on; older archives render without)."""
        lo, hi = f.get(f"{base}_min"), f.get(f"{base}_max")
        return f" [{lo}–{hi}]" if lo is not None else ""

    # --- tier 1: device-bound primaries (A/B-able round over round) -------
    primary_caption = (
        "LEGACY pre-r5 archive: `value` was the TUNNEL-BOUND embedding "
        "throughput then (not A/B-able — see the tunnel tier below)"
        if legacy else
        "compute-only MiniLM-384 embedding throughput, device-resident "
        "batches — DEVICE-BOUND (measured spread ±1-2%; the A/B anchor)")
    rows = [
        ("`value` (primary)", primary_caption,
         f"**{f['value']} emb/s/chip**"),
        ("`mfu_compute_only_pct`",
         "compute-only MFU, MiniLM-384 geometry, no transfers (see below)",
         f"**{f['mfu_compute_only_pct']}"
         f"{rng('mfu_compute_only_pct')} %**"),
    ]
    if "mfu_compute_only_768_pct" in f:
        rows += [
            ("`mfu_compute_only_768_pct`",
             "compute-only MFU, mpnet-768 geometry (the reference's default "
             "model, preprocessing_service/src/main.rs:305)",
             f"**{f['mfu_compute_only_768_pct']}"
             f"{rng('mfu_compute_only_768_pct')} %** "
             f"({f['compute_only_768_emb_per_s']} emb/s)"),
        ]
    if "mfu_compute_only_1024_pct" in f:
        rows += [
            ("`mfu_compute_only_1024_pct`",
             "compute-only MFU, e5-large geometry (1024-d, 24 layers — "
             "BASELINE.md config #3)",
             f"**{f['mfu_compute_only_1024_pct']}"
             f"{rng('mfu_compute_only_1024_pct')} %** "
             f"({f['compute_only_1024_emb_per_s']} emb/s)"),
        ]
    rows += [
        ("`gpt2_124m_tok_per_s`",
         "GPT-2 124M geometry decode, bf16, batch 8 "
         f"(TTFT {f['gpt2_124m_ttft_ms']} ms)",
         f"**{f['gpt2_124m_tok_per_s']} tok/s/chip** "
         f"({f['gpt2_124m_tok_per_s_stream']}/stream)"),
        ("`tinyllama_1b_tok_per_s`",
         "TinyLlama 1.1B geometry (GQA 32/4) decode, batch 8 "
         f"(TTFT {f['tinyllama_1b_ttft_ms']} ms)",
         f"**{f['tinyllama_1b_tok_per_s']} tok/s/chip** "
         f"({f['tinyllama_1b_tok_per_s_stream']}/stream)"),
    ]
    for gkey, glabel in (("gpt2_124m", "GPT-2 124M"),
                         ("tinyllama_1b", "TinyLlama 1.1B")):
        for b in (32, 64, 128):
            if f"{gkey}_tok_per_s_b{b}" in f:
                util = f.get(f"{gkey}_hbm_util_vs_measured_pct_b{b}")
                nl = (" (noise-limited estimate)"
                      if r.get(f"{gkey}_ms_per_step_noise_limited_b{b}")
                      else "")
                extra = (f"; {f[f'{gkey}_ms_per_step_b{b}']} ms/step, "
                         f"{util}% of measured HBM peak{nl}" if util else "")
                rows.append((
                    f"`{gkey}_tok_per_s_b{b}`",
                    f"{glabel} decode at batch {b}{extra}",
                    f"**{f[f'{gkey}_tok_per_s_b{b}']} tok/s/chip**"))
    rows += [
        ("`stream_first_delta_ms`",
         "streaming: first SSE text delta (chunk 16, engine-plane)",
         f"{f['stream_first_delta_ms']} ms"),
    ]
    # --- tier 2: full-stack (what a user of the running stack sees) ------
    if "e2e_search_p50_ms" in f:
        rows += [
            ("`e2e_search_p50_ms` / `p95`",
             "FULL-STACK search: HTTP POST /api/search/semantic through the "
             "C++ gateway + bus + engine plane (the reference's 2-hop "
             "orchestration, api_service/src/main.rs:272-512)",
             f"**{f['e2e_search_p50_ms']}{rng('e2e_search_p50_ms')} / "
             f"{f['e2e_search_p95_ms']} ms**"),
            ("`e2e_ingest_emb_per_s`",
             f"FULL-STACK ingest: HTTP submit-url → C++ perception scrape → "
             f"C++ preprocessing ({f.get('e2e_preproc_replicas', '4')} "
             f"pipelined queue-group replicas, coalesced embed hops) → "
             f"engine embed → coalesced upsert; "
             f"{f['e2e_ingest_sentences']} sentences in "
             f"{f['e2e_ingest_s']} s",
             f"**{f['e2e_ingest_emb_per_s']} emb/s**"),
        ]
    if "e2e_gen_tok_per_s" in f:
        rows += [
            ("`e2e_gen_tok_per_s`",
             f"FULL-STACK generation: {f.get('e2e_gen_clients', '16')} "
             f"concurrent clients POST /api/generate-text → bus → "
             f"continuous-batching LM (GPT-2 geometry) → SSE out of the C++ "
             f"gateway (reference SSE path: api_service/src/main.rs:190-270)",
             f"**{f['e2e_gen_tok_per_s']} tok/s**"),
            ("`e2e_first_delta_ms`",
             "FULL-STACK streaming: POST stream=true → first SSE text delta "
             "through gateway + bus + chunked decode",
             f"{f['e2e_first_delta_ms']} ms"),
        ]
    # --- tier 3: tunnel-bound (informational; carries its spread) --------
    tunnel = f"{f['tunnel_emb_per_s']}"
    if "tunnel_emb_per_s_min" in f:
        tunnel += (f" [{f['tunnel_emb_per_s_min']}–"
                   f"{f['tunnel_emb_per_s_max']}] (median of "
                   f"{f['tunnel_emb_per_s_samples']})")
    rows += [
        ("`tunnel_emb_per_s`",
         "TUNNEL-BOUND: 2k mixed-length corpus through host↔device "
         "transfers on this link (archived r1–r4 history varies 2.5× at "
         "zero code change — never A/B this across rounds)",
         f"{tunnel} emb/s"),
        ("`vs_baseline`",
         f"tunnel policy ratio ÷ reference policy "
         f"(`ref_policy_emb_per_s` = {f['ref_policy_emb_per_s']}; both "
         f"sides measured in the same minutes, so link drift largely "
         f"cancels)",
         f"**{f['vs_baseline']}×**"),
        ("`ingest_10k_emb_per_s`",
         "10k-corpus bulk ingest (one embed_texts call, tunnel-bound)",
         f"{f['ingest_10k_emb_per_s']} emb/s"),
        ("`upsert_10k_points_per_s`",
         f"10k-point WAL-durable upsert (`upsert_10k_s` {f['upsert_10k_s']} s)",
         f"{f['upsert_10k_points_per_s']} points/s"),
        ("`mfu_pct`",
         "useful-FLOPs MFU of the tunnel run (real tokens, real lengths)",
         f"{f['mfu_pct']} %"),
        ("`hw_util_incl_padding_pct`",
         "same run, counting all padded compute the chip executed",
         f"{f['hw_util_incl_padding_pct']} %"),
        ("`search_split_p50_ms` / `p95`",
         "split embed→search, 10k corpus, top-5 (tunnel: 2 device RTTs)",
         f"{f['search_split_p50_ms']}{rng('search_split_p50_ms')} / "
         f"{f['search_split_p95_ms']} ms"),
        ("`search_fused_p50_ms` / `p95`",
         "FUSED single-program path, same query set (1 device RTT)",
         f"**{f['search_fused_p50_ms']}{rng('search_fused_p50_ms')} / "
         f"{f['search_fused_p95_ms']} ms**"),
        ("`rerank_pairs_per_s`",
         f"cross-encoder rerank, 256 pairs pad-128 (`rerank_hop_ms` "
         f"{f['rerank_hop_ms']})",
         f"{f['rerank_pairs_per_s']} pairs/s"),
    ]
    table = "\n".join(f"| {a} | {b} | {c} |" for a, b, c in rows)
    e2e_section = ""
    if "e2e_search_p50_ms" in f:
        gen_bullet = ""
        if "e2e_gen_tok_per_s" in f:
            gen_bullet = (
                f"- Generation: {f.get('e2e_gen_clients', '16')} concurrent "
                f"clients through the gateway sustain "
                f"**{f['e2e_gen_tok_per_s']} tok/s** on one continuous-"
                f"batching decode session; a stream=true request's first "
                f"SSE text delta lands in {f['e2e_first_delta_ms']} ms "
                f"(HTTP → bus → prefill + one 16-token chunk → partial "
                f"event → SSE fan-out).\n")
        e2e_section = f"""## The full-stack tier (what a user of the running stack sees)

`e2e_*` numbers boot the REAL stack — native symbus broker, C++ api_gateway,
C++ perception/preprocessing/vector_memory workers, TPU engine plane — and
drive it over HTTP (`bench_e2e` in bench.py). The delta to the engine-plane
numbers is everything the reference's users also pay: HTTP parse, two bus
round-trips, JSON (de)serialization of 384-float embeddings, queue-group
routing. Note: this whole stack shares ONE host core in this sandbox, so
host-side costs that would vanish on a normal multi-core box are visible
here.

- Search: engine-plane fused p50 {f['search_fused_p50_ms']} ms vs
  full-stack p50 **{f['e2e_search_p50_ms']} ms** — the C++ gateway probes
  the fused `engine.query.search` hop, so the whole native stack (HTTP
  parse, bus round-trips, JSON) adds single-digit milliseconds on top of
  the one device round-trip; the two p50s come from different query sweeps
  on a jittery link, so their small delta can land either side of zero.
  The reference-parity 2-hop fallback costs two device round-trips instead
  (`search_split_p50_ms` = {f['search_split_p50_ms']} ms).
- Ingest: full-stack **{f['e2e_ingest_emb_per_s']} emb/s** steady-state
  (the r4→r5 rework took this from 353: the worker shells are now
  pipelined event loops that coalesce multiple documents per engine hop,
  vectors cross the engine plane as base64 f32 blocks, and f32→JSON text
  formatting uses ryu). The remaining gap to the engine-plane bulk number
  ({f['ingest_10k_emb_per_s']} emb/s, one in-process call) is the measured
  floor of this environment: every engine request-reply hop costs ~100 ms
  of tunnel RTT regardless of batch size (512-row flushes amortize it to
  ~0.2 ms/sentence), and the one shared host core runs every JSON/bus/HTTP
  byte of 15 processes. On a locally-attached multi-core deployment both
  terms collapse.
{gen_bullet}
"""
    mfu768 = ""
    if "mfu_compute_only_768_pct" in f:
        mfu768 = (
            f"\n   At the reference's own default geometry (mpnet, H=768) the "
            f"wider matmuls fill the 128×128 MXU better: "
            f"`mfu_compute_only_768_pct` = **{f['mfu_compute_only_768_pct']} %** "
            f"({f['compute_only_768_emb_per_s']} emb/s at [1024, 128]).\n"
            f"   Why it tops out here (r5 sweep, all measured on this chip): "
            f"the batch/bucket sweep peaked at [1024, 128] (58.8–59.2% vs "
            f"55.9–57.4% at the previous [512, 128]); every other lever "
            f"measured WORSE — pallas flash attention 36–42%, fused QKV "
            f"52.8% (the same post-matmul slicing loss as the decode-side "
            f"negative result), f32 softmax −3 pts at S=128 and −5.7 pts at "
            f"S=512 (the bf16-softmax decision re-confirmed at long "
            f"buckets), and bf16 LayerNorm statistics a wash (the f32 "
            f"stats are already fused). Bare chained matmuls at the "
            f"encoder's own shapes measure BELOW the full fused model on "
            f"this chip, so ~59% useful-FLOPs MFU is the practical ceiling "
            f"of this v5e for a 12-layer 768-wide encoder.")
    return f"""# Measured performance

**Rendered from `{source_name}` — do not edit the numbers by hand.**
Regenerate with `python bench.py --render-doc {source_name} > docs/PERF.md`;
`tests/test_perf_doc.py` asserts this file matches that archive exactly.

All numbers measured on one real **TPU v5 lite (v5e) chip** reached over a
network tunnel. Synthetic weights (`"semantic_validation":
"synthetic-only"` in the JSON line) — throughput is weight-value
independent, but it means **semantic quality is unvalidated in this
sandbox**: no egress, so the gated golden tier against a real pretrained
checkpoint (`tests/test_real_assets.py`, `SYMBIONT_MODEL_DIR`) has never
executed here — run it where a fetched snapshot exists
(`scripts/fetch_model.py`), then check in golden vectors
(`scripts/make_goldens.py` → `tests/test_golden_vectors.py`) so torch-free
hosts re-validate semantic fidelity offline; the flow itself is proven
in-suite on a transformers-serialized synthetic checkpoint.
Reproduce with `python bench.py`: it prints ONE JSON line whose fields carry
**every number in the table below** (the driver archives that line as
`BENCH_r{{N}}.json` each round — the archived line is authoritative).

**Which fields are comparable across rounds.** The JSON line's
`primary_metrics` list names them: device-bound numbers (compute-only MFU
family, decode ms/step) move ±1-2% run to run, and the full-stack `e2e_*`
tier is dominated by its own pipeline, so regressions there are real. The
tunnel-bound fields (`tunnel_emb_per_s`, `ingest_10k_*`, `search_*`,
`rerank_*`) ride a link whose bandwidth drifts on the scale of hours — the
archived r1–r4 history spans **2.5×** on `tunnel_emb_per_s` with zero code
change (r4's min/max: 3,483–8,663 within ONE run). They are reported with
min/max spread and must never be A/B'd across rounds. (Earlier revisions of
this doc claimed "~±20%" — the archive itself refutes that.)

The reference publishes no numbers at all (BASELINE.md), so the baseline
column is the reference's *policy* measured on identical hardware: fixed
padding to the model max in serial batches of 8
(reference: embedding_generator.rs:83-91,146).

| JSON field | Config | Value |
|---|---|---|
{table}

## Reading the MFU numbers (the honest version)

MFU here = useful matmul FLOPs (each sentence's REAL token count and length —
padding is not useful work) ÷ elapsed ÷ 197 TFLOP/s (v5e bf16 peak).

Three tiers, and the gaps between them are the performance story:

1. **{f['mfu_pct']} % end-to-end.** The wall is the *tunnel*, not the chip.
   Measured transfer floor on this link: ~45 MB/s and ~100 ms RTT. A
   10k-sentence ingest moves ~3 MB in and 7.5 MB out (bf16), so even with
   zero compute the link caps this workload at roughly 25–30k emb/s. MiniLM
   at ~16 real tokens/sentence is simply too small a model to amortize a WAN
   hop per batch.
2. **{f['hw_util_incl_padding_pct']} % including padding** — the chip
   executes 64/128-token buckets (and rounded-up batch rows) for ~16-token
   sentences; the delta to tier 1 is padding waste the bucketing already cut
   from the reference's 512-pad (which would sit at ~0.5 %).
3. **{f['mfu_compute_only_pct']} % compute-only** (`mfu_compute_only_pct`):
   20 chained forwards on device-resident data, inputs varied per iteration
   so XLA cannot hoist the loop. This is what a locally-attached chip gets
   per batch; it is the number to compare against other frameworks'
   embedding-path MFU. For a 384-wide, 6-layer model the MXU (128×128
   systolic) is hard to fill much further — the per-layer matmuls are
   [B·64, 384]×[384, 384].{mfu768}

## The fused query path

The interactive search path originally ran two device programs (query embed,
then cosine top-k), each paying a full host↔device round-trip — on a
network-attached chip that floor is ~200–300 ms regardless of compute. The
fix is TPU-native: one compiled program does BERT forward → pool → normalize
→ `[cap, D] @ [D]` cosine scores → `lax.top_k`, and both outputs start their
device→host copies asynchronously. One round-trip total: split p50
{f['search_split_p50_ms']} ms → fused p50 {f['search_fused_p50_ms']} ms here,
and on a locally-attached chip the same path is single-digit ms. The gateway
tries the fused `engine.query.search` hop first (for
`top_k ≤ fused_search_max_top_k`, whose executables are pre-warmed) and falls
back to the reference's 2-hop orchestration when engine and store are not
co-located.

{e2e_section}## The decode roofline (measured, r5)

Decode is weight-read bound, so the honest roofline needs the chip's
MEASURED bandwidth, not the paper number — and that measurement drifts
with the hour on this tunnel-attached device (the same reduce-sum kernel
measured 581 and 715 GB/s hours apart), so each bench run measures its
OWN ceiling: the fastest sustained stream observed in the run, whether
the reduce-sum reference kernel (`hbm_stream_gbps_measured` =
{f.get('hbm_stream_gbps_measured', '—')} GB/s) or the decode path itself
(`hbm_stream_gbps_ceiling` =
**{f.get('hbm_stream_gbps_ceiling', f.get('hbm_stream_gbps_measured', '—'))} GB/s**
this run; v5e paper: 819). The decode utilization fields divide by that
ceiling, so they can never exceed 100% by construction. Also measured
(scripts/profile_decode.py + r5 logs): serially-dependent weight-streaming
matmuls — decode's exact access pattern, each layer's matmul waiting on
the previous — sustain only a fraction of the pure-stream rate
(~90–220 GB/s in isolated chains, batch-independent), a compiler/hardware
pipelining property, not model code.

Against that: TinyLlama batch-8 decode streams
{f.get('tinyllama_1b_hbm_gbps', '—')} GB/s =
**{f.get('tinyllama_1b_hbm_util_vs_measured_pct', '—')}% of this run's
stream ceiling** — small-batch decode is essentially at the wall. At batch
128 the per-step bytes grow only 1.25× (weights dominate; KV reads are
`{f.get('tinyllama_1b_hbm_gbps_b128', '—')}` GB/s effective) but the chain
throughput drops toward the serial-matmul regime — the batch sweep's
`*_hbm_util_vs_measured_pct_b*` fields archive exactly where each point
sits, so a regression-from-roofline is visible (VERDICT r4 weak #3). The
per-step estimator subtracts a paired prefill measurement; points flagged
`*_noise_limited` have a decode window comparable to the subtracted
RTT+prefill term and carry ~±20% uncertainty.

What r5 changed, measured on the CHUNKED serving path (the one streaming /
continuous batching actually runs): donating the KV-cache carry across the
chunk-call boundary (gpt.py `_decode_chunk_jit`) removed an input+output
double-residency that thrashed HBM at serving sizes — TinyLlama b128 with
a 960-slot cache went **385 → 19.8 ms/step (19.5×)**, b128×192 17.8 →
14.3 ms, b8 6.6 → 4.8 ms; storing params at model dtype (bf16) halved
their residency and removed a full f32→bf16 convert per chunk. Ablations
(profile_decode.py): sampling is INNOCENT — greedy-argmax ≡ top-k
sampling ≡ no-top-k within noise at every batch, so the per-row top-k
hypothesis from r4 is dead.

## Where the embedding win comes from (SURVEY.md §5.7/§7)

1. **Length-bucketed static shapes** — the reference pads every sentence to
   the model max (514); the mixed-length corpus here pads to {{64, 128}}.
2. **Large batches** — 256–512-row batches feed the MXU; the reference's
   serial batch-8 loop leaves it idle between launches.
3. **bf16 matmuls** (fp32 statistics in the norms/softmax/pooling).
4. **Pipelined dispatch** — all batches dispatch before any result is
   materialized, and device→host copies start async, so compute, h2d and
   d2h overlap; on a network-attached chip this collapses N round-trips
   into ~1.
5. **Transfer-lean wire format** — lengths instead of masks up, bf16 down.

## Methodology notes

- The PRIMARY metrics are device-bound (`primary_metrics` in the JSON
  line): compute-only MFU family as median-of-5 with min/max, decode
  ms/step as best-of-3. Tunnel-touching metrics (tunnel_emb_per_s, search
  p50s) are median-of-5 with min/max archived alongside
  (`*_min`/`*_max`) — single samples on this link are noise: measured
  floor per engine call = one device RTT (~110 ms here) + result bytes /
  tunnel bandwidth, and both terms drift by hours-scale factors (2.5×
  observed across the r1–r4 archives). Round-over-round comparisons of
  tunnel-bound fields are meaningless; the r02→r03 "27% dip" was exactly
  this: one sample vs one sample.
- Secondary metrics remain best-of-3 (tunnel jitter is one-sided; min is
  the honest estimate of chip-side cost).
- Warmup compiles every (length-bucket, batch-bucket) executable the timed
  run will hit; `compiles` is asserted in engine stats so a recompile storm
  would show up as a regression here.
- `vs_baseline` in the JSON line = our policy ÷ reference policy on the SAME
  chip, same model geometry, same corpus distribution.
- FLOPs model for MFU: per token per layer `8H² + 4HI` (projections + MLP)
  plus `4·H·S` attention; `bert_fwd_flops` in bench.py.
"""


def main() -> None:
    t_start = time.time()
    import jax

    from symbiont_tpu.config import EngineConfig
    from symbiont_tpu.engine.engine import TpuEngine

    dev = jax.devices()[0]
    log(f"device: {dev.device_kind} ({dev.platform})")
    peak = chip_peak_flops(dev)
    rng = np.random.default_rng(0)
    sentences = make_sentences(2048, rng)

    # MiniLM-L6 geometry (BASELINE.md config #1), bf16, synthetic weights —
    # throughput is weight-value independent.
    H, I, L = 384, 1536, 6

    def mk_engine(length_buckets, batch_buckets, max_batch):
        return TpuEngine(EngineConfig(
            embedding_dim=H, length_buckets=length_buckets,
            batch_buckets=batch_buckets, max_batch=max_batch,
            dtype="bfloat16", data_parallel=False,
            host_prep_chunk=256))  # tokenize chunk N+1 under dispatch of N

    # --- our policy: buckets {64,128}, batches up to 512 ------------------
    ours = mk_engine([64, 128], [32, 256, 512], 512)
    ours.embed_texts(sentences)  # warmup: compiles every (bucket, batch) the
    #                              real run will hit (same plan, same shapes)
    eps_samples = []  # median-of-5: one sample on a ±20% link is noise
    for _ in range(5):
        t0 = time.time()
        ours.embed_texts(sentences)
        eps_samples.append(len(sentences) / (time.time() - t0))
    eps_ours, eps_min, eps_max = med_min_max(eps_samples)
    dt_ours = len(sentences) / eps_ours
    log(f"bucketed policy: {len(sentences)} sentences, median of 5 runs "
        f"→ {eps_ours:.0f} emb/s [{eps_min:.0f}–{eps_max:.0f}] "
        f"(compiles={ours.stats['compiles']})")

    # MFU: useful FLOPs use each sentence's REAL token count and length;
    # executed FLOPs replay the engine's actual batch plan — every row of
    # every (length-bucket × batch-bucket) executable, including batch-row
    # padding — at the padded length (what the chip actually ran).
    from symbiont_tpu.engine.bucketing import plan_batches

    cfg_e = ours.config
    max_len = min(cfg_e.length_buckets[-1],
                  ours.model_cfg.max_position_embeddings)
    lengths = [len(e) for e in ours.tokenizer.encode_batch(sentences, max_len)]
    exec_rows: list = []  # one padded length per EXECUTED row
    for bucket, indices in plan_batches(lengths, cfg_e.length_buckets,
                                        cfg_e.max_batch):
        exec_rows.extend([bucket] * ours._batch_bucket(len(indices)))
    useful = bert_fwd_flops(lengths, H, I, L)
    executed = bert_fwd_flops(exec_rows, H, I, L, seq_for_attn=exec_rows)
    results: dict = {"value_min": round(eps_min, 1),
                     "value_max": round(eps_max, 1),
                     "value_samples": len(eps_samples)}
    if peak:
        results["mfu_pct"] = round(100 * useful / dt_ours / peak, 2)
        results["hw_util_incl_padding_pct"] = round(
            100 * executed / dt_ours / peak, 2)
        log(f"MFU {results['mfu_pct']:.2f}% useful "
            f"({results['hw_util_incl_padding_pct']:.2f}% incl. padding) "
            f"against {peak / 1e12:.0f} TFLOP/s bf16 peak")
    else:
        log("MFU: n/a (not a TPU device)")

    # --- reference policy: pad-to-512, serial batch 8 ---------------------
    # The reference materializes every batch before starting the next
    # (to_vec2 inside the batch loop, embedding_generator.rs:146-216), so
    # emulate it with one blocking embed_texts call per 8-sentence batch.
    ref = mk_engine([512], [8], 8)
    n_ref = 256  # subset; serial 512-padded batches are slow by design
    ref.embed_texts(sentences[:n_ref])  # warmup, same shapes as timed run
    dt_ref = float("inf")  # best-of-3, same treatment as "ours"
    for _ in range(3):
        t0 = time.time()
        for i in range(0, n_ref, 8):
            ref.embed_texts(sentences[i:i + 8])
        dt_ref = min(dt_ref, time.time() - t0)
    eps_ref = n_ref / dt_ref
    results["ref_policy_emb_per_s"] = round(eps_ref, 1)
    log(f"reference policy (pad-512, batch 8): {n_ref} sentences in "
        f"{dt_ref:.2f}s → {eps_ref:.0f} emb/s")

    if "--quick" not in sys.argv:
        bench_compute_mfu(results, peak)
        bench_search_latency(results)
        bench_rerank(results)
        bench_stream_ceiling(results)
        bench_lm_decode(results)
        bench_tinyllama_decode(results)
        bench_streaming(results)
        if "--no-e2e" not in sys.argv:
            bench_e2e(results)

    if "hbm_stream_gbps_measured" in results:
        # the stream ceiling is a SAMPLE of a drifting device: one run's
        # reduce-sum reference landed below what decode itself sustained
        # minutes later (decode "146% of ceiling"). The honest ceiling is
        # the fastest sustained stream OBSERVED this run — reference kernel
        # or the decode path itself — so utilization can never exceed 100%
        # by construction and regressions stay meaningful.
        achieved = [
            v for k, v in results.items()
            if "_hbm_gbps" in k and isinstance(v, (int, float))
            # a noise-limited per-step estimate can overshoot wildly —
            # it must never SET the ceiling every other point divides by
            and not results.get(
                k.replace("_hbm_gbps", "_ms_per_step_noise_limited"))]
        ceiling = max([results["hbm_stream_gbps_measured"]] + achieved)
        results["hbm_stream_gbps_ceiling"] = round(ceiling, 1)
        for k in [k for k in results if "_hbm_gbps" in k
                  and k != "hbm_stream_gbps_measured"
                  and k != "hbm_stream_gbps_ceiling"]:
            results[k.replace("_hbm_gbps", "_hbm_util_vs_measured_pct")] = \
                round(100 * results[k] / ceiling, 1)

    log(f"total bench time {time.time() - t_start:.0f}s")
    # tunnel-bound embedding throughput: informational-with-spread, NOT the
    # headline — archived r1-r4 history shows 2.5× run-to-run variance on
    # this link with zero code change (VERDICT r4 weak #1 / next-2)
    results["tunnel_emb_per_s"] = round(eps_ours, 1)
    results["tunnel_emb_per_s_min"] = results.pop("value_min")
    results["tunnel_emb_per_s_max"] = results.pop("value_max")
    results["tunnel_emb_per_s_samples"] = results.pop("value_samples")
    if "compute_only_emb_per_s" in results:
        # the headline is DEVICE-BOUND (A/B-able round over round: measured
        # spread ±1-2%): compute-only embedding throughput at the primary
        # geometry. The tunnel number stays in the archive with its spread.
        metric = ("compute-only embeddings/sec/chip (MiniLM-L6 geometry, "
                  "bf16, device-resident batches)")
        value = results["compute_only_emb_per_s"]
    else:  # --quick: only the tunnel metric was measured
        metric = ("embeddings/sec/chip (MiniLM-L6 geometry, bf16, "
                  "mixed-length corpus, TUNNEL-BOUND)")
        value = round(eps_ours, 1)
    line = {
        "metric": metric,
        "value": value,
        "unit": "embeddings/s",
        "vs_baseline": round(eps_ours / eps_ref, 2),
        "ts": int(time.time()),
        # throughput numbers come from synthetic weights (no egress in this
        # sandbox): they are weight-value independent, but NO consumer may
        # mistake them for a semantically validated model (VERDICT r4 next-6)
        "semantic_validation": "synthetic-only",
        # the fields a round-over-round comparison should use (device-bound
        # or full-stack; everything tunnel-bound carries min/max spread)
        "primary_metrics": [
            "compute_only_emb_per_s", "mfu_compute_only_pct",
            "mfu_compute_only_768_pct", "mfu_compute_only_1024_pct",
            "gpt2_124m_ms_per_step_b128", "tinyllama_1b_ms_per_step_b128",
            "tinyllama_1b_hbm_util_vs_measured_pct",
            "e2e_ingest_emb_per_s", "e2e_search_p50_ms",
            "e2e_gen_tok_per_s", "e2e_first_delta_ms",
        ],
        **results,
    }
    print(json.dumps(line))
    if "--quick" not in sys.argv:
        _persist_latest(line)


def _persist_latest(line: dict) -> None:
    """Archive the freshest full run as BENCH_LATEST.json and re-render
    docs/PERF.md from it, so the committed doc always reflects the newest
    measurement (VERDICT r3: the doc must not pin a stale round;
    tests/test_perf_doc.py enforces freshness against every BENCH_r*.json
    present). Best-effort: a read-only checkout still benches fine."""
    import pathlib

    root = pathlib.Path(__file__).resolve().parent
    try:
        (root / "BENCH_LATEST.json").write_text(json.dumps(line) + "\n")
        (root / "docs" / "PERF.md").write_text(
            render_doc(line, "BENCH_LATEST.json"))
        log("BENCH_LATEST.json + docs/PERF.md regenerated from this run")
    except OSError as e:
        log(f"could not persist BENCH_LATEST.json / docs/PERF.md: {e}")


if __name__ == "__main__":
    if "--render-doc" in sys.argv:
        # doc render needs no device (and no jax): usable anywhere
        path = sys.argv[sys.argv.index("--render-doc") + 1]
        import pathlib

        print(render_doc(load_archive(path), pathlib.Path(path).name), end="")
    else:
        main()
