"""Benchmark: embeddings/sec/chip on the flagship embedding path.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.md: "none exist"), so
vs_baseline is measured, not quoted: the same model on the same chip run the
reference's way — fixed padding to model max (514-equivalent) in serial
batches of 8 (reference: embedding_generator.rs:83-91,146) — versus this
framework's way (length-bucketed static shapes, big batches, bf16). The ratio
is the design win of SURVEY.md §5.7/§7 on identical hardware.

Extra detail lines go to stderr; stdout carries exactly the one JSON line.

`python bench.py --full` additionally measures BASELINE.md configs #4 and #5
(cross-encoder rerank pairs/s; GPT-2-geometry decode tokens/s + TTFT) — the
results land on stderr and in docs/PERF.md's table.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def make_sentences(n: int, rng) -> list:
    """Synthetic corpus with a realistic sentence-length mix (most sentences
    short, a tail of long ones — what the scraper actually produces)."""
    words = ["tensor", "processing", "unit", "accelerates", "matrix", "products",
             "the", "memory", "bandwidth", "of", "embeddings", "semantic",
             "search", "pipeline", "document", "sentences", "vector", "graph",
             "tokens", "model", "attention", "masked", "pooling", "batch"]
    out = []
    for _ in range(n):
        ln = int(np.clip(rng.lognormal(2.6, 0.7), 3, 120))
        out.append(" ".join(rng.choice(words, size=ln)))
    return out


def bench_rerank() -> None:
    """BASELINE.md config #4: ms-marco-MiniLM-L-6 geometry cross-encoder,
    pairs/sec over a top-k-sized candidate set."""
    from symbiont_tpu.config import EngineConfig
    from symbiont_tpu.engine.engine import TpuEngine

    eng = TpuEngine(EngineConfig(
        embedding_dim=384, length_buckets=[128], batch_buckets=[64, 256],
        max_batch=256, dtype="bfloat16", data_parallel=False,
        rerank_enabled=True))
    rng = np.random.default_rng(1)
    passages = make_sentences(256, rng)
    query = "tensor processing unit matrix products"
    eng.rerank(query, passages)  # warmup: compiles the (128, 256) executable
    dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        eng.rerank(query, passages)
        dt = min(dt, time.time() - t0)
    log(f"rerank (MiniLM-L6 CE geometry, 256 pairs, pad-128, bf16): "
        f"{256 / dt:.0f} pairs/s (p50 rerank hop {dt * 1000:.1f}ms)")


def bench_search_latency() -> None:
    """BASELINE.md north-star metric #2: p50 semantic-search latency — query
    embed (MiniLM-L6 geometry) + exact cosine top-k over a 10k-row
    device-resident corpus. This is the compute path of the 2-hop
    request-reply orchestration (SURVEY.md §3.2); bus + HTTP add ~1ms."""
    import tempfile

    from symbiont_tpu.config import EngineConfig, VectorStoreConfig
    from symbiont_tpu.engine.engine import TpuEngine
    from symbiont_tpu.memory.vector_store import VectorStore

    eng = TpuEngine(EngineConfig(
        embedding_dim=384, length_buckets=[32, 64], batch_buckets=[1, 8, 512],
        max_batch=512, dtype="bfloat16", data_parallel=False))
    rng = np.random.default_rng(3)
    corpus = make_sentences(10_000, rng)
    with tempfile.TemporaryDirectory() as td:
        store = VectorStore(VectorStoreConfig(dim=384, data_dir=td,
                                              shard_capacity=16384))
        eng.embed_texts(corpus[:600])  # warm every (bucket, batch) executable
        t0 = time.time()
        vecs = eng.embed_texts(corpus)
        t_embed = time.time() - t0
        t0 = time.time()
        store.upsert([(f"p{i}", vecs[i], {"sentence_text": corpus[i]})
                      for i in range(len(corpus))])
        t_upsert = time.time() - t0
        log(f"bulk ingest: 10k sentences embedded in {t_embed:.2f}s "
            f"({10_000 / t_embed:.0f} emb/s), upserted in {t_upsert:.2f}s")

        def measure(fn):
            fn(make_sentences(4, rng)[0])  # warm
            lat = []
            for q in make_sentences(64, rng):
                t0 = time.time()
                fn(q)
                lat.append(time.time() - t0)
            ms = sorted(1000 * x for x in lat)
            return ms[len(ms) // 2], ms[int(len(ms) * 0.95)]

        def split(q):
            assert len(store.search(eng.embed_query(q), 5)) == 5

        def fused(q):
            assert len(store.search_fused(eng, q, 5)) == 5

        # warm every query-length bucket for both paths
        for ql in ["a b c", " ".join(["word"] * 40)]:
            split(ql), fused(ql)
        p50, p95 = measure(split)
        log(f"semantic search, split path (10k corpus, top-5): "
            f"p50 {p50:.1f}ms, p95 {p95:.1f}ms (embed call + top-k call)")
        p50f, p95f = measure(fused)
        log(f"semantic search, FUSED path (10k corpus, top-5): "
            f"p50 {p50f:.1f}ms, p95 {p95f:.1f}ms "
            f"(one compiled embed+top-k program, one device round-trip)")


def bench_lm_decode() -> None:
    """BASELINE.md config #5: GPT-2-small geometry (124M, vocab 50257)
    autoregressive decode — tokens/sec/chip and time-to-first-token."""
    _bench_decode_geometry("GPT-2 124M", dict(
        vocab_size=50257, hidden_size=768, num_layers=12, num_heads=12,
        intermediate_size=3072, max_position_embeddings=1024, arch="gpt2"))


def bench_tinyllama_decode() -> None:
    """BASELINE.md config #5 (second named model): TinyLlama-1.1B geometry —
    22 layers, GQA 32/4, SwiGLU, RoPE — decode on one chip, bf16."""
    _bench_decode_geometry("TinyLlama 1.1B", dict(
        vocab_size=32000, hidden_size=2048, num_layers=22, num_heads=32,
        num_kv_heads=4, intermediate_size=5632, max_position_embeddings=2048,
        arch="llama"))


def _bench_decode_geometry(label: str, cfg_kw: dict) -> None:
    import jax
    import jax.numpy as jnp

    from symbiont_tpu.models import gpt as gpt_mod

    cfg = gpt_mod.GPTConfig(dtype="bfloat16", **cfg_kw)
    params = gpt_mod.init_params(jax.random.key(0), cfg)
    params = jax.device_put(params)
    rng = np.random.default_rng(2)
    B, P, NEW = 8, 64, 128
    ids = jnp.asarray(rng.integers(1, cfg.vocab_size, (B, P)), jnp.int32)
    mask = jnp.ones((B, P), jnp.int32)
    key = jax.random.key(0)

    def run(max_new):
        toks, _ = gpt_mod.generate(params, ids, mask, key, cfg,
                                   max_new_tokens=max_new, temperature=0.8,
                                   top_k=40)
        jax.block_until_ready(toks)

    run(1)    # compile (prefill + 1-step scan)
    run(NEW)  # compile the NEW-step scan
    ttft = float("inf")
    dt = float("inf")
    for _ in range(3):
        t0 = time.time()
        run(1)
        ttft = min(ttft, time.time() - t0)
        t0 = time.time()
        run(NEW)
        dt = min(dt, time.time() - t0)
    log(f"lm decode ({label} geometry, bf16, batch {B}, prompt {P}, "
        f"{NEW} new): {B * NEW / dt:.0f} tokens/s/chip "
        f"({NEW / dt:.0f} tok/s/stream), TTFT {ttft * 1000:.0f}ms")


def main() -> None:
    t_start = time.time()
    import jax

    from symbiont_tpu.config import EngineConfig
    from symbiont_tpu.engine.engine import TpuEngine

    dev = jax.devices()[0]
    log(f"device: {dev.device_kind} ({dev.platform})")
    rng = np.random.default_rng(0)
    sentences = make_sentences(2048, rng)

    # MiniLM-L6 geometry (BASELINE.md config #1), bf16, synthetic weights —
    # throughput is weight-value independent.
    def mk_engine(length_buckets, batch_buckets, max_batch):
        return TpuEngine(EngineConfig(
            embedding_dim=384, length_buckets=length_buckets,
            batch_buckets=batch_buckets, max_batch=max_batch,
            dtype="bfloat16", data_parallel=False))

    # --- our policy: buckets {64,128}, batches up to 512 ------------------
    ours = mk_engine([64, 128], [32, 256, 512], 512)
    ours.embed_texts(sentences)  # warmup: compiles every (bucket, batch) the
    #                              real run will hit (same plan, same shapes)
    dt_ours = float("inf")  # best-of-3: the tunnel to the chip adds jitter
    for _ in range(3):
        t0 = time.time()
        ours.embed_texts(sentences)
        dt_ours = min(dt_ours, time.time() - t0)
    eps_ours = len(sentences) / dt_ours
    log(f"bucketed policy: {len(sentences)} sentences in {dt_ours:.2f}s "
        f"→ {eps_ours:.0f} emb/s (compiles={ours.stats['compiles']})")

    # --- reference policy: pad-to-512, serial batch 8 ---------------------
    # The reference materializes every batch before starting the next
    # (to_vec2 inside the batch loop, embedding_generator.rs:146-216), so
    # emulate it with one blocking embed_texts call per 8-sentence batch.
    ref = mk_engine([512], [8], 8)
    n_ref = 256  # subset; serial 512-padded batches are slow by design
    ref.embed_texts(sentences[:n_ref])  # warmup, same shapes as timed run
    dt_ref = float("inf")  # best-of-3, same treatment as "ours"
    for _ in range(3):
        t0 = time.time()
        for i in range(0, n_ref, 8):
            ref.embed_texts(sentences[i:i + 8])
        dt_ref = min(dt_ref, time.time() - t0)
    eps_ref = n_ref / dt_ref
    log(f"reference policy (pad-512, batch 8): {n_ref} sentences in "
        f"{dt_ref:.2f}s → {eps_ref:.0f} emb/s")

    if "--full" in sys.argv:
        bench_search_latency()
        bench_rerank()
        bench_lm_decode()
        bench_tinyllama_decode()

    log(f"total bench time {time.time() - t_start:.0f}s")
    print(json.dumps({
        "metric": "embeddings/sec/chip (MiniLM-L6 geometry, bf16, mixed-length corpus)",
        "value": round(eps_ours, 1),
        "unit": "embeddings/s",
        "vs_baseline": round(eps_ours / eps_ref, 2),
    }))


if __name__ == "__main__":
    main()
