// Minimal JSON library for the symbiont native services.
//
// Hand-written (NOT generated). The generated wire-schema header
// (generated/cpp/symbiont_schema.hpp) builds on this. Scope is exactly what the
// wire schema needs: null/bool/number/string/array/object, UTF-8 passthrough,
// \uXXXX escapes (incl. surrogate pairs), strict parse errors. The reference's
// services get this via serde_json (reference: libs/shared_models/Cargo.toml);
// this is the C++ equivalent with the same strictness.
#pragma once

#include <cctype>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <initializer_list>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace json {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& msg) : std::runtime_error("json: " + msg) {}
};

class Value {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Value() : type_(Type::Null) {}
  explicit Value(bool b) : type_(Type::Bool), bool_(b) {}
  explicit Value(double n) : type_(Type::Number), num_(n) {}
  explicit Value(float n) : type_(Type::Number), is_f32_(true), num_(n) {}
  explicit Value(int n) : type_(Type::Number), num_(n) {}
  explicit Value(const std::string& s) : type_(Type::String), str_(s) {}
  explicit Value(std::string&& s) : type_(Type::String), str_(std::move(s)) {}
  explicit Value(const char* s) : type_(Type::String), str_(s) {}

  static Value object() {
    Value v;
    v.type_ = Type::Object;
    return v;
  }
  static Value array() {
    Value v;
    v.type_ = Type::Array;
    return v;
  }

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool is_object() const { return type_ == Type::Object; }
  bool is_array() const { return type_ == Type::Array; }

  bool as_bool() const {
    require(Type::Bool, "bool");
    return bool_;
  }
  double as_number() const {
    require(Type::Number, "number");
    return num_;
  }
  // Strict u64 decode parity with the Python schema decoder: fractional or
  // negative numbers are rejected. Values are limited to 2^53 (the double
  // mantissa) — the wire schema's u64 fields are timestamps/counts, far below.
  uint64_t as_u64() const {
    require(Type::Number, "number");
    if (num_ < 0 || num_ != std::floor(num_) || num_ >= 9007199254740992.0)
      throw Error("expected integer (u64-safe)");
    return (uint64_t)num_;
  }
  const std::string& as_string() const {
    require(Type::String, "string");
    return str_;
  }
  const std::vector<Value>& as_array() const {
    require(Type::Array, "array");
    return arr_;
  }
  const std::map<std::string, Value>& as_object() const {
    require(Type::Object, "object");
    return obj_;
  }

  void set(const std::string& key, Value v) {
    require(Type::Object, "object");
    // std::map keeps keys sorted; field order is not part of JSON equality.
    obj_[key] = std::move(v);
  }
  void push_back(Value v) {
    require(Type::Array, "array");
    arr_.push_back(std::move(v));
  }

  bool has(const std::string& key) const {
    require(Type::Object, "object");
    return obj_.count(key) != 0;
  }
  const Value& at(const std::string& key) const {
    require(Type::Object, "object");
    auto it = obj_.find(key);
    if (it == obj_.end()) throw Error("missing required field '" + key + "'");
    return it->second;
  }
  // Strict-decode parity with the Python schema decoder
  // (symbiont_tpu/schema/__init__.py from_dict): unknown fields are an error.
  void check_known_fields(std::initializer_list<const char*> known) const {
    require(Type::Object, "object");
    for (const auto& kv : obj_) {
      bool ok = false;
      for (const char* k : known)
        if (kv.first == k) {
          ok = true;
          break;
        }
      if (!ok) throw Error("unknown field '" + kv.first + "'");
    }
  }

  std::string dump() const {
    std::string out;
    write(out);
    return out;
  }

 private:
  void require(Type t, const char* name) const {
    if (type_ != t) throw Error(std::string("expected ") + name);
  }

  static void write_escaped(std::string& out, const std::string& s) {
    out += '"';
    for (unsigned char c : s) {
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\b': out += "\\b"; break;
        case '\f': out += "\\f"; break;
        case '\n': out += "\\n"; break;
        case '\r': out += "\\r"; break;
        case '\t': out += "\\t"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            std::snprintf(buf, sizeof buf, "\\u%04x", c);
            out += buf;
          } else {
            out += static_cast<char>(c);  // UTF-8 bytes pass through
          }
      }
    }
    out += '"';
  }

  void write(std::string& out) const {
    switch (type_) {
      case Type::Null: out += "null"; break;
      case Type::Bool: out += bool_ ? "true" : "false"; break;
      case Type::Number: {
        if (std::isfinite(num_) && num_ == std::floor(num_) &&
            std::fabs(num_) < 9.007199254740992e15) {
          char buf[32];
          std::snprintf(buf, sizeof buf, "%lld", (long long)num_);
          out += buf;
        } else if (is_f32_) {
          // Shortest representation that round-trips the f32 exactly —
          // matches serde_json's f32 output (the wire format embeddings use,
          // reference: libs/shared_models/src/lib.rs:42 Vec<f32>).
          // std::to_chars is ryu-based shortest-round-trip in one shot; the
          // old snprintf/strtof precision ladder produced the same bytes but
          // ~30x slower (measured 636 ms vs 21 ms per 384k floats) — at a
          // million floats per bulk-ingest wave that was seconds of CPU on
          // the one-core host.
          char buf[40];
          float f = (float)num_;
          auto r = std::to_chars(buf, buf + sizeof buf, f);
          out.append(buf, r.ptr - buf);
        } else {
          char buf[40];
          auto r = std::to_chars(buf, buf + sizeof buf, num_);
          out.append(buf, r.ptr - buf);
        }
        break;
      }
      case Type::String: write_escaped(out, str_); break;
      case Type::Array: {
        out += '[';
        bool first = true;
        for (const auto& v : arr_) {
          if (!first) out += ',';
          first = false;
          v.write(out);
        }
        out += ']';
        break;
      }
      case Type::Object: {
        out += '{';
        bool first = true;
        for (const auto& kv : obj_) {
          if (!first) out += ',';
          first = false;
          write_escaped(out, kv.first);
          out += ':';
          kv.second.write(out);
        }
        out += '}';
        break;
      }
    }
  }

  Type type_;
  bool bool_ = false;
  bool is_f32_ = false;
  double num_ = 0.0;
  std::string str_;
  std::vector<Value> arr_;
  std::map<std::string, Value> obj_;
};

template <typename T, typename F>
Value to_array(const std::vector<T>& items, F f) {
  Value a = Value::array();
  for (const T& v : items) a.push_back(f(v));
  return a;
}

// ---------------------------------------------------------------------------
// Parser
// ---------------------------------------------------------------------------

class Parser {
 public:
  explicit Parser(const std::string& src) : s_(src) {}

  Value parse() {
    skip_ws();
    Value v = parse_value();
    skip_ws();
    if (pos_ != s_.size()) throw Error("trailing characters at offset " + std::to_string(pos_));
    return v;
  }

 private:
  char peek() {
    if (pos_ >= s_.size()) throw Error("unexpected end of input");
    return s_[pos_];
  }
  char next() {
    char c = peek();
    ++pos_;
    return c;
  }
  void skip_ws() {
    while (pos_ < s_.size() &&
           (s_[pos_] == ' ' || s_[pos_] == '\t' || s_[pos_] == '\n' || s_[pos_] == '\r'))
      ++pos_;
  }
  void expect(const char* lit) {
    for (const char* p = lit; *p; ++p)
      if (pos_ >= s_.size() || s_[pos_++] != *p) throw Error(std::string("expected '") + lit + "'");
  }

  Value parse_value() {
    switch (peek()) {
      case 'n': expect("null"); return Value();
      case 't': expect("true"); return Value(true);
      case 'f': expect("false"); return Value(false);
      case '"': return Value(parse_string());
      case '[': return parse_array();
      case '{': return parse_object();
      default: return parse_number();
    }
  }

  Value parse_number() {
    // Strict JSON grammar: -?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?
    // — same forms serde_json and Python's json accept; '01', '.5', '1.'
    // are rejected.
    size_t start = pos_;
    if (pos_ < s_.size() && s_[pos_] == '-') ++pos_;
    if (pos_ >= s_.size() || !std::isdigit((unsigned char)s_[pos_]))
      throw Error("invalid number");
    if (s_[pos_] == '0') {
      ++pos_;
    } else {
      while (pos_ < s_.size() && std::isdigit((unsigned char)s_[pos_])) ++pos_;
    }
    if (pos_ < s_.size() && s_[pos_] == '.') {
      ++pos_;
      if (pos_ >= s_.size() || !std::isdigit((unsigned char)s_[pos_]))
        throw Error("invalid number");
      while (pos_ < s_.size() && std::isdigit((unsigned char)s_[pos_])) ++pos_;
    }
    if (pos_ < s_.size() && (s_[pos_] == 'e' || s_[pos_] == 'E')) {
      ++pos_;
      if (pos_ < s_.size() && (s_[pos_] == '+' || s_[pos_] == '-')) ++pos_;
      if (pos_ >= s_.size() || !std::isdigit((unsigned char)s_[pos_]))
        throw Error("invalid number");
      while (pos_ < s_.size() && std::isdigit((unsigned char)s_[pos_])) ++pos_;
    }
    // from_chars is locale-independent (std::stod honors LC_NUMERIC and
    // would misparse "1.5" under a comma-decimal locale).
    double d = 0.0;
    auto res = std::from_chars(s_.data() + start, s_.data() + pos_, d);
    if (res.ec == std::errc::result_out_of_range) throw Error("number out of range");
    if (res.ec != std::errc() || res.ptr != s_.data() + pos_)
      throw Error("invalid number");
    return Value(d);
  }

  static void append_utf8(std::string& out, uint32_t cp) {
    if (cp < 0x80) {
      out += (char)cp;
    } else if (cp < 0x800) {
      out += (char)(0xC0 | (cp >> 6));
      out += (char)(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += (char)(0xE0 | (cp >> 12));
      out += (char)(0x80 | ((cp >> 6) & 0x3F));
      out += (char)(0x80 | (cp & 0x3F));
    } else {
      out += (char)(0xF0 | (cp >> 18));
      out += (char)(0x80 | ((cp >> 12) & 0x3F));
      out += (char)(0x80 | ((cp >> 6) & 0x3F));
      out += (char)(0x80 | (cp & 0x3F));
    }
  }

  uint32_t parse_hex4() {
    uint32_t v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = next();
      v <<= 4;
      if (c >= '0' && c <= '9') v |= (uint32_t)(c - '0');
      else if (c >= 'a' && c <= 'f') v |= (uint32_t)(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') v |= (uint32_t)(c - 'A' + 10);
      else throw Error("invalid \\u escape");
    }
    return v;
  }

  std::string parse_string() {
    expect("\"");
    std::string out;
    for (;;) {
      char c = next();
      if (c == '"') return out;
      if (c == '\\') {
        char e = next();
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            uint32_t cp = parse_hex4();
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // high surrogate
              expect("\\u");
              uint32_t lo = parse_hex4();
              if (lo < 0xDC00 || lo > 0xDFFF) throw Error("invalid surrogate pair");
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            } else if (cp >= 0xDC00 && cp <= 0xDFFF) {
              throw Error("lone low surrogate");  // serde_json rejects these too
            }
            append_utf8(out, cp);
            break;
          }
          default: throw Error("invalid escape");
        }
      } else if ((unsigned char)c < 0x20) {
        throw Error("unescaped control character in string");
      } else {
        out += c;
      }
    }
  }

  Value parse_array() {
    expect("[");
    Value a = Value::array();
    skip_ws();
    if (peek() == ']') {
      ++pos_;
      return a;
    }
    for (;;) {
      skip_ws();
      a.push_back(parse_value());
      skip_ws();
      char c = next();
      if (c == ']') return a;
      if (c != ',') throw Error("expected ',' or ']' in array");
    }
  }

  Value parse_object() {
    expect("{");
    Value o = Value::object();
    skip_ws();
    if (peek() == '}') {
      ++pos_;
      return o;
    }
    for (;;) {
      skip_ws();
      std::string key = parse_string();
      skip_ws();
      expect(":");
      skip_ws();
      o.set(key, parse_value());
      skip_ws();
      char c = next();
      if (c == '}') return o;
      if (c != ',') throw Error("expected ',' or '}' in object");
    }
  }

  const std::string& s_;
  size_t pos_ = 0;
};

inline Value parse(const std::string& src) { return Parser(src).parse(); }

}  // namespace json
