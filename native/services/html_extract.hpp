// HTML main-content extraction — native twin of
// symbiont_tpu/services/html_extract.py; parity with the reference's scraper
// cascade (reference: services/perception_service/src/main.rs:86-170):
// 1. first element matching, in order: article, main, div[role='main'],
//    div.content, div.post-content, div.entry-content, body — else whole doc;
// 2. within it, for each of h1..h6, p, li, span in that order, each element's
//    trimmed space-joined text nodes, skipping empties;
// 3. join with newlines, trim lines, drop empty lines.
//
// The parser is a tolerant single-pass tag scanner (no external deps):
// nearest-matching-open-tag close semantics, void elements, raw-text
// script/style/noscript/template skipping, comment/doctype skipping, and
// decoding of the common character references (Python's html.parser decodes
// all named refs; the long tail of exotic entities passes through verbatim).
#pragma once

#include <cctype>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

namespace symbiont {
namespace html {

struct Node {
  std::string tag;
  std::map<std::string, std::string> attrs;
  std::vector<std::unique_ptr<Node>> children;  // ownership
  // ordered child stream: element (node != nullptr) or text run
  struct Item {
    Node* node = nullptr;
    std::string text;
  };
  std::vector<Item> stream;
};

inline bool is_void_element(const std::string& t) {
  static const char* kVoid[] = {"area", "base", "br",     "col",  "embed",
                                "hr",   "img",  "input",  "link", "meta",
                                "param", "source", "track", "wbr"};
  for (const char* v : kVoid)
    if (t == v) return true;
  return false;
}

inline bool is_rawtext_element(const std::string& t) {
  return t == "script" || t == "style" || t == "noscript" || t == "template";
}

inline std::string decode_entities(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (size_t i = 0; i < s.size();) {
    if (s[i] != '&') {
      out += s[i++];
      continue;
    }
    size_t semi = s.find(';', i);
    if (semi == std::string::npos || semi - i > 12) {
      out += s[i++];
      continue;
    }
    std::string ent = s.substr(i + 1, semi - i - 1);
    std::string rep;
    if (ent == "amp") rep = "&";
    else if (ent == "lt") rep = "<";
    else if (ent == "gt") rep = ">";
    else if (ent == "quot") rep = "\"";
    else if (ent == "apos") rep = "'";
    else if (ent == "nbsp") rep = "\xc2\xa0";
    else if (!ent.empty() && ent[0] == '#') {
      long cp = -1;
      try {
        cp = (ent.size() > 1 && (ent[1] == 'x' || ent[1] == 'X'))
                 ? std::stol(ent.substr(2), nullptr, 16)
                 : std::stol(ent.substr(1));
      } catch (...) {
      }
      if (cp >= 0 && cp <= 0x10ffff) {  // encode UTF-8
        if (cp < 0x80) rep += (char)cp;
        else if (cp < 0x800) {
          rep += (char)(0xc0 | (cp >> 6));
          rep += (char)(0x80 | (cp & 0x3f));
        } else if (cp < 0x10000) {
          rep += (char)(0xe0 | (cp >> 12));
          rep += (char)(0x80 | ((cp >> 6) & 0x3f));
          rep += (char)(0x80 | (cp & 0x3f));
        } else {
          rep += (char)(0xf0 | (cp >> 18));
          rep += (char)(0x80 | ((cp >> 12) & 0x3f));
          rep += (char)(0x80 | ((cp >> 6) & 0x3f));
          rep += (char)(0x80 | (cp & 0x3f));
        }
      }
    }
    if (rep.empty() && !(ent == "#0")) {
      out += s[i++];  // unknown entity: pass through verbatim
    } else {
      out += rep;
      i = semi + 1;
    }
  }
  return out;
}

inline std::string ascii_lower(std::string s) {
  for (auto& c : s) c = (char)std::tolower((unsigned char)c);
  return s;
}

class Parser {
 public:
  std::unique_ptr<Node> parse(const std::string& src) {
    auto root = std::make_unique<Node>();
    root->tag = "#document";
    stack_.clear();
    stack_.push_back(root.get());
    size_t i = 0;
    const size_t n = src.size();
    while (i < n) {
      if (src[i] == '<') {
        if (src.compare(i, 4, "<!--") == 0) {
          size_t end = src.find("-->", i + 4);
          i = end == std::string::npos ? n : end + 3;
          continue;
        }
        if (i + 1 < n && (src[i + 1] == '!' || src[i + 1] == '?')) {
          size_t end = src.find('>', i);
          i = end == std::string::npos ? n : end + 1;
          continue;
        }
        if (i + 1 < n && src[i + 1] == '/') {
          size_t end = src.find('>', i);
          if (end == std::string::npos) break;
          std::string tag = ascii_lower(trim(src.substr(i + 2, end - i - 2)));
          close_tag(tag);
          i = end + 1;
          continue;
        }
        // open tag
        size_t end = find_tag_end(src, i);
        if (end == std::string::npos) {  // stray '<' at EOF: treat as text
          append_text(src.substr(i));
          break;
        }
        bool self_close = end >= 2 && src[end - 1] == '/';
        parse_open_tag(src.substr(i + 1, end - i - 1 - (self_close ? 1 : 0)),
                       self_close);
        i = end + 1;
        // raw-text elements: consume until the matching close tag
        if (!stack_.empty() && is_rawtext_element(stack_.back()->tag) &&
            !self_close) {
          std::string closer = "</" + stack_.back()->tag;
          size_t close_at = find_ci(src, closer, i);
          size_t gt = close_at == std::string::npos
                          ? std::string::npos
                          : src.find('>', close_at);
          // raw text content is intentionally dropped (SKIP_TEXT_IN)
          close_tag(stack_.back()->tag);
          i = gt == std::string::npos ? n : gt + 1;
        }
        continue;
      }
      size_t next = src.find('<', i);
      if (next == std::string::npos) next = n;
      append_text(src.substr(i, next - i));
      i = next;
    }
    return root;
  }

 private:
  static std::string trim(const std::string& s) {
    size_t b = s.find_first_not_of(" \t\r\n\f\v");
    if (b == std::string::npos) return "";
    size_t e = s.find_last_not_of(" \t\r\n\f\v");
    return s.substr(b, e - b + 1);
  }

  // '>' inside quoted attribute values does not end the tag
  static size_t find_tag_end(const std::string& s, size_t start) {
    char quote = 0;
    for (size_t i = start + 1; i < s.size(); ++i) {
      char c = s[i];
      if (quote) {
        if (c == quote) quote = 0;
      } else if (c == '"' || c == '\'') {
        quote = c;
      } else if (c == '>') {
        return i;
      }
    }
    return std::string::npos;
  }

  static size_t find_ci(const std::string& hay, const std::string& needle,
                        size_t from) {
    if (needle.empty()) return from;
    for (size_t i = from; i + needle.size() <= hay.size(); ++i) {
      size_t j = 0;
      while (j < needle.size() &&
             std::tolower((unsigned char)hay[i + j]) ==
                 std::tolower((unsigned char)needle[j]))
        ++j;
      if (j == needle.size()) return i;
    }
    return std::string::npos;
  }

  void parse_open_tag(const std::string& body, bool self_close) {
    size_t i = 0;
    const size_t n = body.size();
    while (i < n && !std::isspace((unsigned char)body[i])) ++i;
    std::string tag = ascii_lower(body.substr(0, i));
    if (tag.empty()) return;
    auto node = std::make_unique<Node>();
    node->tag = tag;
    // attributes
    while (i < n) {
      while (i < n && std::isspace((unsigned char)body[i])) ++i;
      if (i >= n) break;
      size_t name_start = i;
      while (i < n && !std::isspace((unsigned char)body[i]) && body[i] != '=')
        ++i;
      std::string name = ascii_lower(body.substr(name_start, i - name_start));
      while (i < n && std::isspace((unsigned char)body[i])) ++i;
      std::string value;
      if (i < n && body[i] == '=') {
        ++i;
        while (i < n && std::isspace((unsigned char)body[i])) ++i;
        if (i < n && (body[i] == '"' || body[i] == '\'')) {
          char q = body[i++];
          size_t vstart = i;
          while (i < n && body[i] != q) ++i;
          value = body.substr(vstart, i - vstart);
          if (i < n) ++i;
        } else {
          size_t vstart = i;
          while (i < n && !std::isspace((unsigned char)body[i])) ++i;
          value = body.substr(vstart, i - vstart);
        }
      }
      if (!name.empty()) node->attrs[name] = decode_entities(value);
    }
    Node* raw = node.get();
    stack_.back()->stream.push_back({raw, ""});
    stack_.back()->children.push_back(std::move(node));
    if (!self_close && !is_void_element(tag)) stack_.push_back(raw);
  }

  void close_tag(const std::string& tag) {
    // close the nearest matching open tag (tolerant of malformed HTML)
    for (size_t i = stack_.size(); i-- > 1;) {
      if (stack_[i]->tag == tag) {
        stack_.resize(i);
        return;
      }
    }
  }

  void append_text(const std::string& raw) {
    if (raw.empty()) return;
    stack_.back()->stream.push_back({nullptr, decode_entities(raw)});
  }

  std::vector<Node*> stack_;
};

// ---- selector support: tag | tag.class | tag[attr='value'] -----------------

inline bool matches(const Node& node, const std::string& selector) {
  auto br = selector.find('[');
  if (br != std::string::npos) {
    std::string tag = selector.substr(0, br);
    std::string rest = selector.substr(br + 1);
    if (!rest.empty() && rest.back() == ']') rest.pop_back();
    auto eq = rest.find('=');
    if (eq == std::string::npos) return false;
    std::string attr = rest.substr(0, eq);
    std::string value = rest.substr(eq + 1);
    while (!value.empty() && (value.front() == '\'' || value.front() == '"'))
      value.erase(value.begin());
    while (!value.empty() && (value.back() == '\'' || value.back() == '"'))
      value.pop_back();
    auto it = node.attrs.find(attr);
    return node.tag == tag && it != node.attrs.end() && it->second == value;
  }
  auto dot = selector.find('.');
  if (dot != std::string::npos) {
    std::string tag = selector.substr(0, dot);
    std::string cls = selector.substr(dot + 1);
    if (node.tag != tag) return false;
    auto it = node.attrs.find("class");
    if (it == node.attrs.end()) return false;
    std::istringstream in(it->second);
    std::string c;
    while (in >> c)
      if (c == cls) return true;
    return false;
  }
  return node.tag == selector;
}

inline void walk(Node& node, const std::string& selector,
                 std::vector<Node*>& out) {
  for (auto& item : node.stream) {
    if (item.node) {
      if (matches(*item.node, selector)) out.push_back(item.node);
      walk(*item.node, selector, out);
    }
  }
}

inline Node* find_first(Node& root, const std::string& selector) {
  std::vector<Node*> out;
  walk(root, selector, out);
  return out.empty() ? nullptr : out.front();
}

inline void collect_text(const Node& node, std::vector<std::string>& parts) {
  if (is_rawtext_element(node.tag)) return;
  for (const auto& item : node.stream) {
    if (item.node) collect_text(*item.node, parts);
    else parts.push_back(item.text);
  }
}

inline std::string trim_copy(const std::string& s) {
  size_t b = s.find_first_not_of(" \t\r\n\f\v");
  if (b == std::string::npos) return "";
  size_t e = s.find_last_not_of(" \t\r\n\f\v");
  return s.substr(b, e - b + 1);
}

// Trimmed text nodes joined with single spaces (reference main.rs:133-142).
inline std::string element_text(const Node& node) {
  std::vector<std::string> raw;
  collect_text(node, raw);
  std::string out;
  for (auto& t : raw) {
    std::string p = trim_copy(t);
    if (p.empty()) continue;
    if (!out.empty()) out += ' ';
    out += p;
  }
  return out;
}

// Full cascade (reference main.rs:100-160).
inline std::string extract_main_text(const std::string& src) {
  static const char* kContentSelectors[] = {
      "article", "main", "div[role='main']", "div.content",
      "div.post-content", "div.entry-content", "body"};
  static const char* kTextSelectors[] = {"h1", "h2", "h3", "h4", "h5",
                                         "h6", "p",  "li", "span"};
  Parser parser;
  auto doc = parser.parse(src);
  Node* scope = nullptr;
  for (const char* sel : kContentSelectors) {
    scope = find_first(*doc, sel);
    if (scope) break;
  }
  if (!scope) scope = doc.get();
  std::vector<std::string> parts;
  for (const char* sel : kTextSelectors) {
    std::vector<Node*> els;
    walk(*scope, sel, els);
    for (Node* el : els) {
      std::string text = element_text(*el);
      if (!text.empty()) parts.push_back(text);
    }
  }
  std::string out;
  for (auto& p : parts) {
    std::string line = trim_copy(p);
    if (line.empty()) continue;
    if (!out.empty()) out += '\n';
    out += line;
  }
  return out;
}

}  // namespace html
}  // namespace symbiont
