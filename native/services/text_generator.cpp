// text_generator worker — C++ equivalent of the reference's
// text_generator_service (SURVEY.md §2 checklist item 7; reference:
// services/text_generator_service/src/main.rs).
//
// Markov backend runs fully native (order-1 word chain, behavioral parity
// with reference main.rs:13-109 — see MarkovModel below), trained
// continuously on every ingested document instead of the reference's one
// hardcoded boot sentence (main.rs:169-174). With
// SYMBIONT_TEXTGEN_BACKEND=lm the worker instead forwards the prompt to the
// TPU decoder LM over the engine.generate request-reply plane.
//
// Usage: text_generator [SYMBIONT_BUS_URL=symbus://host:port]

#include <cstdint>
#include <map>
#include <random>
#include <set>
#include <sstream>
#include <string>
#include <vector>

#include "../../generated/cpp/symbiont_schema.hpp"
#include "common.hpp"

namespace {

const char* SERVICE = "text_generator";

// the reference's single hardcoded training sentence (main.rs:170) — kept as
// the cold-start corpus so an empty system still generates
const char* SEED_CORPUS =
    "Это первое предложение для обучения нашей марковской модели оно простое";

std::vector<std::string> split_ws(const std::string& text) {
  std::vector<std::string> out;
  std::istringstream in(text);
  std::string w;
  while (in >> w) out.push_back(w);
  return out;
}

// Order-1 word-level Markov chain; parity with the reference
// (main.rs:29-108) and the Python twin (symbiont_tpu/models/markov.py):
// - <2 words: record starter only; starters are sorted + deduped after every
//   train; transitions are a multiset (duplicates weight the walk);
// - generate: uniform starter, then up to max_length-1 uniform successor
//   picks, stopping at a dead end; untrained → "Model not trained."
class MarkovModel {
 public:
  void train(const std::string& text) {
    auto words = split_ws(text);
    if (words.empty()) return;
    starters_.insert(words[0]);
    if (words.size() < 2) return;
    for (size_t i = 0; i + 1 < words.size(); ++i)
      chain_[words[i]].push_back(words[i + 1]);
  }

  std::string generate(uint64_t max_length) {
    if (chain_.empty() || starters_.empty()) return "Model not trained.";
    std::vector<std::string> starters(starters_.begin(), starters_.end());
    std::string current = starters[pick(starters.size())];
    std::string out = current;
    for (uint64_t i = 1; i < max_length; ++i) {
      auto it = chain_.find(current);
      if (it == chain_.end() || it->second.empty()) break;
      current = it->second[pick(it->second.size())];
      out += " ";
      out += current;
    }
    return out;
  }

  size_t chain_size() const { return chain_.size(); }

 private:
  size_t pick(size_t n) {
    std::uniform_int_distribution<size_t> d(0, n - 1);
    return d(rng_);
  }
  std::map<std::string, std::vector<std::string>> chain_;
  std::set<std::string> starters_;  // ordered == reference's sort+dedup
  std::mt19937_64 rng_{std::random_device{}()};
};

}  // namespace

int main() try {
  bool lm_backend = symbiont::env_or("SYMBIONT_TEXTGEN_BACKEND", "markov") == "lm";
  int engine_timeout_ms =
      std::atoi(symbiont::env_or("SYMBIONT_ENGINE_TIMEOUT_MS", "120000").c_str());
  MarkovModel markov;
  markov.train(SEED_CORPUS);

  symbus::Client bus;
  if (!symbiont::connect_with_retry(bus, SERVICE)) return 1;

  uint32_t sid_gen = bus.subscribe(symbiont::subjects::TASKS_GENERATION_TEXT,
                                   symbiont::subjects::Q_TEXT_GENERATOR);
  // continuous learning from the pipeline (no queue group: every generator
  // replica learns the full stream) — skipped in LM mode where the chain
  // would grow unboundedly while never generating
  uint32_t sid_train = 0;
  if (!lm_backend)
    sid_train = bus.subscribe(symbiont::subjects::DATA_RAW_TEXT_DISCOVERED);

  symbiont::logline("INFO", SERVICE,
                    lm_backend ? "ready (backend=lm)" : "ready (backend=markov)");

  // fleet liveness: beat `_sys.heartbeat.<role>` so the process supervisor's
  // hang detector covers this shell (SYMBIONT_RUNNER_HEARTBEAT_S > 0)
  symbiont::Heartbeat hb = symbiont::heartbeat_from_env(SERVICE);

  while (bus.connected()) {
    auto msg = bus.next(1000);
    symbiont::maybe_heartbeat(bus, hb);
    if (!msg) continue;
    if (sid_train != 0 && msg->sid == sid_train) {
      try {
        auto raw = symbiont::RawTextMessage::parse(msg->data);
        markov.train(raw.raw_text);
      } catch (const std::exception& e) {
        symbiont::logline("WARN", SERVICE,
                          std::string("bad raw-text message: ") + e.what(),
                          msg->headers);
      }
      continue;
    }
    if (msg->sid != sid_gen) continue;
    // expired-deadline drop (Service._run_handler parity): the reader that
    // wanted this generation is past its deadline — never decode for it
    if (symbiont::drop_if_expired(bus, *msg, SERVICE)) continue;

    symbiont::GenerateTextTask task;
    try {
      task = symbiont::GenerateTextTask::parse(msg->data);
    } catch (const std::exception& e) {
      symbiont::logline("WARN", SERVICE,
                        std::string("bad generate task: ") + e.what(),
                        msg->headers);
      continue;
    }

    std::string text;
    if (lm_backend) {
      json::Value req = json::Value::object();
      req.set("prompt", task.prompt ? json::Value(*task.prompt) : json::Value());
      req.set("max_new_tokens", json::Value((double)task.max_length));
      // per-request sampling overrides ride through to the engine plane
      if (task.temperature)
        req.set("temperature", json::Value((double)*task.temperature));
      if (task.top_k) req.set("top_k", json::Value((double)*task.top_k));
      auto reply = bus.request(symbiont::subjects::ENGINE_GENERATE, req.dump(),
                               engine_timeout_ms,
                               symbiont::child_headers(msg->headers));
      if (!reply) {
        symbiont::logline("WARN", SERVICE, "engine.generate timed out",
                          msg->headers);
        continue;
      }
      try {
        json::Value r = json::parse(reply->data);
        if (!r.at("error_message").is_null()) {
          symbiont::logline("WARN", SERVICE,
                            "engine error: " + r.at("error_message").as_string(),
                            msg->headers);
          continue;
        }
        text = r.at("text").as_string();
      } catch (const std::exception& e) {
        symbiont::logline("WARN", SERVICE,
                          std::string("bad engine reply: ") + e.what(),
                          msg->headers);
        continue;
      }
    } else {
      // the reference accepts but ignores the prompt (main.rs:120-123 TODO)
      text = markov.generate(task.max_length);
    }

    symbiont::GeneratedTextMessage out;
    out.original_task_id = task.task_id;
    out.generated_text = text;
    out.timestamp_ms = symbiont::now_ms();
    bus.publish(symbiont::subjects::EVENTS_TEXT_GENERATED,
                out.to_json_string(), "", symbiont::child_headers(msg->headers));
  }
  symbiont::logline("INFO", SERVICE, "bus connection closed; exiting");
  return 0;
} catch (const std::exception& e) {
  // bus drop mid-handler etc.: exit cleanly for the supervisor to
  // restart instead of std::terminate aborting with no log
  symbiont::logline("ERROR", SERVICE, std::string("fatal: ") + e.what());
  return 1;
}
