// perception worker — C++ equivalent of the reference's perception_service
// (SURVEY.md §2 checklist item 2; reference:
// services/perception_service/src/main.rs): consumes PerceiveUrlTask,
// fetches the page with a 15s budget + custom UA (main.rs:89-94), extracts
// main content via the selector cascade (html_extract.hpp), publishes
// RawTextMessage to data.raw_text.discovered (main.rs:67-69). Empty
// extractions and fetch failures are dropped with a warning
// (scrape_and_publish, main.rs:15-84).
//
// The fetcher is a raw-socket HTTP/1.1 client; https:// is served by TLS
// over dlopen(libssl) (tls_client.hpp — the image ships OpenSSL runtime
// libraries but no headers, so the API slice is declared by hand). Parity:
// the reference scrapes https via reqwest's TLS (main.rs:89-94). When no
// libssl runtime exists, https falls back to a forward proxy
// (SYMBIONT_HTTP_PROXY) or the Python perception service, with a clear
// error naming both options.
//
// Usage: perception [SYMBIONT_BUS_URL=...] [SYMBIONT_TLS_CA_FILE=...]
//        [SYMBIONT_TLS_INSECURE=1] [SYMBIONT_HTTP_PROXY=...]

#include <fcntl.h>

#include <cstring>
#include <memory>
#include <string>

#include "../../generated/cpp/symbiont_schema.hpp"
#include "common.hpp"
#include "html_extract.hpp"
#include "tls_client.hpp"

namespace {

const char* SERVICE = "perception";

struct Url {
  std::string host;
  int port = 80;
  std::string path = "/";
  bool tls = false;
};

// Host/port/path extraction for either scheme (used for the Host header in
// proxy mode, where https targets are legal — the proxy terminates TLS).
bool parse_any_url(const std::string& url, Url& out, std::string& err) {
  int default_port;
  std::string rest;
  if (url.rfind("http://", 0) == 0) {
    rest = url.substr(7);
    default_port = 80;
    out.tls = false;
  } else if (url.rfind("https://", 0) == 0) {
    rest = url.substr(8);
    default_port = 443;
    out.tls = true;
  } else {
    err = "unsupported scheme";
    return false;
  }
  auto slash = rest.find('/');
  std::string hostport = slash == std::string::npos ? rest : rest.substr(0, slash);
  out.path = slash == std::string::npos ? "/" : rest.substr(slash);
  auto colon = hostport.rfind(':');
  if (colon == std::string::npos) {
    out.host = hostport;
    out.port = default_port;
  } else {
    out.host = hostport.substr(0, colon);
    out.port = std::atoi(hostport.c_str() + colon + 1);
  }
  if (out.host.empty()) {
    err = "empty host";
    return false;
  }
  return true;
}

bool parse_http_url(const std::string& url, Url& out, std::string& err) {
  if (url.rfind("https://", 0) == 0) {
    std::string why;
    if (!symbiont::tls::available(&why)) {
      err = "https needs a TLS runtime and none was found (" + why +
            "); set SYMBIONT_HTTP_PROXY or use the Python perception service";
      return false;
    }
    return parse_any_url(url, out, err);
  }
  if (url.rfind("http://", 0) != 0) {
    err = "unsupported scheme (need http:// or https://)";
    return false;
  }
  return parse_any_url(url, out, err);
}

// Deadline-bounded connect: non-blocking + poll, so an unroutable host costs
// the scrape budget, not the kernel's multi-minute SYN retry cycle. (DNS via
// getaddrinfo has no portable timeout and is assumed fast/local.)
int connect_with_deadline(const std::string& host, int port, int64_t deadline_ms) {
  struct addrinfo hints {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  int rc = ::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints, &res);
  if (rc != 0)
    throw std::runtime_error("resolve " + host + ": " + gai_strerror(rc));
  int fd = -1;
  std::string last_err = "no address";
  for (auto* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype | SOCK_NONBLOCK, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;  // immediate
    if (errno == EINPROGRESS) {
      int wait = (int)(deadline_ms - (int64_t)symbiont::now_ms());
      struct pollfd p {fd, POLLOUT, 0};
      int prc = wait > 0 ? ::poll(&p, 1, wait) : 0;
      int soerr = 0;
      socklen_t len = sizeof(soerr);
      if (prc > 0 &&
          ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &soerr, &len) == 0 &&
          soerr == 0)
        break;  // connected
      last_err = prc == 0 ? "connect timeout" : std::strerror(soerr ? soerr : errno);
    } else {
      last_err = std::strerror(errno);
    }
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  if (fd < 0) throw std::runtime_error("connect " + host + " failed: " + last_err);
  // back to blocking; subsequent reads are poll()-bounded anyway
  int flags = ::fcntl(fd, F_GETFL, 0);
  ::fcntl(fd, F_SETFL, flags & ~O_NONBLOCK);
  return fd;
}

// Minimal HTTP/1.1 GET with Content-Length / close-delimited bodies and
// chunked transfer decoding; follows up to 5 redirects. deadline_ms caps the
// whole scrape (reference: 15s total budget, main.rs:89-91).
std::string http_get(const std::string& url, const std::string& user_agent,
                     int64_t deadline_ms, int redirects_left = 5) {
  Url u;
  std::string err;
  // proxy mode: send the absolute URL through a forward proxy
  std::string proxy = symbiont::env_or("SYMBIONT_HTTP_PROXY", "");
  std::string target_url = url;
  if (!proxy.empty()) {
    if (!parse_http_url(proxy, u, err))
      throw std::runtime_error("bad proxy url: " + err);
  } else if (!parse_http_url(url, u, err)) {
    throw std::runtime_error(err);
  }

  int fd = connect_with_deadline(u.host, u.port, deadline_ms);

  auto remaining = [&]() -> int {
    int64_t left = deadline_ms - (int64_t)symbiont::now_ms();
    return left < 0 ? 0 : (int)left;
  };
  struct FdGuard {
    int fd;
    ~FdGuard() { ::close(fd); }
  } guard{fd};

  // TLS ops run on the blocking socket; SO_RCVTIMEO/SO_SNDTIMEO bound the
  // handshake and every read with what's left of the scrape budget
  std::unique_ptr<symbiont::tls::Conn> tls_conn;
  if (u.tls) {
    int rem = remaining();
    if (rem <= 0) throw std::runtime_error("scrape timeout");
    rem = rem < 1 ? 1 : rem;  // a {0,0} timeval would mean NO timeout
    struct timeval tv {rem / 1000, (rem % 1000) * 1000};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    bool insecure = symbiont::env_or("SYMBIONT_TLS_INSECURE", "") == "1";
    tls_conn = std::make_unique<symbiont::tls::Conn>(
        fd, u.host, /*verify=*/!insecure,
        symbiont::env_or("SYMBIONT_TLS_CA_FILE", ""));
  }

  std::string path_or_url = proxy.empty() ? u.path : target_url;
  Url host_of;
  if (!proxy.empty() && !parse_any_url(target_url, host_of, err))
    throw std::runtime_error("bad target url: " + err);
  const Url& hu = proxy.empty() ? u : host_of;
  std::string req = "GET " + path_or_url + " HTTP/1.1\r\nHost: " + hu.host +
                    "\r\nUser-Agent: " + user_agent +
                    "\r\nAccept: text/html\r\nConnection: close\r\n\r\n";
  if (tls_conn) {
    tls_conn->write_all(req.data(), req.size());
  } else {
    size_t off = 0;
    while (off < req.size()) {
      ssize_t n = ::send(fd, req.data() + off, req.size() - off, 0);
      if (n <= 0) throw std::runtime_error("send failed");
      off += (size_t)n;
    }
  }

  std::string buf;
  char chunk[65536];
  for (;;) {
    int wait = remaining();
    if (wait <= 0) throw std::runtime_error("scrape timeout");
    ssize_t n;
    if (tls_conn) {
      // budget re-armed per read: a slow trickle can't stretch past it
      struct timeval tv {wait / 1000, (wait % 1000) * 1000};
      ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      n = tls_conn->read(chunk, sizeof(chunk));
    } else {
      struct pollfd p {fd, POLLIN, 0};
      int prc = ::poll(&p, 1, wait);
      if (prc == 0) throw std::runtime_error("scrape timeout");
      if (prc < 0) {
        if (errno == EINTR) continue;
        throw std::runtime_error("poll failed");
      }
      n = ::recv(fd, chunk, sizeof(chunk), 0);
      if (n < 0) throw std::runtime_error("recv failed");
    }
    if (n == 0) break;
    buf.append(chunk, (size_t)n);
    if (buf.size() > 32 * 1024 * 1024) throw std::runtime_error("response too large");
  }

  auto hdr_end = buf.find("\r\n\r\n");
  if (hdr_end == std::string::npos) throw std::runtime_error("bad http response");
  std::string headers = buf.substr(0, hdr_end);
  std::string body = buf.substr(hdr_end + 4);

  // status line
  auto sp = headers.find(' ');
  int status = sp == std::string::npos ? 0 : std::atoi(headers.c_str() + sp + 1);

  // header lookup (case-insensitive)
  auto header_value = [&](const std::string& name) -> std::string {
    std::string low = symbiont::html::ascii_lower(headers);
    std::string needle = "\r\n" + symbiont::html::ascii_lower(name) + ":";
    auto at = low.find(needle);
    if (at == std::string::npos) return "";
    auto vstart = at + needle.size();
    auto vend = low.find("\r\n", vstart);
    std::string v = headers.substr(vstart, vend - vstart);
    return symbiont::html::trim_copy(v);
  };

  if (status >= 301 && status <= 308 && status != 304) {
    if (redirects_left <= 0) throw std::runtime_error("too many redirects");
    std::string loc = header_value("Location");
    if (loc.empty()) throw std::runtime_error("redirect without Location");
    if (loc.rfind("http", 0) != 0) {  // relative redirect keeps the scheme
      loc = std::string(hu.tls ? "https://" : "http://") + hu.host +
            (hu.port != 80 && hu.port != 443 ? ":" + std::to_string(hu.port) : "") +
            (loc[0] == '/' ? loc : "/" + loc);
    }
    return http_get(loc, user_agent, deadline_ms, redirects_left - 1);
  }
  if (status < 200 || status >= 300)
    throw std::runtime_error("http status " + std::to_string(status));

  // Truncation guards: a mid-transfer FIN (network failure, or the
  // injected-close attack close_notify exists to prevent — TLS reads map
  // OpenSSL 3's "unexpected eof" to EOF, see tls_client.hpp) must never
  // publish a partial page as complete. Chunked framing requires the
  // terminating 0-chunk; Content-Length bodies must be complete.
  if (symbiont::html::ascii_lower(header_value("Transfer-Encoding"))
          .find("chunked") != std::string::npos) {
    std::string decoded;
    size_t i = 0;
    for (;;) {
      auto eol = body.find("\r\n", i);
      if (eol == std::string::npos)
        throw std::runtime_error("truncated chunked body");
      char* endp = nullptr;
      long len = std::strtol(body.c_str() + i, &endp, 16);
      // strtol returns 0 for garbage too; require at least one hex digit so a
      // malformed chunk-size line can't masquerade as the 0-terminator and
      // pass off a corrupted body as complete (ADVICE r4)
      if (endp == body.c_str() + i || len < 0)
        throw std::runtime_error("bad chunk length");
      if (len == 0) return decoded;  // proper terminator seen
      if (eol + 2 + (size_t)len > body.size())
        throw std::runtime_error("truncated chunked body");
      decoded.append(body, eol + 2, (size_t)len);
      i = eol + 2 + (size_t)len + 2;
      if (i > body.size())
        throw std::runtime_error("truncated chunked body");
    }
  }
  std::string cl = header_value("Content-Length");
  if (!cl.empty()) {
    size_t want = (size_t)std::strtoull(cl.c_str(), nullptr, 10);
    if (body.size() < want)
      throw std::runtime_error(
          "truncated body: " + std::to_string(body.size()) + " of " + cl);
    body.resize(want);  // ignore trailing bytes past the declared length
  } else if (u.tls) {
    // close-delimited https body: no framing means an injected FIN is
    // indistinguishable from a complete page — surface it (ADVICE r4)
    symbiont::logline("WARN", SERVICE,
                      "https body has neither Content-Length nor chunked "
                      "framing; completeness unverifiable: " + target_url);
  }
  return body;
}

}  // namespace

int main() try {
  int timeout_ms = (int)(1000 * std::atof(
      symbiont::env_or("SYMBIONT_PERCEPTION_SCRAPE_TIMEOUT_S", "15").c_str()));
  std::string user_agent = symbiont::env_or(
      "SYMBIONT_PERCEPTION_USER_AGENT", "SymbiontTPU/0.1 (+research crawler)");

  symbus::Client bus;
  if (!symbiont::connect_with_retry(bus, SERVICE)) return 1;
  uint32_t sid = bus.subscribe(symbiont::subjects::TASKS_PERCEIVE_URL,
                               symbiont::subjects::Q_PERCEPTION);
  symbiont::logline("INFO", SERVICE, "ready");

  // fleet liveness: beat `_sys.heartbeat.<role>` so the process supervisor's
  // hang detector covers this shell (SYMBIONT_RUNNER_HEARTBEAT_S > 0)
  symbiont::Heartbeat hb = symbiont::heartbeat_from_env(SERVICE);

  while (bus.connected()) {
    auto msg = bus.next(1000);
    symbiont::maybe_heartbeat(bus, hb);
    if (!msg || msg->sid != sid) continue;
    // expired-deadline drop (Service._run_handler parity). Ingest mints no
    // deadline by default (zero-loss invariant) — this only fires for a
    // client-supplied deadline, exactly like the Python perception service.
    if (symbiont::drop_if_expired(bus, *msg, SERVICE)) continue;
    symbiont::PerceiveUrlTask task;
    try {
      task = symbiont::PerceiveUrlTask::parse(msg->data);
    } catch (const std::exception& e) {
      symbiont::logline("WARN", SERVICE,
                        std::string("bad perceive task: ") + e.what(),
                        msg->headers);
      continue;
    }
    std::string html;
    try {
      html = http_get(task.url, user_agent,
                      (int64_t)symbiont::now_ms() + timeout_ms);
    } catch (const std::exception& e) {
      symbiont::logline("WARN", SERVICE,
                        "scrape failed for " + task.url + ": " + e.what(),
                        msg->headers);
      continue;
    }
    std::string text = symbiont::html::extract_main_text(html);
    if (text.empty()) {
      symbiont::logline("WARN", SERVICE,
                        "no meaningful text extracted from " + task.url,
                        msg->headers);
      continue;
    }
    symbiont::RawTextMessage out;
    out.id = symbiont::uuid4();
    out.source_url = task.url;
    out.raw_text = text;
    out.timestamp_ms = symbiont::now_ms();
    bus.publish(symbiont::subjects::DATA_RAW_TEXT_DISCOVERED,
                out.to_json_string(), "", symbiont::child_headers(msg->headers));
    symbiont::logline("INFO", SERVICE, "published raw text for " + task.url,
                      msg->headers);
  }
  symbiont::logline("INFO", SERVICE, "bus connection closed; exiting");
  return 0;
} catch (const std::exception& e) {
  // bus drop mid-handler etc.: exit cleanly for the supervisor to
  // restart instead of std::terminate aborting with no log
  symbiont::logline("ERROR", SERVICE, std::string("fatal: ") + e.what());
  return 1;
}
