// Shared shell infrastructure for the native C++ workers.
//
// Each worker binary is the C++ equivalent of one reference Rust service
// (SURVEY.md §2 native-components checklist): env config → bus connect →
// subscribe under a queue group → handler loop. Compute and storage stay
// behind the engine.* request-reply plane owned by the Python TPU process
// (symbiont_tpu/services/engine_service.py), so these shells never link
// against JAX or any ML runtime.
#pragma once

#include <algorithm>
#include <array>
#include <cctype>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <map>
#include <mutex>
#include <random>
#include <set>
#include <string>
#include <utility>
#include <vector>

#include "../symbus/client.hpp"

namespace symbiont {

// ---- subjects (mirror of symbiont_tpu/subjects.py; the reference hardcodes
// these per service, e.g. reference: services/api_service/src/main.rs:20-24)
namespace subjects {
inline const char* TASKS_PERCEIVE_URL = "tasks.perceive.url";
inline const char* DATA_RAW_TEXT_DISCOVERED = "data.raw_text.discovered";
inline const char* DATA_TEXT_WITH_EMBEDDINGS = "data.text.with_embeddings";
inline const char* DATA_PROCESSED_TEXT_TOKENIZED = "data.processed_text.tokenized";
inline const char* TASKS_GENERATION_TEXT = "tasks.generation.text";
inline const char* EVENTS_TEXT_GENERATED = "events.text.generated";
inline const char* EVENTS_TEXT_GENERATED_PARTIAL = "events.text.generated.partial";
inline const char* TASKS_GENERATION_CANCEL = "tasks.generation.cancel";
inline const char* TASKS_EMBEDDING_FOR_QUERY = "tasks.embedding.for_query";
inline const char* TASKS_SEARCH_SEMANTIC_REQUEST = "tasks.search.semantic.request";
inline const char* TASKS_SEARCH_GRAPH_REQUEST = "tasks.search.graph.request";
inline const char* ENGINE_EMBED_BATCH = "engine.embed.batch";
inline const char* ENGINE_EMBED_QUERY = "engine.embed.query";
inline const char* ENGINE_RERANK = "engine.rerank";
inline const char* ENGINE_GENERATE = "engine.generate";
inline const char* ENGINE_VECTOR_UPSERT = "engine.vector.upsert";
inline const char* ENGINE_VECTOR_SEARCH = "engine.vector.search";
inline const char* ENGINE_QUERY_SEARCH = "engine.query.search";
inline const char* ENGINE_GRAPH_SAVE = "engine.graph.save";
inline const char* ENGINE_HEALTH = "engine.health";
inline const char* Q_PERCEPTION = "q.perception";
inline const char* Q_PREPROCESSING = "q.preprocessing";
inline const char* Q_VECTOR_MEMORY = "q.vector_memory";
inline const char* Q_KNOWLEDGE_GRAPH = "q.knowledge_graph";
inline const char* Q_TEXT_GENERATOR = "q.text_generator";
}  // namespace subjects

inline const char* TRACE_HEADER = "X-Trace-Id";
inline const char* SPAN_HEADER = "X-Span-Id";
// overload-protection plane (telemetry.py parity): absolute epoch-ms
// deadline + tenant identity, threaded verbatim through child_headers so a
// native hop in a mixed pipeline never strips the admission context
inline const char* DEADLINE_HEADER = "X-Symbiont-Deadline";
inline const char* TENANT_HEADER = "X-Symbiont-Tenant";

inline std::string env_or(const char* key, const std::string& dflt) {
  const char* v = std::getenv(key);
  return (v && *v) ? std::string(v) : dflt;
}

// RFC-4122 text form from a 128-bit value, with the version nibble and
// variant bits forced (shared by random uuid4 and deterministic point ids).
inline std::string format_uuid(uint64_t hi, uint64_t lo, unsigned version) {
  hi = (hi & 0xffffffffffff0fffULL) | ((uint64_t)version << 12);
  lo = (lo & 0x3fffffffffffffffULL) | 0x8000000000000000ULL;  // variant 10
  char out[37];
  std::snprintf(out, sizeof(out),
                "%08x-%04x-%04x-%04x-%04x%08x",
                (uint32_t)(hi >> 32), (uint32_t)((hi >> 16) & 0xffff),
                (uint32_t)(hi & 0xffff), (uint32_t)(lo >> 48),
                (uint32_t)((lo >> 32) & 0xffff), (uint32_t)(lo & 0xffffffff));
  return std::string(out);
}

// uuid4 (same shape as the Python side's generate_uuid)
inline std::string uuid4() {
  static thread_local std::mt19937_64 rng{std::random_device{}()};
  return format_uuid(rng(), rng(), 4);
}

// Deterministic UUID-shaped id for a (document, sentence_order) pair —
// byte-for-byte identical to Python's utils.ids.deterministic_point_id, so a
// durable redelivery (or a mixed Python/C++ queue group) overwrites the same
// vector point instead of duplicating it.
inline uint64_t fnv1a64(const std::string& data) {
  uint64_t h = 0xCBF29CE484222325ULL;
  for (unsigned char b : data) h = (h ^ b) * 0x100000001B3ULL;
  return h;
}

inline std::string deterministic_point_id(const std::string& doc_id,
                                          uint64_t order) {
  std::string key = doc_id + '\0' + std::to_string(order);
  return format_uuid(fnv1a64(key), fnv1a64(key + '\1'), 5);
}

inline uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (uint64_t)ts.tv_sec * 1000 + (uint64_t)ts.tv_nsec / 1000000;
}

// Trace propagation: same trace, same ACTIVE span id (telemetry.child_headers
// parity — the span-id header names the span under which the message was
// published; a bus hop is an edge in the trace tree, not a span of its own).
// Native workers record no spans, so propagating verbatim is what keeps a
// mixed Python/native pipeline's downstream handler spans linked to the last
// recording hop instead of to a fresh id nobody owns.
inline std::map<std::string, std::string> child_headers(
    const std::map<std::string, std::string>& parent) {
  std::map<std::string, std::string> h;
  auto it = parent.find(TRACE_HEADER);
  if (it == parent.end()) {  // no context: start a fresh trace
    h[TRACE_HEADER] = uuid4();
    h[SPAN_HEADER] = uuid4();
  } else {
    h[TRACE_HEADER] = it->second;
    auto sp = parent.find(SPAN_HEADER);
    h[SPAN_HEADER] = sp != parent.end() ? sp->second : uuid4();
  }
  // admission context threads verbatim (telemetry.child_headers parity):
  // the deadline minted at the API edge must reach the LAST hop
  for (const char* key : {DEADLINE_HEADER, TENANT_HEADER}) {
    auto v = parent.find(key);
    if (v != parent.end()) h[key] = v->second;
  }
  return h;
}

// ---- expired-deadline drop (Service._run_handler parity, PR 9/10) -------
//
// The edge mints X-Symbiont-Deadline (absolute epoch ms) and child_headers
// threads it through every hop. A delivery whose deadline has passed is
// DEAD WORK: the caller already gave up, so a mid-pipeline C++ worker must
// not burn capacity on it — drop BEFORE the handler body runs, ACK on
// durable streams (expiry is the caller giving up, not a handler failure:
// never retried, never dead-lettered), exactly like the Python services.
// An unparseable deadline is NO deadline (garbage must not make work
// immortal OR instantly dead).

inline bool deadline_expired(const std::map<std::string, std::string>& headers) {
  auto it = headers.find(DEADLINE_HEADER);
  if (it == headers.end()) return false;
  char* end = nullptr;
  double dl = std::strtod(it->second.c_str(), &end);
  if (end == it->second.c_str()) return false;  // unparseable: no deadline
  return (double)now_ms() > dl;
}

// Structured one-line log: ts level service msg key=value... trace=...
inline void logline(const char* level, const std::string& service,
                    const std::string& msg,
                    const std::map<std::string, std::string>& headers = {}) {
  auto it = headers.find(TRACE_HEADER);
  std::fprintf(stderr, "%llu %s %s %s trace=%s\n",
               (unsigned long long)now_ms(), level, service.c_str(),
               msg.c_str(),
               it != headers.end() ? it->second.c_str() : "-");
}

// The ack half of the expired-deadline drop (declared after logline — see
// deadline_expired above): returns true when the delivery was expired (and
// therefore acked + consumed); the worker loop `continue`s past it.
// bus.ack is a no-op on non-durable deliveries (no X-Symbus-* headers), so
// this is safe on every subject, request-reply included — an expired
// request gets NO reply, the caller's timeout already fired.
inline bool drop_if_expired(symbus::Client& bus, const symbus::BusMsg& msg,
                            const std::string& service) {
  if (!deadline_expired(msg.headers)) return false;
  logline("INFO", service,
          "dropping expired work on " + msg.subject +
              " (deadline passed; acked, never retried)",
          msg.headers);
  bus.ack(msg);
  return true;
}

// ---- fleet liveness heartbeats (runner.py _heartbeat_loop parity) -------
//
// The process supervisor (symbiont_tpu/resilience/procsup.py) judges hang
// liveness on `_sys.heartbeat.<role>` — the signal a SIGSTOPped or
// deadlocked worker cannot fake. Python runners beat when
// SYMBIONT_RUNNER_HEARTBEAT_S > 0; these helpers give the C++ shells the
// SAME contract (subject + payload byte-parity pinned by
// tests/test_fleet.py's stub-json harness, which compiles on GCC 10 — no
// json.hpp, no float to_chars), so procsup hang-detection and the
// GET /api/fleet roll-up cover native workers, not just Python ones.

inline const char* SYS_HEARTBEAT = "_sys.heartbeat";

inline std::string heartbeat_subject(const std::string& role) {
  return std::string(SYS_HEARTBEAT) + "." + role;
}

inline std::string heartbeat_payload(const std::string& role,
                                     bool draining = false) {
  // byte-for-byte what the Python runner publishes:
  // json.dumps({"role": role, "pid": os.getpid(),
  //             "capacity": 0|1, "draining": false|true})
  // capacity/draining are the elastic-autoscaler fields (resilience/
  // autoscale.py): capacity 1 = serving, 0 = draining out. The C++
  // shells do not implement the drain protocol yet, so they always beat
  // serving — the supervisor retires them with the SIGTERM fallback.
  std::string out = "{\"role\": \"";
  for (char c : role) {
    if (c == '"' || c == '\\') out.push_back('\\');
    out.push_back(c);
  }
  out += "\", \"pid\": " + std::to_string((long)getpid()) +
         ", \"capacity\": " + (draining ? "0" : "1") +
         ", \"draining\": " + (draining ? "true" : "false") + "}";
  return out;
}

struct Heartbeat {
  std::string role;
  uint64_t interval_ms = 0;  // 0 = disabled (the default, like Python)
  uint64_t last_ms = 0;
};

inline Heartbeat heartbeat_from_env(const std::string& default_role) {
  Heartbeat hb;
  hb.role = env_or("SYMBIONT_RUNNER_ROLE", default_role);
  double s = std::atof(env_or("SYMBIONT_RUNNER_HEARTBEAT_S", "0").c_str());
  if (s > 0) hb.interval_ms = (uint64_t)(s * 1000.0);
  return hb;
}

// Call once per worker-loop iteration (the loops wake at least every
// bus.next timeout): publishes at most once per interval, and a publish
// failure is a skipped beat, never a crash — the supervisor treats a
// missing beat as evidence, and a broker gap already suppresses hang
// verdicts fleet-wide.
inline void maybe_heartbeat(symbus::Client& bus, Heartbeat& hb) {
  if (hb.interval_ms == 0) return;
  uint64_t now = now_ms();
  if (hb.last_ms != 0 && now - hb.last_ms < hb.interval_ms) return;
  hb.last_ms = now;
  try {
    bus.publish(heartbeat_subject(hb.role), heartbeat_payload(hb.role));
  } catch (const std::exception&) {
    // skip this beat; the client reconnects on its own backoff
  }
}

// ---- per-tenant admission (resilience/admission.py parity) ---------------
//
// The C++ gateway was the ONE ingress where a hot tenant could bypass the
// overload-protection plane entirely (ROADMAP item 1's last named
// admission gap): per-tenant token buckets per request class (ingest /
// search / generate, tenant from X-Symbiont-Tenant), exhaustion answered
// 429 + Retry-After, and the client-suppliable tenant universe BOUNDED —
// past max_tenants every new identity shares the "(overflow)" bucket, so
// minting fresh tenant headers buys no fresh burst and grows no state.
// Header-only and json-free so the GCC10 stub-json harness
// (tests/test_native_services.py) can compile AND run it.

struct TokenBucket {
  double rate = 1.0, burst = 1.0, tokens = 1.0;
  int64_t last_ms = 0;

  void refill(int64_t now_ms) {
    tokens = std::min(burst, tokens + (now_ms - last_ms) / 1000.0 * rate);
    last_ms = now_ms;
  }
  bool try_take(int64_t now_ms) {
    refill(now_ms);
    if (tokens >= 1.0) {
      tokens -= 1.0;
      return true;
    }
    return false;
  }
  double retry_after_s(int64_t now_ms) {
    refill(now_ms);
    return (1.0 - tokens) / rate > 0.0 ? (1.0 - tokens) / rate : 0.0;
  }
};

class AdmissionGate {
 public:
  enum Class { INGEST = 0, SEARCH = 1, GENERATE = 2 };

  // read SYMBIONT_ADMISSION_* (defaults in lockstep with AdmissionConfig,
  // symbiont_tpu/config.py; knob rows in docs/RESILIENCE.md)
  void configure() {
    std::string on = env_or("SYMBIONT_ADMISSION_ENABLED", "true");
    enabled_ = (on != "false" && on != "0" && on != "no");
    rate_[INGEST] = env_num("SYMBIONT_ADMISSION_INGEST_RATE", 200.0);
    burst_[INGEST] = env_num("SYMBIONT_ADMISSION_INGEST_BURST", 400.0);
    rate_[SEARCH] = env_num("SYMBIONT_ADMISSION_SEARCH_RATE", 100.0);
    burst_[SEARCH] = env_num("SYMBIONT_ADMISSION_SEARCH_BURST", 200.0);
    rate_[GENERATE] = env_num("SYMBIONT_ADMISSION_GENERATE_RATE", 20.0);
    burst_[GENERATE] = env_num("SYMBIONT_ADMISSION_GENERATE_BURST", 40.0);
    max_tenants_ = (size_t)env_num("SYMBIONT_ADMISSION_MAX_TENANTS", 1024.0);
    for (int c = 0; c < 3; ++c) {
      if (rate_[c] <= 0 || burst_[c] <= 0) {
        // a typo'd knob must not silently admit everything at rate 0 —
        // the loudest stance a process without a config validator has
        logline("ERROR", "admission",
                "rate/burst must be positive; using class defaults");
        rate_[c] = c == INGEST ? 200.0 : c == SEARCH ? 100.0 : 20.0;
        burst_[c] = 2 * rate_[c];
      }
    }
  }

  bool enabled() const { return enabled_; }
  uint64_t tenant_overflows() const { return overflow_; }

  // one admission decision; on refusal returns false and sets
  // *retry_after_s (the Retry-After hint a 429 carries). now_ms defaults
  // to the steady clock; injectable for the compile-harness test.
  bool admit(Class klass, const std::string& raw_tenant,
             double* retry_after_s, int64_t now_ms = -1) {
    if (!enabled_) return true;
    if (now_ms < 0)
      now_ms = (int64_t)std::chrono::duration_cast<
                   std::chrono::milliseconds>(
                   std::chrono::steady_clock::now().time_since_epoch())
                   .count();
    std::lock_guard<std::mutex> g(mu_);
    std::string tenant = resolve_locked(raw_tenant);
    auto key = std::make_pair(tenant, (int)klass);
    auto it = buckets_.find(key);
    if (it == buckets_.end()) {
      TokenBucket b;
      b.rate = rate_[klass];
      b.burst = burst_[klass];
      b.tokens = b.burst;
      b.last_ms = now_ms;
      it = buckets_.emplace(key, b).first;
    }
    if (it->second.try_take(now_ms)) return true;
    if (retry_after_s) *retry_after_s = it->second.retry_after_s(now_ms);
    return false;
  }

 private:
  static double env_num(const char* name, double dflt) {
    std::string v = env_or(name, "");
    return v.empty() ? dflt : std::atof(v.c_str());
  }

  // bounded tenant universe (admission.py resolve_tenant): known tenants
  // resolve to themselves; past the bound every NEW identity shares one
  // overflow bucket set
  std::string resolve_locked(const std::string& tenant) {
    if (seen_.count(tenant)) return tenant;
    if (seen_.size() >= max_tenants_) {
      ++overflow_;
      return "(overflow)";
    }
    seen_.insert(tenant);
    return tenant;
  }

  bool enabled_ = true;
  double rate_[3] = {200.0, 100.0, 20.0};
  double burst_[3] = {400.0, 200.0, 40.0};
  size_t max_tenants_ = 1024;
  uint64_t overflow_ = 0;
  std::mutex mu_;
  std::map<std::pair<std::string, int>, TokenBucket> buckets_;
  std::set<std::string> seen_{"default"};
};

// tenant identity from LOWERCASED http headers (the gateway lowercases
// keys on read; admission.py tenant_of parity: trim, default tenant)
inline std::string http_tenant_of(
    const std::map<std::string, std::string>& headers) {
  auto it = headers.find("x-symbiont-tenant");
  if (it == headers.end()) return "default";
  const std::string& t = it->second;
  size_t b = t.find_first_not_of(" \t");
  if (b == std::string::npos) return "default";
  return t.substr(b, t.find_last_not_of(" \t") - b + 1);
}

// Bus URL: symbus://host:port (nats:// accepted as a reference-era alias,
// same stance as symbiont_tpu/bus/connect.py).
struct BusAddr {
  std::string host = "127.0.0.1";
  int port = 4233;
};

inline BusAddr parse_bus_url(const std::string& url) {
  BusAddr a;
  std::string rest = url;
  auto scheme = rest.find("://");
  if (scheme != std::string::npos) rest = rest.substr(scheme + 3);
  while (!rest.empty() && rest.back() == '/') rest.pop_back();
  auto colon = rest.rfind(':');
  if (colon == std::string::npos) {
    if (!rest.empty()) a.host = rest;
  } else {
    if (colon > 0) a.host = rest.substr(0, colon);
    a.port = std::atoi(rest.c_str() + colon + 1);
  }
  return a;
}

// Connect with retry — the reference's clients retry their backends at
// startup (e.g. reference: services/vector_memory_service/src/main.rs:505-532,
// 5 attempts x 5s); workers outliving broker restarts matters more here.
inline bool connect_with_retry(symbus::Client& c, const std::string& service,
                               int attempts = 30, int delay_ms = 1000) {
  BusAddr addr = parse_bus_url(env_or("SYMBIONT_BUS_URL",
                                      env_or("NATS_URL", "symbus://127.0.0.1:4233")));
  for (int i = 0; i < attempts; ++i) {
    try {
      c.connect(addr.host, addr.port);
      logline("INFO", service,
              "connected to bus " + addr.host + ":" + std::to_string(addr.port));
      return true;
    } catch (const std::exception& e) {
      logline("WARN", service, std::string("bus connect failed: ") + e.what());
      struct timespec ts {delay_ms / 1000, (long)(delay_ms % 1000) * 1000000};
      nanosleep(&ts, nullptr);
    }
  }
  return false;
}

// Engine request-reply unwrap shared by the worker shells: request, throw on
// timeout, parse, throw on a non-null error_message (the engine plane's typed
// error convention, symbiont_tpu/services/engine_service.py).
inline json::Value engine_call(symbus::Client& bus, const char* subject,
                               const json::Value& req, int timeout_ms,
                               const std::map<std::string, std::string>& headers) {
  auto reply = bus.request(subject, req.dump(), timeout_ms, headers);
  if (!reply) throw std::runtime_error(std::string(subject) + " timed out");
  json::Value r = json::parse(reply->data);
  if (!r.at("error_message").is_null())
    throw std::runtime_error("engine error: " +
                             r.at("error_message").as_string());
  return r;
}

// base64 decode (standard alphabet, '=' padding) — the engine plane's
// compact vector encoding: engine.embed.batch with {"encoding": "b64"}
// replies with the [n, dim] f32 little-endian array base64'd instead of
// ~10 bytes of JSON digits per float (symbiont_tpu/services/engine_service
// .py::_embed_batch). Both ends of this wire are little-endian (x86/arm64).
inline std::string b64_encode(const unsigned char* data, size_t n) {
  static const char* a =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  out.reserve((n + 2) / 3 * 4);
  size_t i = 0;
  for (; i + 3 <= n; i += 3) {
    uint32_t v = (uint32_t)data[i] << 16 | (uint32_t)data[i + 1] << 8 |
                 (uint32_t)data[i + 2];
    out.push_back(a[(v >> 18) & 63]);
    out.push_back(a[(v >> 12) & 63]);
    out.push_back(a[(v >> 6) & 63]);
    out.push_back(a[v & 63]);
  }
  if (i < n) {
    uint32_t v = (uint32_t)data[i] << 16;
    bool two = i + 1 < n;
    if (two) v |= (uint32_t)data[i + 1] << 8;
    out.push_back(a[(v >> 18) & 63]);
    out.push_back(a[(v >> 12) & 63]);
    out.push_back(two ? a[(v >> 6) & 63] : '=');
    out.push_back('=');
  }
  return out;
}

inline std::vector<unsigned char> b64_decode(const std::string& s) {
  static const auto table = [] {
    std::array<int8_t, 256> t;
    t.fill(-1);
    const char* a =
        "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
    for (int i = 0; i < 64; ++i) t[(unsigned char)a[i]] = (int8_t)i;
    return t;
  }();
  std::vector<unsigned char> out;
  out.reserve(s.size() / 4 * 3);
  uint32_t acc = 0;
  int bits = 0;
  for (char c : s) {
    if (c == '=' || c == '\n' || c == '\r') continue;
    int8_t v = table[(unsigned char)c];
    if (v < 0) throw std::runtime_error("invalid base64 input");
    acc = (acc << 6) | (uint32_t)v;
    bits += 6;
    if (bits >= 8) {
      bits -= 8;
      out.push_back((unsigned char)((acc >> bits) & 0xFF));
    }
  }
  return out;
}

// ---- binary tensor frames (mirror of symbiont_tpu/schema/frames.py) -----
//
// A frame is a fixed 16-byte header + packed little-endian f32 rows,
// APPENDED to the ordinary JSON message body; the X-Symbiont-Frame header
// ("tensor/f32;off=<n>", n = JSON prefix length) announces it. Golden-byte
// fixtures in tests/test_frames.py pin this layout against the Python
// codec. Both ends of this wire are little-endian (x86/arm64) — the same
// stance the b64 vector encoding above already takes.
inline const char* FRAME_HEADER = "X-Symbiont-Frame";
// Reply-frame negotiation on reference-parity request-reply subjects
// (tasks.embedding.for_query): the requester announces frame capability
// with this header ("1"); a peer that ignores it replies JSON float lists
// and every requester accepts both forms (schema/frames.py wants_frame).
inline const char* ACCEPT_FRAME_HEADER = "X-Symbiont-Accept-Frame";
constexpr size_t FRAME_HDR_LEN = 16;
constexpr uint8_t FRAME_VERSION = 1;
constexpr uint8_t FRAME_DTYPE_F32 = 1;
// IEEE half rows — half the bytes/embedding (mirror of frames.DTYPE_F16).
// A dtype byte outside this set throws on decode: the delivery stays
// unacked for redelivery/DLQ instead of being misparsed.
constexpr uint8_t FRAME_DTYPE_F16 = 2;

inline size_t frame_elem_size(uint8_t dtype) {
  if (dtype == FRAME_DTYPE_F32) return 4;
  if (dtype == FRAME_DTYPE_F16) return 2;
  throw std::runtime_error("unsupported frame dtype " +
                           std::to_string((int)dtype));
}

// IEEE 754 binary16 → binary32 (bit-exact, subnormals and inf/nan
// included) — the decode half of the f16 wire form. The ENCODE direction
// never runs in C++: the shells either forward raw f16 payload bytes
// (vector_memory) or re-slice an engine reply that was already f16
// (preprocessing requested the frame16 encoding), so no C++ rounding mode
// can ever disagree with numpy's.
inline float half_to_float(uint16_t h) {
  uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1Fu;
  uint32_t mant = h & 0x3FFu;
  uint32_t bits;
  if (exp == 0) {
    if (mant == 0) {
      bits = sign;  // ±0
    } else {
      // subnormal half (value = mant·2⁻²⁴) → normalized float: after s
      // left-shifts the implicit bit lands, so the unbiased exponent is
      // −14−s and the float field is 127−14−s = 113−s
      int shift = 0;
      while (!(mant & 0x400u)) {
        mant <<= 1;
        ++shift;
      }
      mant &= 0x3FFu;
      bits = sign | ((uint32_t)(113 - shift) << 23) | (mant << 13);
    }
  } else if (exp == 0x1Fu) {
    bits = sign | 0x7F800000u | (mant << 13);  // inf / nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  std::memcpy(&out, &bits, sizeof out);
  return out;
}

inline void put_u16le(std::string& out, uint16_t v) {
  out.push_back((char)(v & 0xff));
  out.push_back((char)(v >> 8));
}

inline void put_u32le(std::string& out, uint32_t v) {
  for (int i = 0; i < 4; ++i) out.push_back((char)((v >> (8 * i)) & 0xff));
}

inline uint32_t get_u32le(const char* p) {
  return (uint32_t)(unsigned char)p[0] | (uint32_t)(unsigned char)p[1] << 8 |
         (uint32_t)(unsigned char)p[2] << 16 |
         (uint32_t)(unsigned char)p[3] << 24;
}

// Header + raw payload (`raw` must hold rows*cols little-endian elements
// of `dtype` — 4 bytes each for f32, 2 for f16).
inline std::string make_frame(const std::string& raw, uint32_t rows,
                              uint32_t cols,
                              uint8_t dtype = FRAME_DTYPE_F32) {
  if (raw.size() != (size_t)rows * cols * frame_elem_size(dtype))
    throw std::runtime_error("frame payload size mismatch");
  std::string out;
  out.reserve(FRAME_HDR_LEN + raw.size());
  out += "SYTF";
  out.push_back((char)FRAME_VERSION);
  out.push_back((char)dtype);
  put_u16le(out, 0);  // reserved
  put_u32le(out, rows);
  put_u32le(out, cols);
  out += raw;
  return out;
}

inline std::string frame_header_value(size_t json_len,
                                      uint8_t dtype = FRAME_DTYPE_F32) {
  return std::string(dtype == FRAME_DTYPE_F16 ? "tensor/f16" : "tensor/f32")
      + ";off=" + std::to_string(json_len);
}

// View into a frame-bearing body (payload points INTO the body string).
struct FrameView {
  uint32_t rows = 0;
  uint32_t cols = 0;
  uint8_t dtype = FRAME_DTYPE_F32;
  const char* payload = nullptr;
  size_t payload_len = 0;
  size_t elem_size() const { return frame_elem_size(dtype); }
};

// Split a possibly-frame-bearing body. Returns false (json_part = whole
// body) when no frame header is present — the JSON fallback. Throws on a
// malformed header or truncated frame (the delivery stays unacked).
inline bool split_frame(const std::map<std::string, std::string>& headers,
                        const std::string& body, std::string& json_part,
                        FrameView& frame) {
  auto it = headers.find(FRAME_HEADER);
  if (it == headers.end()) {
    json_part = body;
    return false;
  }
  const std::string& v = it->second;
  if (v.rfind("tensor/f32", 0) != 0 && v.rfind("tensor/f16", 0) != 0)
    throw std::runtime_error("unknown frame content type: " + v);
  auto off_pos = v.find("off=");
  if (off_pos == std::string::npos)
    throw std::runtime_error("frame header missing off=: " + v);
  long long off = std::atoll(v.c_str() + off_pos + 4);
  if (off < 0 || (size_t)off + FRAME_HDR_LEN > body.size())
    throw std::runtime_error("frame offset beyond body");
  const char* p = body.data() + off;
  if (std::memcmp(p, "SYTF", 4) != 0)
    throw std::runtime_error("bad frame magic");
  if ((uint8_t)p[4] != FRAME_VERSION)
    throw std::runtime_error("unsupported frame version");
  if ((uint8_t)p[5] != FRAME_DTYPE_F32 && (uint8_t)p[5] != FRAME_DTYPE_F16)
    throw std::runtime_error("unsupported frame dtype");
  frame.dtype = (uint8_t)p[5];
  frame.rows = get_u32le(p + 8);
  frame.cols = get_u32le(p + 12);
  frame.payload = p + FRAME_HDR_LEN;
  frame.payload_len = (size_t)frame.rows * frame.cols * frame.elem_size();
  if ((size_t)off + FRAME_HDR_LEN + frame.payload_len > body.size())
    throw std::runtime_error("frame payload truncated");
  json_part.assign(body.data(), (size_t)off);
  return true;
}

// Frame payload → [rows][cols] float rows (f32: memcpy per row; f16:
// bit-exact upconvert per element — no text parse either way).
inline std::vector<std::vector<float>> frame_rows(const FrameView& f) {
  std::vector<std::vector<float>> rows(f.rows);
  for (uint32_t i = 0; i < f.rows; ++i) {
    rows[i].resize(f.cols);
    if (f.dtype == FRAME_DTYPE_F16) {
      const char* src = f.payload + (size_t)i * f.cols * 2;
      for (uint32_t j = 0; j < f.cols; ++j) {
        uint16_t h = (uint16_t)(unsigned char)src[2 * j] |
                     (uint16_t)(unsigned char)src[2 * j + 1] << 8;
        rows[i][j] = half_to_float(h);
      }
    } else {
      std::memcpy(rows[i].data(),
                  f.payload + (size_t)i * f.cols * sizeof(float),
                  f.cols * sizeof(float));
    }
  }
  return rows;
}

// Frames deployment knob, mirror of schema.frames.frames_mode: 0 = off
// (reference wire JSON), FRAME_DTYPE_F32 = default frames, FRAME_DTYPE_F16
// = half-width frames (SYMBIONT_FRAMES=f16 — deploy only when every
// consumer on the subject decodes dtype 2).
inline uint8_t frames_mode() {
  std::string v = env_or("SYMBIONT_FRAMES", "");
  // normalize exactly like frames.frames_mode (strip + lowercase): the two
  // halves of one deployment knob must read "OFF" / " f16" / "off\r\n"
  // (CRLF env files) identically — strip ALL whitespace, like str.strip()
  const char* ws = " \t\r\n\f\v";
  size_t a = v.find_first_not_of(ws);
  size_t b = v.find_last_not_of(ws);
  v = (a == std::string::npos) ? "" : v.substr(a, b - a + 1);
  for (char& c : v) c = (char)std::tolower((unsigned char)c);
  if (v == "0" || v == "false" || v == "no" || v == "off") return 0;
  if (v == "f16") return FRAME_DTYPE_F16;
  return FRAME_DTYPE_F32;
}

inline bool frames_enabled() { return frames_mode() != 0; }

// Decode an engine embed reply into [n][dim] float rows. Accepts either the
// compact b64 form ({"vectors_b64", "count", "dim"}) or the plain JSON
// array-of-arrays form ({"vectors"}), so callers work against old and new
// engine processes alike.
inline std::vector<std::vector<float>> decode_vectors(const json::Value& r) {
  std::vector<std::vector<float>> vectors;
  if (r.has("vectors_b64")) {
    auto bytes = b64_decode(r.at("vectors_b64").as_string());
    size_t n = (size_t)r.at("count").as_number();
    size_t dim = (size_t)r.at("dim").as_number();
    if (bytes.size() != n * dim * sizeof(float))
      throw std::runtime_error("b64 vector payload size mismatch");
    vectors.resize(n);
    for (size_t i = 0; i < n; ++i) {
      vectors[i].resize(dim);
      std::memcpy(vectors[i].data(), bytes.data() + i * dim * sizeof(float),
                  dim * sizeof(float));
    }
    return vectors;
  }
  for (const auto& row : r.at("vectors").as_array()) {
    std::vector<float> v;
    v.reserve(row.as_array().size());
    for (const auto& x : row.as_array()) v.push_back((float)x.as_number());
    vectors.push_back(std::move(v));
  }
  return vectors;
}

// Durable pipeline opt-in (SYMBIONT_BUS_DURABLE=1): ensure the shared
// "pipeline" stream exists (idempotent; mirrors the Python runner's setup).
// Returns true when durable mode is on.
inline bool maybe_setup_pipeline_stream(symbus::Client& bus) {
  if (env_or("SYMBIONT_BUS_DURABLE", "") != "1") return false;
  int64_t ack_wait_ms = std::atoll(
      env_or("SYMBIONT_BUS_DURABLE_ACK_WAIT_MS", "60000").c_str());
  uint32_t max_deliver = (uint32_t)std::atoi(
      env_or("SYMBIONT_BUS_DURABLE_MAX_DELIVER", "5").c_str());
  bus.add_stream("pipeline",
                 {subjects::DATA_RAW_TEXT_DISCOVERED,
                  subjects::DATA_TEXT_WITH_EMBEDDINGS,
                  subjects::DATA_PROCESSED_TEXT_TOKENIZED},
                 ack_wait_ms, max_deliver);
  return true;
}

}  // namespace symbiont
