// tls_client.hpp — TLS for the native perception fetcher via dlopen(libssl).
//
// The build image ships OpenSSL *runtime* libraries but no headers, so the
// needed slice of the libssl/libcrypto API is declared by hand and resolved
// with dlsym at first use. If no usable libssl is present the runtime reports
// unavailable and the caller falls back to proxy mode — the worker still
// builds and runs everywhere. Parity target: the reference scrapes https via
// reqwest's native TLS (reference: services/perception_service/src/main.rs:89-94).
//
// Verification defaults to ON (system CA paths + hostname check);
//   SYMBIONT_TLS_CA_FILE=<pem>   adds/overrides the trust anchor (tests use a
//                                self-signed listener),
//   SYMBIONT_TLS_INSECURE=1      disables verification entirely.

#pragma once

#include <dlfcn.h>

#include <memory>
#include <stdexcept>
#include <string>
#include <type_traits>

namespace symbiont {
namespace tls {

// Opaque OpenSSL types — only ever handled through pointers.
struct SSL_CTX;
struct SSL;
struct SSL_METHOD;
struct X509_VERIFY_PARAM;

class Runtime {
 public:
  // nullptr when no usable libssl could be loaded (error in `why`).
  static Runtime* get(std::string* why = nullptr) {
    static Runtime* inst = load(&load_error());
    if (!inst && why) *why = load_error();
    return inst;
  }

  const SSL_METHOD* (*TLS_client_method)() = nullptr;
  SSL_CTX* (*SSL_CTX_new)(const SSL_METHOD*) = nullptr;
  void (*SSL_CTX_free)(SSL_CTX*) = nullptr;
  void (*SSL_CTX_set_verify)(SSL_CTX*, int, void*) = nullptr;
  int (*SSL_CTX_set_default_verify_paths)(SSL_CTX*) = nullptr;
  int (*SSL_CTX_load_verify_locations)(SSL_CTX*, const char*, const char*) = nullptr;
  SSL* (*SSL_new)(SSL_CTX*) = nullptr;
  void (*SSL_free)(SSL*) = nullptr;
  int (*SSL_set_fd)(SSL*, int) = nullptr;
  int (*SSL_connect)(SSL*) = nullptr;
  int (*SSL_read)(SSL*, void*, int) = nullptr;
  int (*SSL_write)(SSL*, const void*, int) = nullptr;
  int (*SSL_shutdown)(SSL*) = nullptr;
  int (*SSL_get_error)(const SSL*, int) = nullptr;
  long (*SSL_ctrl)(SSL*, int, long, void*) = nullptr;
  X509_VERIFY_PARAM* (*SSL_get0_param)(SSL*) = nullptr;
  int (*X509_VERIFY_PARAM_set1_host)(X509_VERIFY_PARAM*, const char*, size_t) = nullptr;
  int (*X509_VERIFY_PARAM_set1_ip_asc)(X509_VERIFY_PARAM*, const char*) = nullptr;
  unsigned long (*ERR_get_error)() = nullptr;
  void (*ERR_error_string_n)(unsigned long, char*, size_t) = nullptr;

  // Pops the queue head; 0 when empty/unavailable.
  unsigned long last_error_code() const {
    return ERR_get_error ? ERR_get_error() : 0;
  }

  std::string error_string(unsigned long code) const {
    if (code == 0 || !ERR_error_string_n) return "unknown TLS error";
    char buf[256] = {0};
    ERR_error_string_n(code, buf, sizeof(buf));
    return buf;
  }

  std::string last_error() const { return error_string(last_error_code()); }

 private:
  static std::string& load_error() {
    static std::string err;
    return err;
  }

  static Runtime* load(std::string* err) {
    // RTLD_GLOBAL so libssl's own libcrypto dependency satisfies the ERR_*
    // symbols too (they live in libcrypto).
    void* h = nullptr;
    for (const char* name : {"libssl.so.3", "libssl.so.1.1", "libssl.so"}) {
      h = ::dlopen(name, RTLD_NOW | RTLD_GLOBAL);
      if (h) break;
    }
    if (!h) {
      *err = "no libssl runtime found (dlopen failed)";
      return nullptr;
    }
    auto* rt = new Runtime();
    auto sym = [&](const char* n) { return ::dlsym(h, n); };
    bool ok = true;
    auto req = [&](auto& fn, const char* n) {
      fn = reinterpret_cast<std::remove_reference_t<decltype(fn)>>(sym(n));
      if (!fn) ok = false;
    };
    req(rt->TLS_client_method, "TLS_client_method");
    req(rt->SSL_CTX_new, "SSL_CTX_new");
    req(rt->SSL_CTX_free, "SSL_CTX_free");
    req(rt->SSL_CTX_set_verify, "SSL_CTX_set_verify");
    req(rt->SSL_CTX_set_default_verify_paths, "SSL_CTX_set_default_verify_paths");
    req(rt->SSL_CTX_load_verify_locations, "SSL_CTX_load_verify_locations");
    req(rt->SSL_new, "SSL_new");
    req(rt->SSL_free, "SSL_free");
    req(rt->SSL_set_fd, "SSL_set_fd");
    req(rt->SSL_connect, "SSL_connect");
    req(rt->SSL_read, "SSL_read");
    req(rt->SSL_write, "SSL_write");
    req(rt->SSL_shutdown, "SSL_shutdown");
    req(rt->SSL_get_error, "SSL_get_error");
    req(rt->SSL_ctrl, "SSL_ctrl");
    req(rt->SSL_get0_param, "SSL_get0_param");
    req(rt->X509_VERIFY_PARAM_set1_host, "X509_VERIFY_PARAM_set1_host");
    req(rt->X509_VERIFY_PARAM_set1_ip_asc, "X509_VERIFY_PARAM_set1_ip_asc");
    // ERR_* come from libcrypto; resolve via the default namespace (pulled
    // in by RTLD_GLOBAL above). Optional: errors degrade to "unknown".
    rt->ERR_get_error =
        reinterpret_cast<unsigned long (*)()>(::dlsym(RTLD_DEFAULT, "ERR_get_error"));
    rt->ERR_error_string_n = reinterpret_cast<void (*)(unsigned long, char*, size_t)>(
        ::dlsym(RTLD_DEFAULT, "ERR_error_string_n"));
    if (!ok) {
      *err = "libssl loaded but required symbols missing";
      delete rt;
      return nullptr;
    }
    return rt;
  }
};

// One TLS connection over an already-connected blocking socket. The socket's
// SO_RCVTIMEO/SO_SNDTIMEO (set by the caller from its deadline budget) bound
// every handshake/read/write.
class Conn {
 public:
  // Throws std::runtime_error on handshake/verification failure.
  Conn(int fd, const std::string& host, bool verify, const std::string& ca_file)
      : rt_(Runtime::get()) {
    if (!rt_) throw std::runtime_error("TLS runtime unavailable");
    ctx_ = rt_->SSL_CTX_new(rt_->TLS_client_method());
    if (!ctx_) throw std::runtime_error("SSL_CTX_new failed");
    if (verify) {
      if (!ca_file.empty()) {
        if (rt_->SSL_CTX_load_verify_locations(ctx_, ca_file.c_str(), nullptr) != 1) {
          std::string e = rt_->last_error();
          rt_->SSL_CTX_free(ctx_);
          throw std::runtime_error("cannot load CA file " + ca_file + ": " + e);
        }
      } else {
        rt_->SSL_CTX_set_default_verify_paths(ctx_);
      }
      rt_->SSL_CTX_set_verify(ctx_, 1 /*SSL_VERIFY_PEER*/, nullptr);
    }
    ssl_ = rt_->SSL_new(ctx_);
    if (!ssl_) {
      rt_->SSL_CTX_free(ctx_);
      throw std::runtime_error("SSL_new failed");
    }
    // SNI (SSL_set_tlsext_host_name is a macro over SSL_ctrl):
    // SSL_CTRL_SET_TLSEXT_HOSTNAME=55, TLSEXT_NAMETYPE_host_name=0
    bool is_ip = host.find_first_not_of("0123456789.") == std::string::npos ||
                 host.find(':') != std::string::npos;  // v4 / v6 literal
    if (!is_ip) rt_->SSL_ctrl(ssl_, 55, 0, const_cast<char*>(host.c_str()));
    if (verify) {
      // IP literals check against IP SANs (set1_host would compare
      // DNS-IDs). A failed binding must THROW, never silently degrade to
      // chain-only verification; a digits-and-dots host set1_ip_asc can't
      // parse (e.g. trailing dot) falls back to the DNS-ID check.
      auto* param = rt_->SSL_get0_param(ssl_);
      int bound = 0;
      if (is_ip) bound = rt_->X509_VERIFY_PARAM_set1_ip_asc(param, host.c_str());
      if (!bound)
        bound = rt_->X509_VERIFY_PARAM_set1_host(param, host.c_str(), 0);
      if (!bound) {
        cleanup();
        throw std::runtime_error("cannot bind peer name " + host +
                                 " for certificate verification");
      }
    }
    rt_->SSL_set_fd(ssl_, fd);
    if (rt_->SSL_connect(ssl_) != 1) {
      std::string e = rt_->last_error();
      cleanup();
      throw std::runtime_error("TLS handshake with " + host + " failed: " + e);
    }
  }

  ~Conn() {
    if (ssl_) rt_->SSL_shutdown(ssl_);
    cleanup();
  }

  // >0 bytes, 0 on orderly close, throws on error/timeout.
  int read(char* buf, int n) {
    int r = rt_->SSL_read(ssl_, buf, n);
    if (r > 0) return r;
    int err = rt_->SSL_get_error(ssl_, r);
    if (err == 6 /*SSL_ERROR_ZERO_RETURN*/) return 0;
    if (err == 5 /*SSL_ERROR_SYSCALL*/ && r == 0) return 0;  // abrupt EOF
    if (err == 1 /*SSL_ERROR_SSL*/) {
      // OpenSSL 3 reports a peer close without close_notify as a protocol
      // error; many servers (incl. Python's http.server) close abruptly
      // after Connection: close. Treat exactly that case as EOF. The
      // CALLER must enforce body framing (Content-Length / chunked
      // terminator — perception.cpp's http_get throws on truncation), so
      // an injected FIN cannot pass a partial body off as complete; only
      // close-delimited bodies with no framing remain unknowable, same as
      // every pragmatic client (curl's default).
      unsigned long code = rt_->last_error_code();
      // Primary check is the stable numeric reason — OpenSSL 3's
      // SSL_R_UNEXPECTED_EOF_WHILE_READING (294) raised by ERR_LIB_SSL
      // (20): ERR_GET_REASON for a non-system error is code & 0x7FFFFF and
      // ERR_GET_LIB is (code >> 23) & 0xFF (the 1.1-era 0xFFF mask doesn't
      // apply: 1.1 reports this case as SSL_ERROR_SYSCALL, handled above).
      // Requiring the lib id keeps a non-SSL error whose reason bits happen
      // to equal 294 from masquerading as a clean EOF. The message-text
      // match stays only as a fallback for builds whose numbering differs
      // (ADVICE r4: text is not a stable API).
      bool system_err = (code & 0x80000000UL) != 0;
      if (!system_err && ((code >> 23) & 0xFFUL) == 20UL
          && (code & 0x7FFFFFUL) == 294UL)
        return 0;
      std::string e = rt_->error_string(code);
      if (e.find("unexpected eof") != std::string::npos) return 0;
      throw std::runtime_error("TLS read failed: " + e);
    }
    throw std::runtime_error("TLS read failed (ssl err " + std::to_string(err) + ")");
  }

  void write_all(const char* buf, size_t n) {
    size_t off = 0;
    while (off < n) {
      int w = rt_->SSL_write(ssl_, buf + off, (int)(n - off));
      if (w <= 0) throw std::runtime_error("TLS write failed");
      off += (size_t)w;
    }
  }

  Conn(const Conn&) = delete;
  Conn& operator=(const Conn&) = delete;

 private:
  void cleanup() {
    if (ssl_) rt_->SSL_free(ssl_);
    if (ctx_) rt_->SSL_CTX_free(ctx_);
    ssl_ = nullptr;
    ctx_ = nullptr;
  }

  Runtime* rt_;
  SSL_CTX* ctx_ = nullptr;
  SSL* ssl_ = nullptr;
};

inline bool available(std::string* why = nullptr) {
  return Runtime::get(why) != nullptr;
}

}  // namespace tls
}  // namespace symbiont
