// vector_memory worker — C++ shell of the reference's vector_memory_service
// (SURVEY.md §2 checklist item 5; reference:
// services/vector_memory_service/src/main.rs). The store itself is the
// TPU-native vector store owned by the engine process (exact cosine top-k on
// the MXU, symbiont_tpu/memory/vector_store.py) reached over
// engine.vector.* request-reply — replacing the reference's Qdrant gRPC hop.
//
// Roles, same as the reference:
// 1. data.text.with_embeddings → one point per sentence, uuid ids, 6-field
//    payload, ack-after-durable upsert (main.rs:121-228; wait=true :196);
// 2. tasks.search.semantic.request request-reply with typed error replies
//    (main.rs:230-456).
//
// PIPELINED UPSERTS (VERDICT r4 next-1, same rework as preprocessing.cpp):
// the synchronous one-doc-per-upsert form made each replica pay a full
// engine round-trip per document. This shell now keeps up to
// SYMBIONT_VECMEM_MAX_INFLIGHT upsert requests in flight, COALESCES the
// points of multiple pending documents into one engine.vector.upsert hop
// (up to SYMBIONT_VECMEM_MAX_BATCH_POINTS), and ships the vectors as one
// base64 f32 block instead of JSON digit arrays. Each document's delivery
// is acked only after the upsert carrying ITS points succeeded.
//
// Usage: vector_memory [SYMBIONT_BUS_URL=...] [SYMBIONT_ENGINE_TIMEOUT_MS=...]
//        [SYMBIONT_VECMEM_MAX_INFLIGHT=3] [SYMBIONT_VECMEM_MAX_BATCH_POINTS=256]

#include <deque>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "../../generated/cpp/symbiont_schema.hpp"
#include "common.hpp"

namespace {

const char* SERVICE = "vector_memory";

using symbiont::engine_call;

// A parsed document whose points are waiting for (or riding in) an upsert.
// The vectors are held as RAW little-endian bytes in the dtype the wire
// delivered (tensor frame: a straight copy of the payload — f32 or the
// half-width f16; legacy JSON: packed f32 once at parse) — dispatch never
// touches floats again.
struct PendingDoc {
  symbus::BusMsg delivery;
  symbiont::TextWithEmbeddingsMessage m;
  std::map<std::string, std::string> headers;
  std::string raw_vectors;  // m.embeddings_data.size() * dim elements
  size_t dim = 0;
  uint8_t dtype = symbiont::FRAME_DTYPE_F32;
  // set after a coalesced upsert failed: retry this doc in its own request
  // so one poison doc (e.g. dim mismatch) cannot dead-letter the healthy
  // docs batched with it
  bool solo = false;
};

struct InflightUpsert {
  std::vector<PendingDoc> docs;
  size_t total_points = 0;
  uint64_t deadline_ms = 0;
};

}  // namespace

// See preprocessing.cpp: a skipped redelivery still counts toward
// max_deliver, so the final attempt must override the skip conditions.
inline bool last_chance(const symbus::BusMsg& m, uint32_t max_deliver) {
  auto it = m.headers.find("X-Symbus-Deliveries");
  if (it == m.headers.end()) return false;  // core mode: no dead-letter
  return (uint32_t)std::atoi(it->second.c_str()) + 1 >= max_deliver;
}

inline size_t env_size_t(const char* key, long dflt, long lo) {
  long v = std::atol(symbiont::env_or(key, std::to_string(dflt)).c_str());
  return (size_t)(v < lo ? lo : v);  // clamp BEFORE the size_t cast: a
  // negative value must not wrap to 2^64 and disable the bound
}

int main() try {
  int engine_timeout_ms =
      std::atoi(symbiont::env_or("SYMBIONT_ENGINE_TIMEOUT_MS", "120000").c_str());
  size_t max_inflight = env_size_t("SYMBIONT_VECMEM_MAX_INFLIGHT", 3, 1);
  size_t max_batch_points =
      env_size_t("SYMBIONT_VECMEM_MAX_BATCH_POINTS", 256, 1);
  uint32_t max_deliver = (uint32_t)std::atoi(
      symbiont::env_or("SYMBIONT_BUS_DURABLE_MAX_DELIVER", "5").c_str());

  // binary tensor frames (common.hpp / schema/frames.py): forward the
  // vectors to engine.vector.upsert as one attached f32 block instead of
  // base64 text. SYMBIONT_FRAMES=0 restores the b64 request form (an old
  // engine accepts that; a new engine accepts both).
  bool use_frames = symbiont::frames_enabled();

  symbus::Client bus;
  if (!symbiont::connect_with_retry(bus, SERVICE)) return 1;

  // durable mode: ack only after the engine confirms the upsert — the
  // ack-after-durable design SURVEY.md §7 hard part #6 calls for (an engine
  // restart between delivery and write redelivers instead of losing data)
  bool durable = symbiont::maybe_setup_pipeline_stream(bus);
  uint32_t sid_store =
      durable ? bus.durable_subscribe("pipeline", symbiont::subjects::Q_VECTOR_MEMORY,
                                      symbiont::subjects::DATA_TEXT_WITH_EMBEDDINGS)
              : bus.subscribe(symbiont::subjects::DATA_TEXT_WITH_EMBEDDINGS,
                              symbiont::subjects::Q_VECTOR_MEMORY);
  uint32_t sid_search = bus.subscribe(symbiont::subjects::TASKS_SEARCH_SEMANTIC_REQUEST,
                                      symbiont::subjects::Q_VECTOR_MEMORY);
  symbiont::logline("INFO", SERVICE, durable ? "ready (durable)" : "ready");

  std::deque<PendingDoc> ready;
  std::unordered_map<uint32_t, InflightUpsert> inflight;  // by inbox sid
  // doc ids currently queued or in flight: an ack_wait redelivery of a doc
  // we already hold must not enter the pipeline twice (duplicate work; the
  // deterministic point ids keep the STORE idempotent either way)
  std::unordered_set<std::string> pending_ids;
  bool backlog_warned = false;

  // Build and send one coalesced upsert for ≥1 ready docs. The vectors go
  // out as ONE block built by concatenating each doc's raw f32 bytes —
  // as an attached tensor frame (default), or base64'd for an old engine
  // (SYMBIONT_FRAMES=0). Engine-plane contract:
  // engine_service.py::_vec_upsert; the bus wire schema
  // (TextWithEmbeddingsMessage) is untouched.
  auto dispatch = [&]() {
    while (inflight.size() < max_inflight && !ready.empty()) {
      InflightUpsert batch;
      size_t dim = 0;
      uint8_t dtype = symbiont::FRAME_DTYPE_F32;
      json::Value ids = json::Value::array();
      json::Value payloads = json::Value::array();
      std::string raw;
      while (!ready.empty()) {
        PendingDoc& d = ready.front();
        size_t pts = d.m.embeddings_data.size();
        if (!batch.docs.empty() &&
            (d.solo || batch.total_points + pts > max_batch_points ||
             d.dtype != dtype))  // dtype-pure batches: one frame, one form
          break;
        bool was_solo = d.solo;
        uint64_t now = symbiont::now_ms();
        if (dim == 0) dim = d.dim;
        if (batch.docs.empty()) dtype = d.dtype;
        for (size_t order = 0; order < pts; ++order) {
          const auto& se = d.m.embeddings_data[order];
          symbiont::QdrantPointPayload payload;
          payload.original_document_id = d.m.original_id;
          payload.source_url = d.m.source_url;
          payload.sentence_text = se.sentence_text;
          payload.sentence_order = order;
          payload.model_name = d.m.model_name;
          payload.processed_at_ms = now;
          ids.push_back(json::Value(
              symbiont::deterministic_point_id(d.m.original_id, order)));
          payloads.push_back(payload.to_json());
        }
        raw += d.raw_vectors;
        batch.total_points += pts;
        batch.docs.push_back(std::move(d));
        ready.pop_front();
        if (was_solo || batch.total_points >= max_batch_points) break;
      }
      json::Value req = json::Value::object();
      req.set("ids", std::move(ids));
      req.set("payloads", std::move(payloads));
      req.set("dim", json::Value((double)dim));
      std::string inbox = "_INBOX." + symbiont::uuid4();
      uint32_t sid = bus.subscribe(inbox);
      batch.deadline_ms = symbiont::now_ms() + (uint64_t)engine_timeout_ms;
      auto headers = batch.docs.front().headers;
      std::string data;
      // the frame path requires a consistent block (mixed-dim docs
      // coalesced together cannot frame); the b64 fallback ships the
      // same bytes and lets the ENGINE reject the mismatch, which routes
      // the batch through the per-doc solo-retry isolation below. A batch
      // is dtype-pure by construction (the pop loop breaks on mismatch),
      // so the frame forwards f16 payloads at half width untouched; the
      // b64 form is an f32 contract, so a non-framable f16 batch upcasts
      // once here (rare: only mixed-dim f16 docs take this path).
      if (use_frames &&
          raw.size() == (size_t)batch.total_points * dim *
                            symbiont::frame_elem_size(dtype)) {
        std::string body = req.dump();
        headers[symbiont::FRAME_HEADER] =
            symbiont::frame_header_value(body.size(), dtype);
        data = body + symbiont::make_frame(
                          raw, (uint32_t)batch.total_points, (uint32_t)dim,
                          dtype);
      } else {
        if (dtype == symbiont::FRAME_DTYPE_F16) {
          std::string wide(raw.size() * 2, '\0');
          for (size_t i = 0; i * 2 < raw.size(); ++i) {
            uint16_t h = (uint16_t)(unsigned char)raw[2 * i] |
                         (uint16_t)(unsigned char)raw[2 * i + 1] << 8;
            float f = symbiont::half_to_float(h);
            std::memcpy(&wide[i * 4], &f, 4);
          }
          raw = std::move(wide);
        }
        req.set("vectors_b64",
                json::Value(symbiont::b64_encode(
                    (const unsigned char*)raw.data(), raw.size())));
        data = req.dump();
      }
      bus.publish(symbiont::subjects::ENGINE_VECTOR_UPSERT, data, inbox,
                  headers);
      inflight.emplace(sid, std::move(batch));
    }
  };

  auto complete = [&](InflightUpsert& batch, const symbus::BusMsg& msg) {
    json::Value r = json::parse(msg.data);
    if (!r.at("error_message").is_null())
      throw std::runtime_error("engine error: " +
                               r.at("error_message").as_string());
    uint64_t n = (uint64_t)r.at("upserted").as_number();
    for (auto& d : batch.docs) {
      bus.ack(d.delivery);  // request-reply == ack-after-durable (wait=true)
      pending_ids.erase(d.m.original_id);
    }
    symbiont::logline("INFO", SERVICE,
                      "upserted " + std::to_string(n) + " points for " +
                          std::to_string(batch.docs.size()) + " docs",
                      batch.docs.front().headers);
  };

  // fleet liveness: beat `_sys.heartbeat.<role>` so the process supervisor's
  // hang detector covers this shell (SYMBIONT_RUNNER_HEARTBEAT_S > 0)
  symbiont::Heartbeat hb = symbiont::heartbeat_from_env(SERVICE);

  while (bus.connected()) {
    auto msg = bus.next(1000);
    symbiont::maybe_heartbeat(bus, hb);

    uint64_t now = symbiont::now_ms();
    for (auto it = inflight.begin(); it != inflight.end();) {
      if (it->second.deadline_ms < now) {
        symbiont::logline("WARN", SERVICE,
                          "upsert timed out (" +
                              std::to_string(it->second.docs.size()) +
                              " docs)");
        bus.unsubscribe(it->first);
        for (auto& d : it->second.docs) pending_ids.erase(d.m.original_id);
        it = inflight.erase(it);  // docs stay unacked → durable redelivery
      } else {
        ++it;
      }
    }
    if (!msg) {
      dispatch();
      continue;
    }

    // ----------------------------------------------- upsert reply (inbox)
    if (auto it = inflight.find(msg->sid); it != inflight.end()) {
      bus.unsubscribe(msg->sid);
      InflightUpsert batch = std::move(it->second);
      inflight.erase(it);
      try {
        complete(batch, *msg);
      } catch (const std::exception& e) {
        symbiont::logline("WARN", SERVICE,
                          std::string("upsert failed: ") + e.what(),
                          batch.docs.front().headers);
        if (batch.docs.size() > 1) {
          // per-doc error isolation: one poison doc (dim mismatch etc.)
          // must not dead-letter the healthy docs coalesced with it —
          // retry each alone; only the bad one will fail then
          for (auto it2 = batch.docs.rbegin(); it2 != batch.docs.rend();
               ++it2) {
            it2->solo = true;
            ready.push_front(std::move(*it2));
          }
        } else {
          // singleton already: leave unacked so the durable stream
          // redelivers after ack_wait
          pending_ids.erase(batch.docs.front().m.original_id);
        }
      }
      dispatch();
      continue;
    }

    // ------------------------------------------------------------- upsert
    if (msg->sid == sid_store) {
      // expired-deadline drop (Service._run_handler parity): acked, never
      // retried, never dead-lettered. Ingest mints no deadline by default
      // (docs/RESILIENCE.md) — this only fires on client-opt-in deadlines.
      if (symbiont::drop_if_expired(bus, *msg, SERVICE)) continue;
      PendingDoc d;
      d.delivery = *msg;
      try {
        // both wire forms: a frame-bearing message (JSON metadata + f32
        // block) or the reference's plain-JSON float lists
        std::string json_part;
        symbiont::FrameView fv;
        bool framed =
            symbiont::split_frame(msg->headers, msg->data, json_part, fv);
        d.m = symbiont::TextWithEmbeddingsMessage::parse(
            framed ? json_part : msg->data);
        if (framed) {
          if (fv.rows != d.m.embeddings_data.size())
            throw std::runtime_error(
                "frame holds " + std::to_string(fv.rows) + " rows for " +
                std::to_string(d.m.embeddings_data.size()) + " sentences");
          d.dim = fv.cols;
          d.dtype = fv.dtype;  // forwarded as-is (f16 stays half-width)
          d.raw_vectors.assign(fv.payload, fv.payload_len);
        } else {
          for (const auto& se : d.m.embeddings_data) {
            if (se.embedding.empty()) continue;
            if (d.dim == 0) d.dim = se.embedding.size();
            size_t at = d.raw_vectors.size();
            d.raw_vectors.resize(at + se.embedding.size() * sizeof(float));
            std::memcpy(&d.raw_vectors[at], se.embedding.data(),
                        se.embedding.size() * sizeof(float));
          }
        }
      } catch (const std::exception& e) {
        symbiont::logline("WARN", SERVICE,
                          std::string("bad embeddings message: ") + e.what(),
                          msg->headers);
        bus.ack(*msg);  // permanent failure: redelivery cannot help
        continue;
      }
      if (d.m.embeddings_data.empty()) {
        bus.ack(*msg);  // nothing to store
        continue;
      }
      if (pending_ids.count(d.m.original_id)
          && !last_chance(*msg, max_deliver)) {
        // ack_wait redelivery of a doc still queued/in flight here: taking
        // it again would double the work; skipping WITHOUT ack keeps the
        // at-least-once contract (if our copy fails, a later redelivery
        // re-enters because the id is erased on drop). Final attempt
        // overrides the skip — see last_chance above.
        continue;
      }
      if (durable && ready.size() >= 512
          && !last_chance(*msg, max_deliver)) {
        // backpressure: the engine is slower than the feed; leave the
        // delivery unacked for redelivery instead of growing an unbounded
        // queue whose tail would blow past ack_wait anyway
        if (!backlog_warned) {
          backlog_warned = true;
          symbiont::logline("WARN", SERVICE,
                            "ready backlog >= 512 docs; deferring to "
                            "redelivery");
        }
        continue;
      }
      d.headers = symbiont::child_headers(msg->headers);
      pending_ids.insert(d.m.original_id);
      ready.push_back(std::move(d));
      dispatch();
      continue;
    }

    // ------------------------------------------------------------- search
    if (msg->sid == sid_search) {
      // an expired search gets NO reply — the edge's deadline-capped bus
      // timeout already fired (api.py _deadline_capped)
      if (symbiont::drop_if_expired(bus, *msg, SERVICE)) continue;
      if (msg->reply.empty()) {
        symbiont::logline("WARN", SERVICE, "search task without reply inbox",
                          msg->headers);
        continue;
      }
      symbiont::SemanticSearchNatsResult result;
      try {
        auto task = symbiont::SemanticSearchNatsTask::parse(msg->data);
        result.request_id = task.request_id;
        json::Value req = json::Value::object();
        req.set("vector", json::to_array(task.query_embedding, [](const float& x) {
          return json::Value(x);
        }));
        req.set("top_k", json::Value((double)task.top_k));
        // synchronous: the search path is the latency path; pipeline
        // replies arriving meanwhile stay queued for next()
        json::Value r = engine_call(bus, symbiont::subjects::ENGINE_VECTOR_SEARCH,
                                    req, engine_timeout_ms,
                                    symbiont::child_headers(msg->headers));
        for (const auto& h : r.at("hits").as_array()) {
          symbiont::SemanticSearchResultItem item;
          item.qdrant_point_id = h.at("id").as_string();
          item.score = (float)h.at("score").as_number();
          item.payload = symbiont::QdrantPointPayload::from_json(h.at("payload"));
          result.results.push_back(std::move(item));
        }
      } catch (const std::exception& e) {
        // typed error reply even on deserialize failure (main.rs:240-251)
        if (result.request_id.empty()) result.request_id = "unknown";
        result.error_message = e.what();
      }
      bus.publish(msg->reply, result.to_json_string(), "",
                  symbiont::child_headers(msg->headers));
      continue;
    }
  }
  symbiont::logline("INFO", SERVICE, "bus connection closed; exiting");
  return 0;
} catch (const std::exception& e) {
  // bus drop mid-handler etc.: exit cleanly for the supervisor to
  // restart instead of std::terminate aborting with no log
  symbiont::logline("ERROR", SERVICE, std::string("fatal: ") + e.what());
  return 1;
}
