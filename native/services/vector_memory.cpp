// vector_memory worker — C++ shell of the reference's vector_memory_service
// (SURVEY.md §2 checklist item 5; reference:
// services/vector_memory_service/src/main.rs). The store itself is the
// TPU-native vector store owned by the engine process (exact cosine top-k on
// the MXU, symbiont_tpu/memory/vector_store.py) reached over
// engine.vector.* request-reply — replacing the reference's Qdrant gRPC hop.
//
// Roles, same as the reference:
// 1. data.text.with_embeddings → one point per sentence, uuid ids, 6-field
//    payload, ack-after-durable upsert (main.rs:121-228; wait=true :196);
// 2. tasks.search.semantic.request request-reply with typed error replies
//    (main.rs:230-456).
//
// Usage: vector_memory [SYMBIONT_BUS_URL=...] [SYMBIONT_ENGINE_TIMEOUT_MS=...]

#include <string>
#include <vector>

#include "../../generated/cpp/symbiont_schema.hpp"
#include "common.hpp"

namespace {

const char* SERVICE = "vector_memory";

using symbiont::engine_call;

}  // namespace

int main() try {
  int engine_timeout_ms =
      std::atoi(symbiont::env_or("SYMBIONT_ENGINE_TIMEOUT_MS", "120000").c_str());

  symbus::Client bus;
  if (!symbiont::connect_with_retry(bus, SERVICE)) return 1;

  // durable mode: ack only after the engine confirms the upsert — the
  // ack-after-durable design SURVEY.md §7 hard part #6 calls for (an engine
  // restart between delivery and write redelivers instead of losing data)
  bool durable = symbiont::maybe_setup_pipeline_stream(bus);
  uint32_t sid_store =
      durable ? bus.durable_subscribe("pipeline", symbiont::subjects::Q_VECTOR_MEMORY,
                                      symbiont::subjects::DATA_TEXT_WITH_EMBEDDINGS)
              : bus.subscribe(symbiont::subjects::DATA_TEXT_WITH_EMBEDDINGS,
                              symbiont::subjects::Q_VECTOR_MEMORY);
  uint32_t sid_search = bus.subscribe(symbiont::subjects::TASKS_SEARCH_SEMANTIC_REQUEST,
                                      symbiont::subjects::Q_VECTOR_MEMORY);
  symbiont::logline("INFO", SERVICE, durable ? "ready (durable)" : "ready");

  while (bus.connected()) {
    auto msg = bus.next(1000);
    if (!msg) continue;

    // ------------------------------------------------------------- upsert
    if (msg->sid == sid_store) {
      symbiont::TextWithEmbeddingsMessage m;
      try {
        m = symbiont::TextWithEmbeddingsMessage::parse(msg->data);
      } catch (const std::exception& e) {
        symbiont::logline("WARN", SERVICE,
                          std::string("bad embeddings message: ") + e.what(),
                          msg->headers);
        bus.ack(*msg);  // permanent failure: redelivery cannot help
        continue;
      }
      auto headers = symbiont::child_headers(msg->headers);
      json::Value points = json::Value::array();
      uint64_t now = symbiont::now_ms();
      for (size_t order = 0; order < m.embeddings_data.size(); ++order) {
        const auto& se = m.embeddings_data[order];
        symbiont::QdrantPointPayload payload;
        payload.original_document_id = m.original_id;
        payload.source_url = m.source_url;
        payload.sentence_text = se.sentence_text;
        payload.sentence_order = order;
        payload.model_name = m.model_name;
        payload.processed_at_ms = now;
        json::Value p = json::Value::object();
        p.set("id", json::Value(
                        symbiont::deterministic_point_id(m.original_id, order)));
        p.set("vector", json::to_array(se.embedding, [](const float& x) {
          return json::Value(x);
        }));
        p.set("payload", payload.to_json());
        points.push_back(std::move(p));
      }
      json::Value req = json::Value::object();
      req.set("points", std::move(points));
      try {
        // request-reply == ack-after-durable (reference wait=true, :196)
        json::Value r = engine_call(bus, symbiont::subjects::ENGINE_VECTOR_UPSERT,
                                    req, engine_timeout_ms, headers);
        symbiont::logline("INFO", SERVICE,
                          "upserted " +
                              std::to_string((uint64_t)r.at("upserted").as_number()) +
                              " points for doc " + m.original_id,
                          headers);
        bus.ack(*msg);  // upsert is durable; safe to drop from stream
      } catch (const std::exception& e) {
        // transient (engine down / timeout): leave unacked so the durable
        // stream redelivers after ack_wait
        symbiont::logline("WARN", SERVICE,
                          std::string("upsert failed: ") + e.what(), headers);
      }
      continue;
    }

    // ------------------------------------------------------------- search
    if (msg->sid == sid_search) {
      if (msg->reply.empty()) {
        symbiont::logline("WARN", SERVICE, "search task without reply inbox",
                          msg->headers);
        continue;
      }
      symbiont::SemanticSearchNatsResult result;
      try {
        auto task = symbiont::SemanticSearchNatsTask::parse(msg->data);
        result.request_id = task.request_id;
        json::Value req = json::Value::object();
        req.set("vector", json::to_array(task.query_embedding, [](const float& x) {
          return json::Value(x);
        }));
        req.set("top_k", json::Value((double)task.top_k));
        json::Value r = engine_call(bus, symbiont::subjects::ENGINE_VECTOR_SEARCH,
                                    req, engine_timeout_ms,
                                    symbiont::child_headers(msg->headers));
        for (const auto& h : r.at("hits").as_array()) {
          symbiont::SemanticSearchResultItem item;
          item.qdrant_point_id = h.at("id").as_string();
          item.score = (float)h.at("score").as_number();
          item.payload = symbiont::QdrantPointPayload::from_json(h.at("payload"));
          result.results.push_back(std::move(item));
        }
      } catch (const std::exception& e) {
        // typed error reply even on deserialize failure (main.rs:240-251)
        if (result.request_id.empty()) result.request_id = "unknown";
        result.error_message = e.what();
      }
      bus.publish(msg->reply, result.to_json_string(), "",
                  symbiont::child_headers(msg->headers));
      continue;
    }
  }
  symbiont::logline("INFO", SERVICE, "bus connection closed; exiting");
  return 0;
} catch (const std::exception& e) {
  // bus drop mid-handler etc.: exit cleanly for the supervisor to
  // restart instead of std::terminate aborting with no log
  symbiont::logline("ERROR", SERVICE, std::string("fatal: ") + e.what());
  return 1;
}
