// knowledge_graph worker — C++ shell of the reference's knowledge_graph_service
// (SURVEY.md §2 checklist item 6; reference:
// services/knowledge_graph_service/src/main.rs). The store itself is the
// embedded sqlite property graph owned by the engine process (MERGE-semantics
// parity, symbiont_tpu/graph/store.py) reached over engine.graph.save
// request-reply — replacing the reference's Neo4j Bolt hop, same two-plane
// split as the native vector_memory worker.
//
// Role, same as the reference's handler (main.rs:142-156): consume
// data.processed_text.tokenized → persist the whole document in one
// transaction (main.rs:23-140). In the reference this consumer is orphaned —
// nothing publishes the subject in v0.3.0 (SURVEY.md fact #3); here the
// preprocessing workers publish it, so this shell is live.
//
// Durable mode (SYMBIONT_BUS_DURABLE=1): ack only after the engine confirms
// the transaction committed — a crash between delivery and commit redelivers
// instead of silently losing the document (SURVEY.md §5.3's gap).
//
// Usage: knowledge_graph [SYMBIONT_BUS_URL=...] [SYMBIONT_ENGINE_TIMEOUT_MS=...]

#include <string>

#include "../../generated/cpp/symbiont_schema.hpp"
#include "common.hpp"

namespace {

const char* SERVICE = "knowledge_graph";

}  // namespace

int main() try {
  int engine_timeout_ms =
      std::atoi(symbiont::env_or("SYMBIONT_ENGINE_TIMEOUT_MS", "120000").c_str());

  symbus::Client bus;
  if (!symbiont::connect_with_retry(bus, SERVICE)) return 1;

  bool durable = symbiont::maybe_setup_pipeline_stream(bus);
  if (durable)
    bus.durable_subscribe("pipeline", symbiont::subjects::Q_KNOWLEDGE_GRAPH,
                          symbiont::subjects::DATA_PROCESSED_TEXT_TOKENIZED);
  else
    bus.subscribe(symbiont::subjects::DATA_PROCESSED_TEXT_TOKENIZED,
                  symbiont::subjects::Q_KNOWLEDGE_GRAPH);
  symbiont::logline("INFO", SERVICE, durable ? "ready (durable)" : "ready");

  // fleet liveness: beat `_sys.heartbeat.<role>` so the process supervisor's
  // hang detector covers this shell (SYMBIONT_RUNNER_HEARTBEAT_S > 0)
  symbiont::Heartbeat hb = symbiont::heartbeat_from_env(SERVICE);

  while (bus.connected()) {
    auto msg = bus.next(1000);
    symbiont::maybe_heartbeat(bus, hb);
    if (!msg) continue;
    // expired-deadline drop (Service._run_handler parity): acked, never
    // retried — a mid-pipeline worker must not burn graph writes on work
    // whose caller already gave up
    if (symbiont::drop_if_expired(bus, *msg, SERVICE)) continue;

    symbiont::TokenizedTextMessage m;
    try {
      m = symbiont::TokenizedTextMessage::parse(msg->data);
    } catch (const std::exception& e) {
      // reference logs-and-continues on bad payloads (main.rs:296-301)
      symbiont::logline("WARN", SERVICE,
                        std::string("bad tokenized message: ") + e.what(),
                        msg->headers);
      bus.ack(*msg);  // permanent failure: redelivery cannot help
      continue;
    }
    auto headers = symbiont::child_headers(msg->headers);
    json::Value req = json::Value::object();
    req.set("message", m.to_json());
    try {
      // request-reply == ack-after-commit (reference: explicit tx.commit,
      // main.rs:132-134)
      json::Value r = symbiont::engine_call(bus, "engine.graph.save", req,
                                            engine_timeout_ms, headers);
      symbiont::logline(
          "INFO", SERVICE,
          "saved doc " + m.original_id + " (db id " +
              std::to_string((int64_t)r.at("document_db_id").as_number()) +
              ", " + std::to_string(m.sentences.size()) + " sentences, " +
              std::to_string(m.tokens.size()) + " tokens)",
          headers);
      bus.ack(*msg);  // the transaction committed; safe to drop from stream
    } catch (const std::exception& e) {
      // transient (engine down / timeout): leave unacked so the durable
      // stream redelivers after ack_wait
      symbiont::logline("WARN", SERVICE,
                        std::string("graph save failed: ") + e.what(), headers);
    }
  }
  symbiont::logline("INFO", SERVICE, "bus connection closed; exiting");
  return 0;
} catch (const std::exception& e) {
  symbiont::logline("ERROR", SERVICE, std::string("fatal: ") + e.what());
  return 1;
}
