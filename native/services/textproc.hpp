// Text cleaning / sentence splitting / word tokenization — native twin of
// symbiont_tpu/engine/text.py, behavioral parity with the reference's
// preprocessing core (reference: services/preprocessing_service/src/main.rs:28-70).
//
// The delimiters '.', '?', '!' are ASCII, and in UTF-8 no continuation byte
// can equal an ASCII byte, so byte-wise scanning is codepoint-safe — the
// multi-byte-slicing hazard SURVEY.md §4 flags in the reference cannot occur.
#pragma once

#include <sstream>
#include <string>
#include <vector>

namespace symbiont {

inline std::string clean_text(const std::string& raw) {
  std::istringstream in(raw);
  std::string w, out;
  while (in >> w) {
    if (!out.empty()) out += ' ';
    out += w;
  }
  return out;
}

inline std::string trim_ws(const std::string& s) {
  size_t b = s.find_first_not_of(" \t\r\n\f\v");
  if (b == std::string::npos) return "";
  size_t e = s.find_last_not_of(" \t\r\n\f\v");
  return s.substr(b, e - b + 1);
}

inline bool is_sentence_delim(char c) { return c == '.' || c == '?' || c == '!'; }

// A sentence ends at each '.', '?' or '!' (delimiter kept, slice trimmed);
// trailing remainder becomes a final sentence; non-empty text with no
// delimiters is one sentence (reference main.rs:41-62).
inline std::vector<std::string> split_sentences(const std::string& cleaned) {
  std::vector<std::string> out;
  size_t start = 0;
  for (size_t i = 0; i < cleaned.size(); ++i) {
    if (is_sentence_delim(cleaned[i])) {
      std::string s = trim_ws(cleaned.substr(start, i + 1 - start));
      if (!s.empty()) out.push_back(s);
      start = i + 1;
    }
  }
  if (start < cleaned.size()) {
    std::string rest = trim_ws(cleaned.substr(start));
    if (!rest.empty()) out.push_back(rest);
  }
  if (out.empty() && !cleaned.empty()) out.push_back(cleaned);
  return out;
}

inline std::vector<std::string> tokenize_words(const std::string& cleaned) {
  std::istringstream in(cleaned);
  std::vector<std::string> out;
  std::string w;
  while (in >> w) out.push_back(w);
  return out;
}

}  // namespace symbiont
