// preprocessing worker — C++ shell of the reference's preprocessing_service
// (SURVEY.md §2 checklist item 3; reference:
// services/preprocessing_service/src/main.rs), with the tensor compute
// relocated to the TPU engine process behind engine.embed.* request-reply
// (checklist item 4: the shell never touches the device).
//
// Two roles, same as the reference:
// 1. pipeline: data.raw_text.discovered → clean/split (native, textproc.hpp)
//    → engine.embed.batch → data.text.with_embeddings (main.rs:126-171);
//    plus the un-orphaned data.processed_text.tokenized publish
//    (SURVEY.md fact #3 — the reference's CHANGELOG.md:57-60 left it dead).
// 2. query embedding request-reply on tasks.embedding.for_query with typed
//    error replies even on undecodable input (main.rs:173-298).
//
// PIPELINED FEED (VERDICT r4 next-1): the reference's model — and our first
// three rounds' — was one synchronous embed hop per document, so each
// replica held exactly one doc in flight and the engine round-trip (~110 ms
// device RTT on a tunnel) was paid per document. This shell now:
//   - keeps up to SYMBIONT_PREPROC_MAX_INFLIGHT embed requests in flight at
//     once (async inbox request-reply, single-threaded event loop), and
//   - COALESCES the sentences of multiple pending documents into one
//     engine.embed.batch hop (up to SYMBIONT_PREPROC_MAX_BATCH_SENTS), so
//     the hop count scales with total sentences, not documents;
//   - asks the engine for the compact base64 f32 reply encoding (~4.3 bytes
//     per float on the wire instead of ~10 digits of JSON).
// Per-document ack/publish semantics are unchanged: each doc's two publishes
// happen (and its delivery is acked) only after ITS vectors arrived; a
// failed/timed-out batch leaves every affected doc unacked for durable
// redelivery.
//
// Usage: preprocessing [SYMBIONT_BUS_URL=...] [SYMBIONT_ENGINE_TIMEOUT_MS=...]
//        [SYMBIONT_PREPROC_MAX_INFLIGHT=3] [SYMBIONT_PREPROC_MAX_BATCH_SENTS=128]

#include <deque>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "../../generated/cpp/symbiont_schema.hpp"
#include "common.hpp"
#include "textproc.hpp"

namespace {

const char* SERVICE = "preprocessing";

// A parsed document whose sentences are waiting for (or riding in) an
// embed hop. Holds the original delivery for the ack.
struct PendingDoc {
  symbus::BusMsg delivery;
  symbiont::RawTextMessage raw;
  std::string cleaned;
  std::vector<std::string> sentences;
  std::map<std::string, std::string> headers;  // child trace headers
};

// One in-flight engine.embed.batch request carrying 1..n documents.
struct InflightBatch {
  std::vector<PendingDoc> docs;
  size_t total_sentences = 0;
  uint64_t deadline_ms = 0;
};

}  // namespace

// The broker counts EVERY delivery attempt toward max_deliver, including
// ones a worker skips (dedupe of a copy it already holds, backpressure) —
// so a skipped redelivery silently burns a retry. When the NEXT redelivery
// would dead-letter the message, the worker must take it despite the skip
// conditions: duplicate work / memory beats data loss.
inline bool last_chance(const symbus::BusMsg& m, uint32_t max_deliver) {
  auto it = m.headers.find("X-Symbus-Deliveries");
  if (it == m.headers.end()) return false;  // core mode: no dead-letter
  return (uint32_t)std::atoi(it->second.c_str()) + 1 >= max_deliver;
}

inline size_t env_size_t(const char* key, long dflt, long lo) {
  long v = std::atol(symbiont::env_or(key, std::to_string(dflt)).c_str());
  return (size_t)(v < lo ? lo : v);  // clamp BEFORE the size_t cast: a
  // negative value must not wrap to 2^64 and disable the bound
}

int main() try {
  int engine_timeout_ms =
      std::atoi(symbiont::env_or("SYMBIONT_ENGINE_TIMEOUT_MS", "120000").c_str());
  size_t max_inflight = env_size_t("SYMBIONT_PREPROC_MAX_INFLIGHT", 3, 1);
  size_t max_batch_sents =
      env_size_t("SYMBIONT_PREPROC_MAX_BATCH_SENTS", 128, 1);
  uint32_t max_deliver = (uint32_t)std::atoi(
      symbiont::env_or("SYMBIONT_BUS_DURABLE_MAX_DELIVER", "5").c_str());
  // binary tensor frames (common.hpp / schema/frames.py): ask the engine
  // for frame replies and publish data.text.with_embeddings with the
  // float block attached — floats never pass through text. SYMBIONT_FRAMES
  // =0 restores the reference-era JSON wire for old downstream peers;
  // =f16 negotiates the half-width dtype from the ENGINE (frame16
  // encoding) and forwards those raw bytes — this shell never converts
  // floats, it re-slices whatever dtype the engine framed.
  uint8_t fmode = symbiont::frames_mode();
  bool use_frames = fmode != 0;

  symbus::Client bus;
  if (!symbiont::connect_with_retry(bus, SERVICE)) return 1;

  // durable mode: at-least-once consumption, ack only after both downstream
  // publishes succeed (SURVEY.md §5.3). Query request-reply stays core.
  bool durable = symbiont::maybe_setup_pipeline_stream(bus);
  uint32_t sid_raw =
      durable ? bus.durable_subscribe("pipeline", symbiont::subjects::Q_PREPROCESSING,
                                      symbiont::subjects::DATA_RAW_TEXT_DISCOVERED)
              : bus.subscribe(symbiont::subjects::DATA_RAW_TEXT_DISCOVERED,
                              symbiont::subjects::Q_PREPROCESSING);
  uint32_t sid_query = bus.subscribe(symbiont::subjects::TASKS_EMBEDDING_FOR_QUERY,
                                     symbiont::subjects::Q_PREPROCESSING);
  symbiont::logline("INFO", SERVICE, durable ? "ready (durable)" : "ready");

  std::deque<PendingDoc> ready;                       // parsed, not dispatched
  std::unordered_map<uint32_t, InflightBatch> inflight;  // by inbox sid
  // doc ids currently queued or in flight: an ack_wait redelivery of a doc
  // we already hold must not be embedded twice
  std::unordered_set<std::string> pending_ids;
  bool ready_high_water_warned = false;

  // Pop ready docs into one coalesced embed request (≥1 doc; stop before
  // exceeding max_batch_sents unless a single doc alone does) and send it
  // with a fresh inbox. Trace headers: a coalesced hop carries the FIRST
  // doc's trace (one request cannot ride n traces); per-doc publishes keep
  // their own traces.
  auto dispatch = [&]() {
    while (inflight.size() < max_inflight && !ready.empty()) {
      InflightBatch batch;
      json::Value texts = json::Value::array();
      while (!ready.empty()) {
        PendingDoc& d = ready.front();
        if (!batch.docs.empty() &&
            batch.total_sentences + d.sentences.size() > max_batch_sents)
          break;
        for (const auto& s : d.sentences) texts.push_back(json::Value(s));
        batch.total_sentences += d.sentences.size();
        batch.docs.push_back(std::move(d));
        ready.pop_front();
        if (batch.total_sentences >= max_batch_sents) break;
      }
      json::Value req = json::Value::object();
      req.set("texts", std::move(texts));
      // an old engine ignores the unknown "frame"/"frame16" encoding and
      // replies with JSON float lists — complete() accepts every reply form
      req.set("encoding",
              json::Value(!use_frames ? "b64"
                          : fmode == symbiont::FRAME_DTYPE_F16 ? "frame16"
                                                               : "frame"));
      std::string inbox = "_INBOX." + symbiont::uuid4();
      uint32_t sid = bus.subscribe(inbox);
      batch.deadline_ms = symbiont::now_ms() + (uint64_t)engine_timeout_ms;
      bus.publish(symbiont::subjects::ENGINE_EMBED_BATCH, req.dump(), inbox,
                  batch.docs.front().headers);
      inflight.emplace(sid, std::move(batch));
    }
  };

  // Distribute one reply's vectors back to its documents in order and
  // publish/ack per doc. Throws on malformed replies (docs stay unacked).
  // A frame reply is re-sliced per document as RAW BYTES (memcpy, no float
  // parse/format anywhere between the engine and the downstream consumers).
  auto complete = [&](InflightBatch& batch, const symbus::BusMsg& msg) {
    std::string json_part;
    symbiont::FrameView fv;
    bool framed = symbiont::split_frame(msg.headers, msg.data, json_part, fv);
    json::Value r = json::parse(framed ? json_part : msg.data);
    if (!r.at("error_message").is_null())
      throw std::runtime_error("engine error: " +
                               r.at("error_message").as_string());
    std::vector<std::vector<float>> vectors;
    if (framed) {
      if (fv.rows != batch.total_sentences)
        throw std::runtime_error(
            "engine frame holds " + std::to_string(fv.rows) +
            " rows for " + std::to_string(batch.total_sentences) +
            " sentences");
      if (!use_frames)  // frames toggled off: fall back to JSON publishes
        vectors = symbiont::frame_rows(fv);
    } else {
      vectors = symbiont::decode_vectors(r);
      if (vectors.size() != batch.total_sentences)
        throw std::runtime_error(
            "engine returned " + std::to_string(vectors.size()) +
            " vectors for " + std::to_string(batch.total_sentences) +
            " sentences");
    }
    std::string model_name = r.at("model_name").as_string();
    size_t off = 0;
    for (auto& d : batch.docs) {
      symbiont::TextWithEmbeddingsMessage out;
      out.original_id = d.raw.id;
      out.source_url = d.raw.source_url;
      out.model_name = model_name;
      out.timestamp_ms = symbiont::now_ms();
      bool publish_frame = framed && use_frames;
      for (size_t i = 0; i < d.sentences.size(); ++i) {
        symbiont::SentenceEmbedding se;
        se.sentence_text = d.sentences[i];
        if (!publish_frame)
          se.embedding = std::move(vectors[off + i]);
        out.embeddings_data.push_back(std::move(se));
      }
      if (publish_frame) {
        std::string body = out.to_json_string();
        size_t dim = fv.cols;
        size_t elem = fv.elem_size();  // 4 (f32) or 2 (negotiated f16)
        std::string raw(fv.payload + off * dim * elem,
                        d.sentences.size() * dim * elem);
        auto headers = d.headers;
        headers[symbiont::FRAME_HEADER] =
            symbiont::frame_header_value(body.size(), fv.dtype);
        bus.publish(symbiont::subjects::DATA_TEXT_WITH_EMBEDDINGS,
                    body + symbiont::make_frame(
                               raw, (uint32_t)d.sentences.size(),
                               (uint32_t)dim, fv.dtype),
                    "", headers);
      } else {
        bus.publish(symbiont::subjects::DATA_TEXT_WITH_EMBEDDINGS,
                    out.to_json_string(), "", d.headers);
      }
      off += d.sentences.size();
      // un-orphaned knowledge-graph feed (SURVEY.md fact #3)
      symbiont::TokenizedTextMessage tok;
      tok.original_id = d.raw.id;
      tok.source_url = d.raw.source_url;
      tok.tokens = symbiont::tokenize_words(d.cleaned);
      tok.sentences = d.sentences;
      tok.timestamp_ms = symbiont::now_ms();
      bus.publish(symbiont::subjects::DATA_PROCESSED_TEXT_TOKENIZED,
                  tok.to_json_string(), "", d.headers);
      bus.ack(d.delivery);  // both downstream publishes are on the broker
    }
  };

  auto forget = [&](const InflightBatch& batch) {
    for (const auto& d : batch.docs) pending_ids.erase(d.raw.id);
  };

  // fleet liveness: beat `_sys.heartbeat.<role>` so the process supervisor's
  // hang detector covers this shell (SYMBIONT_RUNNER_HEARTBEAT_S > 0)
  symbiont::Heartbeat hb = symbiont::heartbeat_from_env(SERVICE);

  while (bus.connected()) {
    auto msg = bus.next(1000);
    symbiont::maybe_heartbeat(bus, hb);

    // expired in-flight batches: drop (docs stay unacked → durable
    // redelivery after ack_wait; core mode loses them, same as before)
    uint64_t now = symbiont::now_ms();
    for (auto it = inflight.begin(); it != inflight.end();) {
      if (it->second.deadline_ms < now) {
        symbiont::logline("WARN", SERVICE,
                          "embed batch timed out (" +
                              std::to_string(it->second.docs.size()) +
                              " docs)");
        bus.unsubscribe(it->first);
        forget(it->second);
        it = inflight.erase(it);
      } else {
        ++it;
      }
    }
    if (!msg) {
      dispatch();  // a freed slot may have pending docs waiting
      continue;
    }

    // ------------------------------------------------ embed reply (inbox)
    if (auto it = inflight.find(msg->sid); it != inflight.end()) {
      bus.unsubscribe(msg->sid);
      InflightBatch batch = std::move(it->second);
      inflight.erase(it);
      try {
        complete(batch, *msg);
        forget(batch);
      } catch (const std::exception& e) {
        // transient (engine down / bad reply): leave unacked so the durable
        // stream redelivers after ack_wait
        symbiont::logline("WARN", SERVICE,
                          std::string("embed failed: ") + e.what(),
                          batch.docs.front().headers);
        forget(batch);
      }
      dispatch();
      continue;
    }

    // ------------------------------------------------------------ pipeline
    if (msg->sid == sid_raw) {
      // expired-deadline drop (Service._run_handler parity): dead work is
      // acked BEFORE any embed capacity is spent on it
      if (symbiont::drop_if_expired(bus, *msg, SERVICE)) continue;
      PendingDoc d;
      d.delivery = *msg;
      try {
        d.raw = symbiont::RawTextMessage::parse(msg->data);
      } catch (const std::exception& e) {
        symbiont::logline("WARN", SERVICE,
                          std::string("bad raw-text message: ") + e.what(),
                          msg->headers);
        bus.ack(*msg);  // permanent failure: redelivery cannot help
        continue;
      }
      d.cleaned = symbiont::clean_text(d.raw.raw_text);
      if (d.cleaned.empty()) {
        // empty cleaned text is an error at this stage (main.rs:33-39)
        symbiont::logline("WARN", SERVICE,
                          "cleaned text empty for id " + d.raw.id,
                          msg->headers);
        bus.ack(*msg);  // permanent: the document has no content
        continue;
      }
      if (pending_ids.count(d.raw.id) && !last_chance(*msg, max_deliver)) {
        // ack_wait redelivery of a doc still queued/in flight here:
        // embedding it again would duplicate downstream publishes; skip
        // WITHOUT ack (if our copy fails, a later redelivery re-enters
        // because the id is erased on drop). On the final attempt the
        // skip is overridden — a skipped delivery still counts toward
        // max_deliver, and duplicate work beats dead-lettering the doc.
        continue;
      }
      if (durable && ready.size() >= 256 && !last_chance(*msg, max_deliver)) {
        // backpressure: leave the delivery unacked for redelivery instead
        // of growing a queue whose tail would blow past ack_wait anyway
        if (!ready_high_water_warned) {
          ready_high_water_warned = true;
          symbiont::logline("WARN", SERVICE,
                            "ready backlog >= 256 docs; deferring to "
                            "redelivery");
        }
        continue;
      }
      d.sentences = symbiont::split_sentences(d.cleaned);
      d.headers = symbiont::child_headers(msg->headers);
      pending_ids.insert(d.raw.id);
      ready.push_back(std::move(d));
      dispatch();
      continue;
    }

    // ----------------------------------------------------- query embedding
    if (msg->sid == sid_query) {
      // an expired query gets NO reply: the edge's deadline-capped bus
      // timeout already fired, a late reply would land in a dead inbox
      if (symbiont::drop_if_expired(bus, *msg, SERVICE)) continue;
      if (msg->reply.empty()) {
        symbiont::logline("WARN", SERVICE, "query task without reply inbox",
                          msg->headers);
        continue;
      }
      symbiont::QueryEmbeddingResult result;
      try {
        auto task = symbiont::QueryForEmbeddingTask::parse(msg->data);
        result.request_id = task.request_id;
        auto headers = symbiont::child_headers(msg->headers);
        json::Value req = json::Value::object();
        json::Value texts = json::Value::array();
        texts.push_back(json::Value(task.text_to_embed));
        req.set("texts", std::move(texts));
        req.set("encoding", json::Value("b64"));
        // synchronous: the query path is one text on the latency path, and
        // pipeline replies arriving meanwhile stay queued for next()
        json::Value r = symbiont::engine_call(
            bus, symbiont::subjects::ENGINE_EMBED_BATCH, req,
            engine_timeout_ms, headers);
        auto vectors = symbiont::decode_vectors(r);
        result.embedding = vectors.at(0);
        result.model_name = r.at("model_name").as_string();
      } catch (const std::exception& e) {
        // typed error reply even on deserialize failure (main.rs:183-196)
        if (result.request_id.empty()) result.request_id = "unknown";
        result.error_message = e.what();
      }
      auto reply_headers = symbiont::child_headers(msg->headers);
      std::string body;
      auto accept = msg->headers.find(symbiont::ACCEPT_FRAME_HEADER);
      if (!result.error_message.has_value() && result.embedding &&
          accept != msg->headers.end() && accept->second == "1") {
        // negotiated reply frame (schema/frames.py wants_frame): the
        // [1, dim] f32 block rides appended to a schema-valid reply whose
        // embedding list is empty; requesters without the accept header
        // keep getting the reference float-list reply below
        std::vector<float> v = std::move(*result.embedding);
        std::string raw(reinterpret_cast<const char*>(v.data()),
                        v.size() * sizeof(float));
        result.embedding = std::vector<float>{};
        body = result.to_json_string();
        reply_headers[symbiont::FRAME_HEADER] =
            symbiont::frame_header_value(body.size());
        body += symbiont::make_frame(raw, 1, (uint32_t)v.size());
      } else {
        body = result.to_json_string();
      }
      bus.publish(msg->reply, body, "", reply_headers);
      continue;
    }
  }
  symbiont::logline("INFO", SERVICE, "bus connection closed; exiting");
  return 0;
} catch (const std::exception& e) {
  // bus drop mid-handler etc.: exit cleanly for the supervisor to
  // restart instead of std::terminate aborting with no log
  symbiont::logline("ERROR", SERVICE, std::string("fatal: ") + e.what());
  return 1;
}
