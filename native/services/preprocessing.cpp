// preprocessing worker — C++ shell of the reference's preprocessing_service
// (SURVEY.md §2 checklist item 3; reference:
// services/preprocessing_service/src/main.rs), with the tensor compute
// relocated to the TPU engine process behind engine.embed.* request-reply
// (checklist item 4: the shell never touches the device).
//
// Two roles, same as the reference:
// 1. pipeline: data.raw_text.discovered → clean/split (native, textproc.hpp)
//    → engine.embed.batch → data.text.with_embeddings (main.rs:126-171);
//    plus the un-orphaned data.processed_text.tokenized publish
//    (SURVEY.md fact #3 — the reference's CHANGELOG.md:57-60 left it dead).
// 2. query embedding request-reply on tasks.embedding.for_query with typed
//    error replies even on undecodable input (main.rs:173-298).
//
// Usage: preprocessing [SYMBIONT_BUS_URL=...] [SYMBIONT_ENGINE_TIMEOUT_MS=...]

#include <string>
#include <vector>

#include "../../generated/cpp/symbiont_schema.hpp"
#include "common.hpp"
#include "textproc.hpp"

namespace {

const char* SERVICE = "preprocessing";

struct EngineError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// engine.embed.batch / engine.embed.query → (vectors, model_name)
std::pair<std::vector<std::vector<float>>, std::string> embed_batch(
    symbus::Client& bus, const std::vector<std::string>& texts, int timeout_ms,
    const std::map<std::string, std::string>& headers) {
  json::Value req = json::Value::object();
  req.set("texts", json::to_array(texts, [](const std::string& t) {
    return json::Value(t);
  }));
  auto reply = bus.request(symbiont::subjects::ENGINE_EMBED_BATCH, req.dump(),
                           timeout_ms, headers);
  if (!reply) throw EngineError("engine.embed.batch timed out");
  json::Value r = json::parse(reply->data);
  if (!r.at("error_message").is_null())
    throw EngineError("engine error: " + r.at("error_message").as_string());
  std::vector<std::vector<float>> vectors;
  for (const auto& row : r.at("vectors").as_array()) {
    std::vector<float> v;
    v.reserve(row.as_array().size());
    for (const auto& x : row.as_array()) v.push_back((float)x.as_number());
    vectors.push_back(std::move(v));
  }
  return {std::move(vectors), r.at("model_name").as_string()};
}

}  // namespace

int main() try {
  int engine_timeout_ms =
      std::atoi(symbiont::env_or("SYMBIONT_ENGINE_TIMEOUT_MS", "120000").c_str());

  symbus::Client bus;
  if (!symbiont::connect_with_retry(bus, SERVICE)) return 1;

  // durable mode: at-least-once consumption, ack only after both downstream
  // publishes succeed (SURVEY.md §5.3). Query request-reply stays core.
  bool durable = symbiont::maybe_setup_pipeline_stream(bus);
  uint32_t sid_raw =
      durable ? bus.durable_subscribe("pipeline", symbiont::subjects::Q_PREPROCESSING,
                                      symbiont::subjects::DATA_RAW_TEXT_DISCOVERED)
              : bus.subscribe(symbiont::subjects::DATA_RAW_TEXT_DISCOVERED,
                              symbiont::subjects::Q_PREPROCESSING);
  uint32_t sid_query = bus.subscribe(symbiont::subjects::TASKS_EMBEDDING_FOR_QUERY,
                                     symbiont::subjects::Q_PREPROCESSING);
  symbiont::logline("INFO", SERVICE, durable ? "ready (durable)" : "ready");

  while (bus.connected()) {
    auto msg = bus.next(1000);
    if (!msg) continue;

    // ------------------------------------------------------------ pipeline
    if (msg->sid == sid_raw) {
      symbiont::RawTextMessage raw;
      try {
        raw = symbiont::RawTextMessage::parse(msg->data);
      } catch (const std::exception& e) {
        symbiont::logline("WARN", SERVICE,
                          std::string("bad raw-text message: ") + e.what(),
                          msg->headers);
        bus.ack(*msg);  // permanent failure: redelivery cannot help
        continue;
      }
      std::string cleaned = symbiont::clean_text(raw.raw_text);
      if (cleaned.empty()) {
        // empty cleaned text is an error at this stage (main.rs:33-39)
        symbiont::logline("WARN", SERVICE, "cleaned text empty for id " + raw.id,
                          msg->headers);
        bus.ack(*msg);  // permanent: the document has no content
        continue;
      }
      auto sentences = symbiont::split_sentences(cleaned);
      auto headers = symbiont::child_headers(msg->headers);
      try {
        auto [vectors, model_name] =
            embed_batch(bus, sentences, engine_timeout_ms, headers);
        symbiont::TextWithEmbeddingsMessage out;
        out.original_id = raw.id;
        out.source_url = raw.source_url;
        out.model_name = model_name;
        out.timestamp_ms = symbiont::now_ms();
        for (size_t i = 0; i < sentences.size(); ++i) {
          symbiont::SentenceEmbedding se;
          se.sentence_text = sentences[i];
          se.embedding = vectors[i];
          out.embeddings_data.push_back(std::move(se));
        }
        bus.publish(symbiont::subjects::DATA_TEXT_WITH_EMBEDDINGS,
                    out.to_json_string(), "", headers);
      } catch (const std::exception& e) {
        // transient (engine down / timeout): leave unacked so the durable
        // stream redelivers after ack_wait
        symbiont::logline("WARN", SERVICE,
                          std::string("embed failed: ") + e.what(), headers);
        continue;
      }
      // un-orphaned knowledge-graph feed (SURVEY.md fact #3)
      symbiont::TokenizedTextMessage tok;
      tok.original_id = raw.id;
      tok.source_url = raw.source_url;
      tok.tokens = symbiont::tokenize_words(cleaned);
      tok.sentences = sentences;
      tok.timestamp_ms = symbiont::now_ms();
      bus.publish(symbiont::subjects::DATA_PROCESSED_TEXT_TOKENIZED,
                  tok.to_json_string(), "", headers);
      bus.ack(*msg);  // both downstream publishes are on the broker
      continue;
    }

    // ----------------------------------------------------- query embedding
    if (msg->sid == sid_query) {
      if (msg->reply.empty()) {
        symbiont::logline("WARN", SERVICE, "query task without reply inbox",
                          msg->headers);
        continue;
      }
      symbiont::QueryEmbeddingResult result;
      try {
        auto task = symbiont::QueryForEmbeddingTask::parse(msg->data);
        result.request_id = task.request_id;
        auto headers = symbiont::child_headers(msg->headers);
        auto [vectors, model_name] =
            embed_batch(bus, {task.text_to_embed}, engine_timeout_ms, headers);
        result.embedding = vectors.at(0);
        result.model_name = model_name;
      } catch (const std::exception& e) {
        // typed error reply even on deserialize failure (main.rs:183-196)
        if (result.request_id.empty()) result.request_id = "unknown";
        result.error_message = e.what();
      }
      bus.publish(msg->reply, result.to_json_string(), "",
                  symbiont::child_headers(msg->headers));
      continue;
    }
  }
  symbiont::logline("INFO", SERVICE, "bus connection closed; exiting");
  return 0;
} catch (const std::exception& e) {
  // bus drop mid-handler etc.: exit cleanly for the supervisor to
  // restart instead of std::terminate aborting with no log
  symbiont::logline("ERROR", SERVICE, std::string("fatal: ") + e.what());
  return 1;
}
